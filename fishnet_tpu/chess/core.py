"""ctypes binding to the native chess core (cpp/libfishnetcore.so).

The reference delegates chess rules to the shakmaty library
(src/queue.rs:524-552); here the same single implementation of the rules
serves both the Python scheduler (legality replay, batch expansion) and
the native search engine — no duplicated rules logic.

The library is built with ``make -C cpp``. This module locates it next to
the repo's ``cpp/`` directory and (re)builds it on demand if missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_CPP_DIR = Path(__file__).resolve().parent.parent.parent / "cpp"
_LIB_PATH = _CPP_DIR / "libfishnetcore.so"

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()

#: Expected C ABI version (cpp/src/capi.cpp fc_abi_version). A library
#: built from different-era sources must be rejected, not loaded: ctypes
#: has no signature checking, so a mismatched argument layout corrupts
#: memory silently.
ABI_VERSION = 11


class NativeCoreError(RuntimeError):
    pass


def _build() -> None:
    """(Re)build the library. `make` is a cheap no-op when up to date, so
    this runs on every first load — a stale .so surviving C++ source
    changes would otherwise be loaded silently."""
    try:
        subprocess.run(
            ["make", "-C", str(_CPP_DIR), "libfishnetcore.so"],
            check=True,
            capture_output=True,
            text=True,
        )
    except (subprocess.CalledProcessError, OSError) as err:
        # No toolchain (packaged deployment): fall back to whatever
        # prebuilt library _candidate_libraries finds — native or a CPU
        # tier. Only surface the build error when nothing loadable exists.
        candidates = [_LIB_PATH, *_CPP_DIR.glob("libfishnetcore-*.so")]
        if any(p.exists() for p in candidates):
            return
        stderr = getattr(err, "stderr", "") or str(err)
        raise NativeCoreError(
            f"failed to build native core: {stderr[-2000:]}"
        ) from err


def _candidate_libraries() -> list:
    """Libraries to try, best first: FISHNET_TPU_CORE_LIB env >
    host-built -march=native library > best CPU-feature tier (v4, then v3
    with fast PEXT, then v2 — mirroring the reference's tier selection and
    AMD slow-PEXT heuristic, assets.rs:86-126). Later candidates are
    fallbacks for earlier ones that fail the ABI handshake (e.g. a
    stale host build next to freshly shipped tiers)."""
    override = os.environ.get("FISHNET_TPU_CORE_LIB")
    if override:
        path = Path(override)
        if not path.exists():
            raise NativeCoreError(
                f"FISHNET_TPU_CORE_LIB points to a missing file: {override}"
            )
        return [path]  # explicit override: no silent fallback
    candidates = []
    if _LIB_PATH.exists():
        candidates.append(_LIB_PATH)
    from fishnet_tpu.chess.cpu import detect

    tier = detect().best_tier()
    tiers = {
        "v4": ["v4", "v3", "v2"],
        "v3": ["v3", "v2"],
        "v2": ["v2"],
        "arm64": ["arm64"],
    }.get(tier, [])
    # Tier libraries live in cpp/ (source checkout) or in the package's
    # own _native/ (pip/pipx wheel install, where cpp/ doesn't exist) —
    # setup.py's build hook copies `make tiers` output there.
    native_dir = Path(__file__).resolve().parent.parent / "_native"
    for t in tiers:
        for base in (_CPP_DIR, native_dir):
            path = base / f"libfishnetcore-{t}.so"
            if path.exists():
                candidates.append(path)
    if not candidates:
        raise NativeCoreError(
            "no native core library found (build with `make -C cpp` or ship "
            "`make tiers` artifacts)"
        )
    return candidates


def load() -> ctypes.CDLL:
    """Load (building if necessary) the native core library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        _build()
        lib = None
        mismatches = []
        for path in _candidate_libraries():
            try:
                candidate = ctypes.CDLL(str(path))
            except OSError as err:
                # Truncated file / wrong arch / missing deps: skip to the
                # next candidate instead of aborting the fallback chain.
                mismatches.append(f"{path} (unloadable: {err})")
                continue
            try:
                candidate.fc_abi_version.restype = ctypes.c_int
                abi = candidate.fc_abi_version()
            except AttributeError:
                abi = -1
            if abi == ABI_VERSION:
                lib = candidate
                break
            mismatches.append(f"{path} (ABI {abi})")
        if lib is None:
            raise NativeCoreError(
                f"no native core with ABI version {ABI_VERSION} found; "
                f"rejected: {', '.join(mismatches)} — rebuild with "
                "`make -C cpp` or ship matching tier libraries"
            )

        lib.fc_init.restype = ctypes.c_int
        lib.fc_variant_supported.argtypes = [ctypes.c_int]
        lib.fc_variant_supported.restype = ctypes.c_int
        lib.fc_pos_new.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.fc_pos_new.restype = ctypes.c_void_p
        lib.fc_pos_clone.argtypes = [ctypes.c_void_p]
        lib.fc_pos_clone.restype = ctypes.c_void_p
        lib.fc_pos_free.argtypes = [ctypes.c_void_p]
        lib.fc_pos_play_uci.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.fc_pos_play_uci.restype = ctypes.c_int
        lib.fc_pos_fen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.fc_pos_fen.restype = ctypes.c_int
        lib.fc_pos_turn.argtypes = [ctypes.c_void_p]
        lib.fc_pos_turn.restype = ctypes.c_int
        lib.fc_pos_is_check.argtypes = [ctypes.c_void_p]
        lib.fc_pos_is_check.restype = ctypes.c_int
        lib.fc_pos_halfmove.argtypes = [ctypes.c_void_p]
        lib.fc_pos_halfmove.restype = ctypes.c_int
        lib.fc_pos_fullmove.argtypes = [ctypes.c_void_p]
        lib.fc_pos_fullmove.restype = ctypes.c_int
        lib.fc_pos_hash.argtypes = [ctypes.c_void_p]
        lib.fc_pos_hash.restype = ctypes.c_uint64
        lib.fc_pos_outcome.argtypes = [ctypes.c_void_p]
        lib.fc_pos_outcome.restype = ctypes.c_int
        lib.fc_pos_parse_uci.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.fc_pos_parse_uci.restype = ctypes.c_int
        lib.fc_pos_legal_moves.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.fc_pos_legal_moves.restype = ctypes.c_int
        lib.fc_perft.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.fc_perft.restype = ctypes.c_uint64

        lib.fc_nnue_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.fc_nnue_load.restype = ctypes.c_void_p
        lib.fc_nnue_free.argtypes = [ctypes.c_void_p]
        lib.fc_nnue_evaluate.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.fc_nnue_evaluate.restype = ctypes.c_int
        lib.fc_pos_features.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.fc_pos_features.restype = ctypes.c_int
        lib.fc_pos_psqt_bucket.argtypes = [ctypes.c_void_p]
        lib.fc_pos_psqt_bucket.restype = ctypes.c_int

        lib.fc_init()
        _lib = lib
        return lib
