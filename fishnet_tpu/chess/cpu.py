"""Host CPU feature detection for native-library tier selection.

The reference embeds up to six CPU-feature-tiered engine builds and
picks the best at startup (assets.rs:86-126), including a heuristic
that treats BMI2/PEXT as *slow* on AMD before Zen 3 (family < 0x19,
assets.rs:94-108) — those chips microcode-emulate PEXT, so the
SSE-level build outruns the BMI2 one. This module mirrors that logic
for the portable tiers `make tiers` produces (x86-64-v2, -v3 and
-v4) plus the aarch64 tier; a host-built -march=native library always
wins when present.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import FrozenSet, Optional


@dataclass(frozen=True)
class CpuInfo:
    vendor: str = ""
    family: int = 0
    flags: FrozenSet[str] = field(default_factory=frozenset)
    #: Machine architecture (platform.machine()): tier selection is
    #: x86-feature based on x86-64 and a single armv8 tier on aarch64
    #: (mirroring the reference's armv8 build, build.rs:187-276).
    arch: str = "x86_64"

    @property
    def fast_pext(self) -> bool:
        """BMI2 present and not microcoded (AMD pre-Zen3 emulates PEXT)."""
        if "bmi2" not in self.flags:
            return False
        if self.vendor == "AuthenticAMD" and self.family < 0x19:
            return False
        return True

    def best_tier(self) -> Optional[str]:
        """'v4' (AVX-512), 'v3' (AVX2+fast BMI2), 'v2' (SSE4.2/POPCNT),
        'arm64' (aarch64), or None."""
        if self.arch in ("aarch64", "arm64"):
            return "arm64"
        # x86-64-v4 needs the AVX-512 F/BW/CD/DQ/VL group (and still
        # benefits from fast PEXT — BMI2 is part of v3's baseline).
        if (
            {"avx512f", "avx512bw", "avx512cd", "avx512dq", "avx512vl"}
            <= self.flags
            and self.fast_pext
        ):
            return "v4"
        if {"avx2", "bmi2"} <= self.flags and self.fast_pext:
            return "v3"
        if {"sse4_2", "popcnt"} <= self.flags:
            return "v2"
        return None


def parse_cpuinfo(text: str) -> CpuInfo:
    vendor = ""
    family = 0
    flags: FrozenSet[str] = frozenset()
    for line in text.splitlines():
        key, _, value = line.partition(":")
        key = key.strip()
        value = value.strip()
        if key == "vendor_id" and not vendor:
            vendor = value
        elif key == "cpu family" and not family:
            try:
                family = int(value)
            except ValueError:
                pass
        elif key == "flags" and not flags:
            flags = frozenset(value.split())
    return CpuInfo(vendor=vendor, family=family, flags=flags)


def detect(cpuinfo_path: str = "/proc/cpuinfo") -> CpuInfo:
    import dataclasses
    import platform

    arch = platform.machine() or "x86_64"
    try:
        text = Path(cpuinfo_path).read_text()
    except OSError:
        return CpuInfo(arch=arch)
    return dataclasses.replace(parse_cpuinfo(text), arch=arch)
