"""CLI / config layer.

Equivalent of the reference's config system (src/configure.rs:19-612):
three sources with precedence CLI > ``fishnet.ini`` (section
``[Fishnet]``) > interactive first-run dialog. Ships the same flag
surface (key/key-file, endpoint, cores, user/system backlog,
max-backoff, stats-file, conf/no-conf, auto-update, -v, subcommands
run/configure/systemd/systemd-user/license) plus the TPU-era additions:
``--engine {tpu-nnue,uci,mock}`` selects the backend behind the engine
seam and ``--nnue-file`` points at HalfKAv2_hm weights.

Durations parse like the reference (configure.rs:323-342): ``90s``,
``2h``, ``1d``, ``500ms``, bare seconds.
"""

from __future__ import annotations

import argparse
import configparser
import io
import os
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, List, Optional, Sequence, TextIO
from urllib.parse import urlsplit

from fishnet_tpu.version import __version__

DEFAULT_ENDPOINT = "https://lichess.org/fishnet"
INI_SECTION = "Fishnet"


class ConfigError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Scalar option types (configure.rs:84-305)
# ---------------------------------------------------------------------------


def parse_endpoint(s: str) -> str:
    """Normalize an endpoint URL: strip one trailing slash
    (configure.rs:103-113)."""
    parts = urlsplit(s)
    if parts.scheme not in ("http", "https") or not parts.netloc:
        raise ConfigError(f"invalid endpoint url: {s!r}")
    return s[:-1] if s.endswith("/") else s


def endpoint_is_development(endpoint: str) -> bool:
    """Any host other than lichess.org is a development endpoint
    (configure.rs:115-119)."""
    return urlsplit(endpoint).hostname != "lichess.org"


def parse_key(s: str) -> str:
    """Keys are non-empty ASCII alphanumeric (configure.rs:148-161)."""
    if not s:
        raise ConfigError("key expected to be non-empty")
    if not all(c.isascii() and c.isalnum() for c in s):
        raise ConfigError("key expected to be alphanumeric")
    return s


def available_cores() -> int:
    return os.cpu_count() or 1


def parse_cores(s: str) -> str:
    """Validate a cores spec, keeping the symbolic form
    (configure.rs:163-191)."""
    if s in ("auto", "all", "max"):
        return "all" if s == "max" else s
    try:
        n = int(s)
    except ValueError as err:
        raise ConfigError(f"invalid cores: {s!r}") from err
    if n < 1:
        raise ConfigError("cores must be >= 1")
    return str(n)


def resolve_cores(spec: Optional[str]) -> int:
    """``auto`` = n-1 (min 1), ``all`` = n (configure.rs:194-204)."""
    n = available_cores()
    if spec is None or spec == "auto":
        return max(1, n - 1)
    if spec == "all":
        return n
    return int(spec)


def parse_duration(s: str) -> float:
    """Duration in seconds from ``1d`` / ``2h`` / ``3m`` / ``500ms`` /
    ``90s`` / ``90`` (configure.rs:323-342)."""
    s = s.strip()
    for suffix, factor in (("ms", 0.001), ("d", 86400.0), ("h", 3600.0), ("m", 60.0), ("s", 1.0)):
        if s.endswith(suffix):
            body = s[: -len(suffix)]
            break
    else:
        body, factor = s, 1.0
    try:
        value = int(body.strip())
    except ValueError as err:
        raise ConfigError(f"invalid duration: {s!r}") from err
    if value < 0:
        raise ConfigError("duration must be non-negative")
    return value * factor


def parse_backlog(s: str) -> float:
    """``short`` = 30 s, ``long`` = 1 h, else a duration
    (configure.rs:240-276)."""
    if s == "short":
        return 30.0
    if s == "long":
        return 3600.0
    return parse_duration(s)


def parse_mesh(s: str) -> str:
    """``auto`` | ``off`` | explicit ``DATAxMODEL`` (e.g. ``4x2``)."""
    t = s.strip().lower()
    if t in ("auto", "off"):
        return t
    m = re.fullmatch(r"(\d+)x(\d+)", t)
    if m and int(m.group(1)) >= 1 and int(m.group(2)) >= 1:
        return t
    raise ConfigError(f"invalid mesh spec: {s!r} (use auto, off, or DATAxMODEL)")


def parse_toggle(s: str) -> Optional[bool]:
    """Lenient y/n parsing for dialog answers (configure.rs:352-363).
    Returns None for the empty string (take the default); raises on
    unrecognized input."""
    t = s.strip().lower()
    if t in ("y", "j", "yes", "yep", "yay", "true", "t", "1", "ok"):
        return True
    if t in ("n", "no", "nop", "nope", "nay", "f", "false", "0"):
        return False
    if t == "":
        return None
    raise ConfigError(f"not a yes/no answer: {s!r}")


# ---------------------------------------------------------------------------
# Opt
# ---------------------------------------------------------------------------

COMMANDS = ("run", "configure", "systemd", "systemd-user", "uci",
            "verify-net", "license")

ENGINE_BACKENDS = ("tpu-nnue", "az-mcts", "uci", "mock")


@dataclass
class Opt:
    """Resolved options (reference ``Opt``, configure.rs:19-69)."""

    #: None = bare invocation (no subcommand). Distinct from an explicit
    #: ``run``: the first-run dialog triggers for bare invocations only
    #: (configure.rs:421-423).
    command: Optional[str] = None
    verbose: int = 0
    auto_update: bool = False
    conf: Optional[str] = None
    no_conf: bool = False
    key: Optional[str] = None
    key_file: Optional[str] = None
    endpoint: Optional[str] = None
    cores: Optional[str] = None
    max_backoff: Optional[float] = None
    user_backlog: Optional[float] = None
    system_backlog: Optional[float] = None
    stats_file: Optional[str] = None
    no_stats_file: bool = False
    # TPU-era extensions (north star: `--engine tpu-nnue` behind the
    # stockfish.rs seam).
    engine: Optional[str] = None
    engine_exe: Optional[str] = None
    nnue_file: Optional[str] = None
    az_net_file: Optional[str] = None
    microbatch: Optional[int] = None
    pipeline: Optional[int] = None
    #: Scheduler threads driving the shared search pool (the host
    #: parallelism tier: each thread steps its own slot groups' fibers;
    #: the reference gets the same from one engine process per core,
    #: src/main.rs:158-170). Default: the resolved worker-core count.
    search_threads: Optional[int] = None
    #: Worker (pull-loop) count. None = auto: batched device engines
    #: (tpu-nnue, az-mcts) run many pull loops per core — a worker there
    #: is an asyncio task over one SHARED device service, so concurrency
    #: is set by the service's pool, not by host cores, and a batch's
    #: ~30 positions analyze concurrently instead of one per device
    #: round-trip; subprocess/mock engines keep the reference's
    #: one-worker-per-core model.
    search_concurrency: Optional[int] = None
    #: Device-mesh policy for the serving evaluator: "auto" (shard the
    #: eval batch whenever >1 device is visible), "off" (single device),
    #: or an explicit "DATAxMODEL" shape such as "4x2".
    mesh: Optional[str] = None
    #: Telemetry exposition port (doc/observability.md). None = telemetry
    #: off (the default; hot paths pay one flag check); 0 = an ephemeral
    #: port (logged at startup); otherwise the port /metrics binds on.
    metrics_port: Optional[int] = None
    #: File to write the exporter's BOUND port to once it is listening
    #: (one decimal integer). The point is ``--metrics-port 0``: a
    #: fleet supervisor spawning many clients on one host gives each an
    #: ephemeral port and a port file, and the fleet aggregator
    #: discovers/follows them by re-reading the files. None = don't
    #: write one.
    metrics_port_file: Optional[str] = None
    #: Directory for span flight-recorder JSONL dumps
    #: (doc/observability.md). None = the ``FISHNET_SPANS_DIR`` /
    #: ``FISHNET_SPANS_FILE`` environment, falling back to a
    #: ``fishnet-spans/`` directory under the system tempdir — never
    #: the process working directory.
    spans_dir: Optional[str] = None
    #: Batch-span journal file: every batch-trace span (the per-work-
    #: unit lifecycle, not the kHz device path) is appended and flushed
    #: line-by-line, so a SIGKILLed process's final spans survive for
    #: the fleet stitcher. None = journaling off.
    spans_journal: Optional[str] = None
    #: Deterministic fault plan (doc/resilience.md grammar). None =
    #: fault injection off (the default; sites pay one flag check).
    #: ``FISHNET_FAULT_PLAN`` in the environment is the fallback for
    #: processes not started via this CLI.
    fault_plan: Optional[str] = None
    #: Per-batch deadline budget in seconds: a pending batch older than
    #: this is flushed as a partial analysis instead of wedging the
    #: queue (doc/resilience.md). None = no deadline (the reference
    #: model: the server's own timeout reassigns).
    batch_deadline: Optional[float] = None
    #: Concurrent acquire streams (sched/frontend.py). >1 wires the
    #: multi-tenant front end: priority lanes, DRR fairness, admission
    #: control + load shedding. None/1 = the classic single stream.
    tenants: Optional[int] = None
    #: Admission-control high watermark: queued throughput-lane
    #: positions past which analysis batches are shed (accounted abort;
    #: the server reassigns). None = the shed policy default.
    lane_depth_limit: Optional[int] = None
    #: Graceful-drain deadline in seconds (doc/resilience.md "Graceful
    #: drain"): on SIGTERM the client stops acquiring and flushes
    #: in-flight batches for at most this long before aborting the rest
    #: upstream and exiting 0. None = the 25 s default (chosen to fit
    #: under Kubernetes' 30 s terminationGracePeriodSeconds).
    drain_deadline: Optional[float] = None

    def resolved_tenants(self) -> int:
        return self.tenants if self.tenants is not None else 1

    def resolved_drain_deadline(self) -> float:
        return self.drain_deadline if self.drain_deadline is not None else 25.0

    def conf_path(self) -> Path:
        return Path(self.conf) if self.conf else Path("fishnet.ini")

    def resolved_endpoint(self) -> str:
        return self.endpoint or DEFAULT_ENDPOINT

    def resolved_cores(self) -> int:
        return resolve_cores(self.cores)

    def resolved_max_backoff(self) -> float:
        return self.max_backoff if self.max_backoff is not None else 30.0

    def resolved_engine(self) -> str:
        return self.engine or "tpu-nnue"

    def resolved_microbatch(self) -> int:
        return self.microbatch if self.microbatch is not None else 1024

    def resolved_search_threads(self) -> int:
        if self.search_threads is not None:
            return self.search_threads
        return self.resolved_cores()

    def resolved_workers(self) -> int:
        if self.search_concurrency is not None:
            return self.search_concurrency
        if self.resolved_engine() in ("tpu-nnue", "az-mcts"):
            return min(256, 32 * self.resolved_cores())
        return self.resolved_cores()

    def resolved_mesh(self) -> str:
        return self.mesh or "auto"

    def resolved_fault_plan(self) -> Optional[str]:
        return self.fault_plan or os.environ.get("FISHNET_FAULT_PLAN") or None

    def resolved_command(self) -> str:
        return self.command or "run"

    def is_systemd(self) -> bool:
        return self.command in ("systemd", "systemd-user")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fishnet-tpu",
        description="Distributed TPU-batched chess analysis for lichess.org.",
    )
    p.add_argument("--version", action="version", version=f"fishnet-tpu {__version__}")
    p.add_argument(
        "command",
        nargs="?",
        choices=COMMANDS,
        default=None,
        help="run (default) | configure | systemd | systemd-user | uci | license",
    )
    p.add_argument("-v", "--verbose", action="count", default=0, help="Increase verbosity.")
    p.add_argument("--auto-update", action="store_true", help="Install updates on startup and periodically.")
    p.add_argument("--conf", help="Configuration file (default: fishnet.ini).")
    p.add_argument("--no-conf", action="store_true", help="Do not use a configuration file.")
    p.add_argument("-k", "--key", "--apikey", dest="key", help="Fishnet key.")
    p.add_argument("--key-file", help="File containing the fishnet key.")
    p.add_argument("--endpoint", help=f"HTTP endpoint (default: {DEFAULT_ENDPOINT}).")
    p.add_argument("--cores", "--threads", dest="cores", help="Worker count: a number, auto (n-1), or all.")
    p.add_argument("--max-backoff", help="Maximum randomized backoff when idle (default 30s).")
    p.add_argument("--user-backlog", help="Join user queue only if backlog is older than this (e.g. 120s, short, long).")
    p.add_argument("--system-backlog", help="Join system queue only if backlog is older than this (e.g. 2h).")
    p.add_argument("--stats-file", help="File for local statistics (default: ~/.fishnet-stats).")
    p.add_argument("--no-stats-file", action="store_true", help="Do not record local statistics.")
    p.add_argument("--engine", choices=ENGINE_BACKENDS, default=None,
                   help="Engine backend: tpu-nnue (default; batched TPU evaluator), uci (subprocess oracle), mock.")
    p.add_argument("--engine-exe", help="UCI engine executable for --engine uci.")
    p.add_argument("--nnue-file", help="Path to HalfKAv2_hm .nnue weights for the TPU evaluator.")
    p.add_argument("--microbatch", type=int, default=None, help="TPU eval microbatch size (default 1024).")
    p.add_argument("--az-net-file", default=None,
                   help="Policy+value net checkpoint (.npz) for --engine az-mcts.")
    p.add_argument("--pipeline", type=int, default=None,
                   help="Eval pipeline depth (in-flight device batches). Default: "
                        "probe the device at startup (serialized tunnels get 1, "
                        "locally attached TPUs 2-4).")
    p.add_argument("--search-threads", type=int, default=None,
                   help="Scheduler threads driving the search pool (host "
                        "parallelism tier). Default: the worker-core count.")
    p.add_argument("--search-concurrency", type=int, default=None,
                   help="Concurrent position analyses (worker pull loops). "
                        "Default: 32 per core for the batched device engines "
                        "(they share one service; a batch's positions analyze "
                        "concurrently), 1 per core for uci/mock.")
    p.add_argument("--mesh", default=None,
                   help="Device mesh for the serving evaluator: auto (default; "
                        "shard eval batches over all visible devices), off "
                        "(single device), or DATAxMODEL (e.g. 4x2).")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="Serve live telemetry (/metrics Prometheus text, "
                        "/json snapshot) on this port and arm the SIGUSR2 "
                        "span-dump. 0 picks an ephemeral port. Default: "
                        "telemetry off.")
    p.add_argument("--metrics-port-file", default=None,
                   help="Write the exporter's bound port to this file once "
                        "listening (pairs with --metrics-port 0; the fleet "
                        "aggregator's --port-dir discovery reads these).")
    p.add_argument("--spans-dir", default=None,
                   help="Directory for span flight-recorder JSONL dumps "
                        "(fishnet-spans-<pid>.jsonl). Default: "
                        "$FISHNET_SPANS_DIR, else <tempdir>/fishnet-spans.")
    p.add_argument("--spans-journal", default=None,
                   help="Append every batch-trace span to this JSONL file "
                        "(flushed per line) so spans recorded after the "
                        "last scrape survive a SIGKILL for the fleet "
                        "stitcher. Default: off.")
    p.add_argument("--fault-plan", default=None,
                   help="Deterministic fault plan (doc/resilience.md "
                        "grammar), e.g. 'seed=7;net.acquire:nth=2:error'. "
                        "Testing/soak aid — never set in production. "
                        "Default: fault injection off "
                        "(FISHNET_FAULT_PLAN is the env fallback).")
    p.add_argument("--batch-deadline", default=None,
                   help="Per-batch deadline budget (duration, e.g. 120s): "
                        "batches older than this are flushed as partial "
                        "analyses instead of wedging the queue. Default: "
                        "no deadline.")
    p.add_argument("--tenants", type=int, default=None,
                   help="Concurrent acquire streams (multi-tenant front "
                        "end with priority lanes, per-tenant fairness, and "
                        "admission control; doc/resilience.md). Default: 1 "
                        "(the classic single stream). "
                        "FISHNET_NO_MULTITENANT=1 forces single-stream.")
    p.add_argument("--lane-depth-limit", type=int, default=None,
                   help="Admission-control high watermark: queued "
                        "analysis-lane positions past which bulk batches "
                        "are shed (accounted abort; the server reassigns). "
                        "Default: the shed policy's built-in watermark.")
    p.add_argument("--drain-deadline", default=None,
                   help="Graceful-drain deadline (duration, e.g. 25s): on "
                        "SIGTERM, flush in-flight batches for at most this "
                        "long before aborting the rest upstream (accounted; "
                        "the server reassigns) and exiting 0. Default: 25s.")
    return p


def _opt_from_namespace(ns: argparse.Namespace) -> Opt:
    opt = Opt(command=ns.command, verbose=ns.verbose, auto_update=ns.auto_update,
              conf=ns.conf, no_conf=ns.no_conf, key_file=ns.key_file,
              no_stats_file=ns.no_stats_file, stats_file=ns.stats_file,
              engine_exe=ns.engine_exe, nnue_file=ns.nnue_file,
              az_net_file=ns.az_net_file)
    if ns.conf and ns.no_conf:
        raise ConfigError("--conf conflicts with --no-conf")
    if ns.key and ns.key_file:
        raise ConfigError("--key conflicts with --key-file")
    if ns.stats_file and ns.no_stats_file:
        raise ConfigError("--stats-file conflicts with --no-stats-file")
    if ns.key is not None:
        opt.key = parse_key(ns.key)
    if ns.endpoint is not None:
        opt.endpoint = parse_endpoint(ns.endpoint)
    if ns.cores is not None:
        opt.cores = parse_cores(ns.cores)
    if ns.max_backoff is not None:
        opt.max_backoff = parse_duration(ns.max_backoff)
    if ns.user_backlog is not None:
        opt.user_backlog = parse_backlog(ns.user_backlog)
    if ns.system_backlog is not None:
        opt.system_backlog = parse_backlog(ns.system_backlog)
    if ns.engine is not None:
        opt.engine = ns.engine
    if ns.microbatch is not None:
        if ns.microbatch < 1:
            raise ConfigError("--microbatch must be >= 1")
        opt.microbatch = ns.microbatch
    if ns.pipeline is not None:
        if ns.pipeline < 1:
            raise ConfigError("--pipeline must be >= 1")
        opt.pipeline = ns.pipeline
    if ns.search_threads is not None:
        if ns.search_threads < 1:
            raise ConfigError("--search-threads must be >= 1")
        opt.search_threads = ns.search_threads
    if ns.search_concurrency is not None:
        if ns.search_concurrency < 1:
            raise ConfigError("--search-concurrency must be >= 1")
        opt.search_concurrency = ns.search_concurrency
    if ns.mesh is not None:
        opt.mesh = parse_mesh(ns.mesh)
    if ns.metrics_port is not None:
        opt.metrics_port = _parse_port(str(ns.metrics_port))
    if ns.metrics_port_file is not None:
        opt.metrics_port_file = ns.metrics_port_file
    if ns.spans_dir is not None:
        opt.spans_dir = ns.spans_dir
    if ns.spans_journal is not None:
        opt.spans_journal = ns.spans_journal
    if ns.fault_plan is not None:
        opt.fault_plan = _parse_fault_plan(ns.fault_plan)
    if ns.batch_deadline is not None:
        opt.batch_deadline = parse_duration(ns.batch_deadline)
        if opt.batch_deadline <= 0:
            raise ConfigError("--batch-deadline must be positive")
    if ns.tenants is not None:
        if ns.tenants < 1:
            raise ConfigError("--tenants must be >= 1")
        opt.tenants = ns.tenants
    if ns.lane_depth_limit is not None:
        if ns.lane_depth_limit < 1:
            raise ConfigError("--lane-depth-limit must be >= 1")
        opt.lane_depth_limit = ns.lane_depth_limit
    if ns.drain_deadline is not None:
        opt.drain_deadline = parse_duration(ns.drain_deadline)
        if opt.drain_deadline <= 0:
            raise ConfigError("--drain-deadline must be positive")
    return opt


def _parse_fault_plan(value: str) -> str:
    """Validate a fault-plan spec at config time (the plan grammar lives
    in resilience/faults.py) so a typo fails with a ConfigError instead
    of a traceback at first injection."""
    from fishnet_tpu.resilience.faults import FaultPlan, FaultPlanError

    try:
        FaultPlan.parse(value)
    except FaultPlanError as err:
        raise ConfigError(f"invalid --fault-plan: {err}") from err
    return value


def _parse_port(value: str) -> int:
    try:
        port = int(value)
    except ValueError as err:
        raise ConfigError(f"invalid port: {value!r}") from err
    if not 0 <= port <= 65535:
        raise ConfigError("metrics port must be in 0..65535 (0 = ephemeral)")
    return port


# ---------------------------------------------------------------------------
# Ini handling (configure.rs:405-419, 574-599)
# ---------------------------------------------------------------------------

#: ini key -> (Opt attribute, parser)
_INI_FIELDS = (
    ("Endpoint", "endpoint", parse_endpoint),
    ("Key", "key", parse_key),
    ("Cores", "cores", parse_cores),
    ("UserBacklog", "user_backlog", parse_backlog),
    ("SystemBacklog", "system_backlog", parse_backlog),
    ("MaxBackoff", "max_backoff", parse_duration),
    ("Engine", "engine", lambda s: s if s in ENGINE_BACKENDS else _bad_engine(s)),
    ("EngineExe", "engine_exe", str),
    ("NnueFile", "nnue_file", str),
    ("AzNetFile", "az_net_file", str),
    ("Mesh", "mesh", parse_mesh),
    ("SearchThreads", "search_threads", lambda v: _positive_int(v, "SearchThreads")),
    ("SearchConcurrency", "search_concurrency",
     lambda v: _positive_int(v, "SearchConcurrency")),
    ("MetricsPort", "metrics_port", lambda v: _parse_port(v)),
    ("MetricsPortFile", "metrics_port_file", str),
    ("SpansDir", "spans_dir", str),
    ("SpansJournal", "spans_journal", str),
    ("FaultPlan", "fault_plan", lambda v: _parse_fault_plan(v)),
    ("BatchDeadline", "batch_deadline", parse_duration),
    ("Tenants", "tenants", lambda v: _positive_int(v, "Tenants")),
    ("LaneDepthLimit", "lane_depth_limit",
     lambda v: _positive_int(v, "LaneDepthLimit")),
    ("DrainDeadline", "drain_deadline", parse_duration),
)


def _positive_int(value: str, name: str) -> int:
    n = int(value)
    if n < 1:
        raise ConfigError(f"{name} must be >= 1")
    return n


def _bad_engine(s: str) -> str:
    raise ConfigError(f"invalid engine backend: {s!r} (choose from {', '.join(ENGINE_BACKENDS)})")


def load_ini(path: Path) -> configparser.ConfigParser:
    ini = configparser.ConfigParser()
    ini.optionxform = str  # preserve CamelCase keys like the reference ini
    if path.exists():
        ini.read_string(path.read_text())
    if not ini.has_section(INI_SECTION):
        ini.add_section(INI_SECTION)
    return ini


def write_ini(ini: configparser.ConfigParser, path: Path) -> None:
    buf = io.StringIO()
    ini.write(buf)
    path.write_text(buf.getvalue())


def merge_ini(opt: Opt, ini: configparser.ConfigParser) -> None:
    """Fill unset Opt fields from the ini (CLI wins, configure.rs:574-599)."""
    for ini_key, attr, parse in _INI_FIELDS:
        if ini.has_option(INI_SECTION, ini_key):
            raw = ini.get(INI_SECTION, ini_key)
            if getattr(opt, attr) is None:
                setattr(opt, attr, parse(raw))


# ---------------------------------------------------------------------------
# Interactive dialog (configure.rs:420-572)
# ---------------------------------------------------------------------------

INTRO = r"""#   _________         .    .
#  (..       \_    ,  |\  /|
#   \       O  \  /|  \ \/ /
#    \______    \/ |   \  /      _____ _     _     _   _      _
#       vvvv\    \ |   /  |     |  ___(_)___| |__ | \ | | ___| |_
#       \^^^^  ==   \_/   |     | |_  | / __| '_ \|  \| |/ _ \ __|
#        `\_   ===    \.  |     |  _| | \__ \ | | | |\  |  __/ |_
#        / /\_   \ /      |     |_|   |_|___/_| |_|_| \_|\___|\__| {version} (tpu)
#        |/   \_  \|      /
#               \________/      Distributed TPU chess analysis for lichess.org
""".format(version=__version__)


KeyCheck = Callable[[str, str], Optional[str]]
"""(endpoint, key) -> None if valid, else an error message. Network check."""


def run_dialog(
    opt: Opt,
    ini: configparser.ConfigParser,
    *,
    input_fn: Callable[[], str],
    output: TextIO,
    key_check: Optional[KeyCheck] = None,
) -> None:
    """First-run / ``configure`` dialog: endpoint -> key -> cores ->
    backlog -> write (configure.rs:425-559). Mutates ``ini`` in place;
    the caller merges + writes."""

    def ask(prompt: str) -> str:
        output.write(prompt)
        output.flush()
        line = input_fn()
        if line == "":  # EOF: stdin closed, e.g. piped invocation
            raise ConfigError("stdin closed during configuration dialog")
        return line.strip()

    endpoint = opt.endpoint or (
        ini.get(INI_SECTION, "Endpoint") if ini.has_option(INI_SECTION, "Endpoint") else DEFAULT_ENDPOINT
    )

    # Step 1: key (with optional live validation; '!' suffix skips it,
    # configure.rs:437-492).
    while True:
        if ini.has_option(INI_SECTION, "Key"):
            masked = "*" * len(ini.get(INI_SECTION, "Key"))
            raw = ask(f"Personal fishnet key (append ! to force, default: keep {masked}): ")
            required = False
        elif endpoint_is_development(endpoint):
            raw = ask("Personal fishnet key (append ! to force, probably not required): ")
            required = False
        else:
            raw = ask("Personal fishnet key (append ! to force, https://lichess.org/get-fishnet): ")
            required = True
        if not raw:
            if required:
                output.write("Key required.\n")
                continue
            break
        check = key_check
        if raw.endswith("!"):
            raw, check = raw[:-1], None
        try:
            key = parse_key(raw)
        except ConfigError as err:
            output.write(f"Invalid: {err}\n")
            continue
        if check is not None:
            err_msg = check(endpoint, key)
            if err_msg is not None:
                output.write(f"Invalid: {err_msg}\n")
                continue
        ini.set(INI_SECTION, "Key", key)
        break

    # Step 2: cores (configure.rs:494-523).
    all_cores = available_cores()
    auto = resolve_cores("auto")
    while True:
        raw = ask(f"\nNumber of worker cores (default {auto}, max {all_cores}): ")
        try:
            spec = parse_cores(raw) if raw else "auto"
        except ConfigError as err:
            output.write(f"Invalid: {err}\n")
            continue
        if spec.isdigit() and int(spec) > all_cores:
            output.write(f"At most {all_cores} logical cores available on your machine.\n")
            continue
        ini.set(INI_SECTION, "Cores", spec)
        break

    # Step 3: backlog (configure.rs:525-553).
    output.write(
        "\nYou can choose to not join unless a backlog is building up. Examples:\n"
        "* Rented server exclusively for fishnet: choose no\n"
        "* Running on a laptop: choose yes\n"
    )
    while True:
        raw = ask("Would you prefer to keep your client idle? (default: no) ")
        try:
            answer = parse_toggle(raw)
        except ConfigError:
            continue
        if answer:
            ini.set(INI_SECTION, "UserBacklog", "short")
            ini.set(INI_SECTION, "SystemBacklog", "long")
        else:
            ini.set(INI_SECTION, "UserBacklog", "0")
            ini.set(INI_SECTION, "SystemBacklog", "0")
        break

    # Step 4: write confirmation is handled by the caller so tests can
    # inspect the ini without touching the filesystem.


def parse_and_configure(
    argv: Optional[Sequence[str]] = None,
    *,
    input_fn: Optional[Callable[[], str]] = None,
    output: Optional[TextIO] = None,
    key_check: Optional[KeyCheck] = None,
    write: bool = True,
) -> Opt:
    """Full config resolution (configure.rs:380-613): parse CLI, read key
    file, maybe run the dialog, merge ini under CLI, cap cores."""
    ns = build_parser().parse_args(argv)
    opt = _opt_from_namespace(ns)
    output = output or sys.stderr

    if not opt.is_systemd() and opt.key_file:
        opt.key = parse_key(Path(opt.key_file).read_text().strip())

    use_conf = opt.command == "configure" or (opt.command != "license" and not opt.no_conf)
    if use_conf:
        ini = load_ini(opt.conf_path())
        file_found = opt.conf_path().exists()
        # The dialog triggers for bare invocations and `configure` only —
        # never for `uci` (stdin belongs to the GUI's handshake) or the
        # non-interactive `verify-net`.
        if (not file_found and opt.command not in ("run", "uci", "verify-net")) or opt.command == "configure":
            if input_fn is None:
                input_fn = lambda: sys.stdin.readline()
            output.write(INTRO)
            output.write("\n### Configuration\n\n")
            run_dialog(opt, ini, input_fn=input_fn, output=output, key_check=key_check)
            if write:
                write_ini(ini, opt.conf_path())
                output.write(f"Configuration saved to {opt.conf_path()}.\n")
        if not opt.is_systemd():
            merge_ini(opt, ini)

    # Cap cores at what the machine has (configure.rs:602-612).
    if opt.cores and opt.cores.isdigit() and int(opt.cores) > available_cores():
        output.write(
            f"W: Requested {opt.cores} cores, but only {available_cores()} available. Capped.\n"
        )
        opt.cores = "all"

    return opt
