"""Ring attention: sequence-parallel attention over a mesh axis.

The reference has nothing transformer-like — its long-sequence analogue
is splitting a game into per-ply searches (SURVEY.md §5). This op is the
real thing for the framework's model side: when a sequence model (e.g. a
game-history policy net) outgrows one chip's memory, the sequence axis
shards across devices and attention runs as a ring — each device holds
its local Q forever, while K/V blocks rotate around the mesh axis via
``ppermute`` (ICI neighbor exchange, no all-gather), accumulating the
softmax online flash-attention-style. Peak memory per device is O(S/n)
with full-attention semantics and compute overlapped with the rotation.

Layout: inputs are [batch, seq_shard, heads, head_dim] per device under
``shard_map`` (sequence axis sharded over the given mesh axis). The
causal variant masks by absolute position, handled via the rotating
block's global offset.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "reference_attention"]


def _block_attend(q, k, v, mask):
    """One Q-block x K/V-block pass returning (scores_max, exp_sums,
    weighted_values) for online-softmax accumulation."""
    # q: [B, Sq, H, D]; k/v: [B, Sk, H, D]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1])
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    block_max = jnp.max(logits, axis=-1)  # [B, H, Sq]
    # Guard fully-masked rows: exp(-inf - -inf) would be NaN.
    safe_max = jnp.where(jnp.isfinite(block_max), block_max, 0.0)
    p = jnp.exp(logits - safe_max[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    block_sum = jnp.sum(p, axis=-1)  # [B, H, Sq]
    block_out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return safe_max, block_sum, block_out


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str,
    causal: bool = False,
) -> jax.Array:
    """Full-sequence attention with the sequence axis sharded over
    ``axis``. q, k, v: [batch, seq, heads, head_dim] GLOBAL shapes; the
    function shard_maps internally and returns the globally-sharded
    output with the same layout."""
    n = mesh.shape[axis]

    def local(q_blk, k_blk, v_blk):
        idx = jax.lax.axis_index(axis)
        s_local = q_blk.shape[1]

        def make_mask(kv_owner):
            if not causal:
                return None
            q_pos = idx * s_local + jnp.arange(s_local)  # [Sq]
            k_pos = kv_owner * s_local + jnp.arange(s_local)  # [Sk]
            return (q_pos[:, None] >= k_pos[None, :])[None, None, :, :]

        def merge(acc, owner, k_cur, v_cur):
            m_run, l_run, o_run = acc
            mask = make_mask(owner)
            b_max, b_sum, b_out = _block_attend(q_blk, k_cur, v_cur, mask)
            # Online softmax merge (flash-attention recurrence).
            new_max = jnp.maximum(m_run, b_max)
            alpha = jnp.exp(m_run - new_max)  # rescale old accumulators
            beta = jnp.exp(b_max - new_max)
            l_new = l_run * alpha + b_sum * beta
            o_new = (
                o_run * alpha.transpose(0, 2, 1)[..., None]
                + b_out * beta.transpose(0, 2, 1)[..., None]
            )
            return new_max, l_new, o_new

        def step(carry, _):
            # Rotate first, then attend: the local block was consumed
            # before the scan, so exactly n-1 rotations happen and none
            # is discarded.
            k_cur, v_cur, owner, m_run, l_run, o_run = carry
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            owner_nxt = (owner - 1) % n  # we now hold the previous device's block
            m_new, l_new, o_new = merge(
                (m_run, l_run, o_run), owner_nxt, k_nxt, v_nxt
            )
            return (k_nxt, v_nxt, owner_nxt, m_new, l_new, o_new), None

        b, s, h, d = q_blk.shape
        m0 = jnp.full((b, h, s), -jnp.inf, q_blk.dtype)
        l0 = jnp.zeros((b, h, s), q_blk.dtype)
        # Newer jax tracks varying-over-mesh-axes types through scan:
        # constant-initialized carries must be marked varying explicitly.
        if hasattr(jax.lax, "pvary"):
            m0 = jax.lax.pvary(m0, (axis,))
            l0 = jax.lax.pvary(l0, (axis,))
        # Local block first (no rotation), then n-1 rotate-and-attend hops.
        m0, l0, o0 = merge((m0, l0, jnp.zeros_like(q_blk)), idx, k_blk, v_blk)
        (k_f, v_f, _, m_f, l_f, o_f), _ = jax.lax.scan(
            step, (k_blk, v_blk, idx, m0, l0, o0), None, length=n - 1
        )
        del k_f, v_f
        denom = jnp.maximum(l_f, 1e-20).transpose(0, 2, 1)[..., None]
        return o_f / denom

    try:
        from jax import shard_map  # jax >= 0.8 (no check_rep param)

        kwargs = {}
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        kwargs = {"check_rep": False}

    spec = P(None, axis, None, None)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **kwargs,
    )(q, k, v)


def reference_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Single-device reference for parity tests."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(q.shape[-1])
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
