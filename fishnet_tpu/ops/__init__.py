"""Custom TPU ops: Pallas kernels.

* ``ft_gather`` — fused NNUE feature-transformer gather-accumulate,
  the evaluator's hot op (Pallas, XLA fallback), including the sparse
  mode behind incremental (delta) evaluation.

(A ring-attention op existed through round 1 but was deliberately
removed: nothing in this workload is transformer-shaped — SURVEY.md §5
records sequence parallelism as n/a, the "long context" analogue here
is scaling the eval batch, and a tested-but-unused op is negative
value. See git history if a game-history model ever motivates it.)
"""

from fishnet_tpu.ops.ft_gather import ft_accumulate

__all__ = ["ft_accumulate"]
