"""Custom TPU ops: Pallas kernels and mesh collectives.

* ``ft_gather`` — fused NNUE feature-transformer gather-accumulate,
  the evaluator's hot op (Pallas, XLA fallback).
* ``ring_attention`` — sequence-parallel attention over a mesh axis
  (shard_map + ppermute ring, flash-style online softmax).
"""

from fishnet_tpu.ops.ft_gather import ft_accumulate
from fishnet_tpu.ops.ring_attention import reference_attention, ring_attention

__all__ = ["ft_accumulate", "reference_attention", "ring_attention"]
