"""Custom TPU kernels (Pallas) with XLA fallbacks.

* ``ft_gather`` — fused NNUE feature-transformer gather-accumulate,
  the evaluator's hot op.
"""

from fishnet_tpu.ops.ft_gather import ft_accumulate

__all__ = ["ft_accumulate"]
