"""Pallas TPU kernel for the NNUE feature-transformer gather-accumulate.

The feature transformer is the NNUE hot op: for every position and both
perspectives, sum ~30 sparse rows of a [22529, 1024] int16 table and add
the bias. XLA's take+sum lowers to a dynamic-gather that materializes a
[B, 2, 32, 1024] int16 intermediate in HBM (128 MiB at B=1024) and then
reduces it — every gathered byte crosses HBM twice. This kernel streams
each row HBM->VMEM exactly once with 32 concurrent row DMAs per
accumulator and reduces in VMEM, so the traffic is the 64 KiB of rows
per accumulator and the 4 KiB result, nothing else.

The weight table stays resident in HBM (46 MiB > VMEM); row addresses
are data-dependent, which is exactly what PrefetchScalarGridSpec's
scalar-prefetched index argument enables: the indices are available
before the kernel body, so the DMAs can be issued immediately.

Used by jax_eval.evaluate_batch on TPU backends; the plain XLA path
remains the fallback (CPU tests, odd shapes) and the parity test runs
this kernel in interpreter mode against it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fishnet_tpu.nnue.spec import DELTA_SLOTS as _DELTA_SLOTS

__all__ = ["ft_accumulate"]


def _xla_ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    delta_base: int | None = None,
) -> jax.Array:
    if delta_base is not None:
        # Removal encodings (delta_base + f) subtract row f; their pads
        # decode to the zero sentinel, so the sign is irrelevant there.
        is_rem = indices >= delta_base
        indices = jnp.where(is_rem, indices - delta_base, indices)
        sign = jnp.where(is_rem, -1, 1)
    rows = jnp.take(ft_w, indices, axis=0).astype(jnp.int32)  # [B, 2, A, L1]
    if delta_base is not None:
        rows = rows * sign[..., None]
    return ft_b.astype(jnp.int32) + jnp.sum(rows, axis=2)


#: Slot budget of the SPARSE mode, per perspective: incremental (delta)
#: entries carry up to DELTA_SLOTS added rows in slots [0, DELTA_SLOTS)
#: and up to DELTA_SLOTS removed rows (encoded delta_base + f) in slots
#: [DELTA_SLOTS, 2*DELTA_SLOTS), each region padded with its own
#: sentinel. The kernel fetches exactly these 2*DELTA_SLOTS slots,
#: pads included (sentinel rows are zero, so sums stay exact), and
#: reduces adds minus removes. Both modes are branch-free per row —
#: per-row control flow (predicates or dynamic loops) was measured to
#: cost MORE than the padded DMAs it avoids; a 4x shorter unrolled loop
#: is what cashes in the gather's ~12 ns/row DMA-count bound.
#: The slot count _DELTA_SLOTS (imported above) is the WIRE contract
#: shared with the native pool (spec.DELTA_SLOTS == cpp/src/nnue.h
#: NNUE_DELTA_SLOTS).
_SPARSE_SLOTS = 2 * _DELTA_SLOTS


def _kernel(idx_ref, sparse_ref, ft_ref, bias_ref, out_ref, rows, sems, *,
            delta_base):
    # Software-pipelined gather: scratch holds TWO positions' rows. Grid
    # step b waits on the buffer its predecessor filled for it, issues
    # position b+1's row DMAs into the other buffer, then reduces — so
    # row copies stay in flight at all times and the HBM pipe never
    # drains between positions. Row addresses come from the scalar-
    # prefetched index operand, available before the body runs.
    #
    # Per-position mode, a pure function of the scalar-prefetched sparse
    # flags (so the issuing step for b+1 and the waiting step at b+1
    # always agree): sparse (incremental/delta) entries touch only
    # _SPARSE_SLOTS slots per perspective — removal slots' indices are
    # decoded by subtracting delta_base — while dense entries fetch all
    # slots as plain additions.
    b = pl.program_id(0)
    n = pl.num_programs(0)
    n_active = rows.shape[1] // 2  # both perspectives share a buffer

    def transfer(pos, slot, start, limit, is_sparse):
        # Each feature row is one native (sub, 128) int16 tile, so
        # single-row HBM slices stay tile-aligned.
        for p in range(2):
            for k in range(limit):
                idx = idx_ref[pos, p, k]
                if is_sparse and k >= _DELTA_SLOTS:
                    idx = idx - delta_base  # removal slot: decode
                i = p * n_active + k
                dma = pltpu.make_async_copy(
                    ft_ref.at[idx], rows.at[slot, i], sems.at[slot, i],
                )
                dma.start() if start else dma.wait()

    def both_modes(pos, fn):
        # fn(limit, is_sparse); the flag is explicit rather than inferred
        # from the limit so a dense n_active equal to _SPARSE_SLOTS could
        # never alias into removal decoding.
        if delta_base is None:
            fn(n_active, False)
            return
        sparse = sparse_ref[pos] != 0

        @pl.when(sparse)
        def _():
            fn(_SPARSE_SLOTS, True)

        @pl.when(jnp.logical_not(sparse))
        def _():
            fn(n_active, False)

    slot = jax.lax.rem(b, 2)

    @pl.when(b == 0)
    def _():
        both_modes(0, lambda lim, sp: transfer(0, 0, True, lim, sp))

    @pl.when(b + 1 < n)
    def _():
        nxt = jax.lax.rem(b + 1, 2)
        both_modes(b + 1, lambda lim, sp: transfer(b + 1, nxt, True, lim, sp))

    both_modes(b, lambda lim, sp: transfer(b, slot, False, lim, sp))

    bias = bias_ref[:].astype(jnp.int32)

    def reduce(limit, is_sparse):
        # jnp.sum (tree reduction), not a serial add chain.
        for p in range(2):
            base = p * n_active
            if is_sparse:
                adds = jnp.sum(
                    rows[slot, base : base + _DELTA_SLOTS].astype(jnp.int32),
                    axis=0,
                )
                rems = jnp.sum(
                    rows[slot, base + _DELTA_SLOTS : base + _SPARSE_SLOTS]
                    .astype(jnp.int32),
                    axis=0,
                )
                out_ref[0, p] = bias + adds - rems
            else:
                out_ref[0, p] = bias + jnp.sum(
                    rows[slot, base : base + limit].astype(jnp.int32), axis=0
                )

    both_modes(b, reduce)


# Positions per pallas_call: the scalar-prefetch index operand lives in
# SMEM (1 MiB, shared with Mosaic's own scalar state — 1024-position
# chunks overflow it by a hair), so the whole batch's indices cannot
# ride one call; each call costs a launch plus a pipeline fill/drain,
# so use the largest chunk that reliably fits ([512, 2, 32] int32 =
# 128 KiB).
_CHUNK = 512


@functools.partial(jax.jit, static_argnames=("interpret", "delta_base"))
def _pallas_ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    sparse: Optional[jax.Array] = None,
    interpret: bool = False,
    delta_base: int | None = None,
) -> jax.Array:
    batch, persp, n_active = indices.shape
    l1 = ft_w.shape[1]
    assert persp == 2, "indices must be [B, 2, MAX_ACTIVE]"
    assert l1 % 1024 == 0, "L1 must fold into whole (8, 128) int16 tiles"
    sub = l1 // 128  # sublane count of one feature row viewed as a tile

    # View each L1-wide row as an (sub, 128) tile so single-row HBM
    # slices are tile-aligned (Mosaic requires sublane multiples of 8).
    ft_tiles = ft_w.reshape(ft_w.shape[0], sub, 128)
    bias_tile = ft_b.reshape(sub, 128)

    def run_chunk(idx_chunk: jax.Array, sparse_chunk: jax.Array) -> jax.Array:
        chunk = idx_chunk.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # indices + per-position sparse flags
            grid=(chunk,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),  # ft_w stays in HBM
                pl.BlockSpec(memory_space=pltpu.VMEM),  # bias
            ],
            out_specs=pl.BlockSpec(
                (1, 2, sub, 128), lambda b, idx_ref, sparse_ref: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2, 2 * n_active, sub, 128), ft_w.dtype),
                pltpu.SemaphoreType.DMA((2, 2 * n_active)),
            ],
        )
        return pl.pallas_call(
            functools.partial(_kernel, delta_base=delta_base),
            out_shape=jax.ShapeDtypeStruct((chunk, 2, sub, 128), jnp.int32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(idx_chunk, sparse_chunk, ft_tiles, bias_tile)

    idx = indices.astype(jnp.int32)
    flags = (
        jnp.zeros((batch,), jnp.int32)
        if sparse is None
        else sparse.astype(jnp.int32)
    )
    outs = [
        run_chunk(idx[start : start + _CHUNK], flags[start : start + _CHUNK])
        for start in range(0, batch, _CHUNK)
    ]
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    return out.reshape(batch, persp, l1)


def ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    *,
    use_pallas: bool | None = None,
    interpret: bool = False,
    delta_base: int | None = None,
    sparse: Optional[jax.Array] = None,
) -> jax.Array:
    """Feature-transformer accumulators, bias included: int32 [B, 2, L1].

    ``ft_w`` [rows, L1] int16 whose LAST row is the zero sentinel;
    ``ft_b`` [L1] int16; ``indices`` integer [B, 2, MAX_ACTIVE] padded
    with the sentinel index. With ``delta_base`` set, rows flagged by
    ``sparse`` (bool [B]) are incremental (delta) entries following the
    spec.DELTA_SLOTS wire contract: adds in the first slots, removals
    (encoded delta_base + f) after them — the fused kernel fetches only
    those few slots and subtracts the removal rows, which is where
    incremental eval's DMA savings land. ``use_pallas=None``
    auto-selects: the fused kernel on TPU backends when shapes conform
    (lane-aligned L1), XLA otherwise.
    """
    indices = indices.astype(jnp.int32)
    if use_pallas is None:
        use_pallas = (
            jax.default_backend() == "tpu" and ft_w.shape[1] % 1024 == 0
        )
    if use_pallas or interpret:
        return _pallas_ft_accumulate(
            ft_w, ft_b, indices, sparse,
            interpret=interpret, delta_base=delta_base,
        )
    return _xla_ft_accumulate(ft_w, ft_b, indices, delta_base=delta_base)
