"""Pallas TPU kernel for the NNUE feature-transformer gather-accumulate.

The feature transformer is the NNUE hot op: for every position and both
perspectives, sum ~30 sparse rows of a [22529, 1024] int16 table and add
the bias. XLA's take+sum lowers to a dynamic-gather that materializes a
[B, 2, 32, 1024] int16 intermediate in HBM (128 MiB at B=1024) and then
reduces it — every gathered byte crosses HBM twice. This kernel streams
each row HBM->VMEM exactly once with 32 concurrent row DMAs per
accumulator and reduces in VMEM, so the traffic is the 64 KiB of rows
per accumulator and the 4 KiB result, nothing else.

The weight table stays resident in HBM (46 MiB > VMEM); row addresses
are data-dependent, which is exactly what PrefetchScalarGridSpec's
scalar-prefetched index argument enables: the indices are available
before the kernel body, so the DMAs can be issued immediately.

Incremental (delta) entries are RESOLVED in-kernel (round 3): the native
pool guarantees every delta entry references the most recent preceding
FULL entry of the same batch (cpp/src/pool.cpp evaluate_block's anchor
protocol), so the kernel keeps one running "anchor" accumulator in VMEM
scratch — full entries refresh it, delta entries add their few delta
rows to it (perspective-swapped when the sides to move differ). Round 2
instead shipped partial accumulators and resolved references with a
batch-wide XLA gather over [B, 2, L1] int32 — a full extra HBM pass
(~2 ms per 16k batch) that this design deletes outright.

The same pass now also produces the [B, 2, 8] PSQT accumulator
(``ft_psqt`` given): the PSQT columns ride the same decoded index
stream as 32-byte DMAs next to the 2 KiB feature rows, with the same
running-anchor discipline and a persistent anchor-PSQT table next to
the accumulator table — so anchor-code entries resolve ENTIRELY on
device and the wire no longer needs the host-computed material term
(doc/wire-format.md).

Used by jax_eval.evaluate_batch on TPU backends; the plain XLA path
remains the fallback (CPU tests, odd shapes) and the parity test runs
this kernel in interpreter mode against it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fishnet_tpu.nnue.spec import DELTA_SLOTS as _DELTA_SLOTS
from fishnet_tpu.utils.tracing import is_concrete

__all__ = [
    "ft_accumulate",
    "derive_segment_offsets",
    "recode_segment_parents",
    "plan_segment_dedup",
]

#: Accumulator poison for persistent anchor codes evaluated WITHOUT an
#: anchor table.  Under tracing the misuse cannot raise (the values are
#: not inspectable), so the structural guard stamps the affected
#: entries' accumulators with this constant instead: every lane clips to
#: zero downstream, collapsing the entry's eval to a per-bucket constant
#: — loudly broken, unlike the plausibly-wrong unresolved partials the
#: old code returned.  Direct consumers of the accumulator see -2^30.
_POISON_ACC = -(1 << 30)


def _xla_ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    delta_base: int | None = None,
) -> jax.Array:
    if delta_base is not None:
        # Removal encodings (delta_base + f) subtract row f; their pads
        # decode to the zero sentinel, so the sign is irrelevant there.
        is_rem = indices >= delta_base
        indices = jnp.where(is_rem, indices - delta_base, indices)
        sign = jnp.where(is_rem, -1, 1)
    rows = jnp.take(ft_w, indices, axis=0).astype(jnp.int32)  # [B, 2, A, L1]
    if delta_base is not None:
        rows = rows * sign[..., None]
    return ft_b.astype(jnp.int32) + jnp.sum(rows, axis=2)


def _xla_psqt_accumulate(
    ft_psqt: jax.Array,
    indices: jax.Array,
    delta_base: int | None = None,
) -> jax.Array:
    """PSQT accumulators over the same index stream as the FT gather:
    int32 [B, 2, 8], no bias term. Removal encodings subtract their row;
    pads decode to the zero sentinel row either way."""
    if delta_base is not None:
        is_rem = indices >= delta_base
        indices = jnp.where(is_rem, indices - delta_base, indices)
        sign = jnp.where(is_rem, -1, 1)
    rows = jnp.take(ft_psqt, indices, axis=0)  # [B, 2, A, 8] int32
    if delta_base is not None:
        rows = rows * sign[..., None]
    return jnp.sum(rows, axis=2)


def _swap_persp(a: jax.Array, swap: jax.Array) -> jax.Array:
    """Swap the perspective axis (axis 1 of [B, 2, ...]) where ``swap``."""
    perm = jnp.where(swap[:, None], jnp.array([1, 0]), jnp.array([0, 1]))
    return jnp.take_along_axis(a, perm[:, :, None], axis=1)


def decode_parent(parent: jax.Array):
    """Split the wire's parent codes (cpp/src/pool.cpp emit_block) into
    masks: -1 plain full; >= 0 in-batch delta (ref << 1 | swap); <= -2
    anchor-entry codes -(2 + v), v = (table_row << 2) | (is_delta << 1)
    | swap — the entry resolves against (is_delta) and/or refreshes
    (always) its device anchor-table row. Returns (in_batch, persistent,
    stores, ref, swap, aid)."""
    parent = parent.astype(jnp.int32)
    v = -parent - 2
    stores = parent <= -2
    persistent = stores & ((v & 2) != 0)
    in_batch = parent >= 0
    ref = jnp.where(in_batch, parent >> 1, 0)
    # Plain fulls (-1) decode v = -1, whose low bit is set: mask the swap
    # bit with (in_batch | stores) so fulls come back swap=0 — otherwise
    # every full entry would grow a phantom perspective-swap flag that
    # only the where-masks downstream happen to ignore today.
    swap = jnp.where(
        in_batch, parent & 1, jnp.where(stores, v & 1, 0)
    ).astype(bool)
    aid = jnp.where(stores, v >> 2, 0)
    return in_batch, persistent, stores, ref, swap, aid


def derive_segment_offsets(parent: jax.Array, seg_rows: jax.Array,
                           tier: int) -> jax.Array:
    """Row offsets for a SEGMENTED (coalesced multi-group) dispatch.

    ``parent`` int32 [K, size] holds each segment's wire parent codes;
    ``seg_rows`` int32 [K] each segment's emitted row count; ``tier``
    is the common per-segment row tier of the concatenated [K*tier]
    stream. Per segment the offsets are the usual exclusive cumsum
    (4 rows per full entry, 1 per delta), but each segment's padding
    clamps into ITS OWN sentinel block at ``seg_rows[k]`` and the whole
    segment shifts by ``k*tier`` — offsets never cross a segment
    boundary, the same invariant the sharded repack enforces per shard
    (search/service.py _dispatch_sharded_packed). Returns flat int32
    [K*size] offsets into the concatenated row stream."""
    parent = parent.astype(jnp.int32)
    k_segs = parent.shape[0]
    in_batch, persistent, _, _, _, _ = decode_parent(parent.reshape(-1))
    is_delta = (in_batch | persistent).reshape(parent.shape)
    rows_per = jnp.where(is_delta, 1, 4)
    local = jnp.cumsum(rows_per, axis=1) - rows_per  # exclusive per segment
    local = jnp.minimum(local, seg_rows.astype(jnp.int32)[:, None])
    base = (jnp.arange(k_segs, dtype=jnp.int32) * jnp.int32(tier))[:, None]
    return (local + base).reshape(-1)


def recode_segment_parents(parent: jax.Array, anchor_rows: int) -> jax.Array:
    """Rebase segment-local wire parent codes into the fused frame of a
    segmented dispatch. ``parent`` int32 [K, size]; ``anchor_rows`` is
    one group's anchor-table row count A (the stacked [K, A, ...]
    tables flatten to [K*A, ...]).

    In-batch refs (code ``ref << 1 | swap``) shift by the segment's
    entry base ``k*size``; persistent anchor codes (``-(2 + v)``,
    ``v = (row << 2) | bits``) shift their table row by the segment's
    table base ``k*A``; plain fulls (-1) pass through. Because the pool
    guarantees every group batch STARTS with an anchor entry (full or
    persistent), the fused kernel's running in-VMEM anchor resets at
    each segment's first entry and never leaks across a segment
    boundary — the recoded stream satisfies exactly the contract the
    single-group kernel (and its bit-identical XLA twin,
    _xla_resolve_parents) already enforce, so no new kernel mode is
    needed. Returns flat int32 [K*size]."""
    parent = parent.astype(jnp.int32)
    k_segs, size = parent.shape
    entry_base = (jnp.arange(k_segs, dtype=jnp.int32) * size)[:, None]
    tab_base = (jnp.arange(k_segs, dtype=jnp.int32) * anchor_rows)[:, None]
    out = jnp.where(parent >= 0, parent + (entry_base << 1), parent)
    out = jnp.where(parent <= -2, parent - (tab_base << 2), out)
    return out.reshape(-1)


def plan_segment_dedup(parents, buckets, offsets, ns, packed, material=None,
                       hashes=None, cache_hits=None):
    """Plan cross-segment eval-dedup for ONE fused (coalesced) dispatch:
    deterministic, pure host-side planning (numpy in, plain lists out).

    Per-slot emission cannot see these duplicates — the in-step dedup
    was DELETED per VERDICT r4 because WITHIN one group it retired only
    ~0.1% of evals while its hash build sat on the per-step hot path —
    but ACROSS the segments of one fused dispatch, sibling groups
    searching adjacent plies of the same game routinely evaluate the
    same transpositions in the same step. Here the planning cost rides
    the async pack worker, off the driver threads entirely.

    Inputs are per-segment host views (only the first ``ns[k]`` entries
    of each are read):

    * ``parents``: int32 [size] segment-local wire parent codes
    * ``buckets``: int32 [size] layer-stack bucket ids
    * ``offsets``: int32 [size] each entry's row offset into its
      segment's packed stream (the host copy; the device re-derives)
    * ``ns``: real entry counts
    * ``packed``: uint16 [rows_k, 2, 8] row streams
    * ``material``: optional int32 [size] host-material columns

    A DUPLICATE is a plain full (code -1) whose 4-row feature block —
    keyed with its bucket (and material when shipped) — matches an
    earlier 4-row entry anywhere in the dispatch, provided it has no
    in-batch consumer and is not its segment's first entry. The anchor
    protocol makes removal safe: a full with no consumer is, by the
    most-recent-anchor rule, immediately followed by another anchor
    entry (or padding), so re-encoding it as a one-row sentinel
    in-batch delta never disturbs any other entry's resolution — the
    replacement computes garbage on device and its true value is
    restored host-side from its original (_FusedValues).

    POSITION-KEYED MODE (doc/eval-cache.md): when ``hashes`` carries
    per-segment uint64 Zobrist arrays, the dedup key is the position
    hash itself instead of the 4-row byte image — bucket and material
    are pure functions of the position, so the hash subsumes them, and
    a duplicate now matches ANY earlier kept entry decoding to the same
    position (delta-encoded entries included; a delta's device output
    is its true eval, so it is a valid fan-out source). The droppable
    set widens to EVERY encoding, because anchored traffic is ~100%
    persistent codes (each block's entry 0 stores its anchor row) and a
    plain-full-only rule would never fire:

    * plain fulls and in-batch deltas re-encode as the one-row sentinel
      in-batch delta exactly as before (nothing resolves through them —
      unconsumed — and they write no table row);
    * PERSISTENT codes (<= -2) re-encode as a one-row sentinel
      persistent DELTA that KEEPS the original aid and store bit, so
      the entry still refreshes its anchor-table row on device. The
      bytes it stores are made correct by the eval's ``copy_src``
      fan-in gather (_packed_anchored_core): the duplicate's resolved
      accumulator is replaced by its same-position source's before the
      head eval and the scatter. A persistent drop therefore REQUIRES
      an in-dispatch source (a ``pairs`` entry) — cache-satisfied fills
      have no device accumulator to store, so cache drops stay
      restricted to plain fulls and in-batch deltas.
    ``cache_hits`` (optional, per-segment ``(mask, values)`` from the
    driver's pre-dispatch probe) additionally drops droppable entries
    whose eval the process-wide cache already knows.

    Returns ``(drops, refs, pairs)``: per-segment lists of dropped
    entry indices, the replacement-code metadata, and global
    ``(dst_seg, dst_idx, src_seg, src_idx)`` value overwrites (every
    duplicate maps to the FIRST occurrence, which is by construction
    never itself dropped). ``refs`` in BYTE mode are in-batch anchor
    indices (the caller writes ``ref << 1``, swap 0 — the most recent
    preceding KEPT anchor, always present since entry 0 is an anchor
    and never dropped); in POSITION-KEYED mode they are ready-to-write
    WIRE PARENT CODES (sentinel in-batch delta or sentinel persistent
    delta, per the drop's original encoding). In position-keyed mode a
    FOURTH element is returned: ``fills`` — ``(seg, idx, value)``
    cache-satisfied drops whose value comes from the cache, not from
    another entry of this dispatch."""
    import numpy as np

    n_segs = len(parents)
    seen = {}
    fill_vals = {}  # hash -> cached value (position-keyed mode)
    drops = [[] for _ in range(n_segs)]
    refs = [[] for _ in range(n_segs)]
    pairs = []
    fills = []
    for k in range(n_segs):
        n = int(ns[k])
        if n <= 0:
            continue
        p = np.asarray(parents[k][:n])
        consumed = np.zeros(n, dtype=bool)
        inb = p >= 0
        if inb.any():
            consumed[p[inb] >> 1] = True
        # Anchor entries (fulls and persistent codes) vs 4-row entries
        # (fulls and persistent FULLS — persistent deltas ship 1 row).
        is_anchor = (p == -1) | (p <= -2)
        is_full4 = (p == -1) | ((p <= -2) & ((((-p - 2) >> 1) & 1) == 0))
        off = np.asarray(offsets[k][:n])
        rows = packed[k]
        hseg = None if hashes is None else hashes[k]
        cmask = cvals = None
        if cache_hits is not None and cache_hits[k] is not None:
            cmask, cvals = cache_hits[k]
        last_anchor = 0
        for i in range(n):
            dropped = False
            if hseg is not None:
                h = int(hseg[i])
                pers = bool(p[i] <= -2)
                droppable = not consumed[i] and i > 0
                # A persistent drop still stores its anchor row: its
                # sentinel keeps aid + store bit (delta form, swap 0)
                # and the copy_src gather supplies the true bytes.
                sentinel = (
                    -(2 + ((((-int(p[i]) - 2) >> 2) << 2) | 2))
                    if pers else (last_anchor << 1)
                )
                src = seen.get(h)
                if droppable and src is not None:
                    # Fan out from the earlier kept entry (any wire
                    # encoding — its device output is the true eval).
                    drops[k].append(i)
                    refs[k].append(sentinel)
                    pairs.append((k, i, src[0], src[1]))
                    dropped = True
                elif droppable and not pers and cmask is not None \
                        and cmask[i]:
                    drops[k].append(i)
                    refs[k].append(sentinel)
                    fills.append((k, i, int(cvals[i])))
                    fill_vals.setdefault(h, int(cvals[i]))
                    dropped = True
                elif droppable and not pers and h in fill_vals:
                    # Duplicate of an entry that itself left the wire on
                    # a cache hit: same cached value, no device source.
                    drops[k].append(i)
                    refs[k].append(sentinel)
                    fills.append((k, i, fill_vals[h]))
                    dropped = True
                elif src is None:
                    seen[h] = (k, i)
            elif is_full4[i]:
                key = (int(buckets[k][i]),
                       rows[off[i] : off[i] + 4].tobytes())
                if material is not None:
                    key = key + (int(material[k][i]),)
                src = seen.get(key)
                if (src is not None and p[i] == -1
                        and not consumed[i] and i > 0):
                    drops[k].append(i)
                    refs[k].append(last_anchor)
                    pairs.append((k, i, src[0], src[1]))
                    dropped = True
                elif src is None:
                    seen[key] = (k, i)
            if not dropped and is_anchor[i]:
                last_anchor = i
    if hashes is not None:
        return drops, refs, pairs, fills
    return drops, refs, pairs


def _xla_resolve_parents(
    acc: jax.Array,
    bias: jax.Array,
    parent: jax.Array,
    anchor_tab: Optional[jax.Array] = None,
) -> jax.Array:
    """Resolve incremental entries of an XLA-partials accumulator batch
    (see decode_parent for the codes). Two passes: persistent deltas
    resolve against their anchor-table rows first (anchor entries are
    never in-batch deltas, so their resolution is final), then in-batch
    deltas gather their — now resolved — anchor entries. Exact: integer
    adds commute, so delta partial + referenced accumulator - (the
    doubly counted) bias is bit-identical to a full gather.

    ``bias`` is whatever the partials already include and must not be
    double-counted: the FT bias for the feature-transformer accumulator,
    a zero scalar for the (bias-free) PSQT accumulator. Works for any
    trailing accumulator shape ([B, 2, L1] and [B, 2, 8] alike)."""
    in_batch, persistent, _, ref, swap, aid = decode_parent(parent)
    if anchor_tab is not None:
        tab_acc = _swap_persp(
            jnp.take(anchor_tab.astype(jnp.int32), aid, axis=0), swap
        )
        acc = jnp.where(persistent[:, None, None], acc + tab_acc - bias, acc)
    else:
        # Structural misuse guard (works under tracing, where the eager
        # check in ft_accumulate cannot see the codes): persistent
        # entries have no table to resolve against — poison them instead
        # of returning unresolved partials that read as plausible evals.
        acc = jnp.where(
            persistent[:, None, None], jnp.int32(_POISON_ACC), acc
        )
    ref_acc = _swap_persp(jnp.take(acc, ref, axis=0), swap)
    return jnp.where(in_batch[:, None, None], acc + ref_acc - bias, acc)


#: Slot budget of the SPARSE mode, per perspective: incremental (delta)
#: entries carry up to DELTA_SLOTS added rows in slots [0, DELTA_SLOTS)
#: and up to DELTA_SLOTS removed rows (encoded delta_base + f) in slots
#: [DELTA_SLOTS, 2*DELTA_SLOTS), each region padded with its own
#: sentinel. The kernel fetches exactly these 2*DELTA_SLOTS slots,
#: pads included (sentinel rows are zero, so sums stay exact), and
#: reduces adds minus removes. Both modes are branch-free per row —
#: per-row control flow (predicates or dynamic loops) was measured to
#: cost MORE than the padded DMAs it avoids; a 4x shorter unrolled loop
#: is what cashes in the gather's ~12 ns/row DMA-count bound.
#: The slot count _DELTA_SLOTS (imported above) is the WIRE contract
#: shared with the native pool (spec.DELTA_SLOTS == cpp/src/nnue.h
#: NNUE_DELTA_SLOTS).
_SPARSE_SLOTS = 2 * _DELTA_SLOTS


def _kernel(idx_ref, flags_ref, aid_ref, ft_ref, bias_ref, carry_ref,
            tab_ref, *rest, delta_base, anchored, with_psqt):
    # Software-pipelined gather: scratch holds TWO positions' rows. Grid
    # step b waits on the buffer its predecessor filled for it, issues
    # position b+1's row DMAs into the other buffer, then reduces — so
    # row copies stay in flight at all times and the HBM pipe never
    # drains between positions. Row addresses come from the scalar-
    # prefetched index operand, available before the body runs.
    #
    # FUSED PSQT (with_psqt): the same index stream also drives a second,
    # tiny DMA per row — the feature's 8-bucket PSQT column (32 bytes vs
    # the 2 KiB FT row, so the extra traffic is noise against the row
    # DMAs it rides with) — and the reduce produces a second [2, 8]
    # accumulator per position with the SAME anchor discipline (running
    # in-VMEM anchor, persistent rows from a [A, 2, 8] anchor-PSQT
    # table). Integer adds commute, so the fused PSQT is bit-identical
    # to the XLA gather path and to the host-side material walk the wire
    # used to ship.
    #
    # Per-position flags (scalar-prefetched, so the issuing step for b+1
    # and the waiting step at b+1 always agree): bit 0 = sparse
    # (incremental/delta) entry touching only _SPARSE_SLOTS slots per
    # perspective with removal slots decoded by subtracting delta_base;
    # bit 1 (anchored mode) = the entry's perspectives are swapped
    # relative to its anchor; bit 2 (anchored mode) = PERSISTENT — the
    # anchor is not the running in-batch one but row aid_ref[b] of the
    # HBM anchor table (the accumulator this entry's pool slot stored in
    # a previous batch), DMA'd into the pa scratch alongside the delta
    # rows (~8 KB vs the ~120 KB of a full gather). Dense entries fetch
    # all slots as plain additions. Table WRITES happen outside the
    # kernel (jax_eval scatters the output accumulators of anchor
    # entries back into the table).
    if with_psqt:
        (pq_ref, pcarry_ref, ptab_ref, out_ref, pout_ref, rows, sems,
         anchor, pa, pa_sems, pq_rows, pq_sems, pq_anchor, pq_pa,
         pq_pa_sems) = rest
    else:
        out_ref, rows, sems, anchor, pa, pa_sems = rest

    b = pl.program_id(0)
    n = pl.num_programs(0)
    n_active = rows.shape[1] // 2  # both perspectives share a buffer

    def transfer(pos, slot, start, limit, is_sparse):
        # Each feature row is one native (sub, 128) int16 tile, so
        # single-row HBM slices stay tile-aligned. The PSQT column rides
        # the same decoded index (32-byte DMA alongside the 2 KiB row).
        for p in range(2):
            for k in range(limit):
                idx = idx_ref[pos, p, k]
                if is_sparse and k >= _DELTA_SLOTS:
                    idx = idx - delta_base  # removal slot: decode
                i = p * n_active + k
                dma = pltpu.make_async_copy(
                    ft_ref.at[idx], rows.at[slot, i], sems.at[slot, i],
                )
                dma.start() if start else dma.wait()
                if with_psqt:
                    pdma = pltpu.make_async_copy(
                        pq_ref.at[idx], pq_rows.at[slot, i],
                        pq_sems.at[slot, i],
                    )
                    pdma.start() if start else pdma.wait()

    def both_modes(pos, fn):
        # fn(limit, is_sparse); the flag is explicit rather than inferred
        # from the limit so a dense n_active equal to _SPARSE_SLOTS could
        # never alias into removal decoding.
        if delta_base is None:
            fn(n_active, False)
            return
        sparse = (flags_ref[pos] & 1) != 0

        @pl.when(sparse)
        def _():
            fn(_SPARSE_SLOTS, True)

        @pl.when(jnp.logical_not(sparse))
        def _():
            fn(n_active, False)

    def anchor_dma(pos, slot, start):
        # One DMA for the whole [2, sub, 128] anchor row (plus its
        # [2, 8] PSQT twin when fused); issued/awaited only for
        # persistent entries (scalar-prefetched flag, so the issuing
        # step for b+1 and the waiting step at b+1 agree).
        if not anchored:
            return

        @pl.when((flags_ref[pos] & 4) != 0)
        def _():
            dma = pltpu.make_async_copy(
                tab_ref.at[aid_ref[pos]], pa.at[slot], pa_sems.at[slot]
            )
            dma.start() if start else dma.wait()
            if with_psqt:
                pdma = pltpu.make_async_copy(
                    ptab_ref.at[aid_ref[pos]], pq_pa.at[slot],
                    pq_pa_sems.at[slot],
                )
                pdma.start() if start else pdma.wait()

    slot = jax.lax.rem(b, 2)

    @pl.when(b == 0)
    def _():
        both_modes(0, lambda lim, sp: transfer(0, 0, True, lim, sp))
        anchor_dma(0, 0, True)
        if anchored:
            # Chunk carry-in: the anchor as of the end of the previous
            # chunk (zeros for the first — the pool guarantees batch
            # entry 0 is an anchor entry, so it is never read there).
            anchor[...] = carry_ref[...]
            if with_psqt:
                pq_anchor[...] = pcarry_ref[...]

    @pl.when(b + 1 < n)
    def _():
        nxt = jax.lax.rem(b + 1, 2)
        both_modes(b + 1, lambda lim, sp: transfer(b + 1, nxt, True, lim, sp))
        anchor_dma(b + 1, nxt, True)

    both_modes(b, lambda lim, sp: transfer(b, slot, False, lim, sp))
    anchor_dma(b, slot, False)

    bias = bias_ref[...].astype(jnp.int32)

    def reduce_full(limit):
        # jnp.sum (tree reduction), not a serial add chain.
        for p in range(2):
            base = p * n_active
            acc = bias + jnp.sum(
                rows[slot, base : base + limit].astype(jnp.int32), axis=0
            )
            out_ref[0, p] = acc
            if with_psqt:
                pq = jnp.sum(pq_rows[slot, base : base + limit], axis=0)
                pout_ref[0, p] = pq
            if anchored:
                anchor[p] = acc
                if with_psqt:
                    pq_anchor[p] = pq

    def reduce_sparse():
        partial = []
        pq_partial = []
        for p in range(2):
            base = p * n_active
            adds = jnp.sum(
                rows[slot, base : base + _DELTA_SLOTS].astype(jnp.int32),
                axis=0,
            )
            rems = jnp.sum(
                rows[slot, base + _DELTA_SLOTS : base + _SPARSE_SLOTS]
                .astype(jnp.int32),
                axis=0,
            )
            partial.append(adds - rems)
            if with_psqt:
                pq_partial.append(
                    jnp.sum(pq_rows[slot, base : base + _DELTA_SLOTS], axis=0)
                    - jnp.sum(
                        pq_rows[
                            slot, base + _DELTA_SLOTS : base + _SPARSE_SLOTS
                        ],
                        axis=0,
                    )
                )
        if not anchored:
            for p in range(2):
                out_ref[0, p] = bias + partial[p]
                if with_psqt:
                    pout_ref[0, p] = pq_partial[p]
            return
        # Resolve against the running anchor (the most recent anchor
        # entry), or — persistent entries — the anchor-table row DMA'd
        # into pa. Bit 1 says whether the perspectives are swapped.
        swap = (flags_ref[b] & 2) != 0
        persistent = (flags_ref[b] & 4) != 0
        base = [
            jnp.where(persistent, pa[slot, p], anchor[p]) for p in range(2)
        ]
        res = [
            jnp.where(swap, base[1 - p], base[p]) + partial[p]
            for p in range(2)
        ]
        for p in range(2):
            out_ref[0, p] = res[p]
        if with_psqt:
            pq_base = [
                jnp.where(persistent, pq_pa[slot, p], pq_anchor[p])
                for p in range(2)
            ]
            pq_res = [
                jnp.where(swap, pq_base[1 - p], pq_base[p]) + pq_partial[p]
                for p in range(2)
            ]
            for p in range(2):
                pout_ref[0, p] = pq_res[p]

        @pl.when(persistent)
        def _():
            # A resolved persistent entry IS an anchor entry: later
            # in-batch deltas of its block reference it.
            for p in range(2):
                anchor[p] = res[p]
                if with_psqt:
                    pq_anchor[p] = pq_res[p]

    if delta_base is None:
        reduce_full(n_active)
    else:
        sparse = (flags_ref[b] & 1) != 0

        @pl.when(sparse)
        def _():
            reduce_sparse()

        @pl.when(jnp.logical_not(sparse))
        def _():
            reduce_full(n_active)


# Positions per pallas_call: the scalar-prefetch index operand lives in
# SMEM (1 MiB, shared with Mosaic's own scalar state — 1024-position
# chunks overflow it by a hair), so the whole batch's indices cannot
# ride one call; each call costs a launch plus a pipeline fill/drain,
# so use the largest chunk that reliably fits ([512, 2, 32] int32 =
# 128 KiB).
_CHUNK = 512


@functools.partial(
    jax.jit, static_argnames=("interpret", "delta_base", "anchored")
)
def _pallas_ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    flags: Optional[jax.Array] = None,
    anchor_ids: Optional[jax.Array] = None,
    anchor_tab: Optional[jax.Array] = None,
    ft_psqt: Optional[jax.Array] = None,
    psqt_tab: Optional[jax.Array] = None,
    interpret: bool = False,
    delta_base: int | None = None,
    anchored: bool = False,
):
    """Returns [B, 2, L1] int32 accumulators, or — with ``ft_psqt``
    given — the tuple (accumulators, [B, 2, 8] int32 PSQT accumulators)
    from one fused pass over the index stream."""
    batch, persp, n_active = indices.shape
    l1 = ft_w.shape[1]
    with_psqt = ft_psqt is not None
    assert persp == 2, "indices must be [B, 2, MAX_ACTIVE]"
    assert l1 % 1024 == 0, "L1 must fold into whole (8, 128) int16 tiles"
    sub = l1 // 128  # sublane count of one feature row viewed as a tile

    # View each L1-wide row as an (sub, 128) tile so single-row HBM
    # slices are tile-aligned (Mosaic requires sublane multiples of 8).
    ft_tiles = ft_w.reshape(ft_w.shape[0], sub, 128)
    bias_tile = ft_b.reshape(sub, 128)
    if anchor_tab is None:
        # Dummy 1-row table: flag bit 2 is never set without a real
        # table, so the kernel issues no anchor DMAs against it.
        tab_tiles = jnp.zeros((1, 2, sub, 128), jnp.int32)
    else:
        tab_tiles = anchor_tab.astype(jnp.int32).reshape(-1, 2, sub, 128)
    n_buckets = 0
    pq_rows = ptab = None
    if with_psqt:
        n_buckets = ft_psqt.shape[1]
        pq_rows = ft_psqt.astype(jnp.int32)  # [rows, 8] in HBM
        if psqt_tab is None:
            ptab = jnp.zeros((1, 2, n_buckets), jnp.int32)
        else:
            ptab = psqt_tab.astype(jnp.int32)

    def run_chunk(idx_chunk, flags_chunk, aid_chunk, carry, pcarry):
        chunk = idx_chunk.shape[0]
        in_specs = [
            pl.BlockSpec(memory_space=pltpu.ANY),  # ft_w stays in HBM
            pl.BlockSpec(memory_space=pltpu.VMEM),  # bias
            pl.BlockSpec(memory_space=pltpu.VMEM),  # anchor carry-in
            pl.BlockSpec(memory_space=pltpu.ANY),  # anchor table (HBM)
        ]
        out_specs = pl.BlockSpec(
            (1, 2, sub, 128),
            lambda b, idx_ref, flags_ref, aid_ref: (b, 0, 0, 0),
        )
        out_shape = jax.ShapeDtypeStruct((chunk, 2, sub, 128), jnp.int32)
        scratch = [
            pltpu.VMEM((2, 2 * n_active, sub, 128), ft_w.dtype),
            pltpu.SemaphoreType.DMA((2, 2 * n_active)),
            pltpu.VMEM((2, sub, 128), jnp.int32),  # running anchor
            pltpu.VMEM((2, 2, sub, 128), jnp.int32),  # persistent rows
            pltpu.SemaphoreType.DMA((2,)),
        ]
        operands = [idx_chunk, flags_chunk, aid_chunk, ft_tiles, bias_tile,
                    carry, tab_tiles]
        if with_psqt:
            in_specs += [
                pl.BlockSpec(memory_space=pltpu.ANY),  # PSQT columns (HBM)
                pl.BlockSpec(memory_space=pltpu.VMEM),  # PSQT carry-in
                pl.BlockSpec(memory_space=pltpu.ANY),  # anchor-PSQT table
            ]
            out_specs = [
                out_specs,
                pl.BlockSpec(
                    (1, 2, n_buckets),
                    lambda b, idx_ref, flags_ref, aid_ref: (b, 0, 0),
                ),
            ]
            out_shape = [
                out_shape,
                jax.ShapeDtypeStruct((chunk, 2, n_buckets), jnp.int32),
            ]
            scratch += [
                pltpu.VMEM((2, 2 * n_active, n_buckets), jnp.int32),
                pltpu.SemaphoreType.DMA((2, 2 * n_active)),
                pltpu.VMEM((2, n_buckets), jnp.int32),  # running PSQT anchor
                pltpu.VMEM((2, 2, n_buckets), jnp.int32),  # persistent rows
                pltpu.SemaphoreType.DMA((2,)),
            ]
            operands += [pq_rows, pcarry, ptab]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # indices + flags + anchor row ids
            grid=(chunk,),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
        )
        return pl.pallas_call(
            functools.partial(_kernel, delta_base=delta_base,
                              anchored=anchored, with_psqt=with_psqt),
            out_shape=out_shape,
            grid_spec=grid_spec,
            interpret=interpret,
        )(*operands)

    idx = indices.astype(jnp.int32)
    if flags is None:
        flags = jnp.zeros((batch,), jnp.int32)
    else:
        flags = flags.astype(jnp.int32)
    if anchor_ids is None:
        anchor_ids = jnp.zeros((batch,), jnp.int32)
    else:
        anchor_ids = anchor_ids.astype(jnp.int32)
    carry = jnp.zeros((2, sub, 128), jnp.int32)
    pcarry = jnp.zeros((2, n_buckets), jnp.int32) if with_psqt else None
    outs = []
    pouts = []
    for start in range(0, batch, _CHUNK):
        idx_c = idx[start : start + _CHUNK]
        fl_c = flags[start : start + _CHUNK]
        aid_c = anchor_ids[start : start + _CHUNK]
        out = run_chunk(idx_c, fl_c, aid_c, carry, pcarry)
        if with_psqt:
            out, pout = out
            pouts.append(pout)
        outs.append(out)
        if anchored and start + _CHUNK < batch:
            # Next chunk's carry-in: the accumulator of the last ANCHOR
            # entry so far — full (bit 0 clear) or persistent-resolved
            # (bit 2) — matching the in-kernel running-anchor rule.
            is_anchor = ((fl_c & 1) == 0) | ((fl_c & 4) != 0)
            has_anchor = jnp.any(is_anchor)
            last_anchor = (
                idx_c.shape[0] - 1
                - jnp.argmax(is_anchor[::-1]).astype(jnp.int32)
            )
            carry = jnp.where(
                has_anchor, jnp.take(out, last_anchor, axis=0), carry
            )
            if with_psqt:
                pcarry = jnp.where(
                    has_anchor,
                    jnp.take(pouts[-1], last_anchor, axis=0),
                    pcarry,
                )
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    acc = out.reshape(batch, persp, l1)
    if not with_psqt:
        return acc
    pout = pouts[0] if len(pouts) == 1 else jnp.concatenate(pouts, axis=0)
    return acc, pout


def ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    *,
    use_pallas: bool | None = None,
    interpret: bool = False,
    delta_base: int | None = None,
    sparse: Optional[jax.Array] = None,
    parent: Optional[jax.Array] = None,
    anchor_tab: Optional[jax.Array] = None,
    ft_psqt: Optional[jax.Array] = None,
    psqt_tab: Optional[jax.Array] = None,
):
    """Feature-transformer accumulators, bias included: int32 [B, 2, L1].

    ``ft_w`` [rows, L1] int16 whose LAST row is the zero sentinel;
    ``ft_b`` [L1] int16; ``indices`` integer [B, 2, MAX_ACTIVE] padded
    with the sentinel index. With ``delta_base`` set, incremental
    (delta) entries follow the spec.DELTA_SLOTS wire contract: adds in
    the first slots, removals (encoded delta_base + f) after them — the
    fused kernel fetches only those few slots and subtracts the removal
    rows, which is where incremental eval's DMA savings land.

    Two incremental modes:

    * ``parent`` given (int32 [B]; see decode_parent for the codes):
      delta entries are RESOLVED — the result is every entry's complete
      accumulator. The fused kernel resolves from a running in-VMEM
      anchor, relying on the pool's guarantee that an in-batch ref is
      always the most recent preceding anchor entry; the XLA fallback
      gathers by the explicit ref index. Bit-identical either way.
      With ``anchor_tab`` ([A, 2, L1] int32) given, PERSISTENT codes
      (<= -2 with the delta bit) resolve against the table instead —
      callers own storing anchor entries' accumulators back (the table
      is read-only here).
    * ``sparse`` given (bool [B]) without ``parent``: delta entries come
      back as bias-included PARTIALS (adds - removes); the caller owns
      resolution. (Kept for tests and schema-level users.)

    FUSED PSQT: with ``ft_psqt`` ([rows, 8] int32, same zero sentinel
    last row as ``ft_w``) the return value is the tuple ``(acc, psqt)``
    where ``psqt`` is the int32 [B, 2, 8] PSQT accumulator built from
    the SAME index stream in the same pass — same removal decoding,
    same anchor resolution (persistent codes resolve against
    ``psqt_tab`` [A, 2, 8], the anchor-PSQT twin of ``anchor_tab``).
    Bit-identical to the XLA gather and to the host material walk.

    ``use_pallas=None`` auto-selects: the fused kernel on TPU backends
    when shapes conform (lane-aligned L1), XLA otherwise.
    """
    indices = indices.astype(jnp.int32)
    with_psqt = ft_psqt is not None
    if use_pallas is None:
        use_pallas = (
            jax.default_backend() == "tpu" and ft_w.shape[1] % 1024 == 0
        )
    if parent is not None:
        # Persistent codes REQUIRE a table: without one neither backend
        # can resolve them. Concrete parents (every direct caller) get
        # the precise eager error below; traced parents are handled
        # STRUCTURALLY — the XLA fallback poisons the affected entries'
        # accumulators (_xla_resolve_parents) and the fused kernel strips
        # the persistent flag (so no DMA is ever issued against the
        # 1-row dummy table) and poisons the outputs likewise.
        if anchor_tab is None and is_concrete(parent):
            import numpy as _np

            if bool((_np.asarray(parent) <= -2).any()):
                raise ValueError(
                    "parent contains persistent anchor codes but no "
                    "anchor_tab was given"
                )
        parent = parent.astype(jnp.int32)
        if use_pallas or interpret:
            # bit 0: sparse; bit 1: perspective swap vs the anchor;
            # bit 2: persistent (anchor-table row in anchor_ids).
            in_batch, persistent, _, _, swap, aid = decode_parent(parent)
            sparse_f = in_batch | persistent
            tab_persistent = (
                persistent if anchor_tab is not None
                else jnp.zeros_like(persistent)
            )
            flags = (
                sparse_f.astype(jnp.int32)
                | (swap.astype(jnp.int32) << 1)
                | (tab_persistent.astype(jnp.int32) << 2)
            )
            acc = _pallas_ft_accumulate(
                ft_w, ft_b, indices, flags, aid, anchor_tab,
                ft_psqt, psqt_tab,
                interpret=interpret, delta_base=delta_base, anchored=True,
            )
            psqt = None
            if with_psqt:
                acc, psqt = acc
            if anchor_tab is None:
                acc = jnp.where(
                    persistent[:, None, None], jnp.int32(_POISON_ACC), acc
                )
                if with_psqt:
                    psqt = jnp.where(
                        persistent[:, None, None], jnp.int32(_POISON_ACC),
                        psqt,
                    )
            return (acc, psqt) if with_psqt else acc
        acc = _xla_ft_accumulate(ft_w, ft_b, indices, delta_base=delta_base)
        acc = _xla_resolve_parents(
            acc, ft_b.astype(jnp.int32), parent, anchor_tab
        )
        if not with_psqt:
            return acc
        psqt = _xla_psqt_accumulate(ft_psqt, indices, delta_base=delta_base)
        psqt = _xla_resolve_parents(psqt, jnp.int32(0), parent, psqt_tab)
        return acc, psqt
    if use_pallas or interpret:
        flags = None if sparse is None else sparse.astype(jnp.int32)
        return _pallas_ft_accumulate(
            ft_w, ft_b, indices, flags, ft_psqt=ft_psqt,
            interpret=interpret, delta_base=delta_base,
        )
    acc = _xla_ft_accumulate(ft_w, ft_b, indices, delta_base=delta_base)
    if not with_psqt:
        return acc
    return acc, _xla_psqt_accumulate(ft_psqt, indices, delta_base=delta_base)
