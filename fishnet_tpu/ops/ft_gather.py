"""Pallas TPU kernel for the NNUE feature-transformer gather-accumulate.

The feature transformer is the NNUE hot op: for every position and both
perspectives, sum ~30 sparse rows of a [22529, 1024] int16 table and add
the bias. XLA's take+sum lowers to a dynamic-gather that materializes a
[B, 2, 32, 1024] int16 intermediate in HBM (128 MiB at B=1024) and then
reduces it — every gathered byte crosses HBM twice. This kernel streams
each row HBM->VMEM exactly once with 32 concurrent row DMAs per
accumulator and reduces in VMEM, so the traffic is the 64 KiB of rows
per accumulator and the 4 KiB result, nothing else.

The weight table stays resident in HBM (46 MiB > VMEM); row addresses
are data-dependent, which is exactly what PrefetchScalarGridSpec's
scalar-prefetched index argument enables: the indices are available
before the kernel body, so the DMAs can be issued immediately.

Used by jax_eval.evaluate_batch on TPU backends; the plain XLA path
remains the fallback (CPU tests, odd shapes) and the parity test runs
this kernel in interpreter mode against it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["ft_accumulate"]


def _xla_ft_accumulate(ft_w: jax.Array, ft_b: jax.Array, indices: jax.Array) -> jax.Array:
    rows = jnp.take(ft_w, indices, axis=0)  # [B, 2, A, L1] int16
    return ft_b.astype(jnp.int32) + jnp.sum(rows.astype(jnp.int32), axis=2)


def _kernel(idx_ref, ft_ref, bias_ref, out_ref, rows, sems):
    # Software-pipelined gather: scratch holds TWO positions' rows. Grid
    # step b waits on the buffer its predecessor filled for it, issues
    # position b+1's row DMAs into the other buffer, then reduces — so
    # ~2x MAX_ACTIVE row copies are in flight at all times and the HBM
    # pipe never drains between positions. Row addresses come from the
    # scalar-prefetched index operand, available before the body runs.
    b = pl.program_id(0)
    n = pl.num_programs(0)
    n_active = rows.shape[1] // 2  # both perspectives share a buffer

    def issue(pos, slot):
        # Each feature row is one native (sub, 128) int16 tile, so
        # single-row HBM slices stay tile-aligned. Padded index slots
        # point at the sentinel zero row: no branches needed.
        for p in range(2):
            for k in range(n_active):
                pltpu.make_async_copy(
                    ft_ref.at[idx_ref[pos, p, k]],
                    rows.at[slot, p * n_active + k],
                    sems.at[slot, p * n_active + k],
                ).start()

    slot = jax.lax.rem(b, 2)

    @pl.when(b == 0)
    def _():
        issue(0, 0)

    @pl.when(b + 1 < n)
    def _():
        issue(b + 1, jax.lax.rem(b + 1, 2))

    for p in range(2):
        for k in range(n_active):
            pltpu.make_async_copy(
                ft_ref.at[idx_ref[b, p, k]],
                rows.at[slot, p * n_active + k],
                sems.at[slot, p * n_active + k],
            ).wait()

    bias = bias_ref[:].astype(jnp.int32)
    all_rows = rows[slot].astype(jnp.int32)  # [2A, sub, 128]
    out_ref[0, 0] = bias + jnp.sum(all_rows[:n_active], axis=0)
    out_ref[0, 1] = bias + jnp.sum(all_rows[n_active:], axis=0)


# Positions per pallas_call: the scalar-prefetch index operand lives in
# SMEM (1 MiB, shared with Mosaic's own scalar state — 1024-position
# chunks overflow it by a hair), so the whole batch's indices cannot
# ride one call; each call costs a launch plus a pipeline fill/drain,
# so use the largest chunk that reliably fits ([512, 2, 32] int32 =
# 128 KiB).
_CHUNK = 512


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_ft_accumulate(
    ft_w: jax.Array, ft_b: jax.Array, indices: jax.Array, interpret: bool = False
) -> jax.Array:
    batch, persp, n_active = indices.shape
    l1 = ft_w.shape[1]
    assert persp == 2, "indices must be [B, 2, MAX_ACTIVE]"
    assert l1 % 1024 == 0, "L1 must fold into whole (8, 128) int16 tiles"
    sub = l1 // 128  # sublane count of one feature row viewed as a tile

    # View each L1-wide row as an (sub, 128) tile so single-row HBM
    # slices are tile-aligned (Mosaic requires sublane multiples of 8).
    ft_tiles = ft_w.reshape(ft_w.shape[0], sub, 128)
    bias_tile = ft_b.reshape(sub, 128)

    def run_chunk(idx_chunk: jax.Array) -> jax.Array:
        chunk = idx_chunk.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(chunk,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),  # ft_w stays in HBM
                pl.BlockSpec(memory_space=pltpu.VMEM),  # bias
            ],
            out_specs=pl.BlockSpec(
                (1, 2, sub, 128), lambda b, idx_ref: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2, 2 * n_active, sub, 128), ft_w.dtype),
                pltpu.SemaphoreType.DMA((2, 2 * n_active)),
            ],
        )
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((chunk, 2, sub, 128), jnp.int32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(idx_chunk, ft_tiles, bias_tile)

    idx = indices.astype(jnp.int32)
    outs = [
        run_chunk(idx[start : start + _CHUNK])
        for start in range(0, batch, _CHUNK)
    ]
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    return out.reshape(batch, persp, l1)


def ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    *,
    use_pallas: bool | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Feature-transformer accumulators, bias included: int32 [B, 2, L1].

    ``ft_w`` [N+1, L1] int16 with a zero sentinel row at index N;
    ``ft_b`` [L1] int16; ``indices`` integer [B, 2, MAX_ACTIVE] padded
    with N. ``use_pallas=None`` auto-selects: the fused kernel on TPU
    backends when shapes conform (lane-aligned L1), XLA otherwise.
    """
    indices = indices.astype(jnp.int32)
    if use_pallas is None:
        use_pallas = (
            jax.default_backend() == "tpu" and ft_w.shape[1] % 1024 == 0
        )
    if use_pallas or interpret:
        return _pallas_ft_accumulate(ft_w, ft_b, indices, interpret=interpret)
    return _xla_ft_accumulate(ft_w, ft_b, indices)
