"""Pallas TPU kernel for the NNUE feature-transformer gather-accumulate.

The feature transformer is the NNUE hot op: for every position and both
perspectives, sum ~30 sparse rows of a [22529, 1024] int16 table and add
the bias. XLA's take+sum lowers to a dynamic-gather that materializes a
[B, 2, 32, 1024] int16 intermediate in HBM (128 MiB at B=1024) and then
reduces it — every gathered byte crosses HBM twice. This kernel streams
each row HBM->VMEM exactly once with 32 concurrent row DMAs per
accumulator and reduces in VMEM, so the traffic is the 64 KiB of rows
per accumulator and the 4 KiB result, nothing else.

The weight table stays resident in HBM (46 MiB > VMEM); row addresses
are data-dependent, which is exactly what PrefetchScalarGridSpec's
scalar-prefetched index argument enables: the indices are available
before the kernel body, so the DMAs can be issued immediately.

Used by jax_eval.evaluate_batch on TPU backends; the plain XLA path
remains the fallback (CPU tests, odd shapes) and the parity test runs
this kernel in interpreter mode against it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["ft_accumulate"]


def _xla_ft_accumulate(ft_w: jax.Array, ft_b: jax.Array, indices: jax.Array) -> jax.Array:
    rows = jnp.take(ft_w, indices, axis=0)  # [B, 2, A, L1] int16
    return ft_b.astype(jnp.int32) + jnp.sum(rows.astype(jnp.int32), axis=2)


def _kernel(idx_ref, ft_ref, bias_ref, out_ref, rows, sems):
    b = pl.program_id(0)
    n_active = rows.shape[0] // 2  # both perspectives share the scratch

    # Issue every row copy up front — the DMA engine overlaps them — then
    # wait and reduce. Each feature row is viewed as one native (8, 128)
    # int16 tile, so single-row HBM slices stay tile-aligned. Padded
    # slots point at the sentinel zero row, so no branches are needed.
    copies = []
    for p in range(2):
        for k in range(n_active):
            dma = pltpu.make_async_copy(
                ft_ref.at[idx_ref[b, p, k]], rows.at[p * n_active + k],
                sems.at[p * n_active + k],
            )
            dma.start()
            copies.append(dma)
    for dma in copies:
        dma.wait()

    bias = bias_ref[:].astype(jnp.int32)
    all_rows = rows[:].astype(jnp.int32)  # [2A, 8S, 128]
    out_ref[0, 0] = bias + jnp.sum(all_rows[:n_active], axis=0)
    out_ref[0, 1] = bias + jnp.sum(all_rows[n_active:], axis=0)


# Positions per pallas_call: the scalar-prefetch index operand lives in
# SMEM (1 MiB total), so the whole batch's indices cannot ride one call.
_CHUNK = 256


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_ft_accumulate(
    ft_w: jax.Array, ft_b: jax.Array, indices: jax.Array, interpret: bool = False
) -> jax.Array:
    batch, persp, n_active = indices.shape
    l1 = ft_w.shape[1]
    assert persp == 2, "indices must be [B, 2, MAX_ACTIVE]"
    assert l1 % 1024 == 0, "L1 must fold into whole (8, 128) int16 tiles"
    sub = l1 // 128  # sublane count of one feature row viewed as a tile

    # View each L1-wide row as an (sub, 128) tile so single-row HBM
    # slices are tile-aligned (Mosaic requires sublane multiples of 8).
    ft_tiles = ft_w.reshape(ft_w.shape[0], sub, 128)
    bias_tile = ft_b.reshape(sub, 128)

    def run_chunk(idx_chunk: jax.Array) -> jax.Array:
        chunk = idx_chunk.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(chunk,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),  # ft_w stays in HBM
                pl.BlockSpec(memory_space=pltpu.VMEM),  # bias
            ],
            out_specs=pl.BlockSpec(
                (1, 2, sub, 128), lambda b, idx_ref: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2 * n_active, sub, 128), ft_w.dtype),
                pltpu.SemaphoreType.DMA((2 * n_active,)),
            ],
        )
        return pl.pallas_call(
            _kernel,
            out_shape=jax.ShapeDtypeStruct((chunk, 2, sub, 128), jnp.int32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(idx_chunk, ft_tiles, bias_tile)

    idx = indices.astype(jnp.int32)
    outs = [
        run_chunk(idx[start : start + _CHUNK])
        for start in range(0, batch, _CHUNK)
    ]
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    return out.reshape(batch, persp, l1)


def ft_accumulate(
    ft_w: jax.Array,
    ft_b: jax.Array,
    indices: jax.Array,
    *,
    use_pallas: bool | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Feature-transformer accumulators, bias included: int32 [B, 2, L1].

    ``ft_w`` [N+1, L1] int16 with a zero sentinel row at index N;
    ``ft_b`` [L1] int16; ``indices`` integer [B, 2, MAX_ACTIVE] padded
    with N. ``use_pallas=None`` auto-selects: the fused kernel on TPU
    backends when shapes conform (lane-aligned L1), XLA otherwise.
    """
    indices = indices.astype(jnp.int32)
    if use_pallas is None:
        use_pallas = (
            jax.default_backend() == "tpu" and ft_w.shape[1] % 1024 == 0
        )
    if use_pallas or interpret:
        return _pallas_ft_accumulate(ft_w, ft_b, indices, interpret=interpret)
    return _xla_ft_accumulate(ft_w, ft_b, indices)
