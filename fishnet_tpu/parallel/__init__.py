"""Multi-chip parallelism: device meshes, shardings, and the sharded
NNUE evaluator. See mesh.py for the design rationale."""

from fishnet_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    ShardedEvaluator,
    batch_sharding,
    factor_mesh,
    make_mesh,
    replicated,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "ShardedEvaluator",
    "batch_sharding",
    "factor_mesh",
    "make_mesh",
    "replicated",
]
