"""Device-mesh construction and sharded batched evaluation.

The reference has no multi-device tier at all — its "distributed backend"
is one HTTPS client (SURVEY.md §5, reference src/api.rs:489-536) and its
intra-client parallelism is one engine subprocess per core. The TPU-native
equivalent introduced here sits *below* the engine seam: NNUE microbatches
are sharded across a ``jax.sharding.Mesh`` so the evaluator scales over
ICI instead of over processes.

Axes:

* ``data``  — batch dimension of eval/training microbatches (dp).
* ``model`` — the feature-transformer width L1 and the contracting
  dimension of the first dense layer (tp). Only the *trainer* shards
  over it (the FT table is the one big tensor, 22528 x 1024, and its
  optimizer state triples the footprint); serving replicates params
  and uses the model axis as extra batch parallelism — see
  ``ShardedEvaluator``.

All collectives are inserted by XLA/GSPMD from sharding annotations —
there are no hand-written collectives anywhere in the framework.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def factor_mesh(n_devices: int, max_model: int = 2) -> Tuple[int, int]:
    """Split ``n_devices`` into (data, model) sizes. Model parallelism
    beyond a few ways does not pay for a 1024-wide FT, so ``model`` is
    capped and the rest goes to data parallelism."""
    model = 1
    for cand in range(min(max_model, n_devices), 0, -1):
        if n_devices % cand == 0:
            model = cand
            break
    return n_devices // model, model


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data: Optional[int] = None,
    model: Optional[int] = None,
) -> Mesh:
    """Build a ("data", "model") mesh over the given (default: all)
    devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data is None and model is None:
        data, model = factor_mesh(n)
    elif data is None:
        data = n // model
    elif model is None:
        model = n // data
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dimension over BOTH mesh axes — for
    inference there is no reason to leave the model axis idle."""
    return NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS)))


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple)) * multiple


class ShardedEvaluator:
    """Batched NNUE evaluation sharded across a mesh.

    Serving shards the *batch* over every device on both mesh axes (pure
    dp — for a ~47 MiB net, replicating params and splitting positions is
    strictly better than splitting the FT width; tp over the model axis
    is used by the trainer, not here). Drop-in for ``evaluate_batch_jit``
    behind ``SearchService``'s ``evaluator`` seam.

    The sharded computation is a ``shard_map``: every device evaluates
    its batch shard COMPLETELY LOCALLY — zero collectives in the
    compiled program (asserted by tests/test_parallel.py against the
    HLO). That is only sound because incremental (delta) entries never
    reference across a shard boundary: the native pool aligns block
    emission to the shard size (cpp/src/pool.cpp emit_block `align`;
    SearchService passes group_capacity / n_devices) and this wrapper
    rebases the anchor codes to shard-local indices. Round 2 instead
    let GSPMD resolve batch-relative references, which required an
    all-gather of the [B, 2, 1024] int32 accumulators over ICI —
    ~134 MB per 16k eval step, a scaling hazard the alignment deletes.
    """

    def __init__(self, params, mesh: Optional[Mesh] = None, batch_capacity: int = 1024):
        from jax.sharding import PartitionSpec

        from fishnet_tpu.nnue.jax_eval import evaluate_batch

        try:
            from jax import shard_map as _shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map as _shard_map

        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = self.mesh.devices.size
        #: Batch sizes fed to __call__ must be multiples of this so the
        #: leading dimension splits evenly across the mesh.
        self.size_multiple = self.n_devices
        self.batch_capacity = pad_to_multiple(batch_capacity, self.n_devices)
        self.params = jax.device_put(params, replicated(self.mesh))
        batch_axes = PartitionSpec((DATA_AXIS, MODEL_AXIS))
        repl = PartitionSpec()

        def local_eval(params, indices, buckets, parent, material):
            return evaluate_batch(params, indices, buckets, parent, material)

        def local_eval_nomat(params, indices, buckets, parent):
            return evaluate_batch(params, indices, buckets, parent)

        self._fn_mat = jax.jit(
            _shard_map(
                local_eval, mesh=self.mesh,
                in_specs=(repl, batch_axes, batch_axes, batch_axes, batch_axes),
                out_specs=batch_axes,
            )
        )
        self._fn = jax.jit(
            _shard_map(
                local_eval_nomat, mesh=self.mesh,
                in_specs=(repl, batch_axes, batch_axes, batch_axes),
                out_specs=batch_axes,
            )
        )

        # PACKED WIRE over the mesh (VERDICT r4 item 4): the service
        # repacks the pool's row stream into a fixed per-shard row tier
        # (see SearchService._dispatch_eval), so the leading axis splits
        # evenly and each shard expands ITS OWN rows locally inside the
        # shard_map — the multi-chip path now ships ~32 bytes per delta
        # entry like the single-device path, instead of the 128-byte
        # dense expansion (plus host CPU for expand_packed_np) it paid
        # before. Jitted per row-tier (3 shapes), like the single-device
        # compile matrix.
        from fishnet_tpu.nnue.jax_eval import evaluate_packed

        def local_packed(params, packed, offsets, buckets, parent, material):
            return evaluate_packed(params, packed, offsets, buckets, parent,
                                   material)

        self._packed_fn = jax.jit(
            _shard_map(
                local_packed, mesh=self.mesh,
                in_specs=(repl, batch_axes, batch_axes, batch_axes,
                          batch_axes, batch_axes),
                out_specs=batch_axes,
            )
        )

    #: SearchService probes this to keep the packed wire on (service-side
    #: per-shard repack + on-device expansion) instead of falling back to
    #: the dense host-side expansion.
    supports_packed = True

    def packed_eval(self, params, packed, offsets, buckets, parent, material):
        """Evaluate an ALREADY per-shard-repacked row stream: ``packed``
        [n_devices * tier, 2, 8] (each shard's rows padded to the same
        tier, trailing 4 sentinel rows per shard), ``offsets`` [B] with
        SHARD-LOCAL row values. ``params`` is ignored like __call__."""
        import numpy as _np

        batch = offsets.shape[0]
        parent = self._local_parents(parent, batch)
        if material is None:
            material = _np.zeros((batch,), _np.int32)
        return self._packed_fn(
            self.params, packed, offsets, buckets, parent, material
        )

    def _local_parents(self, parent, batch):
        """Rebase batch-relative anchor codes to shard-local indices.
        Valid because the pool's aligned emission keeps every delta and
        its anchor inside one shard (asserted here: a violation would
        silently read another position's accumulator)."""
        import numpy as _np

        shard = batch // self.n_devices
        parent = _np.asarray(parent, _np.int32)
        valid = parent >= 0
        ref = parent >> 1
        if valid.any():
            same_shard = (ref[valid] // shard) == (
                _np.nonzero(valid)[0] // shard
            )
            if not same_shard.all():
                raise ValueError(
                    "delta entry references an anchor outside its mesh "
                    "shard — the pool must emit with align = shard size"
                )
        return _np.where(valid, ((ref % shard) << 1) | (parent & 1), -1).astype(
            _np.int32
        )

    def __call__(self, params, indices, buckets, parent=None, material=None):
        # Signature-compatible with evaluate_batch_jit; `params` is
        # ignored — the replicated tree from construction is used.
        import numpy as _np

        batch = indices.shape[0]
        if parent is None:
            parent = _np.full((batch,), -1, _np.int32)
        else:
            parent = self._local_parents(parent, batch)
        if material is None:
            return self._fn(self.params, indices, buckets, parent)
        return self._fn_mat(self.params, indices, buckets, parent, material)
