"""Device-mesh construction and sharded batched evaluation.

The reference has no multi-device tier at all — its "distributed backend"
is one HTTPS client (SURVEY.md §5, reference src/api.rs:489-536) and its
intra-client parallelism is one engine subprocess per core. The TPU-native
equivalent introduced here sits *below* the engine seam: NNUE microbatches
are sharded across a ``jax.sharding.Mesh`` so the evaluator scales over
ICI instead of over processes.

Axes:

* ``data``  — batch dimension of eval/training microbatches (dp).
* ``model`` — the feature-transformer width L1 and the contracting
  dimension of the first dense layer (tp). Only the *trainer* shards
  over it (the FT table is the one big tensor, 22528 x 1024, and its
  optimizer state triples the footprint); serving replicates params
  and uses the model axis as extra batch parallelism — see
  ``ShardedEvaluator``.

All collectives are inserted by XLA/GSPMD from sharding annotations —
there are no hand-written collectives anywhere in the framework.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def serving_devices(requested=None) -> List[jax.Device]:
    """Resolve the device list the placement-aware serving mesh drives
    (doc/sharding.md). ``requested`` is ``None``/``"auto"`` (every
    visible device), an int (the first N devices), or an explicit device
    sequence. ``FISHNET_NO_MESH=1`` is the operational escape hatch: it
    clamps any request to the first device, restoring the single-device
    serving path byte-for-byte."""
    if requested is None or requested == "auto":
        devs = list(jax.devices())
    elif isinstance(requested, int):
        devs = list(jax.devices())[: max(1, requested)]
    else:
        devs = list(requested)
    if os.environ.get("FISHNET_NO_MESH", "0") == "1":
        devs = devs[:1]
    return devs


def replicate_params(params, devices: Sequence[jax.Device]) -> List[Dict]:
    """Place one full replica of a (pytree) param dict on each serving
    device — the per-shard weight placement both dispatch planes use
    (doc/sharding.md, doc/search.md): each mesh shard evaluates its own
    groups' microbatches against its local replica, so a dispatch never
    crosses devices. Returns one params handle per device, in device
    order; with a single device this is one ``device_put`` (the
    single-shard service's existing placement, byte-for-byte)."""
    return [
        jax.tree_util.tree_map(lambda a, d=dev: jax.device_put(a, d), params)
        for dev in devices
    ]


class ShardRouter:
    """Occupancy-weighted pipeline-group -> mesh-slot assignment for the
    placement-aware coalescer (doc/sharding.md).

    Groups start on the deterministic round-robin layout (group g ->
    shard g % n_shards), so each driver thread's contiguous group range
    spreads over the mesh and table placement is decidable before any
    traffic. The load-balancing step happens at a group's FIRST traffic
    (``note_occupancy``, called by the coalescer per submitted
    microbatch): the group is re-homed to the least-loaded alive shard —
    ordered by (occupancy EMA, assigned-group count, keep-current,
    shard id) — which is a no-op while the mesh is balanced (ties
    prefer the current home) but moves a waking group off a hot shard
    onto an idle one, the MULTICHIP_r06 failure mode (8-shard
    dispatches [253,240,0,0,8,34,35,20] under pure round-robin).
    ``FISHNET_SHARD_PLACEMENT=rr`` restores the static assignment.

    With no traffic the assignment stays a pure function of (n_groups,
    n_shards), including after ``drain`` — the per-shard degradation
    ladder's last resort — which re-homes the dead shard's groups
    least-loaded-first (identical to the old round-robin walk when
    loads are equal, which keeps the drain decision deterministic in
    the fault drills).

    Thread safety: every driver thread reads ``shard_of`` per step while
    a degrading sibling may be draining — all state is guarded by one
    leaf lock (never held while calling out), the pattern the R4
    cross-thread checker certifies (tests/analysis_fixtures).
    """

    def __init__(self, n_groups: int, n_shards: int) -> None:
        if n_shards < 1 or n_groups < 1:
            raise ValueError("need at least one group and one shard")
        self.n_groups = n_groups
        self.n_shards = n_shards
        self._lock = threading.Lock()
        self._alive = list(range(n_shards))
        self._assign = {g: g % n_shards for g in range(n_groups)}
        self._rr_only = (
            os.environ.get("FISHNET_SHARD_PLACEMENT", "lb") == "rr"
        )
        self._active: set = set()
        self._load = [0.0] * n_shards

    def _least_loaded_locked(self, current: Optional[int] = None) -> int:
        counts = {s: 0 for s in self._alive}
        for s in self._assign.values():
            if s in counts:
                counts[s] += 1
        return min(
            self._alive,
            key=lambda s: (
                self._load[s], counts[s], 0 if s == current else 1, s
            ),
        )

    def shard_of(self, group: int) -> int:
        with self._lock:
            return self._assign[group]

    def note_occupancy(self, group: int, n: int) -> None:
        """Record one submitted microbatch of ``n`` entries against
        ``group``'s shard (EMA matching the coalescer's width policy).
        A group's first note re-homes it to the least-loaded shard
        unless FISHNET_SHARD_PLACEMENT=rr pins the static layout."""
        with self._lock:
            s = self._assign[group]
            if not self._rr_only and group not in self._active:
                self._active.add(group)
                tgt = self._least_loaded_locked(current=s)
                if tgt != s:
                    self._assign[group] = tgt
                    s = tgt
            self._load[s] = 0.8 * self._load[s] + 0.2 * float(n)

    def shard_loads(self) -> List[float]:
        with self._lock:
            return list(self._load)

    def groups_of(self, shard: int) -> List[int]:
        with self._lock:
            return sorted(g for g, s in self._assign.items() if s == shard)

    def group_count(self, shard: int) -> int:
        with self._lock:
            return sum(1 for s in self._assign.values() if s == shard)

    def alive_shards(self) -> List[int]:
        with self._lock:
            return list(self._alive)

    def drain(self, shard: int) -> Dict[int, int]:
        """Mark ``shard`` dead and reassign its groups over the
        surviving shards — least-loaded-first (round-robin under
        FISHNET_SHARD_PLACEMENT=rr, and equivalent to it when loads are
        level). Returns {group: new_shard} for the moved groups. Raises
        RuntimeError when no shard would remain — the caller escalates
        to the whole-service failure path."""
        with self._lock:
            if shard in self._alive:
                if len(self._alive) == 1:
                    raise RuntimeError("no alive shard left in the mesh")
                self._alive.remove(shard)
            moved = {}
            drained = sorted(g for g, s in self._assign.items() if s == shard)
            for i, g in enumerate(drained):
                if self._rr_only:
                    tgt = self._alive[i % len(self._alive)]
                else:
                    tgt = self._least_loaded_locked()
                self._assign[g] = tgt
                moved[g] = tgt
            return moved


def factor_mesh(n_devices: int, max_model: int = 2) -> Tuple[int, int]:
    """Split ``n_devices`` into (data, model) sizes. Model parallelism
    beyond a few ways does not pay for a 1024-wide FT, so ``model`` is
    capped and the rest goes to data parallelism."""
    model = 1
    for cand in range(min(max_model, n_devices), 0, -1):
        if n_devices % cand == 0:
            model = cand
            break
    return n_devices // model, model


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data: Optional[int] = None,
    model: Optional[int] = None,
) -> Mesh:
    """Build a ("data", "model") mesh over the given (default: all)
    devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data is None and model is None:
        data, model = factor_mesh(n)
    elif data is None:
        data = n // model
    elif model is None:
        model = n // data
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dimension over BOTH mesh axes — for
    inference there is no reason to leave the model axis idle."""
    return NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS)))


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple)) * multiple


class ShardedEvaluator:
    """Batched NNUE evaluation sharded across a mesh.

    Serving shards the *batch* over every device on both mesh axes (pure
    dp — for a ~47 MiB net, replicating params and splitting positions is
    strictly better than splitting the FT width; tp over the model axis
    is used by the trainer, not here). Drop-in for ``evaluate_batch_jit``
    behind ``SearchService``'s ``evaluator`` seam.

    The sharded computation is a ``shard_map``: every device evaluates
    its batch shard COMPLETELY LOCALLY — zero collectives in the
    compiled program (asserted by tests/test_parallel.py against the
    HLO). That is only sound because incremental (delta) entries never
    reference across a shard boundary: the native pool aligns block
    emission to the shard size (cpp/src/pool.cpp emit_block `align`;
    SearchService passes group_capacity / n_devices) and this wrapper
    rebases the anchor codes to shard-local indices. Round 2 instead
    let GSPMD resolve batch-relative references, which required an
    all-gather of the [B, 2, 1024] int32 accumulators over ICI —
    ~134 MB per 16k eval step, a scaling hazard the alignment deletes.
    """

    def __init__(self, params, mesh: Optional[Mesh] = None, batch_capacity: int = 1024):
        from jax.sharding import PartitionSpec

        from fishnet_tpu.nnue.jax_eval import evaluate_batch

        try:
            from jax import shard_map as _shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map as _shard_map

        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = self.mesh.devices.size
        #: Batch sizes fed to __call__ must be multiples of this so the
        #: leading dimension splits evenly across the mesh.
        self.size_multiple = self.n_devices
        self.batch_capacity = pad_to_multiple(batch_capacity, self.n_devices)
        self.params = jax.device_put(params, replicated(self.mesh))
        batch_axes = PartitionSpec((DATA_AXIS, MODEL_AXIS))
        repl = PartitionSpec()

        def local_eval(params, indices, buckets, parent, material):
            return evaluate_batch(params, indices, buckets, parent, material)

        def local_eval_nomat(params, indices, buckets, parent):
            return evaluate_batch(params, indices, buckets, parent)

        self._fn_mat = jax.jit(
            _shard_map(
                local_eval, mesh=self.mesh,
                in_specs=(repl, batch_axes, batch_axes, batch_axes, batch_axes),
                out_specs=batch_axes,
            )
        )
        self._fn = jax.jit(
            _shard_map(
                local_eval_nomat, mesh=self.mesh,
                in_specs=(repl, batch_axes, batch_axes, batch_axes),
                out_specs=batch_axes,
            )
        )

        # PACKED WIRE over the mesh (VERDICT r4 item 4): the service
        # repacks the pool's row stream into a fixed per-shard row tier
        # (see SearchService._dispatch_eval), so the leading axis splits
        # evenly and each shard expands ITS OWN rows locally inside the
        # shard_map — the multi-chip path now ships ~32 bytes per delta
        # entry like the single-device path, instead of the 128-byte
        # dense expansion (plus host CPU for expand_packed_np) it paid
        # before. Jitted per row-tier (3 shapes), like the single-device
        # compile matrix.
        from fishnet_tpu.nnue.jax_eval import evaluate_packed

        def local_packed(params, packed, offsets, buckets, parent, material):
            return evaluate_packed(params, packed, offsets, buckets, parent,
                                   material)

        self._packed_fn = jax.jit(
            _shard_map(
                local_packed, mesh=self.mesh,
                in_specs=(repl, batch_axes, batch_axes, batch_axes,
                          batch_axes, batch_axes),
                out_specs=batch_axes,
            )
        )

    #: SearchService probes this to keep the packed wire on (service-side
    #: per-shard repack + on-device expansion) instead of falling back to
    #: the dense host-side expansion.
    supports_packed = True

    def packed_eval(self, params, packed, offsets, buckets, parent, material):
        """Evaluate an ALREADY per-shard-repacked row stream: ``packed``
        [n_devices * tier, 2, 8] (each shard's rows padded to the same
        tier, trailing 4 sentinel rows per shard), ``offsets`` [B] with
        SHARD-LOCAL row values. ``params`` is ignored like __call__."""
        import numpy as _np

        batch = offsets.shape[0]
        parent = self._local_parents(parent, batch)
        if material is None:
            material = _np.zeros((batch,), _np.int32)
        return self._packed_fn(
            self.params, packed, offsets, buckets, parent, material
        )

    def _local_parents(self, parent, batch):
        """Rebase batch-relative anchor codes to shard-local indices.
        Valid because the pool's aligned emission keeps every delta and
        its anchor inside one shard (asserted here: a violation would
        silently read another position's accumulator)."""
        import numpy as _np

        shard = batch // self.n_devices
        parent = _np.asarray(parent, _np.int32)
        valid = parent >= 0
        ref = parent >> 1
        if valid.any():
            same_shard = (ref[valid] // shard) == (
                _np.nonzero(valid)[0] // shard
            )
            if not same_shard.all():
                raise ValueError(
                    "delta entry references an anchor outside its mesh "
                    "shard — the pool must emit with align = shard size"
                )
        return _np.where(valid, ((ref % shard) << 1) | (parent & 1), -1).astype(
            _np.int32
        )

    def __call__(self, params, indices, buckets, parent=None, material=None):
        # Signature-compatible with evaluate_batch_jit; `params` is
        # ignored — the replicated tree from construction is used.
        import numpy as _np

        batch = indices.shape[0]
        if parent is None:
            parent = _np.full((batch,), -1, _np.int32)
        else:
            parent = self._local_parents(parent, batch)
        if material is None:
            return self._fn(self.params, indices, buckets, parent)
        return self._fn_mat(self.params, indices, buckets, parent, material)


class ShardedSegmentedEvaluator:
    """shard_map over the packed-anchored SEGMENTED evaluator: the fused
    coalescer wire (nnue/jax_eval.evaluate_packed_anchored_segmented)
    as ONE mesh-wide program, segments sharded over the data axis with
    each shard's persistent anchor/PSQT tables resident on that shard.

    Segment-locality is what makes this collective-free: every
    segment's parent codes are SEGMENT-LOCAL (in-batch refs and
    persistent-anchor rows both rebase inside the segment —
    ops/ft_gather.recode_segment_parents / derive_segment_offsets), so
    a device holding segments [k, k+K/n) never reads another device's
    rows or tables. tests/test_parallel.py asserts the compiled HLO
    contains zero collectives, the same invariant the single-program
    benchmark path proved for evaluate_packed in round 5.

    Serving itself uses per-shard PLACEMENT (independent per-device
    dispatches driven by SearchService's shard router) rather than this
    one fused program — placement lets shards degrade, drain, and
    pipeline independently, which one mesh-wide program cannot. This
    class is the topology's reference semantics: sharded-vs-single
    parity and the zero-collectives proof are pinned against it.

    The XLA realization is pinned (``use_pallas=False``): inside
    shard_map the fused Pallas kernel's interpreter fallback is not a
    supported venue, and all rungs are bit-identical anyway.
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        from jax.sharding import PartitionSpec

        from fishnet_tpu.nnue.jax_eval import (
            evaluate_packed_anchored_segmented,
        )

        try:
            from jax import shard_map as _shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map as _shard_map

        if mesh is None:
            devs = devices if devices is not None else jax.devices()
            mesh = make_mesh(devs, model=1)
        self.mesh = mesh
        self.n_devices = mesh.devices.size
        seg = PartitionSpec(DATA_AXIS)
        repl = PartitionSpec()

        def local_mat(params, packed, buckets, parent, material,
                      anchor_tabs, seg_rows, psqt_tabs):
            return evaluate_packed_anchored_segmented(
                params, packed, buckets, parent, material,
                anchor_tabs, seg_rows, psqt_tabs, use_pallas=False,
            )

        def local_nomat(params, packed, buckets, parent,
                        anchor_tabs, seg_rows, psqt_tabs):
            return evaluate_packed_anchored_segmented(
                params, packed, buckets, parent, None,
                anchor_tabs, seg_rows, psqt_tabs, use_pallas=False,
            )

        self._fn_mat = jax.jit(
            _shard_map(
                local_mat, mesh=mesh,
                in_specs=(repl, seg, seg, seg, seg, seg, seg, seg),
                out_specs=(seg, seg, seg),
            )
        )
        self._fn = jax.jit(
            _shard_map(
                local_nomat, mesh=mesh,
                in_specs=(repl, seg, seg, seg, seg, seg, seg),
                out_specs=(seg, seg, seg),
            )
        )

    def __call__(self, params, packed, buckets, parent, material,
                 anchor_tabs, seg_rows, psqt_tabs):
        """Same contract as evaluate_packed_anchored_segmented; the
        segment count K (= anchor_tabs.shape[0]) must divide evenly over
        the mesh so each device owns whole segments."""
        k = anchor_tabs.shape[0]
        if k % self.n_devices:
            raise ValueError(
                f"segment count {k} does not divide over {self.n_devices} "
                "devices — pad the dispatch to a whole-segment multiple"
            )
        if material is None:
            return self._fn(params, packed, buckets, parent,
                            anchor_tabs, seg_rows, psqt_tabs)
        return self._fn_mat(params, packed, buckets, parent, material,
                            anchor_tabs, seg_rows, psqt_tabs)
