"""Device-mesh construction and sharded batched evaluation.

The reference has no multi-device tier at all — its "distributed backend"
is one HTTPS client (SURVEY.md §5, reference src/api.rs:489-536) and its
intra-client parallelism is one engine subprocess per core. The TPU-native
equivalent introduced here sits *below* the engine seam: NNUE microbatches
are sharded across a ``jax.sharding.Mesh`` so the evaluator scales over
ICI instead of over processes.

Axes:

* ``data``  — batch dimension of eval/training microbatches (dp).
* ``model`` — the feature-transformer width L1 and the contracting
  dimension of the first dense layer (tp). Only the *trainer* shards
  over it (the FT table is the one big tensor, 22528 x 1024, and its
  optimizer state triples the footprint); serving replicates params
  and uses the model axis as extra batch parallelism — see
  ``ShardedEvaluator``.

All collectives are inserted by XLA/GSPMD from sharding annotations —
there are no hand-written collectives anywhere in the framework.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def factor_mesh(n_devices: int, max_model: int = 2) -> Tuple[int, int]:
    """Split ``n_devices`` into (data, model) sizes. Model parallelism
    beyond a few ways does not pay for a 1024-wide FT, so ``model`` is
    capped and the rest goes to data parallelism."""
    model = 1
    for cand in range(min(max_model, n_devices), 0, -1):
        if n_devices % cand == 0:
            model = cand
            break
    return n_devices // model, model


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data: Optional[int] = None,
    model: Optional[int] = None,
) -> Mesh:
    """Build a ("data", "model") mesh over the given (default: all)
    devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if data is None and model is None:
        data, model = factor_mesh(n)
    elif data is None:
        data = n // model
    elif model is None:
        model = n // data
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dimension over BOTH mesh axes — for
    inference there is no reason to leave the model axis idle."""
    return NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS)))


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple)) * multiple


class ShardedEvaluator:
    """Batched NNUE evaluation sharded across a mesh.

    Serving shards the *batch* over every device on both mesh axes (pure
    dp — for a ~47 MiB net, replicating params and splitting positions is
    strictly better than splitting the FT width; tp over the model axis
    is used by the trainer, not here). XLA turns the final gather of
    per-position scores into an all-gather over ICI. Drop-in for
    ``evaluate_batch_jit`` behind ``SearchService``'s ``evaluator`` seam.
    """

    def __init__(self, params, mesh: Optional[Mesh] = None, batch_capacity: int = 1024):
        from fishnet_tpu.nnue.jax_eval import evaluate_batch

        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = self.mesh.devices.size
        #: Batch sizes fed to __call__ must be multiples of this so the
        #: leading dimension splits evenly across the mesh.
        self.size_multiple = self.n_devices
        self.batch_capacity = pad_to_multiple(batch_capacity, self.n_devices)
        self.params = jax.device_put(params, replicated(self.mesh))
        in_shard = batch_sharding(self.mesh)
        # Incremental (delta) entries reference other entries of the
        # SAME batch; with the batch sharded, that gather crosses shard
        # boundaries, and GSPMD resolves it (all-gather of the partial
        # accumulators over ICI) from these annotations alone.
        self._fn = jax.jit(
            evaluate_batch,
            in_shardings=(replicated(self.mesh), in_shard, in_shard, in_shard),
            out_shardings=replicated(self.mesh),
        )

    def __call__(self, params, indices, buckets, parent=None):
        # Signature-compatible with evaluate_batch_jit; `params` is
        # ignored — the replicated tree from construction is used.
        if parent is None:
            import numpy as _np

            parent = _np.full((indices.shape[0],), -1, _np.int32)
        return self._fn(self.params, indices, buckets, parent)
