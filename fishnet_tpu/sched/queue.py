"""Batch scheduler: validates acquired games, expands them into per-ply
positions, schedules positions to workers, reassembles results, and
submits completed batches.

Behavioral equivalent of the reference's queue layer (src/queue.rs):

* acquired games are replayed move-by-move at the trust boundary before
  any engine sees them (queue.rs:543-552) — here via the native Board;
* a game expands into one Position per ply, root first (queue.rs:571-600),
  honoring ``skipPositions`` (queue.rs:602-606) with the all-skipped
  edge case completing immediately (queue.rs:608-621);
* engine flavor: standard-chess analysis -> OFFICIAL (NNUE); variants and
  all best-move jobs -> MULTI_VARIANT (HCE) (queue.rs:530-539);
* any position failure abandons the whole batch silently so the server
  reassigns it by timeout (queue.rs:207-214);
* partial progress is reported every 2 x cores completed positions, with
  the first part forced to null — lila distinguishes progress reports
  from final analysis by the first part (queue.rs:286-300, 686-697);
* acquire pacing: user/system backlog thresholds plus the NPS-derived
  minimum, polling the server's /status (queue.rs:331-365).
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from fishnet_tpu import telemetry as _telemetry
from fishnet_tpu.chess import Board, InvalidFenError, UnsupportedVariantError
from fishnet_tpu.resilience import accounting as _accounting
from fishnet_tpu.resilience import faults as _faults
from fishnet_tpu.resilience.shedding import (
    LANE_LATENCY,
    LANE_THROUGHPUT,
    LANES,
)
from fishnet_tpu.telemetry import tracing as _tracing
from fishnet_tpu.telemetry.spans import RECORDER as _SPANS
from fishnet_tpu.ipc import Position, PositionFailed, PositionResponse
from fishnet_tpu.net.api import ApiStub
from fishnet_tpu.protocol.types import (
    AcquiredKind,
    AcquireResponseBody,
    AnalysisPart,
    AnalysisPartJson,
    EngineFlavor,
    Variant,
    Work,
)
from fishnet_tpu.utils.backoff import RandomizedBackoff
from fishnet_tpu.utils.logger import Logger, ProgressAt, QueueStatusBar
from fishnet_tpu.utils.stats import NpsRecorder, Stats, StatsRecorder


#: How many times a batch may be requeued after position failures
#: before it is abandoned to the server's reassignment timeout. Caps
#: the retry loop a deterministically-failing position would otherwise
#: spin forever (doc/resilience.md).
MAX_REQUEUE_GENERATIONS = 2

_REQUEUED = _telemetry.REGISTRY.counter(
    "fishnet_batches_requeued_total",
    "Failed positions re-queued for retry (bounded generations).",
)
_FLUSHED = _telemetry.REGISTRY.counter(
    "fishnet_batches_flushed_total",
    "Batches flushed as partial analyses by the per-batch deadline "
    "budget.",
)
_ABANDONED = _telemetry.REGISTRY.counter(
    "fishnet_batches_abandoned_total",
    "Batches abandoned to the server's reassignment timeout.",
    labelnames=("reason",),
)
_QUEUE_ERRORS = _telemetry.REGISTRY.counter(
    "fishnet_queue_exceptions_total",
    "Unexpected exceptions caught (and survived) by the queue actor.",
)

#: DRR quantum: positions a tenant may drain from the throughput lane
#: per scheduling turn. Large enough to keep a whole small batch
#: together (cache-friendly for the coalescer), small enough that no
#: tenant monopolizes a turn.
DRR_QUANTUM = 8


def lane_of_work(work: Work) -> str:
    """Best-move jobs ride the latency lane; analysis the throughput
    lane (resilience/shedding.py)."""
    return LANE_LATENCY if work.is_move else LANE_THROUGHPUT


class _Skip:
    """Sentinel marking a skipped position (distinct from None = not yet
    analysed), mirroring the reference's Skip<T> (queue.rs:495-505)."""

    __slots__ = ()

    def __repr__(self) -> str:
        return "SKIP"


SKIP = _Skip()


class IncomingError(Exception):
    pass


class AllSkipped(IncomingError):
    def __init__(self, completed: "CompletedBatch") -> None:
        super().__init__("all positions skipped")
        self.completed = completed


@dataclass
class IncomingBatch:
    work: Work
    flavor: EngineFlavor
    variant: Variant
    positions: List[object]  # Position | SKIP
    url: Optional[str] = None

    @classmethod
    def from_acquired(
        cls, endpoint: str, body: AcquireResponseBody
    ) -> "IncomingBatch":
        """Validate + expand an acquired game (queue.rs:516-627). Raises
        IncomingError for invalid games, AllSkipped for the empty edge."""
        url = body.batch_url(endpoint)

        if body.variant.is_standard and body.work.is_analysis:
            flavor = EngineFlavor.OFFICIAL
        else:
            flavor = EngineFlavor.MULTI_VARIANT

        try:
            board = Board(body.position, body.variant)
        except (InvalidFenError, UnsupportedVariantError) as err:
            raise IncomingError(f"invalid position: {err}") from err
        root_fen = board.fen()

        # Trust-boundary legality replay; also normalizes each move's UCI
        # (e.g. e1g1 -> e1h1 castling notation).
        moves: List[str] = []
        replay = board.copy()
        for uci in body.moves:
            normalized = replay.normalize_uci(uci)
            if normalized is None:
                raise IncomingError(f"illegal move {uci!r}")
            replay.push_uci(normalized)
            moves.append(normalized)

        if body.work.is_move:
            positions: List[object] = [
                Position(
                    work=body.work,
                    position_id=0,
                    flavor=flavor,
                    variant=body.variant,
                    root_fen=root_fen,
                    moves=moves,
                    url=url,
                )
            ]
        else:
            positions = []
            for ply in range(len(moves) + 1):
                positions.append(
                    Position(
                        work=body.work,
                        position_id=ply,
                        flavor=flavor,
                        variant=body.variant,
                        root_fen=root_fen,
                        moves=moves[:ply],
                        url=f"{url}#{ply}" if url else None,
                    )
                )
            for skip in body.skip_positions:
                if 0 <= skip < len(positions):
                    positions[skip] = SKIP

            if all(p is SKIP for p in positions):
                now = time.monotonic()
                raise AllSkipped(
                    CompletedBatch(
                        work=body.work,
                        flavor=flavor,
                        variant=body.variant,
                        positions=[SKIP] * len(positions),
                        started_at=now,
                        completed_at=now,
                        url=url,
                    )
                )

        return cls(
            work=body.work,
            flavor=flavor,
            variant=body.variant,
            positions=positions,
            url=url,
        )


@dataclass
class PendingBatch:
    work: Work
    flavor: EngineFlavor
    variant: Variant
    # None = in flight, SKIP = skipped, PositionResponse = done.
    positions: List[object]
    started_at: float
    url: Optional[str] = None
    #: The original Position per index (SKIP for skipped) so a failed
    #: position can be re-queued without re-expanding the batch.
    sources: List[object] = field(default_factory=list)
    #: Requeue generation (bounded by MAX_REQUEUE_GENERATIONS).
    generation: int = 0
    #: The batch's ``schedule`` trace context (telemetry/tracing.py),
    #: set when telemetry is on: ``queue_wait`` spans recorded at
    #: worker-pull time chain under it. None with telemetry off.
    trace: Optional[object] = None
    #: Owning tenant ("" in single-tenant mode) — routes submissions
    #: back through the acquiring tenant's api actor.
    tenant: str = ""
    #: Serving lane (resilience/shedding.py).
    lane: str = LANE_THROUGHPUT

    def pending(self) -> int:
        return sum(1 for p in self.positions if p is None)

    def into_partial_completed(self, now: float) -> "CompletedBatch":
        """Deadline-flush view: everything not yet analysed reports as
        skipped — lila accepts skipped parts, and a partial analysis
        beats wedging the queue behind a hung position."""
        return CompletedBatch(
            work=self.work,
            flavor=self.flavor,
            variant=self.variant,
            positions=[SKIP if p is None else p for p in self.positions],
            started_at=self.started_at,
            completed_at=now,
            url=self.url,
            tenant=self.tenant,
        )

    def try_into_completed(self) -> Optional["CompletedBatch"]:
        if any(p is None for p in self.positions):
            return None
        return CompletedBatch(
            work=self.work,
            flavor=self.flavor,
            variant=self.variant,
            positions=list(self.positions),
            started_at=self.started_at,
            completed_at=time.monotonic(),
            url=self.url,
            tenant=self.tenant,
        )

    def progress_report(self) -> List[Optional[AnalysisPartJson]]:
        report: List[Optional[AnalysisPartJson]] = []
        for i, p in enumerate(self.positions):
            # Lila quirk: the first part must stay null in progress
            # reports (queue.rs:686-697).
            if i > 0 and isinstance(p, PositionResponse):
                report.append(p.to_best())
            else:
                report.append(None)
        return report


@dataclass
class CompletedBatch:
    work: Work
    flavor: EngineFlavor
    variant: Variant
    positions: List[object]  # PositionResponse | SKIP
    started_at: float
    completed_at: float
    url: Optional[str] = None
    tenant: str = ""

    def into_analysis(self) -> List[Optional[AnalysisPartJson]]:
        out: List[Optional[AnalysisPartJson]] = []
        for p in self.positions:
            if p is SKIP:
                out.append(AnalysisPart.skipped())
            else:
                assert isinstance(p, PositionResponse)
                out.append(p.into_matrix() if p.work.matrix_wanted else p.to_best())
        return out

    def into_best_move(self) -> Optional[str]:
        for p in self.positions:
            return p.best_move if isinstance(p, PositionResponse) else None
        return None

    def total_positions(self) -> int:
        return sum(1 for p in self.positions if p is not SKIP)

    def total_nodes(self) -> int:
        return sum(p.nodes for p in self.positions if isinstance(p, PositionResponse))

    def nps(self) -> Optional[int]:
        elapsed = self.completed_at - self.started_at
        if elapsed <= 0:
            return None
        return int(self.total_nodes() / elapsed)


# ---------------------------------------------------------------------------
# Multi-tenant lane scheduler
# ---------------------------------------------------------------------------


class LaneScheduler:
    """Two priority lanes x N tenants with deficit-round-robin fairness.

    Pop order is strict priority: the latency lane (best-move jobs)
    always drains first, plain round-robin across tenants — move jobs
    are rare and tiny, so strict priority cannot starve the bulk lane
    in practice while it guarantees the interactive p99. The
    throughput lane runs classic DRR with unit cost per position: each
    turn a tenant gets ``quantum`` credits and serves until they run
    out or its queue empties, then the turn rotates. Tenants with
    nothing queued hold no turn, so fairness is over *active* tenants
    (max/min served ratio bounded near 1 under sustained load).

    Single-threaded by construction — only the queue actor's event
    loop touches it; the metrics collector reads ``served``/depths as
    racy snapshots, which is fine for gauges.
    """

    def __init__(self, quantum: int = DRR_QUANTUM) -> None:
        self.quantum = max(1, int(quantum))
        # lane -> tenant -> FIFO of Position
        self._queues: Dict[str, Dict[str, Deque[Position]]] = {
            lane: {} for lane in LANES
        }
        self._rings: Dict[str, Deque[str]] = {lane: deque() for lane in LANES}
        self._credit: Dict[str, int] = {}  # throughput-lane DRR deficits
        # Control-plane admission weights: a tenant's DRR turn refills
        # round(quantum * weight) credits (min 1). Missing = 1.0.
        self._weights: Dict[str, float] = {}
        #: Positions handed to workers, per tenant (fairness measure).
        self.served: Dict[str, int] = {}

    def set_tenant_weights(self, weights: Optional[Dict[str, float]]) -> None:
        """Control-plane actuation: REPLACE the admission-weight map
        (None or {} restores unweighted DRR). Weights scale the credit
        refill, so they reshape sustained throughput shares without
        ever starving a tenant — every active tenant still gets a turn
        with at least one credit."""
        self._weights = dict(weights) if weights else {}

    def tenant_weights(self) -> Dict[str, float]:
        return dict(self._weights)

    def push(
        self, position: Position, tenant: str, lane: str,
        front: bool = False,
    ) -> None:
        queues = self._queues[lane]
        q = queues.get(tenant)
        if q is None:
            q = queues[tenant] = deque()
        if front:
            q.appendleft(position)
        else:
            q.append(position)
        ring = self._rings[lane]
        if tenant not in ring:
            ring.append(tenant)

    def _pop_latency(self) -> Optional[Position]:
        ring = self._rings[LANE_LATENCY]
        queues = self._queues[LANE_LATENCY]
        while ring:
            q = queues.get(ring[0])
            if not q:
                ring.popleft()
                continue
            position = q.popleft()
            ring.rotate(-1)
            return position
        return None

    def _pop_throughput(self) -> Optional[Position]:
        ring = self._rings[LANE_THROUGHPUT]
        queues = self._queues[LANE_THROUGHPUT]
        while ring:
            tenant = ring[0]
            q = queues.get(tenant)
            if not q:
                # Queue drained mid-turn: the tenant leaves the ring
                # (and forfeits leftover credit) until new work arrives.
                ring.popleft()
                self._credit.pop(tenant, None)
                continue
            credit = self._credit.get(tenant)
            if credit is None:
                weight = self._weights.get(tenant, 1.0)
                credit = self._credit[tenant] = max(
                    1, int(round(self.quantum * weight))
                )
            if credit <= 0:
                # Turn over: rotate to the back; credit refills on the
                # next visit.
                del self._credit[tenant]
                ring.rotate(-1)
                continue
            self._credit[tenant] = credit - 1
            return q.popleft()
        return None

    def pop(self) -> Optional[Position]:
        position = self._pop_latency()
        if position is None:
            position = self._pop_throughput()
        return position

    def note_served(self, tenant: str) -> None:
        self.served[tenant] = self.served.get(tenant, 0) + 1

    def drop_batch(self, batch_id: str) -> int:
        """Remove every queued position of ``batch_id``; returns the
        number removed."""
        dropped = 0
        for queues in self._queues.values():
            for tenant, q in queues.items():
                kept = deque(p for p in q if p.work.id != batch_id)
                dropped += len(q) - len(kept)
                queues[tenant] = kept
        return dropped

    def clear(self) -> int:
        """Drop everything (hard shutdown); returns the count dropped."""
        dropped = len(self)
        for lane in LANES:
            self._queues[lane] = {}
            self._rings[lane].clear()
        self._credit.clear()
        return dropped

    def depth(self, lane: str) -> int:
        return sum(len(q) for q in self._queues[lane].values())

    def depths(self) -> Dict[str, int]:
        return {lane: self.depth(lane) for lane in LANES}

    def __len__(self) -> int:
        return sum(self.depth(lane) for lane in LANES)


# ---------------------------------------------------------------------------
# Queue state shared between stub and actor
# ---------------------------------------------------------------------------


def _register_queue_collector(state: "QueueState") -> int:
    """Scheduler-depth metrics (doc/observability.md), pulled at scrape
    time from the live QueueState: positions in flight, batch count,
    incoming queue depth, and backlog seconds (age of the oldest pending
    batch). Holds only a weakref so a finished client's state is
    collectable; reads are snapshot-copied (exporter thread vs event
    loop) and never mutate."""
    ref = weakref.ref(state)

    def collect():
        st = ref()
        if st is None:
            return None
        batches = list(st.pending.values())
        oldest = min((b.started_at for b in batches), default=None)
        backlog = 0.0 if oldest is None else max(
            0.0, time.monotonic() - oldest
        )
        families = [
            _telemetry.gauge_family(
                "fishnet_queue_pending_positions",
                "Positions assigned to workers but not yet analysed.",
                sum(b.pending() for b in batches),
            ),
            _telemetry.gauge_family(
                "fishnet_queue_pending_batches",
                "Acquired batches not yet fully analysed.", len(batches),
            ),
            _telemetry.gauge_family(
                "fishnet_queue_incoming_positions",
                "Positions queued for worker pull.", st.incoming_len(),
            ),
            _telemetry.gauge_family(
                "fishnet_queue_backlog_seconds",
                "Age of the oldest pending batch.", backlog,
            ),
            _telemetry.gauge_family(
                "fishnet_queue_move_submissions",
                "Completed move jobs awaiting submission.",
                len(st.move_submissions),
            ),
        ]
        sched = st.scheduler
        if sched is not None:
            families.append(_telemetry.MetricFamily(
                "fishnet_lane_depth", "gauge",
                "Positions queued per serving lane.",
                [
                    _telemetry.Sample(
                        "fishnet_lane_depth", float(depth), {"lane": lane}
                    )
                    for lane, depth in sched.depths().items()
                ],
            ))
            families.append(_telemetry.MetricFamily(
                "fishnet_tenant_positions_served", "gauge",
                "Positions handed to workers, per tenant (fairness).",
                [
                    _telemetry.Sample(
                        "fishnet_tenant_positions_served",
                        float(count), {"tenant": tenant},
                    )
                    for tenant, count in sorted(sched.served.items())
                ],
            ))
        return families

    return _telemetry.REGISTRY.register_collector(collect, name="queue")


class QueueState:
    def __init__(
        self,
        cores: int,
        stats: StatsRecorder,
        logger: Logger,
        batch_deadline: Optional[float] = None,
        scheduler: Optional[LaneScheduler] = None,
        api_router=None,
    ) -> None:
        self.shutdown_soon = False
        self.cores = cores
        self.incoming: Deque[Position] = deque()
        self.pending: Dict[str, PendingBatch] = {}
        self.move_submissions: Deque[CompletedBatch] = deque()
        self.stats_recorder = stats
        self.logger = logger
        #: Per-batch deadline budget (seconds); None = no deadline.
        self.batch_deadline = batch_deadline
        #: Multi-tenant mode: a LaneScheduler replaces the single
        #: ``incoming`` deque (every access goes through the
        #: ``incoming_*`` methods below, which keep the legacy deque
        #: path byte-for-byte when no scheduler is installed).
        self.scheduler = scheduler
        #: Callable[[tenant], Optional[ApiStub]] — routes submissions
        #: back through the acquiring tenant's api actor. None in
        #: single-tenant mode (the stub/actor default applies).
        self.api_router = api_router

    # -- incoming-queue access (legacy deque vs lane scheduler) -----------

    def incoming_len(self) -> int:
        if self.scheduler is not None:
            return len(self.scheduler)
        return len(self.incoming)

    def incoming_push(
        self, position: Position, tenant: str = "",
        lane: str = LANE_THROUGHPUT, front: bool = False,
    ) -> None:
        if self.scheduler is not None:
            self.scheduler.push(position, tenant, lane, front=front)
        elif front:
            self.incoming.appendleft(position)
        else:
            self.incoming.append(position)

    def incoming_pop(self) -> Optional[Position]:
        if self.scheduler is not None:
            return self.scheduler.pop()
        return self.incoming.popleft() if self.incoming else None

    def incoming_drop_batch(self, batch_id: str) -> None:
        if self.scheduler is not None:
            self.scheduler.drop_batch(batch_id)
        else:
            self.incoming = deque(
                p for p in self.incoming if p.work.id != batch_id
            )

    def incoming_clear(self) -> None:
        if self.scheduler is not None:
            self.scheduler.clear()
        else:
            self.incoming.clear()

    def api_for(self, batch, default: ApiStub) -> ApiStub:
        """The api stub that owns ``batch`` (its tenant's actor in
        multi-tenant mode, the shared default otherwise)."""
        if self.api_router is not None and batch.tenant:
            stub = self.api_router(batch.tenant)
            if stub is not None:
                return stub
        return default

    def flush_expired(self, api: ApiStub) -> int:
        """Enforce the per-batch deadline budget: analysis batches older
        than the budget are submitted PARTIALLY (unanalysed plies marked
        skipped); expired move jobs are aborted (a stale move is
        useless). Cheap when nothing is pending or no deadline is set;
        called from the worker-pull hot points so one hung engine can
        never wedge every other batch behind it."""
        if self.batch_deadline is None or not self.pending:
            return 0
        now = time.monotonic()
        flushed = 0
        for batch_id in list(self.pending):
            batch = self.pending[batch_id]
            if now - batch.started_at <= self.batch_deadline:
                continue
            del self.pending[batch_id]
            self.incoming_drop_batch(batch_id)
            led = _accounting.get()
            if batch.work.is_analysis:
                _FLUSHED.inc()
                if led is not None:
                    led.record_flushed(batch_id)
                completed = batch.into_partial_completed(now)
                done = sum(
                    1 for p in completed.positions
                    if isinstance(p, PositionResponse)
                )
                self.logger.error(
                    f"Batch {batch.url or batch_id} exceeded its "
                    f"{self.batch_deadline:.0f}s deadline; flushing "
                    f"{done}/{len(completed.positions)} analysed plies."
                )
                self.api_for(batch, api).submit_analysis(
                    completed.work.id,
                    completed.flavor.eval_flavor(),
                    completed.into_analysis(),
                    final=True,
                )
            else:
                _ABANDONED.inc(reason="deadline")
                if led is not None:
                    led.record_abandoned(batch_id, "deadline")
                self.logger.error(
                    f"Move job {batch_id} exceeded its deadline; aborting."
                )
                self.api_for(batch, api).abort(batch_id)
            flushed += 1
        return flushed

    def status_bar(self) -> QueueStatusBar:
        return QueueStatusBar(
            pending=sum(p.pending() for p in self.pending.values()), cores=self.cores
        )

    def try_pull(self, callback: asyncio.Future) -> bool:
        """Serve a queued position to a worker callback; False if empty."""
        position = self.incoming_pop()
        if position is None:
            return False
        batch = self.pending.get(position.work.id)
        if not callback.done():
            if _telemetry.enabled():
                # "queue_wait" span: this position's dwell in the
                # incoming queue, from batch enqueue to this pull —
                # a child of the batch's schedule span (the context
                # stashed on PendingBatch at accept time).
                if batch is not None and batch.trace is not None:
                    _SPANS.record(
                        "queue_wait", batch.started_at,
                        trace=batch.trace.child(),
                        batch=position.work.id,
                        position_id=position.position_id,
                    )
            callback.set_result(position)
            if self.scheduler is not None and batch is not None:
                self.scheduler.note_served(batch.tenant)
            return True
        # Callback abandoned (worker gone): keep the position, front of
        # its own lane/tenant queue so ordering is preserved.
        if batch is not None:
            self.incoming_push(
                position, tenant=batch.tenant, lane=batch.lane, front=True
            )
        else:
            self.incoming_push(position, front=True)
        return True

    def add_incoming_batch(
        self,
        batch: IncomingBatch,
        trace: Optional[object] = None,
        tenant: str = "",
    ) -> None:
        batch_id = batch.work.id
        if batch_id in self.pending:
            self.logger.error(f"Dropping duplicate incoming batch {batch_id}")
            return
        lane = lane_of_work(batch.work)
        if tenant:
            # Stamp the originating tenant onto every position BEFORE
            # the push loop and the ``sources`` copy below, so both the
            # first pass and any requeue carry it down to the engine
            # tier and the cost plane (telemetry/cost.py). Position is
            # frozen — replace, don't mutate.
            batch.positions = [
                p if p is SKIP else dataclasses.replace(p, tenant=tenant)
                for p in batch.positions
            ]
        placeholders: List[object] = []
        for pos in batch.positions:
            if pos is SKIP:
                placeholders.append(SKIP)
            else:
                placeholders.append(None)
                self.incoming_push(pos, tenant=tenant, lane=lane)
        self.pending[batch_id] = PendingBatch(
            work=batch.work,
            flavor=batch.flavor,
            variant=batch.variant,
            positions=placeholders,
            started_at=time.monotonic(),
            url=batch.url,
            sources=list(batch.positions),
            trace=trace,
            tenant=tenant,
            lane=lane,
        )
        led = _accounting.get()
        if led is not None:
            led.record_scheduled(batch_id)
        self.logger.progress(
            self.status_bar(), ProgressAt(batch_id=batch_id, batch_url=batch.url)
        )


# ---------------------------------------------------------------------------
# Stub + actor
# ---------------------------------------------------------------------------


@dataclass
class Pull:
    """The work-stealing handshake (ipc.rs:100-115): a worker hands back
    its previous result (if any) and a future to receive the next job."""

    response: Optional[object]  # PositionResponse | PositionFailed | None
    callback: asyncio.Future


class QueueStub:
    def __init__(
        self,
        tx: "asyncio.Queue",
        interrupt: asyncio.Event,
        state: QueueState,
        api: ApiStub,
    ) -> None:
        self._tx: Optional[asyncio.Queue] = tx
        self._interrupt = interrupt
        self._state = state
        self._api = api

    async def pull(self, pull: Pull) -> None:
        if pull.response is not None:
            self._handle_position_response(pull.response)
        # Deadline budget: every worker handoff checks for expired
        # batches, so a single hung engine cannot wedge the rest of the
        # queue behind its batch.
        self._state.flush_expired(self._api)
        if self._state.try_pull(pull.callback):
            return
        if self._state.shutdown_soon and not self._state.incoming_len():
            # Drain complete for this worker; release it (the reference
            # releases workers by dropping their callbacks, main.rs:374-382).
            if not pull.callback.done():
                pull.callback.cancel()
            return
        if self._tx is not None:
            await self._tx.put(pull.callback)
        elif not pull.callback.done():
            pull.callback.cancel()

    def _handle_position_response(self, res: object) -> None:
        state = self._state
        if isinstance(res, PositionFailed):
            self._handle_position_failed(res)
            return
        assert isinstance(res, PositionResponse)
        batch = state.pending.get(res.work.id)
        if batch is not None and 0 <= res.position_id < len(batch.positions):
            batch.positions[res.position_id] = res
            led = _accounting.get()
            if led is not None:
                led.record_stepped(res.work.id)
        state.logger.progress(
            state.status_bar(),
            ProgressAt(
                batch_id=res.work.id, batch_url=res.url, position_id=res.position_id
            ),
        )
        self._maybe_finished(res.work.id)

    def _handle_position_failed(self, res: PositionFailed) -> None:
        """Requeue a failed position (bounded generations) instead of
        abandoning the whole batch on the first transient engine
        failure. The requeued position goes to the FRONT of the
        incoming queue so an older batch's retry is served before fresh
        acquires' positions (acquire order preserved — a failed batch
        can no longer starve behind new work). Producers that do not
        identify the position (legacy PositionFailed without
        position_id), and batches over the generation cap, keep the
        reference behavior: abandon silently, the server reassigns by
        timeout (queue.rs:207-214)."""
        state = self._state
        batch = state.pending.get(res.batch_id)
        if batch is None:
            return
        src = None
        if res.position_id is not None and (
            0 <= res.position_id < len(batch.sources)
        ):
            src = batch.sources[res.position_id]
        led = _accounting.get()
        if (
            src is None
            or src is SKIP
            or batch.generation >= MAX_REQUEUE_GENERATIONS
        ):
            reason = (
                "requeue_cap" if batch.generation >= MAX_REQUEUE_GENERATIONS
                else "position_failed"
            )
            state.pending.pop(res.batch_id, None)
            state.incoming_drop_batch(res.batch_id)
            _ABANDONED.inc(reason=reason)
            if led is not None:
                led.record_abandoned(res.batch_id, reason)
            state.logger.warn(
                f"Abandoning batch {batch.url or res.batch_id} ({reason}); "
                "the server will reassign it."
            )
            return
        batch.generation += 1
        _REQUEUED.inc()
        if led is not None:
            led.record_requeued(res.batch_id, batch.generation)
        state.incoming_push(
            src, tenant=batch.tenant, lane=batch.lane, front=True
        )
        state.logger.debug(
            f"Requeued position {res.position_id} of {res.batch_id} "
            f"(generation {batch.generation}/{MAX_REQUEUE_GENERATIONS})."
        )

    def _maybe_finished(self, batch_id: str) -> None:
        state = self._state
        pending = state.pending.pop(batch_id, None)
        if pending is None:
            return
        completed = pending.try_into_completed()
        if completed is not None:
            # Batch completion ticks the eval cache's eviction clock
            # (search/eval_cache.py): entries inserted while this batch
            # was live age one generation, so under memory pressure the
            # cache sheds dead batches' positions before the live
            # working set. Purely an eviction-ordering signal — values
            # are never invalidated by it.
            from fishnet_tpu.search import eval_cache

            cache = eval_cache.get_cache()
            if cache is not None:
                cache.advance_generation()
                # The fleet tier shares the clock: any process's batch
                # completion ages the whole segment's slots, so fixed-
                # slot replacement prefers positions no live batch
                # anywhere in the fleet is still visiting.
                from fishnet_tpu.cluster import position_tier

                tier = position_tier.get_tier()
                if tier is not None:
                    tier.advance_generation()
        if completed is None:
            if not pending.work.matrix_wanted:
                report = pending.progress_report()
                done = sum(1 for p in report if p is not None)
                if done and done % (state.cores * 2) == 0:
                    state.api_for(pending, self._api).submit_analysis(
                        pending.work.id, pending.flavor.eval_flavor(), report
                    )
            state.pending[batch_id] = pending
            return

        extra = []
        short = completed.variant.short_name()
        if short:
            extra.append(short)
        if completed.flavor.eval_flavor().is_hce:
            extra.append("hce")
        nps = completed.nps()
        if nps is not None:
            nnue_nps = nps if completed.flavor is EngineFlavor.OFFICIAL else None
            state.stats_recorder.record_batch(
                completed.total_positions(), completed.total_nodes(), nnue_nps
            )
            extra.append(f"{nps // 1000} knps")
        else:
            extra.append("? nps")
        label = completed.url or batch_id
        log = f"{state.status_bar()} {label} finished ({', '.join(extra)})"

        if completed.work.is_analysis:
            state.logger.info(log)
            state.api_for(completed, self._api).submit_analysis(
                completed.work.id,
                completed.flavor.eval_flavor(),
                completed.into_analysis(),
                final=True,
            )
        else:
            state.logger.debug(log)
            state.move_submissions.append(completed)
            self._move_submitted()

    def _move_submitted(self) -> None:
        if self._tx is not None:
            self._tx.put_nowait("move_submitted")
            self._interrupt.set()

    def shutdown_soon(self) -> None:
        self._state.shutdown_soon = True
        if self._tx is not None:
            self._tx.put_nowait("wake")
        self._tx = None
        self._interrupt.set()

    def shutdown(self) -> None:
        self.shutdown_soon()
        led = _accounting.get()
        for batch_id in list(self._state.pending):
            batch = self._state.pending.pop(batch_id)
            if led is not None:
                led.record_abandoned(batch_id, "shutdown_abort")
            self._state.api_for(batch, self._api).abort(batch_id)
        # The queued positions belonged to the batches just aborted;
        # drop them too so the drain check sees an empty queue.
        self._state.incoming_clear()

    def depth(self) -> Dict[str, int]:
        """Remaining-work snapshot (graceful drain's readiness body,
        resilience/drain.py): batches still pending, their unanalysed
        positions, and positions queued for worker pull."""
        state = self._state
        return {
            "batches": len(state.pending),
            "positions": sum(b.pending() for b in state.pending.values()),
            "queued": state.incoming_len(),
        }

    def stats(self) -> Tuple[Stats, NpsRecorder]:
        return (
            self._state.stats_recorder.stats,
            self._state.stats_recorder.nnue_nps,
        )


@dataclass
class BacklogOpt:
    """Backlog thresholds in seconds (reference: configure.rs:231-276;
    'short' = 30 s, 'long' = 1 h)."""

    user: Optional[float] = None
    system: Optional[float] = None


class QueueActor:
    def __init__(
        self,
        rx: "asyncio.Queue",
        interrupt: asyncio.Event,
        state: QueueState,
        api: ApiStub,
        backlog: BacklogOpt,
        logger: Logger,
        max_backoff: float = 30.0,
    ) -> None:
        self.rx = rx
        self.interrupt = interrupt
        self.state = state
        self.api = api
        self.backlog = backlog
        self.logger = logger
        self.backoff = RandomizedBackoff(max_backoff)

    async def backlog_wait_time(self) -> Tuple[float, bool]:
        """(seconds to wait before acquiring, slow?) — queue.rs:331-365."""
        min_user = self.state.stats_recorder.min_user_backlog()
        user_backlog = max(min_user, self.backlog.user or 0.0)
        system_backlog = self.backlog.system or 0.0

        if user_backlog >= 1.0 or system_backlog >= 1.0:
            status = await self.api.status()
            if status is not None:
                user_wait = max(0.0, user_backlog - status.user.oldest_seconds)
                system_wait = max(0.0, system_backlog - status.system.oldest_seconds)
                slow = user_wait >= system_wait + 1.0
                return min(user_wait, system_wait), slow
            self.logger.debug("Queue status not available. Will not delay acquire.")
            return 0.0, user_backlog >= system_backlog + 1.0
        return 0.0, False

    async def _interruptible_sleep(self, seconds: float) -> None:
        self.interrupt.clear()
        try:
            await asyncio.wait_for(self.interrupt.wait(), timeout=seconds)
        except asyncio.TimeoutError:
            pass

    async def handle_acquired(self, body: AcquireResponseBody) -> None:
        context = body.work.id
        # "schedule" span: trust-boundary replay + per-ply expansion +
        # enqueue — the stage between acquire and the search pipeline.
        # Its trace context parents into the batch trace by digest
        # (tracing.batch_child: the acquire root's span id IS the
        # deterministic trace id, no cross-actor plumbing needed).
        tel = _telemetry.enabled()
        t0 = time.monotonic() if tel else 0.0
        sched_ctx = _tracing.batch_child(context) if tel else None
        try:
            # "queue.schedule" fault site: a failure here is a
            # trust-boundary failure — the batch is dropped like an
            # invalid one and the server reassigns by timeout.
            if _faults.enabled():
                await _faults.fire_async("queue.schedule")
            incoming = IncomingBatch.from_acquired(self.api.endpoint, body)
        except AllSkipped as all_skipped:
            self.logger.warn(f"Completed empty batch {context}.")
            completed = all_skipped.completed
            led = _accounting.get()
            if led is not None:
                led.record_scheduled(completed.work.id)
            self.api.submit_analysis(
                completed.work.id,
                completed.flavor.eval_flavor(),
                completed.into_analysis(),
                final=True,
            )
            if tel:
                _SPANS.record(
                    "schedule", t0, trace=sched_ctx,
                    batch=context, outcome="all_skipped",
                )
            return
        except (IncomingError, _faults.FaultInjected) as err:
            self.logger.warn(f"Ignoring invalid batch {context}: {err}")
            led = _accounting.get()
            if led is not None:
                led.record_invalid(context, str(err))
            if tel:
                _SPANS.record(
                    "schedule", t0, trace=sched_ctx,
                    batch=context, outcome="invalid",
                )
            return
        if self.state.shutdown_soon:
            # Accepted while shutting down (an in-flight acquire
            # resolving after shutdown()): nobody will run it. Abandon
            # it through the ledger and hand it back to the server
            # instead of dropping it on the floor.
            _ABANDONED.inc(reason="shutdown_incoming")
            led = _accounting.get()
            if led is not None:
                led.record_abandoned(context, "shutdown_incoming")
            self.api.abort(context)
            if tel:
                _SPANS.record(
                    "schedule", t0, trace=sched_ctx,
                    batch=context, outcome="shutdown",
                )
            return
        self.state.add_incoming_batch(
            incoming, trace=sched_ctx, tenant=self.api.tenant
        )
        if tel:
            _SPANS.record(
                "schedule", t0, trace=sched_ctx,
                batch=context, outcome="accepted",
                positions=len(incoming.positions),
            )

    async def handle_move_submissions(self) -> None:
        while True:
            if self.state.shutdown_soon:
                # Move submissions can chain follow-up jobs; stop chasing
                # them during shutdown (queue.rs:399-404).
                return
            if not self.state.move_submissions:
                return
            completed = self.state.move_submissions.popleft()
            acquired = await self.api.submit_move_and_acquire(
                completed.work.id, completed.into_best_move()
            )
            if acquired is not None and acquired.kind is AcquiredKind.ACCEPTED:
                await self.handle_acquired(acquired.body)

    async def run(self) -> None:
        self.logger.debug("Queue actor started")
        try:
            while True:
                msg = await self.rx.get()
                if msg == "move_submitted":
                    await self.handle_move_submissions()
                    continue
                if msg == "wake":
                    if self.state.shutdown_soon:
                        break
                    continue
                callback: asyncio.Future = msg
                try:
                    await self._pull_loop(callback)
                except asyncio.CancelledError:
                    raise
                except Exception as err:  # noqa: BLE001 - keep the queue alive
                    _QUEUE_ERRORS.inc()
                    self.logger.error(f"Queue error: {err!r}")
                    if not callback.done():
                        callback.cancel()
                if self.state.shutdown_soon and not self.state.incoming_len():
                    break
        finally:
            # Release any workers still parked in the mailbox.
            while not self.rx.empty():
                leftover = self.rx.get_nowait()
                if isinstance(leftover, asyncio.Future) and not leftover.done():
                    leftover.cancel()
            self.logger.debug("Queue actor exited")

    async def _pull_loop(self, callback: asyncio.Future) -> None:
        while True:
            await self.handle_move_submissions()
            self.state.flush_expired(self.api)

            if self.state.try_pull(callback):
                return
            if self.state.shutdown_soon:
                # Drain phase: no more work will come; release the worker.
                if not callback.done():
                    callback.cancel()
                return
            if callback.done():
                return

            wait, slow = await self.backlog_wait_time()
            if wait >= 1.0:
                level = self.logger.info if wait >= 40.0 else self.logger.debug
                level(f"Going idle for {wait:.0f}s.")
                await self._interruptible_sleep(wait)
                continue

            acquired = await self.api.acquire(slow)
            if acquired is None:
                # Transport error: the api actor already backed off.
                continue
            if acquired.kind is AcquiredKind.ACCEPTED:
                self.backoff.reset()
                await self.handle_acquired(acquired.body)
            elif acquired.kind is AcquiredKind.NO_CONTENT:
                backoff = self.backoff.next()
                self.logger.debug(f"No job received. Backing off {backoff:.1f}s.")
                await self._interruptible_sleep(backoff)
            elif acquired.kind is AcquiredKind.REJECTED:
                self.logger.error(
                    "Client update or reconfiguration might be required. Stopping queue."
                )
                self.state.shutdown_soon = True
                if not callback.done():
                    callback.cancel()
                return


def channel(
    cores: int,
    api: ApiStub,
    logger: Logger,
    stats: Optional[StatsRecorder] = None,
    backlog: Optional[BacklogOpt] = None,
    max_backoff: float = 30.0,
    batch_deadline: Optional[float] = None,
) -> Tuple[QueueStub, QueueActor]:
    rx: "asyncio.Queue" = asyncio.Queue()
    interrupt = asyncio.Event()
    state = QueueState(
        cores, stats or StatsRecorder(cores, no_stats_file=True), logger,
        batch_deadline=batch_deadline,
    )
    _register_queue_collector(state)
    stub = QueueStub(rx, interrupt, state, api)
    actor = QueueActor(
        rx, interrupt, state, api, backlog or BacklogOpt(), logger, max_backoff
    )
    return stub, actor
