"""Multi-tenant serving front end: N concurrent acquire streams
multiplexed into the shared coalescer/eval plane.

The reference client is one acquire stream feeding one queue; the
north-star deployment is many request sources feeding one accelerator
plane, because that is what keeps device batches full (PAPERS.md
1908.09296 fills Crazyhouse batches from concurrent games the same
way). This module is that multiplexing layer:

* each **tenant** owns a full ``net/api.py`` channel — its own actor
  task, error backoff, 429 suspension, and submit breaker, so one
  misbehaving stream cannot suspend traffic for the rest;
* all tenants feed one shared :class:`~fishnet_tpu.sched.queue.QueueState`
  whose :class:`~fishnet_tpu.sched.queue.LaneScheduler` splits work
  into a latency lane (best-move) and a throughput lane (analysis)
  with deficit-round-robin fairness across tenants;
* admission control (:class:`~fishnet_tpu.resilience.shedding.ShedPolicy`)
  bounds the throughput lane: past the high watermark, analysis
  batches are **shed** — abandoned through the exactly-once ledger and
  aborted back to the server (which reassigns them), never silently
  lost — while shed-aware pacing slows every tenant's acquire stream
  until the queue drains under the low watermark;
* workers keep pulling through the ordinary ``QueueStub``; when the
  queue is empty their callbacks park here and are served the moment
  any tenant admits a batch.

``FISHNET_NO_MULTITENANT=1`` (or ``--tenants 1``) disables all of
this: the client wires the classic single-stream actor pair and no
code in this module runs.
"""

from __future__ import annotations

import asyncio
import os
import time
import weakref
from collections import deque
from typing import Deque, Dict, List, Optional

from fishnet_tpu import telemetry as _telemetry
from fishnet_tpu.net import api as api_mod
from fishnet_tpu.resilience import accounting as _accounting
from fishnet_tpu.resilience import faults as _faults
from fishnet_tpu.resilience.shedding import (
    ADMIT,
    LANE_LATENCY,
    LANE_THROUGHPUT,
    SHED,
    ShedPolicy,
)
from fishnet_tpu.resilience.supervisor import any_breaker_open, breaker_states
from fishnet_tpu.sched.queue import (
    _ABANDONED,
    _QUEUE_ERRORS,
    BacklogOpt,
    LaneScheduler,
    QueueActor,
    QueueState,
    QueueStub,
    lane_of_work,
)
from fishnet_tpu.protocol.types import AcquiredKind, AcquireResponseBody
from fishnet_tpu.telemetry import tracing as _tracing
from fishnet_tpu.telemetry.spans import RECORDER as _SPANS
from fishnet_tpu.utils.backoff import RandomizedBackoff
from fishnet_tpu.utils.logger import Logger
from fishnet_tpu.utils.stats import StatsRecorder

#: Escape hatch: restores the single-stream client path byte-for-byte
#: regardless of --tenants.
NO_MULTITENANT_ENV = "FISHNET_NO_MULTITENANT"

_TENANT_ACQUIRED = _telemetry.REGISTRY.counter(
    "fishnet_tenant_batches_acquired_total",
    "Batches acquired per tenant stream.",
    labelnames=("tenant",),
)
_TENANT_SHED = _telemetry.REGISTRY.counter(
    "fishnet_tenant_batches_shed_total",
    "Batches shed (accounted abort back to the server) per tenant.",
    labelnames=("tenant",),
)


def multitenant_enabled(tenants: int) -> bool:
    """True when the multi-tenant front end should be wired."""
    return tenants > 1 and os.environ.get(NO_MULTITENANT_ENV) != "1"


class TenantStream:
    """One acquire stream: an api channel plus a helper QueueActor
    whose ``handle_acquired`` does the trust-boundary expansion (the
    helper's mailbox loop never runs — the front end is the loop)."""

    def __init__(self, name: str, stub, actor, helper: QueueActor) -> None:
        self.name = name
        self.stub = stub
        self.actor = actor
        self.helper = helper
        self.acquired = 0
        self.shed = 0
        self.rejected = False


class FrontEnd:
    """The multiplexer: owns the shared queue state, the per-tenant
    channels, and the admission/shedding policy. ``run()`` is the
    queue task; the per-tenant api actors are separate tasks the
    client schedules (``api_actors()``)."""

    def __init__(
        self,
        endpoint: str,
        key: Optional[str],
        logger: Logger,
        cores: int,
        tenants: int = 4,
        stats: Optional[StatsRecorder] = None,
        backlog: Optional[BacklogOpt] = None,
        max_backoff: float = 30.0,
        batch_deadline: Optional[float] = None,
        shed_policy: Optional[ShedPolicy] = None,
        supervisor=None,
    ) -> None:
        if tenants < 2:
            raise ValueError("FrontEnd needs >= 2 tenants")
        self.logger = logger
        self.cores = cores
        self.max_backoff = max_backoff
        self.backlog = backlog or BacklogOpt()
        rung_fn = (lambda: supervisor.rung) if supervisor is not None else None
        self.shed_policy = shed_policy or ShedPolicy(
            breaker_open_fn=any_breaker_open, rung_fn=rung_fn,
        )
        self.rx: "asyncio.Queue" = asyncio.Queue()
        self.interrupt = asyncio.Event()
        self.state = QueueState(
            cores,
            stats or StatsRecorder(cores, no_stats_file=True),
            logger,
            batch_deadline=batch_deadline,
            scheduler=LaneScheduler(),
            api_router=self._api_for_tenant,
        )
        self.tenants: Dict[str, TenantStream] = {}
        for i in range(tenants):
            name = f"t{i}"
            stub, actor = api_mod.channel(endpoint, key, logger, tenant=name)
            stub.pacer = api_mod.ShedAwarePacer(
                lambda: self.shed_policy.shed_active, tenant=name
            )
            helper = QueueActor(
                self.rx, self.interrupt, self.state, stub,
                self.backlog, logger, max_backoff,
            )
            self.tenants[name] = TenantStream(name, stub, actor, helper)
        self._default = next(iter(self.tenants.values()))
        self.stub = QueueStub(
            self.rx, self.interrupt, self.state, self._default.stub
        )
        #: Worker callbacks parked while the queue is empty.
        self._waiting: Deque[asyncio.Future] = deque()
        _register_frontend_health(self)

    # -- plumbing ---------------------------------------------------------

    def _api_for_tenant(self, tenant: str):
        ts = self.tenants.get(tenant)
        return ts.stub if ts is not None else None

    def api_actors(self) -> List[tuple]:
        """(name, actor) pairs for the client to schedule as tasks."""
        return [(f"api-{ts.name}", ts.actor) for ts in self.tenants.values()]

    def health_snapshot(self) -> Dict[str, object]:
        """Serving state for /healthz (telemetry/exporter.py). The
        exporter turns ``healthy: False`` into a non-200 so a load
        balancer drains this worker while it sheds."""
        sched = self.state.scheduler
        shed = self.shed_policy.shed_active
        snap: Dict[str, object] = {
            "healthy": not shed,
            "shedding": shed,
            "policy": self.shed_policy.snapshot(),
            "lane_depths": sched.depths() if sched is not None else {},
            "pending_batches": len(self.state.pending),
            "breakers": breaker_states(),
            "tenants": {
                ts.name: {"acquired": ts.acquired, "shed": ts.shed}
                for ts in self.tenants.values()
            },
        }
        return snap

    # -- admission --------------------------------------------------------

    async def _admit(self, ts: TenantStream, body: AcquireResponseBody) -> None:
        """Admission-check one acquired batch, then either schedule it
        (tenant-tagged, through the helper's trust-boundary expansion)
        or shed it: abandon through the ledger + abort upstream so the
        server reassigns it. Nothing is ever silently dropped."""
        context = body.work.id
        lane = lane_of_work(body.work)
        # Positions this batch will enqueue if admitted; known before
        # the (more expensive) legality replay.
        est = 1 if body.work.is_move else len(body.moves) + 1
        tel = _telemetry.enabled()
        t0 = time.monotonic() if tel else 0.0
        sched = self.state.scheduler
        decision = ADMIT
        try:
            # "queue.admit" fault site: an admission-layer failure
            # degrades to a shed — accounted and aborted, never lost.
            if _faults.enabled():
                await _faults.fire_async("queue.admit")
        except _faults.FaultInjected as err:
            self.logger.warn(f"Admission fault for {context}: {err}")
            decision = SHED
        if decision is not SHED:
            decision = self.shed_policy.admit(
                lane, est,
                sched.depth(LANE_THROUGHPUT), sched.depth(LANE_LATENCY),
            )
        if tel:
            _SPANS.record(
                "admit", t0, trace=_tracing.batch_child(context),
                batch=context, tenant=ts.name, lane=lane,
                decision=decision, positions=est,
            )
        if decision is SHED:
            ts.shed += 1
            _TENANT_SHED.inc(tenant=ts.name)
            _ABANDONED.inc(reason="shed")
            led = _accounting.get()
            if led is not None:
                led.record_abandoned(context, "shed")
            ts.stub.abort(context)
            self.logger.debug(
                f"Shed {lane}-lane batch {context} from {ts.name} "
                "(admission control); the server will reassign it."
            )
            return
        ts.acquired += 1
        _TENANT_ACQUIRED.inc(tenant=ts.name)
        await ts.helper.handle_acquired(body)
        self._kick()

    def _kick(self) -> None:
        """Serve parked worker callbacks from the (now non-empty)
        scheduler."""
        while self._waiting and self.state.incoming_len():
            callback = self._waiting.popleft()
            if callback.done():
                continue
            if not self.state.try_pull(callback):
                self._waiting.appendleft(callback)
                return

    # -- the two loop families --------------------------------------------

    async def _acquire_loop(self, ts: TenantStream) -> None:
        """One tenant's continuous acquire stream. Mirrors the
        single-stream actor's pull loop pacing (backlog thresholds,
        no-content backoff, reject stop) with shed-aware pacing layered
        on: while the policy sheds, each round first sleeps a pacing
        quantum, so a saturated queue is not churned with
        acquire/abort cycles any faster than it drains."""
        backoff = RandomizedBackoff(self.max_backoff)
        while not self.state.shutdown_soon:
            try:
                # Deadline budget: the single-stream actor flushes on
                # every pull-loop round; here the acquire rounds are the
                # periodic heartbeat (workers park in ``_waiting`` and
                # cannot drive the check while the queue is empty).
                self.state.flush_expired(ts.stub)
                await ts.stub.pace_acquire()
                if self.state.shutdown_soon:
                    return
                wait, slow = await ts.helper.backlog_wait_time()
                if wait >= 1.0:
                    self.logger.debug(
                        f"Tenant {ts.name} idle for {wait:.0f}s (backlog)."
                    )
                    await self._interruptible_sleep(wait)
                    continue
                acquired = await ts.stub.acquire(slow)
                if self.state.shutdown_soon:
                    if (
                        acquired is not None
                        and acquired.kind is AcquiredKind.ACCEPTED
                    ):
                        # Raced shutdown: hand it straight back, through
                        # the ledger (same contract as queue shutdown).
                        await ts.helper.handle_acquired(acquired.body)
                    return
                if acquired is None:
                    continue  # transport error: the api actor backed off
                if acquired.kind is AcquiredKind.ACCEPTED:
                    backoff.reset()
                    try:
                        await self._admit(ts, acquired.body)
                    except asyncio.CancelledError:
                        # Stream torn down mid-admission (client stop):
                        # the api actor already recorded the acquire, so
                        # close the lifecycle — abandoned + aborted, the
                        # server reassigns. (If the batch DID reach
                        # pending first, the queue-stub shutdown abandons
                        # it again — idempotent, still exactly-once.)
                        led = _accounting.get()
                        if led is not None:
                            led.record_abandoned(
                                acquired.body.work.id, "shutdown_cancelled"
                            )
                        ts.stub.abort(acquired.body.work.id)
                        raise
                elif acquired.kind is AcquiredKind.NO_CONTENT:
                    await self._interruptible_sleep(backoff.next())
                elif acquired.kind is AcquiredKind.REJECTED:
                    self.logger.error(
                        f"Server rejected tenant {ts.name}; stopping its "
                        "acquire stream."
                    )
                    ts.rejected = True
                    if all(t.rejected for t in self.tenants.values()):
                        # Every stream rejected: the client cannot work.
                        self.state.shutdown_soon = True
                        self.rx.put_nowait("wake")
                        self.interrupt.set()
                    return
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001 - keep the stream alive
                _QUEUE_ERRORS.inc()
                self.logger.error(f"Tenant {ts.name} stream error: {err!r}")
                await self._interruptible_sleep(backoff.next())

    async def _interruptible_sleep(self, seconds: float) -> None:
        self.interrupt.clear()
        try:
            await asyncio.wait_for(self.interrupt.wait(), timeout=seconds)
        except asyncio.TimeoutError:
            pass

    async def _handle_move_submissions(self) -> None:
        while True:
            if self.state.shutdown_soon:
                return
            if not self.state.move_submissions:
                return
            completed = self.state.move_submissions.popleft()
            ts = self.tenants.get(completed.tenant) or self._default
            acquired = await ts.stub.submit_move_and_acquire(
                completed.work.id, completed.into_best_move()
            )
            if acquired is not None and acquired.kind is AcquiredKind.ACCEPTED:
                await self._admit(ts, acquired.body)

    async def run(self) -> None:
        """The queue task: per-tenant acquire streams plus the shared
        mailbox loop (worker parking, move submissions, wake)."""
        self.logger.debug(
            f"Front end started ({len(self.tenants)} tenants)."
        )
        streams = [
            asyncio.create_task(
                self._acquire_loop(ts), name=f"tenant-{ts.name}"
            )
            for ts in self.tenants.values()
        ]
        try:
            while True:
                msg = await self.rx.get()
                if msg == "move_submitted":
                    try:
                        await self._handle_move_submissions()
                    except Exception as err:  # noqa: BLE001 - keep serving
                        _QUEUE_ERRORS.inc()
                        self.logger.error(f"Move submission error: {err!r}")
                    continue
                if msg == "wake":
                    if self.state.shutdown_soon:
                        break
                    continue
                callback: asyncio.Future = msg
                # The stub already tried the queue before parking this
                # callback; between then and now an admit may have
                # landed, so try once more before parking.
                if self.state.try_pull(callback):
                    continue
                if self.state.shutdown_soon:
                    if not callback.done():
                        callback.cancel()
                    continue
                self._waiting.append(callback)
        finally:
            # Retire the /healthz serving provider before any await: a
            # hard-cancelled run() must not leave an exited front end
            # reporting serving state until gc happens to collect it.
            from fishnet_tpu.telemetry import exporter as _exporter

            _exporter.unregister_health_provider_if(
                "serving", self._health_provider
            )
            for task in streams:
                task.cancel()
            await asyncio.gather(*streams, return_exceptions=True)
            # Serve what remains of the queue to anyone still parked,
            # then release the rest (drain semantics, like QueueActor).
            self._kick()
            while self._waiting:
                leftover = self._waiting.popleft()
                if not leftover.done():
                    leftover.cancel()
            while not self.rx.empty():
                msg = self.rx.get_nowait()
                if isinstance(msg, asyncio.Future) and not msg.done():
                    msg.cancel()
            self.logger.debug("Front end exited")


def _register_frontend_health(frontend: FrontEnd) -> None:
    """Register the serving-state provider with the exporter's
    /healthz. Weakly referenced: a collected front end silently drops
    out of the report; :meth:`FrontEnd.run` retires it deterministically
    on exit (gc of a cyclic front end can lag the process by a long
    time, and an exited front end has no serving state to report)."""
    from fishnet_tpu.telemetry import exporter as _exporter

    ref = weakref.ref(frontend)

    def provide():
        fe = ref()
        if fe is None:
            return None
        return fe.health_snapshot()

    frontend._health_provider = provide
    _exporter.register_health_provider("serving", provide)
