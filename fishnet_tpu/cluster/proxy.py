"""Chaos proxy: an HTTP forwarder between one client process and the
server that injects network faults from a fault plan.

One :class:`ChaosProxy` fronts one client process (the fleet supervisor
gives every process its own proxy + its own plan), so partitions are
per-link, the way real networks fail. The proxy polls three sites once
per forwarded request, in this order:

* ``proxy.partition`` — action ``latency=S`` opens an S-second window
  during which EVERY request is dropped with a connection reset (no
  HTTP response; the client sees ``ClientConnectionError`` and takes
  its error-backoff path); action ``error`` drops just the matched
  request. Window opens increment
  ``fishnet_fleet_partitions_total{proxy}``.
* ``proxy.error5xx`` — answer 502 without reaching the server (an LB
  or gateway failing, as opposed to the link dying).
* ``proxy.latency`` — action ``latency=S`` delays the matched request
  S seconds, then forwards it.

The proxy is pure HTTP plumbing: it never parses or rewrites bodies,
so client/server protocol behavior through a quiet proxy is
byte-for-byte the direct behavior.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional
from urllib.parse import urlsplit

import aiohttp
from aiohttp import web

from fishnet_tpu import telemetry as _telemetry
from fishnet_tpu.resilience.faults import FaultPlan

_PARTITIONS = _telemetry.REGISTRY.counter(
    "fishnet_fleet_partitions_total",
    "Network partition windows opened by the chaos proxy, per proxy.",
    labelnames=("proxy",),
)

#: Request headers the proxy must not blindly copy: Host names the
#: proxy, and aiohttp recomputes framing headers for the new request.
_HOP_HEADERS = ("host", "content-length", "transfer-encoding", "connection")


class ChaosProxy:
    """Forward ``http://127.0.0.1:<port><path>`` to ``upstream``,
    injecting faults per ``plan`` (None = a quiet, faithful proxy).

    ``upstream`` is the full endpoint the client would otherwise use
    (e.g. ``http://127.0.0.1:43210/fishnet``); :attr:`endpoint` is the
    same path on the proxy's own ephemeral port, ready to hand to the
    client's ``--endpoint``.
    """

    def __init__(
        self,
        upstream: str,
        plan: Optional[FaultPlan] = None,
        name: str = "proxy",
    ) -> None:
        parts = urlsplit(upstream)
        if parts.scheme not in ("http",) or not parts.netloc:
            raise ValueError(f"chaos proxy needs an http upstream: {upstream!r}")
        self._base = f"{parts.scheme}://{parts.netloc}"
        self._path = parts.path.rstrip("/")
        self.name = name
        self.plan = plan
        self.port = 0
        self._partition_until = 0.0
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        # Per-proxy tallies for reports (the counter above is fleet-wide).
        self.forwarded = 0
        self.dropped = 0
        self.injected_5xx = 0
        self.delayed = 0
        self.partitions = 0

    @property
    def endpoint(self) -> str:
        """The endpoint to hand to the client (proxy port, same path)."""
        return f"http://127.0.0.1:{self.port}{self._path}"

    async def start(self) -> "ChaosProxy":
        # Dropped requests die mid-response by design; aiohttp's server
        # logger reports each one as an unhandled error. Chaos runs are
        # the only place this proxy exists, so silence that logger
        # rather than drown the run's own output.
        logging.getLogger("aiohttp.server").setLevel(logging.CRITICAL)
        self._session = aiohttp.ClientSession()
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    def stats(self):
        return {
            "forwarded": self.forwarded,
            "dropped": self.dropped,
            "injected_5xx": self.injected_5xx,
            "delayed": self.delayed,
            "partitions": self.partitions,
        }

    def _drop(self, request: web.Request) -> web.Response:
        """Connection reset: close the transport under the in-flight
        request so the client sees the link die, not an HTTP status."""
        self.dropped += 1
        transport = request.transport
        if transport is not None:
            transport.close()
        # Never reaches the wire (transport is closing); returning a
        # response keeps aiohttp's handler machinery on its happy path.
        return web.Response(status=502, text="partitioned\n")

    async def _handle(self, request: web.Request) -> web.Response:
        now = time.monotonic()
        if now < self._partition_until:
            return self._drop(request)
        plan = self.plan
        if plan is not None:
            rule = plan.poll("proxy.partition")
            if rule is not None:
                if rule.action == "latency" and rule.arg > 0:
                    self._partition_until = now + rule.arg
                self.partitions += 1
                _PARTITIONS.inc(proxy=self.name)
                return self._drop(request)
            rule = plan.poll("proxy.error5xx")
            if rule is not None:
                self.injected_5xx += 1
                return web.Response(status=502, text="chaos proxy: injected 502\n")
            rule = plan.poll("proxy.latency")
            if rule is not None and rule.arg > 0:
                self.delayed += 1
                await asyncio.sleep(rule.arg)
        body = await request.read()
        headers = {
            k: v
            for k, v in request.headers.items()
            if k.lower() not in _HOP_HEADERS
        }
        url = self._base + request.rel_url.path_qs
        try:
            async with self._session.request(
                request.method, url, data=body, headers=headers
            ) as resp:
                payload = await resp.read()
                out_headers = {}
                if "Content-Type" in resp.headers:
                    out_headers["Content-Type"] = resp.headers["Content-Type"]
                self.forwarded += 1
                return web.Response(
                    status=resp.status, body=payload, headers=out_headers
                )
        except aiohttp.ClientError:
            # Upstream itself is down/unreachable: surface as a 502 so
            # the client backs off the same way it would behind a real
            # gateway.
            return web.Response(status=502, text="chaos proxy: upstream error\n")
