"""Fleet-wide position-eval tier: one shared segment for every process.

The process-wide caches (``search/eval_cache.py``) stop at the process
boundary: each client the fleet supervisor spawns re-pays the same
popular-opening evals its siblings already computed. This module lifts
that reuse one level up — a single mmap'd fixed-slot table on the local
filesystem that every process attaches to (``FISHNET_POSITION_TIER=1``)
and probes pre-wire, right after its process-local cache misses. The
fallback ladder per position is strictly local -> fleet -> miss
(doc/eval-cache.md "Fleet tier").

Three keyspaces ride the same segment, mirroring the process caches:

* **NNUE region** — 32-byte slots keyed ``zobrist ^ net_fingerprint``
  holding the EXACT int32 static eval. Values are stored bit-exact (not
  quantized): substituting a fleet hit for a recomputed eval must keep
  analyses byte-identical, the same contract the process cache carries.
* **AZ region** — large slots keyed
  ``az_position_key ^ az_net_fingerprint`` holding the exact fp16
  policy row plus the float32 value — the same fp16 eval round-trip the
  ``AzEvalCache`` stores, so fleet hits reconstruct identical fp32
  bits.
* **Bounds region** (v2) — 48-byte slots keyed like the NNUE region
  holding full TT bound records ``(value, eval, depth, bound,
  best-move)`` in the native representation, so one frontend's search
  facts seed every sibling's pool TT (doc/eval-cache.md "Bounds
  tier"). Same-key replacement is deeper-entry-wins, matching the
  process ``BoundsCache``.

Cross-process safety WITHOUT cross-process locks: plain files have no
shared mutexes, so every slot carries a generation-stamped seqlock
(odd = write in progress) plus a checksum word over its payload.
Writers bump the seq odd, write the payload, write the checksum, bump
the seq even; readers snapshot the seq before and after, reject
odd/odd-changed snapshots, and reject any checksum mismatch — a torn
read (or two racing writers interleaving their stores) surfaces as a
plain miss, never as a wrong value. A writer SIGKILLed mid-write
leaves its slot odd; the next writer reclaims it (the bump-to-odd
always succeeds), so a crash costs one slot until its next insert, not
the segment. In-process, writes are additionally lock-striped
(64 ``threading.Lock`` stripes over the slot index space), matching
the process caches' striping discipline.

Ownership: every slot records the writer's pid, so a hit splits into
``scope="local"`` (this process wrote it — a snapshot-restored or
re-probed entry) vs ``scope="fleet"`` (another process paid the eval),
which is exactly the cross-process reuse the fleet bench gates on.

Attach is graceful: a missing/unwritable path, a foreign magic, a
version or geometry mismatch all fall back to tier-off (the process
keeps its local cache; ``fishnet_postier_attach_total{scope="local"}``
counts the fallback). Nothing here is a liveness dependency.
"""

from __future__ import annotations

import mmap
import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

#: Master gate (read at ``get_tier`` time): "1" attaches the shared
#: segment; anything else keeps eval reuse process-local.
TIER_ENV = "FISHNET_POSITION_TIER"
#: Segment file path; default: one per uid in the system tempdir.
TIER_PATH_ENV = "FISHNET_POSITION_TIER_PATH"
#: NNUE-region slot count (32 bytes each).
TIER_CAPACITY_ENV = "FISHNET_POSITION_TIER_CAPACITY"
#: AZ-region slot count (~9.4 KB each — fp16 policy payload).
TIER_AZ_CAPACITY_ENV = "FISHNET_POSITION_TIER_AZ_CAPACITY"
#: Bounds-region slot count (48 bytes each — full TT bound records).
TIER_BOUNDS_CAPACITY_ENV = "FISHNET_POSITION_TIER_BOUNDS_CAPACITY"

_MAGIC = 0x46_4E_50_54_49_45_52_31  # "FNPTIER1"
# v2: bounds region appended after the AZ region; header gains
# ``bounds_slots`` (doc/eval-cache.md "Bounds tier"). A v1 segment
# fails the version check and the process falls back tier-off — the
# same graceful-attach contract as any geometry mismatch.
_VERSION = 2
_HEADER_BYTES = 4096
_U64 = (1 << 64) - 1
_MIX = 0x9E3779B97F4A7C15  # splitmix64 odd constant (index mixing)

DEFAULT_NNUE_SLOTS = 1 << 16
DEFAULT_AZ_SLOTS = 256
DEFAULT_BOUNDS_SLOTS = 1 << 14
#: AZ policy width (models/az.py POLICY_SIZE); carried in the header so
#: an attach against a different architecture fails cleanly instead of
#: reading misaligned rows.
AZ_POLICY_SIZE = 4672

_PROBE_WINDOW = 8
_N_STRIPES = 64

_HEADER_DTYPE = np.dtype([
    ("magic", "<u8"),
    ("version", "<u4"),
    ("nnue_slots", "<u4"),
    ("az_slots", "<u4"),
    ("policy_size", "<u4"),
    ("bounds_slots", "<u4"),
    ("generation", "<u8"),
])

_NNUE_SLOT_DTYPE = np.dtype([
    ("key", "<u8"),
    ("value", "<i4"),
    ("owner", "<u4"),
    ("seq", "<u4"),
    ("gen", "<u4"),
    ("check", "<u8"),
])
assert _NNUE_SLOT_DTYPE.itemsize == 32

#: Bounds region: one full TT bound record per slot — value in the
#: native stored (value_to_tt) form, static eval, depth, bound type
#: (1=upper/2=lower/3=exact) and the 21-bit packed best move — the same
#: columns ``fc_pool_tt_fill_bound`` consumes, so a fleet hit seeds a
#: sibling's pool TT without any host-side decode.
_BOUNDS_SLOT_DTYPE = np.dtype([
    ("key", "<u8"),
    ("value", "<i4"),
    ("eval", "<i4"),
    ("depth", "<u4"),
    ("bound", "<u4"),
    ("move", "<u4"),
    ("owner", "<u4"),
    ("seq", "<u4"),
    ("gen", "<u4"),
    ("check", "<u8"),
])
assert _BOUNDS_SLOT_DTYPE.itemsize == 48


def _az_slot_dtype(policy_size: int) -> np.dtype:
    return np.dtype([
        ("key", "<u8"),
        ("owner", "<u4"),
        ("seq", "<u4"),
        ("value", "<f4"),
        ("gen", "<u4"),
        ("check", "<u8"),
        ("policy", "<u2", (policy_size,)),
    ])


def tier_enabled() -> bool:
    """The master hatch, read per call so tests can monkeypatch env."""
    return os.environ.get(TIER_ENV, "") == "1"


def tier_path() -> str:
    uid = getattr(os, "getuid", lambda: 0)()
    return os.environ.get(TIER_PATH_ENV) or os.path.join(
        tempfile.gettempdir(), f"fishnet-postier-{uid}.seg"
    )


def _env_slots(name: str, default: int) -> int:
    try:
        return max(_PROBE_WINDOW, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _nnue_check(key: int, value: int, owner: int) -> int:
    """Payload checksum: any interleaving of two writers' stores (or a
    half-written slot) fails this with overwhelming probability."""
    return (key ^ ((value & 0xFFFFFFFF) | (owner << 32)) ^ _MIX) & _U64


def _az_check(key: int, value_bits: int, owner: int,
              policy_words: np.ndarray) -> int:
    acc = int(np.bitwise_xor.reduce(policy_words)) if len(policy_words) else 0
    return (key ^ value_bits ^ (owner * _MIX) ^ acc) & _U64


def _bounds_check(key: int, value: int, eval_: int, depth: int,
                  bound: int, move: int, owner: int) -> int:
    lo = ((value & 0xFFFFFFFF) | ((eval_ & 0xFFFFFFFF) << 32)) & _U64
    hi = (depth | (bound << 8) | (move << 16)) & _U64
    return (key ^ lo ^ ((hi * _MIX) & _U64) ^ ((owner * _MIX) & _U64)) & _U64


class PositionTier:
    """One attached shared-memory position segment (both keyspaces).

    All probe/insert methods are thread-safe in-process (striped locks)
    and torn-read-safe cross-process (seqlock + checksum). Keys are
    SALTED — callers XOR their net fingerprint in before calling, the
    same keys they use against the process caches."""

    def __init__(self, path: str, mm: mmap.mmap, nnue_slots: int,
                 az_slots: int, policy_size: int,
                 bounds_slots: int = DEFAULT_BOUNDS_SLOTS) -> None:
        self.path = path
        self._mm = mm
        self._owner = os.getpid() & 0xFFFFFFFF
        self._header = np.frombuffer(mm, dtype=_HEADER_DTYPE, count=1)
        self._nnue = np.frombuffer(
            mm, dtype=_NNUE_SLOT_DTYPE, count=nnue_slots,
            offset=_HEADER_BYTES,
        )
        self.az_policy_size = policy_size
        az_dtype = _az_slot_dtype(policy_size)
        self._az = np.frombuffer(
            mm, dtype=az_dtype, count=az_slots,
            offset=_HEADER_BYTES + nnue_slots * _NNUE_SLOT_DTYPE.itemsize,
        )
        self._bounds = np.frombuffer(
            mm, dtype=_BOUNDS_SLOT_DTYPE, count=bounds_slots,
            offset=(
                _HEADER_BYTES
                + nnue_slots * _NNUE_SLOT_DTYPE.itemsize
                + az_slots * az_dtype.itemsize
            ),
        )
        self._nnue_slots = nnue_slots
        self._az_slots = az_slots
        self._bounds_slots = bounds_slots
        self._locks = [threading.Lock() for _ in range(_N_STRIPES)]

    # -- slot addressing ---------------------------------------------------

    @staticmethod
    def _mix(key: int) -> int:
        # splitmix64 finalizer-ish: decorrelate the probe index from the
        # low Zobrist bits the pool TT and cache stripes already use.
        x = (key * _MIX) & _U64
        x ^= x >> 29
        return x

    def _window(self, key: int, n_slots: int) -> range:
        base = self._mix(key) % n_slots
        return range(base, base + min(_PROBE_WINDOW, n_slots))

    # -- NNUE keyspace -----------------------------------------------------

    def _read_nnue(self, idx: int, key: int) -> Optional[Tuple[int, int]]:
        """Validated ``(value, owner)`` for ``key`` at slot ``idx``, or
        None (empty / other key / torn)."""
        slot = self._nnue[idx]
        s1 = int(slot["seq"])
        if s1 & 1:
            return None  # write in progress (or a dead writer's slot)
        k = int(slot["key"])
        if k != key:
            return None
        value = int(slot["value"])
        owner = int(slot["owner"])
        check = int(slot["check"])
        if int(slot["seq"]) != s1:
            return None  # torn: a writer landed mid-read
        if check != _nnue_check(k, value, owner):
            return None  # torn or interleaved write
        return value, owner

    def probe_nnue_block(
        self, keys: np.ndarray, values: np.ndarray, mask: np.ndarray
    ) -> int:
        """Fill the MISS rows of a process-cache probe from the fleet
        segment: for each ``i`` with ``mask[i]`` false, a valid segment
        entry writes ``values[i]`` and sets ``mask[i]``. Returns the
        number of rows filled (counters split self- vs cross-process
        hits by slot owner)."""
        hits_local = hits_fleet = misses = 0
        n = len(keys)
        for i in range(n):
            if mask[i]:
                continue
            key = int(keys[i])
            found = None
            for idx in self._window(key, self._nnue_slots):
                found = self._read_nnue(idx % self._nnue_slots, key)
                if found is not None:
                    break
            if found is None:
                misses += 1
                continue
            value, owner = found
            values[i] = value
            mask[i] = True
            if owner == self._owner:
                hits_local += 1
            else:
                hits_fleet += 1
        _count("nnue", hits_local, hits_fleet, misses)
        return hits_local + hits_fleet

    def insert_nnue_block(self, keys: np.ndarray, values: np.ndarray) -> None:
        """Publish freshly paid evals to the segment (provide-time
        path). Last-writer-wins on slot collisions — it's a cache."""
        gen = int(self._header["generation"][0]) & 0xFFFFFFFF
        n = min(len(keys), len(values))
        evictions = 0
        for i in range(n):
            key = int(keys[i])
            evictions += self._insert_nnue_one(key, int(values[i]), gen)
        if evictions:
            _count_evict("nnue", evictions)

    def _insert_nnue_one(self, key: int, value: int, gen: int) -> int:
        window = self._window(key, self._nnue_slots)
        target = None
        victim = None
        victim_gen = None
        for idx in window:
            idx %= self._nnue_slots
            slot = self._nnue[idx]
            k = int(slot["key"])
            if k == key:
                target = idx
                break
            if k == 0 and int(slot["seq"]) == 0:
                if target is None:
                    target = idx
                continue
            g = int(slot["gen"])
            if victim_gen is None or g < victim_gen:
                victim, victim_gen = idx, g
        evicted = 0
        if target is None:
            target = victim if victim is not None else (
                self._mix(key) % self._nnue_slots
            )
            evicted = 1
        with self._locks[target & (_N_STRIPES - 1)]:
            slot = self._nnue[target]
            s = int(slot["seq"])
            slot["seq"] = ((s + 1) | 1) & 0xFFFFFFFF  # odd: mid-write
            slot["key"] = key
            slot["value"] = value
            slot["owner"] = self._owner
            slot["gen"] = gen
            slot["check"] = _nnue_check(key, value, self._owner)
            slot["seq"] = (((s + 1) | 1) + 1) & 0xFFFFFFFF  # even: published
        return evicted

    # -- AZ keyspace -------------------------------------------------------

    def probe_az(self, key: int) -> Optional[Tuple[np.ndarray, float]]:
        """Validated ``(policy_fp16 [policy_size], value)`` for a salted
        AZ key, or None. The policy row is a COPY (the segment slot may
        be overwritten the instant this returns)."""
        key = int(key) & _U64
        found = None
        owner = 0
        for idx in self._window(key, self._az_slots):
            idx %= self._az_slots
            slot = self._az[idx]
            s1 = int(slot["seq"])
            if s1 & 1:
                continue
            if int(slot["key"]) != key:
                continue
            policy = np.array(slot["policy"], copy=True)
            value = np.float32(slot["value"])
            owner = int(slot["owner"])
            check = int(slot["check"])
            if int(slot["seq"]) != s1:
                continue
            words = policy.view(np.uint8)
            pad = (-len(words)) % 8
            if pad:
                words = np.concatenate([words, np.zeros(pad, np.uint8)])
            if check != _az_check(
                key, int(value.view(np.uint32)), owner,
                words.view(np.uint64),
            ):
                continue
            found = (policy.view(np.float16), float(value))
            break
        if found is None:
            _count("az", 0, 0, 1)
        elif owner == self._owner:
            _count("az", 1, 0, 0)
        else:
            _count("az", 0, 1, 0)
        return found

    def insert_az(self, key: int, policy_fp16: np.ndarray,
                  value: float) -> None:
        key = int(key) & _U64
        policy = np.ascontiguousarray(policy_fp16, dtype=np.float16)
        if policy.shape != (self.az_policy_size,):
            return  # architecture drift; never corrupt the region
        gen = int(self._header["generation"][0]) & 0xFFFFFFFF
        window = self._window(key, self._az_slots)
        target = None
        victim = None
        victim_gen = None
        for idx in window:
            idx %= self._az_slots
            slot = self._az[idx]
            k = int(slot["key"])
            if k == key:
                target = idx
                break
            if k == 0 and int(slot["seq"]) == 0:
                if target is None:
                    target = idx
                continue
            g = int(slot["gen"])
            if victim_gen is None or g < victim_gen:
                victim, victim_gen = idx, g
        evicted = 0
        if target is None:
            target = victim if victim is not None else (
                self._mix(key) % self._az_slots
            )
            evicted = 1
        vbits = int(np.float32(value).view(np.uint32))
        words = policy.view(np.uint8)
        pad = (-len(words)) % 8
        if pad:
            words = np.concatenate([words, np.zeros(pad, np.uint8)])
        check = _az_check(key, vbits, self._owner, words.view(np.uint64))
        with self._locks[target & (_N_STRIPES - 1)]:
            slot = self._az[target]
            s = int(slot["seq"])
            slot["seq"] = ((s + 1) | 1) & 0xFFFFFFFF
            slot["key"] = key
            slot["value"] = np.float32(value)
            slot["owner"] = self._owner
            slot["gen"] = gen
            slot["policy"] = policy.view(np.uint16)
            slot["check"] = check
            slot["seq"] = (((s + 1) | 1) + 1) & 0xFFFFFFFF
        if evicted:
            _count_evict("az", 1)

    # -- bounds keyspace ---------------------------------------------------

    def _read_bound(
        self, idx: int, key: int
    ) -> Optional[Tuple[int, int, int, int, int, int]]:
        """Validated ``(value, eval, depth, bound, move, owner)`` for
        ``key`` at slot ``idx``, or None (empty / other key / torn)."""
        slot = self._bounds[idx]
        s1 = int(slot["seq"])
        if s1 & 1:
            return None  # write in progress (or a dead writer's slot)
        if int(slot["key"]) != key:
            return None
        value = int(np.int32(slot["value"]))
        eval_ = int(np.int32(slot["eval"]))
        depth = int(slot["depth"])
        bound = int(slot["bound"])
        move = int(slot["move"])
        owner = int(slot["owner"])
        check = int(slot["check"])
        if int(slot["seq"]) != s1:
            return None  # torn: a writer landed mid-read
        if bound == 0 or check != _bounds_check(
            key, value, eval_, depth, bound, move, owner
        ):
            return None  # torn or interleaved write
        return value, eval_, depth, bound, move, owner

    def probe_bounds_block(
        self,
        keys: np.ndarray,
        values: np.ndarray,
        evals: np.ndarray,
        depths: np.ndarray,
        bounds: np.ndarray,
        moves: np.ndarray,
    ) -> int:
        """Fill the MISS rows (``bounds[i] == 0``) of a process
        bounds-cache probe from the fleet segment; the column layout
        matches ``BoundsCache.probe_bounds_block``. Returns rows
        filled."""
        hits_local = hits_fleet = misses = 0
        for i in range(len(keys)):
            if bounds[i]:
                continue
            key = int(keys[i])
            found = None
            for idx in self._window(key, self._bounds_slots):
                found = self._read_bound(idx % self._bounds_slots, key)
                if found is not None:
                    break
            if found is None:
                misses += 1
                continue
            values[i], evals[i], depths[i], bounds[i], moves[i], owner = found
            if owner == self._owner:
                hits_local += 1
            else:
                hits_fleet += 1
        _count("bounds", hits_local, hits_fleet, misses)
        return hits_local + hits_fleet

    def insert_bound(self, key: int, value: int, eval_: int, depth: int,
                     bound: int, move: int) -> None:
        """Publish one bound record. Same-key replacement is
        deeper-entry-wins (the :class:`BoundsCache` policy): a live
        same-key slot holding a strictly deeper record is left alone —
        a shallow re-search must never clobber the deep record a
        sibling paid for. Cross-key collisions evict lowest-gen, like
        the other regions."""
        if bound <= 0 or bound > 3:
            return
        key = int(key) & _U64
        gen = int(self._header["generation"][0]) & 0xFFFFFFFF
        window = self._window(key, self._bounds_slots)
        target = None
        victim = None
        victim_gen = None
        for idx in window:
            idx %= self._bounds_slots
            slot = self._bounds[idx]
            k = int(slot["key"])
            if k == key:
                if int(slot["depth"]) > depth and not (int(slot["seq"]) & 1):
                    return  # resident record is deeper; keep it
                target = idx
                break
            if k == 0 and int(slot["seq"]) == 0:
                if target is None:
                    target = idx
                continue
            g = int(slot["gen"])
            if victim_gen is None or g < victim_gen:
                victim, victim_gen = idx, g
        evicted = 0
        if target is None:
            target = victim if victim is not None else (
                self._mix(key) % self._bounds_slots
            )
            evicted = 1
        check = _bounds_check(
            key, value, eval_, depth, bound, move, self._owner
        )
        with self._locks[target & (_N_STRIPES - 1)]:
            slot = self._bounds[target]
            s = int(slot["seq"])
            slot["seq"] = ((s + 1) | 1) & 0xFFFFFFFF  # odd: mid-write
            slot["key"] = key
            slot["value"] = np.int32(value)
            slot["eval"] = np.int32(eval_)
            slot["depth"] = depth & 0xFFFFFFFF
            slot["bound"] = bound
            slot["move"] = move & 0xFFFFFFFF
            slot["owner"] = self._owner
            slot["gen"] = gen
            slot["check"] = check
            slot["seq"] = (((s + 1) | 1) + 1) & 0xFFFFFFFF  # even: published
        if evicted:
            _count_evict("bounds", 1)

    def insert_bounds_block(
        self,
        keys: np.ndarray,
        values: np.ndarray,
        evals: np.ndarray,
        depths: np.ndarray,
        bounds: np.ndarray,
        moves: np.ndarray,
    ) -> None:
        """Publish a harvested batch of bound records (rows with
        ``bounds[i] == 0`` are skipped — the harvest layout marks
        misses that way)."""
        for i in range(len(keys)):
            if not bounds[i]:
                continue
            self.insert_bound(
                int(keys[i]), int(np.int32(values[i])),
                int(np.int32(evals[i])), int(depths[i]), int(bounds[i]),
                int(moves[i]),
            )

    # -- shared clock ------------------------------------------------------

    def advance_generation(self) -> int:
        """Tick the fleet-wide eviction clock (batch completion,
        sched/queue.py). Racy read-modify-write across processes is
        fine — it's a coarse ordering signal, not a counter."""
        g = (int(self._header["generation"][0]) + 1) & _U64
        self._header["generation"][0] = g
        return g

    def generation(self) -> int:
        return int(self._header["generation"][0])

    def close(self) -> None:
        # Release the numpy views before the mmap (else BufferError).
        self._header = self._nnue = self._az = self._bounds = None
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass


# -- module counters + telemetry collector ----------------------------------

_count_lock = threading.Lock()
_counts: Dict[str, int] = {}


def _count(family: str, local: int, fleet: int, misses: int) -> None:
    with _count_lock:
        if local:
            k = f"hits.local.{family}"
            _counts[k] = _counts.get(k, 0) + local
        if fleet:
            k = f"hits.fleet.{family}"
            _counts[k] = _counts.get(k, 0) + fleet
        if misses:
            k = f"misses.fleet.{family}"
            _counts[k] = _counts.get(k, 0) + misses


def _count_evict(family: str, n: int) -> None:
    with _count_lock:
        k = f"evictions.fleet.{family}"
        _counts[k] = _counts.get(k, 0) + n


def _count_attach(scope: str) -> None:
    with _count_lock:
        k = f"attach.{scope}"
        _counts[k] = _counts.get(k, 0) + 1


def stats() -> Dict[str, int]:
    """Process-lifetime tier counters (keys ``hits.local.nnue``,
    ``hits.fleet.az``, ``misses.fleet.nnue``, ``attach.fleet``, ...)."""
    with _count_lock:
        return dict(_counts)


def _collect_postier() -> Optional[List]:
    from fishnet_tpu.telemetry.registry import counter_family

    with _count_lock:
        snap = dict(_counts)
    fams = []
    for fam in ("nnue", "az", "bounds"):
        for scope in ("local", "fleet"):
            fams.append(counter_family(
                "fishnet_postier_hits_total",
                "Fleet position-tier hits by scope (local=slot written "
                "by this process, fleet=cross-process) and family.",
                snap.get(f"hits.{scope}.{fam}", 0),
                labels={"scope": scope, "family": fam},
            ))
        fams.append(counter_family(
            "fishnet_postier_misses_total",
            "Fleet position-tier probes that found no valid slot "
            "(torn/checksum-rejected reads count as misses).",
            snap.get(f"misses.fleet.{fam}", 0),
            labels={"scope": "fleet", "family": fam},
        ))
        fams.append(counter_family(
            "fishnet_postier_evictions_total",
            "Fleet position-tier slots overwritten while holding a "
            "different live key (fixed-slot replacement).",
            snap.get(f"evictions.fleet.{fam}", 0),
            labels={"scope": "fleet", "family": fam},
        ))
    for scope in ("local", "fleet"):
        fams.append(counter_family(
            "fishnet_postier_attach_total",
            "Segment attach outcomes: fleet=attached the shared "
            "segment, local=fell back to process-local reuse.",
            snap.get(f"attach.{scope}", 0),
            labels={"scope": scope},
        ))
    return fams


# -- process-wide singleton --------------------------------------------------

_tier_lock = threading.Lock()
_tier: Optional[PositionTier] = None
_tier_resolved = False
_collector_token: Optional[int] = None


def _attach(path: str) -> PositionTier:
    nnue_slots = _env_slots(TIER_CAPACITY_ENV, DEFAULT_NNUE_SLOTS)
    az_slots = _env_slots(TIER_AZ_CAPACITY_ENV, DEFAULT_AZ_SLOTS)
    bounds_slots = _env_slots(TIER_BOUNDS_CAPACITY_ENV, DEFAULT_BOUNDS_SLOTS)
    az_itemsize = _az_slot_dtype(AZ_POLICY_SIZE).itemsize
    size = (
        _HEADER_BYTES
        + nnue_slots * _NNUE_SLOT_DTYPE.itemsize
        + az_slots * az_itemsize
        + bounds_slots * _BOUNDS_SLOT_DTYPE.itemsize
    )
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
    try:
        existing = os.fstat(fd).st_size
        if existing == 0:
            # Fresh segment: size it, then publish the header with the
            # magic LAST — a concurrent creator writes identical bytes
            # (geometry comes from the same envs), so the race is
            # benign; a reader that loses it sees magic==0 and retries
            # as a failed attach (fallback, not corruption).
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
            header = np.frombuffer(mm, dtype=_HEADER_DTYPE, count=1)
            header["version"] = _VERSION
            header["nnue_slots"] = nnue_slots
            header["az_slots"] = az_slots
            header["policy_size"] = AZ_POLICY_SIZE
            header["bounds_slots"] = bounds_slots
            header["generation"] = 1
            header["magic"] = _MAGIC
        else:
            mm = mmap.mmap(fd, existing)
            header = np.frombuffer(mm, dtype=_HEADER_DTYPE, count=1)
            if int(header["magic"][0]) != _MAGIC:
                raise ValueError(f"{path}: not a position-tier segment")
            if int(header["version"][0]) != _VERSION:
                raise ValueError(f"{path}: tier version mismatch")
            nnue_slots = int(header["nnue_slots"][0])
            az_slots = int(header["az_slots"][0])
            policy = int(header["policy_size"][0])
            bounds_slots = int(header["bounds_slots"][0])
            expect = (
                _HEADER_BYTES
                + nnue_slots * _NNUE_SLOT_DTYPE.itemsize
                + az_slots * _az_slot_dtype(policy).itemsize
                + bounds_slots * _BOUNDS_SLOT_DTYPE.itemsize
            )
            if (
                policy != AZ_POLICY_SIZE
                or bounds_slots < _PROBE_WINDOW
                or existing < expect
            ):
                raise ValueError(f"{path}: tier geometry mismatch")
        del header  # release the view; PositionTier re-views
    finally:
        os.close(fd)
    return PositionTier(
        path, mm, nnue_slots, az_slots, AZ_POLICY_SIZE, bounds_slots
    )


def get_tier() -> Optional[PositionTier]:
    """The process-wide tier handle, or None (env off, or the attach
    fell back). Resolved once per process; ``reset_tier()`` re-arms."""
    global _tier, _tier_resolved, _collector_token
    with _tier_lock:
        if _tier_resolved:
            return _tier
        _tier_resolved = True
        if not tier_enabled():
            return None
        try:
            _tier = _attach(tier_path())
            _count_attach("fleet")
        except (OSError, ValueError, BufferError):
            _tier = None
            _count_attach("local")
        from fishnet_tpu.telemetry.registry import REGISTRY

        if _collector_token is None:
            _collector_token = REGISTRY.register_collector(
                _collect_postier, name="position-tier"
            )
        return _tier


def reset_tier() -> None:
    """Detach and forget the process tier (tests / bench phase resets).
    Counters survive — they are process-lifetime totals."""
    global _tier, _tier_resolved
    with _tier_lock:
        if _tier is not None:
            _tier.close()
        _tier = None
        _tier_resolved = False
