"""Fleet-scale crash tolerance harness.

Everything below this package treats a *process* as the unit of
failure: the chaos proxy (:mod:`fishnet_tpu.cluster.proxy`) sits
between each client process and the server and injects partitions,
latency and 5xx storms; the fleet supervisor
(:mod:`fishnet_tpu.cluster.supervisor`) spawns real
``python -m fishnet_tpu`` client processes, kills or drains them on a
deterministic fault plan, and restarts them under a bounded budget.
``python -m fishnet_tpu.cluster.chaos`` wires both against the fake
server and audits the fleet ledger: every work unit handed to any
process is completed exactly once, across SIGKILL, SIGTERM drain and
network partitions.

All chaos is driven by the fault-plan grammar
(:mod:`fishnet_tpu.resilience.faults`) through the fleet sites
``proxy.partition``, ``proxy.latency``, ``proxy.error5xx``,
``proc.kill`` and ``proc.sigterm`` — seedable, deterministic,
documented in doc/resilience.md.

The package also hosts the fleet-wide position tier
(:mod:`fishnet_tpu.cluster.position_tier`) — imported by the search
service in every client process — so the chaos-harness names below are
resolved lazily: attaching the shared eval segment must not drag the
proxy's aiohttp dependency into the serving path.
"""

_LAZY = {
    "ChaosProxy": "fishnet_tpu.cluster.proxy",
    "FleetSupervisor": "fishnet_tpu.cluster.supervisor",
    "ProcSpec": "fishnet_tpu.cluster.supervisor",
}

__all__ = ["ChaosProxy", "FleetSupervisor", "ProcSpec"]


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
