"""Fleet chaos soak: real client processes, a chaos proxy per link,
SIGKILL/SIGTERM/partitions from a seeded plan, exactly-once audited.

Run it from a repo checkout::

    python -m fishnet_tpu.cluster.chaos                 # canned scenario
    python -m fishnet_tpu.cluster.chaos --procs 4 --seconds 20

The canned scenario (3 processes, ~12 s):

* **PROC0** is SIGKILLed mid-run (``proc.kill``) — no goodbye, no
  flush; its in-flight work must come back through the server's
  reassignment sweep and complete on another (or the restarted)
  process.
* **PROC1** runs behind a flapping link: a partition window
  (``proxy.partition``) plus background 502s and latency.
* **PROC2** is SIGTERMed (``proc.sigterm``) — it must drain: stop
  acquiring, flush in-flight batches within the deadline, exit 0.

The supervisor restarts every exited process under its budget; the run
ends with a fleet-wide drain, the fleet-ledger audit (0 lost, 0
duplicated, kills reassigned across processes) and a ``/metrics``
scrape asserting the fleet metric families. Everything chaotic comes
from the fault-plan grammar, so a failing run replays exactly.

Split-topology scenarios (``ProcSpec(role="frontend"|"evaluator")``,
doc/disaggregation.md) script with the same one-string-per-proc
grammar: give the evaluator spec ``rpc.detach:nth=N:error`` and its
host drops one frontend link mid-flight on its Nth service sweep — the
frontend reattaches and resubmits, exactly-once audited like every
other fault here (exercised by ``bench.py --split``).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
import urllib.request
from typing import Dict, List, Optional

from fishnet_tpu.cluster.supervisor import FleetSupervisor, ProcSpec
from fishnet_tpu.resilience.soak import _load_fake_server

#: Fleet metric families the final scrape must include
#: (doc/observability.md contract).
REQUIRED_FAMILIES = (
    "fishnet_proc_restarts_total",
    "fishnet_fleet_partitions_total",
    "fishnet_faults_injected_total",
)

#: Per-process canned plans (supervisor tick = 0.2 s, so nth=10 fires
#: ~2 s in — after the child has started and begun acquiring).
CANNED_SPECS = (
    "seed=11;proc.kill:nth=10:crash;proxy.latency:every=13:latency=0.05",
    "seed=12;proxy.partition:nth=8:latency=1.5;proxy.error5xx:every=19:error",
    "seed=13;proc.sigterm:nth=16:error",
)


def fleet_specs(procs: int) -> List[ProcSpec]:
    """The canned scenario, extended with quiet processes past 3."""
    specs = []
    for i in range(procs):
        fault_spec = CANNED_SPECS[i] if i < len(CANNED_SPECS) else ""
        specs.append(ProcSpec(name=f"PROC{i}", fault_spec=fault_spec))
    return specs


def _scrape(port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as res:
        return res.read().decode()


def recovery_seconds(supervisor: FleetSupervisor, lichess) -> Dict[str, float]:
    """Seconds from each kill/sigterm event to that process's first
    post-event acquire — the fleet's recovery time, measured at the
    server (the only place it matters)."""
    out: Dict[str, float] = {}
    for t_rel, name, kind in supervisor.events:
        if kind not in ("kill", "sigterm"):
            continue
        key = supervisor.procs[name].spec.key or name
        t_abs = supervisor._t0 + t_rel
        acquires = lichess.fleet.acquires_by_proc.get(key, ())
        after = [t for t in acquires if t > t_abs]
        if after:
            out[f"{name}:{kind}"] = round(after[0] - t_abs, 3)
    return out


async def run_chaos(
    procs: int = 3,
    seconds: float = 12.0,
    metrics_port: int = 0,
    drain_deadline: float = 5.0,
    verbose: int = 0,
    fleet_port: Optional[int] = None,
) -> Dict:
    """Run the fleet scenario; returns the report dict (key ``ok``).
    Raises AssertionError on a contract violation.

    ``fleet_port`` (0 = ephemeral) additionally runs a
    :class:`~fishnet_tpu.telemetry.fleet.FleetAggregator` over the
    supervisor's port-file directory for the duration — the federated
    /metrics and /fleet routes stay scrapeable through every kill —
    and folds its final state document into the report under
    ``fleet_observability``."""
    from fishnet_tpu import telemetry
    from fishnet_tpu.utils.logger import Logger

    fake_server_mod = _load_fake_server()
    logger = Logger(verbose=verbose)
    report: Dict = {"procs": procs, "ok": False}
    exporter = telemetry.start_exporter(metrics_port)
    supervisor: Optional[FleetSupervisor] = None
    aggregator = None
    try:
        lichess = fake_server_mod.FakeLichess(require_key=False)
        lichess.auto_refill = procs * 2
        lichess.refill_move_every = 4
        # Stale handouts (a SIGKILLed process's work) come back after
        # 2 s — well inside the run, so kills are recovered, not just
        # excused as "still open".
        lichess.reassign_after = 2.0
        async with fake_server_mod.FakeServer(lichess) as server:
            supervisor = FleetSupervisor(
                server.endpoint,
                fleet_specs(procs),
                logger=logger,
                tick_seconds=0.2,
                drain_deadline=drain_deadline,
            )
            await supervisor.start()
            if fleet_port is not None:
                from fishnet_tpu.telemetry.fleet import (
                    FleetAggregator,
                    port_dir_targets,
                )

                aggregator = FleetAggregator(
                    targets_fn=port_dir_targets(str(supervisor.workdir)),
                    poll_interval=0.3,
                    journal_dir=str(supervisor.workdir),
                ).start()
                fleet_exporter = aggregator.serve(fleet_port)
                logger.info(
                    f"fleet aggregator on {fleet_exporter.url}/fleet"
                )
            t0 = time.monotonic()
            while time.monotonic() - t0 < seconds:
                await asyncio.sleep(0.25)
            if aggregator is not None:
                # Final sweep + state doc BEFORE drain, while the
                # children still answer.
                aggregator.poll_once()
                doc = aggregator.fleet_doc()
                report["fleet_observability"] = {
                    "procs": doc["procs"],
                    "slo": doc["slo"],
                    "stitch": doc["stitch"],
                    "critical_path": doc["critical_path"],
                }
            exit_codes = await supervisor.drain()
            supervisor_done = supervisor
            supervisor = None  # drained; skip the error-path kill_all
            fleet = lichess.fleet_report()
            report.update(
                seconds=round(time.monotonic() - t0, 2),
                events=[list(e) for e in supervisor_done.events],
                exit_codes=exit_codes,
                restarts=supervisor_done.restarts_total(),
                proxies={
                    name: h.proxy.stats()
                    for name, h in supervisor_done.procs.items()
                },
                recovery=recovery_seconds(supervisor_done, lichess),
                fleet=fleet,
                analyses_completed=len(lichess.analyses),
                moves_completed=len(lichess.moves),
            )
        kinds = [kind for _, _, kind in report["events"]]
        if not fleet["clean"]:
            raise AssertionError(f"fleet ledger dirty: {fleet}")
        if fleet["completed"] < 1:
            raise AssertionError(f"fleet completed nothing: {report}")
        if "kill" not in kinds:
            raise AssertionError(f"no SIGKILL fired: {kinds}")
        if "restart" not in kinds:
            raise AssertionError(f"no restart observed: {kinds}")
        if report["restarts"] < 1:
            raise AssertionError("restart counter never moved")
        bad_exits = {n: rc for n, rc in exit_codes.items() if rc != 0}
        if bad_exits:
            raise AssertionError(
                f"fleet drain exited nonzero: {bad_exits} "
                f"(logs under {supervisor_done.workdir})"
            )
        text = _scrape(exporter.port)
        missing = [f for f in REQUIRED_FAMILIES if f"# TYPE {f} " not in text]
        report["metric_families"] = sorted(REQUIRED_FAMILIES)
        if missing:
            raise AssertionError(f"/metrics missing families: {missing}")
        report["ok"] = True
        return report
    finally:
        if aggregator is not None:
            aggregator.close()
        if supervisor is not None:
            await supervisor.kill_all()
        exporter.close()
        telemetry.disable()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fishnet_tpu.cluster.chaos",
        description="Fleet chaos soak: client processes under kills, "
        "drains and partitions, exactly-once audited.",
    )
    parser.add_argument("--procs", type=int, default=3)
    parser.add_argument("--seconds", type=float, default=12.0)
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="telemetry port for the run (0 = ephemeral)",
    )
    parser.add_argument(
        "--drain-deadline", type=float, default=5.0,
        help="drain deadline handed to every client process (seconds)",
    )
    parser.add_argument(
        "--fleet-port", type=int, default=None,
        help="also run the fleet aggregator over the supervised procs "
             "and serve /fleet on this port (0 = ephemeral)",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    args = parser.parse_args(argv)
    try:
        report = asyncio.run(
            run_chaos(
                procs=args.procs,
                seconds=args.seconds,
                metrics_port=args.metrics_port,
                drain_deadline=args.drain_deadline,
                verbose=args.verbose,
                fleet_port=args.fleet_port,
            )
        )
    except AssertionError as err:
        print(f"CHAOS FAILED: {err}", file=sys.stderr)
        return 1
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
