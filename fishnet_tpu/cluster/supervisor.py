"""Fleet supervisor: real client processes under a crash/restart
contract.

:class:`FleetSupervisor` spawns one ``python -m fishnet_tpu run``
process per :class:`ProcSpec`, each behind its own
:class:`~fishnet_tpu.cluster.proxy.ChaosProxy`, and monitors the fleet
on a fixed tick. Each process carries its OWN fault plan (parsed from
``ProcSpec.fault_spec``) shared between its proxy (which polls the
``proxy.*`` sites per forwarded request) and this supervisor (which
polls ``proc.kill`` / ``proc.sigterm`` once per monitor tick, so
``nth=N`` means that process's Nth tick). One plan per process keeps a
whole chaos scenario — "partition PROC1 at 2s, SIGKILL PROC0 at 3s" —
a pair of plain grammar strings, seedable and replayable.

A process that exits (killed, drained, or crashed on its own) is
restarted under a bounded per-process budget after a deterministic
jittered backoff (RNG seeded from the process name), incrementing
``fishnet_proc_restarts_total{proc}``. :meth:`drain` is the fleet-wide
shutdown: SIGTERM everyone, wait out the drain deadline, SIGKILL
stragglers, stop the proxies.

Observability wiring (``metrics=True``, the default): every child runs
its metrics exporter on an ephemeral port and writes the bound port to
``<workdir>/<name>.port`` (``--metrics-port-file``). That directory IS
the fleet's service discovery: the
:class:`~fishnet_tpu.telemetry.fleet.FleetAggregator` re-reads it every
poll (:func:`~fishnet_tpu.telemetry.fleet.port_dir_targets`), so a
restarted child that rebinds a fresh port is picked up automatically
and a killed child goes stale instead of vanishing.
"""

from __future__ import annotations

import asyncio
import os
import random
import signal
import sys
import tempfile
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from fishnet_tpu import telemetry as _telemetry
from fishnet_tpu.cluster.proxy import ChaosProxy
from fishnet_tpu.resilience.faults import PLAN_ENV, FaultPlan

_RESTARTS = _telemetry.REGISTRY.counter(
    "fishnet_proc_restarts_total",
    "Client processes restarted by the fleet supervisor, per process.",
    labelnames=("proc",),
)

_REPO_ROOT = Path(__file__).resolve().parents[2]


@dataclass
class ProcSpec:
    """One client process in the fleet.

    ``key`` doubles as the process's identity on the wire: every
    protocol POST body carries ``fishnet.apikey``, so the fake server's
    fleet ledger attributes handouts and completions per-process
    without any header rewriting in the proxy.

    ``role`` selects the split-plane shape (doc/disaggregation.md):
    ``monolith`` (default) is today's self-contained client;
    ``role=frontend`` runs the same client with ``FISHNET_RPC=1`` so
    its eval traffic rides the ring transport; ``role=evaluator`` runs
    ``python -m fishnet_tpu.rpc.host`` serving every frontend's link in
    the supervisor's ``rpc_dir``. All three ride the same chaos
    proxies, restart budgets, drain, and metrics discovery.
    """

    name: str
    key: Optional[str] = None  # default: the name
    fault_spec: str = ""  # proxy.* + proc.* plan for THIS process
    extra_args: Tuple[str, ...] = ()
    restart_budget: int = 3
    role: str = "monolith"  # monolith | frontend | evaluator

    def __post_init__(self) -> None:
        if self.role not in ("monolith", "frontend", "evaluator"):
            raise ValueError(
                f"ProcSpec role must be monolith|frontend|evaluator, "
                f"got {self.role!r}"
            )


@dataclass
class ProcHandle:
    spec: ProcSpec
    plan: Optional[FaultPlan]
    proxy: ChaosProxy
    log_path: Path
    rng: random.Random
    process: Optional[asyncio.subprocess.Process] = None
    restarts: int = 0
    spawns: int = 0
    exit_codes: List[int] = field(default_factory=list)
    monitor: Optional[asyncio.Task] = None


class FleetSupervisor:
    """Spawn, chaos-drive, restart and drain a fleet of client
    processes against ``server_endpoint``."""

    def __init__(
        self,
        server_endpoint: str,
        specs: List[ProcSpec],
        *,
        workdir: Optional[str] = None,
        logger=None,
        tick_seconds: float = 0.25,
        drain_deadline: float = 5.0,
        restart_backoff: float = 0.4,
        metrics: bool = True,
        rpc_dir: Optional[str] = None,
    ) -> None:
        self.server_endpoint = server_endpoint
        self.specs = list(specs)
        self.workdir = Path(workdir) if workdir else Path(
            tempfile.mkdtemp(prefix="fishnet-fleet-")
        )
        # Link-file directory for split-role specs (frontend/evaluator);
        # monolith-only fleets never touch it.
        self.rpc_dir = rpc_dir or str(self.workdir / "rpc")
        self.logger = logger
        self.tick_seconds = tick_seconds
        self.drain_deadline = drain_deadline
        self.restart_backoff = restart_backoff
        self.metrics = metrics
        self.procs: Dict[str, ProcHandle] = {}
        #: Chaos/lifecycle timeline: (seconds since start, proc, kind)
        #: with kinds spawn, kill, sigterm, exit:<rc>, restart,
        #: budget-exhausted, drain-sigterm, drain-sigkill.
        self.events: List[Tuple[float, str, str]] = []
        self._t0 = 0.0
        self._stopping = False

    def _log(self, message: str) -> None:
        if self.logger is not None:
            self.logger.info(message)

    def _event(self, proc: str, kind: str) -> None:
        self.events.append((round(time.monotonic() - self._t0, 3), proc, kind))

    async def start(self) -> "FleetSupervisor":
        self._t0 = time.monotonic()
        self.workdir.mkdir(parents=True, exist_ok=True)
        for spec in self.specs:
            plan = FaultPlan.parse(spec.fault_spec) if spec.fault_spec else None
            proxy = await ChaosProxy(
                self.server_endpoint, plan=plan, name=spec.name
            ).start()
            handle = ProcHandle(
                spec=spec,
                plan=plan,
                proxy=proxy,
                log_path=self.workdir / f"{spec.name}.log",
                # str seeding is stable across runs and processes, so a
                # given fleet replays the same backoff schedule.
                rng=random.Random(spec.name),
            )
            self.procs[spec.name] = handle
            await self._spawn(handle)
            handle.monitor = asyncio.create_task(self._monitor(handle))
        return self

    async def _spawn(self, handle: ProcHandle) -> None:
        spec = handle.spec
        if spec.role == "evaluator":
            # Device-holding half of the split plane: serves every
            # frontend link in rpc_dir; no lichess client underneath.
            cmd = [
                sys.executable, "-m", "fishnet_tpu.rpc.host",
                "--dir", self.rpc_dir,
                *spec.extra_args,
            ]
            if self.metrics:
                cmd += [
                    "--metrics-port", "0",
                    "--metrics-port-file",
                    str(self.workdir / f"{spec.name}.port"),
                ]
        else:
            cmd = [
                sys.executable, "-m", "fishnet_tpu", "run",
                "--no-conf", "--no-stats-file",
                "--engine", "mock",
                "--endpoint", handle.proxy.endpoint,
                "--key", spec.key or spec.name,
                "--cores", "1",
                "--max-backoff", "1s",
                "--drain-deadline", f"{int(self.drain_deadline * 1000)}ms",
                *spec.extra_args,
            ]
            if self.metrics:
                cmd += [
                    "--metrics-port", "0",
                    "--metrics-port-file",
                    str(self.workdir / f"{spec.name}.port"),
                    # Batch-span write-ahead: spans recorded after the
                    # aggregator's last scrape survive a SIGKILL, so the
                    # fleet stitcher can join the dead incarnation's
                    # reassigned unit cross-process. Restarts append a
                    # new incarnation header to the same file.
                    "--spans-journal",
                    str(self.workdir / f"{spec.name}.journal.jsonl"),
                ]
        env = dict(os.environ)
        existing = env.get("PYTHONPATH")
        env["PYTHONPATH"] = (
            f"{_REPO_ROOT}{os.pathsep}{existing}" if existing else str(_REPO_ROOT)
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Chaos lives at the proxy and this supervisor; the child runs
        # a clean, production-shaped client.
        env.pop(PLAN_ENV, None)
        # Role plumbing: a frontend is the SAME client binary with the
        # rpc gate flipped; a monolith must never inherit a split env
        # from the operator's shell.
        if spec.role == "frontend":
            env["FISHNET_RPC"] = "1"
            env["FISHNET_RPC_DIR"] = self.rpc_dir
        else:
            env.pop("FISHNET_RPC", None)
            if spec.role == "evaluator":
                env["FISHNET_RPC_DIR"] = self.rpc_dir
                # The host polls rpc.detach from ITS OWN plan env (the
                # proxy sites are meaningless to it).
                if spec.fault_spec:
                    env[PLAN_ENV] = spec.fault_spec
            else:
                env.pop("FISHNET_RPC_DIR", None)
        logf = open(handle.log_path, "ab")
        try:
            handle.process = await asyncio.create_subprocess_exec(
                *cmd,
                stdout=logf,
                stderr=asyncio.subprocess.STDOUT,
                cwd=str(self.workdir),
                env=env,
                start_new_session=True,
            )
        finally:
            logf.close()
        handle.spawns += 1
        self._event(spec.name, "spawn")
        self._log(f"fleet: spawned {spec.name} (pid {handle.process.pid})")

    async def _monitor(self, handle: ProcHandle) -> None:
        name = handle.spec.name
        while not self._stopping:
            await asyncio.sleep(self.tick_seconds)
            if self._stopping:
                return
            process = handle.process
            if process is None:
                return
            rc = process.returncode
            if rc is None:
                rc = await self._poll_exit(process)
            if rc is not None:
                handle.exit_codes.append(rc)
                self._event(name, f"exit:{rc}")
                if handle.restarts >= handle.spec.restart_budget:
                    self._event(name, "budget-exhausted")
                    self._log(f"fleet: {name} restart budget exhausted")
                    return
                delay = (
                    self.restart_backoff
                    * (1 + handle.restarts)
                    * (0.75 + 0.5 * handle.rng.random())
                )
                await asyncio.sleep(delay)
                if self._stopping:
                    return
                await self._spawn(handle)
                handle.restarts += 1
                _RESTARTS.inc(proc=name)
                self._event(name, "restart")
                continue
            # Chaos tick: poll BOTH proc sites every tick so nth=N means
            # tick N for each independently.
            plan = handle.plan
            if plan is None:
                continue
            kill_rule = plan.poll("proc.kill")
            term_rule = plan.poll("proc.sigterm")
            if kill_rule is not None:
                self._event(name, "kill")
                self._log(f"fleet: SIGKILL {name} (pid {process.pid})")
                self._signal(process, signal.SIGKILL)
            elif term_rule is not None:
                self._event(name, "sigterm")
                self._log(f"fleet: SIGTERM {name} (pid {process.pid}) -> drain")
                self._signal(process, signal.SIGTERM)

    @staticmethod
    async def _poll_exit(process: asyncio.subprocess.Process) -> Optional[int]:
        try:
            return await asyncio.wait_for(asyncio.shield(process.wait()), 0.01)
        except asyncio.TimeoutError:
            return None

    @staticmethod
    def _signal(process: asyncio.subprocess.Process, sig: int) -> None:
        try:
            process.send_signal(sig)
        except ProcessLookupError:
            pass  # lost the race with its own exit; the monitor sees it

    def metrics_targets(self) -> Dict[str, str]:
        """Current ``{proc_name: exporter_url}`` map from the workdir's
        port files (empty entries for children that haven't written
        theirs yet). The fleet aggregator takes the same directory via
        ``targets_fn=port_dir_targets(str(sup.workdir))`` to follow
        restarts live."""
        from fishnet_tpu.telemetry.fleet import port_dir_targets

        return port_dir_targets(str(self.workdir))()

    def live_count(self) -> int:
        return sum(
            1
            for h in self.procs.values()
            if h.process is not None and h.process.returncode is None
        )

    def restarts_total(self) -> int:
        return sum(h.restarts for h in self.procs.values())

    async def drain(self, grace: float = 10.0) -> Dict[str, Optional[int]]:
        """Fleet-wide graceful shutdown. SIGTERM every live process,
        wait out the drain deadline plus ``grace``, SIGKILL stragglers,
        stop the proxies. Returns final exit codes by process."""
        self._stopping = True
        for handle in self.procs.values():
            if handle.monitor is not None:
                handle.monitor.cancel()
        await asyncio.gather(
            *(h.monitor for h in self.procs.values() if h.monitor is not None),
            return_exceptions=True,
        )
        for name, handle in self.procs.items():
            process = handle.process
            if process is not None and process.returncode is None:
                self._event(name, "drain-sigterm")
                self._signal(process, signal.SIGTERM)
        deadline = time.monotonic() + self.drain_deadline + grace
        exit_codes: Dict[str, Optional[int]] = {}
        for name, handle in self.procs.items():
            process = handle.process
            if process is None:
                exit_codes[name] = (
                    handle.exit_codes[-1] if handle.exit_codes else None
                )
                continue
            remaining = max(0.1, deadline - time.monotonic())
            try:
                rc = await asyncio.wait_for(process.wait(), remaining)
            except asyncio.TimeoutError:
                self._event(name, "drain-sigkill")
                self._log(f"fleet: {name} missed the drain deadline; SIGKILL")
                self._signal(process, signal.SIGKILL)
                rc = await process.wait()
            if not handle.exit_codes or handle.exit_codes[-1] != rc:
                handle.exit_codes.append(rc)
            exit_codes[name] = rc
        for handle in self.procs.values():
            await handle.proxy.close()
        return exit_codes

    async def kill_all(self) -> None:
        """Error-path teardown: SIGKILL everything, close proxies."""
        self._stopping = True
        for handle in self.procs.values():
            if handle.monitor is not None:
                handle.monitor.cancel()
            process = handle.process
            if process is not None and process.returncode is None:
                self._signal(process, signal.SIGKILL)
        for handle in self.procs.values():
            if handle.process is not None:
                try:
                    await asyncio.wait_for(handle.process.wait(), 5)
                except asyncio.TimeoutError:
                    pass
            await handle.proxy.close()
