"""Auto-update subsystem.

Equivalent of the reference's ``--auto-update`` flow
(src/main.rs:48-65, 179-199, 412-464): check a release index on startup
and every UPDATE_INTERVAL, and when a newer version exists, finish
draining work and re-``exec`` the process so the new code takes over.

The reference self-replaces a static binary from an S3 bucket; a Python
deployment updates its environment instead, so the update *source* is
pluggable: ``FISHNET_TPU_UPDATE_URL`` names an HTTP JSON index
``{"latest": "x.y.z", "command": ["pip", ...]}`` (absent ⇒ updates are a
no-op). The drain-then-exec restart semantics are preserved exactly.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
from dataclasses import dataclass
from typing import List, Optional

from fishnet_tpu.utils.logger import Logger
from fishnet_tpu.version import __version__

#: Periodic re-check cadence (main.rs:179: every 5 h, with jitter applied
#: by the caller's select loop).
UPDATE_INTERVAL_SECONDS = 5 * 60 * 60

UPDATE_URL_ENV = "FISHNET_TPU_UPDATE_URL"


def parse_version(v: str) -> tuple:
    return tuple(int(p) for p in v.strip().lstrip("v").split("."))


@dataclass
class UpdateStatus:
    checked: bool
    current: str
    latest: Optional[str] = None
    updated: bool = False
    command: Optional[List[str]] = None

    @property
    def update_available(self) -> bool:
        return self.latest is not None and parse_version(self.latest) > parse_version(self.current)


async def check_for_update(url: Optional[str] = None) -> UpdateStatus:
    """Fetch the release index (one GET; the command rides along so
    apply_update doesn't re-fetch a possibly changed index). Returns
    ``checked=False`` when no update source is configured (the common,
    zero-egress deployment)."""
    url = url or os.environ.get(UPDATE_URL_ENV)
    if not url:
        return UpdateStatus(checked=False, current=__version__)
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.get(url, timeout=aiohttp.ClientTimeout(total=30)) as resp:
            resp.raise_for_status()
            index = json.loads(await resp.text())
    return UpdateStatus(
        checked=True,
        current=__version__,
        latest=index.get("latest"),
        command=index.get("command"),
    )


async def apply_update(url: Optional[str] = None, logger: Optional[Logger] = None) -> UpdateStatus:
    """Check and, when newer, run the index's update command
    (e.g. a pip install). Restart is the caller's job — after draining,
    like main.rs:257-259."""
    logger = logger or Logger()
    status = await check_for_update(url)
    if not status.checked:
        logger.debug("Auto-update: no update source configured.")
        return status
    if not status.update_available:
        logger.fishnet_info(f"fishnet-tpu {__version__} is up to date.")
        return status
    command = status.command
    if command:
        logger.fishnet_info(f"Updating to {status.latest} ...")
        proc = await asyncio.create_subprocess_exec(*command)
        rc = await proc.wait()
        if rc != 0:
            logger.error(f"Update command failed with exit code {rc}.")
            return status
        status.updated = True
    return status


#: Set on exec so the restarted process doesn't loop forever when the
#: update command succeeded but didn't actually change the installed
#: version (wrong env, source checkout, ...).
_ATTEMPT_ENV = "FISHNET_TPU_UPDATE_ATTEMPTED"


def restart_process(logger: Logger, target_version: Optional[str] = None) -> None:
    """Replace this process with a fresh invocation of the same argv
    (main.rs:412-438, Unix exec path)."""
    logger.fishnet_info("Restarting ...")
    if target_version:
        os.environ[_ATTEMPT_ENV] = target_version
    os.execv(sys.executable, [sys.executable, "-m", "fishnet_tpu", *sys.argv[1:]])


def auto_update(logger: Logger) -> UpdateStatus:
    """Startup-time check (main.rs:48-65). Blocking wrapper; the periodic
    re-check runs inside the supervisor loop via ``check_for_update``."""
    logger.fishnet_info("Checking for updates (--auto-update) ...")
    try:
        status = asyncio.run(apply_update(logger=logger))
    except Exception as err:
        logger.error(f"Failed to check for updates: {err}")
        return UpdateStatus(checked=False, current=__version__)
    if status.updated:
        if os.environ.get(_ATTEMPT_ENV) == status.latest:
            logger.error(
                f"Update to {status.latest} ran but the installed version is "
                f"still {__version__}; not restarting again."
            )
            return status
        restart_process(logger, status.latest)
    return status
