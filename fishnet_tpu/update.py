"""Auto-update subsystem.

Equivalent of the reference's ``--auto-update`` flow
(src/main.rs:48-65, 179-199, 412-464): check a release index on startup
and every UPDATE_INTERVAL, and when a newer version exists, finish
draining work and re-``exec`` the process so the new code takes over.

The reference self-replaces a static binary from an S3 bucket
(src/main.rs:440-464, bucket ``fishnet-releases``); the equivalent here
is a DEFAULT static-HTTPS release channel with the same S3-compatible
layout, used whenever ``--auto-update`` is set: a JSON index names the
latest version plus a release tarball, its sha256, and a detached
Ed25519 signature over the tarball made with the release-signing key
(whose PUBLIC half is pinned below). The tarball is downloaded,
hash-verified, signature-verified, and unpacked over the installation
root before the drain-then-exec restart. The sha256 alone only protects
against truncation — it comes from the same unauthenticated index, so
the pinned-key signature is what makes bucket compromise ≠ RCE.
``FISHNET_TPU_UPDATE_URL`` overrides the channel (private mirrors, the
integration tests); only then may the index alternatively carry a
``command`` (e.g. a pip install) for environments that manage their own
packages — the default channel NEVER executes index-supplied commands.

Index schema, served at ``<channel>/index.json``::

    {"latest": "x.y.z",
     "artifact": "vX.Y.Z/fishnet-tpu-vX.Y.Z.tar.gz",   # urljoin vs index
     "sha256": "<hex digest of the tarball>",
     "signature": "<hex Ed25519 sig over the tarball bytes>",
     "command": ["pip", "install", ...]}   # env-override channels only

The artifact layout is exactly what CI packages (.github/workflows/
build.yml: ``fishnet_tpu/`` + prebuilt ``cpp/libfishnetcore*.so`` tiers
+ sources).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import sys
import tarfile
import tempfile
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional

from fishnet_tpu.utils.logger import Logger
from fishnet_tpu.version import __version__

#: Periodic re-check cadence (main.rs:179: every 5 h, with jitter applied
#: by the caller's select loop).
UPDATE_INTERVAL_SECONDS = 5 * 60 * 60

UPDATE_URL_ENV = "FISHNET_TPU_UPDATE_URL"

#: Default release channel (S3-compatible static HTTPS, the layout the
#: reference's self_update consumes from its own bucket). Engaged only
#: when the caller opts in (--auto-update passes allow_default=True);
#: the env override always wins.
DEFAULT_CHANNEL = (
    "https://fishnet-tpu-releases.s3.dualstack.eu-west-3.amazonaws.com"
    "/fishnet-tpu"
)

#: Ed25519 public key pinned in the client; the private half lives only
#: in the release pipeline's secret store (tools/sign_release.py is the
#: signing side). Artifacts from the DEFAULT channel must verify against
#: this key — a compromised bucket can then serve stale or broken
#: indexes, but not code we will execute. Override channels may supply
#: their own key via FISHNET_TPU_UPDATE_PUBKEY (hex).
SIGNING_PUBKEY_HEX = (
    "e7aa856c36f1f3f9b2a415b9d1bef208f5ceacdc9b0ecefb993a36a46c6e7733"
)

UPDATE_PUBKEY_ENV = "FISHNET_TPU_UPDATE_PUBKEY"


def verify_signature(data: bytes, signature_hex: str, pubkey_hex: str) -> None:
    """Raise if ``signature_hex`` is not a valid Ed25519 signature over
    ``data`` by ``pubkey_hex``. Fails loudly (ImportError) when the
    ``cryptography`` package is absent — a signature we cannot check is
    treated exactly like a bad one."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    key = Ed25519PublicKey.from_public_bytes(bytes.fromhex(pubkey_hex))
    try:
        key.verify(bytes.fromhex(signature_hex), data)
    except InvalidSignature:
        raise ValueError(
            "release artifact signature does not verify against the "
            "pinned release key"
        ) from None


def parse_version(v: str) -> tuple:
    return tuple(int(p) for p in v.strip().lstrip("v").split("."))


@dataclass
class UpdateStatus:
    checked: bool
    current: str
    latest: Optional[str] = None
    updated: bool = False
    command: Optional[List[str]] = None
    #: Release-tarball channel fields (the default path): artifact URL
    #: resolved against the index URL, its required sha256, and the
    #: detached Ed25519 signature (required on the default channel).
    artifact: Optional[str] = None
    sha256: Optional[str] = None
    signature: Optional[str] = None
    #: True when the index came from the built-in DEFAULT channel (no
    #: explicit url, no env override) — the trust decisions key off this:
    #: signature mandatory, index `command` never executed.
    from_default: bool = False
    #: Verified, fully-extracted staging directory awaiting promotion
    #: (set when apply_update ran with defer_promote=True).
    staged: Optional[Path] = None
    #: True when defer_promote postponed the actual install (staged
    #: tarball promotion OR legacy command execution) to the caller's
    #: post-drain path.
    deferred: bool = False

    @property
    def update_available(self) -> bool:
        return self.latest is not None and parse_version(self.latest) > parse_version(self.current)


async def check_for_update(
    url: Optional[str] = None, allow_default: bool = False
) -> UpdateStatus:
    """Fetch the release index (one GET; artifact/command ride along so
    apply_update doesn't re-fetch a possibly changed index). Source
    precedence: explicit ``url`` > ``FISHNET_TPU_UPDATE_URL`` > the
    default channel (only with ``allow_default``, i.e. --auto-update).
    Returns ``checked=False`` when no source applies (the common
    zero-egress deployment without --auto-update)."""
    from urllib.parse import urljoin

    explicit = url or os.environ.get(UPDATE_URL_ENV)
    from_default = False
    if not explicit and allow_default:
        url = DEFAULT_CHANNEL + "/index.json"
        from_default = True
    else:
        url = explicit
    if not url:
        return UpdateStatus(checked=False, current=__version__)
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.get(url, timeout=aiohttp.ClientTimeout(total=30)) as resp:
            resp.raise_for_status()
            index = json.loads(await resp.text())
    artifact = index.get("artifact")
    return UpdateStatus(
        checked=True,
        current=__version__,
        latest=index.get("latest"),
        command=index.get("command"),
        artifact=urljoin(url, artifact) if artifact else None,
        sha256=index.get("sha256"),
        signature=index.get("signature"),
        from_default=from_default,
    )


def default_install_root() -> Path:
    """Where release tarballs unpack: the directory containing the
    ``fishnet_tpu`` package (the tarball carries ``fishnet_tpu/``,
    ``cpp/...`` at its top level — CI's artifact layout)."""
    return Path(__file__).resolve().parent.parent


async def download_and_verify(
    artifact_url: str, sha256: str, dest: Path,
    signature: Optional[str] = None, pubkey_hex: Optional[str] = None,
    require_signature: bool = False,
) -> Path:
    """Stream the release tarball to ``dest``; require the announced
    sha256 (truncation/corruption guard) and — whenever a pubkey applies
    — a valid Ed25519 signature over the tarball bytes. The sha256 comes
    from the same unauthenticated index as the artifact, so only the
    pinned-key signature authenticates the release; ``require_signature``
    (the default channel) makes a missing signature fatal rather than
    skippable."""
    import aiohttp

    digest = hashlib.sha256()
    tmp = dest.with_suffix(".part")
    async with aiohttp.ClientSession() as session:
        async with session.get(
            artifact_url, timeout=aiohttp.ClientTimeout(total=600)
        ) as resp:
            resp.raise_for_status()
            with open(tmp, "wb") as f:
                async for chunk in resp.content.iter_chunked(1 << 16):
                    digest.update(chunk)
                    f.write(chunk)
    if digest.hexdigest() != sha256.lower():
        tmp.unlink(missing_ok=True)
        raise ValueError(
            f"release artifact hash mismatch: got {digest.hexdigest()}, "
            f"index announced {sha256}"
        )
    if require_signature and not signature:
        tmp.unlink(missing_ok=True)
        raise ValueError(
            "release index carries no signature; the default channel "
            "requires artifacts signed by the pinned release key"
        )
    if signature and pubkey_hex:
        try:
            verify_signature(tmp.read_bytes(), signature, pubkey_hex)
        except Exception:
            tmp.unlink(missing_ok=True)
            raise
    tmp.rename(dest)
    return dest


def _validate_member(member: "tarfile.TarInfo") -> None:
    """Manual stand-in for tarfile's ``filter='data'`` on interpreters
    predating extraction filters (3.9–3.11 early patch levels): reject
    path traversal, absolute names, links, and special files. Regular
    files and directories only — exactly what CI's artifact layout
    contains."""
    name = member.name
    if Path(name).is_absolute() or ".." in Path(name).parts:
        raise ValueError(f"release member has unsafe path: {name!r}")
    if not (member.isfile() or member.isdir()):
        raise ValueError(
            f"release member {name!r} is not a regular file or directory "
            f"(type {member.type!r})"
        )
    # Match the 'data' filter's mode sanitization: no setuid/setgid/
    # sticky, no group/other write, from an untrusted archive.
    member.mode &= 0o755


def install_tarball(tar_path: Path, staging: Path) -> None:
    """Unpack a verified release tarball into a STAGING directory.
    ``filter='data'`` rejects path traversal, links, and device nodes
    outright (the 'all engine input is carefully validated' stance of
    the reference, applied to our own update channel); interpreters
    without extraction filters get the explicit member validation above
    instead of silently failing every update cycle. Staging keeps a
    mid-extract failure (disk full, rejected member) from leaving the
    live tree mixed-version — nothing touches it until promote_staged.
    """
    with tarfile.open(tar_path, "r:gz") as tar:
        if hasattr(tarfile, "data_filter"):
            tar.extractall(staging, filter="data")
        else:
            members = tar.getmembers()
            for m in members:
                _validate_member(m)
            tar.extractall(staging, members=members)


def promote_staged(staging: Path, install_root: Path) -> None:
    """Move a fully-extracted staging tree into place, one atomic
    os.replace per file. Rename (not truncate-in-place) is what keeps a
    still-running process safe: its dlopen'ed native libraries and
    imported modules hold the OLD inodes, which persist unlinked until
    process exit — extracting directly over the live tree would
    truncate mapped .so files and SIGBUS the engine mid-drain. Callers
    promote only when idle: at startup (nothing loaded yet) or after
    the drain completes, right before the exec restart.

    A validation pre-pass rejects file/directory type collisions BEFORE
    any file moves, so the common mid-walk failures cannot leave a
    mixed-version tree (a crash mid-promotion still can — per-file
    rename is as atomic as a portable install gets)."""
    files = [p for p in sorted(staging.rglob("*")) if p.is_file()]
    for src in files:
        rel = src.relative_to(staging)
        dest = install_root / rel
        if dest.exists() and dest.is_dir():
            raise IsADirectoryError(
                f"release file {rel} collides with an existing directory"
            )
        probe = install_root
        for part in rel.parts[:-1]:
            probe = probe / part
            if probe.exists() and not probe.is_dir():
                raise NotADirectoryError(
                    f"release path {rel} crosses existing file {probe}"
                )
    for src in files:
        dest = install_root / src.relative_to(staging)
        dest.parent.mkdir(parents=True, exist_ok=True)
        os.replace(src, dest)
    import shutil

    shutil.rmtree(staging, ignore_errors=True)


async def apply_update(
    url: Optional[str] = None,
    logger: Optional[Logger] = None,
    allow_default: bool = False,
    install_root: Optional[Path] = None,
    defer_promote: bool = False,
) -> UpdateStatus:
    """Check and, when newer, install: download + sha256-verify + unpack
    the release tarball into staging (default channel), or run the
    index's update command (legacy/pip deployments). With
    ``defer_promote`` the verified staging dir is returned in
    ``status.staged`` instead of being promoted — the periodic updater
    promotes only after the drain completes, so the live process never
    has files swapped under it while work is in flight. Restart is the
    caller's job — after draining, like main.rs:257-259."""
    logger = logger or Logger()
    status = await check_for_update(url, allow_default=allow_default)
    if not status.checked:
        logger.debug("Auto-update: no update source configured.")
        return status
    if not status.update_available:
        logger.fishnet_info(f"fishnet-tpu {__version__} is up to date.")
        return status
    if status.artifact and status.sha256:
        logger.fishnet_info(f"Updating to {status.latest} ...")
        root = install_root or default_install_root()
        staging = root / f".fishnet-tpu-staging-{status.latest}"
        import shutil

        # A previous run may have staged this version and been stopped
        # before promoting; extracting over the stale tree would merge
        # files a re-cut artifact no longer contains.
        shutil.rmtree(staging, ignore_errors=True)
        # Default channel: the pinned key is mandatory. Override
        # channels (tests, private mirrors): verify only when the
        # operator configured a key for it.
        pubkey = (
            SIGNING_PUBKEY_HEX if status.from_default
            else os.environ.get(UPDATE_PUBKEY_ENV)
        )
        # Wherever a key applies, an index that OMITS the signature must
        # fail — otherwise a hostile mirror downgrades verification by
        # simply not announcing one.
        require_sig = status.from_default or bool(pubkey)
        with tempfile.TemporaryDirectory(prefix="fishnet-tpu-update-") as td:
            try:
                tar = await download_and_verify(
                    status.artifact, status.sha256,
                    Path(td) / "release.tar.gz",
                    signature=status.signature,
                    pubkey_hex=pubkey,
                    require_signature=require_sig,
                )
                install_tarball(tar, staging)
            except Exception as err:  # noqa: BLE001 - keep running on bad updates
                logger.error(f"Update download/verify failed: {err}")
                import shutil

                shutil.rmtree(staging, ignore_errors=True)
                return status
        if defer_promote:
            status.staged = staging
            status.deferred = True
        else:
            promote_staged(staging, root)
        status.updated = True
        return status
    if status.command:
        if status.from_default:
            # Executing an index-supplied argv from the DEFAULT channel
            # would turn bucket takeover into RCE on every --auto-update
            # worker; only operator-configured channels (explicit url /
            # FISHNET_TPU_UPDATE_URL) are trusted that far.
            logger.error(
                "Update index from the default channel carries a `command`; "
                "refusing to execute it (only FISHNET_TPU_UPDATE_URL "
                "channels may use command-based updates)."
            )
            return status
        if defer_promote:
            # The live environment must not be mutated while work is in
            # flight: the caller runs the command after its drain, like
            # the tarball promotion.
            status.deferred = True
            status.updated = True
            return status
        logger.fishnet_info(f"Updating to {status.latest} ...")
        proc = await asyncio.create_subprocess_exec(*status.command)
        rc = await proc.wait()
        if rc != 0:
            logger.error(f"Update command failed with exit code {rc}.")
            return status
        status.updated = True
    return status


#: Set on exec so the restarted process doesn't loop forever when the
#: update command succeeded but didn't actually change the installed
#: version (wrong env, source checkout, ...).
_ATTEMPT_ENV = "FISHNET_TPU_UPDATE_ATTEMPTED"


def restart_process(logger: Logger, target_version: Optional[str] = None) -> None:
    """Replace this process with a fresh invocation of the same argv
    (main.rs:412-438, Unix exec path)."""
    logger.fishnet_info("Restarting ...")
    if target_version:
        os.environ[_ATTEMPT_ENV] = target_version
    os.execv(sys.executable, [sys.executable, "-m", "fishnet_tpu", *sys.argv[1:]])


def auto_update(logger: Logger) -> UpdateStatus:
    """Startup-time check (main.rs:48-65). Blocking wrapper; the periodic
    re-check runs inside the supervisor loop via ``check_for_update``.
    --auto-update is the opt-in that engages the DEFAULT release channel
    (env override still wins inside check_for_update)."""
    logger.fishnet_info("Checking for updates (--auto-update) ...")
    try:
        status = asyncio.run(apply_update(logger=logger, allow_default=True))
    except Exception as err:
        logger.error(f"Failed to check for updates: {err}")
        return UpdateStatus(checked=False, current=__version__)
    if status.updated:
        if os.environ.get(_ATTEMPT_ENV) == status.latest:
            logger.error(
                f"Update to {status.latest} ran but the installed version is "
                f"still {__version__}; not restarting again."
            )
            return status
        restart_process(logger, status.latest)
    return status
