"""``fishnet-tpu verify-net``: prove a real .nnue asset is compatible.

No real lichess net can ship inside this repository (the reference
embeds `nn-ad9b42354671.nnue` at build time, reference build.rs:7; this
environment has no egress), so compatibility with real nets is made a
one-command, user-runnable proof instead: a deployer points this at the
net they intend to serve and gets a pass/fail report covering

1. **layout** — strict SFv5+ (nnue-pytorch) parse: version word,
   architecture hash, section sizes, padded l2 rows (see
   nnue/spec.py for what remains offline-unverifiable, e.g. per-section
   content hashes of nets we cannot have);
2. **oracle parity** — the C++ scalar evaluator and the batched JAX
   evaluator (the full wire path: uint16 features, delta blocks,
   host-side material) must agree BIT-EXACTLY on sampled random
   positions;
3. **search parity** — fixed-depth searches through the scalar and
   batched backends must return identical scores and best moves;
4. **material probe** — reports whether the net's eval tracks material
   (nnue_material_correlated), which decides if the full SEE policy
   engages in search.

Any failure names the stage; exit code 0 only when every stage passes.
"""

from __future__ import annotations

import asyncio
import ctypes
from typing import Callable, List, Optional

__all__ = ["verify_net", "run_cli"]


def _sample_fens(n: int, seed: int) -> List[str]:
    import random

    from fishnet_tpu.chess import Board

    rng = random.Random(seed)
    fens = []
    while len(fens) < n:
        b = Board()
        for _ in range(rng.randrange(2, 70)):
            if b.outcome() != 0:
                break
            b.push_uci(rng.choice(b.legal_moves()))
        if b.outcome() == 0:
            fens.append(b.fen())
    return fens


def verify_net(
    path: str,
    positions: int = 200,
    depth: int = 4,
    log: Optional[Callable[[str], None]] = None,
) -> bool:
    """Run every stage; returns True when all pass. ``log`` receives
    one human-readable line per stage."""
    emit = log or (lambda s: None)
    ok = True

    # -- stage 1: layout ---------------------------------------------------
    from fishnet_tpu.nnue.weights import NnueWeights

    try:
        weights = NnueWeights.load(path)
        emit(f"layout          PASS  ({path})")
    except Exception as err:  # noqa: BLE001 - report, don't crash
        emit(f"layout          FAIL  {err}")
        return False

    # C++ loader must accept it too (it is the search-side consumer).
    from fishnet_tpu.chess.core import load as load_lib

    lib = load_lib()
    err_buf = ctypes.create_string_buffer(256)
    net = lib.fc_nnue_load(path.encode(), err_buf, len(err_buf))
    if not net:
        emit(f"scalar load     FAIL  {err_buf.value.decode(errors='replace')}")
        return False
    emit("scalar load     PASS")

    # -- stage 2: scalar vs JAX bit parity on sampled positions ------------
    import numpy as np

    from fishnet_tpu.chess import Board
    from fishnet_tpu.nnue import spec
    from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit, params_from_weights

    fens = _sample_fens(positions, seed=1234)
    params = params_from_weights(weights)

    feats = np.full(
        (len(fens), 2, spec.MAX_ACTIVE_FEATURES), spec.NUM_FEATURES, np.uint16
    )
    buckets = np.empty((len(fens),), np.int32)
    scalar_vals = np.empty((len(fens),), np.int64)
    feat_buf = (ctypes.c_int32 * spec.MAX_ACTIVE_FEATURES)()
    try:
        for i, fen in enumerate(fens):
            board = Board(fen)
            for p in range(2):
                cnt = lib.fc_pos_features(board._pos, p, feat_buf)
                feats[i, p, :cnt] = np.frombuffer(
                    feat_buf, dtype=np.int32, count=cnt
                ).astype(np.uint16)
            buckets[i] = lib.fc_pos_psqt_bucket(board._pos)
            scalar_vals[i] = lib.fc_nnue_evaluate(net, board._pos)
    finally:
        lib.fc_nnue_free(net)

    jax_vals = np.asarray(evaluate_batch_jit(params, feats, buckets)).astype(
        np.int64
    )
    bad = np.nonzero(jax_vals != scalar_vals)[0]
    if bad.size:
        i = int(bad[0])
        emit(
            f"eval parity     FAIL  {bad.size}/{len(fens)} positions differ; "
            f"first: {fens[i]!r} scalar={scalar_vals[i]} jax={jax_vals[i]}"
        )
        ok = False
    else:
        emit(f"eval parity     PASS  ({len(fens)} positions, bit-exact)")

    # -- stage 3: fixed-depth search self-parity ---------------------------
    from fishnet_tpu.search.service import SearchService

    async def search_all(backend: str):
        svc = SearchService(
            weights=weights, pool_slots=8, batch_capacity=64,
            tt_bytes=64 << 20, backend=backend,
        )
        svc.set_prefetch(8, adaptive=False)
        try:
            out = []
            for fen in fens[: max(10, positions // 10)]:
                r = await svc.search(fen, [], depth=depth)
                line = [l for l in r.lines if l.multipv == 1][-1]
                out.append((line.value, line.is_mate, r.best_move))
            return out
        finally:
            svc.close()

    scalar_search = asyncio.run(search_all("scalar"))
    jax_search = asyncio.run(search_all("jax"))
    mismatches = [
        (f, s, j)
        for f, s, j in zip(fens, scalar_search, jax_search)
        if s != j
    ]
    if mismatches:
        emit(
            f"search parity   FAIL  {len(mismatches)} diverged at depth "
            f"{depth}; first: {mismatches[0]}"
        )
        ok = False
    else:
        emit(
            f"search parity   PASS  ({len(scalar_search)} searches at "
            f"depth {depth})"
        )

    # -- stage 4: material probe (informational, never fails) --------------
    if not hasattr(lib.fc_nnue_material_correlated, "_bound"):
        lib.fc_nnue_material_correlated.argtypes = [ctypes.c_void_p]
        lib.fc_nnue_material_correlated.restype = ctypes.c_int
        lib.fc_nnue_material_correlated._bound = True
    net = lib.fc_nnue_load(path.encode(), err_buf, len(err_buf))
    if net:
        correlated = bool(lib.fc_nnue_material_correlated(net))
        lib.fc_nnue_free(net)
        emit(
            "material probe  "
            + (
                "PASS  eval tracks material; full SEE policy engages"
                if correlated
                else "INFO  eval does not track material (random/dev "
                "net?); SEE capture demotion stays off"
            )
        )
    return ok


def run_cli(path: str) -> int:
    ok = verify_net(path, log=print)
    print("verify-net: " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1
