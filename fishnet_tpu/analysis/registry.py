"""The escape-hatch contract: every knob the platform reads, declared.

The codebase has grown ~30 ``FISHNET_*`` environment switches plus the
ini/CLI surface in ``configure.py``, and they drift: a kill switch gets
added under deadline, never lands in a doc, and six months later nobody
remembers whether ``FISHNET_NO_DEDUP`` disables byte-dedup, position
dedup, or both. R8 (:class:`~fishnet_tpu.analysis.contracts
.EscapeHatchRule`) closes the loop both ways against THIS file:

* an env read / CLI option / ini key in code that is not declared here
  is a finding at the usage site (add a row — and while you're at it, a
  doc line);
* a row declared here with no usage left in the tree is a finding here
  (delete the row — the knob is dead);
* ``documented_in`` / ``tested_by`` must name real files that actually
  mention the knob, so the pointers can't rot silently.

This module is DATA for the analysis package itself (the one deliberate
exception to "the analyzer never imports analyzed code" — it imports
its own contract, nothing from the runtime). Keep it dependency-free.

Conventions: ``documented_in`` is required — every knob a user can flip
deserves at least one sentence somewhere under ``doc/`` (or README).
``tested_by`` is ``None`` only when no test exercises the knob yet;
that's visible here on purpose, as a checklist, not hidden.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass(frozen=True)
class Knob:
    name: str  # "FISHNET_X" | "--option" | "IniKey"
    kind: str  # "env" | "cli" | "ini"
    default: str  # human-readable default ("unset", "0", "auto", ...)
    documented_in: str  # repo-relative file that mentions the knob
    tested_by: Optional[str] = None  # repo-relative test file, if any


KNOBS: Tuple[Knob, ...] = (
    # -- environment switches (kill switches & tuning) ---------------------
    Knob("FISHNET_AZ_COALESCE_WIDTH", "env", "unset (service width policy)",
         "doc/search.md"),
    Knob("FISHNET_AZ_EVAL_CACHE_CAPACITY", "env", "unset (NNUE cache size)",
         "doc/search.md"),
    Knob("FISHNET_BREAKER_COOLDOWN", "env", "60 (seconds)",
         "doc/resilience.md"),
    Knob("FISHNET_BREAKER_THRESHOLD", "env", "5 (consecutive failures)",
         "doc/resilience.md"),
    Knob("FISHNET_BOUNDS_CACHE_CAPACITY", "env", "65536 bound records",
         "doc/eval-cache.md"),
    Knob("FISHNET_CACHE_PREFETCH", "env", "unset (prefetch enabled)",
         "doc/eval-cache.md"),
    Knob("FISHNET_COALESCE_WIDTH", "env", "unset (adaptive width)",
         "doc/wire-format.md", "tests/test_coalesce.py"),
    Knob("FISHNET_EVAL_CACHE_CAPACITY", "env", "1048576 entries",
         "doc/eval-cache.md", "tests/test_eval_cache.py"),
    Knob("FISHNET_EVAL_CACHE_SNAPSHOT", "env", "unset (no snapshot file)",
         "doc/eval-cache.md"),
    Knob("FISHNET_FAULT_PLAN", "env", "unset (no fault injection)",
         "doc/resilience.md", "tests/test_configure.py"),
    Knob("FISHNET_HOST_LINGER_MS", "env", "2 (milliseconds)",
         "doc/disaggregation.md", "tests/test_bounds_plane.py"),
    Knob("FISHNET_HOST_MATERIAL", "env", "unset (fused-PSQT wire path)",
         "doc/wire-format.md"),
    Knob("FISHNET_METRICS_PORT", "env", "unset (exporter off)",
         "doc/observability.md"),
    Knob("FISHNET_MOCK_ENGINE_DELAY", "env", "0 (seconds; test hook)",
         "doc/install.md"),
    Knob("FISHNET_NO_ASYNC", "env", "unset (async pipeline on)",
         "doc/observability.md", "tests/test_async_dispatch.py"),
    Knob("FISHNET_NO_BOUNDS", "env", "unset (bounds tier on)",
         "doc/eval-cache.md", "tests/test_bounds_plane.py"),
    Knob("FISHNET_NO_COALESCE", "env", "unset (coalescing on)",
         "doc/wire-format.md", "tests/test_coalesce.py"),
    Knob("FISHNET_NO_CONTROL", "env", "unset (control plane may actuate)",
         "doc/control-plane.md", "tests/test_control.py"),
    Knob("FISHNET_NO_DEDUP", "env", "unset (fused dedup on)",
         "doc/wire-format.md", "tests/test_eval_cache.py"),
    Knob("FISHNET_NO_EVAL_CACHE", "env", "unset (eval cache on)",
         "doc/eval-cache.md", "tests/test_eval_cache.py"),
    Knob("FISHNET_NO_EXPANSION_MEMO", "env", "unset (MCTS memo on)",
         "doc/search.md"),
    Knob("FISHNET_NO_MESH", "env", "unset (mesh sharding on)",
         "doc/sharding.md", "tests/test_parallel.py"),
    Knob("FISHNET_NO_MULTITENANT", "env", "unset (multi-tenant on)",
         "doc/resilience.md", "tests/test_overload.py"),
    Knob("FISHNET_NO_SHARED_AZ_PLANE", "env", "unset (shared plane on)",
         "doc/search.md", "tests/test_mcts_plane.py"),
    Knob("FISHNET_NO_SPECULATION", "env", "unset (speculative pads on)",
         "doc/search.md", "tests/test_bounds_plane.py"),
    Knob("FISHNET_NO_SUBTREE_REUSE", "env", "unset (subtree reuse on)",
         "doc/search.md"),
    Knob("FISHNET_POSITION_TIER", "env", "unset (fleet tier off)",
         "doc/eval-cache.md", "tests/test_position_tier.py"),
    Knob("FISHNET_POSITION_TIER_PATH", "env",
         "fishnet-postier-<uid>.seg in the system tempdir",
         "doc/eval-cache.md", "tests/test_position_tier.py"),
    Knob("FISHNET_POSITION_TIER_CAPACITY", "env", "65536 NNUE slots",
         "doc/eval-cache.md", "tests/test_position_tier.py"),
    Knob("FISHNET_POSITION_TIER_AZ_CAPACITY", "env", "256 AZ slots",
         "doc/eval-cache.md", "tests/test_position_tier.py"),
    Knob("FISHNET_POSITION_TIER_BOUNDS_CAPACITY", "env", "16384 bound slots",
         "doc/eval-cache.md", "tests/test_bounds_plane.py"),
    Knob("FISHNET_PROFILE", "env", "unset (profiler off)",
         "doc/observability.md", "tests/test_profiler.py"),
    Knob("FISHNET_PROFILE_HZ", "env", "29 (samples/second)",
         "doc/observability.md"),
    Knob("FISHNET_RPC", "env", "unset (monolith)",
         "doc/disaggregation.md", "tests/test_rpc.py"),
    Knob("FISHNET_RPC_DIR", "env",
         "fishnet-rpc-<uid> in the system tempdir",
         "doc/disaggregation.md", "tests/test_rpc.py"),
    Knob("FISHNET_RPC_RING_SLOTS", "env", "8 slots per ring",
         "doc/disaggregation.md", "tests/test_rpc.py"),
    Knob("FISHNET_RPC_SLOT_BYTES", "env", "4 MiB per slot",
         "doc/disaggregation.md", "tests/test_rpc.py"),
    Knob("FISHNET_RPC_TIMEOUT", "env", "120 (seconds)",
         "doc/disaggregation.md", "tests/test_rpc.py"),
    Knob("FISHNET_SHARD_PLACEMENT", "env", "auto (round-robin groups)",
         "doc/sharding.md"),
    Knob("FISHNET_SPANS_DIR", "env", "unset (system tempdir)",
         "doc/observability.md", "tests/test_tracing.py"),
    Knob("FISHNET_SPANS_FILE", "env", "unset (per-pid file in spans dir)",
         "doc/observability.md", "tests/test_tracing.py"),
    Knob("FISHNET_SPECULATION_BUDGET", "env", "8 pad rows per dispatch",
         "doc/search.md", "tests/test_bounds_plane.py"),
    Knob("FISHNET_TPU_CORE_LIB", "env", "bundled libfishnet_core",
         "doc/install.md"),
    Knob("FISHNET_TPU_UPDATE_ATTEMPTED", "env", "unset (recursion guard)",
         "doc/install.md"),
    Knob("FISHNET_TPU_UPDATE_PUBKEY", "env", "release signing key",
         "doc/install.md", "tests/test_update_channel.py"),
    Knob("FISHNET_TPU_UPDATE_URL", "env", "release channel URL",
         "doc/install.md"),
    # -- CLI options (fishnet_tpu/configure.py, the product argparser) -----
    Knob("--auto-update", "cli", "off", "README.md"),
    Knob("--az-net-file", "cli", "unset (random weights)", "doc/install.md",
         "tests/test_az_trainer.py"),
    Knob("--batch-deadline", "cli", "unset (no deadline flushes)",
         "doc/resilience.md", "tests/test_configure.py"),
    Knob("--conf", "cli", "fishnet.ini next to the module", "README.md"),
    Knob("--control", "cli", "off (bench.py / fleet console mode flag)",
         "doc/control-plane.md", "tests/test_control.py"),
    Knob("--cores", "cli", "auto (n-1)", "README.md",
         "tests/test_configure.py"),
    Knob("--depth", "cli", "off (bench.py mode flag)",
         "doc/eval-cache.md", "tests/test_bounds_plane.py"),
    Knob("--drain-deadline", "cli", "10s", "doc/resilience.md",
         "tests/test_cluster.py"),
    Knob("--endpoint", "cli", "https://lichess.org/fishnet",
         "doc/install.md", "tests/test_configure.py"),
    Knob("--engine", "cli", "auto", "README.md", "tests/test_configure.py"),
    Knob("--engine-exe", "cli", "bundled binary", "doc/install.md"),
    Knob("--fault-plan", "cli", "unset", "doc/resilience.md",
         "tests/test_configure.py"),
    Knob("--fleet-cache", "cli", "off (bench.py mode flag)",
         "doc/eval-cache.md", "tests/test_position_tier.py"),
    Knob("--key", "cli", "unset (dialog asks)", "README.md",
         "tests/test_configure.py"),
    Knob("--key-file", "cli", "unset", "doc/install.md",
         "tests/test_configure.py"),
    Knob("--lane-depth-limit", "cli", "unset (no admission control)",
         "doc/install.md"),
    Knob("--max-backoff", "cli", "120s", "doc/install.md",
         "tests/test_cluster.py"),
    Knob("--mesh", "cli", "unset (single device)", "doc/sharding.md",
         "tests/test_configure.py"),
    Knob("--metrics-port", "cli", "unset (exporter off)",
         "doc/observability.md", "tests/test_cluster.py"),
    Knob("--metrics-port-file", "cli", "unset", "doc/observability.md"),
    Knob("--microbatch", "cli", "auto", "README.md",
         "tests/test_configure.py"),
    Knob("--nnue-file", "cli", "bundled network", "README.md"),
    Knob("--no-conf", "cli", "off", "doc/install.md",
         "tests/test_configure.py"),
    Knob("--no-stats-file", "cli", "off", "doc/install.md",
         "tests/test_configure.py"),
    Knob("--pipeline", "cli", "2 (double buffer)", "doc/install.md",
         "tests/test_async_dispatch.py"),
    Knob("--search-concurrency", "cli", "auto", "doc/install.md"),
    Knob("--search-threads", "cli", "1", "doc/install.md"),
    Knob("--spans-dir", "cli", "unset (system tempdir)",
         "doc/observability.md"),
    Knob("--spans-journal", "cli", "unset (ring dumps only)",
         "doc/observability.md"),
    Knob("--split", "cli", "off (bench.py mode flag)",
         "doc/disaggregation.md", "tests/test_rpc.py"),
    Knob("--stats-file", "cli", "platform data dir", "doc/install.md",
         "tests/test_configure.py"),
    Knob("--system-backlog", "cli", "0s", "doc/install.md"),
    Knob("--tenants", "cli", "unset (single tenant)", "doc/resilience.md",
         "tests/test_overload.py"),
    Knob("--user-backlog", "cli", "0s", "doc/install.md",
         "tests/test_configure.py"),
    Knob("--version", "cli", "-", "doc/install.md",
         "tests/test_configure.py"),
    Knob("--verbose", "cli", "off", "doc/install.md",
         "tests/test_configure.py"),
    # -- supervisor spec fields (cluster/supervisor.py ProcSpec) -----------
    Knob("role=", "cli", "monolith (frontend|evaluator split the plane)",
         "doc/disaggregation.md", "tests/test_rpc.py"),
    # -- fishnet.ini keys (mirror of _INI_FIELDS in configure.py) ----------
    Knob("Endpoint", "ini", "https://lichess.org/fishnet",
         "doc/install.md", "tests/test_configure.py"),
    Knob("Key", "ini", "unset", "doc/install.md",
         "tests/test_configure.py"),
    Knob("Cores", "ini", "auto (n-1)", "doc/install.md",
         "tests/test_configure.py"),
    Knob("UserBacklog", "ini", "0s", "doc/install.md",
         "tests/test_configure.py"),
    Knob("SystemBacklog", "ini", "0s", "doc/install.md",
         "tests/test_configure.py"),
    Knob("MaxBackoff", "ini", "120s", "doc/install.md"),
    Knob("Engine", "ini", "auto", "doc/install.md"),
    Knob("EngineExe", "ini", "bundled binary", "doc/install.md"),
    Knob("NnueFile", "ini", "bundled network", "doc/install.md"),
    Knob("AzNetFile", "ini", "unset", "doc/install.md"),
    Knob("Mesh", "ini", "unset (single device)", "doc/install.md",
         "tests/test_eval_cache.py"),
    Knob("SearchThreads", "ini", "1", "doc/install.md"),
    Knob("SearchConcurrency", "ini", "auto", "doc/install.md"),
    Knob("MetricsPort", "ini", "unset (exporter off)",
         "doc/install.md"),
    Knob("MetricsPortFile", "ini", "unset", "doc/install.md"),
    Knob("SpansDir", "ini", "unset (system tempdir)", "doc/install.md"),
    Knob("SpansJournal", "ini", "unset", "doc/install.md"),
    Knob("FaultPlan", "ini", "unset", "doc/install.md"),
    Knob("BatchDeadline", "ini", "unset", "doc/install.md"),
    Knob("Tenants", "ini", "unset (single tenant)", "doc/install.md"),
    Knob("LaneDepthLimit", "ini", "unset", "doc/install.md"),
    Knob("DrainDeadline", "ini", "10s", "doc/install.md"),
)
