"""R9: use-after-donation of ``donate_argnums``-donated arrays.

``jax.jit(f, donate_argnums=...)`` hands the donated argument's buffer
to XLA: after the call the caller-side array is DELETED, and touching
it raises ``RuntimeError: Array has been deleted`` — but only at run
time, only on backends that honor donation, and only on the code path
that actually reaches the stale read. The wire-format layer leans on
donation hard (ping-pong anchor tables, trainer state), so a refactor
that inserts a read between a donating dispatch and its rebind is
exactly the kind of bug that survives CPU-backend tests and detonates
on the TPU. R9 makes the discipline static:

1. **Wrapper discovery** — a donating callable is

   * a module-level ``NAME = jax.jit(fn, donate_argnums=...)``
     (``jax_eval.evaluate_packed_anchored_jit``),
   * a ``self._attr = jax.jit(self._method, donate_argnums=...)``
     bound in a method (``Trainer._step_jit``; the jitted callable
     wraps a BOUND method, so donated indices map straight onto call
     arguments with no ``self`` offset), or
   * a function decorated ``@functools.partial(jax.jit,
     donate_argnums=...)``.

2. **Call-site check** — at every call of a donating wrapper, each
   donated positional argument that is a plain name or a plain
   ``self.x`` attribute must be REBOUND (assigned, including the
   classic same-statement ``state = step(state, ...)``) before any
   later load in the function. A load first is a finding.

Only plain names and plain self-attributes are tracked — donated
subscripts like ``self._tabs[g]`` are the per-group ping-pong chains
whose rebind discipline is enforced dynamically by the eval chain (and
suppressed R4 sites document it); flagging them here would re-litigate
that contract with worse precision. Statement order is program-text
order, with one path fact honored: a donating call inside ``return``/
``raise`` ends its path, so text after it is a different branch. A
loop back-edge that re-reads a donated name ABOVE the call is out of
scope (documented limitation, same as R4's).

Like every rule here: purely syntactic, never imports analyzed code.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

from fishnet_tpu.analysis.engine import Finding, FuncInfo, Module, Project
from fishnet_tpu.analysis.rules import _walk_own_body

#: call heads that produce a donating wrapper when given donate_argnums.
_JIT_HEADS = ("jit",)  # matched against the LAST dotted segment


def _is_jit_call(call: ast.Call, mod: Module, imports: Dict[str, str]) -> bool:
    proj = Project()
    dotted = proj.resolve_dotted(call.func, imports or mod.imports)
    if dotted is None:
        return False
    return dotted.rpartition(".")[2] in _JIT_HEADS


def _donated_indices(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """donate_argnums as a tuple of ints, or None when absent/opaque."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                    return None
            return tuple(elt.value for elt in v.elts)
        return None
    return None


def _partial_jit_decorator(dec: ast.AST, mod: Module) -> Optional[Tuple[int, ...]]:
    """``@functools.partial(jax.jit, donate_argnums=...)`` -> indices."""
    if not (isinstance(dec, ast.Call) and dec.args):
        return None
    proj = Project()
    head = proj.resolve_dotted(dec.func, mod.imports)
    if head is None or head.rpartition(".")[2] != "partial":
        return None
    inner = dec.args[0]
    inner_dotted = proj.resolve_dotted(inner, mod.imports)
    if inner_dotted is None or inner_dotted.rpartition(".")[2] not in _JIT_HEADS:
        return None
    return _donated_indices(dec)


@dataclass(frozen=True)
class _Wrapper:
    """One donating callable and where it lives."""

    donated: Tuple[int, ...]
    line: int


def _expr_key(node: ast.AST) -> Optional[str]:
    """Stable key for the argument shapes we track: ``name`` or
    ``self.attr``. Anything else (subscripts, calls, chains) -> None."""
    if isinstance(node, ast.Name):
        return node.id
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return "self." + node.attr
    return None


class DonationSafetyRule:
    id = "R9"
    name = "donation-safety"
    description = (
        "an array passed at a donate_argnums position is deleted by the "
        "call; it must be rebound before any later use"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules.values():
            # wrappers addressable as module-level names, per module
            mod_wrappers: Dict[str, _Wrapper] = {}
            # wrappers addressable as self.<attr>, per class
            attr_wrappers: Dict[str, Dict[str, _Wrapper]] = {}
            self._collect_wrappers(mod, mod_wrappers, attr_wrappers)
            # Donating names imported from sibling modules resolve too:
            # "from ..nnue.jax_eval import evaluate_packed_anchored_jit".
            for alias, dotted in mod.imports.items():
                src_mod, _, src_name = dotted.rpartition(".")
                src = project.modules.get(src_mod)
                if src is None or alias in mod_wrappers:
                    continue
                w = self._module_wrapper_in(src, src_name)
                if w is not None:
                    mod_wrappers[alias] = w
            if not mod_wrappers and not attr_wrappers:
                continue
            for info in mod.functions.values():
                yield from self._check_function(
                    mod, info, mod_wrappers, attr_wrappers
                )

    # -- wrapper discovery ------------------------------------------------

    def _collect_wrappers(
        self,
        mod: Module,
        mod_wrappers: Dict[str, _Wrapper],
        attr_wrappers: Dict[str, Dict[str, _Wrapper]],
    ) -> None:
        for stmt in mod.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
                and _is_jit_call(stmt.value, mod, mod.imports)
            ):
                donated = _donated_indices(stmt.value)
                if donated:
                    mod_wrappers[stmt.targets[0].id] = _Wrapper(
                        donated, stmt.lineno
                    )
        for info in mod.functions.values():
            if isinstance(info.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in info.node.decorator_list:
                    donated = _partial_jit_decorator(dec, mod)
                    if donated and info.class_name is None:
                        mod_wrappers[info.node.name] = _Wrapper(
                            donated, info.node.lineno
                        )
            if info.class_name is None:
                continue
            for node in _walk_own_body(info.node):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                    and _is_jit_call(node.value, mod, info.imports)
                ):
                    continue
                key = _expr_key(node.targets[0])
                if key is None or not key.startswith("self."):
                    continue
                donated = _donated_indices(node.value)
                if donated:
                    attr_wrappers.setdefault(info.class_name, {})[
                        key[len("self.") :]
                    ] = _Wrapper(donated, node.lineno)

    def _module_wrapper_in(self, mod: Module, name: str) -> Optional[_Wrapper]:
        for stmt in mod.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == name
                and isinstance(stmt.value, ast.Call)
                and _is_jit_call(stmt.value, mod, mod.imports)
            ):
                donated = _donated_indices(stmt.value)
                if donated:
                    return _Wrapper(donated, stmt.lineno)
        return None

    # -- call-site check --------------------------------------------------

    def _check_function(
        self,
        mod: Module,
        info: FuncInfo,
        mod_wrappers: Dict[str, _Wrapper],
        attr_wrappers: Dict[str, Dict[str, _Wrapper]],
    ) -> Iterator[Finding]:
        class_attrs = attr_wrappers.get(info.class_name or "", {})
        # A call syntactically inside a `return`/`raise` ends its path:
        # any later load in the function is on a DIFFERENT branch (the
        # two-branch `return self._step_jit(state, batch)` mesh/no-mesh
        # shape in the trainers), so those calls are exempt.
        terminal_calls = set()
        for node in _walk_own_body(info.node):
            if isinstance(node, (ast.Return, ast.Raise)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        terminal_calls.add(id(sub))
        # One linear pass collecting every donating call and every
        # load/store of a tracked key, all in source order.
        calls: List[Tuple[int, str, str]] = []  # (line, arg key, callee)
        loads: List[Tuple[int, int, str]] = []  # (line, col, key)
        stores: List[Tuple[int, str]] = []  # (line, key)
        for node in _walk_own_body(info.node):
            if isinstance(node, ast.Call) and id(node) not in terminal_calls:
                w = self._wrapper_of(node.func, mod_wrappers, class_attrs)
                if w is not None:
                    wrapper, callee = w
                    for idx in wrapper.donated:
                        if idx >= len(node.args):
                            continue
                        key = _expr_key(node.args[idx])
                        if key is not None:
                            calls.append((node.lineno, key, callee))
            if isinstance(node, (ast.Name, ast.Attribute)):
                key = _expr_key(node)
                if key is None:
                    continue
                if isinstance(node.ctx, ast.Store):
                    stores.append((node.lineno, key))
                elif isinstance(node.ctx, ast.Load):
                    loads.append((node.lineno, node.col_offset, key))
        for call_line, key, callee in calls:
            rebind = min(
                (ln for ln, k in stores if k == key and ln >= call_line),
                default=None,
            )
            for ln, col, k in loads:
                if k != key or ln <= call_line:
                    continue
                if rebind is not None and rebind <= ln:
                    break  # rebound first — the chain is ping-ponged
                yield Finding(
                    rule=self.id,
                    path=str(mod.path),
                    line=ln,
                    col=col,
                    message=(
                        f"`{key}` was donated to `{callee}` on line "
                        f"{call_line} (donate_argnums) and read again "
                        "before being rebound; the buffer is deleted "
                        "after the call"
                    ),
                    suggestion=(
                        "rebind the name from the call's result (ping-"
                        "pong) before any further use, or drop it from "
                        "donate_argnums"
                    ),
                )
                break  # one finding per donated arg per call
        return

    def _wrapper_of(
        self,
        func: ast.AST,
        mod_wrappers: Dict[str, _Wrapper],
        class_attrs: Dict[str, _Wrapper],
    ) -> Optional[Tuple[_Wrapper, str]]:
        if isinstance(func, ast.Name) and func.id in mod_wrappers:
            return mod_wrappers[func.id], func.id
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and func.attr in class_attrs
        ):
            return class_attrs[func.attr], "self." + func.attr
        return None
