"""The four project rules (R1-R4). See package docstring and
doc/static-analysis.md for rationale and worked examples.

All rules operate on the indexed :class:`~fishnet_tpu.analysis.engine.
Project`; none import the code under analysis.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from fishnet_tpu.analysis.engine import Finding, FuncInfo, Module, Project

# ---------------------------------------------------------------------------
# R1: blocking calls inside async def bodies
# ---------------------------------------------------------------------------

#: Fully-resolved callables that block the event loop.
_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.getoutput",
    "subprocess.getstatusoutput",
    "socket.create_connection",
    "socket.getaddrinfo",
    "socket.gethostbyname",
}

#: Module prefixes whose every call is synchronous network I/O.
_BLOCKING_PREFIXES = ("requests.", "urllib.request.")

#: Attribute calls that block unless awaited (asyncio's subprocess API
#: has awaitable twins of both).
_BLOCKING_METHODS = {"communicate"}


class AsyncBlockingRule:
    id = "R1"
    name = "async-blocking"

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules.values():
            for info in mod.functions.values():
                if not info.is_async:
                    continue
                yield from self._check_async_body(project, mod, info)

    def _check_async_body(
        self, project: Project, mod: Module, info: FuncInfo
    ) -> Iterator[Finding]:
        # Walk the async body but NOT nested sync defs/lambdas: those are
        # values, typically shipped to executors (asyncio.to_thread),
        # where blocking is the point.  Awaited calls are exempt from the
        # method-name heuristic (asyncio's communicate/wait are fine).
        awaited: Set[int] = set()
        for node in _walk_own_body(info.node):
            if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
                awaited.add(id(node.value))
        for node in _walk_own_body(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = project.resolve_dotted(node.func, info.imports)
            if dotted and (
                dotted in _BLOCKING_CALLS
                or dotted.startswith(_BLOCKING_PREFIXES)
            ):
                yield Finding(
                    rule=self.id,
                    path=str(mod.path),
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"blocking call `{dotted}` inside async function "
                        f"`{info.qualname}` stalls the event loop (and with "
                        "it every worker's pull loop)"
                    ),
                    suggestion=(
                        "use the asyncio equivalent (asyncio.sleep, "
                        "asyncio.create_subprocess_exec, aiohttp) or ship it "
                        "off-loop via asyncio.to_thread(...)"
                    ),
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS
                and id(node) not in awaited
            ):
                yield Finding(
                    rule=self.id,
                    path=str(mod.path),
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"un-awaited `.{node.func.attr}()` inside async "
                        f"function `{info.qualname}` — on a subprocess this "
                        "blocks the event loop until the child exits"
                    ),
                    suggestion=(
                        "use asyncio.create_subprocess_exec and `await "
                        "proc.communicate()`"
                    ),
                )


def _walk_own_body(func_node: ast.AST) -> Iterator[ast.AST]:
    """Yield nodes of a function body without descending into nested
    function definitions or lambdas (they execute in their own context)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _nested_defs(func_node: ast.AST) -> Iterator[ast.AST]:
    """Function definitions nested directly in a function's own body
    (not inside deeper nested defs or lambdas) — the shape R2 needs to
    see `@pl.when`-decorated kernel regions."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
            continue
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# R2: host sync inside jit-traced code paths
# ---------------------------------------------------------------------------

#: Callables that wrap a function for tracing; their first argument (after
#: unwrapping nested wrappers / functools.partial) becomes a trace root.
_JIT_WRAPPERS = {
    "jax.jit",
    "jax.pjit",
    "pjit",
    "jax.experimental.pjit.pjit",
    "jax.shard_map",
    "shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.pallas.pallas_call",
}

_PARTIAL = {"functools.partial", "partial"}

#: Resolved callables that force a device->host sync / concretization.
_HOST_SYNC_CALLS = {
    "numpy.asarray",
    "numpy.array",
    "numpy.ascontiguousarray",
    "jax.device_get",
}

#: Concreteness guards: an `if` whose test calls one of these is a
#: deliberate host-only region (executed at trace time on concrete
#: inputs only) — its subtree is exempt from R2.  `isinstance` qualifies
#: because branching on Python types can never branch on traced VALUES.
_CONCRETENESS_GUARDS = {"is_concrete", "is_tracer", "isinstance", "is_concrete_array"}

#: Decorators that EXECUTE the decorated nested def under the enclosing
#: trace (``@pl.when(cond)`` immediately traces the body as the
#: predicated region of the surrounding Pallas kernel).  A nested def
#: carrying one of these is a call-graph edge from its enclosing
#: function — the fused-PSQT kernel's reduce paths live in exactly such
#: defs, and without the edge R2 never scanned them.
_TRACED_DECORATORS = {
    "jax.experimental.pallas.when",
    "jax.experimental.pallas.tpu.when",
}


class JitHostSyncRule:
    id = "R2"
    name = "jit-host-sync"

    def check(self, project: Project) -> Iterator[Finding]:
        roots = self._find_roots(project)
        reachable = self._reachable(project, roots)
        for info, root in reachable.items():
            yield from self._scan(project, info, root)

    # -- root discovery ---------------------------------------------------

    def _find_roots(self, project: Project) -> Dict[FuncInfo, str]:
        roots: Dict[FuncInfo, str] = {}
        for mod in project.modules.values():
            # Decorators.
            for info in mod.functions.values():
                for dec in getattr(info.node, "decorator_list", []):
                    if self._is_jit_wrapper(project, dec, info.imports):
                        roots.setdefault(info, info.qualname)
            # jax.jit(f) call sites anywhere in the module.
            for info in mod.functions.values():
                for node in _walk_own_body(info.node):
                    self._roots_from_call(project, mod, info, node, roots)
            # Module-level statements (evaluate_batch_jit = jax.jit(...)).
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    self._roots_from_call(project, mod, None, node, roots)
        return roots

    def _roots_from_call(self, project, mod, info, node, roots) -> None:
        if not isinstance(node, ast.Call):
            return
        imports = info.imports if info is not None else mod.imports
        dotted = project.resolve_dotted(node.func, imports)
        if dotted is None or dotted not in _JIT_WRAPPERS:
            return
        target = self._unwrap(project, node, imports)
        if target is None:
            return
        fi = self._resolve_func_ref(project, mod, info, target)
        if fi is not None:
            roots.setdefault(fi, fi.qualname)

    def _unwrap(self, project, call: ast.Call, imports) -> Optional[ast.AST]:
        """First positional arg, unwrapping nested wrapper/partial calls."""
        if not call.args:
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Call):
            dotted = project.resolve_dotted(arg.func, imports)
            if dotted in _JIT_WRAPPERS or dotted in _PARTIAL:
                return self._unwrap(project, arg, imports)
            return None
        return arg

    def _is_jit_wrapper(self, project, dec: ast.AST, imports) -> bool:
        if isinstance(dec, ast.Call):
            dotted = project.resolve_dotted(dec.func, imports)
            if dotted in _JIT_WRAPPERS:
                return True
            if dotted in _PARTIAL and dec.args:
                inner = project.resolve_dotted(dec.args[0], imports)
                return inner in _JIT_WRAPPERS
            return False
        dotted = project.resolve_dotted(dec, imports)
        return dotted in _JIT_WRAPPERS

    def _resolve_func_ref(
        self, project: Project, mod: Module, info: Optional[FuncInfo], node: ast.AST
    ) -> Optional[FuncInfo]:
        """Resolve a function REFERENCE (not call): bare name, nested def,
        self.method, or imported project function.  Bare names search the
        lexical scope chain — a sibling nested def (``reduce_sparse``
        called from a ``def _():`` under ``pl.when``) lives in the
        ENCLOSING function's locals, not the caller's own."""
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if node.value.id == "self" and info is not None and info.class_name:
                methods = mod.classes.get(info.class_name, {})
                qual = methods.get(node.attr)
                if qual:
                    return mod.functions.get(qual)
        imports = info.imports if info is not None else mod.imports
        dotted = project.resolve_dotted(node, imports)
        if dotted is None:
            return None
        if info is not None:
            qn: Optional[str] = info.qualname
            while qn is not None:
                scope = mod.functions.get(qn)
                if scope is not None and dotted in scope.locals_:
                    return mod.functions.get(scope.locals_[dotted])
                qn, sep, _ = qn.rpartition(".<locals>.")
                if not sep:
                    qn = None
        return project.find_function(dotted, mod)

    # -- reachability -----------------------------------------------------

    def _reachable(
        self, project: Project, roots: Dict[FuncInfo, str]
    ) -> Dict[FuncInfo, str]:
        seen: Dict[FuncInfo, str] = {}
        stack = [(info, root) for info, root in roots.items()]
        while stack:
            info, root = stack.pop()
            if info in seen:
                continue
            seen[info] = root
            for callee in self._callees(project, info):
                if callee not in seen:
                    stack.append((callee, root))
        return seen

    def _callees(self, project: Project, info: FuncInfo) -> Iterable[FuncInfo]:
        mod = info.module
        for node in _walk_own_body(info.node):
            if not isinstance(node, ast.Call):
                continue
            # Calls to concreteness guards are host-side by definition:
            # they never trace, so they create no edge.
            dotted = project.resolve_dotted(node.func, info.imports)
            if dotted and dotted.rpartition(".")[2] in _CONCRETENESS_GUARDS:
                continue
            fi = self._resolve_func_ref(project, mod, info, node.func)
            if fi is not None:
                yield fi
            # Function REFERENCES passed as arguments also trace: jax.grad
            # /value_and_grad/vmap/lax.scan bodies, functools.partial, the
            # kernel handed to pallas_call — any of them may run under the
            # caller's trace.  Lambdas passed as arguments run there too
            # (``both_modes(pos, lambda lim, sp: transfer(...))`` in the
            # fused gather kernel): resolve the calls their bodies make.
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    fa = self._resolve_func_ref(project, mod, info, arg)
                    if fa is not None:
                        yield fa
                elif isinstance(arg, ast.Lambda):
                    for sub in ast.walk(arg.body):
                        if isinstance(sub, ast.Call):
                            fa = self._resolve_func_ref(
                                project, mod, info, sub.func
                            )
                            if fa is not None:
                                yield fa
        # Nested defs under a tracing decorator execute as part of THIS
        # function's trace (`@pl.when(cond)` applies the body to the
        # kernel's predicated region at definition time): edge to each.
        for nested in _nested_defs(info.node):
            for dec in nested.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if project.resolve_dotted(
                    target, info.imports
                ) in _TRACED_DECORATORS:
                    fn = self._func_info_for_node(mod, nested)
                    if fn is not None:
                        yield fn
                    break

    @staticmethod
    def _func_info_for_node(mod: Module, node: ast.AST) -> Optional[FuncInfo]:
        for fi in mod.functions.values():
            if fi.node is node:
                return fi
        return None

    # -- violation scan ---------------------------------------------------

    def _scan(
        self, project: Project, info: FuncInfo, root: str
    ) -> Iterator[Finding]:
        mod = info.module
        via = "" if root == info.qualname else f" (reachable from jit root `{root}`)"
        for node in self._walk_unguarded(info.node):
            if isinstance(node, ast.Call):
                dotted = project.resolve_dotted(node.func, info.imports)
                if dotted in _HOST_SYNC_CALLS:
                    yield Finding(
                        rule=self.id,
                        path=str(mod.path),
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"host-synchronizing call `{dotted}` in jit-"
                            f"traced `{info.qualname}`{via} — under tracing "
                            "this raises TracerArrayConversionError at best, "
                            "or silently concretizes at trace time"
                        ),
                        suggestion=(
                            "keep device values in jnp; if a concrete-input "
                            "fast path is intended, guard it with "
                            "fishnet_tpu.utils.tracing.is_concrete(x)"
                        ),
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    yield Finding(
                        rule=self.id,
                        path=str(mod.path),
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`.item()` in jit-traced `{info.qualname}`{via} "
                            "forces a device->host sync and fails under "
                            "tracing"
                        ),
                        suggestion="keep the value as a traced array",
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("float", "int", "bool")
                    and node.args
                    and not _is_static_expr(node.args[0])
                    and _has_bare_value_name(node.args[0], info.imports)
                ):
                    yield Finding(
                        rule=self.id,
                        path=str(mod.path),
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{node.func.id}(...)` on a non-static value in "
                            f"jit-traced `{info.qualname}`{via} concretizes "
                            "the operand (TracerArrayConversionError under "
                            "tracing)"
                        ),
                        suggestion=(
                            "use jnp casts (x.astype(...)) or guard the host "
                            "path with is_concrete(x)"
                        ),
                    )
            elif isinstance(node, (ast.If, ast.While)):
                if _test_branches_on_array(node.test):
                    yield Finding(
                        rule=self.id,
                        path=str(mod.path),
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            "Python branch on array truthiness in jit-traced "
                            f"`{info.qualname}`{via} — the trace bakes in one "
                            "side of the branch"
                        ),
                        suggestion="use jnp.where / jax.lax.cond",
                    )

    def _walk_unguarded(self, func_node: ast.AST) -> Iterator[ast.AST]:
        """Like _walk_own_body but skips `if` subtrees whose test is a
        concreteness guard (host-only regions by construction)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(func_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.If) and _is_concreteness_guard(node.test):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


def _is_concreteness_guard(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            attr = None
            if isinstance(node.func, ast.Name):
                attr = node.func.id
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
            if attr in _CONCRETENESS_GUARDS:
                return True
    return False


def _is_static_expr(node: ast.AST) -> bool:
    """Expressions that are static under tracing: literals, len(), and
    anything derived from `.shape`/`.ndim`/`.dtype`/`.size` attributes."""
    if isinstance(node, ast.Constant):
        return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
            "shape",
            "ndim",
            "dtype",
            "size",
        ):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) and sub.func.id == "len":
            return True
    return False


def _has_bare_value_name(node: ast.AST, imports: Dict[str, str]) -> bool:
    """True when the expression mentions a bare value name — a Name that
    is neither the object of an attribute access (``cfg.l1`` is config,
    not data), the callee of a call, nor a module alias.  This is what
    separates ``bool(parent.any())`` (traced data) from
    ``float(np.sqrt(1.0 / cfg.l1))`` (static config math)."""
    skip: Set[int] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name):
            skip.add(id(sub.value))
        elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
            skip.add(id(sub.func))
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Name)
            and id(sub) not in skip
            and sub.id not in imports
            and sub.id not in ("len", "min", "max", "sum", "abs", "range")
        ):
            return True
    return False


def _test_branches_on_array(test: ast.AST) -> bool:
    """Heuristic: a branch condition that calls .any()/.all() or bool()
    on a non-static expression is branching on array truthiness."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("any", "all")
                and not node.args
            ):
                return True
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "bool"
                and node.args
                and not _is_static_expr(node.args[0])
            ):
                return True
    return False


# ---------------------------------------------------------------------------
# R3: deprecated / private JAX API
# ---------------------------------------------------------------------------


class DeprecatedJaxRule:
    id = "R3"
    name = "deprecated-jax"

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules.values():
            # Imports of private modules.
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ImportFrom):
                    if node.module and node.module.startswith("jax._src"):
                        yield self._finding(
                            mod,
                            node,
                            f"import from private module `{node.module}`",
                            "jax._src has no stability guarantees; import "
                            "the public equivalent (jax., jax.extend., "
                            "jax.experimental.)",
                        )
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name.startswith("jax._src"):
                            yield self._finding(
                                mod,
                                node,
                                f"import of private module `{alias.name}`",
                                "use the public equivalent",
                            )
            # Attribute uses, resolved through the import tables.
            scopes = [(None, mod.imports)] + [
                (info, info.imports) for info in mod.functions.values()
            ]
            seen: set = set()
            for info, imports in scopes:
                body = (
                    _walk_own_body(info.node)
                    if info is not None
                    else _walk_module_level(mod.tree)
                )
                for node in body:
                    if not isinstance(node, (ast.Attribute, ast.Name)):
                        continue
                    dotted = project.resolve_dotted(node, imports)
                    if dotted is None or id(node) in seen:
                        continue
                    if dotted == "jax.core.Tracer" or dotted.endswith(
                        ".core.Tracer"
                    ):
                        seen.add(id(node))
                        yield self._finding(
                            mod,
                            node,
                            "use of deprecated `jax.core.Tracer`",
                            "replace isinstance(x, jax.core.Tracer) checks "
                            "with fishnet_tpu.utils.tracing.is_concrete(x) "
                            "(backed by jax.core.is_concrete on jax 0.4.x)",
                        )
                    elif dotted.startswith("jax._src"):
                        seen.add(id(node))
                        yield self._finding(
                            mod,
                            node,
                            f"use of private API `{dotted}`",
                            "use the public equivalent",
                        )

    def _finding(self, mod: Module, node: ast.AST, msg: str, hint: str) -> Finding:
        return Finding(
            rule=self.id,
            path=str(mod.path),
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=msg,
            suggestion=hint,
        )


def _walk_module_level(tree: ast.Module) -> Iterator[ast.AST]:
    """Module statements without descending into function/class bodies
    (those are covered per-function)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# R4: cross-thread shared-state heuristics
# ---------------------------------------------------------------------------

#: Method calls that mutate their receiver.
_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "clear",
    "discard",
    "extend",
    "insert",
    "pop",
    "popleft",
    "remove",
    "setdefault",
    "update",
}


class CrossThreadStateRule:
    id = "R4"
    name = "cross-thread-state"

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules.values():
            for cls, methods in mod.classes.items():
                yield from self._check_class(project, mod, cls, methods)
            yield from self._check_module_globals(project, mod)

    # -- instance state ---------------------------------------------------

    def _check_class(
        self, project: Project, mod: Module, cls: str, methods: Dict[str, str]
    ) -> Iterator[Finding]:
        infos = {
            name: mod.functions[q] for name, q in methods.items() if q in mod.functions
        }
        if not infos:
            return
        thread_roots = self._thread_roots(project, mod, infos)
        if not thread_roots:
            return
        thread_closure = self._closure(infos, thread_roots)
        other = {
            name
            for name in infos
            if name not in thread_closure and name != "__init__"
        }
        # attr -> list of (method, line, guarded)
        thread_mut: Dict[str, List[Tuple[str, int, bool]]] = {}
        other_mut: Dict[str, List[Tuple[str, int, bool]]] = {}
        for name, info in infos.items():
            for attr, line, guarded in self._mutations(info):
                if name in thread_closure:
                    thread_mut.setdefault(attr, []).append((name, line, guarded))
                if name in other:
                    other_mut.setdefault(attr, []).append((name, line, guarded))
        for attr in sorted(set(thread_mut) & set(other_mut)):
            sites = thread_mut[attr] + other_mut[attr]
            unguarded = [s for s in sites if not s[2]]
            if not unguarded:
                continue
            name, line, _ = unguarded[0]
            others = ", ".join(
                sorted({f"{n}:{ln}" for n, ln, _ in sites if (n, ln) != (name, line)})
            )
            yield Finding(
                rule=self.id,
                path=str(mod.path),
                line=line,
                col=0,
                message=(
                    f"`self.{attr}` of `{cls}` is mutated from both a driver "
                    f"thread and event-loop/async methods, and the mutation "
                    f"in `{name}` holds no lock (other sites: {others})"
                ),
                suggestion=(
                    "guard every mutation with the instance lock (`with "
                    "self._lock:`) or hand the update through a queue"
                ),
            )

    def _thread_roots(
        self, project: Project, mod: Module, infos: Dict[str, FuncInfo]
    ) -> Set[str]:
        """Methods passed as Thread(target=self.X) / to_thread(self.X) /
        run_in_executor(_, self.X) anywhere in the class."""
        roots: Set[str] = set()
        for info in infos.values():
            for node in _walk_own_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = project.resolve_dotted(node.func, info.imports) or ""
                candidates: List[ast.AST] = []
                if dotted.endswith("Thread"):
                    candidates += [
                        kw.value for kw in node.keywords if kw.arg == "target"
                    ]
                elif dotted.endswith(("to_thread",)):
                    candidates += node.args[:1]
                elif isinstance(node.func, ast.Attribute) and node.func.attr == (
                    "run_in_executor"
                ):
                    candidates += node.args[1:2]
                for cand in candidates:
                    if (
                        isinstance(cand, ast.Attribute)
                        and isinstance(cand.value, ast.Name)
                        and cand.value.id == "self"
                        and cand.attr in infos
                    ):
                        roots.add(cand.attr)
        return roots

    def _closure(self, infos: Dict[str, FuncInfo], roots: Set[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            name = stack.pop()
            if name in seen or name not in infos:
                continue
            seen.add(name)
            for node in _walk_own_body(infos[name].node):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in infos
                ):
                    stack.append(node.func.attr)
        return seen

    def _mutations(self, info: FuncInfo) -> Iterator[Tuple[str, int, bool]]:
        """(attr, line, guarded) for every `self.attr` mutation: plain /
        aug / subscript assignment, and mutating method calls.  `guarded`
        = lexically inside `with self.<something-lockish>:`."""
        guarded_spans = self._lock_spans(info.node)

        def is_guarded(line: int) -> bool:
            return any(lo <= line <= hi for lo, hi in guarded_spans)

        for node in _walk_own_body(info.node):
            attr = None
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    attr = _self_attr_of_target(t)
                    if attr:
                        yield attr, node.lineno, is_guarded(node.lineno)
                continue
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                if node.func.attr in _MUTATORS:
                    attr = _self_attr_of_target(node.func.value)
                    if attr:
                        yield attr, node.lineno, is_guarded(node.lineno)

    def _lock_spans(self, func_node: ast.AST) -> List[Tuple[int, int]]:
        spans: List[Tuple[int, int]] = []
        for node in _walk_own_body(func_node):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                expr = item.context_expr
                # `with self._lock:` or `with self._lock.acquire…` etc.
                # (`with open(...)` has a Name func — no attr to inspect.)
                if isinstance(expr, ast.Call):
                    if not isinstance(expr.func, ast.Attribute):
                        continue
                    target = expr.func.value
                else:
                    target = expr
                attr = _self_attr_of_target(target)
                if attr and ("lock" in attr.lower() or "mutex" in attr.lower()):
                    end = getattr(node, "end_lineno", node.lineno)
                    spans.append((node.lineno, end))
                    break
        return spans

    # -- module globals ---------------------------------------------------

    def _check_module_globals(self, project: Project, mod: Module) -> Iterator[Finding]:
        """Module-level names rebound (via `global`) both from a function
        that is a thread target and from an async function."""
        thread_fns: Set[str] = set()
        for info in mod.functions.values():
            for node in _walk_own_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = project.resolve_dotted(node.func, info.imports) or ""
                if dotted.endswith("Thread"):
                    for kw in node.keywords:
                        if kw.arg == "target" and isinstance(kw.value, ast.Name):
                            thread_fns.add(kw.value.id)

        def global_writes(info: FuncInfo) -> Dict[str, int]:
            declared: Set[str] = set()
            for node in _walk_own_body(info.node):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            writes: Dict[str, int] = {}
            for node in _walk_own_body(info.node):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id in declared:
                            writes.setdefault(t.id, node.lineno)
            return writes

        thread_writes: Dict[str, Tuple[str, int]] = {}
        async_writes: Dict[str, Tuple[str, int]] = {}
        for info in mod.functions.values():
            w = global_writes(info)
            base = info.qualname.split(".")[0]
            if base in thread_fns or info.qualname in thread_fns:
                for name, line in w.items():
                    thread_writes.setdefault(name, (info.qualname, line))
            if info.is_async:
                for name, line in w.items():
                    async_writes.setdefault(name, (info.qualname, line))
        for name in sorted(set(thread_writes) & set(async_writes)):
            fn, line = thread_writes[name]
            ofn, oline = async_writes[name]
            yield Finding(
                rule=self.id,
                path=str(mod.path),
                line=line,
                col=0,
                message=(
                    f"module global `{name}` is rebound from thread target "
                    f"`{fn}` and async function `{ofn}` (line {oline}) "
                    "without synchronization"
                ),
                suggestion="protect with a lock or pass through a queue",
            )


def _self_attr_of_target(node: ast.AST) -> Optional[str]:
    """`self.x`, `self.x[...]`, `self.x.y` → "x"; else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        inner = node
        while isinstance(inner.value, ast.Attribute):
            inner = inner.value
        if isinstance(inner.value, ast.Name) and inner.value.id == "self":
            return inner.attr
    return None


# ---------------------------------------------------------------------------
# R5: swallowed exceptions in the serving layers
# ---------------------------------------------------------------------------

#: Resolved exception names that are "broad": catching one of these (or
#: a bare ``except:``) without making the failure observable hides real
#: outages from the recovery machinery and the telemetry plane.
_BROAD_EXCEPTIONS = {
    "Exception",
    "BaseException",
    "builtins.Exception",
    "builtins.BaseException",
}


class SwallowedExceptionRule:
    """Broad ``except`` handlers in the serving layers (``net/``,
    ``sched/``, ``search/``) must make the failure observable: either
    re-raise, increment a telemetry counter (``.inc(...)``), or
    propagate the exception as a value (``return err`` /
    ``set_exception(err)``). Logging alone is NOT enough — log lines
    are invisible to the metrics plane the resilience subsystem (and
    any alerting built on it) watches. Narrow handlers (specific
    exception types) are exempt: catching what you expect is handling,
    not swallowing."""

    id = "R5"
    name = "swallowed-exception"

    #: Serving-layer module prefixes this rule polices. Stand-alone
    #: files (no package anchor — the test fixtures) are always in
    #: scope so the rule itself stays testable.
    _SCOPES = ("fishnet_tpu.net", "fishnet_tpu.sched", "fishnet_tpu.search")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules.values():
            if "." in mod.name and not (
                mod.name in self._SCOPES
                or mod.name.startswith(tuple(s + "." for s in self._SCOPES))
            ):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not self._is_broad(project, mod, node):
                    continue
                if self._is_observable(node):
                    continue
                caught = (
                    "bare except" if node.type is None
                    else f"except {ast.unparse(node.type)}"
                )
                yield Finding(
                    rule=self.id,
                    path=str(mod.path),
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"`{caught}` swallows the failure: the handler "
                        "neither re-raises, increments a telemetry "
                        "counter, nor propagates the exception as a value"
                    ),
                    suggestion=(
                        "narrow the exception type, `raise`, count it "
                        "(`<counter>.inc(...)`), or hand it on (`return "
                        "err` / `future.set_exception(err)`); justified "
                        "suppressions: `# fishnet: ignore[R5] -- why`"
                    ),
                )

    def _is_broad(self, project: Project, mod: Module, node) -> bool:
        if node.type is None:
            return True
        types = (
            node.type.elts if isinstance(node.type, ast.Tuple) else [node.type]
        )
        for t in types:
            dotted = project.resolve_dotted(t, mod.imports)
            if dotted in _BROAD_EXCEPTIONS:
                return True
        return False

    def _is_observable(self, handler) -> bool:
        """The handler body (nested defs excluded — they don't run here)
        makes the failure observable."""
        name = handler.name
        for node in _walk_own_stmts(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                # A telemetry counter increment, or propagation into a
                # future the caller is awaiting.
                if node.func.attr in ("inc", "set_exception"):
                    return True
            if (
                name is not None
                and isinstance(node, ast.Return)
                and isinstance(node.value, ast.Name)
                and node.value.id == name
            ):
                return True  # `return err`: propagation by value
        return False


def _walk_own_stmts(handler) -> Iterator[ast.AST]:
    """Walk an except handler's body without descending into nested
    function definitions or lambdas."""
    stack: List[ast.AST] = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# R6-R9 live in their own modules (lock graphs, doc/registry contracts,
# and donation tracking each deserve a file) but build on the resolution
# spine above, so they import THIS module. Importing them down here —
# after every shared helper and rule class is defined — keeps the
# one-stop ALL_RULES registry without an import cycle: any entry into
# the package runs fishnet_tpu.analysis.__init__ first, which imports
# this module before any sibling.
from fishnet_tpu.analysis.contracts import (  # noqa: E402
    EscapeHatchRule,
    TelemetryContractRule,
)
from fishnet_tpu.analysis.donation import DonationSafetyRule  # noqa: E402
from fishnet_tpu.analysis.locks import LockOrderRule  # noqa: E402

ALL_RULES = [
    AsyncBlockingRule(),
    JitHostSyncRule(),
    DeprecatedJaxRule(),
    CrossThreadStateRule(),
    SwallowedExceptionRule(),
    LockOrderRule(),
    TelemetryContractRule(),
    EscapeHatchRule(),
    DonationSafetyRule(),
]
