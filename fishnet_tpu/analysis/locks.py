"""R6: static lock-order analysis over the whole serving plane.

The platform runs ~10 threads per process (driver / pack / decode /
frontend / profiler / fleet-aggregator) sharing the coalescer, the shard
router, the eval cache, the metrics registry, and the span rings. Every
one of those subsystems has its own lock, and the ONLY thing keeping
them deadlock-free is a consistent acquisition order that until now
lived in comments ("the router's lock is a leaf, never held while
calling out"). This module makes the order checkable:

1. **Lock discovery** — every ``threading.Lock/RLock/Condition`` bound
   to ``self.<attr>`` in a class body or to a module-level name gets a
   stable identity (``module.Class._attr``). A ``Condition(self._lock)``
   is an ALIAS of the lock it wraps (waking a ``with self._cond:`` is
   the same mutex as ``with self._lock:``).
2. **Type environment** — ``self.x = ClassName(...)`` assignments,
   annotated constructor parameters, and module-level instances give
   attribute chains like ``self._svc._router`` a class, so the lock an
   expression acquires resolves across modules (the same resolution
   spine R2/R4 use for the call graph).
3. **Held-lock walk** — each function is walked with the lexical
   ``with``-stack (plus ``acquire()``/``release()`` pairing); every
   acquisition and every resolvable call is recorded with the locks
   held at that point. Calls on attributes resolve through the type
   environment, including overrides in subclasses (the
   ``CoalesceBackend`` seam dispatches into both ``SearchService`` and
   ``AzDispatchPlane``).
4. **Graph** — transitive acquisition closures turn "call m while
   holding L" into edges L -> every lock m can take. Findings: cycles
   (potential deadlock), re-acquisition of a non-reentrant lock, and
   functions that reach the metrics-registry SCRAPE lock while holding
   any other lock. The scrape lock is special: ``collect()`` holds it
   across every registered collector callback, and those callbacks take
   project locks — so the scrape lock sits at the TOP of the canonical
   order, and acquiring it underneath anything else (an
   ``unregister_collector`` in a close path that still holds a service
   lock — the PR 13 exporter race family) inverts the order.

Thread entry points (``Thread(target=...)`` resolutions) are collected
so tests can assert the call graph actually follows the cross-thread
handoffs (driver -> coalescer -> pack worker -> backend dispatch).

Like every rule here: purely syntactic, never imports analyzed code.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from fishnet_tpu.analysis.engine import Finding, FuncInfo, Module, Project
from fishnet_tpu.analysis.rules import JitHostSyncRule, _walk_own_body

#: threading factories that create a mutex we track. asyncio.Lock is
#: deliberately absent: it lives on one event loop and cannot deadlock
#: against OS threads the way these can.
_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}

#: Lock ids whose attribute name matches this suffix are scrape locks —
#: held across collector callbacks by MetricsRegistry.collect().
_SCRAPE_SUFFIX = "_scrape_lock"

_R2 = JitHostSyncRule()  # reuse the call-graph resolution spine


@dataclass(frozen=True)
class Site:
    path: str
    line: int
    col: int
    func: str  # qualname of the function containing the event
    detail: str = ""


@dataclass
class _Event:
    kind: str  # "acquire" | "call"
    line: int
    col: int
    lock: Optional[str] = None  # acquire
    callee: Optional[FuncInfo] = None  # call
    held: Tuple[str, ...] = ()


@dataclass
class LockGraph:
    """The static lock-acquisition graph R6 checks and the doc table is
    generated from."""

    #: lock id -> kind ("Lock" / "RLock" / "Condition")
    locks: Dict[str, str] = field(default_factory=dict)
    #: (outer, inner) -> example site where inner is taken under outer
    edges: Dict[Tuple[str, str], Site] = field(default_factory=dict)
    #: FuncInfo -> description ("Thread target in <qualname>")
    entry_points: Dict[FuncInfo, str] = field(default_factory=dict)
    #: resolvable static call edges (virtual dispatch included)
    callees: Dict[FuncInfo, Set[FuncInfo]] = field(default_factory=dict)
    #: transitive lock-acquisition closure per function
    acquires: Dict[FuncInfo, Set[str]] = field(default_factory=dict)
    #: collector callbacks registered via register_collector(...)
    collectors: Set[FuncInfo] = field(default_factory=set)
    #: the scrape lock id in effect (None when no registry is in scope)
    scrape_lock: Optional[str] = None

    def reachable_from(self, func: FuncInfo) -> Set[FuncInfo]:
        seen: Set[FuncInfo] = set()
        stack = [func]
        while stack:
            fn = stack.pop()
            if fn in seen:
                continue
            seen.add(fn)
            stack.extend(self.callees.get(fn, ()))
        return seen


class _Analyzer:
    def __init__(self, project: Project):
        self.project = project
        # class key = "module.Class"
        self.class_defs: Dict[str, ast.ClassDef] = {}
        self.class_mod: Dict[str, Module] = {}
        self.bases: Dict[str, List[str]] = {}
        self.subclasses: Dict[str, List[str]] = {}
        self.class_locks: Dict[str, Dict[str, str]] = {}  # key -> attr -> id
        self.lock_kinds: Dict[str, str] = {}
        self.attr_types: Dict[str, Dict[str, str]] = {}  # key -> attr -> key
        self.module_locks: Dict[str, Dict[str, str]] = {}  # mod -> name -> id
        self.global_types: Dict[str, str] = {}  # "mod.NAME" -> class key
        self.events: Dict[FuncInfo, List[_Event]] = {}
        self.graph = LockGraph()

    # -- pass 1: classes, locks, types ------------------------------------

    def index(self) -> None:
        for mod in self.project.modules.values():
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    key = f"{mod.name}.{node.name}"
                    self.class_defs[key] = node
                    self.class_mod[key] = mod
            for stmt in mod.tree.body:
                self._module_level_assign(mod, stmt)
        for key, node in self.class_defs.items():
            mod = self.class_mod[key]
            resolved = []
            for base in node.bases:
                bk = self._class_key_of_expr(base, mod, mod.imports)
                if bk is not None:
                    resolved.append(bk)
                    self.subclasses.setdefault(bk, []).append(key)
            self.bases[key] = resolved
        for key in self.class_defs:
            self._index_class(key)

    def _module_level_assign(self, mod: Module, stmt: ast.stmt) -> None:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            return
        target = stmt.targets[0]
        if not isinstance(target, ast.Name) or not isinstance(
            stmt.value, ast.Call
        ):
            return
        dotted = self.project.resolve_dotted(stmt.value.func, mod.imports)
        if dotted in _LOCK_FACTORIES:
            lock_id = f"{mod.name}.{target.id}"
            self.module_locks.setdefault(mod.name, {})[target.id] = lock_id
            self.lock_kinds[lock_id] = _LOCK_FACTORIES[dotted]
            return
        key = self._class_key_of_dotted(dotted, mod)
        if key is not None:
            self.global_types[f"{mod.name}.{target.id}"] = key

    def _class_key_of_expr(
        self, node: ast.AST, mod: Module, imports: Dict[str, str]
    ) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            # String annotation: "CoalesceBackend".
            dotted = node.value.strip()
        else:
            dotted = self.project.resolve_dotted(node, imports)
        if dotted is None:
            return None
        return self._class_key_of_dotted(dotted, mod)

    def _class_key_of_dotted(
        self, dotted: Optional[str], mod: Module
    ) -> Optional[str]:
        if not dotted:
            return None
        if "." not in dotted:
            if dotted in mod.classes:
                return f"{mod.name}.{dotted}"
            return None
        mod_name, _, cls = dotted.rpartition(".")
        owner = self.project.modules.get(mod_name)
        if owner is not None and cls in owner.classes:
            return f"{mod_name}.{cls}"
        # Re-export hop: `from .registry import MetricsRegistry` in a
        # package __init__ the caller imported through.
        if owner is not None and cls in owner.imports:
            return self._class_key_of_dotted(owner.imports[cls], owner)
        return None

    def _index_class(self, key: str) -> None:
        mod = self.class_mod[key]
        cls = key.rpartition(".")[2]
        locks: Dict[str, str] = {}
        types: Dict[str, str] = {}
        cond_aliases: List[Tuple[str, str]] = []  # (attr, wrapped attr)
        for qual in mod.classes.get(cls, {}).values():
            info = mod.functions.get(qual)
            if info is None:
                continue
            params = self._param_types(info, mod)
            for node in _walk_own_body(info.node):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                target = node.targets[0]
                attr = _self_plain_attr(target)
                if attr is None:
                    continue
                value = node.value
                if isinstance(value, ast.Call):
                    dotted = self.project.resolve_dotted(
                        value.func, info.imports
                    )
                    if dotted in _LOCK_FACTORIES:
                        kind = _LOCK_FACTORIES[dotted]
                        if kind == "Condition" and value.args:
                            wrapped = _self_plain_attr(value.args[0])
                            if wrapped is not None:
                                cond_aliases.append((attr, wrapped))
                                continue
                        lock_id = f"{key}.{attr}"
                        locks[attr] = lock_id
                        self.lock_kinds[lock_id] = kind
                        continue
                    ck = self._class_key_of_dotted(dotted, mod)
                    if ck is not None:
                        types.setdefault(attr, ck)
                        continue
                if isinstance(value, ast.Name) and value.id in params:
                    types.setdefault(attr, params[value.id])
        for attr, wrapped in cond_aliases:
            if wrapped in locks:
                locks[attr] = locks[wrapped]  # alias: same mutex
            else:
                lock_id = f"{key}.{attr}"
                locks[attr] = lock_id
                self.lock_kinds[lock_id] = "Condition"
        self.class_locks[key] = locks
        self.attr_types[key] = types

    def _param_types(self, info: FuncInfo, mod: Module) -> Dict[str, str]:
        out: Dict[str, str] = {}
        args = getattr(info.node, "args", None)
        if args is None:
            return out
        for a in list(args.posonlyargs) + list(args.args) + list(
            args.kwonlyargs
        ):
            if a.annotation is not None:
                ck = self._class_key_of_expr(a.annotation, mod, info.imports)
                if ck is not None:
                    out[a.arg] = ck
        return out

    # -- lookup with inheritance ------------------------------------------

    def _lookup(
        self, table: Dict[str, Dict[str, str]], key: str, attr: str
    ) -> Optional[str]:
        seen: Set[str] = set()
        stack = [key]
        while stack:  # the class itself, then bases
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            hit = table.get(k, {}).get(attr)
            if hit is not None:
                return hit
            stack.extend(self.bases.get(k, ()))
        # Subclass fallback: annotations name the seam (CoalesceBackend)
        # while the state lives on the implementation (SearchService).
        for sub in sorted(self._all_subclasses(key)):
            hit = table.get(sub, {}).get(attr)
            if hit is not None:
                return hit
        return None

    def _all_subclasses(self, key: str) -> Set[str]:
        out: Set[str] = set()
        stack = list(self.subclasses.get(key, ()))
        while stack:
            k = stack.pop()
            if k in out:
                continue
            out.add(k)
            stack.extend(self.subclasses.get(k, ()))
        return out

    def _lock_of_attr(self, key: str, attr: str) -> Optional[str]:
        return self._lookup(self.class_locks, key, attr)

    def _type_of_attr(self, key: str, attr: str) -> Optional[str]:
        return self._lookup(self.attr_types, key, attr)

    # -- expression typing -------------------------------------------------

    def _object_type(
        self, node: ast.AST, info: FuncInfo, env: Dict[str, str]
    ) -> Optional[str]:
        if isinstance(node, ast.Name):
            if node.id == "self" and info.class_name is not None:
                return f"{info.module.name}.{info.class_name}"
            if node.id in env:
                return env[node.id]
            dotted = self.project.resolve_dotted(node, info.imports)
            return self._global_type(dotted)
        if isinstance(node, ast.Attribute):
            base = self._object_type(node.value, info, env)
            if base is not None:
                return self._type_of_attr(base, node.attr)
            dotted = self.project.resolve_dotted(node, info.imports)
            return self._global_type(dotted)
        return None

    def _global_type(self, dotted: Optional[str]) -> Optional[str]:
        for _ in range(5):  # follow re-export hops with bounded fuel
            if not dotted or "." not in dotted:
                return None
            if dotted in self.global_types:
                return self.global_types[dotted]
            mod_name, _, name = dotted.rpartition(".")
            owner = self.project.modules.get(mod_name)
            if owner is None or name not in owner.imports:
                return None
            dotted = owner.imports[name]
        return None

    def _resolve_lock(
        self, node: ast.AST, info: FuncInfo, env: Dict[str, str],
        lock_env: Dict[str, str],
    ) -> Optional[str]:
        if isinstance(node, ast.Name):
            if node.id in lock_env:
                return lock_env[node.id]
            dotted = self.project.resolve_dotted(node, info.imports)
            return self._module_lock(dotted, info.module)
        if isinstance(node, ast.Attribute):
            base = self._object_type(node.value, info, env)
            if base is not None:
                return self._lock_of_attr(base, node.attr)
            dotted = self.project.resolve_dotted(node, info.imports)
            return self._module_lock(dotted, info.module)
        return None

    def _module_lock(
        self, dotted: Optional[str], mod: Module
    ) -> Optional[str]:
        if not dotted:
            return None
        if "." not in dotted:
            return self.module_locks.get(mod.name, {}).get(dotted)
        mod_name, _, name = dotted.rpartition(".")
        return self.module_locks.get(mod_name, {}).get(name)

    # -- call resolution ---------------------------------------------------

    def _resolve_calls(
        self, func: ast.AST, info: FuncInfo, env: Dict[str, str]
    ) -> List[FuncInfo]:
        """Resolve a call target to project FuncInfos, virtual dispatch
        included: a method on a seam class resolves to the base def AND
        every subclass override."""
        out: List[FuncInfo] = []
        if isinstance(func, ast.Attribute):
            base = self._object_type(func.value, info, env)
            if base is not None:
                for key in [base] + sorted(self._all_subclasses(base)):
                    fn = self._method(key, func.attr)
                    if fn is not None and fn not in out:
                        out.append(fn)
                if out:
                    return out
        fn = _R2._resolve_func_ref(self.project, info.module, info, func)
        if fn is not None:
            out.append(fn)
        return out

    def _method(self, key: str, name: str) -> Optional[FuncInfo]:
        mod = self.class_mod.get(key)
        if mod is None:
            return None
        cls = key.rpartition(".")[2]
        seen: Set[str] = set()
        stack = [key]
        while stack:  # own method, then inherited defs
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            kmod = self.class_mod.get(k)
            if kmod is not None:
                qual = kmod.classes.get(k.rpartition(".")[2], {}).get(name)
                if qual is not None:
                    return kmod.functions.get(qual)
            stack.extend(self.bases.get(k, ()))
        del cls, mod
        return None

    # -- pass 2: per-function events ---------------------------------------

    def collect_events(self) -> None:
        for mod in self.project.modules.values():
            for info in mod.functions.values():
                self.events[info] = self._function_events(info)

    def _function_events(self, info: FuncInfo) -> List[_Event]:
        env = self._param_types(info, info.module)
        lock_env: Dict[str, str] = {}
        # Forward pre-pass: local aliases (`co = self._coalescer`,
        # `lk = self._lock`) and acquire()/release() line ranges.
        manual: List[Tuple[str, int, int]] = []
        pending: Dict[str, int] = {}
        end_line = getattr(info.node, "end_lineno", 10**9)
        for node in _walk_own_body(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    lk = self._resolve_lock(node.value, info, env, lock_env)
                    if lk is not None:
                        lock_env.setdefault(t.id, lk)
                    ty = self._object_type(node.value, info, env)
                    if ty is not None:
                        env.setdefault(t.id, ty)
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr in ("acquire", "release"):
                    lk = self._resolve_lock(
                        node.func.value, info, env, lock_env
                    )
                    if lk is None:
                        continue
                    if node.func.attr == "acquire":
                        pending.setdefault(lk, node.lineno)
                    elif lk in pending:
                        manual.append((lk, pending.pop(lk), node.lineno))
        for lk, start in pending.items():
            manual.append((lk, start, end_line))

        events: List[_Event] = []

        def held_at(line: int, lexical: Tuple[str, ...]) -> Tuple[str, ...]:
            extra = tuple(
                lk for lk, lo, hi in manual
                if lo < line <= hi and lk not in lexical
            )
            return lexical + extra

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    expr = item.context_expr
                    lk = self._resolve_lock(expr, info, env, lock_env)
                    if lk is not None:
                        events.append(
                            _Event(
                                "acquire", expr.lineno, expr.col_offset,
                                lock=lk, held=held_at(expr.lineno, inner),
                            )
                        )
                        inner = inner + (lk,)
                    else:
                        walk_expr(expr, inner)
                for child in node.body:
                    walk(child, inner)
                return
            if isinstance(node, ast.Call):
                walk_call(node, held)
                for child in ast.iter_child_nodes(node):
                    walk(child, held)
                return
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        def walk_expr(node: ast.AST, held: Tuple[str, ...]) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    walk_call(sub, held)

        def walk_call(node: ast.Call, held: Tuple[str, ...]) -> None:
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "acquire":
                    lk = self._resolve_lock(
                        node.func.value, info, env, lock_env
                    )
                    if lk is not None:
                        events.append(
                            _Event(
                                "acquire", node.lineno, node.col_offset,
                                lock=lk, held=held_at(node.lineno, held),
                            )
                        )
                        return
                elif node.func.attr in (
                    "release", "wait", "notify", "notify_all", "locked",
                ):
                    if self._resolve_lock(
                        node.func.value, info, env, lock_env
                    ) is not None:
                        return  # operations on the lock itself: no edge
            for callee in self._resolve_calls(node.func, info, env):
                events.append(
                    _Event(
                        "call", node.lineno, node.col_offset,
                        callee=callee, held=held_at(node.lineno, held),
                    )
                )

        for child in ast.iter_child_nodes(info.node):
            walk(child, ())
        return events

    # -- pass 3: closures, entry points, collectors ------------------------

    def build_graph(self) -> LockGraph:
        graph = self.graph
        graph.locks = dict(self.lock_kinds)
        scrape_ids = sorted(
            lk for lk in self.lock_kinds if lk.endswith(_SCRAPE_SUFFIX)
        )
        graph.scrape_lock = scrape_ids[0] if scrape_ids else None
        # Static call edges + direct acquisitions.
        direct: Dict[FuncInfo, Set[str]] = {}
        for info, events in self.events.items():
            callees = graph.callees.setdefault(info, set())
            for ev in events:
                if ev.kind == "call" and ev.callee is not None:
                    callees.add(ev.callee)
                elif ev.kind == "acquire" and ev.lock is not None:
                    direct.setdefault(info, set()).add(ev.lock)
        # Transitive acquisition closure (fixpoint; graph may be cyclic).
        acq: Dict[FuncInfo, Set[str]] = {
            info: set(direct.get(info, ())) for info in self.events
        }
        changed = True
        while changed:
            changed = False
            for info in self.events:
                mine = acq[info]
                before = len(mine)
                for callee in graph.callees.get(info, ()):
                    mine |= acq.get(callee, set())
                if len(mine) != before:
                    changed = True
        graph.acquires = acq
        # Thread entry points.
        for mod in self.project.modules.values():
            for info in mod.functions.values():
                for node in _walk_own_body(info.node):
                    if not isinstance(node, ast.Call):
                        continue
                    dotted = (
                        self.project.resolve_dotted(node.func, info.imports)
                        or ""
                    )
                    if dotted.endswith("Thread"):
                        for kw in node.keywords:
                            if kw.arg != "target":
                                continue
                            for fn in self._resolve_calls(
                                kw.value, info, {}
                            ):
                                graph.entry_points.setdefault(
                                    fn,
                                    f"Thread target in `{info.qualname}` "
                                    f"({mod.name})",
                                )
        # Collector callbacks: collect() holds the scrape lock while
        # calling them, so each one contributes scrape -> its closure.
        for mod in self.project.modules.values():
            for info in mod.functions.values():
                for node in _walk_own_body(info.node):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "register_collector"
                        and node.args
                    ):
                        continue
                    for fn in self._resolve_calls(node.args[0], info, {}):
                        graph.collectors.add(fn)
                        if graph.scrape_lock is not None:
                            for lk in acq.get(fn, ()):
                                graph.edges.setdefault(
                                    (graph.scrape_lock, lk),
                                    Site(
                                        str(mod.path), node.lineno,
                                        node.col_offset, info.qualname,
                                        f"collector `{fn.qualname}` runs "
                                        "under the scrape lock",
                                    ),
                                )
        # Nesting edges from the event streams.
        for info, events in self.events.items():
            path = str(info.module.path)
            for ev in events:
                if not ev.held:
                    continue
                inner: Set[str] = set()
                detail = ""
                if ev.kind == "acquire" and ev.lock is not None:
                    inner = {ev.lock}
                elif ev.kind == "call" and ev.callee is not None:
                    inner = acq.get(ev.callee, set())
                    detail = f"via call to `{ev.callee.qualname}`"
                for outer in ev.held:
                    for lk in inner:
                        graph.edges.setdefault(
                            (outer, lk),
                            Site(path, ev.line, ev.col, info.qualname,
                                 detail),
                        )
        return graph


def build_lock_graph(project: Project) -> LockGraph:
    """Public entry: the full static lock graph for ``project``."""
    an = _Analyzer(project)
    an.index()
    an.collect_events()
    return an.build_graph()


class LockOrderRule:
    """R6 — see module docstring. Three finding shapes: lock-order
    cycles, re-acquisition of a non-reentrant lock, and reaching the
    scrape lock while holding any project lock."""

    id = "R6"
    name = "lock-order"

    def check(self, project: Project) -> Iterator[Finding]:
        an = _Analyzer(project)
        an.index()
        an.collect_events()
        graph = an.build_graph()
        yield from self._cycles(graph)
        yield from self._reacquire(graph)
        yield from self._scrape_under_lock(an, graph)

    # -- cycles ------------------------------------------------------------

    def _cycles(self, graph: LockGraph) -> Iterator[Finding]:
        adj: Dict[str, Set[str]] = {}
        for (a, b), _site in graph.edges.items():
            if a != b:
                adj.setdefault(a, set()).add(b)
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            cyc = sorted(scc)
            sites = sorted(
                (
                    (pair, site)
                    for pair, site in graph.edges.items()
                    if pair[0] in scc and pair[1] in scc and pair[0] != pair[1]
                ),
                key=lambda kv: (kv[1].path, kv[1].line),
            )
            pair, site = sites[0]
            chain = "; ".join(
                f"`{a}` -> `{b}` at {s.path}:{s.line}"
                + (f" ({s.detail})" if s.detail else "")
                for (a, b), s in sites
            )
            yield Finding(
                rule=self.id,
                path=site.path,
                line=site.line,
                col=site.col,
                message=(
                    "lock-order cycle (potential deadlock) between "
                    + ", ".join(f"`{c}`" for c in cyc)
                    + f": {chain}"
                ),
                suggestion=(
                    "pick one canonical order (doc/static-analysis.md "
                    "lock-order table) and release the outer lock before "
                    "calling into the other subsystem"
                ),
            )

    # -- re-acquisition ----------------------------------------------------

    def _reacquire(self, graph: LockGraph) -> Iterator[Finding]:
        for (a, b), site in sorted(
            graph.edges.items(), key=lambda kv: (kv[1].path, kv[1].line)
        ):
            if a != b or graph.locks.get(a) == "RLock":
                continue
            yield Finding(
                rule=self.id,
                path=site.path,
                line=site.line,
                col=site.col,
                message=(
                    f"`{a}` is re-acquired while already held"
                    + (f" ({site.detail})" if site.detail else "")
                    + " — threading.Lock is not reentrant; this "
                    "self-deadlocks on first execution"
                ),
                suggestion=(
                    "hoist the inner acquisition to the caller (the "
                    "`_locked` suffix convention) or make the lock an "
                    "RLock if re-entry is genuinely intended"
                ),
            )

    # -- scrape lock -------------------------------------------------------

    def _scrape_under_lock(
        self, an: _Analyzer, graph: LockGraph
    ) -> Iterator[Finding]:
        scrape = graph.scrape_lock
        if scrape is None:
            return
        out: List[Finding] = []
        for info, events in an.events.items():
            path = str(info.module.path)
            for ev in events:
                held = [h for h in ev.held if h != scrape]
                if not held:
                    continue
                hits = False
                what = ""
                if ev.kind == "acquire" and ev.lock == scrape:
                    hits, what = True, "acquires the scrape lock"
                elif ev.kind == "call" and ev.callee is not None:
                    if scrape in graph.acquires.get(ev.callee, ()):
                        hits = True
                        what = (
                            f"calls `{ev.callee.qualname}`, which acquires "
                            "the scrape lock"
                        )
                if hits:
                    out.append(
                        Finding(
                            rule=self.id,
                            path=path,
                            line=ev.line,
                            col=ev.col,
                            message=(
                                f"`{info.qualname}` {what} while holding "
                                + ", ".join(f"`{h}`" for h in held)
                                + " — collect() holds the scrape lock "
                                "across collector callbacks that take "
                                "project locks, so this inverts the "
                                "canonical order (deadlock against a "
                                "concurrent scrape)"
                            ),
                            suggestion=(
                                "release every project lock before "
                                "(un)registering collectors or forcing a "
                                "scrape barrier — the close paths do this "
                                "by unregistering FIRST"
                            ),
                        )
                    )
        yield from sorted(out, key=lambda f: (f.path, f.line))


def _sccs(adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan's strongly-connected components, iterative."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]
    nodes = sorted(set(adj) | {b for bs in adj.values() for b in bs})

    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[str, Optional[str], List[str]]] = [
            (root, None, sorted(adj.get(root, ())))
        ]
        while work:
            node, parent, todo = work.pop()
            if node not in index:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recursed = False
            while todo:
                nxt = todo[0]
                todo = todo[1:]
                if nxt not in index:
                    work.append((node, parent, todo))
                    work.append((nxt, node, sorted(adj.get(nxt, ()))))
                    recursed = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if recursed:
                continue
            if low[node] == index[node]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)
            if parent is not None:
                low[parent] = min(low[parent], low[node])
    return out


def _self_plain_attr(node: ast.AST) -> Optional[str]:
    """`self.x` (no deeper chain, no subscript) -> "x"."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None
