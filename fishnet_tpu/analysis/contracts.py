"""R7 + R8: the two contract lints — telemetry vs doc, knobs vs registry.

**R7 telemetry contract.** ``doc/observability.md`` is not prose: the
fleet aggregator sums families by NAME, ``telemetry/regress.py`` keys
its baselines by NAME, and dashboards join on LABELS. A family emitted
but not documented silently vanishes from all three; a documented row
whose emitter was deleted leaves dashboards graphing flatlines. R7
diffs the two worlds both ways and checks label sets (code labels must
be a subset of the documented ones). Span stage names get the same
treatment against the doc's stage tables.

Code-side extraction is purely syntactic and covers the repo's three
emission idioms:

* ``REGISTRY.counter/gauge/histogram("fishnet_x", help, labelnames=..)``
  and direct ``Counter/Gauge/Histogram("fishnet_x", ...)`` construction
* ``counter_family/gauge_family("fishnet_x", help, v, labels={...})``
* ``MetricFamily("fishnet_x", "gauge", ...)`` / ``Sample("fishnet_x",
  v, {"label": ...})`` hand-built exposition (fleet/cost/slo planes)
* declarative spec tuples ``("fishnet_x", "gauge", help)`` (the
  ``_COUNTER_METRICS`` table idiom in ``search/service.py``) and local
  builder helpers called with a literal family as FIRST argument
* stages: ``<SPANS-ish receiver>.record("stage", ...)``, including a
  module-constant stage name (``RECOVER_STAGE``)

**R8 escape-hatch registry.** Every ``FISHNET_*`` env read, every
``--option`` in the product argparser (``configure.py``) and every
``fishnet.ini`` key must have a row in
:mod:`fishnet_tpu.analysis.registry` — see that module's docstring for
the contract. Declared-but-unused rows and dangling
``documented_in``/``tested_by`` pointers are findings too.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from fishnet_tpu.analysis.engine import Finding, Module, Project

# =========================================================================
# R7
# =========================================================================

_FAMILY_RE = re.compile(r"^fishnet_[a-z0-9_]+$")
_DOC_TOKEN_RE = re.compile(r"`(fishnet_[a-z0-9_]+)(\{[^`}]*\})?[^`]*`")
def _brace_keys(body: str) -> List[str]:
    """Label keys from a ``{...}`` doc mention: ``{slo,window}`` and
    ``{scope="prewire",family="az"}`` both work."""
    out = []
    for part in body.strip("{}").split(","):
        key = part.split("=", 1)[0].strip().strip("\"'`")
        if re.fullmatch(r"[a-z0-9_]+", key):
            out.append(key)
    return out
_BACKTICK_RE = re.compile(r"`([^`]+)`")
_INSTRUMENT_METHODS = ("counter", "gauge", "histogram")
_FAMILY_HELPERS = ("counter_family", "gauge_family")


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Emission:
    def __init__(self, name: str, path: str, line: int, col: int,
                 labels: Optional[Set[str]] = None):
        self.name = name
        self.path = path
        self.line = line
        self.col = col
        self.labels = labels or set()


def _code_families(project: Project) -> List[_Emission]:
    out: List[_Emission] = []
    for mod in project.modules.values():
        if mod.name.startswith("fishnet_tpu.analysis"):
            continue  # the checker's own fixtures/specs are not emitters
        path = str(mod.path)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                em = _call_emission(node, path)
                if em is not None:
                    out.append(em)
            elif isinstance(node, ast.Tuple) and len(node.elts) >= 2:
                name = _str_const(node.elts[0])
                kind = _str_const(node.elts[1])
                if (
                    name is not None and _FAMILY_RE.match(name)
                    and kind in _INSTRUMENT_METHODS
                ):
                    out.append(
                        _Emission(name, path, node.lineno, node.col_offset)
                    )
    return out


_INSTRUMENT_CLASSES = ("Counter", "Gauge", "Histogram")


def _kwarg(node: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _call_emission(node: ast.Call, path: str) -> Optional[_Emission]:
    func = node.func
    method = None
    if isinstance(func, ast.Attribute):
        method = func.attr
    elif isinstance(func, ast.Name):
        method = func.id
    if method is None:
        return None
    name = _str_const(node.args[0]) if node.args else None
    if name is None:
        kw_name = _kwarg(node, "name")
        name = _str_const(kw_name) if kw_name is not None else None
    if name is None or not _FAMILY_RE.match(name):
        return None
    labels: Set[str] = set()
    if method in _INSTRUMENT_METHODS or method in _INSTRUMENT_CLASSES:
        ln = _kwarg(node, "labelnames")
        if isinstance(ln, (ast.Tuple, ast.List)):
            for elt in ln.elts:
                lab = _str_const(elt)
                if lab is not None:
                    labels.add(lab)
        return _Emission(name, path, node.lineno, node.col_offset, labels)
    if method in _FAMILY_HELPERS:
        lv = _kwarg(node, "labels")
        if isinstance(lv, ast.Dict):
            for key in lv.keys:
                lab = _str_const(key) if key is not None else None
                if lab is not None:
                    labels.add(lab)
        return _Emission(name, path, node.lineno, node.col_offset, labels)
    if method == "Sample":
        lv = _kwarg(node, "labels")
        if lv is None and len(node.args) >= 3:
            lv = node.args[2]
        if isinstance(lv, ast.Dict):
            for key in lv.keys:
                lab = _str_const(key) if key is not None else None
                if lab is not None:
                    labels.add(lab)
        return _Emission(name, path, node.lineno, node.col_offset, labels)
    if method == "MetricFamily":
        return _Emission(name, path, node.lineno, node.col_offset)
    if isinstance(func, ast.Name) and node.args and _str_const(
        node.args[0]
    ) == name:
        # Local builder helper called with a literal family name first
        # (the cost plane's `fam("fishnet_x", help, values, label)`).
        return _Emission(name, path, node.lineno, node.col_offset)
    return None


def _receiver_text(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _module_str_constants(project: Project) -> Dict[str, Dict[str, str]]:
    """Module-level ``NAME = "literal"`` tables, for stage constants."""
    out: Dict[str, Dict[str, str]] = {}
    for mod in project.modules.values():
        table: Dict[str, str] = {}
        for stmt in mod.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
            ):
                value = _str_const(stmt.value)
                if value is not None:
                    table[stmt.targets[0].id] = value
        out[mod.name] = table
    return out


def _code_stages(project: Project) -> List[_Emission]:
    consts = _module_str_constants(project)
    out: List[_Emission] = []
    for mod in project.modules.values():
        if mod.name.startswith("fishnet_tpu.analysis"):
            continue
        path = str(mod.path)
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
                and node.args
            ):
                continue
            recv = _receiver_text(node.func.value).upper()
            if "SPANS" not in recv and "RECORDER" not in recv:
                continue
            arg = node.args[0]
            stage = _str_const(arg)
            if stage is None and isinstance(arg, ast.Name):
                dotted = project.resolve_dotted(arg, mod.imports)
                if dotted is not None and "." in dotted:
                    owner, _, const = dotted.rpartition(".")
                    stage = consts.get(owner, {}).get(const)
                if stage is None:
                    stage = consts.get(mod.name, {}).get(arg.id)
            if stage is not None:
                out.append(
                    _Emission(stage, path, node.lineno, node.col_offset)
                )
    return out


class _DocContract:
    """Parsed view of doc/observability.md."""

    def __init__(self, path: Path):
        self.path = path
        self.mentioned: Set[str] = set()  # any backticked fishnet_* token
        self.declared: Dict[str, int] = {}  # table-row family -> doc line
        self.labels: Dict[str, Set[str]] = {}
        self.stages: Dict[str, int] = {}  # stage table rows -> doc line
        self._parse()

    def _parse(self) -> None:
        lines = self.path.read_text(encoding="utf-8").splitlines()
        header_cells: List[str] = []
        for lineno, line in enumerate(lines, start=1):
            for m in _DOC_TOKEN_RE.finditer(line):
                name = m.group(1)
                if name == "fishnet_tpu" or name.endswith("_"):
                    continue
                self.mentioned.add(name)
                if m.group(2):
                    self.labels.setdefault(name, set()).update(
                        _brace_keys(m.group(2))
                    )
            stripped = line.strip()
            if not stripped.startswith("|"):
                header_cells = []
                continue
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            if cells and cells[0] in ("Name", "Stage"):
                header_cells = cells
                continue
            if not header_cells or set(cells[0]) <= {"-", " ", ":"}:
                continue
            first = _BACKTICK_RE.match(cells[0])
            if first is None:
                continue
            token = first.group(1)
            if header_cells[0] == "Stage":
                self.stages.setdefault(token, lineno)
                continue
            m = _DOC_TOKEN_RE.match(cells[0])
            if m is None:
                continue
            name = m.group(1)
            self.declared.setdefault(name, lineno)
            labs = self.labels.setdefault(name, set())
            # Label names can sit in a dedicated Labels cell, in parens
            # next to the type, or in the Meaning prose ("labels
            # `backend`, `psqt_path` carry static config") — accept any
            # word-like backticked token in the row. Over-collection
            # only relaxes the subset check; it can't fabricate a
            # finding.
            for cell in cells[1:]:
                labs.update(
                    tok for tok in _BACKTICK_RE.findall(cell)
                    if re.fullmatch(r"[a-z0-9_]+", tok)
                )


class TelemetryContractRule:
    """R7 — metric families and span stages must match
    doc/observability.md, both directions, labels included."""

    id = "R7"
    name = "telemetry-contract"

    def __init__(self, doc_path: Optional[Path] = None):
        self._doc_path = doc_path

    def _resolve_doc(self, project: Project) -> Optional[Path]:
        if self._doc_path is not None:
            return self._doc_path if self._doc_path.exists() else None
        for mod in project.modules.values():
            if mod.name.startswith("fishnet_tpu."):
                for parent in Path(mod.path).resolve().parents:
                    cand = parent / "doc" / "observability.md"
                    if cand.exists():
                        return cand
        return None

    def check(self, project: Project) -> Iterator[Finding]:
        doc = self._resolve_doc(project)
        if doc is None:
            return  # nothing to check against (doc-less fixture run)
        contract = _DocContract(doc)
        families = _code_families(project)
        stages = _code_stages(project)
        out: List[Finding] = []
        emitted = {em.name for em in families}
        for em in sorted(families, key=lambda e: (e.path, e.line, e.name)):
            if em.name not in contract.mentioned:
                out.append(Finding(
                    rule=self.id, path=em.path, line=em.line, col=em.col,
                    message=(
                        f"metric family `{em.name}` is emitted here but "
                        f"has no row in {contract.path.name} — the fleet "
                        "aggregator, the regression baseline, and every "
                        "dashboard are blind to it"
                    ),
                    suggestion=(
                        "add a Name/Type/Labels/Meaning row to the "
                        "matching table in doc/observability.md"
                    ),
                ))
                continue
            doc_labels = contract.labels.get(em.name, set())
            extra = em.labels - doc_labels
            if extra:
                out.append(Finding(
                    rule=self.id, path=em.path, line=em.line, col=em.col,
                    message=(
                        f"`{em.name}` is emitted with label(s) "
                        + ", ".join(f"`{x}`" for x in sorted(extra))
                        + f" not documented in {contract.path.name} "
                        f"(documented: {sorted(doc_labels) or 'none'})"
                    ),
                    suggestion=(
                        "document the label in the family's row — label "
                        "drift breaks every aggregation that sums over it"
                    ),
                ))
        for name, lineno in sorted(contract.declared.items()):
            if name not in emitted:
                out.append(Finding(
                    rule=self.id, path=str(contract.path), line=lineno,
                    col=0,
                    message=(
                        f"documented metric family `{name}` has no "
                        "emitter left in the tree — dashboards built on "
                        "this row graph a flatline"
                    ),
                    suggestion=(
                        "delete the doc row, or restore the emitter it "
                        "described"
                    ),
                ))
        emitted_stages = {em.name for em in stages}
        for em in sorted(stages, key=lambda e: (e.path, e.line, e.name)):
            if em.name not in contract.stages:
                out.append(Finding(
                    rule=self.id, path=em.path, line=em.line, col=em.col,
                    message=(
                        f"span stage `{em.name}` is recorded here but "
                        f"missing from the stage tables in "
                        f"{contract.path.name} — stage names are a "
                        "stable contract (bench.py and the span tooling "
                        "key on them)"
                    ),
                    suggestion="add a Stage/Recorded in/Covers row",
                ))
        for name, lineno in sorted(contract.stages.items()):
            if name not in emitted_stages:
                out.append(Finding(
                    rule=self.id, path=str(contract.path), line=lineno,
                    col=0,
                    message=(
                        f"documented span stage `{name}` is never "
                        "recorded in the tree"
                    ),
                    suggestion="delete the stage row or restore the span",
                ))
        yield from out


# =========================================================================
# R8
# =========================================================================

_ENV_NAME_RE = re.compile(r"^FISHNET_[A-Z0-9_]+$")
_INI_KEY_RE = re.compile(r"^[A-Z][A-Za-z0-9]+$")
_ENV_CALLS = ("environ.get", "environ.setdefault", "environ.pop", "getenv")
#: modules whose argparse / ini surface is the PRODUCT contract (aux
#: tools like telemetry/regress.py own their flags).
_CLI_SCOPE = ("fishnet_tpu.configure",)


class _Usage:
    def __init__(self, name: str, kind: str, path: str, line: int, col: int,
                 aliases: Tuple[str, ...] = ()):
        self.name = name
        self.kind = kind
        self.path = path
        self.line = line
        self.col = col
        self.aliases = aliases or (name,)


def _env_usages(
    project: Project, mod: Module,
    consts: Dict[str, Dict[str, str]],
) -> Iterator[_Usage]:
    path = str(mod.path)

    def env_name(node: ast.AST) -> Optional[str]:
        name = _str_const(node)
        if name is None and isinstance(node, ast.Name):
            # `os.environ.get(BREAKER_COOLDOWN_ENV)` — the name lives
            # in a module constant, possibly imported.
            dotted = project.resolve_dotted(node, mod.imports)
            if dotted is not None and "." in dotted:
                owner, _, const = dotted.rpartition(".")
                name = consts.get(owner, {}).get(const)
            if name is None:
                name = consts.get(mod.name, {}).get(node.id)
        if name is not None and _ENV_NAME_RE.match(name):
            return name
        return None

    for node in ast.walk(mod.tree):
        name: Optional[str] = None
        if isinstance(node, ast.Call):
            dotted = _receiver_text(node.func)
            if dotted.endswith(_ENV_CALLS) and node.args:
                name = env_name(node.args[0])
            elif (
                "env" in dotted.rpartition(".")[2].lower() and node.args
            ):
                # repo-local helpers: `_env_int("FISHNET_X")` etc.
                name = env_name(node.args[0])
        elif isinstance(node, ast.Subscript):
            if _receiver_text(node.value).endswith("environ"):
                name = env_name(node.slice)
        elif isinstance(node, ast.Compare):
            if (
                len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and _receiver_text(node.comparators[0]).endswith("environ")
            ):
                name = env_name(node.left)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            # `SNAPSHOT_ENV = "FISHNET_EVAL_CACHE_SNAPSHOT"` — naming a
            # knob for other modules to read through IS a usage.
            target = node.targets[0]
            if isinstance(target, ast.Name) and target.id.endswith("ENV"):
                name = _str_const(node.value)
                if name is not None and not _ENV_NAME_RE.match(name):
                    name = None
        if name is not None:
            yield _Usage(name, "env", path, node.lineno, node.col_offset)


def _cli_ini_usages(project: Project, mod: Module) -> Iterator[_Usage]:
    in_scope = mod.name in _CLI_SCOPE or not mod.name.startswith(
        "fishnet_tpu."
    )
    if not in_scope:
        return
    path = str(mod.path)
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr == "add_argument":
                longs = tuple(
                    s for s in (_str_const(a) for a in node.args)
                    if s is not None and s.startswith("--")
                )
                for opt in longs:
                    yield _Usage(
                        opt, "cli", path, node.lineno, node.col_offset,
                        aliases=longs,
                    )
            elif node.func.attr in ("get", "has_option") and len(
                node.args
            ) >= 2:
                section = node.args[0]
                if (
                    isinstance(section, ast.Name)
                    and "SECTION" in section.id.upper()
                ) or _str_const(section) is not None:
                    key = _str_const(node.args[1])
                    if key is not None and _INI_KEY_RE.match(key):
                        yield _Usage(
                            key, "ini", path, node.lineno, node.col_offset
                        )
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if (
                isinstance(target, ast.Name)
                and "INI_FIELDS" in target.id
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                for elt in node.value.elts:
                    if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts:
                        key = _str_const(elt.elts[0])
                        if key is not None and _INI_KEY_RE.match(key):
                            yield _Usage(
                                key, "ini", path, elt.lineno,
                                elt.col_offset,
                            )


class EscapeHatchRule:
    """R8 — every env/CLI/ini knob declared in analysis/registry.py,
    every declared knob still used, every doc/test pointer valid."""

    id = "R8"
    name = "escape-hatch-registry"

    def __init__(self, knobs=None):
        if knobs is None:
            # The one sanctioned import of "analyzed" code: the
            # analyzer's OWN contract data (plain tuples, no runtime).
            from fishnet_tpu.analysis import registry as _registry
            knobs = _registry.KNOBS
            self._registry_path: Optional[Path] = Path(_registry.__file__)
        else:
            self._registry_path = None
        self._knobs = tuple(knobs)

    def check(self, project: Project) -> Iterator[Finding]:
        declared = {(k.kind, k.name): k for k in self._knobs}
        consts = _module_str_constants(project)
        usages: List[_Usage] = []
        for mod in project.modules.values():
            if mod.name.startswith("fishnet_tpu.analysis"):
                continue  # the contract itself + fixtures
            usages.extend(_env_usages(project, mod, consts))
            usages.extend(_cli_ini_usages(project, mod))
        out: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        reported: Set[Tuple[str, str]] = set()
        for u in usages:
            covered = any(
                (u.kind, alias) in declared for alias in u.aliases
            )
            for alias in u.aliases:
                seen.add((u.kind, alias))
            if covered or (u.kind, u.name) in reported:
                continue
            reported.add((u.kind, u.name))
            out.append(Finding(
                rule=self.id, path=u.path, line=u.line, col=u.col,
                message=(
                    f"{u.kind} knob `{u.name}` is read here but not "
                    "declared in fishnet_tpu/analysis/registry.py — "
                    "undeclared escape hatches drift from docs and "
                    "tests until nobody knows what they do"
                ),
                suggestion=(
                    "add a Knob(name, kind, default, documented_in, "
                    "tested_by) row to analysis/registry.py (and a doc "
                    "line while you still remember the semantics)"
                ),
            ))
        # Reverse direction + pointer validation: only meaningful
        # against the real package (fixture projects see a slice).
        full_run = any(
            m.name.startswith("fishnet_tpu.") and "analysis" not in m.name
            for m in project.modules.values()
        )
        if full_run and self._registry_path is not None:
            reg_path = str(self._registry_path)
            reg_lines = self._registry_path.read_text(
                encoding="utf-8"
            ).splitlines()
            repo_root = self._registry_path.resolve().parents[2]

            def row_line(name: str) -> int:
                needle = f'"{name}"'
                for i, text in enumerate(reg_lines, start=1):
                    if needle in text:
                        return i
                return 1

            # Top-level scripts (bench.py, soak drivers) read knobs
            # too but sit outside the analyzed package — a cheap text
            # probe keeps their knobs from reading as dead.
            script_text = "".join(
                p.read_text(encoding="utf-8", errors="replace")
                for pattern in ("*.py", "tools/*.py")
                for p in sorted(repo_root.glob(pattern))
            )
            for (kind, name), knob in sorted(declared.items()):
                if (kind, name) not in seen and name not in script_text:
                    out.append(Finding(
                        rule=self.id, path=reg_path, line=row_line(name),
                        col=0,
                        message=(
                            f"declared {kind} knob `{name}` has no "
                            "usage left in the tree — the registry row "
                            "describes a dead switch"
                        ),
                        suggestion="delete the row (or restore the knob)",
                    ))
                    continue
                probe = name.lstrip("-")
                for label, rel in (
                    ("documented_in", knob.documented_in),
                    ("tested_by", knob.tested_by),
                ):
                    if rel is None:
                        continue
                    target = repo_root / rel
                    if not target.exists():
                        out.append(Finding(
                            rule=self.id, path=reg_path,
                            line=row_line(name), col=0,
                            message=(
                                f"`{name}`: {label} points at `{rel}`, "
                                "which does not exist"
                            ),
                            suggestion="fix the pointer",
                        ))
                    elif probe not in target.read_text(
                        encoding="utf-8", errors="replace"
                    ):
                        out.append(Finding(
                            rule=self.id, path=reg_path,
                            line=row_line(name), col=0,
                            message=(
                                f"`{name}`: {label} points at `{rel}`, "
                                f"but that file never mentions "
                                f"`{probe}` — the pointer has rotted"
                            ),
                            suggestion=(
                                "re-point it at a file that actually "
                                "covers the knob"
                            ),
                        ))
        yield from sorted(out, key=lambda f: (f.path, f.line, f.col))
