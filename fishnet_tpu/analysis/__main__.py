"""CLI: ``python -m fishnet_tpu.analysis [paths...]``.

With no paths, checks the installed ``fishnet_tpu`` package tree.
Exit status: 0 = clean, 1 = findings, 2 = usage error.

``--json``/``--sarif`` write machine-readable findings to a file (or
``-`` for stdout) for the CI annotation step and code-scanning upload;
the human rendering and exit code are unchanged by either.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from fishnet_tpu.analysis.engine import check_paths, to_json, to_sarif
from fishnet_tpu.analysis.rules import ALL_RULES


def _write(payload: str, dest: str) -> None:
    if dest == "-":
        sys.stdout.write(payload + "\n")
    else:
        Path(dest).write_text(payload + "\n", encoding="utf-8")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m fishnet_tpu.analysis",
        description="fishnet-tpu project-invariant static checker (R1-R9)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to check (default: the fishnet_tpu package)",
    )
    parser.add_argument(
        "--rules",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--json",
        metavar="FILE",
        help="write findings as a JSON array to FILE ('-' for stdout)",
    )
    parser.add_argument(
        "--sarif",
        metavar="FILE",
        help="write findings as SARIF 2.1.0 to FILE ('-' for stdout)",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="print only the summary line"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.name}")
        return 0

    rules = ALL_RULES
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        rules = [r for r in ALL_RULES if r.id in wanted]
        unknown = wanted - {r.id for r in ALL_RULES}
        if unknown:
            known = ", ".join(r.id for r in ALL_RULES)
            print(
                f"unknown rule ids: {', '.join(sorted(unknown))}"
                f" (known rules: {known})",
                file=sys.stderr,
            )
            return 2

    if args.paths:
        paths = [Path(p) for p in args.paths]
        missing = [p for p in paths if not p.exists()]
        if missing:
            print(
                f"no such path: {', '.join(str(p) for p in missing)}",
                file=sys.stderr,
            )
            return 2
    else:
        paths = [Path(__file__).resolve().parent.parent]

    findings = check_paths(paths, rules)
    if args.json:
        _write(json.dumps(to_json(findings), indent=2), args.json)
    if args.sarif:
        _write(json.dumps(to_sarif(findings, rules), indent=2), args.sarif)
    if not args.quiet:
        for f in findings:
            print(f.render())
    n_files = len(
        {f for p in paths for f in ([p] if p.is_file() else p.rglob("*.py"))}
    )
    status = "clean" if not findings else f"{len(findings)} finding(s)"
    print(f"fishnet_tpu.analysis: {n_files} file(s) checked, {status}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
