"""Project-invariant static analysis for fishnet-tpu.

The reference fishnet ships zero tests and keeps its contracts in
comments ("don't hold it wrong"); this package makes the contracts that
actually bit us machine-checked.  It is an AST-based rule engine with
four project-specific rules:

* **R1 async-blocking** — no blocking calls (``time.sleep``,
  ``subprocess.run``, sync ``requests``/``socket`` I/O,
  ``Popen(...).communicate``) inside ``async def`` bodies.  One blocking
  call on the event loop stalls every worker's pull loop at once — the
  exact bug class behind the PR-5 "one position at a time" stall.
* **R2 jit-host-sync** — no host-synchronizing operations (``.item()``,
  ``np.asarray``, ``jax.device_get``, ``float()``/``int()``/``bool()``
  on arrays, Python branches on array truthiness) in code reachable from
  a ``jax.jit``/``pjit``/``shard_map``/``pallas_call`` entry point.
  Under tracing these either crash late or — worse — silently take the
  trace-time branch and bake wrong values into the compiled program.
* **R3 deprecated-jax** — no deprecated/private JAX API usage
  (``jax.core.Tracer``, ``jax._src.*``); suggests pinned-version-safe
  replacements.
* **R4 cross-thread-state** — heuristic detection of instance/module
  state mutated both from a driver thread and from asyncio/event-loop
  methods without a lock or queue.

Run ``python -m fishnet_tpu.analysis`` (exit 0 = clean); see
``doc/static-analysis.md`` for rationale, worked examples, and the
inline suppression syntax (``# fishnet: ignore[R2] -- justification``).
"""

from fishnet_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Project,
    check_paths,
    iter_python_files,
)
from fishnet_tpu.analysis.rules import ALL_RULES  # noqa: F401
