"""Project-invariant static analysis for fishnet-tpu.

The reference fishnet ships zero tests and keeps its contracts in
comments ("don't hold it wrong"); this package makes the contracts that
actually bit us machine-checked.  It is an AST-based rule engine with
nine project-specific rules:

* **R1 async-blocking** — no blocking calls (``time.sleep``,
  ``subprocess.run``, sync ``requests``/``socket`` I/O,
  ``Popen(...).communicate``) inside ``async def`` bodies.  One blocking
  call on the event loop stalls every worker's pull loop at once — the
  exact bug class behind the PR-5 "one position at a time" stall.
* **R2 jit-host-sync** — no host-synchronizing operations (``.item()``,
  ``np.asarray``, ``jax.device_get``, ``float()``/``int()``/``bool()``
  on arrays, Python branches on array truthiness) in code reachable from
  a ``jax.jit``/``pjit``/``shard_map``/``pallas_call`` entry point.
  Under tracing these either crash late or — worse — silently take the
  trace-time branch and bake wrong values into the compiled program.
* **R3 deprecated-jax** — no deprecated/private JAX API usage
  (``jax.core.Tracer``, ``jax._src.*``); suggests pinned-version-safe
  replacements.
* **R4 cross-thread-state** — heuristic detection of instance/module
  state mutated both from a driver thread and from asyncio/event-loop
  methods without a lock or queue.
* **R5 swallowed-exception** — no silent ``except`` bodies on the
  dispatch/telemetry paths.
* **R6 lock-order** (``locks.py``) — static lock-acquisition graph over
  the whole serving plane: deadlock cycles, non-reentrant re-acquires,
  and anything that reaches the metrics SCRAPE lock while holding a
  project lock. The canonical order lives in doc/static-analysis.md.
* **R7 telemetry-contract** (``contracts.py``) — every ``fishnet_*``
  metric family and span stage emitted in code appears in
  doc/observability.md with matching labels, and vice versa.
* **R8 escape-hatch-registry** (``contracts.py``) — every ``FISHNET_*``
  env read and every CLI/ini knob is declared in ``registry.py`` with
  live ``documented_in``/``tested_by`` pointers, and vice versa.
* **R9 donation-safety** (``donation.py``) — no use-after-donation of
  arrays passed at ``donate_argnums`` positions of a jitted callable.

Run ``python -m fishnet_tpu.analysis`` (exit 0 = clean); ``--json`` /
``--sarif`` emit the structured payloads CI ingests.  See
``doc/static-analysis.md`` for rationale, worked examples, the
suppression lifecycle (``# fishnet: ignore[R2] -- justification``;
comments that stop matching become ``SUP`` findings), and the canonical
lock-order table.
"""

from fishnet_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Project,
    check_paths,
    iter_python_files,
    to_json,
    to_sarif,
)
from fishnet_tpu.analysis.rules import ALL_RULES  # noqa: F401
