"""Rule engine: file collection, module indexing, scopes, suppressions.

The engine parses every target file ONCE into an indexed ``Module``
(functions with qualified names, per-scope import tables, recorded call
sites) and hands the whole :class:`Project` to each rule — R2's
reachability analysis needs cross-module edges (``evaluate_batch`` in
``nnue/jax_eval.py`` calls ``ft_accumulate`` in ``ops/ft_gather.py``),
so per-file rules alone cannot express the invariant.

Nothing here imports the code under analysis: analysis is purely
syntactic, so it runs in milliseconds, needs no device, and cannot be
defeated by import-time side effects.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Inline suppression: ``# fishnet: ignore[R1,R2] -- why this is safe``.
#: The justification after ``--`` is MANDATORY — an unexplained
#: suppression is itself reported (rule SUP).
_SUPPRESS_RE = re.compile(
    r"#\s*fishnet:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(?:--\s*(.*))?"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suggestion: Optional[str] = None

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.suggestion:
            out += f"\n    hint: {self.suggestion}"
        return out


@dataclass(eq=False)  # identity semantics: used as dict keys in R2's BFS
class FuncInfo:
    """One function/method (async or not, any nesting level)."""

    qualname: str  # e.g. "SearchService._drive" or "f.<locals>.g"
    module: "Module"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    class_name: Optional[str] = None
    #: alias -> dotted path, merged module + enclosing + own-scope imports
    imports: Dict[str, str] = field(default_factory=dict)
    #: local names bound to nested function defs: name -> qualname
    locals_: Dict[str, str] = field(default_factory=dict)

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


@dataclass
class Module:
    path: Path
    name: str  # dotted module name ("fishnet_tpu.nnue.jax_eval")
    tree: ast.Module
    source_lines: List[str]
    imports: Dict[str, str] = field(default_factory=dict)  # module scope
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    #: class name -> {method name -> qualname}
    classes: Dict[str, Dict[str, str]] = field(default_factory=dict)


class Project:
    """All indexed modules plus name-resolution helpers shared by rules."""

    def __init__(self, package_roots: Sequence[str] = ("fishnet_tpu",)):
        self.modules: Dict[str, Module] = {}
        self.package_roots = tuple(package_roots)

    # -- construction -----------------------------------------------------

    def add_file(self, path: Path) -> Optional[Module]:
        src = path.read_text(encoding="utf-8", errors="replace")
        try:
            tree = ast.parse(src, filename=str(path))
        except SyntaxError as err:
            # Surfaced as a finding by check_paths; unparseable files
            # cannot be certified clean.
            raise _ParseError(path, err) from err
        mod = Module(
            path=path,
            name=self._module_name(path),
            tree=tree,
            source_lines=src.splitlines(),
        )
        _Indexer(mod).visit(tree)
        self.modules[mod.name] = mod
        return mod

    def _module_name(self, path: Path) -> str:
        """Dotted name from the path, anchored at a known package root;
        stand-alone files (test fixtures) get their stem."""
        parts = list(path.with_suffix("").parts)
        for root in self.package_roots:
            if root in parts:
                i = parts.index(root)
                name = ".".join(parts[i:])
                return name[: -len(".__init__")] if name.endswith(".__init__") else name
        return path.stem

    # -- resolution helpers ----------------------------------------------

    def resolve_dotted(self, node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
        """Resolve a Name/Attribute chain to a dotted path using the
        import table ("pl.pallas_call" -> "jax.experimental.pallas
        .pallas_call").  Unresolvable heads fall back to the literal
        chain, so intra-module names come back as themselves."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = imports.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def find_function(
        self, dotted: str, current: Optional[Module] = None
    ) -> Optional[FuncInfo]:
        """Find a project function by resolved dotted path: bare names
        search the current module; package-qualified names search the
        owning module (module-level functions only)."""
        if "." not in dotted:
            if current is not None:
                return current.functions.get(dotted)
            return None
        mod_name, _, func = dotted.rpartition(".")
        mod = self.modules.get(mod_name)
        if mod is not None:
            return mod.functions.get(func)
        return None


class _ParseError(Exception):
    def __init__(self, path: Path, err: SyntaxError):
        super().__init__(str(err))
        self.path = path
        self.err = err


class _Indexer(ast.NodeVisitor):
    """Single pass: import tables per scope, functions with qualnames,
    class method maps."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.scope: List[str] = []  # qualname parts
        self.class_stack: List[str] = []
        self.import_stack: List[Dict[str, str]] = [mod.imports]

    # imports ------------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        table = self.import_stack[-1]
        for alias in node.names:
            table[alias.asname or alias.name.split(".")[0]] = (
                alias.name if alias.asname else alias.name.split(".")[0]
            )
            if alias.asname:
                table[alias.asname] = alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        table = self.import_stack[-1]
        base = node.module or ""
        if node.level:  # relative import: anchor at this module's package
            pkg = self.mod.name.rsplit(".", node.level)[0]
            base = f"{pkg}.{base}" if base else pkg
        for alias in node.names:
            table[alias.asname or alias.name] = (
                f"{base}.{alias.name}" if base else alias.name
            )

    # defs ---------------------------------------------------------------

    def _visit_func(self, node) -> None:
        parent_is_class = bool(self.class_stack) and len(self.scope) == len(
            self.class_stack
        )
        if self.scope and not parent_is_class:
            qual = f"{self.scope[-1]}.<locals>.{node.name}"
        elif parent_is_class:
            qual = ".".join(self.class_stack + [node.name])
        else:
            qual = node.name
        # Colliding qualnames (several `def _():` bodies under pl.when in
        # one kernel, redefinitions) must each keep their own FuncInfo:
        # last-wins indexing silently dropped every earlier body from
        # R2's reachability scan.  `$n` cannot appear in source names, so
        # the suffix never collides with a real qualname.
        if qual in self.mod.functions:
            n = 2
            while f"{qual}${n}" in self.mod.functions:
                n += 1
            qual = f"{qual}${n}"
        imports = dict(self.import_stack[-1])
        info = FuncInfo(
            qualname=qual,
            module=self.mod,
            node=node,
            class_name=self.class_stack[-1] if parent_is_class else None,
            imports=imports,
        )
        self.mod.functions[qual] = info
        if parent_is_class:
            self.mod.classes.setdefault(self.class_stack[-1], {})[node.name] = qual
        # Expose nested defs to the enclosing function's resolution.
        if self.scope and not parent_is_class:
            encl = self.mod.functions.get(self.scope[-1])
            if encl is not None:
                encl.locals_[node.name] = qual

        self.scope.append(qual)
        self.import_stack.append(imports)
        for child in node.body:
            self.visit(child)
        self.import_stack.pop()
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.mod.classes.setdefault(node.name, {})
        self.class_stack.append(node.name)
        self.scope.append(node.name)
        for child in node.body:
            self.visit(child)
        self.scope.pop()
        self.class_stack.pop()


# -- suppression handling -------------------------------------------------


def _suppressions(lines: List[str]) -> Dict[int, Tuple[set, Optional[str], int]]:
    """line number -> (rule ids, justification, comment line)."""
    out: Dict[int, Tuple[set, Optional[str], int]] = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        if m.start() > 0 and text[m.start() - 1] == "`":
            # Backtick-quoted = documentation of the syntax (docstrings,
            # hint strings), not a live directive.
            continue
        rules = {r.strip().upper() for r in m.group(1).split(",") if r.strip()}
        just = (m.group(2) or "").strip() or None
        target = i
        if text.strip().startswith("#") and i < len(lines):
            # Stand-alone comment suppresses the NEXT line.
            target = i + 1
        out[target] = (rules, just, i)
    return out


def apply_suppressions(
    findings: List[Finding], mod: Module, used: Optional[set] = None
) -> List[Finding]:
    """Filter ``findings`` through the module's inline suppressions.

    ``used`` (optional) collects the comment lines of every suppression
    that matched at least one finding — the input to stale-suppression
    detection (a `# fishnet: ignore[Rn]` that matches nothing no longer
    earns its place in the source and is itself reported)."""
    sup = _suppressions(mod.source_lines)
    out: List[Finding] = []
    for f in findings:
        entry = sup.get(f.line)
        if entry is None:
            out.append(f)
            continue
        rules, just, comment_line = entry
        if f.rule not in rules and "ALL" not in rules:
            out.append(f)
            continue
        if used is not None:
            used.add(comment_line)
        if just is None:
            out.append(
                Finding(
                    rule="SUP",
                    path=f.path,
                    line=comment_line,
                    col=0,
                    message=(
                        f"suppression of {f.rule} without a justification "
                        f"(write `# fishnet: ignore[{f.rule}] -- <why>`)"
                    ),
                )
            )
        # Justified: drop the finding.
    return out


def stale_suppressions(
    mod: Module, used: set, ran_rule_ids: set, all_rule_ids: set
) -> List[Finding]:
    """Suppression comments that matched no finding this run.

    A suppression is only judged stale when every rule it names actually
    ran (an `ignore[R4]` is not stale under a `--rules R1` run), and an
    `ignore[ALL]` only when the full rule set ran."""
    out: List[Finding] = []
    for _target, (rules, _just, comment_line) in _suppressions(
        mod.source_lines
    ).items():
        if comment_line in used:
            continue
        named = rules - {"ALL"}
        if "ALL" in rules:
            if not all_rule_ids <= ran_rule_ids:
                continue
        elif not (named and named <= ran_rule_ids):
            continue
        out.append(
            Finding(
                rule="SUP",
                path=str(mod.path),
                line=comment_line,
                col=0,
                message=(
                    "stale suppression: `# fishnet: ignore["
                    + ",".join(sorted(rules))
                    + "]` matches no finding — the code it excused has "
                    "moved or been fixed"
                ),
                suggestion="delete the comment (or re-point it at the "
                "line that still needs it)",
            )
        )
    return out


# -- driver ---------------------------------------------------------------


def iter_python_files(paths: Iterable[Path]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(q for q in p.rglob("*.py") if q.is_file()))
        elif p.suffix == ".py":
            out.append(p)
    return out


def check_paths(
    paths: Iterable[Path], rules: Optional[Sequence] = None
) -> List[Finding]:
    """Index every file, run every rule, apply suppressions (tracking
    which ones earned their keep — the rest are reported stale), and
    return findings deterministically sorted by (path, line, col, rule)
    so CI diffs are stable run to run."""
    from fishnet_tpu.analysis.rules import ALL_RULES

    rules = list(rules if rules is not None else ALL_RULES)
    project = Project()
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        try:
            project.add_file(path)
        except _ParseError as err:
            findings.append(
                Finding(
                    rule="AST",
                    path=str(path),
                    line=err.err.lineno or 1,
                    col=err.err.offset or 0,
                    message=f"file does not parse: {err.err.msg}",
                )
            )
    # Run every rule FIRST, then apply suppressions once per module over
    # the combined findings: staleness is a cross-rule property (a
    # comment is only dead when NO rule it names fires through it).
    per_module: Dict[str, List[Finding]] = {}
    for rule in rules:
        for f in rule.check(project):
            per_module.setdefault(f.path, []).append(f)
    ran_ids = {rule.id for rule in rules}
    all_ids = {rule.id for rule in ALL_RULES}
    for mod in project.modules.values():
        mod_findings = per_module.pop(str(mod.path), [])
        used: set = set()
        findings.extend(apply_suppressions(mod_findings, mod, used))
        findings.extend(stale_suppressions(mod, used, ran_ids, all_ids))
    for leftovers in per_module.values():  # paths not indexed (docs, rare)
        findings.extend(leftovers)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# -- structured output ----------------------------------------------------


def to_json(findings: Sequence[Finding]) -> List[Dict]:
    """Findings as JSON-ready dicts (the `--json` CLI payload and the
    input to the CI annotation step)."""
    return [
        {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "col": f.col,
            "message": f.message,
            "suggestion": f.suggestion,
        }
        for f in findings
    ]


def to_sarif(
    findings: Sequence[Finding], rules: Optional[Sequence] = None
) -> Dict:
    """Findings as a SARIF 2.1.0 log (one run, one driver) — the format
    GitHub code scanning and most CI annotators ingest natively."""
    descriptors = [
        {"id": rule.id, "name": getattr(rule, "name", rule.id)}
        for rule in (rules or [])
    ]
    known = {d["id"] for d in descriptors}
    for extra in ("SUP", "AST"):
        if extra not in known and any(f.rule == extra for f in findings):
            descriptors.append(
                {
                    "id": extra,
                    "name": "suppression-hygiene" if extra == "SUP"
                    else "parse-error",
                }
            )
    results = []
    for f in findings:
        text = f.message if not f.suggestion else (
            f"{f.message} (hint: {f.suggestion})"
        )
        results.append(
            {
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": text},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {
                                "startLine": f.line,
                                "startColumn": max(1, f.col),
                            },
                        }
                    }
                ],
            }
        )
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "fishnet-analysis",
                        "informationUri": (
                            "doc/static-analysis.md"
                        ),
                        "rules": descriptors,
                    }
                },
                "results": results,
            }
        ],
    }
