"""fishnet-tpu: a TPU-native distributed chess-analysis framework.

A brand-new implementation with the capabilities of lichess.org's fishnet
client (reference surveyed in SURVEY.md): it speaks the fishnet HTTP/JSON
work protocol (acquire / analysis / move / abort / status), validates and
expands acquired games into per-ply positions, schedules them across search
workers, and reports PVs and centipawn/mate scores.

Unlike the reference (one single-threaded Stockfish subprocess per CPU core,
cf. /root/reference/src/main.rs:158-170), the engine tier here is a C++
search core whose leaf evaluations are *batched* onto TPU: all concurrent
searches yield positions into a microbatcher that executes one large
JAX/Pallas NNUE forward per step, sharded across a `jax.sharding.Mesh`.

Package layout:
    protocol/   wire model (JSON types of doc/protocol.md)
    net/        HTTP communication backend (the only server-facing I/O)
    sched/      queue scheduler: batch expansion, reassembly, pacing
    chess/      chess rules (ctypes bindings over the C++ core)
    engine/     engine drivers behind the reference's stockfish.rs seam
    nnue/       HalfKAv2_hm feature extraction, .nnue weights, JAX eval
    ops/        Pallas TPU kernels
    models/     model families (NNUE, AlphaZero-style policy+value)
    search/     batched search orchestration, MCTS
    parallel/   device mesh / sharding utilities
    train/      distributed training steps (NNUE, AZ)
    utils/      logger, stats, backoff, config, assets
"""

from fishnet_tpu.version import __version__

__all__ = ["__version__"]
