"""Disaggregated serving: N search frontends, one evaluator mesh.

The reference deployment is a fleet of monoliths — every client process
owns its engine pool AND a device-holding evaluator. This package
splits that plane (doc/disaggregation.md): cheap protocol/search
frontends submit position microbatches over a shared-memory ring
transport (:mod:`~fishnet_tpu.rpc.rings`) to one evaluator process
(:mod:`~fishnet_tpu.rpc.host`) that drains every attached frontend's
ring into ONE process-local dispatch coalescer — batches from different
processes fuse into the same segmented device dispatches, which is the
direct fix for per-process batch fill.

The client shim (:mod:`~fishnet_tpu.rpc.client`) is byte-compatible
with the in-process seam: ``RemoteBackend`` IS a ``SearchService``
whose evaluator ships microbatches over the wire, and ``RemoteAzPlane``
implements the AZ dispatch-plane lane API, so alpha-beta drivers and
MCTS leaf traffic ride unchanged. ``FISHNET_RPC`` unset or ``0`` keeps
the monolithic path byte-for-byte.
"""

from fishnet_tpu.rpc.rings import rpc_enabled  # noqa: F401
