"""Evaluator host: one device-holding process serving N frontends.

The fusion story (doc/disaggregation.md): the host's sweep drains every
attached frontend's submit ring and stages each record as a ticket on
ONE process-local ``_DispatchCoalescer`` per search family — the exact
machinery a monolith uses to fuse ITS pipeline groups — so microbatches
from DIFFERENT PROCESSES fuse into the same segmented device dispatches.
Cross-process batch fill is the direct payoff: three frontends each
trickling 60%-full MCTS leaf batches become one evaluator dispatching
near-full buckets (``fishnet_rpc_fused_rows_total`` over
``fishnet_rpc_fused_slots_total``; gated by bench.py --split).

Parity: NNUE records carry the exact padded dense arrays the
external-evaluator seam emits, replayed through the same
``evaluate_batch`` graph (row independence makes concat+pad
bit-identical — the host-material rung contract); AZ records carry the
exact uint8 plane wire, replayed through the identical jitted forward
``az_plane.AzDispatchPlane`` compiles, and answered with the same fp16
logits wire, so a remote round-trip reconstructs bit-identical fp32.

Failure contract (the PR 12 lease/fencing semantics across the
boundary): submit records carrying an epoch older than the link's
current frontend epoch are refused (a restarted frontend's predecessor
must never be double-served); a frontend past the lease without a
heartbeat has its link detached and unlinked, staged work dropped; an
injected ``rpc.detach`` fault (resilience/faults.py grammar) drops one
live link mid-flight — the next sweep re-attaches and the host-epoch
bump makes the frontend resubmit anything the dead attachment consumed
without answering.

Run it: ``python -m fishnet_tpu.rpc.host --nnue-file w.nnue --az-seed 0``
(the supervisor's ``role="evaluator"`` specs build this command line).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from fishnet_tpu import telemetry as _telemetry
from fishnet_tpu.resilience import faults
from fishnet_tpu.rpc import rings
from fishnet_tpu.telemetry.spans import RECORDER as _SPANS
from fishnet_tpu.search.service import (
    CoalesceBackend,
    NativeCoreError,
    _DispatchCoalescer,
)

__all__ = ["EvaluatorHost", "main"]


def _pad_bucket(total: int, floor: int = 32) -> int:
    """Dispatch-shape bucket: next power of two ≥ total (floor 32), so
    the host's compile-shape count stays logarithmic in load while the
    fill accounting sees honest padded slot counts."""
    b = floor
    while b < total:
        b *= 2
    return b


class _HostNnueBackend(CoalesceBackend):
    """Minimal CoalesceBackend over ``evaluate_batch_jit``: single
    shard, no router, no async pipes — the sweep thread is the only
    driver, so pinned-width parking plus demand-side flushing is the
    whole scheduler."""

    driver_threads = 1

    def __init__(self, params) -> None:
        self._params = params
        self._staged: Dict[int, Tuple] = {}
        self._async_pipes: List = []
        self._coalescer = _DispatchCoalescer(
            self, pinned_width=_DispatchCoalescer.MAX_WIDTH
        )

    def stage(self, group: int, feats, buckets, parents, material) -> None:
        self._staged[group] = (feats, buckets, parents, material)

    def _run(self, segs: List[Tuple]) -> np.ndarray:
        from fishnet_tpu.nnue import spec
        from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit

        tel = _telemetry.enabled()
        t0 = time.monotonic() if tel else 0.0
        total = sum(len(s[1]) for s in segs)
        bucket = _pad_bucket(total)
        feats = np.full((bucket, 2, 32), spec.NUM_FEATURES, np.uint16)
        buckets = np.zeros(bucket, np.int32)
        parents = np.full(bucket, -1, np.int32)
        material = np.zeros(bucket, np.int32)
        off = 0
        for f, b, p, m in segs:
            k = len(b)
            feats[off : off + k] = f
            buckets[off : off + k] = b
            material[off : off + k] = m
            pp = np.array(p, np.int32, copy=True)
            # Delta parent codes index BATCH ENTRIES (code >> 1, low bit
            # = perspective swap): rebase each segment's references by
            # its entry offset in the fused batch.
            pp[pp >= 0] += off << 1
            parents[off : off + k] = pp
            off += k
        values = np.ascontiguousarray(
            np.asarray(
                evaluate_batch_jit(
                    self._params, feats, buckets, parents, material
                )
            ),
            np.int32,
        )
        rings.note("fused.rows.nnue", total)
        rings.note("fused.slots.nnue", bucket)
        if bucket > total:
            rings.note("pad.rows", bucket - total)
        if tel:
            _SPANS.record(
                "dispatch_issue", t0, width=len(segs),
                n=total, slots=bucket, fill=total / bucket,
            )
        return values

    def _dispatch_eval(self, group: int, n: int, rows: int):
        values = self._run([self._staged.pop(group)])
        return values[:n], (n, n * (2 * 32 * 2 + 12), n * 4)

    def _dispatch_segmented(self, tickets) -> None:
        segs = [self._staged.pop(tk.group) for tk in tickets]
        full = self._run(segs)
        off = 0
        for tk, seg in zip(tickets, segs):
            k = len(seg[1])
            tk.values = full[off : off + k]
            tk.start, tk.seg_size = 0, k
            tk.acct = (k, k * (2 * 32 * 2 + 12), k * 4)
            off += k


class _HostAzBackend(CoalesceBackend):
    """AZ twin: the identical jitted forward the in-process
    ``AzDispatchPlane`` compiles (uint8 wire in, fp16 logits out — the
    bit-parity contract), fed with concatenated leaf rows from every
    frontend's MCTS pools."""

    driver_threads = 1

    def __init__(self, params, cfg) -> None:
        import jax
        import jax.numpy as jnp

        from fishnet_tpu.models.az import az_forward

        self._params = jax.device_put(params)
        az_cfg = cfg.az

        def forward(p, x_u8):
            x = x_u8.astype(jnp.float32)
            x = x.at[..., 17].multiply(1.0 / 100.0)
            logits, values = az_forward(p, x, az_cfg)
            return logits.astype(jnp.float16), values

        self._fwd = jax.jit(forward)
        self._staged: Dict[int, np.ndarray] = {}
        self._async_pipes: List = []
        self._coalescer = _DispatchCoalescer(
            self, pinned_width=_DispatchCoalescer.MAX_WIDTH
        )

    def stage(self, group: int, planes_u8: np.ndarray) -> None:
        self._staged[group] = planes_u8

    def _run(self, segs: List[np.ndarray]):
        tel = _telemetry.enabled()
        t0 = time.monotonic() if tel else 0.0
        total = sum(len(s) for s in segs)
        bucket = _pad_bucket(total)
        planes = np.zeros((bucket,) + rings.AZ_PLANE_SHAPE, np.uint8)
        off = 0
        for s in segs:
            planes[off : off + len(s)] = s
            off += len(s)
        logits16, values = self._fwd(self._params, planes)
        rings.note("fused.rows.az", total)
        rings.note("fused.slots.az", bucket)
        if bucket > total:
            rings.note("pad.rows", bucket - total)
        if tel:
            _SPANS.record(
                "dispatch_issue", t0, width=len(segs),
                n=total, slots=bucket, fill=total / bucket,
            )
        return (
            np.asarray(logits16, np.float16),
            np.asarray(values, np.float32),
        )

    def _dispatch_eval(self, group: int, n: int, rows: int):
        logits16, values = self._run([self._staged.pop(group)])
        out = (logits16[:n], values[:n])
        pol = logits16.shape[1]
        return out, (n, n * 8 * 8 * 19, n * (pol * 2 + 4))

    def _dispatch_segmented(self, tickets) -> None:
        segs = [self._staged.pop(tk.group) for tk in tickets]
        logits16, values = self._run(segs)
        pol = logits16.shape[1]
        off = 0
        for tk, seg in zip(tickets, segs):
            k = len(seg)
            tk.values = (logits16[off : off + k], values[off : off + k])
            tk.start, tk.seg_size = 0, k
            tk.acct = (k, k * 8 * 8 * 19, k * (pol * 2 + 4))
            off += k


class EvaluatorHost:
    """Discovers link files in the rpc dir, drains their submit rings
    into the family coalescers, fans results back by link. One sweep
    thread owns every host-side ring word (the single-writer contract).

    ``sweep()`` is public and synchronous so in-process tests (and the
    split bench's parity probe) can drive the host deterministically
    without the polling thread."""

    def __init__(
        self,
        nnue_params=None,
        az_params=None,
        az_cfg=None,
        rpc_dir: Optional[str] = None,
        lease_s: float = rings.LEASE_S,
        poll_s: float = 0.002,
        linger_s: Optional[float] = None,
    ) -> None:
        self._dir = rpc_dir or rings.rpc_dir()
        self._lease_s = lease_s
        self._poll_s = poll_s
        if linger_s is None:
            linger_s = float(
                os.environ.get("FISHNET_HOST_LINGER_MS", "2")
            ) / 1000.0
        self._linger_s = max(0.0, linger_s)
        self._links: Dict[str, rings.RingLink] = {}
        self._groups = itertools.count(1)
        self._nnue = (
            _HostNnueBackend(nnue_params) if nnue_params is not None else None
        )
        self._az = (
            _HostAzBackend(az_params, az_cfg)
            if az_params is not None else None
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Guards _links: the sweep loop runs on the driver thread while
        # close() detaches from the caller's thread.
        self._lock = threading.Lock()
        rings.set_role("evaluator")

    # -- link lifecycle ----------------------------------------------------

    def _scan(self) -> None:
        try:
            names = sorted(os.listdir(self._dir))
        except OSError:
            return
        fresh = []
        for name in names:
            if not name.endswith(".ring"):
                continue
            path = os.path.join(self._dir, name)
            if path in self._links:
                continue
            try:
                link = rings.attach_host_link(path)
            except (OSError, ValueError):
                continue  # foreign/torn/vanished file: skip, never serve
            with self._lock:
                self._links[path] = link
            fresh.append(link)
            rings.note("attach.host")
        if fresh:
            # Generation tick: every frontend watching one of these
            # links sees the epoch move and resubmits its in-flight
            # work — covers both host restart and fault re-attach.
            rings.bump_host_epoch(fresh)

    def _detach(self, path: str, reason: str, unlink: bool) -> None:
        with self._lock:
            link = self._links.pop(path, None)
        if link is None:
            return
        link.close()
        if unlink:
            try:
                os.unlink(path)
            except OSError:
                pass
        rings.note(f"detach.{reason}")

    # -- the sweep ---------------------------------------------------------

    def _drain(self) -> List[Tuple]:
        """Beat, reap, and drain every attached link's submit ring;
        returns the fenced-filtered records. Called once per sweep plus
        once per linger re-drain tick."""
        work: List[Tuple] = []
        for path, link in list(self._links.items()):
            link.beat()
            if link.peer_age() > self._lease_s:
                self._detach(path, "lease", unlink=True)
                continue
            for kind, ticket, epoch, n, payload in link.drain():
                if epoch < link.frontend_epoch:
                    # Fenced: a record from the link's previous life.
                    rings.note("stale_refusals")
                    continue
                work.append((link, kind, ticket, epoch, n, payload))
        return work

    def sweep(self) -> int:
        """One full service round: scan, fault poll, lease reap, drain,
        fuse-dispatch, fan results back. Returns records served."""
        self._scan()
        plan = faults.current()
        if plan is not None and self._links:
            rule = plan.poll("rpc.detach")
            if rule is not None:
                # Drop one live link mid-flight: records its attachment
                # consumed are gone; the re-attach epoch bump makes the
                # frontend re-pay them.
                self._detach(
                    sorted(self._links)[0], "fault", unlink=False
                )
        work = self._drain()
        if not work:
            return 0
        if self._linger_s > 0.0 and len(self._links) > 1:
            # Cross-process fusion pathology (SPLIT_r01): K frontends'
            # waves land microseconds apart, so each sweep used to
            # catch ONE wave and pay its own pow2 bucket — 3×40-row
            # waves dispatched as three 64-slot buckets (192 slots)
            # instead of one 128-slot fused dispatch. A bounded linger
            # re-drains the rings until the window closes, so skewed
            # waves bucket by their FUSED row count. Gated on multiple
            # attached links: with one frontend the linger is pure
            # latency with nothing to fuse.
            deadline = time.monotonic() + self._linger_s
            while True:
                now = time.monotonic()
                if now >= deadline:
                    break
                time.sleep(min(0.0005, deadline - now))
                work.extend(self._drain())
        staged = []
        for link, kind, ticket, epoch, n, payload in work:
            gid = next(self._groups)
            if kind == rings.KIND_NNUE_SUBMIT and self._nnue is not None:
                be = self._nnue
                be.stage(gid, *rings.unpack_nnue_submit(payload, n))
            elif kind == rings.KIND_AZ_SUBMIT and self._az is not None:
                be = self._az
                be.stage(gid, rings.unpack_az_submit(payload, n))
            else:
                rings.note("unserviceable")
                continue
            # Submit-all-then-demand: everything drained this sweep
            # parks together, so the first demand's flush fuses the
            # cross-process batch into one segmented dispatch.
            tk = be._coalescer.submit(gid, n, n)
            staged.append((link, kind, ticket, epoch, n, be, tk))
        served = 0
        for link, kind, ticket, epoch, n, be, tk in staged:
            try:
                values = be._coalescer.demand(tk)
            except NativeCoreError:
                rings.note("eval_errors")
                continue  # the frontend's demand timeout requeues it
            if kind == rings.KIND_NNUE_SUBMIT:
                rkind = rings.KIND_NNUE_RESULT
                out = rings.pack_nnue_result(values)
                family = "nnue"
            else:
                rkind = rings.KIND_AZ_RESULT
                out = rings.pack_az_result(*values)
                family = "az"
            try:
                link.push(rkind, ticket, epoch, n, out, deadline_s=2.0)
            except (rings.RingFull, rings.RecordTooLarge, ValueError):
                # A frontend not draining results is dying; the lease
                # will reap it, and a survivor re-pays via resubmit.
                rings.note("result_drops")
                continue
            rings.note(f"results.{family}")
            served += 1
        return served

    # -- run modes ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="rpc-evaluator", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self.sweep() == 0:
                time.sleep(self._poll_s)

    def serve_forever(self) -> None:
        self._loop()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            links = list(self._links.values())
            self._links.clear()
        for link in links:
            link.close()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    # Fleet drain sends SIGTERM (cluster/supervisor.py drain): exit the
    # serve loop cleanly so the supervisor books exit code 0, exactly
    # like a draining frontend.
    def _graceful(_sig, _frm):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _graceful)

    parser = argparse.ArgumentParser(
        prog="python -m fishnet_tpu.rpc.host",
        description="Evaluator host for the disaggregated (split) plane.",
    )
    parser.add_argument("--dir", default=None,
                        help="link directory (default: FISHNET_RPC_DIR)")
    parser.add_argument("--nnue-file", default=None,
                        help="NNUE weights to serve alpha-beta traffic")
    parser.add_argument("--az-seed", type=int, default=None,
                        help="serve AZ/MCTS traffic with params from "
                        "init_az_params(PRNGKey(seed))")
    parser.add_argument("--az-capacity", type=int, default=256,
                        help="AZ bucket-ladder capacity")
    parser.add_argument("--lease", type=float, default=rings.LEASE_S)
    parser.add_argument("--poll", type=float, default=0.002)
    parser.add_argument("--linger-ms", type=float, default=None,
                        help="cross-frontend fusion window (default: "
                        "FISHNET_HOST_LINGER_MS, 2ms)")
    parser.add_argument("--metrics-port", type=int, default=None)
    parser.add_argument("--metrics-port-file", default=None)
    args = parser.parse_args(argv)

    faults.install_from_env()
    nnue_params = None
    if args.nnue_file:
        import jax

        from fishnet_tpu.nnue.jax_eval import params_from_weights
        from fishnet_tpu.nnue.weights import NnueWeights

        nnue_params = jax.device_put(
            params_from_weights(NnueWeights.load(args.nnue_file))
        )
    az_params = az_cfg = None
    if args.az_seed is not None:
        import jax

        from fishnet_tpu.models.az import init_az_params
        from fishnet_tpu.search.mcts import MctsConfig

        az_cfg = MctsConfig(batch_capacity=args.az_capacity)
        az_params = init_az_params(
            jax.random.PRNGKey(args.az_seed), az_cfg.az
        )
    if nnue_params is None and az_params is None:
        parser.error("nothing to serve: pass --nnue-file and/or --az-seed")

    if args.metrics_port is not None:
        from fishnet_tpu import telemetry

        exporter = telemetry.start_exporter(args.metrics_port)
        if args.metrics_port_file is not None:
            tmp = f"{args.metrics_port_file}.tmp"
            with open(tmp, "w", encoding="utf-8") as fp:
                fp.write(f"{exporter.port}\n")
            os.replace(tmp, args.metrics_port_file)

    host = EvaluatorHost(
        nnue_params=nnue_params, az_params=az_params, az_cfg=az_cfg,
        rpc_dir=args.dir, lease_s=args.lease, poll_s=args.poll,
        linger_s=(
            None if args.linger_ms is None else args.linger_ms / 1000.0
        ),
    )
    try:
        host.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        host.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
