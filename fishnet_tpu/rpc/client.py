"""Frontend-side shims: the split plane behind the in-process seams.

Two entry points, one per search family (doc/disaggregation.md):

* :class:`RemoteBackend` IS a ``SearchService`` whose evaluator ships
  each group's padded microbatch over this frontend's ring link instead
  of running a local jit — the external-evaluator seam
  (``search/service.py _dispatch_eval``) already produces exactly the
  self-contained dense arrays the wire carries, so alpha-beta drivers,
  the engine factories and ``train/selfplay.py`` ride unchanged. The
  evaluator returns a LAZY handle; the service's ``_resolve_eval``
  materializes it one loop iteration later, which preserves the
  per-group pipeline overlap across the process boundary.
* :class:`RemoteAzPlane` implements the AZ dispatch-plane lane API
  (``register_lane``/``warmup``/``evaluate``/``counters``/``close``),
  so ``MctsPool``'s existing ``hasattr(evaluator, "register_lane")``
  wrap routes MCTS leaf microbatches over the same transport.

Failure contract: a demand wait survives an evaluator death by
watching the host epoch and heartbeat — when the evaluator is reborn
(epoch bump) the client cancels its groups' device anchors via the
existing ``fc_pool_cancel_anchors`` path and RESUBMITS the kept
payload bytes; only the total ``FISHNET_RPC_TIMEOUT`` budget expiring
surfaces as an error (the service's requeue machinery takes over).
Results are deduplicated by ticket id, so an at-least-once transport
still yields exactly-once consumption.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from fishnet_tpu.rpc import rings
from fishnet_tpu.search.service import NativeCoreError, SearchService
from fishnet_tpu.telemetry.spans import RECORDER as _SPANS

__all__ = ["RemoteBackend", "RemoteAzPlane", "RemoteEvaluator"]


class EvaluatorLostError(NativeCoreError):
    """The evaluator host stayed unreachable past FISHNET_RPC_TIMEOUT."""


class _RpcClient:
    """One frontend link: serialized submits, ticket table, demand
    waits. All ring writes go through ``_lock`` (the SPSC single-writer
    contract); results drain under the same lock and park in
    ``_results`` until their owner claims them."""

    def __init__(self, directory: Optional[str] = None,
                 name: Optional[str] = None) -> None:
        self._link = rings.create_frontend_link(directory, name=name)
        self._epoch = self._link.frontend_epoch
        self._tickets = itertools.count(1)
        self._lock = threading.Lock()
        self._results: Dict[int, Tuple[int, int, bytes]] = {}
        self._done: set = set()
        self._closed = False
        rings.set_role("frontend")

    @property
    def link(self) -> rings.RingLink:
        return self._link

    def submit(self, kind: int, n: int, payload: bytes) -> int:
        ticket = next(self._tickets)
        with self._lock:
            self._link.beat()
            self._link.push(kind, ticket, self._epoch, n, payload)
        family = "nnue" if kind == rings.KIND_NNUE_SUBMIT else "az"
        rings.note(f"submits.{family}")
        return ticket

    def _drain_locked(self) -> None:
        for kind, ticket, epoch, n, payload in self._link.drain():
            # Fenced results: a record answering a previous life of
            # this frontend (or a duplicate of one already claimed —
            # a resubmit can be answered twice) must not double-
            # consume — exactly-once by ticket id.
            if (epoch != self._epoch or ticket in self._results
                    or ticket in self._done):
                continue
            self._results[ticket] = (kind, n, payload)

    def wait(self, ticket: int, n: int, kind: int,
             payload: bytes) -> Tuple[int, int, bytes]:
        """Block until ``ticket``'s result lands. Resubmits the kept
        ``payload`` after an evaluator rebirth (host epoch moved) and
        raises :class:`EvaluatorLostError` only when the total timeout
        budget runs out — a requeue signal, never a silent hang."""
        t0 = time.monotonic()
        deadline = t0 + rings.timeout_s()
        host_epoch = self._link.host_epoch
        while True:
            with self._lock:
                self._link.beat()
                self._drain_locked()
                got = self._results.pop(ticket, None)
                if got is not None:
                    self._done.add(ticket)
                    if len(self._done) > 8192:
                        floor = ticket - 8192
                        self._done = {t for t in self._done if t > floor}
            if got is not None:
                _SPANS.record(
                    "rpc_wait", t0, ticket=ticket,
                    family="nnue" if kind == rings.KIND_NNUE_SUBMIT
                    else "az",
                )
                return got
            now_epoch = self._link.host_epoch
            if now_epoch != host_epoch:
                # The evaluator died and a successor attached: any
                # record it consumed without answering is gone, so
                # fence local device state and re-pay the submit.
                host_epoch = now_epoch
                self._on_evaluator_lost()
                with self._lock:
                    self._link.push(kind, ticket, self._epoch, n, payload)
                rings.note("resubmits")
            if time.monotonic() >= deadline:
                raise EvaluatorLostError(
                    f"rpc demand timeout: no result for ticket {ticket} "
                    f"within {rings.timeout_s():.0f}s "
                    f"(host heartbeat age {self._link.peer_age():.1f}s); "
                    "requeue the batch"
                )
            time.sleep(0.001)

    def _on_evaluator_lost(self) -> None:
        """Hook: RemoteBackend cancels its groups' device anchors."""

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        import os

        path = self._link.path
        self._link.close()
        try:
            os.unlink(path)
        except OSError:
            pass


class _PendingEval:
    """Lazy result handle for one in-flight NNUE microbatch: the
    service's ``_resolve_eval`` calls ``np.asarray`` on it one pipeline
    iteration after dispatch, so the demand wait overlaps the next
    group's fiber stepping exactly like a device future would."""

    __slots__ = ("_client", "_ticket", "_n", "_payload", "_arr")

    def __init__(self, client: _RpcClient, ticket: int, n: int,
                 payload: bytes) -> None:
        self._client = client
        self._ticket = ticket
        self._n = n
        self._payload = payload
        self._arr: Optional[np.ndarray] = None

    def __array__(self, dtype=None, copy=None) -> np.ndarray:
        if self._arr is None:
            _kind, _n, result = self._client.wait(
                self._ticket, self._n, rings.KIND_NNUE_SUBMIT,
                self._payload,
            )
            self._arr = rings.unpack_nnue_result(result, self._n)
            self._payload = b""  # free the kept bytes
        return self._arr if dtype is None else self._arr.astype(dtype)


class RemoteEvaluator:
    """The external-evaluator callable ``(params, feats, buckets,
    parents, material) -> lazy int32 [B]`` the service seam expects:
    packs the full padded microbatch into one self-contained submit
    record and returns a :class:`_PendingEval`."""

    size_multiple = 1

    def __init__(self, client: _RpcClient) -> None:
        self._client = client

    def __call__(self, params, feats, buckets, parents, material):
        n = len(buckets)
        payload = rings.pack_nnue_submit(feats, buckets, parents, material)
        ticket = self._client.submit(rings.KIND_NNUE_SUBMIT, n, payload)
        return _PendingEval(self._client, ticket, n, payload)


class RemoteBackend(SearchService):
    """A SearchService whose eval plane lives in another process.

    Byte-compatible with the in-process seam: construction takes the
    same arguments (plus ``rpc_dir``), drivers and engine factories see
    a plain SearchService, and analyses are bit-identical to a
    monolith's because the host replays the exact dense microbatch
    through the same ``evaluate_batch`` graph (the host-material rung's
    parity contract; gated by bench.py --split)."""

    def __init__(self, *args, rpc_dir: Optional[str] = None,
                 **kwargs) -> None:
        client = _RpcClient(rpc_dir)
        client._on_evaluator_lost = self._cancel_inflight_anchors
        self._rpc = client
        kwargs["evaluator"] = RemoteEvaluator(client)
        kwargs.setdefault("backend", "jax")
        super().__init__(*args, **kwargs)

    def _cancel_inflight_anchors(self) -> None:
        """Evaluator death fences every group's device anchor state via
        the existing cancellation path. External-evaluator mode never
        enables persistent anchors (in-batch refs only), so this is the
        same no-op-safe call the in-process cache-skip path makes —
        kept so a future anchor-carrying wire inherits the fencing."""
        pool = getattr(self, "_pool", None)
        if not pool:
            return
        for group in range(self._n_groups):
            self._lib.fc_pool_cancel_anchors(pool, group)

    def close(self) -> None:
        try:
            super().close()
        finally:
            self._rpc.close()


class RemoteAzPlane:
    """The AZ dispatch-plane lane API over the ring transport.

    ``MctsPool`` wraps any evaluator exposing ``register_lane`` in its
    ``_PlaneEvaluator`` adapter, so handing this to a pool routes every
    leaf microbatch through the evaluator host — where microbatches
    from ALL frontends fuse into shared bucket dispatches (the
    cross-process fill win bench.py --split gates). ``params`` is
    optional and only salts the client-side pre-wire
    :class:`~fishnet_tpu.search.eval_cache.AzEvalCache` probe; the wire
    payload is the exact uint8 planes / fp16 logits the local plane
    uses, so results are bit-identical either way."""

    def __init__(self, cfg, params: Optional[Dict] = None,
                 rpc_dir: Optional[str] = None,
                 link_name: Optional[str] = None) -> None:
        import os

        from fishnet_tpu.models.az_encoding import POLICY_SIZE

        self.cfg = cfg
        self._policy_size = POLICY_SIZE
        # Link names are per-frontend: same-process planes (bench fill
        # probe, tests) must pass distinct ``link_name``s or the second
        # attach bumps the frontend epoch and fences the first plane's
        # in-flight submits as stale.
        self._client = _RpcClient(
            rpc_dir, name=link_name or f"link-{os.getpid()}-az.ring"
        )
        self._salt = None
        if params is not None:
            from fishnet_tpu.search import eval_cache as _eval_cache

            if not _eval_cache.cache_disabled():
                self._salt = _eval_cache.az_net_fingerprint(params)
        self._lane_lock = threading.Lock()
        self._next_lane = 0
        self._stats_lock = threading.Lock()
        self._prewire_hits = 0
        self._skipped_dispatches = 0
        self._rows_submitted = 0
        self._dispatches = 0

    def register_lane(self) -> int:
        with self._lane_lock:
            lane = self._next_lane
            self._next_lane += 1
            return lane

    def warmup(self) -> None:
        """One tiny round trip: proves the link is served and lets the
        host compile its smallest AZ bucket before real traffic."""
        planes = np.zeros((1,) + rings.AZ_PLANE_SHAPE, np.uint8)
        payload = rings.pack_az_submit(planes)
        ticket = self._client.submit(rings.KIND_AZ_SUBMIT, 1, payload)
        self._client.wait(ticket, 1, rings.KIND_AZ_SUBMIT, payload)

    def evaluate(
        self, lane: int, planes_u8: np.ndarray, n: int, keys=None
    ) -> Tuple[np.ndarray, np.ndarray]:
        out_logits = np.empty((n, self._policy_size), np.float32)
        out_values = np.empty((n,), np.float32)
        if n == 0:
            return out_logits, out_values
        cache = None
        salted = None
        miss = list(range(n))
        if keys is not None and self._salt is not None:
            from fishnet_tpu.search import eval_cache as _eval_cache

            cache = _eval_cache.get_az_cache()
        if cache is not None:
            salted = [
                (int(k) ^ self._salt) & ((1 << 64) - 1) for k in keys
            ]
            miss = []
            hits = 0
            for i, ent in enumerate(cache.probe_many(salted)):
                if ent is None:
                    miss.append(i)
                    continue
                lg16, val = ent
                out_logits[i] = lg16.astype(np.float32)
                out_values[i] = val
                hits += 1
            if hits:
                with self._stats_lock:
                    self._prewire_hits += hits
            if not miss:
                with self._stats_lock:
                    self._skipped_dispatches += 1
                return out_logits, out_values
        rows = np.ascontiguousarray(planes_u8[np.asarray(miss, np.intp)])
        payload = rings.pack_az_submit(rows)
        ticket = self._client.submit(
            rings.KIND_AZ_SUBMIT, len(miss), payload
        )
        _kind, _n, result = self._client.wait(
            ticket, len(miss), rings.KIND_AZ_SUBMIT, payload
        )
        logits16, values = rings.unpack_az_result(
            result, len(miss), self._policy_size
        )
        with self._stats_lock:
            self._rows_submitted += len(miss)
            self._dispatches += 1
        for j, i in enumerate(miss):
            lg16 = logits16[j]
            out_logits[i] = lg16.astype(np.float32)
            out_values[i] = values[j]
            if cache is not None and salted is not None:
                # The exact fp16 wire payload — warm replays
                # reconstruct identical fp32 bits (az_plane contract).
                cache.insert(
                    salted[i],
                    (np.array(lg16, np.float16), np.float32(values[j])),
                )
        return out_logits, out_values

    def counters(self) -> Dict[str, float]:
        """Client-side view (host-side fill rides the rpc_* metric
        families; bench.py --split reads those)."""
        with self._stats_lock:
            return {
                "prewire_hits": self._prewire_hits,
                "skipped_dispatches": self._skipped_dispatches,
                "rows_dispatched": self._rows_submitted,
                "slots_dispatched": 0,
                "dispatches": self._dispatches,
                "dispatch_fill": 0.0,
            }

    def close(self) -> None:
        self._client.close()
