"""Shared-memory ring transport: one mmap'd link file per frontend.

The disaggregation boundary (doc/disaggregation.md) is a plain file in
``FISHNET_RPC_DIR``, created by the frontend and discovered by the
evaluator host's directory scan — no sockets, no broker, no cross-
process locks. Each link carries two SPSC rings of fixed-size slots:

* **submit ring** — frontend writes, host reads (position microbatches
  as self-contained records: the full padded wire arrays, so a record
  can be re-executed verbatim after any crash on either side);
* **result ring** — host writes, frontend reads (ticket-tagged values).

Cross-process safety without locks reuses the ``cluster/
position_tier.py`` machinery: every record carries a seqlock word
(odd = write in progress) plus a checksum over its payload and header
fields, so a torn write from a SIGKILLed peer — or a record clobbered
by a reattaching writer — reads as a MISS (the reader skips it and
counts ``rpc_torn_total``), never as a wrong value. Ring flow control
is the SPSC head/tail pair in the link header: each word has exactly
one writer, so plain monotonic stores suffice.

Fencing (the PR 12 lease/epoch semantics across the new boundary):

* the **frontend epoch** stamps every submit record. A restarted
  frontend reattaching to its predecessor's link file bumps the epoch;
  the host refuses records carrying a stale epoch
  (``rpc_stale_refusals_total``) and the frontend drops result records
  from before its own rebirth — fenced work is re-submitted, never
  double-consumed.
* the **host epoch** bumps on every host attach. A frontend whose
  in-flight ticket outlives the epoch it was submitted under knows the
  evaluator died: it cancels the groups' device anchors
  (``fc_pool_cancel_anchors``) and resubmits — demand timeouts surface
  as a requeue, not a hang.
* **heartbeats** (one f64 per side, wall clock) drive the lease: the
  host detaches and eventually unlinks a link whose frontend stopped
  beating; the frontend treats a stale host heartbeat as a death even
  before the epoch moves.

Knobs (analysis/registry.py): ``FISHNET_RPC`` gates the split path,
``FISHNET_RPC_DIR`` places the link files, ``FISHNET_RPC_RING_SLOTS``
and ``FISHNET_RPC_SLOT_BYTES`` size the rings (wraparound is exercised
at tiny slot counts by tests/test_rpc.py), ``FISHNET_RPC_TIMEOUT``
bounds a frontend's total wait for one result.
"""

from __future__ import annotations

import mmap
import os
import tempfile
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

#: Master gate: "1" makes build_search_service construct the remote
#: (split-plane) backend; unset/anything else keeps the monolith.
RPC_ENV = "FISHNET_RPC"
#: Directory holding the per-frontend link files; default: one per uid
#: in the system tempdir.
RPC_DIR_ENV = "FISHNET_RPC_DIR"
#: Slots per ring (submit and result each).
RING_SLOTS_ENV = "FISHNET_RPC_RING_SLOTS"
#: Bytes per ring slot (record header + payload must fit).
SLOT_BYTES_ENV = "FISHNET_RPC_SLOT_BYTES"
#: Frontend-side total wait bound (seconds) for one eval result.
TIMEOUT_ENV = "FISHNET_RPC_TIMEOUT"

_MAGIC = 0x46_4E_52_50_43_4C_4B_31  # "FNRPCLK1"
_VERSION = 1
_HEADER_BYTES = 4096
_U64 = (1 << 64) - 1
_MIX = 0x9E3779B97F4A7C15  # splitmix64 odd constant (position_tier.py)

DEFAULT_RING_SLOTS = 8
DEFAULT_SLOT_BYTES = 4 << 20
DEFAULT_TIMEOUT_S = 120.0
#: A frontend past this many seconds without a heartbeat is dead to the
#: host (lease expiry: staged work dropped, link detached); a host past
#: it is dead to the frontend (resubmit on the next epoch).
LEASE_S = 10.0

_HEADER_DTYPE = np.dtype([
    ("magic", "<u8"),
    ("version", "<u4"),
    ("ring_slots", "<u4"),
    ("slot_bytes", "<u4"),
    ("frontend_pid", "<u4"),
    ("host_pid", "<u4"),
    ("_pad", "<u4"),
    ("frontend_epoch", "<u8"),
    ("host_epoch", "<u8"),
    ("frontend_heartbeat", "<f8"),
    ("host_heartbeat", "<f8"),
    ("submit_head", "<u8"),
    ("submit_tail", "<u8"),
    ("result_head", "<u8"),
    ("result_tail", "<u8"),
])

#: Per-record header inside a slot; payload bytes follow immediately.
_REC_DTYPE = np.dtype([
    ("seq", "<u4"),
    ("kind", "<u4"),
    ("ticket", "<u8"),
    ("epoch", "<u8"),
    ("n", "<u4"),
    ("nbytes", "<u4"),
    ("check", "<u8"),
])
REC_HEADER_BYTES = _REC_DTYPE.itemsize
assert REC_HEADER_BYTES == 40

KIND_NNUE_SUBMIT = 1
KIND_AZ_SUBMIT = 2
KIND_NNUE_RESULT = 3
KIND_AZ_RESULT = 4


def rpc_enabled() -> bool:
    """The master hatch, read per call so tests can monkeypatch env."""
    return os.environ.get(RPC_ENV, "") == "1"


def rpc_dir() -> str:
    uid = getattr(os, "getuid", lambda: 0)()
    return os.environ.get(RPC_DIR_ENV) or os.path.join(
        tempfile.gettempdir(), f"fishnet-rpc-{uid}"
    )


def _env_int(name: str, default: int, floor: int = 1) -> int:
    try:
        return max(floor, int(os.environ.get(name, default)))
    except ValueError:
        return default


def ring_slots() -> int:
    return _env_int(RING_SLOTS_ENV, DEFAULT_RING_SLOTS, floor=2)


def slot_bytes() -> int:
    return _env_int(
        SLOT_BYTES_ENV, DEFAULT_SLOT_BYTES, floor=REC_HEADER_BYTES + 64
    )


def timeout_s() -> float:
    try:
        return max(1.0, float(os.environ.get(TIMEOUT_ENV, DEFAULT_TIMEOUT_S)))
    except ValueError:
        return DEFAULT_TIMEOUT_S


def _check_words(payload: np.ndarray) -> int:
    """XOR-fold of the payload viewed as u64 words (zero-padded tail)."""
    words = payload.view(np.uint8)
    pad = (-len(words)) % 8
    if pad:
        words = np.concatenate([words, np.zeros(pad, np.uint8)])
    if len(words) == 0:
        return 0
    return int(np.bitwise_xor.reduce(words.view(np.uint64))) & _U64


def _record_check(kind: int, ticket: int, epoch: int, n: int,
                  nbytes: int, payload: np.ndarray) -> int:
    """Record checksum: header fields mixed with the payload fold —
    any interleaving of a dead writer's half-published stores fails
    this with overwhelming probability (the position-tier discipline)."""
    acc = _check_words(payload)
    acc ^= (kind * _MIX) & _U64
    acc ^= ticket & _U64
    acc ^= (epoch * _MIX) & _U64
    acc ^= ((n << 32) | nbytes) & _U64
    return acc & _U64


class RingFull(RuntimeError):
    """A bounded push found no free slot within its deadline."""


class RecordTooLarge(ValueError):
    """A payload exceeds the link's slot size (raise FISHNET_RPC_SLOT_BYTES)."""


class RingLink:
    """One attached link file: header + submit ring + result ring.

    Exactly one frontend and one host attach a link at a time; each
    ring is SPSC between them (submit: frontend writes / host reads;
    result: host writes / frontend reads). All writes from one side go
    through one thread — the frontend's driver or the host's sweep —
    matching the single-writer contract the head/tail words require.
    """

    def __init__(self, path: str, mm: mmap.mmap, role: str) -> None:
        assert role in ("frontend", "host")
        self.path = path
        self.role = role
        self._mm = mm
        self._header = np.frombuffer(mm, dtype=_HEADER_DTYPE, count=1)
        self._slots = int(self._header["ring_slots"][0])
        self._slot_bytes = int(self._header["slot_bytes"][0])
        ring_bytes = self._slots * self._slot_bytes
        self._submit = np.frombuffer(
            mm, dtype=np.uint8, count=ring_bytes, offset=_HEADER_BYTES
        )
        self._result = np.frombuffer(
            mm, dtype=np.uint8, count=ring_bytes,
            offset=_HEADER_BYTES + ring_bytes,
        )
        self._closed = False
        _track_link(self)

    # -- header accessors --------------------------------------------------

    def _h(self, field: str) -> int:
        return int(self._header[field][0])

    @property
    def frontend_epoch(self) -> int:
        return self._h("frontend_epoch")

    @property
    def host_epoch(self) -> int:
        return self._h("host_epoch")

    @property
    def slot_capacity(self) -> int:
        """Largest payload one slot can carry."""
        return self._slot_bytes - REC_HEADER_BYTES

    def beat(self) -> None:
        """Refresh this side's heartbeat (wall clock: comparable across
        processes, unlike monotonic)."""
        field = (
            "frontend_heartbeat" if self.role == "frontend"
            else "host_heartbeat"
        )
        self._header[field] = time.time()

    def peer_age(self) -> float:
        """Seconds since the peer's last heartbeat (inf if it never
        beat)."""
        field = (
            "host_heartbeat" if self.role == "frontend"
            else "frontend_heartbeat"
        )
        t = float(self._header[field][0])
        return float("inf") if t <= 0.0 else max(0.0, time.time() - t)

    def depth(self, ring: str) -> int:
        """Records currently queued (written, not yet consumed)."""
        if ring == "submit":
            return self._h("submit_head") - self._h("submit_tail")
        return self._h("result_head") - self._h("result_tail")

    # -- record write ------------------------------------------------------

    def _ring_for(self, writer: bool) -> Tuple[np.ndarray, str, str]:
        # The frontend writes submits and reads results; the host the
        # reverse — each (ring, direction) pair has one fixed owner.
        if (self.role == "frontend") == writer:
            return self._submit, "submit_head", "submit_tail"
        return self._result, "result_head", "result_tail"

    def push(self, kind: int, ticket: int, epoch: int, n: int,
             payload: bytes, deadline_s: float = 5.0) -> None:
        """Publish one record on this side's outgoing ring; blocks (a
        bounded spin) while the ring is full. Raises :class:`RingFull`
        past the deadline and :class:`RecordTooLarge` for a payload no
        slot can hold — sizing errors must fail loudly, not truncate."""
        pay = np.frombuffer(payload, dtype=np.uint8)
        if len(pay) > self.slot_capacity:
            raise RecordTooLarge(
                f"{len(pay)}-byte record exceeds the {self.slot_capacity}-"
                f"byte slot payload capacity; raise {SLOT_BYTES_ENV}"
            )
        ring, head_f, tail_f = self._ring_for(writer=True)
        deadline = time.monotonic() + deadline_s
        while self._h(head_f) - self._h(tail_f) >= self._slots:
            if time.monotonic() >= deadline:
                raise RingFull(
                    f"{self.path}: {head_f.split('_')[0]} ring full "
                    f"({self._slots} slots) for {deadline_s:.1f}s"
                )
            time.sleep(0.0005)
        head = self._h(head_f)
        base = (head % self._slots) * self._slot_bytes
        rec = np.frombuffer(
            self._mm, dtype=_REC_DTYPE, count=1,
            offset=(_HEADER_BYTES if ring is self._submit
                    else _HEADER_BYTES + self._slots * self._slot_bytes)
            + base,
        )
        s = int(rec["seq"][0])
        rec["seq"] = ((s + 1) | 1) & 0xFFFFFFFF  # odd: mid-write
        rec["kind"] = kind
        rec["ticket"] = ticket & _U64
        rec["epoch"] = epoch & _U64
        rec["n"] = n
        rec["nbytes"] = len(pay)
        ring[base + REC_HEADER_BYTES : base + REC_HEADER_BYTES + len(pay)] = (
            pay
        )
        rec["check"] = _record_check(kind, ticket, epoch, n, len(pay), pay)
        rec["seq"] = (((s + 1) | 1) + 1) & 0xFFFFFFFF  # even: published
        self._header[head_f] = head + 1
        _count(f"push.{'submit' if ring is self._submit else 'result'}", 1)

    # -- record read -------------------------------------------------------

    def drain(self, limit: int = 64) -> List[Tuple[int, int, int, int, bytes]]:
        """Consume up to ``limit`` validated records from this side's
        incoming ring: ``[(kind, ticket, epoch, n, payload), ...]``.
        Torn or checksum-rejected records are SKIPPED (counted as
        ``rpc_torn_total`` — a miss the submitter re-pays, never a
        wrong value)."""
        ring, head_f, tail_f = self._ring_for(writer=False)
        ring_off = (
            _HEADER_BYTES if ring is self._submit
            else _HEADER_BYTES + self._slots * self._slot_bytes
        )
        out: List[Tuple[int, int, int, int, bytes]] = []
        while len(out) < limit and self._h(tail_f) < self._h(head_f):
            tail = self._h(tail_f)
            base = (tail % self._slots) * self._slot_bytes
            rec = np.frombuffer(
                self._mm, dtype=_REC_DTYPE, count=1, offset=ring_off + base
            )
            s1 = int(rec["seq"][0])
            kind = int(rec["kind"][0])
            ticket = int(rec["ticket"][0])
            epoch = int(rec["epoch"][0])
            n = int(rec["n"][0])
            nbytes = int(rec["nbytes"][0])
            check = int(rec["check"][0])
            valid = (
                s1 % 2 == 0 and s1 != 0
                and 0 <= nbytes <= self.slot_capacity
            )
            payload = b""
            if valid:
                payload = bytes(
                    ring[base + REC_HEADER_BYTES
                         : base + REC_HEADER_BYTES + nbytes]
                )
                valid = (
                    int(rec["seq"][0]) == s1
                    and check == _record_check(
                        kind, ticket, epoch, n, nbytes,
                        np.frombuffer(payload, dtype=np.uint8),
                    )
                )
            # Consume the slot either way: a torn record is a dead
            # writer's tombstone, and leaving it would wedge the ring.
            self._header[tail_f] = tail + 1
            if valid:
                out.append((kind, ticket, epoch, n, payload))
            else:
                _count("torn", 1)
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._header = None
        self._submit = self._result = None
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass


# -- attach / create ---------------------------------------------------------


def _link_size(slots: int, sbytes: int) -> int:
    return _HEADER_BYTES + 2 * slots * sbytes


def create_frontend_link(directory: Optional[str] = None,
                         name: Optional[str] = None) -> RingLink:
    """Create (or reattach) THIS frontend's link file and return the
    frontend-side handle. A fresh file publishes its header with the
    magic LAST (the position-tier create discipline); reattaching to an
    existing file — the restarted-frontend shape — bumps the frontend
    epoch so the host fences every record of the previous life."""
    directory = directory or rpc_dir()
    os.makedirs(directory, mode=0o700, exist_ok=True)
    name = name or f"link-{os.getpid()}.ring"
    path = os.path.join(directory, name)
    slots = ring_slots()
    sbytes = slot_bytes()
    size = _link_size(slots, sbytes)
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
    try:
        existing = os.fstat(fd).st_size
        if existing == 0:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
            header = np.frombuffer(mm, dtype=_HEADER_DTYPE, count=1)
            header["version"] = _VERSION
            header["ring_slots"] = slots
            header["slot_bytes"] = sbytes
            header["frontend_pid"] = os.getpid() & 0xFFFFFFFF
            header["frontend_epoch"] = 1
            header["frontend_heartbeat"] = time.time()
            header["magic"] = _MAGIC
            _count("attach.create", 1)
        else:
            mm = mmap.mmap(fd, existing)
            header = np.frombuffer(mm, dtype=_HEADER_DTYPE, count=1)
            _validate_header(path, header, existing)
            header["frontend_pid"] = os.getpid() & 0xFFFFFFFF
            header["frontend_epoch"] = int(header["frontend_epoch"][0]) + 1
            header["frontend_heartbeat"] = time.time()
            _count("attach.reattach", 1)
        del header
    finally:
        os.close(fd)
    return RingLink(path, mm, role="frontend")


def attach_host_link(path: str) -> RingLink:
    """Attach the evaluator host to a discovered link file. Raises
    ``ValueError`` on a foreign/torn header — the host's scan skips the
    file rather than serving garbage."""
    fd = os.open(path, os.O_RDWR)
    try:
        existing = os.fstat(fd).st_size
        mm = mmap.mmap(fd, existing)
        header = np.frombuffer(mm, dtype=_HEADER_DTYPE, count=1)
        _validate_header(path, header, existing)
        header["host_pid"] = os.getpid() & 0xFFFFFFFF
        header["host_heartbeat"] = time.time()
        del header
    finally:
        os.close(fd)
    return RingLink(path, mm, role="host")


def bump_host_epoch(links: List[RingLink]) -> None:
    """One attach-generation tick across every discovered link: the
    fencing signal frontends use to detect an evaluator rebirth."""
    for link in links:
        link._header["host_epoch"] = int(link._header["host_epoch"][0]) + 1


def _validate_header(path: str, header: np.ndarray, size: int) -> None:
    if int(header["magic"][0]) != _MAGIC:
        raise ValueError(f"{path}: not an rpc link file")
    if int(header["version"][0]) != _VERSION:
        raise ValueError(f"{path}: rpc link version mismatch")
    slots = int(header["ring_slots"][0])
    sbytes = int(header["slot_bytes"][0])
    if slots < 2 or sbytes <= REC_HEADER_BYTES or (
        size < _link_size(slots, sbytes)
    ):
        raise ValueError(f"{path}: rpc link geometry mismatch")


# -- wire payload codecs -----------------------------------------------------
# Self-contained per-record formats shared by client and host. NNUE
# submits carry the exact padded arrays the external-evaluator seam
# produces (search/service.py _dispatch_eval) so the host can replay
# them through evaluate_batch verbatim; AZ records carry the exact
# uint8 plane wire / fp16 logits wire the shared AZ plane uses, so a
# remote round-trip reconstructs bit-identical fp32 values.

AZ_PLANE_SHAPE = (8, 8, 19)


def pack_nnue_submit(feats: np.ndarray, buckets: np.ndarray,
                     parents: np.ndarray, material: np.ndarray) -> bytes:
    n = len(buckets)
    assert feats.shape == (n, 2, 32)
    return (
        np.ascontiguousarray(feats, np.uint16).tobytes()
        + np.ascontiguousarray(buckets, np.int32).tobytes()
        + np.ascontiguousarray(parents, np.int32).tobytes()
        + np.ascontiguousarray(material, np.int32).tobytes()
    )


def unpack_nnue_submit(
    payload: bytes, n: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    fb = n * 2 * 32 * 2
    feats = np.frombuffer(payload, np.uint16, count=n * 64).reshape(n, 2, 32)
    buckets = np.frombuffer(payload, np.int32, count=n, offset=fb)
    parents = np.frombuffer(payload, np.int32, count=n, offset=fb + 4 * n)
    material = np.frombuffer(payload, np.int32, count=n, offset=fb + 8 * n)
    return feats, buckets, parents, material


def pack_nnue_result(values: np.ndarray) -> bytes:
    return np.ascontiguousarray(values, np.int32).tobytes()


def unpack_nnue_result(payload: bytes, n: int) -> np.ndarray:
    return np.frombuffer(payload, np.int32, count=n).copy()


def pack_az_submit(planes_u8: np.ndarray) -> bytes:
    return np.ascontiguousarray(planes_u8, np.uint8).tobytes()


def unpack_az_submit(payload: bytes, n: int) -> np.ndarray:
    return np.frombuffer(payload, np.uint8).reshape((n,) + AZ_PLANE_SHAPE)


def pack_az_result(logits_f16: np.ndarray, values_f32: np.ndarray) -> bytes:
    return (
        np.ascontiguousarray(logits_f16, np.float16).tobytes()
        + np.ascontiguousarray(values_f32, np.float32).tobytes()
    )


def unpack_az_result(payload: bytes, n: int,
                     policy_size: int) -> Tuple[np.ndarray, np.ndarray]:
    logits = np.frombuffer(
        payload, np.float16, count=n * policy_size
    ).reshape(n, policy_size)
    values = np.frombuffer(
        payload, np.float32, count=n, offset=n * policy_size * 2
    )
    return logits, values


# -- module counters + telemetry collector ----------------------------------
# The position_tier.py discipline: a process-lifetime counter dict plus
# one registry collector emitting the rpc_* families
# (doc/observability.md "RPC transport").

_count_lock = threading.Lock()
_counts: Dict[str, int] = {}
_role: Optional[str] = None
_links: "weakref.WeakSet[RingLink]" = weakref.WeakSet()
_collector_token: Optional[int] = None


def _count(key: str, n: int) -> None:
    with _count_lock:
        _counts[key] = _counts.get(key, 0) + n


def note(key: str, n: int = 1) -> None:
    """Public counter hook for the client/host layers (``submits.nnue``,
    ``results.az``, ``stale_refusals``, ``reattach``, ``detach.lease``,
    ``fused.rows.az``, ...)."""
    _count(key, n)


def stats() -> Dict[str, int]:
    with _count_lock:
        return dict(_counts)


def set_role(role: str) -> None:
    """Declare this process's split-plane role (``frontend`` |
    ``evaluator``); the fleet console's role column reads the resulting
    gauge."""
    global _role
    _role = role
    _ensure_collector()


def _track_link(link: RingLink) -> None:
    _links.add(link)
    _ensure_collector()


def _ensure_collector() -> None:
    global _collector_token
    with _count_lock:
        if _collector_token is not None:
            return
        from fishnet_tpu.telemetry.registry import REGISTRY

        _collector_token = REGISTRY.register_collector(
            _collect_rpc, name="rpc-transport"
        )


def _collect_rpc() -> Optional[List]:
    from fishnet_tpu.telemetry.registry import counter_family, gauge_family

    with _count_lock:
        snap = dict(_counts)
    fams = []
    for family in ("nnue", "az"):
        fams.append(counter_family(
            "fishnet_rpc_submits_total",
            "Eval microbatch records pushed onto submit rings, by "
            "family.",
            snap.get(f"submits.{family}", 0),
            labels={"family": family},
        ))
        fams.append(counter_family(
            "fishnet_rpc_results_total",
            "Eval result records pushed onto result rings, by family.",
            snap.get(f"results.{family}", 0),
            labels={"family": family},
        ))
        fams.append(counter_family(
            "fishnet_rpc_fused_rows_total",
            "Real eval rows the host dispatched, by family (over "
            "fishnet_rpc_fused_slots_total = cross-process batch fill).",
            snap.get(f"fused.rows.{family}", 0),
            labels={"family": family},
        ))
        fams.append(counter_family(
            "fishnet_rpc_fused_slots_total",
            "Padded bucket slots the host dispatched, by family.",
            snap.get(f"fused.slots.{family}", 0),
            labels={"family": family},
        ))
    fams.append(counter_family(
        "fishnet_dispatch_pad_rows_total",
        "Padding slots shipped in device dispatches (bucket size minus "
        "real entries), by dispatch path.",
        snap.get("pad.rows", 0),
        labels={"path": "host"},
    ))
    fams.append(counter_family(
        "fishnet_rpc_torn_total",
        "Ring records skipped by the seqlock/checksum validation (a "
        "SIGKILLed peer's torn write reads as a miss, never a value).",
        snap.get("torn", 0),
    ))
    fams.append(counter_family(
        "fishnet_rpc_stale_refusals_total",
        "Submit records refused for carrying a fenced (pre-restart) "
        "frontend epoch.",
        snap.get("stale_refusals", 0),
    ))
    fams.append(counter_family(
        "fishnet_rpc_reattach_total",
        "Link attach/reattach events (create = fresh link file, "
        "reattach = epoch-bumping rebirth, host = evaluator attach).",
        snap.get("attach.create", 0)
        + snap.get("attach.reattach", 0)
        + snap.get("attach.host", 0),
    ))
    fams.append(counter_family(
        "fishnet_rpc_detach_total",
        "Links the host dropped, by reason (lease = dead frontend, "
        "fault = injected rpc.detach).",
        snap.get("detach.lease", 0),
        labels={"reason": "lease"},
    ))
    fams.append(counter_family(
        "fishnet_rpc_detach_total",
        "Links the host dropped, by reason (lease = dead frontend, "
        "fault = injected rpc.detach).",
        snap.get("detach.fault", 0),
        labels={"reason": "fault"},
    ))
    fams.append(counter_family(
        "fishnet_rpc_resubmits_total",
        "Microbatches re-submitted after an evaluator epoch change or "
        "stale host heartbeat (the requeue-not-hang contract).",
        snap.get("resubmits", 0),
    ))
    if _role is not None:
        fams.append(gauge_family(
            "fishnet_rpc_role",
            "This process's split-plane role (1 = active role label).",
            1,
            labels={"role": _role},
        ))
    for link in list(_links):
        if link._closed or link._header is None:
            continue
        name = os.path.basename(link.path)
        for ring in ("submit", "result"):
            fams.append(gauge_family(
                "fishnet_rpc_ring_depth",
                "Records queued (written, unconsumed) per link ring.",
                link.depth(ring),
                labels={"link": name, "ring": ring},
            ))
    return fams
