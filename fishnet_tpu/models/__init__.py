"""Model families served by the framework.

* ``az`` — AlphaZero-style policy+value residual network with the
  73-plane move encoding, the evaluator behind the batched-PUCT MCTS
  engine (BASELINE.json config 5).

The NNUE family (HalfKAv2_hm) lives in :mod:`fishnet_tpu.nnue` (serving)
and :mod:`fishnet_tpu.train` (training) for historical layering reasons.
"""

from fishnet_tpu.models.az import AzConfig, az_forward, init_az_params
from fishnet_tpu.models.az_encoding import (
    INPUT_PLANES,
    POLICY_SIZE,
    board_planes,
    legal_policy_indices,
    move_to_index,
)

__all__ = [
    "AzConfig",
    "az_forward",
    "init_az_params",
    "INPUT_PLANES",
    "POLICY_SIZE",
    "board_planes",
    "legal_policy_indices",
    "move_to_index",
]
