"""AlphaZero-style policy+value residual network, TPU-shaped.

A conv tower over 8x8x19 input planes with two heads: a 73-plane policy
(4672 logits, az_encoding.py) and a tanh value in [-1, 1] from the side
to move's perspective. The reference has no neural policy/value path at
all (its engines are alpha-beta C++); this family exists for the
batched-PUCT MCTS engine of BASELINE.json config 5.

TPU shaping choices:

* compute in bfloat16 (MXU-native), parameters in float32;
* NHWC layout with channel counts that are multiples of 8 so XLA tiles
  convs onto the MXU without padding waste;
* no batch norm at inference — the net uses pre-activation residual
  blocks with simple bias (training-time normalization is folded in), so
  the whole forward is a fusion-friendly chain of conv+add+relu;
* everything under one ``jax.jit`` with static shapes: the MCTS engine
  always evaluates fixed-capacity microbatches, padding short batches.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from fishnet_tpu.models.az_encoding import INPUT_PLANES, POLICY_SIZE

Params = Dict[str, jax.Array]


@dataclass(frozen=True)
class AzConfig:
    channels: int = 64
    blocks: int = 6
    value_hidden: int = 128
    policy_planes: int = 73

    @property
    def policy_size(self) -> int:
        return 64 * self.policy_planes


def init_az_params(rng: jax.Array, cfg: AzConfig = AzConfig()) -> Params:
    c = cfg.channels
    keys = jax.random.split(rng, 4 + 2 * cfg.blocks)

    def conv(key, cin, cout, k=3):
        scale = np.sqrt(2.0 / (k * k * cin))
        return jax.random.normal(key, (k, k, cin, cout), jnp.float32) * scale

    params: Params = {
        "stem_w": conv(keys[0], INPUT_PLANES, c),
        "stem_b": jnp.zeros((c,), jnp.float32),
        "policy_w": conv(keys[1], c, cfg.policy_planes, k=1),
        "policy_b": jnp.zeros((cfg.policy_planes,), jnp.float32),
        "value_w": conv(keys[2], c, 4, k=1),
        "value_b": jnp.zeros((4,), jnp.float32),
        "value_fc1_w": jax.random.normal(keys[3], (4 * 64, cfg.value_hidden), jnp.float32)
        * np.sqrt(2.0 / (4 * 64)),
        "value_fc1_b": jnp.zeros((cfg.value_hidden,), jnp.float32),
        "value_fc2_w": jnp.zeros((cfg.value_hidden, 1), jnp.float32),
        "value_fc2_b": jnp.zeros((1,), jnp.float32),
    }
    for i in range(cfg.blocks):
        params[f"res{i}_w1"] = conv(keys[4 + 2 * i], c, c)
        params[f"res{i}_b1"] = jnp.zeros((c,), jnp.float32)
        params[f"res{i}_w2"] = conv(keys[5 + 2 * i], c, c)
        params[f"res{i}_b2"] = jnp.zeros((c,), jnp.float32)
    return params


def _conv2d(x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
    out = jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + b.astype(x.dtype)


def az_forward(params: Params, planes: jax.Array, cfg: AzConfig = AzConfig()):
    """planes [B, 8, 8, 19] -> (policy_logits [B, 4672], value [B]).

    Compute runs in bfloat16; logits/value are returned in float32.
    """
    x = planes.astype(jnp.bfloat16)
    x = jax.nn.relu(_conv2d(x, params["stem_w"], params["stem_b"]))
    for i in range(cfg.blocks):
        h = jax.nn.relu(_conv2d(x, params[f"res{i}_w1"], params[f"res{i}_b1"]))
        h = _conv2d(h, params[f"res{i}_w2"], params[f"res{i}_b2"])
        x = jax.nn.relu(x + h)

    pol = _conv2d(x, params["policy_w"], params["policy_b"])
    policy_logits = pol.reshape(pol.shape[0], -1).astype(jnp.float32)
    # NHWC reshape order = square-major within plane-minor; reorder to the
    # square*73+plane indexing of az_encoding.move_to_index.
    # pol[b, r, f, p] -> index (r*8+f)*73 + p: reshape already yields
    # b, (r*8+f)*planes + p, which is exactly that. (No permute needed.)

    v = jax.nn.relu(_conv2d(x, params["value_w"], params["value_b"]))
    v = v.reshape(v.shape[0], -1)
    v = jax.nn.relu(v @ params["value_fc1_w"].astype(v.dtype) + params["value_fc1_b"].astype(v.dtype))
    v = jnp.tanh(v @ params["value_fc2_w"].astype(v.dtype) + params["value_fc2_b"].astype(v.dtype))
    return policy_logits, v[:, 0].astype(jnp.float32)


def value_to_centipawns(v: float) -> int:
    """Map a [-1, 1] value-head output to centipawns for the fishnet
    protocol (the same tan mapping family Lc0 uses for UCI output)."""
    v = float(np.clip(v, -0.9999, 0.9999))
    return int(round(111.7 * np.tan(1.5620688421 * v)))


def az_config_from_params(params: Params) -> AzConfig:
    """Recover the architecture a checkpoint was trained with.

    Every AzConfig field is determined by parameter shapes, so `.npz`
    checkpoints need no architecture metadata; loading a net trained with
    a non-default config (--az-net-file) reconstructs the right config
    instead of crashing shape-mismatched inside the jitted forward.
    """
    required = ("stem_b", "policy_b", "value_fc1_b")
    missing = [k for k in required if k not in params]
    if missing:
        raise ValueError(
            f"not an AZ checkpoint: missing parameter(s) {missing}; "
            f"got keys {sorted(params)[:8]}..."
        )
    blocks = 0
    while f"res{blocks}_w1" in params:
        blocks += 1
    cfg = AzConfig(
        channels=int(np.shape(params["stem_b"])[0]),
        blocks=blocks,
        value_hidden=int(np.shape(params["value_fc1_b"])[0]),
        policy_planes=int(np.shape(params["policy_b"])[0]),
    )
    # eval_shape: shape-only abstract trace, no device traffic — this runs
    # at client startup where the default backend may be a tunneled TPU.
    shapes = jax.eval_shape(lambda: init_az_params(jax.random.PRNGKey(0), cfg))
    expected = {k: v.shape for k, v in shapes.items()}
    got = {k: tuple(np.shape(v)) for k, v in params.items()}
    if {k: tuple(v) for k, v in expected.items()} != got:
        diff = {k for k in set(expected) ^ set(got)} or {
            k for k in expected if tuple(expected[k]) != got.get(k)
        }
        raise ValueError(
            f"AZ checkpoint does not match any {cfg}: mismatched keys {sorted(diff)}"
        )
    return cfg
