"""Board and move encodings for the AlphaZero-style model family.

Everything is encoded from the side to move's perspective: the board is
flipped vertically when black moves, so the network always sees "my
pawns advance toward rank 8". This halves what the net must learn and is
the standard AlphaZero/Lc0 convention.

Input: 19 feature planes over the 8x8 board (own/opponent piece types,
castling rights, en-passant file, halfmove clock, bias plane).

Policy: the AlphaZero 8x8x73 move encoding — for each from-square, 56
queen-move planes (8 directions x up to 7 steps), 8 knight-move planes,
and 9 underpromotion planes (N/B/R x {push, capture-left,
capture-right}). Queen-promotions ride the queen-move planes. 4672
logits total. (The reference has no policy network at all — its engines'
move ordering is hand-crafted C++; this encoding exists for the MCTS
engine of BASELINE.json config 5.)
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

INPUT_PLANES = 19
POLICY_SIZE = 64 * 73

_PIECE_ORDER = "PNBRQK"

# Queen-move directions in (dfile, drank) order; plane = dir * 7 + (dist-1).
_QUEEN_DIRS = [(0, 1), (1, 1), (1, 0), (1, -1), (0, -1), (-1, -1), (-1, 0), (-1, 1)]
_KNIGHT_DIRS = [(1, 2), (2, 1), (2, -1), (1, -2), (-1, -2), (-2, -1), (-2, 1), (-1, 2)]
# Underpromotion planes: piece in N, B, R x direction {push, capture-left,
# capture-right} (df = 0, -1, +1 from the mover's perspective).
_UNDERPROMO_PIECES = "nbr"
_UNDERPROMO_DF = [0, -1, 1]


def _sq(file: int, rank: int) -> int:
    return rank * 8 + file


def _parse_sq(s: str) -> Tuple[int, int]:
    return ord(s[0]) - ord("a"), ord(s[1]) - ord("1")


def _flip_rank(rank: int) -> int:
    return 7 - rank


def move_to_index(uci: str, stm_white: bool) -> int:
    """Policy index of a UCI move (stm perspective). Raises ValueError on
    moves outside the encoding (e.g. crazyhouse drops — the AZ family
    serves standard chess only)."""
    if "@" in uci:
        raise ValueError(f"drop moves are not in the AZ policy encoding: {uci}")
    ff, fr = _parse_sq(uci[0:2])
    tf, tr = _parse_sq(uci[2:4])
    promo = uci[4:5]
    if not stm_white:
        fr, tr = _flip_rank(fr), _flip_rank(tr)
    df, dr = tf - ff, tr - fr

    if promo and promo != "q":
        try:
            piece = _UNDERPROMO_PIECES.index(promo)
        except ValueError as err:
            raise ValueError(f"bad promotion piece in {uci}") from err
        try:
            direction = _UNDERPROMO_DF.index(df)
        except ValueError as err:
            raise ValueError(f"bad promotion direction in {uci}") from err
        plane = 64 + piece * 3 + direction
    elif (df, dr) in _KNIGHT_DIRS:
        plane = 56 + _KNIGHT_DIRS.index((df, dr))
    else:
        if df and dr and abs(df) != abs(dr):
            raise ValueError(f"not a queen-line move: {uci}")
        dist = max(abs(df), abs(dr))
        if dist == 0 or dist > 7:
            raise ValueError(f"bad move distance: {uci}")
        step = (0 if df == 0 else df // abs(df), 0 if dr == 0 else dr // abs(dr))
        try:
            direction = _QUEEN_DIRS.index(step)
        except ValueError as err:
            raise ValueError(f"bad direction: {uci}") from err
        plane = direction * 7 + (dist - 1)

    return _sq(ff, fr) * 73 + plane


def legal_policy_indices(moves: List[str], stm_white: bool) -> np.ndarray:
    """int32 policy indices for a legal-move list, aligned with `moves`."""
    return np.asarray([move_to_index(m, stm_white) for m in moves], dtype=np.int32)


def _parse_fen_fields(fen: str) -> Dict[str, str]:
    parts = fen.split()
    return {
        "placement": parts[0],
        "turn": parts[1] if len(parts) > 1 else "w",
        "castling": parts[2] if len(parts) > 2 else "-",
        "ep": parts[3] if len(parts) > 3 else "-",
        "halfmove": parts[4] if len(parts) > 4 else "0",
    }


def board_planes(fen: str) -> np.ndarray:
    """[8, 8, 19] float32 feature planes (rank-major, stm perspective).

    Planes 0-5 own P N B R Q K, 6-11 opponent, 12-13 own castling (king /
    queen side), 14-15 opponent castling, 16 en-passant square, 17
    halfmove clock / 100, 18 all-ones.
    """
    f = _parse_fen_fields(fen)
    stm_white = f["turn"] == "w"
    planes = np.zeros((8, 8, INPUT_PLANES), dtype=np.float32)

    rank = 7
    file = 0
    for c in f["placement"].split("[", 1)[0]:
        if c == "/":
            rank -= 1
            file = 0
        elif c.isdigit():
            file += int(c)
        elif c == "~":
            continue
        else:
            white = c.isupper()
            idx = _PIECE_ORDER.index(c.upper())
            plane = idx if white == stm_white else 6 + idx
            r = rank if stm_white else _flip_rank(rank)
            planes[r, file, plane] = 1.0
            file += 1

    own, opp = ("KQ", "kq") if stm_white else ("kq", "KQ")
    castling = f["castling"]
    if own[0] in castling:
        planes[:, :, 12] = 1.0
    if own[1] in castling:
        planes[:, :, 13] = 1.0
    if opp[0] in castling:
        planes[:, :, 14] = 1.0
    if opp[1] in castling:
        planes[:, :, 15] = 1.0
    # Chess960 Shredder-FEN rights (file letters): we can't cheaply tell
    # king- from queen-side here, so light both planes for that color.
    for c in castling:
        if c in "-KQkq":
            continue
        base = 12 if c.isupper() == stm_white else 14
        planes[:, :, base] = 1.0
        planes[:, :, base + 1] = 1.0

    if f["ep"] != "-":
        ef, er = _parse_sq(f["ep"])
        planes[er if stm_white else _flip_rank(er), ef, 16] = 1.0

    try:
        halfmove = float(f["halfmove"])
    except ValueError:
        halfmove = 0.0
    planes[:, :, 17] = min(halfmove, 100.0) / 100.0
    planes[:, :, 18] = 1.0
    return planes
