"""The fishnet-tpu NNUE architecture specification.

Architecture: **HalfKAv2_hm feature set + SFNNv5-shaped network**, the
family used by the reference's embedded Stockfish 15 net
(``nn-ad9b42354671.nnue``, reference build.rs:7). All tensor shapes and
the serialization layout follow the public Stockfish/nnue-pytorch
format; the quantized arithmetic below is specified exactly so that the
C++ scalar evaluator (cpp/src/nnue.cpp) and the batched JAX evaluator
(fishnet_tpu/nnue/jax_eval.py) are bit-identical — that equivalence is
this framework's score-parity oracle (SURVEY.md §4), since no pretrained
net ships in this environment.

Feature set (HalfKAv2_hm):
    For each perspective p (side to move first):
      k0      = ksq(p) ^ (p == BLACK ? 56 : 0)        # color flip
      mirror  = file(k0) >= 4                          # horizontal mirror
      okq     = k0 ^ (mirror ? 7 : 0)
      bucket  = rank(okq) * 4 + file(okq)              # 0..31
      For every piece (c, t) on square s:
        osq   = s ^ (p == BLACK ? 56 : 0) ^ (mirror ? 7 : 0)
        plane = t == KING ? 10 : 2 * t + (c != p)      # 0..10
        index = bucket * 704 + plane * 64 + osq        # 0..22527
    <= 32 active features per perspective (all pieces incl. both kings).

Network (int quantization in brackets):
    ft:    22528 -> 1024 [w,b int16] + 8 PSQT outputs [int32]
    acc    = b + sum of active rows            (int32 math, int16 range)
    c      = clamp(acc, 0, 127)
    pair_i = (c_i * c_{i+512}) >> 7            # 512 per perspective, u8
    x      = concat(pair[stm], pair[opp])      # 1024
    bucket = (popcount(occupied) - 1) // 4     # 8 layer-stack buckets
    l1:    1024 -> 16 [w int8, b int32]; y = W x + b
    skip   = y[15]                             # direct residual neuron
    h      = y[0:15]
    act    = concat(min(127, (h*h) >> 19), clamp(h >> 6, 0, 127))  # 30
    l2:    30 -> 32 [w int8, b int32]; z = clamp((W act + b) >> 6, 0, 127)
    out:   32 -> 1  [w int8, b int32]; v = W z + b
    material   = (psqt[stm][bucket] - psqt[opp][bucket]) / 2
    positional = v + skip + (skip * 23) / 127   # == v + skip*9600/8128
    value      = (positional + material) / 16           # centipawns

    All `/` above are C-style truncating integer divisions (toward zero);
    all `>>` are arithmetic (flooring) shifts. The JAX evaluator
    reproduces exactly these semantics.

Divergence note vs. real SF15 nets — what IS and IS NOT verified
offline (this environment has no network egress and no pretrained net):

  Verified offline:
  * Field order, dtypes, and padded widths of the serialization match
    the documented SF/nnue-pytorch layout, via an independent bytewise
    golden-vector fixture (tests/test_nnue.py
    test_nnue_golden_byte_layout), including the 30->32 padded l2 rows.
  * The arithmetic follows the published SFNNv5 operator set
    (SqrClippedReLU >> 19, ClippedReLU >> 6, pairwise >> 7, FV_SCALE
    16), and the C++ scalar and JAX batched evaluators agree bit for
    bit on random nets and positions — including incremental (delta)
    entries and search-level results at fixed depth.

  NOT verifiable offline (would need a real nn-*.nnue file):
  * The section-hash constants (FT 0x5D69D5B8, stack 0x63337156): the
    loader deliberately skips them rather than verifying.
  * That FILE_VERSION/ARCH_HASH match the bytes of the shipped SF15
    net, and end-to-end score parity against stock Stockfish on it.
  The authoritative offline contract is therefore C++ == JAX on any
  weights this framework loads or trains.
"""

from __future__ import annotations

# Feature transformer
NUM_PLANES = 11
NUM_SQ = 64
NUM_KING_BUCKETS = 32
FEATURES_PER_BUCKET = NUM_PLANES * NUM_SQ  # 704
NUM_FEATURES = NUM_KING_BUCKETS * FEATURES_PER_BUCKET  # 22528
MAX_ACTIVE_FEATURES = 32
#: Incremental (delta) entries encode "remove feature f" as the index
#: DELTA_BASE + f (still uint16; cpp/src/nnue.h NNUE_DELTA_BASE). The
#: evaluators decode by subtraction and SUBTRACT those rows — the table
#: itself stays single-copy (a negated-copy table was tried and cost
#: ~25% extra gather time from the doubled random-read working set).
#: Wire contract per perspective of a delta entry: added features in
#: slots [0, DELTA_SLOTS), removals in [DELTA_SLOTS, 2*DELTA_SLOTS),
#: each region padded with its own sentinel (NUM_FEATURES, resp.
#: DELTA_BASE + NUM_FEATURES); slots beyond are plain sentinel.
DELTA_BASE = NUM_FEATURES + 1
DELTA_SLOTS = 4

L1 = 1024  # feature-transformer width
L1_HALF = L1 // 2  # pairwise-multiplied halves
NUM_PSQT_BUCKETS = 8
L2 = 15  # l1 outputs going through activations (+1 skip neuron)
L3 = 32

# Quantization
FT_CLIP = 127
PAIRWISE_SHIFT = 7
WEIGHT_SCALE_BITS = 6
SQR_SHIFT = 2 * WEIGHT_SCALE_BITS + PAIRWISE_SHIFT  # 19
FV_SCALE = 16
SKIP_NUM = 600 * FV_SCALE  # skip-neuron scale numerator
SKIP_DEN = 127 * (1 << WEIGHT_SCALE_BITS)

# Serialization (little-endian), nnue-pytorch/SF compatible framing
FILE_VERSION = 0x7AF32F20
ARCH_HASH = 0x3E5AA6EE  # HalfKAv2_hm + SFNNv5 stack (public constant)
#: SF's AffineTransform serializes weights over inputs PADDED to a
#: multiple of 32 (SIMD register width): the 30-wide l2 layer occupies
#: 32 int8 per output row on disk, the two pad columns zero. l1 (1024)
#: and out (32) are already aligned.
L2_PADDED_INPUTS = 32
ARCH_DESCRIPTION = (
    b"Features=HalfKAv2_hm(Friend)[22528->1024x2],"
    b"Network=AffineTransform[1->32](ClippedReLU[32](AffineTransform[32->30]"
    b"(SqrClippedReLU+ClippedReLU[15](AffineTransform[15+1<-1024]))))"
)


def psqt_bucket(piece_count: int) -> int:
    """Layer-stack / PSQT bucket from total piece count (1..32)."""
    return min(NUM_PSQT_BUCKETS - 1, max(0, (piece_count - 1) // 4))
