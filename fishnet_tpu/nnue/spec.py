"""The fishnet-tpu NNUE architecture specification.

Architecture: **HalfKAv2_hm feature set + SFNNv5-shaped network**, the
family used by the reference's embedded Stockfish 15 net
(``nn-ad9b42354671.nnue``, reference build.rs:7). All tensor shapes and
the serialization layout follow the public Stockfish/nnue-pytorch
format; the quantized arithmetic below is specified exactly so that the
C++ scalar evaluator (cpp/src/nnue.cpp) and the batched JAX evaluator
(fishnet_tpu/nnue/jax_eval.py) are bit-identical — that equivalence is
this framework's score-parity oracle (SURVEY.md §4), since no pretrained
net ships in this environment.

Feature set (HalfKAv2_hm):
    For each perspective p (side to move first):
      k0      = ksq(p) ^ (p == BLACK ? 56 : 0)        # color flip
      mirror  = file(k0) >= 4                          # horizontal mirror
      okq     = k0 ^ (mirror ? 7 : 0)
      bucket  = rank(okq) * 4 + file(okq)              # 0..31
      For every piece (c, t) on square s:
        osq   = s ^ (p == BLACK ? 56 : 0) ^ (mirror ? 7 : 0)
        plane = t == KING ? 10 : 2 * t + (c != p)      # 0..10
        index = bucket * 704 + plane * 64 + osq        # 0..22527
    <= 32 active features per perspective (all pieces incl. both kings).

Network (int quantization in brackets):
    ft:    22528 -> 1024 [w,b int16] + 8 PSQT outputs [int32]
    acc    = b + sum of active rows            (int32 math, int16 range)
    c      = clamp(acc, 0, 127)
    pair_i = (c_i * c_{i+512}) >> 7            # 512 per perspective, u8
    x      = concat(pair[stm], pair[opp])      # 1024
    bucket = (popcount(occupied) - 1) // 4     # 8 layer-stack buckets
    l1:    1024 -> 16 [w int8, b int32]; y = W x + b
    skip   = y[15]                             # direct residual neuron
    h      = y[0:15]
    act    = concat(min(127, (h*h) >> 19), clamp(h >> 6, 0, 127))  # 30
    l2:    30 -> 32 [w int8, b int32]; z = clamp((W act + b) >> 6, 0, 127)
    out:   32 -> 1  [w int8, b int32]; v = W z + b
    material   = (psqt[stm][bucket] - psqt[opp][bucket]) / 2
    positional = v + skip + (skip * 23) / 127   # == v + skip*9600/8128
    value      = (positional + material) / 16           # centipawns

    All `/` above are C-style truncating integer divisions (toward zero);
    all `>>` are arithmetic (flooring) shifts. The JAX evaluator
    reproduces exactly these semantics.

Divergence note vs. real SF15 nets: the arithmetic above follows the
published SFNNv5 operator set (SqrClippedReLU >> 19, ClippedReLU >> 6,
pairwise >> 7, FV_SCALE 16); exact parity with stock Stockfish on its
shipped net cannot be validated offline, so the authoritative contract
is C++ == JAX on any weights this framework loads or trains.
"""

from __future__ import annotations

# Feature transformer
NUM_PLANES = 11
NUM_SQ = 64
NUM_KING_BUCKETS = 32
FEATURES_PER_BUCKET = NUM_PLANES * NUM_SQ  # 704
NUM_FEATURES = NUM_KING_BUCKETS * FEATURES_PER_BUCKET  # 22528
MAX_ACTIVE_FEATURES = 32

L1 = 1024  # feature-transformer width
L1_HALF = L1 // 2  # pairwise-multiplied halves
NUM_PSQT_BUCKETS = 8
L2 = 15  # l1 outputs going through activations (+1 skip neuron)
L3 = 32

# Quantization
FT_CLIP = 127
PAIRWISE_SHIFT = 7
WEIGHT_SCALE_BITS = 6
SQR_SHIFT = 2 * WEIGHT_SCALE_BITS + PAIRWISE_SHIFT  # 19
FV_SCALE = 16
SKIP_NUM = 600 * FV_SCALE  # skip-neuron scale numerator
SKIP_DEN = 127 * (1 << WEIGHT_SCALE_BITS)

# Serialization (little-endian), nnue-pytorch/SF compatible framing
FILE_VERSION = 0x7AF32F20
ARCH_HASH = 0x3E5AA6EE  # HalfKAv2_hm + SFNNv5 stack (public constant)
ARCH_DESCRIPTION = (
    b"Features=HalfKAv2_hm(Friend)[22528->1024x2],"
    b"Network=AffineTransform[1->32](ClippedReLU[32](AffineTransform[32->30]"
    b"(SqrClippedReLU+ClippedReLU[15](AffineTransform[15+1<-1024]))))"
)


def psqt_bucket(piece_count: int) -> int:
    """Layer-stack / PSQT bucket from total piece count (1..32)."""
    return min(NUM_PSQT_BUCKETS - 1, max(0, (piece_count - 1) // 4))
