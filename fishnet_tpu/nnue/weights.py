"""NNUE weight container: random init, save/load in the SF-style binary
layout described in spec.py.

The reference treats nets as opaque embedded assets (assets.rs:128-133,
build.rs:306); here weights are a first-class object shared by the C++
scalar evaluator, the JAX evaluator, and the trainer.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from pathlib import Path
from typing import BinaryIO, Union

import numpy as np

from fishnet_tpu.nnue import spec


@dataclass
class NnueWeights:
    # Feature transformer
    ft_weight: np.ndarray  # [NUM_FEATURES, L1] int16
    ft_bias: np.ndarray  # [L1] int16
    ft_psqt: np.ndarray  # [NUM_FEATURES, NUM_PSQT_BUCKETS] int32
    # Per-bucket layer stacks
    l1_weight: np.ndarray  # [8, L2+1, L1] int8
    l1_bias: np.ndarray  # [8, L2+1] int32
    l2_weight: np.ndarray  # [8, L3, 2*L2] int8
    l2_bias: np.ndarray  # [8, L3] int32
    out_weight: np.ndarray  # [8, 1, L3] int8
    out_bias: np.ndarray  # [8, 1] int32

    def validate(self) -> None:
        assert self.ft_weight.shape == (spec.NUM_FEATURES, spec.L1)
        assert self.ft_weight.dtype == np.int16
        assert self.ft_bias.shape == (spec.L1,) and self.ft_bias.dtype == np.int16
        assert self.ft_psqt.shape == (spec.NUM_FEATURES, spec.NUM_PSQT_BUCKETS)
        assert self.ft_psqt.dtype == np.int32
        b = spec.NUM_PSQT_BUCKETS
        assert self.l1_weight.shape == (b, spec.L2 + 1, spec.L1)
        assert self.l1_weight.dtype == np.int8
        assert self.l1_bias.shape == (b, spec.L2 + 1) and self.l1_bias.dtype == np.int32
        assert self.l2_weight.shape == (b, spec.L3, 2 * spec.L2)
        assert self.l2_weight.dtype == np.int8
        assert self.l2_bias.shape == (b, spec.L3) and self.l2_bias.dtype == np.int32
        assert self.out_weight.shape == (b, 1, spec.L3)
        assert self.out_weight.dtype == np.int8
        assert self.out_bias.shape == (b, 1) and self.out_bias.dtype == np.int32

    # -- constructors -----------------------------------------------------

    @classmethod
    def random(cls, seed: int = 0) -> "NnueWeights":
        """A random but *plausible* net: FT weights small so accumulators
        stay in int16 range with 32 active features."""
        rng = np.random.default_rng(seed)
        b = spec.NUM_PSQT_BUCKETS
        return cls(
            ft_weight=rng.integers(-32, 33, (spec.NUM_FEATURES, spec.L1)).astype(np.int16),
            ft_bias=rng.integers(-128, 129, (spec.L1,)).astype(np.int16),
            ft_psqt=rng.integers(-6000, 6001, (spec.NUM_FEATURES, b)).astype(np.int32),
            l1_weight=rng.integers(-64, 65, (b, spec.L2 + 1, spec.L1)).astype(np.int8),
            l1_bias=rng.integers(-8192, 8193, (b, spec.L2 + 1)).astype(np.int32),
            l2_weight=rng.integers(-64, 65, (b, spec.L3, 2 * spec.L2)).astype(np.int8),
            l2_bias=rng.integers(-8192, 8193, (b, spec.L3)).astype(np.int32),
            out_weight=rng.integers(-64, 65, (b, 1, spec.L3)).astype(np.int8),
            out_bias=rng.integers(-8192, 8193, (b, 1)).astype(np.int32),
        )

    def fingerprint(self) -> int:
        """Stable 64-bit digest of the CANONICAL serialized form (what
        ``save`` writes), so ``w.fingerprint()`` equals a blake2b over
        the ``.nnue`` file byte-for-byte. The eval cache mixes this into
        its keys so a process serving (or respawning into) a different
        network never reads the old network's evals
        (search/eval_cache.py net_fingerprint)."""
        import hashlib

        h = hashlib.blake2b(digest_size=8)

        class _HashSink:
            def write(self, b: bytes) -> None:
                h.update(b)

        self._write(_HashSink())
        return int.from_bytes(h.digest(), "little")

    # -- serialization ----------------------------------------------------

    def save(self, path: Union[str, Path]) -> None:
        with open(path, "wb") as f:
            self._write(f)

    def _write(self, f: BinaryIO) -> None:
        f.write(struct.pack("<II", spec.FILE_VERSION, spec.ARCH_HASH))
        f.write(struct.pack("<I", len(spec.ARCH_DESCRIPTION)))
        f.write(spec.ARCH_DESCRIPTION)
        # Feature transformer (hash framing as in the SF format).
        f.write(struct.pack("<I", 0x5D69D5B8))
        f.write(self.ft_bias.astype("<i2").tobytes())
        f.write(self.ft_weight.astype("<i2").tobytes())
        f.write(self.ft_psqt.astype("<i4").tobytes())
        # Layer stacks, bucket-major. The l2 rows are padded to
        # spec.L2_PADDED_INPUTS on disk (SF serializes affine inputs
        # rounded up to 32; the pad columns are zero).
        for b in range(spec.NUM_PSQT_BUCKETS):
            f.write(struct.pack("<I", 0x63337156))
            f.write(self.l1_bias[b].astype("<i4").tobytes())
            f.write(self.l1_weight[b].astype("<i1").tobytes())
            f.write(self.l2_bias[b].astype("<i4").tobytes())
            l2 = np.zeros((spec.L3, spec.L2_PADDED_INPUTS), np.int8)
            l2[:, : 2 * spec.L2] = self.l2_weight[b]
            f.write(l2.astype("<i1").tobytes())
            f.write(self.out_bias[b].astype("<i4").tobytes())
            f.write(self.out_weight[b].astype("<i1").tobytes())

    @classmethod
    def load(cls, path: Union[str, Path]) -> "NnueWeights":
        data = Path(path).read_bytes()
        off = 0

        def take(n: int) -> bytes:
            nonlocal off
            chunk = data[off : off + n]
            if len(chunk) != n:
                raise ValueError(
                    "truncated nnue file (wanted "
                    f"{n} bytes at offset {off}, {len(data) - off} left). "
                    "Note: nets saved by pre-r2 builds of this framework "
                    "used unpadded l2 rows and are exactly 512 bytes/stack "
                    "short of the SF/nnue-pytorch layout — re-export them "
                    "with the current build."
                )
            off += n
            return chunk

        version, arch_hash = struct.unpack("<II", take(8))
        if version != spec.FILE_VERSION:
            raise ValueError(f"unsupported nnue version 0x{version:08X}")
        if arch_hash != spec.ARCH_HASH:
            raise ValueError(
                f"wrong architecture hash 0x{arch_hash:08X} "
                f"(expected 0x{spec.ARCH_HASH:08X})"
            )
        (desc_len,) = struct.unpack("<I", take(4))
        take(desc_len)  # description string (informational)
        take(4)  # FT hash

        def arr(dtype: str, shape) -> np.ndarray:
            count = int(np.prod(shape))
            itemsize = np.dtype(dtype).itemsize
            return np.frombuffer(take(count * itemsize), dtype=dtype).reshape(shape).copy()

        ft_bias = arr("<i2", (spec.L1,))
        ft_weight = arr("<i2", (spec.NUM_FEATURES, spec.L1))
        ft_psqt = arr("<i4", (spec.NUM_FEATURES, spec.NUM_PSQT_BUCKETS))

        nb = spec.NUM_PSQT_BUCKETS
        l1_w = np.empty((nb, spec.L2 + 1, spec.L1), np.int8)
        l1_b = np.empty((nb, spec.L2 + 1), np.int32)
        l2_w = np.empty((nb, spec.L3, 2 * spec.L2), np.int8)
        l2_b = np.empty((nb, spec.L3), np.int32)
        o_w = np.empty((nb, 1, spec.L3), np.int8)
        o_b = np.empty((nb, 1), np.int32)
        for b in range(nb):
            take(4)  # stack hash
            l1_b[b] = arr("<i4", (spec.L2 + 1,))
            l1_w[b] = arr("<i1", (spec.L2 + 1, spec.L1))
            l2_b[b] = arr("<i4", (spec.L3,))
            # On disk the l2 rows span the PADDED input width; the pad
            # columns carry no weights.
            l2_w[b] = arr("<i1", (spec.L3, spec.L2_PADDED_INPUTS))[:, : 2 * spec.L2]
            o_b[b] = arr("<i4", (1,))
            o_w[b] = arr("<i1", (1, spec.L3))

        weights = cls(
            ft_weight=ft_weight.astype(np.int16),
            ft_bias=ft_bias.astype(np.int16),
            ft_psqt=ft_psqt.astype(np.int32),
            l1_weight=l1_w,
            l1_bias=l1_b,
            l2_weight=l2_w,
            l2_bias=l2_b,
            out_weight=o_w,
            out_bias=o_b,
        )
        weights.validate()
        return weights
