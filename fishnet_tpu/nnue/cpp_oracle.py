"""Python handle to the C++ scalar NNUE evaluator — the parity oracle."""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Union

from fishnet_tpu.chess.board import Board, UnsupportedVariantError
from fishnet_tpu.chess.core import NativeCoreError, load


class CppNnue:
    def __init__(self, path: Union[str, Path]) -> None:
        self._lib = load()
        err = ctypes.create_string_buffer(256)
        self._net = self._lib.fc_nnue_load(str(path).encode(), err, len(err))
        if not self._net:
            raise NativeCoreError(
                f"failed to load nnue {path}: {err.value.decode(errors='replace')}"
            )

    def __del__(self) -> None:
        net = getattr(self, "_net", None)
        if net:
            self._lib.fc_nnue_free(net)
            self._net = None

    def evaluate(self, board: Board) -> int:
        """Centipawn score from the side to move's point of view."""
        value = self._lib.fc_nnue_evaluate(self._net, board._pos)
        if value == -(2**31):  # sentinel: variant position, NNUE undefined
            raise UnsupportedVariantError(
                "NNUE evaluation is defined for standard chess only"
            )
        return value
