"""Batched NNUE evaluation in JAX.

This is the TPU replacement for the reference's per-process CPU NNUE
(SURVEY.md §0): instead of one position at a time inside a Stockfish
subprocess, whole microbatches of positions are evaluated in one XLA
program. Two paths:

* ``evaluate_batch`` — exact integer semantics, bit-identical to the C++
  scalar oracle (cpp/src/nnue.cpp). Used for score-parity tests and when
  exactness matters more than speed.
* the same function is MXU-friendly: the small dense layers run as int8 x
  int8 -> int32 einsums over all 8 buckets with a final per-position
  bucket select (compute-all-select beats a gather of tiny weight
  matrices on TPU), and the feature-transformer gather is a plain
  embedding take+sum that XLA lowers to dynamic-gather + reduce. A fused
  Pallas kernel for the gather lives in fishnet_tpu/ops/.

Input convention: ``indices`` is int32 [B, 2, MAX_ACTIVE] of HalfKAv2_hm
feature indices — perspective 0 is the side to move — padded with
``NUM_FEATURES`` (a sentinel row of zeros appended to the weights), as
produced by the native core's ``fc_pos_features``.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.utils.tracing import is_concrete

#: Material poison for persistent anchor codes shipped WITHOUT the
#: host-side material term.  Persistent entries' PSQT accumulators live
#: host-side in the pool slot (not in the device anchor table), so the
#: on-device PSQT path cannot resolve them; under tracing the misuse
#: cannot raise, so the score is stamped with this instead — after
#: FV_SCALE the affected evals come back around ±2^24 centipawns,
#: unmistakably broken rather than plausibly wrong.
_POISON_MATERIAL = 1 << 28

Params = Dict[str, jax.Array]


def params_from_weights(weights: NnueWeights) -> Params:
    """Device-ready parameter pytree. The FT tables get a zero sentinel
    row at index NUM_FEATURES so padded feature slots are no-ops.
    (Removed-feature indices of incremental entries, spec.DELTA_BASE+f,
    are decoded by subtraction at eval time — the table stays single
    copy to keep the gather's random-read working set small.)"""
    ft_w = np.vstack([weights.ft_weight, np.zeros((1, spec.L1), np.int16)])
    ft_psqt = np.vstack(
        [weights.ft_psqt, np.zeros((1, spec.NUM_PSQT_BUCKETS), np.int32)]
    )
    return {
        "ft_w": jnp.asarray(ft_w),
        "ft_b": jnp.asarray(weights.ft_bias),
        "ft_psqt": jnp.asarray(ft_psqt),
        "l1_w": jnp.asarray(weights.l1_weight),
        "l1_b": jnp.asarray(weights.l1_bias),
        "l2_w": jnp.asarray(weights.l2_weight),
        "l2_b": jnp.asarray(weights.l2_bias),
        "out_w": jnp.asarray(weights.out_weight),
        "out_b": jnp.asarray(weights.out_bias),
    }


def _trunc_div(a: jax.Array, d: int) -> jax.Array:
    """C-style truncating integer division by a positive constant
    (jnp // floors; lax.div on ints truncates)."""
    return jax.lax.div(a, jnp.int32(d))


def evaluate_batch(
    params: Params,
    indices: jax.Array,
    buckets: jax.Array,
    parent: Optional[jax.Array] = None,
    material: Optional[jax.Array] = None,
) -> jax.Array:
    """Evaluate a batch. indices: integer [B, 2, 32] (stm perspective
    first, padded with NUM_FEATURES) — uint16 on the wire from the native
    pool (half the host->device bytes), any int dtype accepted; buckets:
    int32 [B]. Returns int32 [B] centipawn scores from the side to move's
    point of view.

    ``parent`` (optional, int32 [B]) enables incremental evaluation:
    -1 marks a standalone full entry; code >= 0 means this entry's
    indices are DELTAS (removals via spec.DELTA_BASE + i) against batch
    entry ``code >> 1``'s accumulator, with the perspectives swapped
    when ``code & 1`` (the sides to move differ). The native pool
    guarantees the referenced entry is the MOST RECENT preceding full
    entry — the fused kernel's in-VMEM anchor resolution depends on it
    (ops/ft_gather.py). Exact: integer adds commute, so delta
    reconstruction is bit-identical to a full gather.

    ``material`` (optional, int32 [B]): the bucket-selected PSQT
    material term, precomputed HOST-side by the native pool during
    feature extraction (cpp/src/pool.cpp fill_full/fill_delta). When
    given, the device skips the whole PSQT path; when None the PSQT
    accumulator is produced ON DEVICE by the same fused pass that
    builds the feature-transformer accumulators (ops/ft_gather.py
    fused PSQT; the XLA fallback is bit-identical) — the production
    wire ships no material at all (doc/wire-format.md).
    """
    indices = indices.astype(jnp.int32)
    # Feature transformer: fused Pallas gather-accumulate on TPU (single
    # HBM pass per row, incremental entries resolved against the running
    # anchor), XLA take+sum elsewhere. [B, 2, L1] int32.
    from fishnet_tpu.ops.ft_gather import ft_accumulate

    psqt = None
    if parent is None:
        # Full entries only: no removal encodings can appear, so skip
        # the decode arithmetic entirely in this trace. Without host
        # material the PSQT accumulator rides the same fused pass.
        if material is None:
            acc, psqt = ft_accumulate(
                params["ft_w"], params["ft_b"], indices,
                ft_psqt=params["ft_psqt"],
            )
        else:
            acc = ft_accumulate(params["ft_w"], params["ft_b"], indices)
    else:
        # Incremental entries: the dense entry point carries no anchor
        # tables, so PSQT for material-less calls resolves in
        # _evaluate_from_acc (XLA decode + in-batch refs; persistent
        # codes poison there — the anchored packed path is where tables
        # live, evaluate_packed_anchored).
        acc = ft_accumulate(
            params["ft_w"],
            params["ft_b"],
            indices,
            delta_base=spec.DELTA_BASE,
            parent=parent,
        )
    return _evaluate_from_acc(
        params, acc, indices, buckets, parent, material, psqt=psqt
    )


def _evaluate_from_acc(
    params: Params,
    acc: jax.Array,
    indices: jax.Array,
    buckets: jax.Array,
    parent: Optional[jax.Array],
    material: Optional[jax.Array],
    psqt: Optional[jax.Array] = None,
) -> jax.Array:
    """The network head past the feature transformer: clipped pairwise
    multiply, bucketed dense stack, PSQT/material blend (see
    evaluate_batch for semantics). ``psqt`` (int32 [B, 2, 8], fully
    RESOLVED — the fused kernel's second output) short-circuits this
    function's own XLA PSQT gather; without it the fallback here
    resolves IN-BATCH refs only, so entries carrying persistent anchor
    codes must either arrive with ``psqt`` (anchor-PSQT table path) or
    ship a host-computed ``material``."""
    psqt_resolved = psqt is not None  # tables resolved everything already
    if material is None and psqt is None:
        if parent is not None and is_concrete(parent):
            if bool((np.asarray(parent) <= -2).any()):
                raise ValueError(
                    "persistent anchor codes require host-side material "
                    "or a device-resolved psqt"
                )
        if parent is None:
            psqt_rows = jnp.take(params["ft_psqt"], indices, axis=0)
            psqt = jnp.sum(psqt_rows, axis=2)  # [B, 2, 8] int32
        else:
            # PSQT accumulators, honoring removal encodings (DELTA_BASE
            # + f subtracts feature f's row; its pad decodes to the
            # sentinel), then resolved against the referenced entries.
            is_rem = indices >= spec.DELTA_BASE
            base_idx = jnp.where(is_rem, indices - spec.DELTA_BASE, indices)
            sign = jnp.where(is_rem, -1, 1)
            psqt_rows = jnp.take(params["ft_psqt"], base_idx, axis=0)
            psqt = jnp.sum(psqt_rows * sign[..., None], axis=2)  # [B, 2, 8]
            parent = parent.astype(jnp.int32)
            valid = parent >= 0
            ref = jnp.where(valid, parent >> 1, 0)
            swap = (parent & 1).astype(bool)
            perm = jnp.where(
                swap[:, None], jnp.array([1, 0]), jnp.array([0, 1])
            )  # [B, 2]
            ref_psqt = jnp.take_along_axis(
                jnp.take(psqt, ref, axis=0), perm[:, :, None], axis=1
            )
            psqt = jnp.where(valid[:, None, None], psqt + ref_psqt, psqt)

    # Clipped pairwise multiply; stm half first.
    c = jnp.clip(acc, 0, spec.FT_CLIP)
    pair = (c[..., : spec.L1_HALF] * c[..., spec.L1_HALF :]) >> spec.PAIRWISE_SHIFT
    x = pair.reshape(pair.shape[0], spec.L1)  # [B, 1024] in 0..126

    # l1 over all 8 buckets on the MXU, then per-position select.
    y_all = (
        jnp.einsum(
            "bi,koi->bko",
            x.astype(jnp.int8),
            params["l1_w"],
            preferred_element_type=jnp.int32,
        )
        + params["l1_b"][None, :, :]
    )  # [B, 8, 16]
    y = jnp.take_along_axis(y_all, buckets[:, None, None], axis=1)[:, 0]  # [B, 16]

    skip = y[:, spec.L2]
    h = y[:, : spec.L2]

    # sqr-clipped: clamp |h| first so h*h stays in int32; values past the
    # clamp square to >= 127 anyway (see nnue.cpp for the same identity).
    hs = jnp.clip(h, -8192, 8192)
    sq = jnp.minimum((hs * hs) >> spec.SQR_SHIFT, spec.FT_CLIP)
    ca = jnp.clip(h >> spec.WEIGHT_SCALE_BITS, 0, spec.FT_CLIP)
    act = jnp.concatenate([sq, ca], axis=1)  # [B, 30] in 0..127

    z_all = (
        jnp.einsum(
            "bi,koi->bko",
            act.astype(jnp.int8),
            params["l2_w"],
            preferred_element_type=jnp.int32,
        )
        + params["l2_b"][None, :, :]
    )  # [B, 8, 32]
    z = jnp.take_along_axis(z_all, buckets[:, None, None], axis=1)[:, 0]
    z = jnp.clip(z >> spec.WEIGHT_SCALE_BITS, 0, spec.FT_CLIP)

    v_all = (
        jnp.einsum(
            "bi,koi->bko",
            z.astype(jnp.int8),
            params["out_w"],
            preferred_element_type=jnp.int32,
        )
        + params["out_b"][None, :, :]
    )  # [B, 8, 1]
    v = jnp.take_along_axis(v_all, buckets[:, None, None], axis=1)[:, 0, 0]

    if material is None:
        psqt_sel = jnp.take_along_axis(
            psqt, jnp.repeat(buckets[:, None, None], 2, axis=1), axis=2
        )[..., 0]
        material = _trunc_div(psqt_sel[:, 0] - psqt_sel[:, 1], 2)
        if parent is not None and not psqt_resolved:
            # Structural twin of the eager guard above for TRACED parents:
            # without a device-resolved psqt, anchor-code entries (<= -2)
            # have PSQT state this fallback cannot see — poison their
            # scores so the misuse is visible (see _POISON_MATERIAL).
            material = jnp.where(
                parent.astype(jnp.int32) <= -2,
                jnp.int32(_POISON_MATERIAL),
                material,
            )
    else:
        material = material.astype(jnp.int32)
    positional = v + skip + _trunc_div(skip * 23, 127)
    return _trunc_div(positional + material, spec.FV_SCALE)


#: jit of evaluate_batch; ``parent=None`` (full entries only) and
#: ``parent=array`` (may carry incremental entries) trace separately.
evaluate_batch_jit = jax.jit(evaluate_batch)


def expand_packed(
    packed: jax.Array, offsets: jax.Array, parent: jax.Array
) -> jax.Array:
    """Expand the COMPACT WIRE FORMAT back to dense [B, 2, 32] indices.

    ``packed`` [R, 2, 8] rows (any int dtype; uint16 on the wire from
    cpp/src/pool.cpp emit_block), ``offsets`` int32 [B] row offsets:
    a full entry (parent < 0) owns 4 consecutive rows — its 32 slots
    per perspective, 8 at a time; a delta entry owns ONE row (its
    2*DELTA_SLOTS live slots) and its slots [8, 32) are sentinel by
    wire contract. Deltas therefore ship 32 bytes instead of 128 —
    the host->device payload cut lands exactly on the entries
    speculation multiplies (VERDICT r3 item 4).

    The expansion is one gather + select on device (~sub-ms against a
    multi-ms eval step); the dense array then feeds the unchanged
    gather kernel, so packed and dense evaluation are bit-identical.
    """
    packed = packed.astype(jnp.int32)  # [R, 2, 8]
    offsets = offsets.astype(jnp.int32)
    rows = offsets[:, None] + jnp.arange(4, dtype=jnp.int32)[None, :]  # [B, 4]
    rows = jnp.clip(rows, 0, packed.shape[0] - 1)
    g = jnp.take(packed, rows, axis=0)  # [B, 4, 2, 8]
    dense = jnp.transpose(g, (0, 2, 1, 3)).reshape(-1, 2, 4 * 8)  # [B, 2, 32]
    # Delta entries (in-batch or persistent): row 0 holds the live
    # slots, the rest is sentinel.
    sent = jnp.full(
        (dense.shape[0], 2, 3 * 8), spec.NUM_FEATURES, jnp.int32
    )
    delta_dense = jnp.concatenate([dense[:, :, :8], sent], axis=2)
    return jnp.where(_is_delta(parent)[:, None, None], delta_dense, dense)


def _is_delta(parent: jax.Array) -> jax.Array:
    """True for one-row (delta) entries under the wire's parent codes:
    in-batch refs (>= 0) and persistent anchor deltas (<= -2 with the
    delta bit); plain fulls (-1) and full anchor (re)seeds own 4 rows."""
    from fishnet_tpu.ops.ft_gather import decode_parent

    in_batch, persistent, _, _, _, _ = decode_parent(parent)
    return in_batch | persistent


def evaluate_packed(
    params: Params,
    packed: jax.Array,
    offsets: jax.Array,
    buckets: jax.Array,
    parent: jax.Array,
    material: Optional[jax.Array] = None,
) -> jax.Array:
    """evaluate_batch over the compact wire format (see expand_packed)."""
    dense = expand_packed(packed, offsets, parent)
    return evaluate_batch(params, dense, buckets, parent, material)


evaluate_packed_jit = jax.jit(evaluate_packed)


def evaluate_packed_anchored(
    params: Params,
    packed: jax.Array,
    buckets: jax.Array,
    parent: jax.Array,
    material: Optional[jax.Array],
    anchor_tab: jax.Array,
    n_rows: jax.Array,
    psqt_tab: jax.Array,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
):
    """evaluate_batch over the compact wire with PERSISTENT device-
    resident anchors (VERDICT r4 item 1): ``anchor_tab`` [A, 2, L1]
    int32 holds one feature-transformer accumulator per pool slot of
    the dispatching group; persistent parent codes resolve against it,
    and every anchor entry's resolved accumulator is scattered back to
    its row. ``psqt_tab`` [A, 2, 8] int32 is its PSQT twin: with
    ``material=None`` (the ABI 9 production wire) the PSQT accumulator
    is produced by the same fused pass as the feature-transformer
    accumulators, persistent codes resolve against ``psqt_tab``, and
    anchor entries' resolved PSQT scatters back alongside — the wire
    ships NO material and the old persistent-anchor poison limitation
    is gone. With ``material`` given (host-material fallback wire) the
    device PSQT path is skipped and ``psqt_tab`` rides through
    untouched. Returns ``(values, new_anchor_tab, new_psqt_tab)`` —
    the caller threads both tables into the next step's call, so they
    live on the device across steps and single demand evals ship one
    32-byte row instead of a 128-byte full entry.

    Two wire arrays are GONE relative to evaluate_packed: row offsets
    (derivable — entries own 4 rows when full, 1 when delta, so offsets
    are the exclusive cumsum) and the explicit store list (anchor codes
    carry their own table row). ``n_rows`` (int32 [1], the emitted row
    count) is what replaces the offsets array on the wire: padding
    entries' cumsum continues past the stream into STALE buffer rows
    whose contents can exceed the weight-table bounds (out-of-bounds
    DMAs in the fused kernel), so every offset clamps to ``n_rows``,
    where the service writes one sentinel block.

    ``use_pallas`` / ``interpret`` (static under jit) pin the
    feature-transformer executor instead of ft_accumulate's
    auto-selection — the degradation ladder's seam
    (resilience/supervisor.py): ``use_pallas=False`` forces the
    bit-identical XLA twin; ``interpret=True`` realizes the fused
    kernel in Pallas interpreter mode on non-TPU backends (the PR 2
    parity fixtures' venue).
    """
    parent = parent.astype(jnp.int32)
    rows_per = jnp.where(_is_delta(parent), 1, 4)
    offsets = jnp.cumsum(rows_per) - rows_per  # exclusive prefix sum
    offsets = jnp.minimum(offsets, n_rows.astype(jnp.int32)[0])
    return _packed_anchored_core(
        params, packed, offsets, buckets, parent, material,
        anchor_tab, psqt_tab, use_pallas, interpret,
    )


def _packed_anchored_core(
    params: Params,
    packed: jax.Array,
    offsets: jax.Array,
    buckets: jax.Array,
    parent: jax.Array,
    material: Optional[jax.Array],
    anchor_tab: jax.Array,
    psqt_tab: jax.Array,
    use_pallas: Optional[bool],
    interpret: bool,
    copy_src: Optional[jax.Array] = None,
):
    """Shared tail of the anchored packed entry points (single-group and
    segmented): expand the row stream, run the fused/XLA accumulate with
    table resolution, evaluate the head, and scatter anchor entries'
    resolved accumulators (and PSQT twins) back to their table rows.
    ``anchor_tab``/``psqt_tab`` are FLAT [A, 2, ...]; returns
    ``(values, new_tab, new_psqt_tab)`` with the same flat shapes.

    ``copy_src`` (optional int32 [B], the position-dedup fan-in from
    ``plan_segment_dedup``) redirects entries to a same-position source:
    after resolution, entry i's accumulator (and PSQT twin) is replaced
    by ``acc[copy_src[i]]`` — identity for kept entries. This is what
    makes PERSISTENT duplicates droppable from the wire: a sentinel'd
    store entry resolves to garbage, but the gather swaps in its
    source's accumulator (bit-identical — same position, same features)
    BEFORE the head eval and the anchor-table scatter, so the store
    still refreshes its row with the exact bytes the undropped entry
    would have written."""
    from fishnet_tpu.ops.ft_gather import decode_parent, ft_accumulate

    dense = expand_packed(packed, offsets, parent)
    psqt = None
    if material is None:
        acc, psqt = ft_accumulate(
            params["ft_w"],
            params["ft_b"],
            dense,
            use_pallas=use_pallas,
            interpret=interpret,
            delta_base=spec.DELTA_BASE,
            parent=parent,
            anchor_tab=anchor_tab,
            ft_psqt=params["ft_psqt"],
            psqt_tab=psqt_tab,
        )
    else:
        acc = ft_accumulate(
            params["ft_w"],
            params["ft_b"],
            dense,
            use_pallas=use_pallas,
            interpret=interpret,
            delta_base=spec.DELTA_BASE,
            parent=parent,
            anchor_tab=anchor_tab,
        )
    if copy_src is not None:
        # Position-dedup fan-in: duplicates take their source's resolved
        # accumulator (identity for non-duplicates), so sentinel'd store
        # entries still scatter the true bytes to their table rows.
        acc = jnp.take(acc, copy_src, axis=0)
        if psqt is not None:
            psqt = jnp.take(psqt, copy_src, axis=0)
    values = _evaluate_from_acc(
        params, acc, dense, buckets, parent, material, psqt=psqt
    )
    # Store anchor entries' resolved accumulators back to their rows.
    # Rows are unique within a batch (one block per pool slot per step),
    # so the scatter has no conflicts; non-anchor entries aim past the
    # table and drop.
    _, _, stores, _, _, aid = decode_parent(parent)
    row = jnp.where(stores, aid, anchor_tab.shape[0])
    new_tab = anchor_tab.at[row].set(
        acc.reshape(parent.shape[0], 2, -1), mode="drop"
    )
    new_psqt_tab = psqt_tab
    if psqt is not None:
        new_psqt_tab = psqt_tab.at[row].set(psqt, mode="drop")
    return values, new_tab, new_psqt_tab


#: The anchor tables are DONATED: the scatters update them in place
#: instead of copying every step (callers must rebind their handles to
#: the returned tables — the input buffers are dead after the call).
evaluate_packed_anchored_jit = jax.jit(
    evaluate_packed_anchored,
    donate_argnums=(5, 7),
    static_argnames=("use_pallas", "interpret"),
)


def evaluate_packed_anchored_segmented(
    params: Params,
    packed: jax.Array,
    buckets: jax.Array,
    parent: jax.Array,
    material: Optional[jax.Array],
    anchor_tabs: jax.Array,
    seg_rows: jax.Array,
    psqt_tabs: jax.Array,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
    copy_src: Optional[jax.Array] = None,
):
    """K groups' packed row streams fused into ONE device dispatch — the
    coalesced-dispatch wire (doc/wire-format.md "Segmented dispatch").

    Layout: ``packed`` [K*tier, 2, 8] is K per-group streams, each
    padded to the common row tier ``tier`` with its OWN sentinel block
    at its emitted-row count; ``buckets``/``parent`` (and ``material``
    on the host-material rung) are [K*size], each segment padded to the
    common entry bucket ``size`` with sentinel entries (parent -1,
    bucket 0); ``seg_rows`` int32 [K] carries each segment's emitted
    row count (the per-segment twin of the single-group ``n_rows``
    scalar). ``anchor_tabs`` [K, A, 2, L1] / ``psqt_tabs`` [K, A, 2, 8]
    are the dispatching groups' tables STACKED on a leading group axis,
    donated and returned exactly like the per-group call's tables.

    Parent codes arrive segment-local exactly as each group's pool
    emitted them; they are rebased on device
    (ops/ft_gather.recode_segment_parents) so in-batch refs and anchor
    table rows stay inside their segment — anchors never cross a
    segment boundary. The result is bit-identical, segment by segment,
    to K separate ``evaluate_packed_anchored`` calls on the same
    streams and tables (the tier-1 parity suite pins this across all
    three psqt_path rungs).

    Returns ``(values [K*size], new_anchor_tabs, new_psqt_tabs)``;
    segment k's real entries are ``values[k*size : k*size + n_k]``.

    ``copy_src`` (optional int32 [K*size], flat global indices) is the
    position-dedup fan-in — see ``_packed_anchored_core``. Segments of
    one fused dispatch always share a device, so cross-segment sources
    are plain local gathers.
    """
    from fishnet_tpu.ops.ft_gather import (
        derive_segment_offsets,
        recode_segment_parents,
    )

    k_segs = anchor_tabs.shape[0]
    anchor_rows = anchor_tabs.shape[1]
    size = buckets.shape[0] // k_segs
    tier = packed.shape[0] // k_segs
    parent = parent.astype(jnp.int32).reshape(k_segs, size)
    offsets = derive_segment_offsets(parent, seg_rows, tier)
    gparent = recode_segment_parents(parent, anchor_rows)
    flat_tab = anchor_tabs.reshape(k_segs * anchor_rows, 2, -1)
    flat_ptab = psqt_tabs.reshape(k_segs * anchor_rows, 2, -1)
    values, new_tab, new_ptab = _packed_anchored_core(
        params, packed, offsets, buckets, gparent, material,
        flat_tab, flat_ptab, use_pallas, interpret, copy_src=copy_src,
    )
    return (
        values,
        new_tab.reshape(anchor_tabs.shape),
        new_ptab.reshape(psqt_tabs.shape),
    )


#: Stacked tables donated, like the per-group jit.
evaluate_packed_anchored_segmented_jit = jax.jit(
    evaluate_packed_anchored_segmented,
    donate_argnums=(5, 7),
    static_argnames=("use_pallas", "interpret"),
)


def expand_packed_np(packed, offsets, parent):
    """NumPy twin of expand_packed, for hosts that must hand a DENSE
    batch to an external evaluator (the sharded serving path and test
    doubles take [B, 2, 32]; the native pool now always emits packed)."""
    packed = np.ascontiguousarray(packed)
    rows = offsets[:, None].astype(np.int64) + np.arange(4)
    np.clip(rows, 0, len(packed) - 1, out=rows)
    g = packed[rows]  # [B, 4, 2, 8]
    dense = np.transpose(g, (0, 2, 1, 3)).reshape(-1, 2, 32).copy()
    dense[is_delta_np(parent), :, 8:] = spec.NUM_FEATURES
    return dense


def is_delta_np(parent) -> "np.ndarray":
    """NumPy twin of _is_delta (one-row entries under the wire codes)."""
    parent = np.asarray(parent)
    v = -parent - 2
    return (parent >= 0) | ((parent <= -2) & ((v & 2) != 0))


def anchor_ids_np(parent) -> "np.ndarray":
    """NumPy twin of decode_parent's table-row extraction: the anchor
    row for entries with anchor codes (<= -2), 0 elsewhere."""
    parent = np.asarray(parent)
    v = -parent - 2
    return np.where(parent <= -2, v >> 2, 0)


def derive_offsets_np(parent, n_rows: int) -> "np.ndarray":
    """Host-side twin of the device's offset derivation: exclusive
    cumsum of rows-per-entry (4 full / 1 delta), padding clamped to the
    sentinel block at ``n_rows``."""
    rows_per = np.where(is_delta_np(parent), 1, 4)
    offsets = np.cumsum(rows_per) - rows_per
    return np.minimum(offsets, n_rows).astype(np.int32)
