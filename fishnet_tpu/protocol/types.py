"""Wire model of the fishnet HTTP/JSON work protocol.

These types mirror, field for field, the JSON bodies documented in the
reference's doc/protocol.md and implemented in src/api.rs:74-395. The
protocol must stay byte-compatible with lichess (lila), so all
serialization quirks of the reference are preserved:

* ``work.timeout`` is milliseconds, ``clock.wtime``/``btime`` are
  centiseconds, ``clock.inc`` is seconds (api.rs:140, 275-291);
* acquired ``moves`` is a single space-separated UCI string (api.rs:305);
* an analysis part's ``pv`` is a space-separated string and omitted when
  empty; ``nps`` is omitted when unknown (api.rs:355-369);
* a multipv "matrix" part serializes ``pv``/``score`` as
  multipv x depth nested arrays with nulls for missing cells
  (api.rs:370-380);
* scores are ``{"cp": n}`` or ``{"mate": n}`` (api.rs:382-388).

This module is pure data: no I/O, no chess logic. FENs and UCI moves stay
strings here; legality is enforced by the scheduler via the chess core.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union


class ProtocolError(ValueError):
    """Malformed JSON body from the server."""


# ---------------------------------------------------------------------------
# Engine / eval flavors (reference: src/assets.rs:378-431)
# ---------------------------------------------------------------------------


class EngineFlavor(enum.Enum):
    """Which engine tier handles a position.

    OFFICIAL is the standard-chess analysis path (NNUE eval); MULTI_VARIANT
    handles variants and all best-move jobs (classical HCE eval) — same
    routing as the reference (src/queue.rs:530-539).
    """

    OFFICIAL = "official"
    MULTI_VARIANT = "multivariant"

    def eval_flavor(self) -> "EvalFlavor":
        return EvalFlavor.NNUE if self is EngineFlavor.OFFICIAL else EvalFlavor.HCE


class EvalFlavor(enum.Enum):
    """Evaluation flavor reported to the server (api.rs:117-120)."""

    NNUE = "nnue"
    HCE = "classical"

    @property
    def is_nnue(self) -> bool:
        return self is EvalFlavor.NNUE

    @property
    def is_hce(self) -> bool:
        return self is EvalFlavor.HCE


# ---------------------------------------------------------------------------
# Variants (reference: shakmaty::variant::Variant, logger.rs:192-203)
# ---------------------------------------------------------------------------


class Variant(enum.Enum):
    STANDARD = "standard"
    ANTICHESS = "antichess"
    ATOMIC = "atomic"
    CRAZYHOUSE = "crazyhouse"
    HORDE = "horde"
    KING_OF_THE_HILL = "kingofthehill"
    RACING_KINGS = "racingkings"
    THREE_CHECK = "3check"

    @classmethod
    def parse(cls, s: Optional[str]) -> "Variant":
        if not s:
            return cls.STANDARD
        key = s.lower().replace(" ", "").replace("-", "")
        aliases = {
            "standard": cls.STANDARD,
            "chess960": cls.STANDARD,
            "fromposition": cls.STANDARD,
            "chess": cls.STANDARD,
            "antichess": cls.ANTICHESS,
            "atomic": cls.ATOMIC,
            "crazyhouse": cls.CRAZYHOUSE,
            "horde": cls.HORDE,
            "kingofthehill": cls.KING_OF_THE_HILL,
            "racingkings": cls.RACING_KINGS,
            "3check": cls.THREE_CHECK,
            "threecheck": cls.THREE_CHECK,
        }
        try:
            return aliases[key]
        except KeyError:
            raise ProtocolError(f"unknown variant: {s!r}") from None

    @property
    def is_standard(self) -> bool:
        return self is Variant.STANDARD

    def uci(self) -> str:
        """Variant name as spoken over UCI (`UCI_Variant`)."""
        return {
            Variant.STANDARD: "chess",
            Variant.ANTICHESS: "antichess",
            Variant.ATOMIC: "atomic",
            Variant.CRAZYHOUSE: "crazyhouse",
            Variant.HORDE: "horde",
            Variant.KING_OF_THE_HILL: "kingofthehill",
            Variant.RACING_KINGS: "racingkings",
            Variant.THREE_CHECK: "3check",
        }[self]

    def short_name(self) -> Optional[str]:
        from fishnet_tpu.utils.logger import short_variant_name

        return short_variant_name(self.value)


# ---------------------------------------------------------------------------
# Scores
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Score:
    """Centipawn or mate score (api.rs:382-388)."""

    kind: str  # "cp" | "mate"
    value: int

    @classmethod
    def cp(cls, value: int) -> "Score":
        return cls("cp", value)

    @classmethod
    def mate(cls, value: int) -> "Score":
        return cls("mate", value)

    def to_json(self) -> Dict[str, int]:
        return {self.kind: self.value}

    @classmethod
    def from_json(cls, data: Dict[str, int]) -> "Score":
        if "cp" in data:
            return cls.cp(int(data["cp"]))
        if "mate" in data:
            return cls.mate(int(data["mate"]))
        raise ProtocolError(f"invalid score: {data!r}")


# ---------------------------------------------------------------------------
# Work descriptions
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class NodeLimit:
    """Per-eval-flavor node limits assigned by the server
    (api.rs:207-220; doc/protocol.md:27-31)."""

    classical: int
    sf15: int

    def get(self, flavor: EvalFlavor) -> int:
        return self.sf15 if flavor.is_nnue else self.classical

    @classmethod
    def from_json(cls, data: Dict[str, int]) -> "NodeLimit":
        try:
            return cls(classical=int(data["classical"]), sf15=int(data["sf15"]))
        except (KeyError, TypeError, ValueError) as err:
            raise ProtocolError(f"invalid node limit: {data!r}") from err


class SkillLevel(enum.IntEnum):
    """Play-vs-computer level 1..8 with the reference's exact mapping to
    movetime / engine skill / depth (api.rs:222-273)."""

    ONE = 1
    TWO = 2
    THREE = 3
    FOUR = 4
    FIVE = 5
    SIX = 6
    SEVEN = 7
    EIGHT = 8

    def movetime_ms(self) -> int:
        return {1: 50, 2: 100, 3: 150, 4: 200, 5: 300, 6: 400, 7: 500, 8: 1000}[self.value]

    def skill_level(self) -> int:
        return {1: -9, 2: -5, 3: -1, 4: 3, 5: 7, 6: 11, 7: 16, 8: 20}[self.value]

    def depth(self) -> int:
        return {1: 5, 2: 5, 3: 5, 4: 5, 5: 5, 6: 8, 7: 13, 8: 22}[self.value]


@dataclass(frozen=True)
class Clock:
    """Game clock for best-move jobs: wtime/btime centiseconds, inc seconds
    (api.rs:275-291)."""

    wtime_centis: int
    btime_centis: int
    inc_seconds: int

    @property
    def wtime_ms(self) -> int:
        return self.wtime_centis * 10

    @property
    def btime_ms(self) -> int:
        return self.btime_centis * 10

    @property
    def inc_ms(self) -> int:
        return self.inc_seconds * 1000

    @classmethod
    def from_json(cls, data: Dict[str, int]) -> "Clock":
        try:
            return cls(
                wtime_centis=int(data["wtime"]),
                btime_centis=int(data["btime"]),
                inc_seconds=int(data["inc"]),
            )
        except (KeyError, TypeError, ValueError) as err:
            raise ProtocolError(f"invalid clock: {data!r}") from err


MAX_BATCH_ID_LEN = 24  # BatchId is capacity-bounded in the reference (api.rs:190-199)


def _parse_batch_id(raw: object) -> str:
    batch_id = str(raw)
    if not batch_id or len(batch_id) > MAX_BATCH_ID_LEN:
        raise ProtocolError(f"invalid batch id: {batch_id!r}")
    return batch_id


@dataclass(frozen=True)
class Work:
    """Tagged work description: analysis of a whole game, or a single
    best-move request (api.rs:130-188)."""

    kind: str  # "analysis" | "move"
    id: str
    # analysis
    nodes: Optional[NodeLimit] = None
    depth: Optional[int] = None
    multipv: Optional[int] = None
    timeout_ms: Optional[int] = None
    # move
    level: Optional[SkillLevel] = None
    clock: Optional[Clock] = None

    @property
    def is_analysis(self) -> bool:
        return self.kind == "analysis"

    @property
    def is_move(self) -> bool:
        return self.kind == "move"

    def effective_multipv(self) -> int:
        return self.multipv or 1

    @property
    def matrix_wanted(self) -> bool:
        return self.is_analysis and self.multipv is not None

    def timeout_seconds(self) -> float:
        """Per-position time budget: server-assigned for analysis, a flat
        2 s for best-move jobs (api.rs:160-165)."""
        if self.is_analysis:
            return (self.timeout_ms or 0) / 1000.0
        return 2.0

    @classmethod
    def from_json(cls, data: Dict) -> "Work":
        kind = data.get("type")
        try:
            if kind == "analysis":
                multipv = data.get("multipv")
                if multipv is not None:
                    multipv = int(multipv)
                    if multipv < 1:
                        raise ProtocolError("multipv must be >= 1")
                depth = data.get("depth")
                return cls(
                    kind="analysis",
                    id=_parse_batch_id(data["id"]),
                    nodes=NodeLimit.from_json(data["nodes"]),
                    depth=int(depth) if depth is not None else None,
                    multipv=multipv,
                    timeout_ms=int(data["timeout"]),
                )
            if kind == "move":
                clock = data.get("clock")
                return cls(
                    kind="move",
                    id=_parse_batch_id(data["id"]),
                    level=SkillLevel(int(data["level"])),
                    clock=Clock.from_json(clock) if clock else None,
                )
        except ProtocolError:
            raise
        except (KeyError, TypeError, ValueError) as err:
            raise ProtocolError(f"malformed work: {err}") from err
        raise ProtocolError(f"unknown work type: {kind!r}")


# ---------------------------------------------------------------------------
# Acquire response
# ---------------------------------------------------------------------------

STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


@dataclass(frozen=True)
class AcquireResponseBody:
    """Body of a 200/202 acquire response (api.rs:293-319)."""

    work: Work
    position: str  # root X-FEN
    variant: Variant = Variant.STANDARD
    moves: List[str] = field(default_factory=list)
    skip_positions: List[int] = field(default_factory=list)
    game_id: Optional[str] = None

    @classmethod
    def from_json(cls, data: Dict) -> "AcquireResponseBody":
        if "work" not in data:
            raise ProtocolError("missing work")
        work = Work.from_json(data["work"])
        moves_raw = data.get("moves", "")
        moves = moves_raw.split() if isinstance(moves_raw, str) else list(moves_raw)
        game_id = data.get("game_id") or None  # empty string means absent
        skips = data.get("skipPositions") or []
        try:
            skip_positions = [int(s) for s in skips]
        except (TypeError, ValueError) as err:
            raise ProtocolError(f"malformed skipPositions: {skips!r}") from err
        return cls(
            work=work,
            position=data.get("position") or STARTPOS,
            variant=Variant.parse(data.get("variant")),
            moves=moves,
            skip_positions=skip_positions,
            game_id=game_id,
        )

    def batch_url(self, endpoint_url: str) -> Optional[str]:
        """URL of the game on the website, for log/progress display
        (api.rs:311-319)."""
        if not self.game_id:
            return None
        from urllib.parse import urlsplit, urlunsplit

        parts = urlsplit(endpoint_url)
        return urlunsplit((parts.scheme, parts.netloc, f"/{self.game_id}", "", ""))


class AcquiredKind(enum.Enum):
    ACCEPTED = "accepted"
    NO_CONTENT = "no_content"
    REJECTED = "rejected"


@dataclass(frozen=True)
class Acquired:
    """Outcome of an acquire request (api.rs:321-328). REJECTED means the
    server answered 400/401/403/406 and the client must stop
    (doc/protocol.md:240-244)."""

    kind: AcquiredKind
    body: Optional[AcquireResponseBody] = None

    @classmethod
    def accepted(cls, body: AcquireResponseBody) -> "Acquired":
        return cls(AcquiredKind.ACCEPTED, body)

    @classmethod
    def no_content(cls) -> "Acquired":
        return cls(AcquiredKind.NO_CONTENT)

    @classmethod
    def rejected(cls) -> "Acquired":
        return cls(AcquiredKind.REJECTED)


# ---------------------------------------------------------------------------
# Analysis output
# ---------------------------------------------------------------------------


class Matrix:
    """multipv x depth matrix of values, as accumulated from engine `info`
    lines (reference: src/ipc.rs:67-93). ``best()`` is the deepest entry of
    the first PV."""

    def __init__(self) -> None:
        self.rows: List[List[Optional[object]]] = []

    def set(self, multipv: int, depth: int, value: object) -> None:
        while len(self.rows) < multipv:
            self.rows.append([])
        row = self.rows[multipv - 1]
        while len(row) <= depth:
            row.append(None)
        row[depth] = value

    def best(self) -> Optional[object]:
        if not self.rows or not self.rows[0]:
            return None
        return self.rows[0][-1]

    def to_json(self) -> List[List[Optional[object]]]:
        return self.rows


AnalysisPartJson = Dict[str, object]


class AnalysisPart:
    """One entry of the submitted ``analysis`` array (api.rs:352-380)."""

    @staticmethod
    def skipped() -> AnalysisPartJson:
        return {"skipped": True}

    @staticmethod
    def best(
        pv: List[str],
        score: Score,
        depth: int,
        nodes: int,
        time_ms: int,
        nps: Optional[int] = None,
    ) -> AnalysisPartJson:
        part: AnalysisPartJson = {
            "score": score.to_json(),
            "depth": depth,
            "nodes": nodes,
            "time": time_ms,
        }
        if pv:
            part["pv"] = " ".join(pv)
        if nps is not None:
            part["nps"] = nps
        return part

    @staticmethod
    def matrix(
        pv: List[List[Optional[List[str]]]],
        score: List[List[Optional[Score]]],
        depth: int,
        nodes: int,
        time_ms: int,
        nps: Optional[int] = None,
    ) -> AnalysisPartJson:
        part: AnalysisPartJson = {
            "pv": pv,
            "score": [
                [cell.to_json() if cell is not None else None for cell in row]
                for row in score
            ],
            "depth": depth,
            "nodes": nodes,
            "time": time_ms,
        }
        if nps is not None:
            part["nps"] = nps
        return part


# ---------------------------------------------------------------------------
# Request bodies (client -> server)
# ---------------------------------------------------------------------------


def fishnet_header(version: str, key: Optional[str]) -> Dict[str, str]:
    """The ``fishnet`` object present in every POST body (api.rs:102-115)."""
    return {"version": version, "apikey": key or ""}


def void_request_body(version: str, key: Optional[str]) -> Dict:
    return {"fishnet": fishnet_header(version, key)}


def analysis_request_body(
    version: str,
    key: Optional[str],
    flavor: EvalFlavor,
    analysis: List[Optional[AnalysisPartJson]],
) -> Dict:
    return {
        "fishnet": fishnet_header(version, key),
        "stockfish": {"flavor": flavor.value},
        "analysis": analysis,
    }


def move_request_body(version: str, key: Optional[str], best_move: Optional[str]) -> Dict:
    return {
        "fishnet": fishnet_header(version, key),
        "move": {"bestmove": best_move},
    }


# ---------------------------------------------------------------------------
# Status (server queue monitoring)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class QueueStatus:
    """May be negative: lila computes these as differences of non-atomic
    measurements (api.rs:85-95)."""

    acquired: int = 0
    queued: int = 0
    oldest_seconds: int = 0

    @classmethod
    def from_json(cls, data: Dict) -> "QueueStatus":
        return cls(
            acquired=int(data.get("acquired", 0)),
            queued=int(data.get("queued", 0)),
            oldest_seconds=int(data.get("oldest", 0)),
        )


@dataclass(frozen=True)
class AnalysisStatus:
    user: QueueStatus = QueueStatus()
    system: QueueStatus = QueueStatus()

    @classmethod
    def from_json(cls, data: Dict) -> "AnalysisStatus":
        analysis = data.get("analysis", {})
        return cls(
            user=QueueStatus.from_json(analysis.get("user", {})),
            system=QueueStatus.from_json(analysis.get("system", {})),
        )
