"""Process entry point: ``python -m fishnet_tpu``.

Equivalent of the reference's main()/run() supervisor
(src/main.rs:44-260): resolve config, dispatch subcommands, then start
the actor fleet — API actor, queue actor, one worker per core — with
two-phase signal handling (first SIGINT drains, second aborts) and the
120 s summary line.
"""

from __future__ import annotations

import os as _os

# SSLKEYLOGFILE is applied globally by CPython's ssl module (and thus by
# aiohttp at import time); an unopenable path would otherwise crash the
# process inside `import aiohttp`. Validate early and degrade to a
# warning, matching the reference's rustls KeyLogFile behavior.
_keylog = _os.environ.get("SSLKEYLOGFILE")
if _keylog:
    try:
        with open(_keylog, "a"):
            pass
    except OSError as _err:
        import sys as _sys

        _sys.stderr.write(f"W: Ignoring unopenable SSLKEYLOGFILE {_keylog!r}: {_err}\n")
        del _os.environ["SSLKEYLOGFILE"]

import asyncio
import signal
import sys
from typing import Optional

from fishnet_tpu import configure as configure_mod
from fishnet_tpu import systemd as systemd_mod
from fishnet_tpu.configure import ConfigError, Opt
from fishnet_tpu.engine.base import EngineFactory
from fishnet_tpu.sched.queue import BacklogOpt
from fishnet_tpu.utils.logger import Logger
from fishnet_tpu.utils.stats import StatsRecorder
from fishnet_tpu.version import __version__

LICENSE_NOTICE = """\
fishnet-tpu is free software: you can redistribute it and/or modify it
under the terms of the GNU General Public License as published by the
Free Software Foundation, either version 3 of the License, or (at your
option) any later version. It is distributed WITHOUT ANY WARRANTY; see
https://www.gnu.org/licenses/gpl-3.0.html for the full text.
"""


def _check_key_over_network(endpoint: str, key: str) -> Optional[str]:
    """Live key validation for the config dialog (configure.rs:474-492)."""
    from fishnet_tpu.net import api as api_mod

    async def check() -> Optional[str]:
        stub, actor = api_mod.channel(endpoint, key, Logger())
        task = asyncio.ensure_future(actor.run())
        try:
            err = await asyncio.wait_for(stub.check_key(), timeout=15.0)
            return None if err is None else str(err)
        except asyncio.TimeoutError:
            return None  # network error: accept, like the reference retry path
        finally:
            actor.stop()
            task.cancel()

    try:
        return asyncio.run(check())
    except Exception as err:  # server unreachable: don't block configuration
        sys.stderr.write(f"W: Could not verify key: {err}\n")
        return None


def validate_mesh(opt: Opt) -> None:
    """Fail an explicit --mesh DxM that exceeds the visible devices NOW,
    with a clean ConfigError — the service itself is built lazily (inside
    the engine factory's rebuild path), where a config mistake would
    otherwise surface as an endless worker-restart backoff loop."""
    mesh_spec = opt.resolved_mesh()
    if mesh_spec in ("auto", "off"):
        return
    import jax

    data, model = (int(x) for x in mesh_spec.split("x"))
    n = len(jax.devices())
    if data * model > n:
        raise ConfigError(f"--mesh {mesh_spec} needs {data * model} devices, found {n}")


def build_sharded_evaluator(opt: Opt, weights, logger: Logger):
    """The LEGACY multi-chip tier: one ShardedEvaluator (shard_map) that
    splits every eval microbatch over a single mesh-wide program. Only
    built for an EXPLICIT --mesh DxM with model > 1 — a tensor-parallel
    request the placement-aware serving mesh (per-shard placement,
    doc/sharding.md) cannot express. "auto" and data-only meshes return
    None: SearchService drives those per shard from the coalescer."""
    mesh_spec = opt.resolved_mesh()
    if mesh_spec in ("off", "auto"):
        return None
    import jax

    validate_mesh(opt)
    data, model = (int(x) for x in mesh_spec.split("x"))
    if model <= 1:
        return None  # data-only: the placement-aware path serves it
    from fishnet_tpu.nnue.jax_eval import params_from_weights
    from fishnet_tpu.parallel.mesh import ShardedEvaluator, make_mesh

    mesh = make_mesh(jax.devices()[: data * model], data=data, model=model)
    logger.info(
        f"Sharding eval batches over a {mesh.devices.shape[0]}x"
        f"{mesh.devices.shape[1]} device mesh (single fused program)."
    )
    return ShardedEvaluator(
        params_from_weights(weights),
        mesh=mesh,
        batch_capacity=opt.resolved_microbatch(),
    )


def resolve_mesh_devices(opt: Opt, evaluator, logger: Logger):
    """The placement-aware serving mesh request for SearchService
    (doc/sharding.md): "auto" follows the visible devices, an explicit
    data-only DxM pins the shard count, and anything served by the
    legacy evaluator (or --mesh off) stays single-device. The service
    itself degrades to the single-device path when fewer than two
    devices remain (or FISHNET_NO_MESH=1)."""
    mesh_spec = opt.resolved_mesh()
    if evaluator is not None or mesh_spec == "off":
        return None
    if mesh_spec == "auto":
        import jax

        if len(jax.devices()) < 2:
            return None
        logger.info(
            f"Placement-aware serving mesh over {len(jax.devices())} "
            "devices (per-shard dispatch from the coalescer)."
        )
        return "auto"
    validate_mesh(opt)
    data, model = (int(x) for x in mesh_spec.split("x"))
    n = data * model
    if n < 2:
        return None
    logger.info(
        f"Placement-aware serving mesh over {n} devices "
        "(per-shard dispatch from the coalescer)."
    )
    return n


def build_search_service(opt: Opt, logger: Logger, psqt_path=None):
    """The shared batched-search backend, from CLI options (dev-mode
    random weights when no --nnue-file is given). Without --pipeline the
    depth is probed for DEVICE dispatch overlap and floored at 2: even
    on fully serialized tunnels the host phase (fiber stepping, feature
    extraction) overlaps the other group's wire wait. With >1 visible
    device (or an explicit --mesh) the service drives the whole mesh
    from the coalescer — per-shard placed dispatches, doc/sharding.md —
    while an explicit model-parallel DxM falls back to the legacy
    single-program ShardedEvaluator. ``psqt_path`` requests a
    rung of the eval-path lattice (the degradation ladder's seam,
    resilience/supervisor.py); None = auto-select."""
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.search.service import SearchService, suggest_pipeline_depth

    if opt.nnue_file:
        weights = NnueWeights.load(opt.nnue_file)
    else:
        logger.warn("No --nnue-file given; using random NNUE weights (dev mode).")
        weights = NnueWeights.random(seed=0)

    # Split plane (FISHNET_RPC=1, doc/disaggregation.md): this process
    # is a FRONTEND — no local evaluator, no dispatch probe; every eval
    # microbatch rides the shared-memory ring to the evaluator host.
    # Unset/0 falls through to the monolithic build below byte-for-byte.
    from fishnet_tpu.rpc import rpc_enabled

    if rpc_enabled():
        from fishnet_tpu.rpc.client import RemoteBackend

        logger.info(
            "FISHNET_RPC=1: frontend role — eval traffic rides the "
            "shared-memory ring transport to the evaluator host."
        )
        return RemoteBackend(
            weights=weights,
            net_path=opt.nnue_file,
            batch_capacity=opt.resolved_microbatch(),
            pipeline_depth=opt.pipeline or 2,
            driver_threads=opt.resolved_search_threads(),
            psqt_path=psqt_path,
        )

    evaluator = build_sharded_evaluator(opt, weights, logger)
    mesh_devices = resolve_mesh_devices(opt, evaluator, logger)

    depth = opt.pipeline
    dispatch_probe = None
    if depth is None:
        try:
            # Probe at the production microbatch size: overlap ratios are
            # shape-dependent (dispatch overhead vs compute time). When a
            # sharded evaluator is installed, probe THAT — the
            # single-device jit's overlap says nothing about the sharded
            # computation serving will actually run. The same probe run
            # reports the fixed-vs-marginal dispatch cost that seeds the
            # dispatch coalescer's width policy.
            depth, dispatch_probe = suggest_pipeline_depth(
                weights,
                size=max(64, min(opt.resolved_microbatch(), 4096)),
                eval_fn=evaluator,
                return_probe=True,
            )
            logger.info(
                f"Dispatch cost probe: fixed {dispatch_probe.fixed_ms} ms, "
                f"marginal {dispatch_probe.marginal_ms_per_kslot} ms/kslot."
            )
        except Exception as err:  # noqa: BLE001 - probe is best-effort
            logger.debug(f"Pipeline probe failed ({err!r}); using depth 2.")
            depth = None
        # The probe only sees DEVICE dispatch overlap; the e2e step also
        # contains the host phase (fiber stepping, feature extraction,
        # emission) that depth >= 2 overlaps with the wire wait even on
        # fully serialized transports — measured +12% e2e on the tunnel,
        # where the probe alone says 1. Floor at 2; explicit --pipeline
        # still pins any value.
        depth = max(2, depth or 0)
        logger.info(f"Pipelining {depth} eval batches (host/wire overlap).")
    return SearchService(
        weights=weights,
        net_path=opt.nnue_file,  # native pool reads the original file
        batch_capacity=opt.resolved_microbatch(),
        pipeline_depth=depth,
        evaluator=evaluator,
        mesh_devices=mesh_devices,
        driver_threads=opt.resolved_search_threads(),
        psqt_path=psqt_path,
        dispatch_probe=dispatch_probe,
    )


def build_engine_factory(opt: Opt, logger: Logger) -> EngineFactory:
    """Select the backend behind the engine seam (north star: the
    `--engine tpu-nnue` flavor replaces stockfish.rs subprocesses)."""
    engine = opt.resolved_engine()
    if engine == "tpu-nnue":
        from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
        from fishnet_tpu.resilience.supervisor import ServiceSupervisor

        validate_mesh(opt)  # fail fast; the service builds lazily
        # The supervisor owns respawns: every rebuild of a dead service
        # goes through its bounded respawn budget and — after repeated
        # rapid deaths — steps the eval path down the degradation
        # ladder (fused -> xla -> host-material, doc/resilience.md).
        supervisor = ServiceSupervisor(
            lambda rung: build_search_service(opt, logger, psqt_path=rung),
            logger=logger,
        )
        factory = TpuNnueEngineFactory(service_builder=supervisor.build)
        # Exposed so run_client can hand the ladder to the front end's
        # shed policy (a degraded rung shrinks admission capacity).
        factory.supervisor = supervisor
        return factory
    if engine == "az-mcts":
        import jax

        from fishnet_tpu.engine.az_engine import AzMctsEngineFactory, AzMctsService
        from fishnet_tpu.models.az import az_config_from_params, init_az_params
        from fishnet_tpu.search.mcts import MctsConfig

        if opt.az_net_file:
            import zipfile

            import numpy as np

            # Checkpoints carry no explicit architecture metadata; every
            # AzConfig field is recoverable from parameter shapes, and a
            # missing/corrupt/non-AZ file fails here with a clear message
            # instead of a traceback or a shape error inside the jitted
            # forward at warmup.
            try:
                loaded = np.load(opt.az_net_file)
                params = {k: loaded[k] for k in loaded.files}
                az_cfg = az_config_from_params(params)
            except (OSError, ValueError, zipfile.BadZipFile) as err:
                raise ConfigError(f"--az-net-file {opt.az_net_file}: {err}") from err
            cfg = MctsConfig(batch_capacity=opt.resolved_microbatch(), az=az_cfg)
        else:
            logger.warn("No --az-net-file given; using random policy+value net (dev mode).")
            cfg = MctsConfig(batch_capacity=opt.resolved_microbatch())
            params = init_az_params(jax.random.PRNGKey(0), cfg.az)
        # Variant work can't ride the AZ policy encoding; route it to the
        # native HCE alpha-beta tier (scalar backend: no device traffic).
        from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
        from fishnet_tpu.nnue.weights import NnueWeights
        from fishnet_tpu.search.service import SearchService

        fallback_service = SearchService(
            weights=NnueWeights.random(seed=0), backend="scalar",
            pool_slots=64, batch_capacity=64,
        )
        return AzMctsEngineFactory(
            AzMctsService(params, cfg),
            variant_fallback=TpuNnueEngineFactory(fallback_service),
        )
    if engine == "uci":
        from fishnet_tpu.engine.uci import UciEngineFactory

        if not opt.engine_exe:
            raise ConfigError("--engine uci requires --engine-exe")
        return UciEngineFactory(opt.engine_exe, logger=logger)
    if engine == "mock":
        from fishnet_tpu.engine.mock import MockEngineFactory

        # Per-position artificial latency, for harnesses that need
        # realistic in-flight windows (a SIGKILL should strand work
        # mid-unit the way a real multi-second analysis would, not hit
        # the sub-ms gaps of an instant engine).
        delay = float(_os.environ.get("FISHNET_MOCK_ENGINE_DELAY", 0) or 0)
        if delay > 0:
            return MockEngineFactory(delay_seconds=delay)
        return MockEngineFactory()
    raise ConfigError(f"unknown engine backend: {engine!r}")


async def run_client(opt: Opt, logger: Logger) -> None:
    """The supervisor loop (main.rs:76-260)."""
    from fishnet_tpu.client import Client
    from fishnet_tpu.resilience import drain
    from fishnet_tpu.search import eval_cache as eval_cache_mod

    from pathlib import Path

    stats = StatsRecorder(
        cores=opt.resolved_cores(),
        stats_file=Path(opt.stats_file) if opt.stats_file else None,
        no_stats_file=opt.no_stats_file,
    )

    # Live telemetry (opt-in via --metrics-port / MetricsPort ini key):
    # /metrics + /json on an http.server thread, span recording in the
    # pipeline hot paths, SIGUSR2 armed to dump the flight recorder.
    # --spans-dir / SpansDir steers where the flight recorder dumps its
    # fishnet-spans-<pid>.jsonl (spans.default_path reads the env var;
    # exporting keeps engine subprocesses consistent with this process).
    if opt.spans_dir is not None:
        _os.environ["FISHNET_SPANS_DIR"] = opt.spans_dir

    exporter = None
    if opt.metrics_port is not None:
        from fishnet_tpu import telemetry
        from fishnet_tpu.utils.stats import register_stats_collector

        exporter = telemetry.start_exporter(opt.metrics_port)
        register_stats_collector(stats)
        logger.info(
            f"Serving telemetry on http://127.0.0.1:{exporter.port}/metrics "
            "(SIGUSR2 dumps the span flight recorder)."
        )
        if opt.metrics_port_file is not None:
            # Written AFTER bind so the port is live when read; atomic
            # rename so a fleet aggregator polling the file never sees
            # a half-written number.
            tmp = f"{opt.metrics_port_file}.tmp"
            with open(tmp, "w", encoding="utf-8") as fp:
                fp.write(f"{exporter.port}\n")
            _os.replace(tmp, opt.metrics_port_file)

    if opt.spans_journal is not None:
        # Batch-span write-ahead for the fleet stitcher: spans recorded
        # between the aggregator's last scrape and a SIGKILL survive on
        # disk; the aggregator tails this file per incarnation.
        from fishnet_tpu.telemetry.spans import RECORDER as _span_recorder

        _span_recorder.journal_to(opt.spans_journal)

    # Deterministic fault injection (--fault-plan / FISHNET_FAULT_PLAN):
    # a testing/soak aid — loudly flagged, never silently active.
    plan_spec = opt.resolved_fault_plan()
    if plan_spec:
        from fishnet_tpu.resilience import faults

        faults.install(plan_spec)
        logger.error(
            f"FAULT INJECTION ACTIVE ({plan_spec!r}). "
            "Never run this against production traffic."
        )

    # Warm-restart snapshot (FISHNET_EVAL_CACHE_SNAPSHOT): reload the
    # previous process's eval cache so the first warm batches resolve
    # pre-wire. The net fingerprint keys the snapshot to the serving
    # weights — a mismatch discards it cleanly (doc/eval-cache.md).
    net_fp = (
        eval_cache_mod.net_fingerprint(opt.nnue_file) if opt.nnue_file else 0
    )
    # The AZ cache rides the same snapshot under its own fingerprint
    # (az params hash, 0 for dev-mode random weights) so a restarted
    # MCTS fleet warm-starts pre-wire too.
    az_fp = 0
    if opt.az_net_file:
        try:
            import numpy as _np

            with _np.load(opt.az_net_file) as _loaded:
                az_fp = eval_cache_mod.az_net_fingerprint(
                    {k: _loaded[k] for k in _loaded.files}
                )
        except (OSError, ValueError, KeyError):
            az_fp = 0
    if eval_cache_mod.snapshot_path() is not None:
        if eval_cache_mod.load_snapshot(
            fingerprint=net_fp, az_fingerprint=az_fp
        ):
            cache = eval_cache_mod.get_cache()
            n = len(cache) if cache is not None else 0
            az_cache = eval_cache_mod.get_az_cache()
            n_az = len(az_cache) if az_cache is not None else 0
            logger.info(
                f"Restored {n} eval-cache entries "
                f"(+{n_az} az) from snapshot."
            )

    engine_factory = build_engine_factory(opt, logger)
    shed_policy = None
    if opt.lane_depth_limit is not None:
        from fishnet_tpu.resilience.shedding import ShedPolicy
        from fishnet_tpu.resilience.supervisor import any_breaker_open

        sup = getattr(engine_factory, "supervisor", None)
        shed_policy = ShedPolicy(
            high_watermark=opt.lane_depth_limit,
            breaker_open_fn=any_breaker_open,
            rung_fn=(lambda: sup.rung) if sup is not None else None,
        )
    client = Client(
        endpoint=opt.resolved_endpoint(),
        key=opt.key,
        cores=opt.resolved_cores(),
        engine_factory=engine_factory,
        logger=logger,
        stats=stats,
        backlog=BacklogOpt(user=opt.user_backlog, system=opt.system_backlog),
        max_backoff=opt.resolved_max_backoff(),
        workers=opt.resolved_workers(),
        batch_deadline=opt.batch_deadline,
        tenants=opt.resolved_tenants(),
        shed_policy=shed_policy,
        supervisor=getattr(engine_factory, "supervisor", None),
    )
    if opt.resolved_workers() != opt.resolved_cores():
        shared = opt.resolved_engine() in ("tpu-nnue", "az-mcts")
        what = ("over the shared device service" if shared
                else "(one engine instance per worker)")
        logger.info(
            f"Analyzing up to {opt.resolved_workers()} positions "
            f"concurrently {what}."
        )

    stop = asyncio.Event()
    sigints = 0
    sigterms = 0
    drain_guard: Optional[asyncio.Task] = None

    def on_sigint() -> None:
        nonlocal sigints
        sigints += 1
        if sigints == 1:
            logger.fishnet_info("Stopping soon. Press ^C again to abort pending batches ...")
            drain.begin("sigint", depth_fn=client.queue_depth)
            client.shutdown_soon()
        else:
            logger.fishnet_info("Stopping now.")
            stop.set()

    def on_sigterm() -> None:
        # Graceful drain (doc/resilience.md): stop acquiring, flush
        # in-flight batches until the deadline, then abort the rest
        # upstream (accounted — the server reassigns) and exit 0.
        # Readiness (/healthz, /healthz/ready) flips to 503 so an
        # orchestrator stops routing at this process; liveness
        # (/healthz/live) stays 200 — draining is not wedged.
        nonlocal sigterms, drain_guard
        sigterms += 1
        if sigterms > 1:
            logger.fishnet_info("Stopping now.")
            stop.set()
            return
        deadline = opt.resolved_drain_deadline()
        logger.fishnet_info(
            f"SIGTERM: draining (flushing in-flight batches, deadline "
            f"{deadline:.0f}s; send SIGTERM again to abort now) ..."
        )
        drain.begin("sigterm", deadline=deadline, depth_fn=client.queue_depth)
        client.shutdown_soon()

        async def deadline_guard() -> None:
            await asyncio.sleep(deadline)
            logger.fishnet_info(
                "Drain deadline reached; aborting remaining batches upstream."
            )
            stop.set()

        drain_guard = asyncio.create_task(deadline_guard())

    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(signal.SIGINT, on_sigint)
        loop.add_signal_handler(signal.SIGTERM, on_sigterm)
    except NotImplementedError:  # non-Unix
        pass

    # Periodic auto-update (main.rs:179-199): every 5 h re-check the
    # release channel; an installed update drains work (shutdown_soon ->
    # wait_drained resolves the supervisor wait) and the restart happens
    # after teardown below — the reference's drain-then-exec, exactly.
    restart_to = None  # UpdateStatus of a staged, deferred install

    async def update_loop() -> None:
        nonlocal restart_to
        from fishnet_tpu.update import UPDATE_INTERVAL_SECONDS, apply_update

        while True:
            await asyncio.sleep(UPDATE_INTERVAL_SECONDS)
            try:
                status = await apply_update(
                    logger=logger, allow_default=True, defer_promote=True
                )
            except Exception as err:  # noqa: BLE001 - keep serving on failures
                logger.error(f"Periodic update check failed: {err}")
                continue
            if status.updated:
                logger.fishnet_info(
                    f"Update {status.latest} staged; draining before restart ..."
                )
                restart_to = status
                client.shutdown_soon()
                return

    logger.fishnet_info(f"fishnet-tpu {__version__} connecting to {opt.resolved_endpoint()}")
    await client.start()
    summary = asyncio.create_task(client.run_summary_loop())
    updater = (
        asyncio.create_task(update_loop()) if opt.auto_update else None
    )
    # Exit on explicit stop (second ^C / SIGTERM) OR when a first-^C
    # drain completes on its own (main.rs:248-259).
    stop_task = asyncio.create_task(stop.wait())
    drained_task = asyncio.create_task(client.wait_drained())
    try:
        await asyncio.wait({stop_task, drained_task}, return_when=asyncio.FIRST_COMPLETED)
    finally:
        for t in (stop_task, drained_task, summary, updater, drain_guard):
            if t is not None:
                t.cancel()
        await client.stop(abort_pending=stop.is_set())
        # Persist the eval cache for a warm restart (no-op unless
        # FISHNET_EVAL_CACHE_SNAPSHOT is set). After client.stop so the
        # snapshot holds the final working set; before engine teardown
        # so a slow native close can't outlive the write.
        if eval_cache_mod.snapshot_path() is not None:
            eval_cache_mod.save_snapshot(
                fingerprint=net_fp, az_fingerprint=az_fp
            )
        # Tear down shared engine backends before interpreter exit: a
        # daemon driver thread still inside native/JAX code when Python
        # unwinds takes the process down with SIGABRT.
        engine_factory.close()
        # Flush the (interval-debounced) stats file and stop serving
        # scrapes before teardown completes.
        stats.flush()
        if drain.draining():
            from fishnet_tpu import telemetry

            if telemetry.enabled():
                telemetry.RECORDER.dump(reason="drain")
        if exporter is not None:
            exporter.close()
        logger.fishnet_info(client.stats_summary())
    # Promote + restart only on a clean drain with no operator stop
    # intent: a second ^C / SIGTERM (stop) or even a single ^C (drain
    # then EXIT) must actually stop — resurrecting a unit systemd just
    # killed is worse than missing one update cycle. Deliberately after
    # the try/finally (never inside it: a `return` there would swallow
    # an in-flight CancelledError). The install lands HERE, once the
    # engines are torn down, so no live process ever has files swapped
    # under it (update.py promote_staged).
    if restart_to is not None and not stop.is_set() and sigints == 0 and sigterms == 0:
        from fishnet_tpu.update import (
            default_install_root,
            promote_staged,
            restart_process,
        )

        ok = True
        if restart_to.staged is not None:
            try:
                promote_staged(restart_to.staged, default_install_root())
            except Exception as err:  # noqa: BLE001
                logger.error(f"Update promotion failed: {err}")
                ok = False
        elif restart_to.command:
            # Async subprocess (R1): run_client is still on the event
            # loop here; even post-drain, a sync subprocess.run would
            # block signal handlers and any late api-actor I/O.
            proc = await asyncio.create_subprocess_exec(*restart_to.command)
            rc = await proc.wait()
            if rc != 0:
                logger.error(f"Update command failed with exit code {rc}.")
                ok = False
        if ok:
            restart_process(logger, restart_to.latest)


def main(argv=None) -> int:
    try:
        opt = configure_mod.parse_and_configure(argv, key_check=_check_key_over_network)
    except ConfigError as err:
        sys.stderr.write(f"E: {err}\n")
        return 2

    logger = Logger(verbose=opt.verbose, stderr=opt.is_systemd())

    if opt.command == "license":
        print(LICENSE_NOTICE)
        return 0
    if opt.command == "systemd":
        systemd_mod.systemd_system(opt)
        return 0
    if opt.command == "systemd-user":
        systemd_mod.systemd_user(opt)
        return 0
    if opt.command == "configure":
        return 0  # dialog already ran inside parse_and_configure
    if opt.command == "verify-net":
        # One-command compatibility proof for a user-supplied real net
        # (the reference embeds its net at build time, build.rs:7; no
        # real net can exist offline here, so the proof is shipped
        # instead — see fishnet_tpu/verify_net.py).
        if not opt.nnue_file:
            sys.stderr.write("E: verify-net requires --nnue-file PATH\n")
            return 2
        from fishnet_tpu.verify_net import run_cli

        return run_cli(str(opt.nnue_file))
    if opt.command == "uci":
        from fishnet_tpu.uci_server import serve

        # stdout belongs to the UCI protocol; all logging goes to stderr.
        logger = Logger(verbose=opt.verbose, stderr=True)
        try:
            service = build_search_service(opt, logger)
        except ConfigError as err:
            sys.stderr.write(f"E: {err}\n")
            return 2
        try:
            asyncio.run(serve(service))
        except KeyboardInterrupt:
            pass
        finally:
            service.close()
        return 0

    if opt.auto_update:
        from fishnet_tpu.update import auto_update

        auto_update(logger)

    try:
        asyncio.run(run_client(opt, logger))
    except KeyboardInterrupt:
        pass
    except ConfigError as err:
        # Late config errors (e.g. a bad --az-net-file discovered while
        # building the engine factory) exit cleanly, not as a traceback.
        sys.stderr.write(f"E: {err}\n")
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
