"""UCI server: expose the TPU-batched engine as a standard UCI engine.

``python -m fishnet_tpu uci`` speaks UCI over stdin/stdout, so chess
GUIs and tooling can drive the same batched search backend the fishnet
client serves lichess with. The reference has no such mode — it only
*consumes* UCI engines (src/stockfish.rs); here the engine tier is our
own, so exposing it costs one adapter.

Supported: uci / isready / setoption (MultiPV, UCI_Variant, UCI_Chess960)
/ ucinewgame / position / go (nodes, depth, movetime, infinite) / stop /
quit. ``go infinite`` runs until ``stop`` (bounded by a 1-hour guard).
Info lines are emitted per completed iteration when the search returns.
"""

from __future__ import annotations

import asyncio
import sys
import threading
from typing import List, Optional, TextIO

from fishnet_tpu.protocol.types import STARTPOS, ProtocolError, Variant
from fishnet_tpu.search.service import SearchResultData, SearchService
from fishnet_tpu.version import __version__

INFINITE_GUARD_SECONDS = 3600.0


def _parse_uci_variant(value: str) -> Optional[Variant]:
    if value.lower() == "giveaway":  # Fairy-Stockfish's name for antichess
        return Variant.ANTICHESS
    try:
        return Variant.parse(value)
    except ProtocolError:
        return None


class UciServer:
    def __init__(self, service: SearchService, out: TextIO = sys.stdout) -> None:
        self.service = service
        self.out = out
        self.fen = STARTPOS
        self.moves: List[str] = []
        self.variant = Variant.STANDARD
        self.multipv = 1
        self._search_task: Optional[asyncio.Task] = None
        self._stop_event: Optional[threading.Event] = None

    def _send(self, line: str) -> None:
        self.out.write(line + "\n")
        self.out.flush()

    # -- command handlers --------------------------------------------------

    def _cmd_uci(self) -> None:
        self._send(f"id name fishnet-tpu {__version__}")
        self._send("id author the fishnet-tpu authors")
        self._send("option name MultiPV type spin default 1 min 1 max 8")
        # Castling always uses Chess960 king-takes-rook notation (like an
        # engine with UCI_Chess960 permanently on); no toggle is offered.
        self._send(
            "option name UCI_Variant type combo default chess var "
            + " var ".join(sorted({v.uci() for v in Variant}))
        )
        self._send("uciok")

    def _cmd_setoption(self, tokens: List[str]) -> None:
        # setoption name <id> [value <x>]
        try:
            name_idx = tokens.index("name") + 1
            value_idx = tokens.index("value") + 1 if "value" in tokens else None
            name_end = value_idx - 1 if value_idx else len(tokens)
            name = " ".join(tokens[name_idx:name_end]).lower()
            value = " ".join(tokens[value_idx:]) if value_idx else ""
        except (ValueError, IndexError):
            return
        if name == "multipv":
            try:
                self.multipv = max(1, min(8, int(value)))
            except ValueError:
                pass
        elif name == "uci_variant":
            parsed = _parse_uci_variant(value)
            if parsed is not None:
                self.variant = parsed

    def _cmd_position(self, tokens: List[str]) -> None:
        if not tokens:
            return
        moves: List[str] = []
        if "moves" in tokens:
            mi = tokens.index("moves")
            moves = tokens[mi + 1 :]
            tokens = tokens[:mi]
        if tokens[0] == "startpos":
            fen = STARTPOS
        elif tokens[0] == "fen":
            fen = " ".join(tokens[1:])
        else:
            return
        self.fen = fen
        self.moves = moves

    async def _run_search(self, nodes: int, depth: int,
                          movetime: Optional[float]) -> None:
        try:
            result = await self.service.search(
                self.fen, self.moves, nodes=nodes, depth=depth,
                multipv=self.multipv, movetime_seconds=movetime,
                variant=self.variant, stop_event=self._stop_event,
            )
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001 - engine failure
            self._send(f"info string search failed: {err!r}")
            self._send("bestmove 0000")
            return
        self._emit_result(result)

    def _emit_result(self, result: SearchResultData) -> None:
        nps = int(result.nodes / result.time_seconds) if result.time_seconds > 0 else 0
        for line in result.lines:
            score = f"mate {line.value}" if line.is_mate else f"cp {line.value}"
            parts = [
                f"info depth {line.depth}",
                f"multipv {line.multipv}" if self.multipv > 1 else "",
                f"score {score}",
                f"nodes {result.nodes}",
                f"nps {nps}",
                f"time {int(result.time_seconds * 1000)}",
                ("pv " + " ".join(line.pv)) if line.pv else "",
            ]
            self._send(" ".join(p for p in parts if p))
        self._send(f"bestmove {result.best_move or '0000'}")

    async def _cmd_go(self, tokens: List[str]) -> None:
        await self._interrupt_search()  # one search at a time
        nodes = 0
        depth = 0
        movetime: Optional[float] = None
        clock: dict = {}
        i = 0

        def num(tok: str) -> Optional[int]:
            try:
                return int(tok)
            except ValueError:
                return None  # malformed numbers are ignored, like unknown tokens

        while i < len(tokens):
            tok = tokens[i]
            val = num(tokens[i + 1]) if i + 1 < len(tokens) else None
            if tok == "nodes" and val is not None:
                nodes = val; i += 2
            elif tok == "depth" and val is not None:
                depth = val; i += 2
            elif tok == "movetime" and val is not None:
                movetime = val / 1000.0; i += 2
            elif tok in ("wtime", "btime", "winc", "binc") and val is not None:
                clock[tok] = val; i += 2
            elif tok == "infinite":
                movetime = INFINITE_GUARD_SECONDS; i += 1
            else:
                i += 1
        if movetime is None and clock:
            # Simple time management: a fortieth of the remaining clock
            # plus most of the increment, floored at 50 ms.
            white = self._side_to_move_is_white()
            remaining = clock.get("wtime" if white else "btime", 0)
            inc = clock.get("winc" if white else "binc", 0)
            movetime = max(0.05, remaining / 40_000.0 + inc * 0.8 / 1000.0)
        if nodes == 0 and depth == 0 and movetime is None:
            depth = 12  # a sane default for bare `go`
        self._stop_event = threading.Event()
        self._search_task = asyncio.create_task(
            self._run_search(nodes, depth, movetime)
        )

    def _side_to_move_is_white(self) -> bool:
        fields = self.fen.split()
        white = len(fields) < 2 or fields[1] == "w"
        return white if len(self.moves) % 2 == 0 else not white

    async def _await_search(self) -> None:
        if self._search_task is not None:
            try:
                await self._search_task
            except asyncio.CancelledError:
                pass
            self._search_task = None

    async def _interrupt_search(self) -> None:
        """Cancel any running search (a new `go` supersedes it) — awaiting
        a `go infinite` here would block the stdin loop for the guard's
        full hour, making stop/quit unprocessable."""
        if self._search_task is not None and not self._search_task.done():
            self._search_task.cancel()
        await self._await_search()

    async def _cmd_stop(self) -> None:
        # Graceful stop: the native search halts at its next node poll and
        # the call returns the PARTIAL result (deepest completed
        # iterations), which _run_search emits as usual — the GUI gets the
        # best move the interrupted search actually found.
        if self._search_task is not None and not self._search_task.done():
            if self._stop_event is not None:
                self._stop_event.set()
                self.service.poke()
        await self._await_search()

    # -- main loop ---------------------------------------------------------

    async def handle_line(self, line: str) -> bool:
        """Process one command. Returns False on quit."""
        tokens = line.split()
        if not tokens:
            return True
        cmd, rest = tokens[0], tokens[1:]
        if cmd == "uci":
            self._cmd_uci()
        elif cmd == "isready":
            self._send("readyok")
        elif cmd == "setoption":
            self._cmd_setoption(rest)
        elif cmd == "ucinewgame":
            self.fen = STARTPOS
            self.moves = []
        elif cmd == "position":
            self._cmd_position(rest)
        elif cmd == "go":
            await self._cmd_go(rest)
        elif cmd == "stop":
            await self._cmd_stop()
        elif cmd == "quit":
            return False
        # Unknown commands are ignored, per UCI custom.
        return True

    async def run(self, reader) -> None:
        while True:
            raw = await reader()
            if raw is None:
                break
            if not await self.handle_line(raw.strip()):
                break
        # quit / stdin EOF: a running `go infinite` must not hold the
        # process open for the guard's full hour.
        await self._interrupt_search()


async def serve(service: SearchService) -> None:
    loop = asyncio.get_running_loop()

    async def read_stdin() -> Optional[str]:
        line = await loop.run_in_executor(None, sys.stdin.readline)
        return line if line else None

    await UciServer(service).run(read_stdin)
