"""systemd unit-file generation.

Equivalent of the reference's deployment tooling (src/systemd.rs:11-191):
``fishnet-tpu systemd`` / ``systemd-user`` print a hardened unit file
whose ExecStart reconstructs the exact CLI invocation (flags the user
passed, paths made absolute), so the service runs with the same config.
"""

from __future__ import annotations

import os
import shlex
import sys
from pathlib import Path
from typing import List, Optional, TextIO

from fishnet_tpu.configure import Opt


def _unit_user() -> str:
    """User= value for the system unit: $USER when set, else the real
    account name from the password database (getpass checks LOGNAME/
    USER/LNAME/USERNAME then pwd) — a unit with a literal placeholder
    would fail to start at systemctl time."""
    user = os.environ.get("USER")
    if user:
        return user
    import getpass

    try:
        return getpass.getuser()
    except (KeyError, OSError):
        # No passwd entry for the uid (containers): nobody is the
        # conventional unprivileged fallback and at least names a real
        # account on any systemd host.
        return "nobody"


def _duration(seconds: float) -> str:
    """Serialize a duration so parse_duration round-trips it: integer
    seconds when whole, else milliseconds (parse_duration rejects
    fractional values)."""
    if seconds == int(seconds):
        return f"{int(seconds)}s"
    return f"{int(round(seconds * 1000))}ms"


def _exec_start(opt: Opt, *, absolute: bool) -> str:
    """Rebuild the CLI invocation (systemd.rs:119-191)."""
    if absolute:
        exe = [sys.executable, "-m", "fishnet_tpu"]
    else:
        exe = [os.path.basename(sys.executable), "-m", "fishnet_tpu"]

    def path(p: str) -> str:
        return str(Path(p).resolve()) if absolute else p

    args: List[str] = [shlex.quote(a) for a in exe]
    if opt.verbose:
        args.append("-" + "v" * opt.verbose)
    if opt.auto_update:
        args.append("--auto-update")

    if opt.no_conf:
        args.append("--no-conf")
    elif opt.conf is not None or absolute:
        args += ["--conf", shlex.quote(path(str(opt.conf_path())))]

    if opt.key_file is not None:
        args += ["--key-file", shlex.quote(path(opt.key_file))]
    elif opt.key is not None:
        args += ["--key", shlex.quote(opt.key)]

    if opt.endpoint is not None:
        args += ["--endpoint", shlex.quote(opt.endpoint)]
    if opt.cores is not None:
        args += ["--cores", shlex.quote(opt.cores)]
    if opt.max_backoff is not None:
        args += ["--max-backoff", _duration(opt.max_backoff)]
    if opt.user_backlog is not None:
        args += ["--user-backlog", _duration(opt.user_backlog)]
    if opt.system_backlog is not None:
        args += ["--system-backlog", _duration(opt.system_backlog)]
    if opt.stats_file is not None:
        args += ["--stats-file", shlex.quote(path(opt.stats_file))]
    if opt.no_stats_file:
        args.append("--no-stats-file")
    if opt.engine is not None:
        args += ["--engine", opt.engine]
    if opt.engine_exe is not None:
        args += ["--engine-exe", shlex.quote(path(opt.engine_exe))]
    if opt.nnue_file is not None:
        args += ["--nnue-file", shlex.quote(path(opt.nnue_file))]
    if opt.microbatch is not None:
        args += ["--microbatch", str(opt.microbatch)]
    if opt.az_net_file is not None:
        args += ["--az-net-file", shlex.quote(path(opt.az_net_file))]
    if opt.pipeline is not None:
        args += ["--pipeline", str(opt.pipeline)]
    if opt.search_threads is not None:
        args += ["--search-threads", str(opt.search_threads)]
    if opt.search_concurrency is not None:
        args += ["--search-concurrency", str(opt.search_concurrency)]
    if opt.mesh is not None:
        args += ["--mesh", opt.mesh]
    if opt.drain_deadline is not None:
        args += ["--drain-deadline", _duration(opt.drain_deadline)]

    return " ".join(args)


def _timeout_stop(opt: Opt) -> str:
    """TimeoutStopSec aligned with the client's graceful drain: systemd
    sends SIGTERM (KillMode=mixed), the client drains within its
    deadline (flushing in-flight batches, then aborting the rest
    upstream) and exits 0 on its own — systemd's SIGKILL must only ever
    fire after that whole path has had its chance, so deadline + 15 s
    of margin for the flush/exit tail."""
    return f"TimeoutStopSec={int(opt.resolved_drain_deadline() + 15)}"


def systemd_system(opt: Opt, out: Optional[TextIO] = None) -> None:
    """Hardened system unit (systemd.rs:11-55). Note: no
    CapabilityBoundingSet surprises — the TPU runtime needs device access,
    so DevicePolicy stays open when running the tpu-nnue backend."""
    out = out or sys.stdout
    tpu = opt.resolved_engine() == "tpu-nnue"
    lines = [
        "[Unit]",
        "Description=Fishnet TPU client",
        "After=network-online.target",
        "Wants=network-online.target",
        "",
        "[Service]",
        f"ExecStart={_exec_start(opt, absolute=True)} run",
        "KillMode=mixed",
        _timeout_stop(opt),
        "WorkingDirectory=/tmp",
        f"User={_unit_user()}",
        "Nice=5",
        "CapabilityBoundingSet=",
        "PrivateTmp=true",
    ]
    if not tpu:
        lines += ["PrivateDevices=true", "DevicePolicy=closed"]
    lines += [
        "ProtectSystem=full",
        "NoNewPrivileges=true",
        "Restart=on-failure",
        "",
        "[Install]",
        "WantedBy=multi-user.target",
    ]
    out.write("\n".join(lines) + "\n")
    if out is sys.stdout and sys.stdout.isatty():
        cmd = _exec_start(opt, absolute=False)
        sys.stderr.write(
            "\n# Example usage:\n"
            f"# {cmd} systemd | sudo tee /etc/systemd/system/fishnet-tpu.service\n"
            "# systemctl enable fishnet-tpu.service\n"
            "# systemctl start fishnet-tpu.service\n"
            "# Live view of log: journalctl --unit fishnet-tpu --follow\n"
            f"# Prefer a user unit? {cmd} systemd-user\n"
        )


def systemd_user(opt: Opt, out: Optional[TextIO] = None) -> None:
    """User unit (systemd.rs:57-95)."""
    out = out or sys.stdout
    tpu = opt.resolved_engine() == "tpu-nnue"
    lines = [
        "[Unit]",
        "Description=Fishnet TPU client",
        "After=network-online.target",
        "Wants=network-online.target",
        "",
        "[Service]",
        f"ExecStart={_exec_start(opt, absolute=True)} run",
        "KillMode=mixed",
        _timeout_stop(opt),
        "WorkingDirectory=/tmp",
        "Nice=5",
        "PrivateTmp=true",
    ]
    if not tpu:
        lines += ["DevicePolicy=closed"]
    lines += [
        "ProtectSystem=full",
        "Restart=on-failure",
        "",
        "[Install]",
        "WantedBy=default.target",
    ]
    out.write("\n".join(lines) + "\n")
    if out is sys.stdout and sys.stdout.isatty():
        cmd = _exec_start(opt, absolute=False)
        sys.stderr.write(
            "\n# Example usage:\n"
            f"# {cmd} systemd-user | tee ~/.config/systemd/user/fishnet-tpu.service\n"
            "# systemctl enable --user fishnet-tpu.service\n"
            "# systemctl start --user fishnet-tpu.service\n"
            "# Live view of log: journalctl --user --user-unit fishnet-tpu --follow\n"
        )
