#include "search.h"

#include <algorithm>
#include <cmath>
#include <cstring>

namespace fc {

namespace {

// Late-move-reduction table, the standard log(depth) x log(move_count)
// shape every strong engine converges on: gentle at shallow depth and
// early moves, approaching ~3-4 plies deep in the move list at high
// depth. Built once at static init.
struct LmrTable {
  int8_t r[64][64];
  LmrTable() {
    for (int d = 0; d < 64; d++)
      for (int m = 0; m < 64; m++)
        r[d][m] = d && m
                      ? int8_t(0.8 + std::log(double(d)) * std::log(double(m)) / 1.75)
                      : 0;
  }
};
const LmrTable kLmr;

// The (color-coded) piece a move puts on its to-square, for history
// indexing; drops have an empty from-square.
inline int moving_piece(const Position& pos, Move m) {
  return move_kind(m) == MK_DROP ? make_piece(pos.stm, move_drop_piece(m))
                                 : pos.piece_on(move_from(m));
}

}  // namespace

// ---------------------------------------------------------------------------
// Transposition table
// ---------------------------------------------------------------------------

namespace {
constexpr int16_t EVAL_NONE = TT_EVAL_NONE;

// Approximate piece values for delta pruning (qsearch) — margins only,
// never part of a returned score.
constexpr int kPieceValue[PIECE_TYPE_NB] = {100, 320, 330, 500, 950, 0};

// Qsearch delta-pruning margin (the consuming loop's threshold) and the
// prediction slack the prefetch gate adds on top of it for HCE-vs-NNUE
// skew — shared so the gate can never drift from the loop it mirrors.
constexpr int kQsDeltaMargin = 200;
constexpr int kPredSlack = 120;

// The piece type a capture removes (e.p. takes a pawn); callers pass
// genuine captures only.
inline int capture_victim(const Position& pos, Move m) {
  return move_kind(m) == MK_EN_PASSANT ? PAWN
                                       : piece_type(pos.piece_on(move_to(m)));
}

inline int capture_attacker(const Position& pos, Move m) {
  return move_kind(m) == MK_DROP ? PAWN
                                 : piece_type(pos.piece_on(move_from(m)));
}

// Shared losing-capture predicate for ordering demotion and the
// prefetch prediction gates: SEE is only consulted when the exchange
// CAN lose (attacker outvalues victim) — winning/equal captures stay
// zero-cost. ``threshold``: the SEE value below which the consumer
// skips the move (0 for demotion, -200*depth for the shallow prune).
inline bool losing_capture(const Position& pos, Move m, int threshold) {
  int victim = capture_victim(pos, m);
  int attacker = capture_attacker(pos, m);
  return kPieceValue[attacker] > kPieceValue[victim] + (-threshold) &&
         see_applicable(pos.variant) && see(pos, m) < threshold;
}

size_t floor_pow2(size_t n) {
  size_t p = 1;
  while (p * 2 <= n) p *= 2;
  return p;
}
}  // namespace

TranspositionTable::TranspositionTable(size_t bytes) {
  size_t clusters = floor_pow2(
      std::max<size_t>(256, bytes / ((sizeof(Packed) + 2) * CLUSTER)));
  entries_ = std::vector<Packed>(clusters * CLUSTER);
  // () value-initializes: atomic<uint16_t> has a trivial default ctor,
  // so the array storage is zeroed.
  gens_.reset(new std::atomic<uint16_t>[clusters * CLUSTER]());
  mask_ = clusters - 1;
}

bool TranspositionTable::probe(uint64_t key, TTData& out) {
  constexpr auto R = std::memory_order_relaxed;
  Packed* c = cluster(key);
  for (int i = 0; i < CLUSTER; i++) {
    uint64_t d = c[i].data.load(R);
    if (!(d >> 63) || (c[i].kx.load(R) ^ d) != key) continue;
    TTData t = unpack(d);
    // An entry counts as a hit if it carries either a search bound or a
    // cached static eval for this key.
    if (t.bound != TT_NONE || t.eval != TT_EVAL_NONE) {
      out = t;
      return true;
    }
  }
  return false;
}

void TranspositionTable::store(uint64_t key, Move move, int value, int eval,
                               int depth, TTBound bound) {
  constexpr auto R = std::memory_order_relaxed;
  Packed* c = cluster(key);
  std::atomic<uint16_t>* g = &gens_[(key & mask_) * CLUSTER];
  uint16_t gen = gen_.load(R);
  int idx = -1;
  TTData cur;
  for (int i = 0; i < CLUSTER; i++) {
    uint64_t d = c[i].data.load(R);
    if ((d >> 63) && (c[i].kx.load(R) ^ d) == key) {
      idx = i;
      cur = unpack(d);
      break;
    }
  }
  if (idx >= 0) {
    // Same position: depth-preferred within a generation, merging the
    // old best move / cached eval when the new store lacks them.
    if (cur.bound != TT_NONE && g[idx].load(R) == gen && depth < cur.depth &&
        bound != TT_EXACT)
      return;
    if (move == MOVE_NONE) move = cur.move;
    if (eval == TT_EVAL_NONE) eval = cur.eval;
  } else {
    // Victim: the weakest of the cluster — stale generations first,
    // then shallowest depth (eval-only entries have depth 0 and go
    // before any bound-carrying entry of equal staleness). A torn
    // concurrent entry decodes to garbage ranking here, which only
    // means a different victim gets picked — benign.
    int worst = 1 << 30;
    for (int i = 0; i < CLUSTER; i++) {
      TTData t = unpack(c[i].data.load(R));
      int score = int(t.depth) + (g[i].load(R) == gen ? 512 : 0) +
                  (t.bound != TT_NONE ? 256 : 0);
      if (score < worst) {
        worst = score;
        idx = i;
        cur = t;
      }
    }
    // When even the weakest slot holds a fresh, bound-carrying, deeper
    // entry, drop the store: under pressure, deep results are worth
    // more than this shallower one (measured — evicting them cost a
    // third of a ply at a 2 MiB table).
    if (cur.bound != TT_NONE && g[idx].load(R) == gen && cur.depth > depth &&
        bound != TT_EXACT)
      return;
  }
  // A repurposed victim slot must not inherit a stale speculative tag
  // (pack sets prefetched=false): the next TT eval hit on this key
  // would count a false prefetch hit and inflate the ROI telemetry the
  // budget policy is tuned against.
  uint64_t d = pack(move, int16_t(value), int16_t(eval),
                    uint8_t(std::min(std::max(0, depth), 127)), bound,
                    /*prefetched=*/false);
  c[idx].data.store(d, R);
  c[idx].kx.store(key ^ d, R);
  g[idx].store(gen, R);
}

void TranspositionTable::store_eval(uint64_t key, int eval, bool speculative) {
  constexpr auto R = std::memory_order_relaxed;
  Packed* c = cluster(key);
  std::atomic<uint16_t>* g = &gens_[(key & mask_) * CLUSTER];
  uint16_t gen = gen_.load(R);
  // Victim ranking among bound-free slots (bound-carrying entries are
  // never evicted by a cheap static eval): empty beats unconsumed
  // speculative beats stale-generation eval-only. Round 2 claimed only
  // genuinely EMPTY slots, which silently dropped nearly every prefetch
  // once the table warmed up (measured ROI 0.0008): each dropped child
  // eval then cost a fresh demand round-trip, the exact latency the
  // prefetch was bought to hide.
  int victim = -1;
  int victim_rank = 0;
  for (int i = 0; i < CLUSTER; i++) {
    uint64_t d = c[i].data.load(R);
    bool occupied = d >> 63;
    TTData t = unpack(d);
    if (occupied && (c[i].kx.load(R) ^ d) == key) {
      if (t.eval == TT_EVAL_NONE) {
        uint64_t nd = pack(t.move, t.value, int16_t(eval), t.depth, t.bound,
                           speculative);
        c[i].data.store(nd, R);
        c[i].kx.store(key ^ nd, R);
      }
      return;
    }
    if (occupied && t.bound != TT_NONE) continue;
    int rank = !occupied || t.eval == TT_EVAL_NONE ? 3  // empty
               : t.prefetched                      ? 2  // unconsumed speculation
               : g[i].load(R) != gen               ? 1  // stale cached eval
                                                   : 0;  // fresh demand eval: keep
    if (rank > victim_rank) {
      victim_rank = rank;
      victim = i;
    }
  }
  if (victim >= 0) {
    uint64_t d = pack(MOVE_NONE, 0, int16_t(eval), 0, TT_NONE, speculative);
    c[victim].data.store(d, R);
    c[victim].kx.store(key ^ d, R);
    g[victim].store(gen, R);
  }
}

void TranspositionTable::consume_prefetch(uint64_t key) {
  constexpr auto R = std::memory_order_relaxed;
  Packed* c = cluster(key);
  for (int i = 0; i < CLUSTER; i++) {
    uint64_t d = c[i].data.load(R);
    if (!(d >> 63) || (c[i].kx.load(R) ^ d) != key) continue;
    uint64_t nd = d & ~(1ull << 62);
    c[i].data.store(nd, R);
    c[i].kx.store(key ^ nd, R);
    return;
  }
}

// ---------------------------------------------------------------------------
// Value conversion
// ---------------------------------------------------------------------------

void value_to_uci(int value, bool& mate, int& out) {
  if (value >= VALUE_MATE_IN_MAX) {
    mate = true;
    out = (VALUE_MATE - value + 1) / 2;
  } else if (value <= -VALUE_MATE_IN_MAX) {
    mate = true;
    out = -((VALUE_MATE + value) / 2);
  } else {
    mate = false;
    out = value;
  }
}

// ---------------------------------------------------------------------------
// Static exchange evaluation
// ---------------------------------------------------------------------------

int see(const Position& pos, Move m) {
  if (move_kind(m) == MK_CASTLE || move_kind(m) == MK_DROP) return 0;
  Square from = move_from(m), to = move_to(m);
  int gain[34];
  int d = 0;
  Bitboard occ = pos.occupied() ^ bb(from);
  if (move_kind(m) == MK_EN_PASSANT) {
    occ ^= bb(to + (pos.stm == WHITE ? -8 : 8));
    gain[0] = kPieceValue[PAWN];
  } else {
    gain[0] = pos.empty(to) ? 0 : kPieceValue[piece_type(pos.piece_on(to))];
  }
  int next_victim = piece_type(pos.piece_on(from));
  if (move_promo(m) != NO_PIECE_TYPE) {
    next_victim = move_promo(m);
    gain[0] += kPieceValue[next_victim] - kPieceValue[PAWN];
  }
  Color side = ~pos.stm;
  while (d < 32) {
    // Recompute attackers under the shrinking occupancy so sliders
    // x-ray through departed pieces; mask with occ to drop attackers
    // already spent (the position's bitboards still contain them).
    Bitboard attackers = pos.attackers_to(to, occ) & occ;
    Bitboard ours = attackers & pos.pieces(side);
    if (!ours) break;
    int apt = PAWN;
    Bitboard from_bb = 0;
    for (; apt <= KING; apt++) {
      from_bb = ours & pos.pieces(PieceType(apt));
      if (from_bb) break;
    }
    Bitboard fb = from_bb & -from_bb;
    // The king may only recapture when no enemy attacker remains
    // (x-rays through its own square included) — capturing into check
    // ends the sequence instead.
    if (apt == KING &&
        ((pos.attackers_to(to, occ ^ fb) & (occ ^ fb)) & pos.pieces(~side)))
      break;
    d++;
    gain[d] = kPieceValue[next_victim] - gain[d - 1];
    next_victim = apt;
    occ ^= fb;
    side = ~side;
  }
  // Negamax the gain ladder backwards: at each depth the side to move
  // keeps the better of stopping (not recapturing) and continuing.
  while (d > 0) {
    gain[d - 1] = -std::max(-gain[d - 1], gain[d]);
    d--;
  }
  return gain[0];
}

// ---------------------------------------------------------------------------
// Search
// ---------------------------------------------------------------------------

int Search::evaluate(const Position& pos) {
  // Clamp into the non-mate score range: keeps TT int16 storage exact,
  // avoids the TT_EVAL_NONE sentinel, and prevents huge (e.g. random-net)
  // evals from masquerading as mate scores.
  // Traffic counters track DEVICE batch slots only: scalar/HCE-backed
  // searches sharing the pool never ship slots, and counting them would
  // break the identity evals_shipped == demand_evals + prefetch_shipped
  // that occupancy and cache-rate telemetry are computed from.
  if (counters_ && eval_->batched())
    counters_->bump(counters_->demand_evals);
  int v = eval_->evaluate(pos);
  constexpr int LIMIT = VALUE_MATE_IN_MAX - 1;
  return v < -LIMIT ? -LIMIT : (v > LIMIT ? LIMIT : v);
}

// Mate scores are stored in the TT relative to the entry's node (plies
// from there), not the root; convert on the way in/out.
static int value_to_tt(int v, int ply) {
  if (v >= VALUE_MATE_IN_MAX) return v + ply;
  if (v <= -VALUE_MATE_IN_MAX) return v - ply;
  return v;
}

static int value_from_tt(int v, int ply) {
  if (v >= VALUE_MATE_IN_MAX) return v - ply;
  if (v <= -VALUE_MATE_IN_MAX) return v + ply;
  return v;
}

bool Search::is_repetition_or_50(const Position& pos, int) const {
  if (pos.halfmove >= 100) {
    // Rule-50 draw unless the position is checkmate right now (mate on
    // the 100th halfmove takes precedence).
    if (!pos.in_check()) return true;
    MoveList evasions;
    pos.legal_moves(evasions);
    return evasions.size > 0;
  }
  // Twofold repetition anywhere along game + search path counts as draw
  // (standard engine behavior). Scan is bounded by the halfmove clock.
  int limit = int(path_.size()) - 1;
  int span = std::min(limit, pos.halfmove);
  for (int i = 2; i <= span; i += 2)
    if (path_[limit - i] == pos.hash) return true;
  return false;
}

// Move-ordering scores (higher = earlier).
void Search::order_moves(const Position& pos, MoveList& moves, Move tt_move,
                         int ply) {
  // Eager path (qsearch targets, the depth-1 batched frontier): same
  // scorer as the lazy picker, with SEE applied up front (the prefetch
  // and the qsearch loop consume the ordered prefix immediately), then
  // a full sort.
  int scores[MAX_MOVES];
  score_moves(pos, moves, tt_move, ply, scores, /*eager_see=*/true);
  // Insertion sort (lists are short and mostly sorted after the first few).
  for (int i = 1; i < moves.size; i++) {
    Move m = moves.moves[i];
    int s = scores[i];
    int j = i - 1;
    while (j >= 0 && scores[j] < s) {
      moves.moves[j + 1] = moves.moves[j];
      scores[j + 1] = scores[j];
      j--;
    }
    moves.moves[j + 1] = m;
    scores[j + 1] = s;
  }
}

// Ordering signal for a quiet move: plain from/to history plus the 1-
// and 2-ply continuation histories (shared across the pool's searches
// and scheduler threads) keyed by the pieces/squares of the moves that
// led here. The continuation terms are what plain history cannot see:
// "this reply refutes THAT kind of move", the highest-value ordering
// signal absent from round 3 (VERDICT r3 item 3).
int Search::quiet_history(const Position& pos, Move m, int ply) const {
  int score = history_[pos.stm][move_from(m)][move_to(m)];
  if (shared_ != nullptr) {
    int pc = moving_piece(pos, m);
    Square to = move_to(m);
    if (ply >= 1 && ply <= MAX_PLY && move_stack_[ply] != MOVE_NONE)
      score += shared_->cont1.read(piece_stack_[ply],
                                   move_to(move_stack_[ply]), pc, to);
    if (ply >= 2 && move_stack_[ply - 1] != MOVE_NONE)
      score += shared_->cont2.read(piece_stack_[ply - 1],
                                   move_to(move_stack_[ply - 1]), pc, to);
  }
  return score;
}

// Score moves — THE one banding source for every ordering consumer
// (lazy picker, qsearch targets, depth-1 eager frontier): TT move,
// MVV-LVA capture band, queen promotions, killers, countermove floor,
// then the combined quiet-history signal. ``eager_see``: demote losing
// captures via SEE now (consumers that traverse the whole ordered list
// anyway); otherwise SEE is deferred to pick time, where a cut node
// never pays for the ~30 moves it does not visit.
void Search::score_moves(const Position& pos, const MoveList& moves,
                         Move tt_move, int ply, int* scores, bool eager_see) {
  Move prev = ply > 0 && ply <= MAX_PLY ? move_stack_[ply] : MOVE_NONE;
  Move counter = prev != MOVE_NONE
                     ? countermove_[move_from(prev)][move_to(prev)]
                     : MOVE_NONE;
  for (int i = 0; i < moves.size; i++) {
    Move m = moves.moves[i];
    int score;
    if (m == tt_move) {
      score = 1 << 30;
    } else if (!pos.empty(move_to(m)) || move_kind(m) == MK_EN_PASSANT) {
      int victim = capture_victim(pos, m);
      int attacker = capture_attacker(pos, m);
      score = (1 << 20) + victim * 16 - attacker;
      // Losing captures (SEE < 0) go behind every quiet: MVV-LVA alone
      // tries QxP-with-the-pawn-defended before killers, wasting the
      // early slots the whole ordering scheme exists to protect.
      // Gated on see_full_: demoting captures only pays when a losing
      // exchange implies a losing eval (see search.h ctor comment).
      if (eager_see && see_full_ && losing_capture(pos, m, 0))
        score = -(1 << 20) + victim * 16 - attacker;
    } else if (move_promo(m) == QUEEN) {
      score = 1 << 19;
    } else if (ply < MAX_PLY &&
               (m == killers_[ply][0] || m == killers_[ply][1])) {
      score = 1 << 16;
    } else {
      score = quiet_history(pos, m, ply);
      // The stored refutation of the opponent's previous move floors at
      // its own band: position-specific (killers) beats move-specific,
      // but a strong continuation-history signal may outrank it.
      if (m == counter && score < (1 << 15)) score = 1 << 15;
    }
    scores[i] = score;
  }
}

// History gravity bonus/malus on a beta cutoff by a quiet move: the
// cutting move gains, every quiet tried before it loses — the malus is
// what keeps the tables current (a once-good move that stops cutting
// decays instead of squatting at the top of the ordering).
void Search::update_quiet_stats(const Position& pos, Move best, int depth,
                                int ply, const Move* tried, int n_tried) {
  if (ply < MAX_PLY && killers_[ply][0] != best) {
    killers_[ply][1] = killers_[ply][0];
    killers_[ply][0] = best;
  }
  Move prev = ply > 0 && ply <= MAX_PLY ? move_stack_[ply] : MOVE_NONE;
  if (prev != MOVE_NONE) countermove_[move_from(prev)][move_to(prev)] = best;

  int bonus = std::min(1600, 16 * depth * depth + 32 * depth);
  auto apply = [&](Move m, int b) {
    int& h = history_[pos.stm][move_from(m)][move_to(m)];
    h += b - h * std::abs(b) / (1 << 14);
    if (shared_ != nullptr) {
      int pc = moving_piece(pos, m);
      Square to = move_to(m);
      if (ply >= 1 && ply <= MAX_PLY && move_stack_[ply] != MOVE_NONE)
        ContinuationHistory::bump(
            shared_->cont1.slot(piece_stack_[ply], move_to(move_stack_[ply]),
                                pc, to),
            b);
      if (ply >= 2 && move_stack_[ply - 1] != MOVE_NONE)
        ContinuationHistory::bump(
            shared_->cont2.slot(piece_stack_[ply - 1],
                                move_to(move_stack_[ply - 1]), pc, to),
            b);
    }
  };
  apply(best, bonus);
  for (int i = 0; i < n_tried; i++)
    if (tried[i] != best) apply(tried[i], -bonus);
}

// Prediction-gated speculation (VERDICT r4 item 1): speculative child
// evals are only worth shipping when the search will actually CONSUME
// them, and the consumption sites are all predictable host-side from
// the sub-microsecond classical eval. Measured before gating: 85% of
// shipped evals were speculative with ROI 0.18 — two thirds of all
// device slots bought nothing. The predictions mirror the exact
// pruning conditions of the consuming loops (qsearch delta/SEE
// pruning, depth-1 LMP/futility); a wrong prediction costs one extra
// demand round-trip, never correctness. Only meaningful when the net
// tracks material (see_full_ — the same probe that gates the pruning
// heuristics themselves).
int Search::filter_qsearch_prefetch(const Position& pos,
                                    const MoveList& targets, MoveList& keep,
                                    int pred, int alpha, int beta) const {
  // Predicted stand-pat cutoff: the most common qsearch outcome. The
  // capture loop never runs, so every child eval would be waste. The
  // kPredSlack absorbs HCE-vs-NNUE skew; a misprediction merely
  // defers the children to demand evals (self-correcting via the TT),
  // while every correctly-gated child frees a batch slot on a
  // throughput-bound link (A/B at budget 40: 250->120 slack lifted
  // nodes_per_eval 1.63->1.67 at identical trees).
  if (pred - kPredSlack >= beta && std::abs(beta) < VALUE_MATE_IN_MAX)
    return 0;
  for (Move m : targets) {
    if (move_promo(m) == NO_PIECE_TYPE) {
      // Child predicted delta-pruned (loop: best + victim + 200 <=
      // alpha, best ~= stand ~= pred +- HCE/NNUE skew, 120cp of
      // slack).
      int victim = capture_victim(pos, m);
      if (victim >= 0 && victim < PIECE_TYPE_NB &&
          std::abs(alpha) < VALUE_MATE_IN_MAX &&
          pred + kPieceValue[victim] + kQsDeltaMargin + kPredSlack <= alpha)
        continue;
      // Losing captures are skipped outright by the qsearch SEE prune.
      if (losing_capture(pos, m, 0)) continue;
    }
    keep.push(m);
  }
  return keep.size;
}

int Search::prefetch_evals(const Position& pos, const MoveList& children,
                           bool include_self, int max_children) {
  // Block buffers live on the Search object, not the fiber stack (24
  // Position copies would blow the per-frame stack budget). Safe: the
  // block completes before any recursion, so this is never re-entered.
  int k = 0;
  if (include_self) {
    prefetch_block_[k] = pos;
    prefetch_keys_[k] = pos.hash;
    k++;
  }
  int limit = include_self ? max_children + 1 : max_children;
  if (limit > EVAL_BLOCK_MAX) limit = EVAL_BLOCK_MAX;
  for (Move m : children) {
    if (k >= limit) break;
    Position child = pos;
    child.make(m);
    if (child.in_check()) continue;  // won't stand pat; eval unused
    TTData te;
    if (tt_->probe(child.hash, te) && te.eval != EVAL_NONE)
      continue;  // already cached
    prefetch_block_[k] = child;
    prefetch_keys_[k] = child.hash;
    k++;
  }
  if (k == 0) return 0;
  if (counters_) {
    if (include_self) counters_->bump(counters_->demand_evals);
    counters_->bump(counters_->prefetch_shipped,
                    uint64_t(k) - (include_self ? 1 : 0));
  }
  int32_t vals[EVAL_BLOCK_MAX];
  eval_->evaluate_block(prefetch_block_, k, vals);
  constexpr int LIMIT = VALUE_MATE_IN_MAX - 1;
  int self_value = 0;
  for (int i = 0; i < k; i++) {
    int v = vals[i] < -LIMIT ? -LIMIT : (vals[i] > LIMIT ? LIMIT : vals[i]);
    bool self = include_self && i == 0;
    if (self) self_value = v;
    tt_->store_eval(prefetch_keys_[i], v, /*speculative=*/!self);
  }
  return self_value;
}

int Search::qsearch(const Position& pos, int alpha, int beta, int ply) {
  nodes_++;
  if (counters_) counters_->bump(counters_->nodes);
  if ((allow_stop_ &&
       ((node_limit_ && nodes_ >= node_limit_) ||
        (external_stop_ && *external_stop_))) ||
      (abort_now_ && *abort_now_))
    stopped_ = true;
  // Once stopped (node budget, external stop, or hard abort) the value
  // is discarded by every unwinding caller: return a constant like
  // alpha_beta does instead of shipping one more device eval per
  // stopping fiber (one wasted round-trip each).
  if (stopped_) return 0;
  if (ply >= MAX_PLY) return evaluate(pos);

  if (pos.variant != VR_STANDARD) {
    int vres;
    if (pos.variant_terminal(vres))
      return vres > 0 ? VALUE_MATE - ply
                      : vres < 0 ? -(VALUE_MATE - ply) : VALUE_DRAW;
  }

  bool in_check = pos.effective_check();

  // Moves first: detects mate/stalemate before spending an eval, and the
  // list feeds both the stand-pat prefetch and the capture loop below.
  MoveList moves;
  pos.legal_moves(moves);
  if (moves.size == 0) {
    if (pos.variant == VR_ANTICHESS) return VALUE_MATE - ply;  // no moves: win
    return in_check ? -(VALUE_MATE - ply) : VALUE_DRAW;
  }

  // Antichess: when any capture exists, every legal move IS a capture
  // (the obligation is enforced in legal_moves) — the mover cannot
  // decline, so stand-pat is not a valid lower bound; search every move
  // exactly like check evasions.
  bool forced_captures =
      pos.variant == VR_ANTICHESS && moves.size > 0 &&
      (!pos.empty(move_to(moves.moves[0])) ||
       move_kind(moves.moves[0]) == MK_EN_PASSANT);

  int best = -VALUE_INF;

  // Targets: in check (or under the antichess capture obligation) every
  // move; otherwise captures/promotions only. Built lazily and ORDERED
  // before any prefetch, so speculative evals go to the moves the loop
  // below actually visits first — but a TT-hit stand-pat cutoff (the
  // most common qsearch outcome) returns before paying for any of it.
  MoveList targets;
  auto build_targets = [&]() {
    if (in_check || forced_captures) {
      targets = moves;
    } else {
      for (Move m : moves)
        if (!pos.empty(move_to(m)) || move_kind(m) == MK_EN_PASSANT ||
            move_promo(m) == QUEEN)
          targets.push(m);
    }
    order_moves(pos, targets, MOVE_NONE, ply);
  };

  if (in_check || forced_captures) {
    build_targets();
    // Evasions are searched below and most land in quiet positions
    // needing a stand-pat eval: fetch them (best-ordered first, within
    // the pool's current speculation budget) in one round-trip.
    if (eval_->batched())
      prefetch_evals(pos, targets, /*include_self=*/false,
                     eval_->prefetch_budget());
  } else {
    // Stand pat, with the TT's cached static eval when available. On a
    // miss, evaluate this node AND its best capture children in one
    // round-trip — the recursion below then stands pat from the TT.
    TTData tte;
    bool hit = tt_->probe(pos.hash, tte);
    int stand;
    if (hit && tte.eval != EVAL_NONE) {
      stand = tte.eval;
      if (counters_ && eval_->batched()) {
        counters_->bump(counters_->tt_eval_hits);
        if (tte.prefetched) {
          counters_->bump(counters_->prefetch_hits);
          // Count each speculative eval once.
          tt_->consume_prefetch(pos.hash);
        }
      }
      if (stand >= beta) return stand;  // before any targets/order work
      build_targets();
    } else {
      build_targets();
      if (eval_->batched()) {
        if (see_full_) {
          // Gate the speculative children on the classical eval's
          // prediction of what the loop below will consume (see
          // filter_qsearch_prefetch). Self always ships — it IS the
          // demand eval.
          MoveList keep;
          int n = filter_qsearch_prefetch(pos, targets, keep,
                                          hce_evaluate(pos), alpha, beta);
          stand = prefetch_evals(
              pos, keep, /*include_self=*/true,
              std::min(n, eval_->prefetch_budget()));
        } else {
          stand = prefetch_evals(pos, targets, /*include_self=*/true,
                                 eval_->prefetch_budget());
        }
      } else {
        stand = evaluate(pos);
        tt_->store_eval(pos.hash, stand);
      }
      if (stand >= beta) return stand;
    }
    if (stand > alpha) alpha = stand;
    best = stand;
  }

  for (Move m : targets) {
    // Delta pruning: even winning this capture outright cannot bring the
    // score near alpha. Skipped in check / under forced captures (no
    // stand-pat bound there) and for promotions (the gain is larger).
    if (!in_check && !forced_captures && best > -VALUE_MATE_IN_MAX &&
        std::abs(alpha) < VALUE_MATE_IN_MAX &&
        move_promo(m) == NO_PIECE_TYPE) {
      int victim = capture_victim(pos, m);
      if (victim >= 0 && victim < PIECE_TYPE_NB &&
          best + kPieceValue[victim] + kQsDeltaMargin <= alpha)
        continue;
    }
    // SEE pruning: a capture (or promotion push) that loses material on
    // the exchange cannot beat the stand-pat bound it already failed to
    // raise — the classic qsearch explosion-limiter MVV-LVA's delta
    // margins miss (Stockfish prunes the same class via see_ge). Gated
    // on see_full_ (sound only for material-correlated evals).
    if (see_full_ && !in_check && !forced_captures &&
        best > -VALUE_MATE_IN_MAX && see_applicable(pos.variant) &&
        see(pos, m) < 0)
      continue;
    Position copy = pos;
    int mover = moving_piece(pos, m);
    copy.make(m);
    if (ply + 1 <= MAX_PLY) {
      move_stack_[ply + 1] = m;
      piece_stack_[ply + 1] = mover;
    }
    int value = -qsearch(copy, -beta, -alpha, ply + 1);
    if (stopped_) return best > -VALUE_INF ? best : 0;
    if (value > best) {
      best = value;
      if (value > alpha) {
        alpha = value;
        if (alpha >= beta) break;
      }
    }
  }
  return best;
}

int Search::alpha_beta(const Position& pos, int alpha, int beta, int depth,
                       int ply, bool is_pv) {
  if (is_pv && ply < MAX_PLY) pv_len_[ply] = 0;

  if (ply > 0 && is_repetition_or_50(pos, ply)) return VALUE_DRAW;
  if (ply >= MAX_PLY) return evaluate(pos);

  if (pos.variant != VR_STANDARD) {
    int vres;
    if (pos.variant_terminal(vres))
      return vres > 0 ? VALUE_MATE - ply
                      : vres < 0 ? -(VALUE_MATE - ply) : VALUE_DRAW;
  }

  bool in_check = pos.effective_check();
  if (in_check) depth++;  // check extension

  if (depth <= 0) return qsearch(pos, alpha, beta, ply);

  nodes_++;
  if (counters_) counters_->bump(counters_->nodes);
  if ((allow_stop_ &&
       ((node_limit_ && nodes_ >= node_limit_) ||
        (external_stop_ && *external_stop_))) ||
      (abort_now_ && *abort_now_))
    stopped_ = true;
  if (stopped_) return 0;

  const int alpha_orig = alpha;

  // Mate-distance pruning.
  alpha = std::max(alpha, -(VALUE_MATE - ply));
  beta = std::min(beta, VALUE_MATE - (ply + 1));
  if (alpha >= beta) return alpha;

  const Move excluded = ply <= MAX_PLY ? excluded_[ply] : MOVE_NONE;

  TTData tte;
  bool hit = tt_->probe(pos.hash, tte);
  Move tt_move = hit ? tte.move : MOVE_NONE;
  // No TT cutoff during a singular verification search: the stored
  // bound is for the full move set, this node is searched with the TT
  // move excluded.
  if (hit && !is_pv && ply > 0 && excluded == MOVE_NONE &&
      tte.depth >= depth && tte.bound != TT_NONE) {
    int v = value_from_tt(tte.value, ply);
    if ((tte.bound == TT_EXACT) ||
        (tte.bound == TT_LOWER && v >= beta) ||
        (tte.bound == TT_UPPER && v <= alpha))
      return v;
  }

  // Internal iterative reduction: with no TT move to try first, deep
  // ordering is blind — search one ply shallower and let the re-visit
  // (which then HAS a TT move) go deep. Cheaper than the classic
  // internal iterative deepening search it replaces.
  if (depth >= 4 && tt_move == MOVE_NONE) depth--;

  // Margin eval for the prunings below: the host-side CLASSICAL eval,
  // not NNUE. Deliberate: an NNUE eval costs a device round-trip on the
  // batched bridge (pruning could never repay it), and gating pruning
  // on whichever evals HAPPEN to sit in the TT would make the search
  // tree depend on the backend and on batch pressure (prefetch budget)
  // — the scalar-vs-batched parity oracle found exactly that
  // divergence. hce_evaluate is a sub-microsecond deterministic
  // function of the position, so both backends prune identically;
  // every RETURNED score still comes from NNUE (the razor path returns
  // the qsearch value, reverse futility returns the beta bound).
  int margin_eval = 0;
  bool have_margin = false;
  if (!in_check) {
    // Computed at EVERY quiet node (hce_evaluate is a sub-microsecond
    // deterministic piece loop): the margin prunings below gate on
    // margin_ok, and the eval stack feeds `improving` at any depth.
    constexpr int LIMIT = VALUE_MATE_IN_MAX - 1;
    int v = hce_evaluate(pos);
    margin_eval = v < -LIMIT ? -LIMIT : (v > LIMIT ? LIMIT : v);
    have_margin = true;
    if (ply <= MAX_PLY) eval_stack_[ply] = margin_eval;
  }
  if (ply <= MAX_PLY) eval_valid_[ply] = !in_check;
  // Improving: our static eval rose vs two plies ago (fall back to four
  // when ply-2 was a check); three-state because the heuristics want
  // OPPOSITE defaults when no ancestor exists. In-check nodes never
  // count as improving.
  int improving_state = -1;  // -1 unknown, 0 no, 1 yes
  if (!in_check) {
    if (ply >= 2 && eval_valid_[ply - 2])
      improving_state = margin_eval > eval_stack_[ply - 2] ? 1 : 0;
    else if (ply >= 4 && eval_valid_[ply - 4])
      improving_state = margin_eval > eval_stack_[ply - 4] ? 1 : 0;
  }
  // LMP keeps more moves / LMR reduces less when improving — unknown
  // defaults to the permissive side (treat as improving).
  const bool improving = in_check ? false : improving_state != 0;
  // RFP/futility margins SHRINK when improving (more pruning) — unknown
  // defaults to the wide margin (treat as not improving), so an
  // ancestor-less node never prunes harder than the pre-improving code.
  const bool improving_margin = improving_state == 1;
  // The margin prunings (RFP / razor / futility) keep their historical
  // gates: non-PV, non-root, shallow.
  const bool margin_ok = have_margin && !is_pv && ply > 0 && depth <= 8;

  // Reverse futility (static beta) pruning: far enough above beta that a
  // shallow search will not drop back under it.
  if (margin_ok && std::abs(beta) < VALUE_MATE_IN_MAX &&
      margin_eval - (improving_margin ? 60 : 80) * depth >= beta)
    return beta;

  // Razoring: hopeless at shallow depth — verify with qsearch and trust
  // a confirming fail-low.
  if (margin_ok && depth <= 3 && margin_eval + 280 * depth < alpha) {
    int v = qsearch(pos, alpha - 1, alpha, ply);
    if (stopped_) return 0;
    if (v < alpha) return v;
  }

  // Null-move pruning: skip a turn; if we still beat beta at reduced
  // depth, the node is almost certainly a fail-high. Requires non-pawn
  // material to avoid zugzwang traps. Skipped during singular
  // verification (the exclusion makes this a different node).
  if (!is_pv && !in_check && depth >= 3 && ply > 0 && excluded == MOVE_NONE &&
      pos.variant != VR_ANTICHESS &&
      (pos.pieces(pos.stm) & ~(pos.pieces(pos.stm, PAWN) | pos.pieces(pos.stm, KING)))) {
    Position copy = pos;
    copy.make_null();
    path_.push_back(copy.hash);
    move_stack_[ply + 1] = MOVE_NONE;
    // Depth-scaled reduction (the flat R=2 this replaces wasted most of
    // the null search's verification budget at high depth), deepened
    // further the more the static eval already clears beta.
    int R = 3 + depth / 4;
    if (have_margin && margin_eval > beta)
      R += std::min((margin_eval - beta) / 200, 3);
    int v = -alpha_beta(copy, -beta, -beta + 1, depth - 1 - R, ply + 1, false);
    path_.pop_back();
    if (stopped_) return 0;
    if (v >= beta && v < VALUE_MATE_IN_MAX) return v;
  }

  MoveList moves;
  pos.legal_moves(moves);
  if (moves.size == 0) {
    if (pos.variant == VR_ANTICHESS) return VALUE_MATE - ply;  // no moves: win
    return in_check ? -(VALUE_MATE - ply) : VALUE_DRAW;
  }

  // Probcut: at real depth, a good capture that already clears
  // beta + margin in qsearch AND confirms it at reduced depth is so
  // far above this node's window that the full-depth search is noise —
  // fail high now. (The margin keeps the error rate below the value of
  // the saved subtree; standard in every top engine.) Gated on
  // see_full_ like the other material heuristics: the premise — a
  // winning capture moves the EVAL by about the material won — is
  // exactly the material-correlation property the net probe certifies
  // (measured: under a material-blind random net the probe qsearches
  // cost ~1 ply of depth and buy nothing).
  if (see_full_ && !is_pv && !in_check && depth >= 5 && excluded == MOVE_NONE &&
      std::abs(beta) < VALUE_MATE_IN_MAX) {
    const int pbeta = beta + 180;
    for (Move m : moves) {
      if (pos.empty(move_to(m)) && move_kind(m) != MK_EN_PASSANT) continue;
      if (see_applicable(pos.variant) && see(pos, m) < 0) continue;
      Position copy = pos;
      int mover = moving_piece(pos, m);
      copy.make(m);
      path_.push_back(copy.hash);
      move_stack_[ply + 1] = m;
      piece_stack_[ply + 1] = mover;
      int v = -qsearch(copy, -pbeta, -pbeta + 1, ply + 1);
      if (!stopped_ && v >= pbeta)
        v = -alpha_beta(copy, -pbeta, -pbeta + 1, depth - 4, ply + 1, false);
      path_.pop_back();
      if (stopped_) return 0;
      if (v >= pbeta) return v;
    }
  }

  // Singular extension: when the TT move's stored bound towers over
  // every alternative, it is probably the ONLY move — verify with a
  // reduced search of the remaining moves below (ttValue - margin); a
  // fail-low certifies singularity and the TT move searches one ply
  // deeper. The flip side is multicut: if even the excluded search
  // beats beta, two distinct refutations exist and the node fails high
  // without searching at all.
  int singular_ext = 0;
  if (ply > 0 && ply < MAX_PLY && depth >= 7 && excluded == MOVE_NONE &&
      hit && tt_move != MOVE_NONE &&
      (tte.bound == TT_LOWER || tte.bound == TT_EXACT) &&
      tte.depth >= depth - 3 &&
      std::abs(tte.value) < VALUE_MATE_IN_MAX) {
    int ttv = value_from_tt(tte.value, ply);
    int sbeta = ttv - 2 * depth;
    excluded_[ply] = tt_move;
    int v = alpha_beta(pos, sbeta - 1, sbeta, (depth - 1) / 2, ply, false);
    excluded_[ply] = MOVE_NONE;
    if (stopped_) return 0;
    if (v < sbeta)
      singular_ext = 1;
    else if (sbeta >= beta && std::abs(sbeta) < VALUE_MATE_IN_MAX)
      return sbeta;  // multicut
  }

  // Move ordering: the depth-1 batched frontier needs the full ordered
  // list up front (its prefetch ships the best children in one round-
  // trip), so it keeps the eager sort. Everywhere else moves are
  // scored once and picked lazily — a cut node consumes 1-3 picks and
  // never pays for sorting (or SEE-checking) the other ~30 moves.
  int scores[MAX_MOVES];
  bool taken[MAX_MOVES];
  bool see_checked[MAX_MOVES];
  std::memset(taken, 0, size_t(moves.size));
  std::memset(see_checked, 0, size_t(moves.size));
  // Eager on BOTH backends at depth 1 — the ordering (and therefore
  // the tree) must be a backend-independent function of the position,
  // or the scalar-vs-batched parity invariant breaks; only the
  // prefetch itself is batched-only.
  bool eager = depth == 1;
  if (eager) {
    order_moves(pos, moves, tt_move, ply);
    // Frontier prefetch: at depth 1 each visited child becomes a
    // qsearch root needing a stand-pat eval — fetch them (ordered,
    // within the pool's speculation budget) in one round-trip instead
    // of one each. PREDICTION-GATED: the move loop's own LMP/futility/
    // SEE conditions are exact functions of state already in hand, so
    // children the loop will prune are never shipped (they were the
    // bulk of the measured speculative waste; a futility-exempt
    // check-giving quiet is the one mispredicted class — it costs a
    // demand round-trip, not correctness).
    if (eval_->batched()) {
      if (see_full_ && !is_pv && !in_check) {
        const bool fut_all =
            margin_ok &&
            margin_eval + 120 * (depth - (improving_margin ? 1 : 0)) + 100 <=
                alpha &&
            std::abs(alpha) < VALUE_MATE_IN_MAX;
        const int lmp_bound = (3 + depth * depth) / (improving ? 1 : 2);
        MoveList pf;
        int mc = 0;
        for (Move m : moves) {
          mc++;
          bool quiet = pos.empty(move_to(m)) &&
                       move_kind(m) != MK_EN_PASSANT &&
                       move_promo(m) == NO_PIECE_TYPE;
          // The first move is always searched (pruning waits for a
          // banked score); after it, mirror the loop's quiet pruning.
          if (mc > 1 && quiet && (fut_all || mc > lmp_bound)) continue;
          // Mirror the loop's shallow SEE prune exactly (-200*depth, not
          // 0): a mildly losing capture IS searched and needs its eval.
          if (mc > 1 && !quiet && move_promo(m) == NO_PIECE_TYPE &&
              losing_capture(pos, m, -200 * depth))
            continue;
          pf.push(m);
        }
        prefetch_evals(pos, pf, /*include_self=*/false,
                       std::min(int(pf.size), eval_->prefetch_budget()));
      } else {
        prefetch_evals(pos, moves, /*include_self=*/false,
                       eval_->prefetch_budget());
      }
    }
  } else {
    score_moves(pos, moves, tt_move, ply, scores);
  }
  int next_eager = 0;

  auto pick_move = [&]() -> int {
    if (eager) return next_eager < moves.size ? next_eager++ : -1;
    while (true) {
      int bi = -1, bs = 0;
      for (int i = 0; i < moves.size; i++)
        if (!taken[i] && (bi < 0 || scores[i] > bs)) {
          bi = i;
          bs = scores[i];
        }
      if (bi < 0) return -1;
      Move m = moves.moves[bi];
      // Deferred losing-capture demotion: SEE runs only when a capture
      // is actually about to be picked AND the exchange can lose
      // (attacker outvalues victim). A losing capture drops behind
      // every quiet and the pick restarts. Keyed on the MOVE being an
      // un-demoted capture — not on band arithmetic, which a pawn
      // victim (value 0) slips under.
      if (see_full_ && !see_checked[bi] && m != tt_move && bs > 0 &&
          (!pos.empty(move_to(m)) || move_kind(m) == MK_EN_PASSANT)) {
        see_checked[bi] = true;
        if (losing_capture(pos, m, 0)) {
          scores[bi] = -(1 << 20) + capture_victim(pos, m) * 16 -
                       capture_attacker(pos, m);
          continue;
        }
      }
      taken[bi] = true;
      return bi;
    }
  };

  Move best_move = MOVE_NONE;
  int best = -VALUE_INF;
  int move_count = 0;
  Move tried_quiets[64];
  int n_tried_quiets = 0;

  for (int mi = pick_move(); mi >= 0; mi = pick_move()) {
    Move m = moves.moves[mi];
    if (m == excluded) continue;
    if (ply == 0 &&
        std::find(excluded_root_moves_.begin(), excluded_root_moves_.end(), m) !=
            excluded_root_moves_.end())
      continue;
    move_count++;

    bool is_quiet = pos.empty(move_to(m)) && move_kind(m) != MK_EN_PASSANT &&
                    move_promo(m) == NO_PIECE_TYPE;

    // SEE pruning for captures at shallow depth: an exchange losing more
    // than a depth-scaled margin almost never recovers in the remaining
    // plies. Depth-bounded so deep tactics stay exhaustive; checked
    // before the copy+make below so pruned moves cost nothing.
    if (!is_pv && !in_check && !is_quiet && best > -VALUE_INF &&
        depth <= 5 && std::abs(alpha) < VALUE_MATE_IN_MAX &&
        see_applicable(pos.variant) && see(pos, m) < -200 * depth)
      continue;

    Position copy = pos;
    copy.make(m);

    // Shallow-depth quiet pruning, only once a real score is banked
    // (best > -INF) so a forced line is never pruned into a false mate/
    // stalemate. Checking moves are exempt: they are exactly the quiets
    // a static margin misjudges.
    if (!is_pv && !in_check && is_quiet && best > -VALUE_INF &&
        std::abs(alpha) < VALUE_MATE_IN_MAX && !copy.in_check()) {
      // Late move pruning: quiets this deep in the ordered list at
      // shallow depth almost never raise alpha. The standard quadratic
      // move-count bound, halved when the eval is not improving.
      if (depth <= 8 &&
          move_count > (3 + depth * depth) / (improving ? 1 : 2))
        continue;
      // Futility: margin eval so far below alpha that a quiet move
      // cannot recover within the remaining depth.
      if (depth <= 6 && margin_ok &&
          margin_eval + 120 * (depth - (improving_margin ? 1 : 0)) + 100 <= alpha)
        continue;
      // Continuation-history pruning: a quiet whose combined history
      // signal is THIS bad at shallow depth is virtually never the
      // move that raises alpha (and when it would be, the re-visit at
      // depth+1 — where the bound no longer binds — still finds it).
      if (depth <= 6 && !eager && scores[mi] < (1 << 15) &&
          scores[mi] < -3000 * depth)
        continue;
    }

    if (is_quiet && n_tried_quiets < 64) tried_quiets[n_tried_quiets++] = m;

    path_.push_back(copy.hash);
    move_stack_[ply + 1] = m;
    piece_stack_[ply + 1] = moving_piece(pos, m);

    int ext = m == tt_move ? singular_ext : 0;
    int value;
    if (move_count == 1) {
      value = -alpha_beta(copy, -beta, -alpha, depth - 1 + ext, ply + 1, is_pv);
    } else {
      // Late-move reduction (log-shaped table) for quiet late moves,
      // then PVS re-searches. Adjustments: PV nodes and killers reduce
      // one less; a strong/weak combined history signal nudges the
      // reduction by up to one ply each way; replies that give check
      // reduce one less (exactly the quiets a reduced search misjudges).
      int reduction = 0;
      if (depth >= 2 && move_count > 1 && !in_check && !is_quiet) {
        // Late captures reduce too, one ply gentler than quiets: a
        // capture deep in the ordered list is usually a bad exchange
        // already demoted by SEE, not a tactic.
        reduction = std::max(
            0, kLmr.r[std::min(depth, 63)][std::min(move_count, 63)] - 1);
        if (is_pv) reduction = std::max(0, reduction - 1);
        reduction = std::max(0, std::min(reduction, depth - 2));
      }
      if (depth >= 2 && move_count > 1 && is_quiet && !in_check) {
        reduction = kLmr.r[std::min(depth, 63)][std::min(move_count, 63)];
        if (is_pv) reduction--;
        if (ply < MAX_PLY && (m == killers_[ply][0] || m == killers_[ply][1]))
          reduction--;
        // History nudge from the combined quiet signal — only when the
        // score IS that signal (below the counter band), not a
        // killer/counter band value.
        int h = eager || scores[mi] >= (1 << 15) ? 0 : scores[mi];
        if (h > 8192) reduction--;
        else if (h < -4096) reduction++;
        if (copy.in_check()) reduction--;
        // A non-improving node's late quiets are the least likely
        // moves on the board to matter: reduce one more (standard).
        if (!improving) reduction++;
        reduction = std::max(0, std::min(reduction, depth - 2));
      }
      value = -alpha_beta(copy, -alpha - 1, -alpha, depth - 1 - reduction,
                          ply + 1, false);
      if (value > alpha && reduction > 0)
        value = -alpha_beta(copy, -alpha - 1, -alpha, depth - 1, ply + 1, false);
      if (value > alpha && value < beta)
        value = -alpha_beta(copy, -beta, -alpha, depth - 1, ply + 1, is_pv);
    }
    path_.pop_back();
    if (stopped_ && best > -VALUE_INF) break;
    if (stopped_) return 0;

    if (value > best) {
      best = value;
      best_move = m;
      if (value > alpha) {
        alpha = value;
        if (is_pv && ply + 1 < MAX_PLY) {
          pv_table_[ply][0] = m;
          memcpy(&pv_table_[ply][1], &pv_table_[ply + 1][0],
                 sizeof(Move) * pv_len_[ply + 1]);
          pv_len_[ply] = pv_len_[ply + 1] + 1;
        }
        if (alpha >= beta) {
          // Killer/countermove/history/continuation-history bookkeeping
          // for quiet cutoffs, with a malus for the quiets tried first.
          if (is_quiet)
            update_quiet_stats(pos, m, depth, ply, tried_quiets,
                               n_tried_quiets);
          break;
        }
      }
    }
  }

  if (move_count == 0) {
    // All moves excluded: alpha for a singular verification (the
    // TT move was the only legal move — maximally singular), the
    // MultiPV terminal for an exhausted root.
    return excluded != MOVE_NONE ? alpha : VALUE_DRAW;
  }

  if (!stopped_ && excluded == MOVE_NONE) {
    TTBound bound = best >= beta    ? TT_LOWER
                    : best > alpha_orig ? TT_EXACT
                                        : TT_UPPER;
    tt_->store(pos.hash, best_move, value_to_tt(best, ply), EVAL_NONE, depth, bound);
  }

  return best;
}

SearchResult Search::run(const Position& root,
                         const std::vector<uint64_t>& game_history,
                         const SearchLimits& limits) {
  SearchResult result;
  nodes_ = 0;
  node_limit_ = limits.nodes;
  stopped_ = false;
  allow_stop_ = false;
  external_stop_ = limits.stop;
  abort_now_ = limits.abort_now;
  path_ = game_history;
  if (path_.empty() || path_.back() != root.hash) path_.push_back(root.hash);
  root_history_len_ = path_.size();
  memset(killers_, 0xFF, sizeof(killers_));
  memset(history_, 0, sizeof(history_));
  memset(countermove_, 0xFF, sizeof(countermove_));  // MOVE_NONE fill
  memset(excluded_, 0xFF, sizeof(excluded_));        // MOVE_NONE fill
  memset(piece_stack_, 0, sizeof(piece_stack_));
  move_stack_[0] = MOVE_NONE;
  tt_->new_generation();

  MoveList root_moves;
  root.legal_moves(root_moves);
  if (root_moves.size == 0) {
    // Terminal root: report like a finished engine would (depth 0,
    // mate 0 when checkmated, cp 0 when stalemated; protocol.md:99-104).
    PvLine line;
    line.depth = 0;
    line.mate = root.effective_check();
    line.value = 0;
    result.lines.push_back(line);
    result.nodes = 0;
    return result;
  }

  int max_depth = limits.depth > 0 ? std::min(limits.depth, MAX_PLY - 1) : MAX_PLY - 1;
  int multipv = std::min<int>(std::max(1, limits.multipv), root_moves.size);
  // Weakened play needs candidates to blunder INTO: search at least 4
  // root lines (Stockfish's own skill implementation does the same).
  const bool weakened = limits.skill < 20;
  int search_multipv =
      weakened ? std::min<int>(std::max(multipv, 4), root_moves.size)
               : multipv;

  Move overall_best = MOVE_NONE;
  int prev_value = 0;
  bool have_prev = false;
  // (move, INTERNAL value) per rank of the last fully-completed
  // iteration — the weakened pick needs comparable cp values, not the
  // UCI-converted mate distances stored in result.lines.
  std::vector<std::pair<Move, int>> iter_ranks, final_ranks;

  for (int depth = 1; depth <= max_depth && !stopped_; depth++) {
    std::vector<Move> excluded;
    bool all_ranks = true;
    for (int rank = 1; rank <= search_multipv; rank++) {
      excluded_root_moves_ = excluded;
      // Aspiration window around the previous iteration's score (rank 1
      // only — secondary PVs have no stable anchor). A window miss
      // widens geometrically and re-searches; the savings from the
      // narrow bounds buy roughly an extra ply per node budget.
      int alpha = -VALUE_INF, beta = VALUE_INF;
      int delta = 18;
      if (rank == 1 && depth >= 4 && have_prev &&
          std::abs(prev_value) < VALUE_MATE_IN_MAX) {
        alpha = std::max(prev_value - delta, -VALUE_INF);
        beta = std::min(prev_value + delta, VALUE_INF);
      }
      int value;
      while (true) {
        value = alpha_beta(root, alpha, beta, depth, 0, true);
        if (stopped_) break;
        if (value <= alpha && alpha > -VALUE_INF) {
          alpha = std::max(value - delta, -VALUE_INF);
          delta *= 3;
        } else if (value >= beta && beta < VALUE_INF) {
          beta = std::min(value + delta, VALUE_INF);
          delta *= 3;
        } else {
          break;
        }
      }
      if (stopped_ || pv_len_[0] == 0) {  // discard interrupted search
        all_ranks = false;
        break;
      }
      if (rank == 1) {
        prev_value = value;
        have_prev = true;
        iter_ranks.clear();
      }
      iter_ranks.emplace_back(Move(pv_table_[0][0]), value);
      PvLine line;
      line.multipv = rank;
      line.depth = depth;
      value_to_uci(value, line.mate, line.value);
      line.pv.assign(&pv_table_[0][0], &pv_table_[0][0] + pv_len_[0]);
      result.lines.push_back(line);
      excluded.push_back(line.pv[0]);
      if (rank == 1) {
        overall_best = line.pv[0];
        result.depth = depth;
      }
    }
    // At least one full iteration is in the bag; the node budget may now
    // interrupt freely.
    allow_stop_ = true;
    if (all_ranks) final_ranks = iter_ranks;
    if (abort_now_ && *abort_now_) break;
    if (node_limit_ && nodes_ >= node_limit_) break;
    if (external_stop_ && *external_stop_) break;
  }

  result.best_move = overall_best;
  if (weakened && final_ranks.size() > 1) {
    // Stockfish-style skill pick: each candidate gets a pseudo-random
    // "push" that grows with the level's weakness and with how close the
    // line is to the best one; the highest pushed score plays. Seeded
    // from (root hash, node count) so identical searches stay
    // reproducible while successive moves of a game vary.
    uint64_t s = root.hash ^ (nodes_ * 0x9E3779B97F4A7C15ull);
    auto rng = [&s]() {
      s += 0x9E3779B97F4A7C15ull;
      uint64_t z = s;
      z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
      z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
      return z ^ (z >> 31);
    };
    const int top = final_ranks.front().second;
    const int bottom = final_ranks.back().second;
    const int delta = std::min(top - bottom, 150);  // ~one pawn of spread
    const int weakness = 120 - 2 * limits.skill;    // −9..19 → 138..82
    // Normalizing by max(128, weakness) keeps the candidate's own score
    // coefficient non-negative for the sub-zero skills the protocol
    // allows (weakness > 128 would otherwise actively PREFER the worst
    // line): at skill ≤ −4 the pick degrades to uniform noise among the
    // candidates — a beginner playing any of 4 plausible moves — never
    // an anti-engine.
    const int norm = std::max(128, weakness);
    int max_score = -VALUE_INF;
    for (const auto& cand : final_ranks) {
      const int push =
          (weakness * (top - cand.second) +
           delta * int(rng() % uint64_t(weakness))) / norm;
      if (cand.second + push >= max_score) {
        max_score = cand.second + push;
        result.best_move = cand.first;
      }
    }
  }
  result.nodes = nodes_;
  return result;
}

}  // namespace fc
