// C ABI for the native core, consumed from Python via ctypes
// (fishnet_tpu/chess/core.py). Kept deliberately string-based at the
// boundary (FEN in, UCI out) so the Python side stays simple; the hot
// search path never crosses this boundary per-node.

#include <cstring>
#include <new>
#include <string>

#include "nnue.h"
#include "position.h"
#include "search.h"

using namespace fc;

namespace {

int copy_out(const std::string& s, char* buf, int len) {
  if (!buf || len <= 0) return -1;
  if (int(s.size()) + 1 > len) return -1;
  memcpy(buf, s.c_str(), s.size() + 1);
  return int(s.size());
}

// All eight lichess variants are implemented and perft-validated
// (tests/test_variants.py): standard/Chess960, antichess, atomic,
// crazyhouse, horde, king-of-the-hill, racing kings, three-check —
// the same set the reference serves via Fairy-Stockfish
// (src/logger.rs:192-203).
bool variant_supported(int variant) {
  return variant >= VR_STANDARD && variant <= VR_THREE_CHECK;
}

}  // namespace

extern "C" {

// ABI version of this ctypes surface. Bump on ANY exported-signature
// change; the Python binder refuses mismatched libraries (a stale
// prebuilt tier .so with an old layout would otherwise corrupt memory
// through shifted arguments).
// 8: fc_pool_provide returns int (entries consumed / -1 on a
//    full-provide contract violation with anchors enabled).
// 9: fc_pool_step's out_material may be nullptr — the material column
//    is optional on the wire (device-resident PSQT path; kept for the
//    CPU/XLA host-material fallback and tests).
// 10: position-keyed eval reuse exports — fc_pool_batch_hashes
//     (Zobrist hashes of the pending batch), fc_pool_cancel_anchors
//     (pre-provide anchor invalidation for skipped dispatches),
//     fc_pool_tt_fill (provide-time TT fill from the host eval cache).
// 11: bounds-tier exports — fc_pool_tt_fill_bound (seed a full bound
//     record: value/eval/depth/bound/move) and fc_pool_tt_export
//     (harvest bound-carrying TT entries for the host bounds tier).
int fc_abi_version() { return 11; }

int fc_init() {
  init_bitboards();
  init_zobrist();
  return 0;
}

int fc_variant_supported(int variant) { return variant_supported(variant) ? 1 : 0; }

Position* fc_pos_new(const char* fen, int variant, char* err, int errlen) {
  if (!variant_supported(variant)) {
    if (err) copy_out("unsupported variant", err, errlen);
    return nullptr;
  }
  Position* pos = new (std::nothrow) Position();
  if (!pos) return nullptr;
  std::string e = pos->set_fen(fen ? fen : "", VariantRules(variant));
  if (!e.empty()) {
    if (err) copy_out(e, err, errlen);
    delete pos;
    return nullptr;
  }
  return pos;
}

Position* fc_pos_clone(const Position* pos) {
  return pos ? new (std::nothrow) Position(*pos) : nullptr;
}

void fc_pos_free(Position* pos) { delete pos; }

int fc_pos_play_uci(Position* pos, const char* uci) {
  Move m = pos->parse_uci(uci ? uci : "");
  if (m == MOVE_NONE) return -1;
  pos->make(m);
  return 0;
}

int fc_pos_fen(const Position* pos, char* buf, int len) {
  return copy_out(pos->fen(), buf, len);
}

// Parse a UCI move (accepting standard castling notation) and return its
// canonical encoding (Chess960-style castling), without playing it. -1 if
// illegal. Mirrors the reference's move renormalization through shakmaty
// (src/queue.rs:543-552).
int fc_pos_parse_uci(const Position* pos, const char* uci, char* buf, int len) {
  Move m = pos->parse_uci(uci ? uci : "");
  if (m == MOVE_NONE) return -1;
  return copy_out(pos->uci(m), buf, len);
}

int fc_pos_turn(const Position* pos) { return int(pos->stm); }

int fc_pos_is_check(const Position* pos) { return pos->in_check() ? 1 : 0; }

int fc_pos_halfmove(const Position* pos) { return pos->halfmove; }

int fc_pos_fullmove(const Position* pos) { return pos->fullmove; }

unsigned long long fc_pos_hash(const Position* pos) { return pos->hash; }

int fc_pos_outcome(const Position* pos) { return pos->outcome(); }

// Space-separated UCI strings of all legal moves.
int fc_pos_legal_moves(const Position* pos, char* buf, int len) {
  MoveList legal;
  pos->legal_moves(legal);
  std::string out;
  for (Move m : legal) {
    if (!out.empty()) out += ' ';
    out += pos->uci(m);
  }
  return copy_out(out, buf, len);
}

unsigned long long fc_perft(const Position* pos, int depth) {
  return perft(*pos, depth);
}

// ---------------------------------------------------------------------------
// NNUE
// ---------------------------------------------------------------------------

NnueNet* fc_nnue_load(const char* path, char* err, int errlen) {
  NnueNet* net = new (std::nothrow) NnueNet();
  if (!net) return nullptr;
  std::string e = net->load(path ? path : "");
  if (!e.empty()) {
    if (err) copy_out(e, err, errlen);
    delete net;
    return nullptr;
  }
  return net;
}

void fc_nnue_free(NnueNet* net) { delete net; }

// Incremental-eval cache handles, for the cached-vs-fresh parity tests
// (the search uses a thread_local cache internally; tests need an
// explicit one to drive deterministic sequences through).
NnueEvalCache* fc_nnue_cache_new() { return new (std::nothrow) NnueEvalCache(); }
void fc_nnue_cache_free(NnueEvalCache* cache) { delete cache; }
int fc_nnue_evaluate_cached_test(const NnueNet* net, const Position* pos,
                                 NnueEvalCache* cache) {
  return nnue_evaluate_cached(*net, *pos, *cache);
}

int fc_nnue_material_correlated(const NnueNet* net) {
  return nnue_material_correlated(*net) ? 1 : 0;
}

int fc_nnue_evaluate(const NnueNet* net, const Position* pos) {
  if (pos->variant != VR_STANDARD) return INT32_MIN;  // NNUE needs both kings
  return nnue_evaluate(*net, *pos);
}

// HalfKAv2_hm features of one perspective (0 = side to move, 1 = other).
// out must hold 32 int32s; returns the active count, or -1 for variant
// positions (HalfKA features are anchored on king squares).
int fc_pos_features(const Position* pos, int perspective_rel, int32_t* out) {
  if (pos->variant != VR_STANDARD) return -1;
  Color perspective = perspective_rel == 0 ? pos->stm : ~pos->stm;
  return nnue_features(*pos, perspective, out);
}

// Layer-stack / PSQT bucket of the position.
int fc_pos_psqt_bucket(const Position* pos) { return nnue_psqt_bucket(*pos); }

// Static exchange evaluation of a UCI move (search.h see()); exposed so
// the Python suite can pin the exchange oracle against hand-computed
// sequences. Returns INT32_MIN when the move does not parse.
int fc_pos_see(const Position* pos, const char* uci) {
  Move m = pos->parse_uci(uci);
  if (m == MOVE_NONE) return INT32_MIN;
  return see(*pos, m);
}

}  // extern "C"
