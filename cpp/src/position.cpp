#include "position.h"

#include <cctype>
#include <cstring>
#include <sstream>

namespace fc {

// ---------------------------------------------------------------------------
// Zobrist keys, generated deterministically with splitmix64.
// ---------------------------------------------------------------------------

namespace zobrist {
uint64_t piece_sq[12][64];
uint64_t castling_rook[64];
uint64_t ep_file[8];
uint64_t black_to_move;
uint64_t checks[COLOR_NB][4];
uint64_t hand_piece[COLOR_NB][PIECE_TYPE_NB][17];
uint64_t promoted_sq[64];
// Per-variant keys: the same FEN under different rules (or a different
// eval family) must never collide in the shared transposition table.
uint64_t variant_key[8];
}  // namespace zobrist

static uint64_t splitmix64(uint64_t& x) {
  x += 0x9E3779B97F4A7C15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

void init_zobrist() {
  static bool done = false;
  if (done) return;
  done = true;
  uint64_t seed = 0x5EEDFEEDC0FFEE42ULL;
  for (auto& arr : zobrist::piece_sq)
    for (auto& v : arr) v = splitmix64(seed);
  for (auto& v : zobrist::castling_rook) v = splitmix64(seed);
  for (auto& v : zobrist::ep_file) v = splitmix64(seed);
  zobrist::black_to_move = splitmix64(seed);
  for (auto& arr : zobrist::checks)
    for (auto& v : arr) v = splitmix64(seed);
  for (auto& c : zobrist::hand_piece)
    for (auto& p : c)
      for (auto& v : p) v = splitmix64(seed);
  for (auto& v : zobrist::promoted_sq) v = splitmix64(seed);
  zobrist::variant_key[VR_STANDARD] = 0;  // identity: standard hashes unchanged
  for (int v = VR_STANDARD + 1; v <= VR_THREE_CHECK; v++)
    zobrist::variant_key[v] = splitmix64(seed);
}

// ---------------------------------------------------------------------------
// Board manipulation
// ---------------------------------------------------------------------------

void Position::put_piece(Square s, int pc) {
  board[s] = uint8_t(pc);
  by_color[piece_color(pc)] |= bb(s);
  by_type[piece_type(pc)] |= bb(s);
  hash ^= zobrist::piece_sq[pc][s];
}

void Position::remove_piece(Square s) {
  int pc = board[s];
  board[s] = NO_PIECE;
  by_color[piece_color(pc)] &= ~bb(s);
  by_type[piece_type(pc)] &= ~bb(s);
  hash ^= zobrist::piece_sq[pc][s];
}

Bitboard Position::attackers_to(Square s, Bitboard occ) const {
  return (PAWN_ATTACKS[WHITE][s] & pieces(BLACK, PAWN)) |
         (PAWN_ATTACKS[BLACK][s] & pieces(WHITE, PAWN)) |
         (KNIGHT_ATTACKS[s] & by_type[KNIGHT]) |
         (KING_ATTACKS[s] & by_type[KING]) |
         (rook_attacks(s, occ) & (by_type[ROOK] | by_type[QUEEN])) |
         (bishop_attacks(s, occ) & (by_type[BISHOP] | by_type[QUEEN]));
}

uint64_t Position::compute_hash() const {
  uint64_t h = 0;
  for (Square s = 0; s < 64; s++)
    if (board[s] != NO_PIECE) h ^= zobrist::piece_sq[board[s]][s];
  Bitboard cr = castling_rooks;
  while (cr) h ^= zobrist::castling_rook[pop_lsb(cr)];
  if (ep_square != SQ_NONE) h ^= zobrist::ep_file[file_of(ep_square)];
  if (stm == BLACK) h ^= zobrist::black_to_move;
  for (Color c : {WHITE, BLACK}) {
    if (checks_given[c]) h ^= zobrist::checks[c][checks_given[c] & 3];
    for (int pt = PAWN; pt < PIECE_TYPE_NB; pt++)
      if (hand[c][pt]) h ^= zobrist::hand_piece[c][pt][hand[c][pt]];
  }
  Bitboard promo = promoted;
  while (promo) h ^= zobrist::promoted_sq[pop_lsb(promo)];
  h ^= zobrist::variant_key[variant];
  return h;
}

// ---------------------------------------------------------------------------
// FEN
// ---------------------------------------------------------------------------

static const char PIECE_CHARS[] = "PNBRQKpnbrqk";

static int piece_from_char(char c) {
  const char* p = strchr(PIECE_CHARS, c);
  return p && c ? int(p - PIECE_CHARS) : NO_PIECE;
}

static std::string square_name(Square s) {
  std::string out;
  out += char('a' + file_of(s));
  out += char('1' + rank_of(s));
  return out;
}

static Square parse_square(const std::string& s) {
  if (s.size() != 2 || s[0] < 'a' || s[0] > 'h' || s[1] < '1' || s[1] > '8')
    return SQ_NONE;
  return make_square(s[0] - 'a', s[1] - '1');
}

std::string Position::set_fen(const std::string& fen, VariantRules var) {
  init_bitboards();
  init_zobrist();

  *this = Position();
  variant = var;
  memset(board, NO_PIECE, sizeof(board));

  std::istringstream ss(fen);
  std::string placement, turn, castling, ep;
  ss >> placement >> turn >> castling >> ep;
  if (placement.empty()) return "empty FEN";
  if (turn.empty()) turn = "w";
  if (castling.empty()) castling = "-";
  if (ep.empty()) ep = "-";

  // Remaining fields: halfmove, fullmove, and (three-check) a check-count
  // token that different producers place either between ep and halfmove
  // (X-FEN/shakmaty "3+3" = checks remaining) or trailing ("+0+0" =
  // checks given, legacy lichess). Scan flexibly: any token containing
  // '+' is a check field, plain integers fill halfmove then fullmove.
  std::string half, full, checks_tok;
  {
    std::string tok;
    int ints_seen = 0;
    while (ss >> tok) {
      if (tok.find('+') != std::string::npos) {
        checks_tok = tok;
      } else if (ints_seen == 0) {
        half = tok;
        ints_seen++;
      } else if (ints_seen == 1) {
        full = tok;
        ints_seen++;
      }
    }
  }
  if (!checks_tok.empty()) {
    int a = -1, b = -1;
    bool given = checks_tok[0] == '+';  // "+w+b" counts checks delivered
    if (given && checks_tok.size() == 4 && isdigit(checks_tok[1]) &&
        checks_tok[2] == '+' && isdigit(checks_tok[3])) {
      a = checks_tok[1] - '0';
      b = checks_tok[3] - '0';
    } else if (!given && checks_tok.size() == 3 && isdigit(checks_tok[0]) &&
               checks_tok[1] == '+' && isdigit(checks_tok[2])) {
      // remaining checks -> delivered = 3 - remaining
      a = 3 - (checks_tok[0] - '0');
      b = 3 - (checks_tok[2] - '0');
    }
    if (a < 0 || a > 3 || b < 0 || b > 3) return "bad check-count field";
    checks_given[WHITE] = uint8_t(a);
    checks_given[BLACK] = uint8_t(b);
  }

  // Piece placement. Lichess crazyhouse FENs may carry a pocket either as
  // an extra rank ("...8/PPPP[QRq]") or bracket suffix; accept "[...]".
  std::string pocket;
  size_t lb = placement.find('[');
  if (lb != std::string::npos) {
    size_t rb = placement.find(']', lb);
    if (rb == std::string::npos) return "unterminated pocket";
    pocket = placement.substr(lb + 1, rb - lb - 1);
    placement = placement.substr(0, lb);
  }

  int rank = 7, file = 0;
  for (size_t i = 0; i < placement.size(); i++) {
    char c = placement[i];
    if (c == '/') {
      if (file != 8) return "bad rank length";
      rank--;
      file = 0;
      if (rank < 0) return "too many ranks";
    } else if (isdigit(c)) {
      file += c - '0';
      if (file > 8) return "bad file count";
    } else if (c == '~') {
      // promoted-piece marker (crazyhouse): applies to the piece just
      // placed; it drops back into the pocket as a pawn when captured.
      if (file == 0) return "misplaced ~";
      promoted |= bb(make_square(file - 1, rank));
    } else {
      int pc = piece_from_char(c);
      if (pc == NO_PIECE || file > 7 || rank < 0) return "bad piece placement";
      put_piece(make_square(file, rank), pc);
      file++;
    }
  }
  if (rank != 0 || file != 8) return "incomplete placement";

  for (char c : pocket) {
    int pc = piece_from_char(c);
    if (pc == NO_PIECE) return "bad pocket piece";
    hand[piece_color(pc)][piece_type(pc)]++;
  }

  if (turn == "w")
    stm = WHITE;
  else if (turn == "b")
    stm = BLACK;
  else
    return "bad side to move";

  // Castling rights: K/Q/k/q (X-FEN: outermost rook on that side) or
  // file letters A-H / a-h (Shredder-FEN).
  if (castling != "-") {
    for (char c : castling) {
      Color color = isupper(c) ? WHITE : BLACK;
      int home_rank = color == WHITE ? 0 : 7;
      Square ksq = king_sq(color);
      if (ksq == SQ_NONE || rank_of(ksq) != home_rank) return "castling without king";
      char u = char(toupper(c));
      Square rook = SQ_NONE;
      Bitboard rooks = pieces(color, ROOK) & rank_bb(home_rank);
      if (u == 'K') {
        Bitboard right = rooks & ~(bb(ksq) - 1) & ~bb(ksq);
        if (right) rook = msb(right);  // outermost kingside rook
      } else if (u == 'Q') {
        Bitboard left = rooks & (bb(ksq) - 1);
        if (left) rook = lsb(left);  // outermost queenside rook
      } else if (u >= 'A' && u <= 'H') {
        Square cand = make_square(u - 'A', home_rank);
        if (rooks & bb(cand)) rook = cand;
      } else {
        return "bad castling field";
      }
      if (rook == SQ_NONE) return "castling right without rook";
      castling_rooks |= bb(rook);
    }
  }

  if (ep != "-") {
    Square s = parse_square(ep);
    if (s == SQ_NONE) return "bad en passant square";
    ep_square = s;
    if (!ep_capture_legal()) ep_square = SQ_NONE;
  }

  halfmove = half.empty() ? 0 : atoi(half.c_str());
  fullmove = full.empty() ? 1 : std::max(1, atoi(full.c_str()));

  // Basic sanity: both kings present (variants relax this later).
  if (variant != VR_ANTICHESS && variant != VR_HORDE) {
    if (popcount(pieces(WHITE, KING)) != 1 || popcount(pieces(BLACK, KING)) != 1)
      return "kings missing";
    // Side not to move must not be in check (illegal position) — except
    // in atomic, where adjacent kings annul all checks.
    if (!(variant == VR_ATOMIC && kings_adjacent())) {
      Square k = king_sq(~stm);
      if (k != SQ_NONE && attacked_by(k, stm, occupied()))
        return "side not to move is in check";
    }
  } else if (variant == VR_HORDE) {
    if (popcount(pieces(BLACK, KING)) != 1) return "kings missing";
  }

  hash = compute_hash();
  return "";
}

std::string Position::fen() const {
  std::ostringstream out;
  for (int r = 7; r >= 0; r--) {
    int run = 0;
    for (int f = 0; f < 8; f++) {
      int pc = board[make_square(f, r)];
      if (pc == NO_PIECE) {
        run++;
      } else {
        if (run) out << run;
        run = 0;
        out << PIECE_CHARS[pc];
        if (promoted & bb(make_square(f, r))) out << '~';
      }
    }
    if (run) out << run;
    if (r) out << '/';
  }

  if (variant == VR_CRAZYHOUSE) {
    out << '[';
    for (Color c : {WHITE, BLACK})
      for (int pt = QUEEN; pt >= PAWN; pt--)
        for (int i = 0; i < hand[c][pt]; i++)
          out << PIECE_CHARS[make_piece(c, PieceType(pt))];
    out << ']';
  }

  out << (stm == WHITE ? " w " : " b ");

  if (!castling_rooks) {
    out << '-';
  } else {
    // X-FEN: K/Q when the rook is the outermost one on its side, else the
    // rook's file letter.
    std::string rights;
    for (Color c : {WHITE, BLACK}) {
      int home_rank = c == WHITE ? 0 : 7;
      Square ksq = king_sq(c);
      Bitboard rooks_here = castling_rooks & by_color[c];
      std::vector<Square> sqs;
      Bitboard tmp = rooks_here;
      while (tmp) sqs.push_back(pop_lsb(tmp));
      // Emit kingside first, then queenside (descending file order).
      for (auto it = sqs.rbegin(); it != sqs.rend(); ++it) {
        Square rsq = *it;
        Bitboard all_rooks = pieces(c, ROOK) & rank_bb(home_rank);
        char letter;
        if (rsq > ksq) {
          Bitboard outer = all_rooks & ~(bb(rsq) | (bb(rsq) - 1));
          letter = outer ? char('A' + file_of(rsq)) : 'K';
        } else {
          Bitboard outer = all_rooks & (bb(rsq) - 1);
          letter = outer ? char('A' + file_of(rsq)) : 'Q';
        }
        rights += c == WHITE ? letter : char(tolower(letter));
      }
    }
    out << rights;
  }

  out << ' ' << (ep_square == SQ_NONE ? "-" : square_name(ep_square));

  if (variant == VR_THREE_CHECK)
    // X-FEN / shakmaty style: checks *remaining* as "W+B" between the
    // en-passant and halfmove fields (set_fen also accepts legacy "+w+b").
    out << ' ' << (3 - checks_given[WHITE]) << '+' << (3 - checks_given[BLACK]);

  out << ' ' << halfmove << ' ' << fullmove;
  return out.str();
}

// ---------------------------------------------------------------------------
// Move generation
// ---------------------------------------------------------------------------

bool Position::castle_path_ok(Square kfrom, Square rfrom) const {
  Color us = stm;
  bool kingside = rfrom > kfrom;
  Square kto = make_square(kingside ? 6 : 2, rank_of(kfrom));
  Square rto = make_square(kingside ? 5 : 3, rank_of(kfrom));

  Bitboard occ_wo = occupied() & ~bb(kfrom) & ~bb(rfrom);

  // All squares the king or rook pass through or land on must be empty
  // (ignoring the king and rook themselves).
  Bitboard kpath = BETWEEN[kfrom][kto] | bb(kto);
  Bitboard rpath = BETWEEN[rfrom][rto] | bb(rto);
  if ((kpath | rpath) & occ_wo) return false;

  // The king may not start from, or traverse, an attacked square.
  // Intermediate squares are tested with pre-move occupancy minus the king
  // (the rook has not moved yet). The destination square is deliberately
  // NOT tested here: is_legal()'s make+check covers it with the true final
  // occupancy, which handles the Chess960 rook-shelter case (castling rook
  // leaving its square can expose the king to an attacker behind it).
  Bitboard attack_check = (BETWEEN[kfrom][kto] | bb(kfrom)) & ~bb(kto);
  Bitboard occ_traverse = occupied() & ~bb(kfrom);
  while (attack_check) {
    Square s = pop_lsb(attack_check);
    if (attacked_by(s, ~us, occ_traverse)) return false;
  }
  return true;
}

void Position::gen_castling(MoveList& out) const {
  Color us = stm;
  Square ksq = king_sq(us);
  if (ksq == SQ_NONE) return;
  Bitboard rooks = castling_rooks & by_color[us];
  while (rooks) {
    Square rfrom = pop_lsb(rooks);
    if (castle_path_ok(ksq, rfrom)) out.push(make_move(ksq, rfrom, MK_CASTLE));
  }
}

void Position::gen_pseudo(MoveList& out) const {
  Color us = stm;
  Color them = ~us;
  Bitboard occ = occupied();
  Bitboard targets = ~by_color[us];  // not onto own pieces
  int up = us == WHITE ? 8 : -8;
  Bitboard rank3 = rank_bb(us == WHITE ? 2 : 5);
  Bitboard rank7 = rank_bb(us == WHITE ? 6 : 1);

  // Pawns.
  Bitboard pawns = pieces(us, PAWN);
  Bitboard non7 = pawns & ~rank7;
  Bitboard on7 = pawns & rank7;

  Bitboard single = pawn_pushes(us, non7, ~occ);
  Bitboard dbl = pawn_pushes(us, single & rank3, ~occ);
  // Horde: white pawns on the first rank may also advance two squares
  // (lichess horde rule; only white has back-rank pawns).
  if (variant == VR_HORDE && us == WHITE)
    dbl |= pawn_pushes(us, single & rank_bb(1), ~occ);
  Bitboard tmp = single;
  while (tmp) {
    Square to = pop_lsb(tmp);
    out.push(make_move(to - up, to));
  }
  tmp = dbl;
  while (tmp) {
    Square to = pop_lsb(tmp);
    out.push(make_move(to - 2 * up, to));
  }

  tmp = non7;
  while (tmp) {
    Square from = pop_lsb(tmp);
    Bitboard caps = PAWN_ATTACKS[us][from] & by_color[them];
    while (caps) out.push(make_move(from, pop_lsb(caps)));
    if (ep_square != SQ_NONE && (PAWN_ATTACKS[us][from] & bb(ep_square)))
      out.push(make_move(from, ep_square, MK_EN_PASSANT));
  }

  tmp = on7;
  while (tmp) {
    Square from = pop_lsb(tmp);
    Bitboard dests = (PAWN_ATTACKS[us][from] & by_color[them]);
    if (empty(from + up)) dests |= bb(from + up);
    while (dests) {
      Square to = pop_lsb(dests);
      for (PieceType promo : {QUEEN, KNIGHT, ROOK, BISHOP})
        out.push(make_move(from, to, MK_NORMAL, promo));
      if (variant == VR_ANTICHESS) out.push(make_move(from, to, MK_NORMAL, KING));
    }
  }

  // Knights / bishops / rooks / queens / king.
  for (PieceType pt : {KNIGHT, BISHOP, ROOK, QUEEN, KING}) {
    Bitboard pcs = pieces(us, pt);
    while (pcs) {
      Square from = pop_lsb(pcs);
      Bitboard att;
      switch (pt) {
        case KNIGHT: att = KNIGHT_ATTACKS[from]; break;
        case BISHOP: att = bishop_attacks(from, occ); break;
        case ROOK: att = rook_attacks(from, occ); break;
        case QUEEN: att = queen_attacks(from, occ); break;
        default: att = KING_ATTACKS[from]; break;
      }
      att &= targets;
      // Atomic: kings may never capture (the explosion would take the
      // capturing king with it).
      if (variant == VR_ATOMIC && pt == KING) att &= ~by_color[them];
      while (att) out.push(make_move(from, pop_lsb(att)));
    }
  }

  if (variant != VR_ANTICHESS && !effective_check()) gen_castling(out);

  // Crazyhouse drops.
  if (variant == VR_CRAZYHOUSE) {
    Bitboard empties = ~occ;
    for (int pt = PAWN; pt < KING; pt++) {
      if (!hand[us][pt]) continue;
      Bitboard dests = empties;
      if (pt == PAWN) dests &= ~(RANK_1_BB | rank_bb(7));
      Bitboard d = dests;
      while (d) out.push(make_drop(pop_lsb(d), PieceType(pt)));
    }
  }
}

bool Position::is_legal(Move m) const {
  // Antichess has no check rules; every generated move is legal (the
  // capture obligation is enforced in legal_moves).
  if (variant == VR_ANTICHESS) return true;
  Position copy = *this;
  copy.make(m);
  if (variant == VR_ATOMIC) {
    // Exploding your own king is illegal; exploding the enemy king wins
    // regardless of check; adjacent kings annul check entirely.
    if (!copy.pieces(stm, KING)) return false;
    if (!copy.pieces(~stm, KING)) return true;
    if (copy.kings_adjacent()) return true;
    Square k = copy.king_sq(stm);
    return !copy.attacked_by(k, copy.stm, copy.occupied());
  }
  Square k = copy.king_sq(stm);
  if (k == SQ_NONE) return variant == VR_HORDE;
  if (copy.attacked_by(k, copy.stm, copy.occupied())) return false;
  // Racing kings: delivering check is forbidden.
  if (variant == VR_RACING_KINGS && copy.in_check()) return false;
  return true;
}

void Position::legal_moves(MoveList& out) const {
  MoveList pseudo;
  gen_pseudo(pseudo);
  for (Move m : pseudo)
    if (is_legal(m)) out.push(m);
  // Antichess capture obligation: if any capture is available, only
  // captures are legal.
  if (variant == VR_ANTICHESS) {
    bool have_capture = false;
    for (int i = 0; i < out.size; i++) {
      Move m = out.moves[i];
      if (move_kind(m) == MK_EN_PASSANT || !empty(move_to(m))) {
        have_capture = true;
        break;
      }
    }
    if (have_capture) {
      int n = 0;
      for (int i = 0; i < out.size; i++) {
        Move m = out.moves[i];
        if (move_kind(m) == MK_EN_PASSANT || !empty(move_to(m)))
          out.moves[n++] = m;
      }
      out.size = n;
    }
  }
}

bool Position::ep_capture_legal() const {
  if (ep_square == SQ_NONE) return false;
  Bitboard candidates = PAWN_ATTACKS[~stm][ep_square] & pieces(stm, PAWN);
  while (candidates) {
    Square from = pop_lsb(candidates);
    Move m = make_move(from, ep_square, MK_EN_PASSANT);
    Position copy = *this;
    copy.make(m);
    Square k = copy.king_sq(stm);
    if (k == SQ_NONE || !copy.attacked_by(k, copy.stm, copy.occupied())) return true;
  }
  return false;
}

void Position::make(Move m) {
  Color us = stm;
  Color them = ~us;
  int up = us == WHITE ? 8 : -8;

  // Clear previous ep hash.
  if (ep_square != SQ_NONE) {
    hash ^= zobrist::ep_file[file_of(ep_square)];
    ep_square = SQ_NONE;
  }

  halfmove++;

  switch (move_kind(m)) {
    case MK_CASTLE: {
      Square kfrom = move_from(m), rfrom = move_to(m);
      bool kingside = rfrom > kfrom;
      Square kto = make_square(kingside ? 6 : 2, rank_of(kfrom));
      Square rto = make_square(kingside ? 5 : 3, rank_of(kfrom));
      remove_piece(kfrom);
      remove_piece(rfrom);
      put_piece(kto, make_piece(us, KING));
      put_piece(rto, make_piece(us, ROOK));
      // Drop all castling rights of us (their rooks live on our home rank).
      Bitboard stale = castling_rooks & (us == WHITE ? RANK_1_BB : rank_bb(7));
      while (stale) {
        Square s = pop_lsb(stale);
        castling_rooks &= ~bb(s);
        hash ^= zobrist::castling_rook[s];
      }
      break;
    }
    case MK_DROP: {
      Square to = move_to(m);
      PieceType pt = move_drop_piece(m);
      hash ^= zobrist::hand_piece[us][pt][hand[us][pt]];
      hand[us][pt]--;
      if (hand[us][pt]) hash ^= zobrist::hand_piece[us][pt][hand[us][pt]];
      put_piece(to, make_piece(us, pt));
      if (pt == PAWN) halfmove = 0;
      break;
    }
    default: {
      Square from = move_from(m), to = move_to(m);
      int moving = board[from];
      PieceType mpt = piece_type(moving);
      bool was_capture = move_kind(m) == MK_EN_PASSANT || !empty(to);

      if (move_kind(m) == MK_EN_PASSANT) {
        remove_piece(to - up);  // the double-pushed enemy pawn
        halfmove = 0;
        if (variant == VR_CRAZYHOUSE) {
          if (hand[us][PAWN]) hash ^= zobrist::hand_piece[us][PAWN][hand[us][PAWN]];
          hand[us][PAWN]++;
          hash ^= zobrist::hand_piece[us][PAWN][hand[us][PAWN]];
        }
      } else if (!empty(to)) {
        // Capture: clear rights if a castling rook is taken; pocket it in
        // crazyhouse (promoted pieces demote back to pawns).
        if (castling_rooks & bb(to)) {
          castling_rooks &= ~bb(to);
          hash ^= zobrist::castling_rook[to];
        }
        if (variant == VR_CRAZYHOUSE) {
          PieceType cap = piece_type(board[to]);
          if (promoted & bb(to)) {
            cap = PAWN;
            promoted &= ~bb(to);
            hash ^= zobrist::promoted_sq[to];
          }
          if (hand[us][cap]) hash ^= zobrist::hand_piece[us][cap][hand[us][cap]];
          hand[us][cap]++;
          hash ^= zobrist::hand_piece[us][cap][hand[us][cap]];
        }
        remove_piece(to);
        halfmove = 0;
      }

      remove_piece(from);
      if (move_promo(m) != NO_PIECE_TYPE)
        put_piece(to, make_piece(us, move_promo(m)));
      else
        put_piece(to, moving);

      if (variant == VR_CRAZYHOUSE) {
        // Track promoted status: it travels with the piece and is set on
        // promotion; captured promoted pieces were demoted above.
        if (promoted & bb(from)) {
          promoted &= ~bb(from);
          hash ^= zobrist::promoted_sq[from];
          promoted |= bb(to);
          hash ^= zobrist::promoted_sq[to];
        }
        if (move_promo(m) != NO_PIECE_TYPE && !(promoted & bb(to))) {
          promoted |= bb(to);
          hash ^= zobrist::promoted_sq[to];
        }
      }

      if (variant == VR_ATOMIC && was_capture) {
        // Explosion: the capturer vanishes along with every non-pawn
        // piece adjacent to the capture square.
        remove_piece(to);
        Bitboard blast = KING_ATTACKS[to] & occupied() & ~by_type[PAWN];
        while (blast) {
          Square s = pop_lsb(blast);
          if (castling_rooks & bb(s)) {
            castling_rooks &= ~bb(s);
            hash ^= zobrist::castling_rook[s];
          }
          remove_piece(s);
        }
      }

      if (mpt == PAWN) {
        halfmove = 0;
        if (to - from == 2 * up && rank_of(from) == (us == WHITE ? 1 : 6)) {
          // Tentatively set ep; keep only if a legal capture exists.
          // (Horde first-rank double pushes grant no en-passant rights.)
          ep_square = from + up;
        }
      } else if (mpt == KING) {
        Bitboard stale = castling_rooks & by_color[us] &
                         (us == WHITE ? RANK_1_BB : rank_bb(7));
        while (stale) {
          Square s = pop_lsb(stale);
          castling_rooks &= ~bb(s);
          hash ^= zobrist::castling_rook[s];
        }
      }
      if (castling_rooks & bb(from)) {
        castling_rooks &= ~bb(from);
        hash ^= zobrist::castling_rook[from];
      }
      break;
    }
  }

  if (us == BLACK) fullmove++;
  stm = them;
  hash ^= zobrist::black_to_move;

  if (ep_square != SQ_NONE) {
    if (ep_capture_legal())
      hash ^= zobrist::ep_file[file_of(ep_square)];
    else
      ep_square = SQ_NONE;
  }

  if (variant == VR_THREE_CHECK && in_check()) {
    // Zero count is the identity (compute_hash skips it).
    if (checks_given[us]) hash ^= zobrist::checks[us][checks_given[us] & 3];
    checks_given[us]++;
    hash ^= zobrist::checks[us][checks_given[us] & 3];
  }
}

void Position::make_null() {
  if (ep_square != SQ_NONE) {
    hash ^= zobrist::ep_file[file_of(ep_square)];
    ep_square = SQ_NONE;
  }
  stm = ~stm;
  hash ^= zobrist::black_to_move;
  halfmove++;
}

// ---------------------------------------------------------------------------
// UCI
// ---------------------------------------------------------------------------

static const char PROMO_CHARS[] = {'\0', 'n', 'b', 'r', 'q', 'k'};

std::string Position::uci(Move m) const {
  if (move_kind(m) == MK_DROP) {
    std::string out;
    out += "PNBRQK"[move_drop_piece(m)];
    out += '@';
    out += square_name(move_to(m));
    return out;
  }
  std::string out = square_name(move_from(m)) + square_name(move_to(m));
  if (move_promo(m) != NO_PIECE_TYPE) out += PROMO_CHARS[move_promo(m)];
  return out;
}

Move Position::parse_uci(const std::string& str) const {
  MoveList legal;
  legal_moves(legal);
  for (Move m : legal)
    if (uci(m) == str) return m;
  // Standard castling notation (e1g1 / e1c1): king moves to its castling
  // destination file instead of onto the rook.
  for (Move m : legal) {
    if (move_kind(m) != MK_CASTLE) continue;
    Square kfrom = move_from(m), rfrom = move_to(m);
    Square kto = make_square(rfrom > kfrom ? 6 : 2, rank_of(kfrom));
    if (square_name(kfrom) + square_name(kto) == str) return m;
  }
  return MOVE_NONE;
}

// ---------------------------------------------------------------------------
// Outcome
// ---------------------------------------------------------------------------

int Position::outcome() const {
  MoveList legal;
  legal_moves(legal);

  if (variant == VR_THREE_CHECK && checks_given[~stm] >= 3) return 3;
  if (variant == VR_KING_OF_THE_HILL) {
    if (pieces(~stm, KING) & CENTER4_BB) return 3;
  }
  if (variant == VR_RACING_KINGS) {
    bool they_reached = pieces(~stm, KING) & rank_bb(7);
    bool we_reached = pieces(stm, KING) & rank_bb(7);
    if (they_reached && we_reached) return 5;  // both finished: draw
    if (they_reached) {
      // White moves first, so when white finishes black gets one reply
      // to equalize; the game continues if black can still reach rank 8.
      if (stm == BLACK) {
        for (Move m : legal)
          if (piece_type(board[move_from(m)]) == KING && rank_of(move_to(m)) == 7)
            return 0;
      }
      return 3;
    }
    // We finished earlier and the opponent's equalizing reply failed.
    if (we_reached) return 4;
  }
  if (variant == VR_HORDE && !pieces(WHITE)) return stm == WHITE ? 3 : 4;
  if (variant == VR_ATOMIC) {
    if (!pieces(stm, KING)) return 3;
    if (!pieces(~stm, KING)) return 4;
  }

  if (legal.size == 0) {
    if (variant == VR_ANTICHESS) return 4;  // no moves = win in antichess
    if (effective_check()) return 1;        // checkmate
    if (variant == VR_HORDE && stm == WHITE && !pieces(WHITE)) return 3;
    return 2;  // stalemate
  }

  if (variant == VR_ANTICHESS && !pieces(stm)) return 4;

  if (halfmove >= 150) return 5;  // 75-move rule (automatic)

  // Insufficient material (standard chess only; conservative).
  if (variant == VR_STANDARD) {
    Bitboard heavy = by_type[PAWN] | by_type[ROOK] | by_type[QUEEN];
    if (!heavy) {
      int minors = popcount(by_type[KNIGHT] | by_type[BISHOP]);
      if (minors <= 1) return 5;
      if (!by_type[KNIGHT]) {
        // Bishops only: draw if all on the same color complex.
        constexpr Bitboard DARK = 0xAA55AA55AA55AA55ULL;
        Bitboard b = by_type[BISHOP];
        if (!(b & DARK) || !(b & ~DARK)) return 5;
      }
    }
  }

  return 0;
}

bool Position::variant_terminal(int& res) const {
  switch (variant) {
    case VR_THREE_CHECK:
      if (checks_given[~stm] >= 3) { res = -1; return true; }
      if (checks_given[stm] >= 3) { res = +1; return true; }
      return false;
    case VR_KING_OF_THE_HILL:
      if (pieces(~stm, KING) & CENTER4_BB) { res = -1; return true; }
      if (pieces(stm, KING) & CENTER4_BB) { res = +1; return true; }
      return false;
    case VR_ATOMIC:
      if (!pieces(stm, KING)) { res = -1; return true; }
      if (!pieces(~stm, KING)) { res = +1; return true; }
      return false;
    case VR_HORDE:
      if (!pieces(WHITE)) { res = stm == WHITE ? -1 : +1; return true; }
      return false;
    case VR_RACING_KINGS: {
      bool they = pieces(~stm, KING) & rank_bb(7);
      bool we = pieces(stm, KING) & rank_bb(7);
      if (they && we) { res = 0; return true; }
      if (they) {
        // Black's one-move equalizing chance: only terminal if the black
        // king cannot even pseudo-reach rank 8 (conservative — if it can,
        // the search resolves the reply with real moves).
        if (stm == BLACK) {
          Square k = king_sq(BLACK);
          if (k != SQ_NONE && (KING_ATTACKS[k] & rank_bb(7) & ~pieces(BLACK)))
            return false;
        }
        res = -1;
        return true;
      }
      if (we) { res = +1; return true; }
      return false;
    }
    case VR_ANTICHESS:
      if (!pieces(stm)) { res = +1; return true; }
      if (!pieces(~stm)) { res = -1; return true; }
      return false;
    default:
      return false;
  }
}

// ---------------------------------------------------------------------------
// Perft
// ---------------------------------------------------------------------------

uint64_t perft(const Position& pos, int depth) {
  if (depth <= 0) return 1;
  MoveList legal;
  pos.legal_moves(legal);
  if (depth == 1) return legal.size;
  uint64_t nodes = 0;
  for (Move m : legal) {
    Position copy = pos;
    copy.make(m);
    nodes += perft(copy, depth - 1);
  }
  return nodes;
}

}  // namespace fc
