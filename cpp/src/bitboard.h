// Attack tables. Sliding-piece attacks use PEXT-indexed lookup tables when
// compiled with BMI2 (no magic constants needed), with a portable ray-scan
// fallback otherwise.

#pragma once

#include "types.h"

namespace fc {

extern Bitboard KNIGHT_ATTACKS[64];
extern Bitboard KING_ATTACKS[64];
extern Bitboard PAWN_ATTACKS[COLOR_NB][64];
// between_incl[a][b]: squares strictly between a and b (empty if not aligned);
// line[a][b]: full line through a and b (empty if not aligned).
extern Bitboard BETWEEN[64][64];
extern Bitboard LINE[64][64];

void init_bitboards();

Bitboard rook_attacks(Square s, Bitboard occ);
Bitboard bishop_attacks(Square s, Bitboard occ);

inline Bitboard queen_attacks(Square s, Bitboard occ) {
  return rook_attacks(s, occ) | bishop_attacks(s, occ);
}

inline Bitboard pawn_pushes(Color c, Bitboard pawns, Bitboard empty) {
  return c == WHITE ? ((pawns << 8) & empty) : ((pawns >> 8) & empty);
}

}  // namespace fc
