// SearchPool: many concurrent alpha-beta searches as cooperative fibers,
// all yielding leaf evaluations into one shared microbatch.
//
// This is the TPU-shaped inversion of the reference's engine tier
// (SURVEY.md §7): instead of N independent engine processes each
// evaluating one position at a time on its own CPU core, N search fibers
// suspend at their leaves; the host collects up to `capacity` pending
// evaluations per step, ships them to the JAX/TPU evaluator in one batch,
// and resumes every fiber with its score.
//
// Driving loop (Python side, engine/tpu_engine.py):
//   submit(...) per position  ->  loop {
//     n = fc_pool_step(feats, buckets, slots)   # run fibers to their leaves
//     if n == 0 and nothing active: break
//     values = jax_evaluate(feats[:n])          # one TPU microbatch
//     fc_pool_provide(values, n)                # wake the fibers
//   }  -> fc_pool_finished() / fc_pool_result_*()
//
// THREADING MODEL: slots are partitioned into n_groups (slot id mod
// n_groups), and each group is owned by exactly one scheduler thread —
// the Python service runs one driver thread per `pipeline_depth` groups
// and any number of such threads. All per-slot and per-group state is
// only ever touched by the owning thread; the cross-thread surfaces are
// the lockless XOR-validated transposition table (search.h), the
// relaxed-atomic counters, the per-slot stop/abort latches, and the
// AIMD speculation-budget state (mutex-guarded, try-lock on the hot
// path). This is the host-parallelism answer to the reference's
// process-per-core model (src/main.rs:158-170): N scheduler threads
// each stepping thousands of fibers, all still sharing one TT so
// adjacent plies of one game share work ACROSS threads.

#include <algorithm>
#include <atomic>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "fiber.h"
#include "nnue.h"
#include "position.h"
#include "search.h"

namespace fc {

namespace {

int copy_str(const std::string& s, char* buf, int len) {
  if (!buf || len <= 0 || int(s.size()) + 1 > len) return -1;
  memcpy(buf, s.c_str(), s.size() + 1);
  return int(s.size());
}

struct Slot;

// EvalBridge that extracts features and suspends the calling fiber.
// Block requests (prefetched siblings/children) ride one suspension.
class BatchedEval : public EvalBridge {
 public:
  BatchedEval(Slot* slot, const NnueNet* net, const std::atomic<int>* budget,
              const bool* anchors, const bool* placement)
      : slot_(slot),
        net_(net),
        budget_(budget),
        anchors_(anchors),
        placement_(placement) {}
  int evaluate(const Position& pos) override;
  void evaluate_block(const Position* positions, int n, int32_t* out) override;
  bool batched() const override { return true; }
  // Live view of the pool's adaptive speculation budget.
  int prefetch_budget() const override {
    return budget_->load(std::memory_order_relaxed);
  }

 private:
  Slot* slot_;
  const NnueNet* net_;  // PSQT table for the host-side material term
  const std::atomic<int>* budget_;
  // Pool-level persistent-anchor switch (set once by the service before
  // traffic; read-only afterwards).
  const bool* anchors_;
  // Pool-level anchor-placement switch (FISHNET_NO_ANCHOR_PLACEMENT
  // disables the block-reorder policy; read-only after pool creation).
  const bool* placement_;
};

struct Slot {
  std::unique_ptr<Fiber> fiber;
  std::unique_ptr<Search> search;
  std::unique_ptr<BatchedEval> bridge;
  Position root;
  std::vector<uint64_t> history;
  SearchLimits limits;
  SearchResult result;
  // active/finished are written by the owning group's scheduler thread
  // but read cross-thread (fc_pool_active telemetry, submit routing):
  // relaxed atomics. started/wants_eval stay plain bools — owner-thread
  // only.
  std::atomic<bool> active{false};   // submitted, not yet released
  std::atomic<bool> finished{false}; // search complete, result ready
  bool started = false;    // fiber launched
  bool wants_eval = false; // suspended waiting for scores
  bool use_scalar = false; // evaluate immediately with the scalar net
  // Written by fc_pool_stop (driver thread) AND fc_pool_stop_all (any
  // thread, e.g. service close) while the search polls it per node:
  // atomic, relaxed ordering suffices (it's a latch, not a handoff).
  std::atomic<bool> stop_requested{false};
  // Hard abort (no first-iteration guarantee); see SearchLimits.
  std::atomic<bool> abort_requested{false};
  // Eval request state (valid while wants_eval): a block of 1..EVAL_BLOCK_MAX.
  // Features are stored as uint16 (delta indices reach 2*22528+1, still
  // uint16): half the memory per slot and the emission into the device
  // batch is a straight memcpy.
  int block_n = 0;
  uint16_t features[EVAL_BLOCK_MAX][2][NNUE_MAX_ACTIVE];
  int32_t buckets[EVAL_BLOCK_MAX];
  // Per-entry PSQT accumulators, all 8 buckets x both perspectives (stm
  // first), filled host-side during feature extraction: the material
  // term is a ~60-load walk over an L2-resident 720 KB table here,
  // versus a random-gather over an 11 MB padded table on the device —
  // the one NNUE term that is CHEAPER on the scalar side. The wire
  // ships only the bucket-selected material value (4 bytes/entry).
  int32_t psqt[EVAL_BLOCK_MAX][2][NNUE_PSQT_BUCKETS];
  // Bucket-selected material term per entry, ready for the wire.
  int32_t material[EVAL_BLOCK_MAX];
  // Incremental-eval reference, block-relative: -1 = standalone full
  // feature set; >= 0 is (ref_entry << 1) | persp_swap, meaning this
  // entry's features are DELTAS against that (anchor) entry's
  // accumulator, with the two perspectives swapped when the sides to
  // move differ (rebased to batch-relative indices at emission);
  // -2/-3 (PERSISTENT / PERSISTENT_SWAP) mark entry 0 as a delta
  // against the slot's DEVICE-RESIDENT anchor accumulator — the
  // accumulator this slot's previous block stored on the device
  // (emit_block maps these to the wire's table-row codes).
  int32_t parent_code[EVAL_BLOCK_MAX];
  // Device-resident anchor bookkeeping (VERDICT r4 item 1): the
  // position + host-side PSQT accumulators of the accumulator currently
  // stored in this slot's anchor-table row on the device. `pending_*`
  // snapshots entry 0 of the block built most recently — it becomes the
  // slot's anchor when (and only when) that block is actually emitted
  // (a block can wait several steps for batch capacity).
  bool anchor_valid = false;
  bool pending_anchor_valid = false;
  Position anchor_pos;
  Position pending_pos;
  int32_t anchor_psqt[2][NNUE_PSQT_BUCKETS];
  int32_t pending_psqt[2][NNUE_PSQT_BUCKETS];
  int32_t eval_values[EVAL_BLOCK_MAX];
  // Zobrist hash of the position behind each block entry, in fill
  // (wire) order — the key the host-side eval-reuse plane needs to
  // short-circuit or dedup entries before dispatch (ABI 10;
  // fc_pool_batch_hashes exports them batch-ordered).
  uint64_t pos_hash[EVAL_BLOCK_MAX];
};

namespace {

// Full feature extraction for block entry j, including the host-side
// PSQT accumulators (all 8 buckets; the emission picks the entry's own
// bucket and ships one material int32).
void fill_full(Slot* slot, const NnueNet* net, int j, const Position& pos) {
  for (int p = 0; p < 2; p++) {
    uint16_t* row = slot->features[j][p];
    int cnt = nnue_features(pos, p == 0 ? pos.stm : ~pos.stm, row);
    int32_t* ps = slot->psqt[j][p];
    for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) ps[b] = 0;
    for (int i = 0; i < cnt; i++) {
      const int32_t* prow =
          &net->ft_psqt[size_t(row[i]) * NNUE_PSQT_BUCKETS];
      for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) ps[b] += prow[b];
    }
    for (int i = cnt; i < NNUE_MAX_ACTIVE; i++)
      row[i] = uint16_t(NNUE_FEATURES);
  }
  slot->parent_code[j] = -1;
}

// Slot-level parent codes (mapped to the wire encoding at emission).
constexpr int32_t PARENT_FULL = -1;
constexpr int32_t PARENT_PERSISTENT = -2;       // delta vs device anchor row
constexpr int32_t PARENT_PERSISTENT_SWAP = -3;  // ... with perspectives swapped

// Incremental feature extraction: entry j's accumulator = ref's
// accumulator (perspectives swapped if the side to move differs) plus
// the added-piece rows minus the removed-piece rows. Wire contract
// (fishnet_tpu/nnue/spec.py DELTA_SLOTS, ops/ft_gather.py sparse mode):
// per perspective, adds in slots [0, DELTA_SLOTS) padded with the
// sentinel, removals in [DELTA_SLOTS, 2*DELTA_SLOTS) encoded as
// NNUE_DELTA_BASE + index and padded with NNUE_DELTA_BASE + sentinel
// (which decodes back to the zero row); the rest plain sentinel. Only
// valid while each perspective's king is on the same square in both
// positions — a moved king re-bases every feature of that perspective
// (HalfKA king buckets + mirroring), so such entries fall back to a
// full fill. INVARIANT TWIN: cpp/src/nnue.cpp nnue_evaluate_cached
// applies the same rules host-side for the scalar search's incremental
// accumulator — keep the two in lockstep (the parity suites catch
// drift). Typical delta: 1-3 rows per region vs ~30 for a full fill
// — a ~4x cut in row DMAs for the prefetch-block children that
// dominate batch traffic (one move touches at most 2 adds / 3 removes:
// mover or promotion to-piece, plus from-square, victim, e.p. pawn).
// ``ref_psqt`` points at the reference accumulators ([2][8], reference
// perspective order): the anchor entry's in-block psqt, or the slot's
// device-anchor copy. ``ref_entry`` >= 0 encodes an in-block reference;
// -1 encodes a delta against the slot's DEVICE-RESIDENT anchor
// (PARENT_PERSISTENT codes).
bool fill_delta(Slot* slot, const NnueNet* net, int j, const Position& ref,
                const Position& pos,
                const int32_t (*ref_psqt)[NNUE_PSQT_BUCKETS], int ref_entry) {
  constexpr int DELTA_SLOTS = NNUE_DELTA_SLOTS;
  bool swap = pos.stm != ref.stm;
  for (int p = 0; p < 2; p++) {
    Color c = p == 0 ? pos.stm : ~pos.stm;
    if (ref.king_sq(c) != pos.king_sq(c)) return false;
    Square ksq = pos.king_sq(c);
    uint16_t adds[DELTA_SLOTS], rems[DELTA_SLOTS];
    int n_add = 0, n_rem = 0;
    for (int s = 0; s < 64; s++) {
      int before = ref.piece_on(Square(s));
      int after = pos.piece_on(Square(s));
      if (before == after) continue;
      if (before != NO_PIECE) {
        if (n_rem >= DELTA_SLOTS) return false;
        rems[n_rem++] =
            uint16_t(nnue_feature_index(ksq, c, before, Square(s)));
      }
      if (after != NO_PIECE) {
        if (n_add >= DELTA_SLOTS) return false;
        adds[n_add++] = uint16_t(nnue_feature_index(ksq, c, after, Square(s)));
      }
    }
    uint16_t* row = slot->features[j][p];
    for (int i = 0; i < DELTA_SLOTS; i++)
      row[i] = i < n_add ? adds[i] : uint16_t(NNUE_FEATURES);
    for (int i = 0; i < DELTA_SLOTS; i++)
      row[DELTA_SLOTS + i] = uint16_t(
          NNUE_DELTA_BASE + (i < n_rem ? rems[i] : uint16_t(NNUE_FEATURES)));
    for (int i = 2 * DELTA_SLOTS; i < NNUE_MAX_ACTIVE; i++)
      row[i] = uint16_t(NNUE_FEATURES);
    // PSQT: parent's accumulator for the SAME COLOR (parent perspective
    // p^swap), plus the delta rows. Kings match (checked above), so the
    // child's feature indexing agrees with the parent's for this color.
    const int32_t* ref_ps = ref_psqt[swap ? p ^ 1 : p];
    int32_t* ps = slot->psqt[j][p];
    for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) ps[b] = ref_ps[b];
    for (int i = 0; i < n_add; i++) {
      const int32_t* prow = &net->ft_psqt[size_t(adds[i]) * NNUE_PSQT_BUCKETS];
      for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) ps[b] += prow[b];
    }
    for (int i = 0; i < n_rem; i++) {
      const int32_t* prow = &net->ft_psqt[size_t(rems[i]) * NNUE_PSQT_BUCKETS];
      for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) ps[b] -= prow[b];
    }
  }
  slot->parent_code[j] =
      ref_entry >= 0 ? ((ref_entry << 1) | (swap ? 1 : 0))
                     : (swap ? PARENT_PERSISTENT_SWAP : PARENT_PERSISTENT);
  return true;
}

// Exact cheap predictor of fill_delta success: both kings unmoved (a
// moved king re-bases that perspective's whole feature set) and no more
// than NNUE_DELTA_SLOTS added or removed pieces. The piece-diff counts
// are perspective-independent (fill_delta counts ALL board diffs for
// each perspective), so one scan answers for both.
bool can_delta(const Position& ref, const Position& pos) {
  if (ref.king_sq(WHITE) != pos.king_sq(WHITE) ||
      ref.king_sq(BLACK) != pos.king_sq(BLACK))
    return false;
  int n_add = 0, n_rem = 0;
  for (int s = 0; s < 64; s++) {
    int before = ref.piece_on(Square(s));
    int after = pos.piece_on(Square(s));
    if (before == after) continue;
    if (before != NO_PIECE && ++n_rem > NNUE_DELTA_SLOTS) return false;
    if (after != NO_PIECE && ++n_add > NNUE_DELTA_SLOTS) return false;
  }
  return true;
}

// ANCHOR-PLACEMENT POLICY (the wire diet): a deterministic permutation
// of one eval chunk chosen to maximize delta-encodable entries.
//
// The fill loop below encodes entry k as a delta when fill_delta against
// the running anchor succeeds; a failure makes k a full entry AND the
// new anchor. In search emission order one mid-block king-move child
// resets the anchor and cascades fulls over entries that could have
// delta'd against the previous anchor. Since fill_delta requires equal
// king squares for BOTH colors, entries sharing a (white king, black
// king) pair are laid out contiguously — groups ordered by first
// occurrence, original order preserved within a group — so each king
// pair costs at most one full fill instead of one per alternation.
//
// The persistent-anchor candidate: entry 0 may ship as a one-row delta
// against the slot's device-resident anchor, but only entry 0 may carry
// a persistent code. The old code only ever tried positions[0]; here
// the WHOLE chunk is scanned for the first entry delta-encodable
// against the device anchor, and that entry's group leads with it at
// its head — the anchor_coverage lever.
//
// Deterministic: a pure function of (positions, device_anchor), no
// randomness, no iteration-order dependence.
void plan_block_order(const Position* positions, int chunk,
                      const Position* device_anchor, int* order) {
  int group_of[EVAL_BLOCK_MAX];
  int first_of[EVAL_BLOCK_MAX];
  int n_keys = 0;
  for (int j = 0; j < chunk; j++) {
    int g = -1;
    for (int k = 0; k < n_keys; k++) {
      const Position& rep = positions[first_of[k]];
      if (rep.king_sq(WHITE) == positions[j].king_sq(WHITE) &&
          rep.king_sq(BLACK) == positions[j].king_sq(BLACK)) {
        g = k;
        break;
      }
    }
    if (g < 0) {
      g = n_keys++;
      first_of[g] = j;
    }
    group_of[j] = g;
  }
  int j0 = -1;
  if (device_anchor) {
    for (int j = 0; j < chunk; j++)
      if (can_delta(*device_anchor, positions[j])) {
        j0 = j;
        break;
      }
  }
  int lead = j0 >= 0 ? group_of[j0] : 0;  // group 0 starts at entry 0
  int w = 0;
  if (j0 >= 0) order[w++] = j0;
  for (int j = 0; j < chunk; j++)
    if (group_of[j] == lead && j != j0) order[w++] = j;
  for (int g = 0; g < n_keys; g++) {
    if (g == lead) continue;
    for (int j = 0; j < chunk; j++)
      if (group_of[j] == g) order[w++] = j;
  }
}

}  // namespace

void BatchedEval::evaluate_block(const Position* positions, int n, int32_t* out) {
  // Honor the base-class contract for any n: one suspension per chunk of
  // up to EVAL_BLOCK_MAX (search never exceeds one chunk in practice).
  for (int base = 0; base < n; base += EVAL_BLOCK_MAX) {
    int chunk = std::min(n - base, EVAL_BLOCK_MAX);
    // ANCHOR PROTOCOL (the fused TPU kernel depends on it,
    // ops/ft_gather.py): every delta entry references the MOST RECENT
    // anchor entry preceding it — so the kernel reconstructs children
    // from a single running anchor accumulator held in VMEM instead of
    // a batch-wide gather. Entry 0 is always an anchor: full, or (with
    // persistent anchors enabled) a one-row delta against the
    // accumulator this slot's PREVIOUS block stored device-side —
    // single demand evals then ship 32 bytes instead of 128. A failed
    // delta (king moved, too many diffs) becomes full and the new
    // in-block anchor.
    const Position* danchor =
        (*anchors_ && slot_->anchor_valid) ? &slot_->anchor_pos : nullptr;
    // Anchor-placement reorder (plan_block_order): fill the block in a
    // permuted order chosen to maximize delta encodings; `order[k]` is
    // the caller index filled at block entry k, and the result copy-out
    // applies the inverse map. Disabled (identity order) via
    // FISHNET_NO_ANCHOR_PLACEMENT — the pre-policy layout.
    int order[EVAL_BLOCK_MAX];
    if (*placement_ && chunk > 1) {
      plan_block_order(positions + base, chunk, danchor, order);
    } else {
      for (int j = 0; j < chunk; j++) order[j] = j;
    }
    int last_anchor = 0;
    for (int k = 0; k < chunk; k++) {
      const Position& pos = positions[base + order[k]];
      if (k == 0) {
        if (!(danchor && fill_delta(slot_, net_, 0, *danchor, pos,
                                    slot_->anchor_psqt, /*ref_entry=*/-1)))
          fill_full(slot_, net_, 0, pos);
      } else if (!fill_delta(slot_, net_, k,
                             positions[base + order[last_anchor]], pos,
                             slot_->psqt[last_anchor], last_anchor)) {
        fill_full(slot_, net_, k, pos);
        last_anchor = k;
      }
      slot_->buckets[k] = nnue_psqt_bucket(pos);
      slot_->material[k] =
          (slot_->psqt[k][0][slot_->buckets[k]] -
           slot_->psqt[k][1][slot_->buckets[k]]) / 2;
      slot_->pos_hash[k] = pos.hash;
    }
    if (*anchors_) {
      // Block entry 0 becomes the slot's device anchor once this block
      // ships (emit_block finalizes; see the Slot field comment).
      slot_->pending_anchor_valid = true;
      slot_->pending_pos = positions[base + order[0]];
      memcpy(slot_->pending_psqt, slot_->psqt[0], sizeof(slot_->pending_psqt));
    }
    slot_->block_n = chunk;
    slot_->wants_eval = true;
    slot_->fiber->yield();
    slot_->wants_eval = false;
    slot_->block_n = 0;
    // eval_values is in fill (wire) order: undo the permutation.
    for (int k = 0; k < chunk; k++)
      out[base + order[k]] = slot_->eval_values[k];
  }
}

int BatchedEval::evaluate(const Position& pos) {
  int32_t v = 0;
  evaluate_block(&pos, 1, &v);
  return v;
}

}  // namespace

struct SearchPool {
  TranspositionTable tt;
  // Shared continuation-history tables (search.h SharedHistory): like
  // the TT, one instance serves every search and scheduler thread;
  // racy heuristic updates are benign by design.
  SharedHistory shared_history;
  // Pool-level eval-traffic accounting. Written by the scheduler thread
  // only; read cross-thread by fc_pool_counters, hence relaxed atomics.
  SearchCounters counters;
  std::atomic<uint64_t> steps{0};          // device batches shipped
  std::atomic<uint64_t> evals_shipped{0};  // eval slots across all steps
  std::atomic<uint64_t> suspensions{0};    // fiber blocks (1 round-trip each)
  std::atomic<uint64_t> step_capacity{0};  // sum of capacities (occupancy denom)
  std::atomic<uint64_t> delta_evals{0};    // eval slots shipped as deltas
  std::atomic<uint64_t> anchor_evals{0};   // deltas vs device-resident anchors
  // Persistent-anchor switch: set ONCE by the service (before traffic)
  // when its evaluator understands the anchor-table wire codes; plain
  // bool because it is read-only while fibers run.
  bool anchors_enabled = false;
  // Anchor-placement reorder switch (evaluate_block plan_block_order):
  // set once at pool creation from FISHNET_NO_ANCHOR_PLACEMENT,
  // read-only afterwards.
  bool anchor_placement = true;
  // Adaptive speculation budget (max speculative evals per prefetch
  // block). Halved whenever a step overflows capacity — wasted slots
  // then displace other fibers' demand evals — and grown back while
  // batches run at most half full, where an unshipped prefetch would
  // just leave device capacity idle and cost a later round-trip.
  // Written by the scheduler thread, read by it too (via the bridge);
  // atomic only for the telemetry read.
  std::atomic<int> prefetch_budget{EVAL_BLOCK_MAX};
  // fc_pool_set_prefetch pins the budget (parity suites need identical
  // TT evolution across backends; ROI experiments need fixed points).
  // Atomic: written from caller threads while the scheduler reads it.
  std::atomic<bool> prefetch_adaptive{true};
  // ROI window state: speculation must EARN its batch slots. Every
  // ROI_WINDOW non-empty steps the windowed hit rate is checked;
  // unearned budgets halve to 0 and a periodic probe lets a workload
  // whose consumption recovered re-earn it. Measured r2/r3: with a
  // material-blind net the consumption sites (stand-pat windows,
  // delta-pruned captures) almost never fire — ROI 0.0007 — and the
  // wasted slots displaced demand evals 1:1 on a latency-priced link.
  // Guarded by roi_mu: any scheduler thread may run the update after
  // its step (try-lock — a contended update is just skipped), and
  // fc_pool_set_prefetch pins under the same lock, which is what makes
  // a pin un-clobberable by an in-flight AIMD update (the updater
  // re-checks prefetch_adaptive while holding the lock).
  std::mutex roi_mu;
  uint64_t roi_last_shipped = 0;
  uint64_t roi_last_hits = 0;
  uint64_t roi_check_step = 0;
  uint64_t roi_probe_step = 0;
  bool roi_ok = true;  // last window's verdict; gates budget growth
  std::unique_ptr<NnueNet> scalar_net;
  std::unique_ptr<ScalarEval> scalar_eval;
  // Whether the loaded net's eval tracks material (probed once at pool
  // creation): gates the SEE heuristics whose soundness depends on it.
  bool net_material_correlated = false;
  HceEval hce_eval;  // variant searches (immediate, CPU)
  std::vector<std::unique_ptr<Slot>> slots;
  // Slots are partitioned into n_groups (slot id mod n_groups) so the
  // driver can keep several device batches in flight: step/provide act
  // on one group while other groups' evals ride the wire. Each group
  // keeps its own emission record and fairness cursor.
  int n_groups = 1;
  // (slot id, index within the slot's block) per entry of the group's
  // last step() eval batch, in emission order.
  std::vector<std::vector<std::pair<int, int>>> group_batch;
  // Finished-slot queues, one per group: filled by the owning thread's
  // step(), drained by the same thread's harvest loop.
  std::vector<std::deque<int>> group_finished;
  // Round-robin scan origin per group: each step starts scanning just
  // past the last slot served, so over-capacity steps rotate service
  // instead of starving high-index slots (head-of-line fairness).
  std::vector<size_t> group_cursor;
  // Worst case per fiber.h's sizing analysis (MAX_PLY frames + qsearch
  // tail at ~2.5 KB/frame): needs the full 512 KB; pages commit lazily.
  size_t fiber_stack = 512 * 1024;

  SearchPool(int max_slots, size_t tt_bytes, int groups) : tt(tt_bytes) {
    slots.resize(max_slots);
    for (auto& s : slots) s = std::make_unique<Slot>();
    n_groups = groups < 1 ? 1 : (groups > max_slots ? max_slots : groups);
    group_batch.resize(n_groups);
    group_finished.resize(n_groups);
    group_cursor.assign(n_groups, 0);
  }
};

extern "C" {

SearchPool* fc_pool_new(int max_slots, uint64_t tt_bytes,
                        const char* scalar_net_path, int n_groups) {
  init_bitboards();
  init_zobrist();
  auto* pool = new (std::nothrow) SearchPool(
      max_slots > 0 ? max_slots : 256,
      tt_bytes ? size_t(tt_bytes) : (64ull << 20), n_groups);
  if (!pool) return nullptr;
  // Escape hatch for the block-reorder anchor-placement policy
  // (evaluate_block): restores the pre-policy search-emission layout.
  const char* no_placement = std::getenv("FISHNET_NO_ANCHOR_PLACEMENT");
  pool->anchor_placement = !(no_placement && no_placement[0] == '1');
  if (scalar_net_path && scalar_net_path[0]) {
    pool->scalar_net = std::make_unique<NnueNet>();
    if (!pool->scalar_net->load(scalar_net_path).empty()) {
      delete pool;
      return nullptr;
    }
    pool->scalar_eval = std::make_unique<ScalarEval>(pool->scalar_net.get());
    pool->net_material_correlated =
        nnue_material_correlated(*pool->scalar_net);
  }
  return pool;
}

void fc_pool_free(SearchPool* pool) { delete pool; }

// Submit a search into `group`'s slot partition (the caller must be, or
// coordinate with, that group's owning thread; pass -1 for any group —
// only safe while a single thread drives the whole pool). moves:
// space-separated UCI from the root fen (the game line, for
// history/repetitions). variant: a VariantRules value; non-standard
// variants are evaluated with the classical HCE on the host (the
// reference's MultiVariant flavor) and never suspend for the device.
// Returns the slot id, or a negative error: -1 group/pool full (retry
// after a release), -2/-3 invalid fen/variant/moves, -4 fiber stack
// exhaustion, -5 standard-variant search on a pool built without a
// scalar net (a configuration error — resubmitting cannot clear it).
// skill: engine strength −9..20; <20 enables the weakened best-move
// sampling in Search::run (play jobs; analysis always passes 20).
int fc_pool_submit(SearchPool* pool, int group, const char* fen,
                   const char* moves, uint64_t nodes, int depth, int multipv,
                   int skill, int use_scalar, int variant) {
  if (group >= pool->n_groups) return -1;
  int id = -1;
  for (size_t i = group < 0 ? 0 : size_t(group); i < pool->slots.size();
       i += group < 0 ? 1 : size_t(pool->n_groups))
    if (!pool->slots[i]->active) {
      id = int(i);
      break;
    }
  if (id < 0) return -1;
  Slot& slot = *pool->slots[id];

  if (variant < VR_STANDARD || variant > VR_THREE_CHECK) return -2;
  // A standard-variant search needs the scalar net: the batched bridge
  // walks net->ft_psqt host-side (fill_full/fill_delta material term)
  // and the scalar backend IS the net — and a use_scalar request with
  // no net would silently fall back to that same bridge. Refuse the
  // submit instead of crashing later; a netless pool (fc_pool_new
  // allows one) still serves variant/HCE searches.
  if (variant == VR_STANDARD && !pool->scalar_net) return -5;
  Position pos;
  if (!pos.set_fen(fen ? fen : "", VariantRules(variant)).empty()) return -2;
  slot.history.clear();
  slot.history.push_back(pos.hash);
  if (moves && moves[0]) {
    std::string all(moves);
    size_t start = 0;
    while (start < all.size()) {
      size_t end = all.find(' ', start);
      if (end == std::string::npos) end = all.size();
      std::string uci = all.substr(start, end - start);
      start = end + 1;
      if (uci.empty()) continue;
      Move m = pos.parse_uci(uci);
      if (m == MOVE_NONE) return -3;
      pos.make(m);
      slot.history.push_back(pos.hash);
    }
  }

  slot.root = pos;
  slot.limits.nodes = nodes;
  slot.limits.depth = depth;
  slot.limits.multipv = multipv;
  slot.limits.skill = std::max(-9, std::min(20, skill));
  slot.stop_requested = false;
  slot.abort_requested = false;
  slot.limits.stop = &slot.stop_requested;
  slot.limits.abort_now = &slot.abort_requested;
  slot.use_scalar = use_scalar != 0 && pool->scalar_eval != nullptr;
  slot.active = true;
  slot.started = false;
  slot.finished = false;
  slot.wants_eval = false;
  slot.result = SearchResult();
  if (!slot.fiber) slot.fiber = std::make_unique<Fiber>(pool->fiber_stack);
  if (!slot.fiber->valid()) {
    // Stack mmap failed (memory pressure / map-count exhaustion): refuse
    // the slot instead of crashing in makecontext later.
    slot.fiber.reset();
    slot.active = false;
    return -4;
  }
  // A fresh search must not diff against a previous occupant's anchor.
  slot.anchor_valid = false;
  slot.pending_anchor_valid = false;
  if (!slot.bridge)
    slot.bridge = std::make_unique<BatchedEval>(
        &slot, pool->scalar_net.get(), &pool->prefetch_budget,
        &pool->anchors_enabled, &pool->anchor_placement);
  return id;
}

// Enable persistent device-resident anchors: entry 0 of every eval
// block may ship as a one-row delta against the accumulator the slot's
// previous block stored in its anchor-table row (wire parent codes
// <= -2; see emit_block). Only call when the evaluator implements the
// anchor table (jax_eval.evaluate_packed_anchored) and BEFORE any
// submissions. With anchors on, every step's batch must be provided IN
// FULL (fc_pool_provide n == the step's return): a partial provide
// re-emits a block whose entry-0 delta references an anchor row the
// first emission already overwrote. The one caller (search service)
// always provides in full.
void fc_pool_set_anchors(SearchPool* pool, int enable) {
  pool->anchors_enabled = enable != 0;
}

// Pin (adaptive=0) or re-seed (adaptive=1) the speculation budget.
// Pinned budgets make TT evolution a deterministic function of the
// submission sequence — required by the cross-backend parity suites —
// and give ROI experiments fixed operating points.
void fc_pool_set_prefetch(SearchPool* pool, int budget, int adaptive) {
  if (budget < 0) budget = 0;
  if (budget > EVAL_BLOCK_MAX) budget = EVAL_BLOCK_MAX;
  // Under roi_mu: an in-flight AIMD update (which holds the lock and
  // re-checks prefetch_adaptive inside it) can neither clobber the pin
  // nor interleave half of one.
  std::lock_guard<std::mutex> lk(pool->roi_mu);
  pool->prefetch_adaptive.store(adaptive != 0, std::memory_order_relaxed);
  pool->prefetch_budget.store(budget, std::memory_order_relaxed);
}

void fc_pool_stop(SearchPool* pool, int slot_id) {
  if (slot_id >= 0 && slot_id < int(pool->slots.size()))
    pool->slots[slot_id]->stop_requested = true;
}

// Stop every active search. Unlike fc_pool_stop (driver-thread only,
// slot-id addressed), this is safe to call from ANY thread while the
// driver is blocked inside fc_pool_step: each search polls its
// stop_requested flag per node, so a long-running scalar search unwinds
// promptly. Used by service shutdown.
// Mass stops/aborts invalidate the speculation-ROI window: the drain
// ships prefetches for fibers that are about to die and can never
// consume them, so the next verdict would judge the POLICY on teardown
// traffic and zero the budget for minutes into the following load
// (measured: a post-drain window ran at budget 0 start to finish).
// Restart the window at the current counters and forgive the verdict.
static void reset_roi_window(SearchPool* pool) {
  std::lock_guard<std::mutex> lk(pool->roi_mu);
  pool->roi_last_shipped =
      pool->counters.prefetch_shipped.load(std::memory_order_relaxed);
  pool->roi_last_hits =
      pool->counters.prefetch_hits.load(std::memory_order_relaxed);
  pool->roi_check_step = pool->steps.load(std::memory_order_relaxed);
  pool->roi_ok = true;
}

void fc_pool_stop_all(SearchPool* pool) {
  for (auto& slot : pool->slots) slot->stop_requested = true;
  reset_roi_window(pool);
}

// Hard-abort every active search: unwind at the next node without the
// first-iteration guarantee (results may be empty). For teardown paths
// where wall clock matters more than partial results — on a ~150 ms
// round-trip link a graceful drain of thousands of young fibers costs
// minutes; this costs one step. Safe from any thread.
void fc_pool_abort_all(SearchPool* pool) {
  for (auto& slot : pool->slots) slot->abort_requested = true;
  reset_roi_window(pool);
}

// Run all runnable fibers until each is blocked on an eval or finished.
// Writes up to `capacity` pending eval requests (features [i][2][32],
// bucket [i], slot id [i]) and returns the count. Returns 0 when no
// fiber is waiting for evals (check fc_pool_finished for results).
namespace {

// Append slot i's whole eval block to the group's outgoing batch if it
// fits. COMPACT WIRE FORMAT (VERDICT r3 item 4): features go out as a
// packed stream of uint16 [2][8] rows plus one int32 row-offset per
// entry — a full entry owns 4 consecutive rows (its 32 slots per
// perspective, 8 at a time), an incremental (delta) entry owns ONE row
// (its 2*NNUE_DELTA_SLOTS live slots; the other 24 are sentinel by
// contract and are reconstructed device-side). Deltas ship 32 bytes
// instead of 128 — the wire cost that made speculation net-negative on
// payload-priced links is quartered exactly where speculation grows
// the batch.
// Result of trying to place one slot's eval block into the batch.
enum EmitResult {
  EMIT_OK = 0,        // emitted
  EMIT_FULL = 1,      // batch out of capacity: genuine pressure signal
  EMIT_MISALIGNED = 2 // block would straddle a shard boundary; NOT
                      // pressure — the AIMD budget must not react, or
                      // routine straddles would pin speculation at 0
};

EmitResult emit_block(SearchPool* pool,
                      std::vector<std::pair<int, int>>& batch,
                      int i, uint16_t* out_packed, int32_t* out_offsets,
                      int32_t* out_buckets,
                      int32_t* out_slots, int32_t* out_parent,
                      int32_t* out_material, int capacity, int align,
                      int& row_cursor) {
  Slot& slot = *pool->slots[i];
  int base = int(batch.size());
  // (In-step dedup used to alias identical single requests here; it was
  // DELETED per VERDICT r4 item 8 — measured 0.05-0.3% of evals on
  // production-shaped adjacent-ply workloads, while its hash-map build
  // sat on the hot per-step host path. The TT already dedups across
  // steps: the first eval lands there at provide time.)
  if (base + slot.block_n > capacity) return EMIT_FULL;  // next step
  // Shard alignment (sharded serving): a block must not straddle an
  // `align`-entry boundary, so every delta entry and its anchor land in
  // the same mesh shard and the sharded eval needs NO cross-device
  // gather (parallel/mesh.py ShardedEvaluator runs shard_map with
  // shard-local parent codes). Smaller blocks from other fibers can
  // still fill the gap this block skipped.
  if (align > 0 && slot.block_n > 1 &&
      base / align != (base + slot.block_n - 1) / align)
    return EMIT_MISALIGNED;
  // One fiber block served by this device round-trip.
  pool->suspensions.fetch_add(1, std::memory_order_relaxed);
  constexpr int ROW = 8;                        // slots per packed row
  constexpr int FULL_ROWS = NNUE_MAX_ACTIVE / ROW;
  for (int j = 0; j < slot.block_n; j++) {
    int idx = base + j;
    int32_t code = slot.parent_code[j];
    out_offsets[idx] = row_cursor;
    // Persistent-delta entries (code <= PARENT_PERSISTENT) ship one
    // row exactly like in-block deltas.
    if (code >= 0 || code <= PARENT_PERSISTENT) {
      // Delta entry: one packed row carries its 2*NNUE_DELTA_SLOTS live
      // slots per perspective (= ROW with the spec's DELTA_SLOTS of 4).
      for (int p = 0; p < 2; p++)
        memcpy(out_packed + (size_t(row_cursor) * 2 + p) * ROW,
               &slot.features[j][p][0], sizeof(uint16_t) * ROW);
      row_cursor += 1;
    } else {
      for (int r = 0; r < FULL_ROWS; r++)
        for (int p = 0; p < 2; p++)
          memcpy(out_packed + (size_t(row_cursor + r) * 2 + p) * ROW,
                 &slot.features[j][p][r * ROW], sizeof(uint16_t) * ROW);
      row_cursor += FULL_ROWS;
    }
    out_buckets[idx] = slot.buckets[j];
    out_slots[idx] = i;
    // ABI 9: the material column is optional — callers running the
    // device-resident PSQT path (fused kernel / XLA twin plus the
    // anchor-PSQT table) pass nullptr and the wire drops 4 bytes/entry.
    // The host-side walk still runs (slot.material feeds the stale-
    // batch repair and the CPU/XLA fallback wire).
    if (out_material) out_material[idx] = slot.material[j];
    // WIRE parent encoding: -1 plain full; >= 0 in-batch delta
    // (ref << 1 | swap, rebased from block entries to batch positions —
    // the whole block ships in this batch, so the reference resolves
    // within the same device call; blocks are emitted contiguously, so
    // the anchor protocol's "most recent preceding anchor entry"
    // invariant carries over to batch indices unchanged); <= -2 anchor-
    // entry codes: -(2 + v) with v = (table_row << 2) | (is_delta << 1)
    // | swap — the entry resolves against (is_delta) or refreshes
    // (always) the slot's device-resident anchor-table row.
    if (code >= 0) {
      out_parent[idx] = int32_t(((base + (code >> 1)) << 1) | (code & 1));
      pool->delta_evals.fetch_add(1, std::memory_order_relaxed);
    } else if (j == 0 && slot.pending_anchor_valid) {
      int32_t aid = i / pool->n_groups;  // slot's row in its group's table
      int32_t v;
      if (code <= PARENT_PERSISTENT) {
        v = (aid << 2) | 2 | (code == PARENT_PERSISTENT_SWAP ? 1 : 0);
        pool->delta_evals.fetch_add(1, std::memory_order_relaxed);
        pool->anchor_evals.fetch_add(1, std::memory_order_relaxed);
      } else {
        v = aid << 2;  // full entry that (re)seeds the anchor row
      }
      out_parent[idx] = -(2 + v);
    } else {
      out_parent[idx] = -1;
    }
    batch.emplace_back(i, j);
  }
  // The block is on the wire: entry 0's accumulator is (about to be)
  // the slot's device-side anchor.
  if (slot.pending_anchor_valid) {
    slot.anchor_pos = slot.pending_pos;
    memcpy(slot.anchor_psqt, slot.pending_psqt, sizeof(slot.anchor_psqt));
    slot.anchor_valid = true;
    slot.pending_anchor_valid = false;
  }
  return EMIT_OK;
}

}  // namespace

// `align` > 0 keeps every emitted block inside one align-entry span of
// the batch (sharded serving passes the mesh shard size; 0 disables).
// Callers must keep align >= EVAL_BLOCK_MAX or a maximal block could
// never be placed.
//
// out_packed must hold 4*capacity rows of uint16[2][8] (worst case:
// all entries full); out_offsets/out_buckets/out_slots/out_parent/
// out_material hold `capacity` int32 each. out_material may be nullptr
// (ABI 9): the material column is then skipped — for evaluators that
// resolve PSQT entirely on device (fused kernel + anchor-PSQT table).
// *out_rows receives the number of packed rows written.
int fc_pool_step(SearchPool* pool, int group, uint16_t* out_packed,
                 int32_t* out_offsets, int32_t* out_buckets,
                 int32_t* out_slots, int32_t* out_parent,
                 int32_t* out_material, int capacity, int align,
                 int32_t* out_rows) {
  if (group < 0 || group >= pool->n_groups) group = 0;
  auto& batch = pool->group_batch[group];
  // Defensive repair for the step-without-provide contract breach: a
  // stale batch here means the previous step's values never arrived, so
  // its blocks re-emit below (phase 1). With anchors enabled, an
  // entry-0 persistent delta would then resolve against the anchor row
  // its FIRST emission already refreshed — i.e. against itself. Rebuild
  // such entries as full fills (anchor_pos holds entry 0's own
  // position, committed at emission) and invalidate the slot's device
  // anchor so later blocks reseed instead of diffing against a row
  // whose content is now unknown.
  if (pool->anchors_enabled && !batch.empty() && pool->scalar_net) {
    for (auto [sid, bidx] : batch) {
      if (bidx != 0) continue;
      Slot& slot = *pool->slots[sid];
      if (!slot.wants_eval) continue;
      if (slot.parent_code[0] <= PARENT_PERSISTENT) {
        fill_full(&slot, pool->scalar_net.get(), 0, slot.anchor_pos);
        slot.material[0] =
            (slot.psqt[0][0][slot.buckets[0]] -
             slot.psqt[0][1][slot.buckets[0]]) / 2;
      }
      slot.anchor_valid = false;
      slot.pending_anchor_valid = false;
    }
  }
  batch.clear();
  const size_t n_slots = pool->slots.size();
  const int n_groups = pool->n_groups;
  size_t cursor = pool->group_cursor[group];
  bool overflow = false;
  int row_cursor = 0;

  // Phase 1: fibers still suspended from a previous over-capacity step
  // have waited longest — serve them before any freshly-produced blocks
  // can refill the batch.
  for (size_t k = 0; k < n_slots; k++) {
    size_t i = (cursor + k) % n_slots;
    if (int(i) % n_groups != group) continue;
    Slot& slot = *pool->slots[i];
    if (!slot.active || slot.finished || !slot.wants_eval) continue;
    if (emit_block(pool, batch, int(i), out_packed,
                   out_offsets, out_buckets, out_slots, out_parent,
                   out_material, capacity, align, row_cursor) == EMIT_FULL)
      overflow = true;
  }

  // Phase 2: run every runnable fiber to its next leaf; emit the blocks
  // they produce as long as they fit. (Slots emitted in phase 1 still
  // have wants_eval set and are skipped here.)
  for (size_t k = 0; k < n_slots; k++) {
    size_t i = (cursor + k) % n_slots;
    if (int(i) % n_groups != group) continue;
    Slot& slot = *pool->slots[i];
    if (!slot.active || slot.finished || slot.wants_eval) continue;

    if (!slot.started) {
      if (int(batch.size()) >= capacity) continue;  // defer launch
      slot.started = true;
      Slot* sp = &slot;
      SearchPool* pp = pool;
      EvalBridge* eval =
          slot.root.variant != VR_STANDARD
              ? static_cast<EvalBridge*>(&pp->hce_eval)
          : slot.use_scalar
              ? static_cast<EvalBridge*>(pp->scalar_eval.get())
              : static_cast<EvalBridge*>(slot.bridge.get());
      // HCE is material by construction; NNUE searches get the full SEE
      // policy only when the loaded net's eval was probed to track
      // material (random test nets must not be pruned by material logic).
      bool see_full = slot.root.variant != VR_STANDARD
                          ? true
                          : pp->net_material_correlated;
      slot.search = std::make_unique<Search>(
          &pp->tt, eval, &pp->counters, see_full, &pp->shared_history);
      slot.fiber->start([sp] {
        sp->result = sp->search->run(sp->root, sp->history, sp->limits);
      });
    } else {
      slot.fiber->resume();
    }

    if (slot.fiber->done()) {
      slot.finished = true;
      pool->group_finished[group].push_back(int(i));
    } else if (slot.wants_eval) {
      // Blocks that don't fit stay suspended; phase 1 of the next step
      // picks them up first.
      if (emit_block(pool, batch, int(i), out_packed,
                     out_offsets, out_buckets, out_slots, out_parent,
                     out_material, capacity, align, row_cursor) == EMIT_FULL)
        overflow = true;
    }
  }

  // Rotate: next step starts scanning just past the last slot served.
  if (!batch.empty())
    pool->group_cursor[group] = (size_t(batch.back().first) + 1) % n_slots;

  if (!batch.empty()) {
    // Only non-empty steps ship a device batch; idle polls don't count
    // against occupancy.
    pool->steps.fetch_add(1, std::memory_order_relaxed);
    pool->step_capacity.fetch_add(uint64_t(capacity), std::memory_order_relaxed);
    pool->evals_shipped.fetch_add(batch.size(), std::memory_order_relaxed);
    // Adapt the speculation budget to batch pressure (see the field's
    // comment): multiplicative decrease on overflow, slow additive
    // growth while there is slack. The floor is 0, not 1: when
    // speculation is not earning (VERDICT r2: ROI 0.0008 before the
    // store_eval fix), the policy must be able to turn it off outright.
    if (pool->prefetch_adaptive.load(std::memory_order_relaxed)) {
      // Try-lock: budget adaptation is advisory — if another scheduler
      // thread is mid-update, skip this step's contribution. The
      // re-check of prefetch_adaptive UNDER the lock is what makes a
      // concurrent fc_pool_set_prefetch pin un-clobberable (the pin
      // writer holds the same lock; VERDICT r3 ADVICE: the old CAS let
      // a same-value pin be overwritten by an AIMD result).
      // ROI gate, judged on a step window: speculative slots that are
      // not being consumed (hits/shipped below threshold) displace
      // other fibers' demand evals for nothing — the verdict gates
      // growth and decays the budget all the way to 0. A zero budget
      // ships no speculation, so ROI could never recover by itself:
      // probe with a tiny budget every ROI_PROBE steps and let the next
      // window's verdict re-zero or re-grow it. Measured r2/r3: with a
      // material-blind net the consumption sites (stand-pat alpha
      // windows, delta-pruned captures) almost never fire — ROI 0.0007
      // while ~45% of shipped slots were speculative waste.
      std::unique_lock<std::mutex> lk(pool->roi_mu, std::try_to_lock);
      if (lk.owns_lock() &&
          pool->prefetch_adaptive.load(std::memory_order_relaxed)) {
        // ROI_PROBE at 512 steps was ~4 minutes of wall clock at the
        // tunnel's ~2 steps/s — a zeroed budget could not recover
        // within a bench window. 128 keeps probe overhead negligible
        // (2 slots per 128 steps) while bounding budget-0 stretches to
        // ~1 minute.
        constexpr uint64_t ROI_WINDOW = 32, ROI_PROBE = 128;
        constexpr uint64_t ROI_MIN_SAMPLE = 2048;
        uint64_t step_now = pool->steps.load(std::memory_order_relaxed);
        if (step_now - pool->roi_check_step >= ROI_WINDOW) {
          uint64_t shipped =
              pool->counters.prefetch_shipped.load(std::memory_order_relaxed);
          uint64_t hits =
              pool->counters.prefetch_hits.load(std::memory_order_relaxed);
          uint64_t sd = shipped - pool->roi_last_shipped;
          if (sd >= ROI_MIN_SAMPLE) {
            pool->roi_ok =
                double(hits - pool->roi_last_hits) >= 0.05 * double(sd);
            pool->roi_last_shipped = shipped;
            pool->roi_last_hits = hits;
            pool->roi_check_step = step_now;
          }
        }
        int budget = pool->prefetch_budget.load(std::memory_order_relaxed);
        int next = budget;
        if (!pool->roi_ok) {
          // Not earning: collapse fast (the periodic probe re-earns).
          next = budget / 2;
        } else if (overflow) {
          // Capacity pressure with GOOD ROI: back off gently — the
          // compact wire prices speculative delta slots at a quarter of
          // a full entry, so the equilibrium should sit near capacity
          // rather than sawtooth far below it (measured r4: /2 decay
          // pinned the budget at 5-7 against a 40-slot ceiling).
          next = std::max(0, budget - 1 - budget / 8);
        } else if (int(batch.size()) + EVAL_BLOCK_MAX <= capacity &&
                   budget < EVAL_BLOCK_MAX) {
          // Growth keys on BUCKET HEADROOM (another maximal block would
          // have fit this step) + the ROI verdict above — NOT on the
          // batch running under half capacity, which never held at the
          // 0.80-occupancy equilibrium the e2e workload settles into
          // (VERDICT r3 weak #3: ROI 0.41 yet the budget sat at 1,
          // starving speculation of ~3.3k free slots per 16k bucket;
          // "earns but isn't allowed to spend").
          next = budget + 1;
        }
        if (budget == 0 && next == 0 &&
            step_now - pool->roi_probe_step >= ROI_PROBE) {
          next = 2;
          pool->roi_ok = true;  // let the probe ship and be judged
          pool->roi_probe_step = step_now;
          // Restart the window so the probe's own shipments are judged.
          pool->roi_last_shipped =
              pool->counters.prefetch_shipped.load(std::memory_order_relaxed);
          pool->roi_last_hits =
              pool->counters.prefetch_hits.load(std::memory_order_relaxed);
          pool->roi_check_step = step_now;
        }
        if (next != budget)
          pool->prefetch_budget.store(next, std::memory_order_relaxed);
      }
    }
  }
  if (out_rows) *out_rows = row_cursor;
  return int(batch.size());
}

// Cumulative eval-traffic counters, for bench/telemetry:
// [0] steps (device batches shipped)   [1] eval slots shipped
// [2] fiber suspensions served         [3] sum of step capacities
// [4] demand evals                     [5] prefetched (speculative) evals
// [6] prefetch hits                    [7] TT static-eval hits
// [8] current prefetch budget (adaptive; instantaneous, not cumulative)
// [9] eval slots shipped as incremental deltas (DMA-savings coverage)
// [10] RETIRED (was in-step dedup; always 0 — the alias machinery was
//      deleted after measuring 0.05-0.3% on adjacent-ply workloads)
// [11] search nodes visited, LIVE (bumped per node, not at finish) —
//      lets telemetry compute steady-state nps over a time window
//      without waiting for searches to complete
// [12] eval slots shipped as deltas against DEVICE-RESIDENT anchors
//      (subset of [9]; the persistent-anchor coverage metric)
int fc_pool_counters(SearchPool* pool, uint64_t* out, int n) {
  constexpr auto R = std::memory_order_relaxed;
  const uint64_t vals[13] = {
      pool->steps.load(R),          pool->evals_shipped.load(R),
      pool->suspensions.load(R),    pool->step_capacity.load(R),
      pool->counters.demand_evals.load(R),
      pool->counters.prefetch_shipped.load(R),
      pool->counters.prefetch_hits.load(R),
      pool->counters.tt_eval_hits.load(R),
      uint64_t(pool->prefetch_budget.load(R)),
      pool->delta_evals.load(R),
      0,  // retired dedup slot
      pool->counters.nodes.load(R),
      pool->anchor_evals.load(R),
  };
  int k = n < 13 ? n : 13;
  for (int i = 0; i < k; i++) out[i] = vals[i];
  return k;
}

// Provide centipawn scores for the group's last step() batch, in order.
// A fiber resumes (on the group's next fc_pool_step) once its whole
// block has values; the service always provides all n requested.
//
// Returns the number of entries consumed, or -1 on a FULL-PROVIDE
// contract violation: with persistent anchors enabled (fc_pool_set_
// anchors), a provide with n != the step's batch size is REFUSED and
// consumes nothing — a partial provide would re-emit blocks whose
// entry-0 persistent delta references an anchor-table row the first
// emission already overwrote, silently corrupting device anchor state
// (ADVICE r5 #1). The caller may retry with the full batch; the batch
// mapping is left intact. Without anchors the legacy lenient behavior
// is kept (clamp to the batch, consume, clear).
int fc_pool_provide(SearchPool* pool, int group, const int32_t* values, int n) {
  if (group < 0 || group >= pool->n_groups) group = 0;
  auto& batch = pool->group_batch[group];
  if (pool->anchors_enabled && n != int(batch.size())) return -1;
  int consumed = n < int(batch.size()) ? n : int(batch.size());
  for (int i = 0; i < consumed; i++) {
    auto [sid, bidx] = batch[i];
    Slot& slot = *pool->slots[sid];
    slot.eval_values[bidx] = values[i];
    if (bidx == slot.block_n - 1) slot.wants_eval = false;  // runnable again
  }
  batch.clear();
  return consumed;
}

// Number of slots still working (active and not finished) in `group`,
// or pool-wide with group < 0. Cross-thread safe (relaxed-atomic slot
// flags); the count is a momentary snapshot.
int fc_pool_active(SearchPool* pool, int group) {
  int n = 0;
  for (size_t i = 0; i < pool->slots.size(); i++) {
    if (group >= 0 && int(i) % pool->n_groups != group) continue;
    Slot& s = *pool->slots[i];
    if (s.active && !s.finished) n++;
  }
  return n;
}

// Drain one finished slot id from `group`'s queue, or -1. Owner-thread
// only (like step/provide for the same group).
int fc_pool_next_finished(SearchPool* pool, int group) {
  if (group < 0 || group >= pool->n_groups) group = 0;
  auto& q = pool->group_finished[group];
  if (q.empty()) return -1;
  int id = q.front();
  q.pop_front();
  return id;
}

int fc_pool_result_summary(SearchPool* pool, int slot_id, uint64_t* nodes,
                           int32_t* depth, char* bestmove, int bmlen,
                           int32_t* nlines) {
  if (slot_id < 0 || slot_id >= int(pool->slots.size())) return -1;
  Slot& slot = *pool->slots[slot_id];
  if (!slot.finished) return -1;
  *nodes = slot.result.nodes;
  *depth = slot.result.depth;
  *nlines = int32_t(slot.result.lines.size());
  std::string bm = slot.result.best_move == MOVE_NONE
                       ? ""
                       : slot.root.uci(slot.result.best_move);
  return copy_str(bm, bestmove, bmlen);
}

int fc_pool_result_line(SearchPool* pool, int slot_id, int line_idx,
                        int32_t* multipv, int32_t* depth, int32_t* is_mate,
                        int32_t* value, char* pv, int pvlen) {
  if (slot_id < 0 || slot_id >= int(pool->slots.size())) return -1;
  Slot& slot = *pool->slots[slot_id];
  if (!slot.finished || line_idx < 0 || line_idx >= int(slot.result.lines.size()))
    return -1;
  const PvLine& line = slot.result.lines[line_idx];
  *multipv = line.multipv;
  *depth = line.depth;
  *is_mate = line.mate ? 1 : 0;
  *value = line.value;
  // Render the PV by replaying from the root (castling notation etc.).
  std::string out;
  Position pos = slot.root;
  for (Move m : line.pv) {
    if (!out.empty()) out += ' ';
    out += pos.uci(m);
    pos.make(m);
  }
  return copy_str(out, pv, pvlen);
}

// Export the Zobrist hashes of `group`'s current pending batch, batch
// order (ABI 10). Owner-thread only (same discipline as step/provide).
// Writes min(batch, cap) hashes into `out`, returns the batch size so
// a too-small buffer is detectable.
int fc_pool_batch_hashes(SearchPool* pool, int group, uint64_t* out, int cap) {
  if (group < 0 || group >= pool->n_groups) group = 0;
  auto& batch = pool->group_batch[group];
  int n = int(batch.size()) < cap ? int(batch.size()) : cap;
  for (int i = 0; i < n; i++) {
    auto [sid, bidx] = batch[i];
    out[i] = pool->slots[sid]->pos_hash[bidx];
  }
  return int(batch.size());
}

// Invalidate the device-resident anchors of every slot whose block sits
// in `group`'s pending batch (ABI 10). Required before providing values
// for a batch the caller decided NOT to ship to the device: emit_block
// already committed entry 0 as the slot's anchor, but the device
// anchor-table row was never (re)written, so later blocks must reseed
// with a full entry instead of delta-ing against a stale row. Owner-
// thread only. Returns the number of slots invalidated.
int fc_pool_cancel_anchors(SearchPool* pool, int group) {
  if (group < 0 || group >= pool->n_groups) group = 0;
  int n = 0;
  for (auto& [sid, bidx] : pool->group_batch[group]) {
    if (bidx != 0) continue;
    Slot& slot = *pool->slots[sid];
    if (slot.anchor_valid) n++;
    slot.anchor_valid = false;
    slot.pending_anchor_valid = false;
  }
  return n;
}

// Provide-time TT fill (ABI 10): land an externally-known static eval
// (e.g. the process-wide Python EvalCache) in the pool's own TT so the
// next search touching `key` takes the tt_eval_hits fast path and never
// requests the eval at all. The lockless xor-validated TT is safe to
// call from any thread; store_eval never evicts entries carrying
// bounds/evals for other keys.
void fc_pool_tt_fill(SearchPool* pool, uint64_t key, int32_t eval) {
  pool->tt.store_eval(key, int(eval));
}

// Bound-record TT fill (ABI 11): land a full search fact — value (in
// stored/value_to_tt form), static eval, depth, bound type and best
// move — in the pool's TT so the next search touching `key` gets a
// cutoff or move-ordering hint, not just a cheap eval. `move_bits` is
// the 21-bit packed move (0x1FFFFF = none); a move from a foreign
// position is safe — search only ever COMPARES tt moves against
// generated legal moves, never plays them blindly. Lockless
// xor-validated TT: any-thread safe.
void fc_pool_tt_fill_bound(SearchPool* pool, uint64_t key, int32_t value,
                           int32_t eval, int32_t depth, int32_t bound,
                           uint32_t move_bits) {
  if (bound <= TT_NONE || bound > TT_EXACT) return;
  Move m = move_bits >= 0x1FFFFF ? MOVE_NONE : Move(move_bits);
  pool->tt.store(key, m, int(value), int(eval), int(depth), TTBound(bound));
}

// Bound-record TT export (ABI 11): probe `n` keys against the pool's
// TT and write out the bound-carrying entries so the host can promote
// the pool's private search facts into the process/fleet bounds tier.
// Rows that miss (or carry no bound) get out_bounds[i] = 0 and the
// other columns untouched. Values are exported in stored
// (value_to_tt) form and round-trip verbatim through
// fc_pool_tt_fill_bound. Returns the hit count. Lockless TT:
// any-thread safe.
int fc_pool_tt_export(SearchPool* pool, const uint64_t* keys, int n,
                      int32_t* out_values, int32_t* out_evals,
                      int32_t* out_depths, int32_t* out_bounds,
                      uint32_t* out_moves) {
  int hits = 0;
  for (int i = 0; i < n; i++) {
    out_bounds[i] = 0;
    TTData tte;
    if (!pool->tt.probe(keys[i], tte)) continue;
    if (tte.bound == TT_NONE) continue;
    out_values[i] = tte.value;
    out_evals[i] = tte.eval;
    out_depths[i] = tte.depth;
    out_bounds[i] = int32_t(tte.bound);
    out_moves[i] =
        tte.move == MOVE_NONE ? 0x1FFFFF : uint32_t(tte.move) & 0x1FFFFF;
    hits++;
  }
  return hits;
}

void fc_pool_release(SearchPool* pool, int slot_id) {
  if (slot_id >= 0 && slot_id < int(pool->slots.size())) {
    Slot& slot = *pool->slots[slot_id];
    slot.active = false;
    slot.finished = false;
    slot.result = SearchResult();
  }
}

}  // extern "C"
}  // namespace fc
