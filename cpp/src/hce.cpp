#include "hce.h"

namespace fc {
namespace {

// Material in centipawns. King value only matters for variants where it
// is a normal piece (antichess) — elsewhere kings always balance out.
constexpr int MATERIAL[PIECE_TYPE_NB] = {100, 320, 330, 500, 900, 0};

// Piece-square tables, white's perspective, a1 = index 0. Compact
// midgame-flavor tables: center control for minors/pawns, king shelter,
// seventh-rank rooks. Values are small so material dominates.
constexpr int8_t PST[PIECE_TYPE_NB][64] = {
    // pawn
    {0,  0,  0,  0,  0,  0,  0,  0,   5, 10, 10, -20, -20, 10, 10, 5,
     5, -5, -10, 0,  0, -10, -5, 5,   0,  0,  0,  20,  20,  0,  0, 0,
     5,  5, 10, 25, 25, 10,  5,  5,  10, 10, 20,  30,  30, 20, 10, 10,
     50, 50, 50, 50, 50, 50, 50, 50,  0,  0,  0,  0,   0,  0,  0,  0},
    // knight
    {-50, -40, -30, -30, -30, -30, -40, -50,  -40, -20, 0,  5,  5,  0, -20, -40,
     -30, 5,   10,  15,  15,  10,  5,   -30,  -30, 0,  15, 20, 20, 15, 0,   -30,
     -30, 5,   15,  20,  20,  15,  5,   -30,  -30, 0,  10, 15, 15, 10, 0,   -30,
     -40, -20, 0,   0,   0,   0,   -20, -40,  -50, -40, -30, -30, -30, -30, -40, -50},
    // bishop
    {-20, -10, -10, -10, -10, -10, -10, -20,  -10, 5,  0,  0,  0,  0,  5,  -10,
     -10, 10,  10,  10,  10,  10,  10,  -10,  -10, 0,  10, 10, 10, 10, 0,  -10,
     -10, 5,   5,   10,  10,  5,   5,   -10,  -10, 0,  5,  10, 10, 5,  0,  -10,
     -10, 0,   0,   0,   0,   0,   0,   -10,  -20, -10, -10, -10, -10, -10, -10, -20},
    // rook
    {0,  0, 0, 5, 5, 0, 0, 0,   -5, 0, 0, 0, 0, 0, 0, -5,
     -5, 0, 0, 0, 0, 0, 0, -5,  -5, 0, 0, 0, 0, 0, 0, -5,
     -5, 0, 0, 0, 0, 0, 0, -5,  -5, 0, 0, 0, 0, 0, 0, -5,
     5, 10, 10, 10, 10, 10, 10, 5,  0, 0, 0, 0, 0, 0, 0, 0},
    // queen
    {-20, -10, -10, -5, -5, -10, -10, -20,  -10, 0,  5,  0,  0,  0,  0,  -10,
     -10, 5,   5,   5,  5,  5,   0,   -10,  0,   0,  5,  5,  5,  5,  0,  -5,
     -5,  0,   5,   5,  5,  5,   0,   -5,   -10, 0,  5,  5,  5,  5,  0,  -10,
     -10, 0,   0,   0,  0,  0,   0,   -10,  -20, -10, -10, -5, -5, -10, -10, -20},
    // king (shelter-seeking midgame table)
    {20, 30, 10, 0,  0,  10, 30, 20,   20,  20,  0,   0,   0,   0,   20,  20,
     -10, -20, -20, -20, -20, -20, -20, -10, -20, -30, -30, -40, -40, -30, -30, -20,
     -30, -40, -40, -50, -50, -40, -40, -30, -30, -40, -40, -50, -50, -40, -40, -30,
     -30, -40, -40, -50, -50, -40, -40, -30, -30, -40, -40, -50, -50, -40, -40, -30},
};

inline Square flip(Square s) { return s ^ 56; }

// Material + PST for one color, white-normalized squares.
int side_score(const Position& pos, Color c) {
  int score = 0;
  for (int pt = PAWN; pt < PIECE_TYPE_NB; pt++) {
    Bitboard pcs = pos.pieces(c, PieceType(pt));
    while (pcs) {
      Square s = pop_lsb(pcs);
      score += MATERIAL[pt] + PST[pt][c == WHITE ? s : flip(s)];
    }
  }
  return score;
}

// Chebyshev distance to the four center squares (KotH objective).
int center_distance(Square s) {
  int f = file_of(s), r = rank_of(s);
  int df = f < 3 ? 3 - f : (f > 4 ? f - 4 : 0);
  int dr = r < 3 ? 3 - r : (r > 4 ? r - 4 : 0);
  return df > dr ? df : dr;
}

}  // namespace

int hce_evaluate(const Position& pos) {
  Color us = pos.stm, them = ~us;
  int score;

  switch (pos.variant) {
    case VR_ANTICHESS: {
      // Objective inverted: shedding material is winning. PSTs would
      // point the wrong way, so use pure (negated) material with the
      // king as an ordinary ~300 cp piece, plus a nudge for mobility
      // freedom (fewer forced captures for us = more control).
      int mat = 0;
      for (int pt = PAWN; pt < PIECE_TYPE_NB; pt++) {
        int v = pt == KING ? 300 : MATERIAL[pt];
        mat += v * (popcount(pos.pieces(us, PieceType(pt))) -
                    popcount(pos.pieces(them, PieceType(pt))));
      }
      score = -mat;
      break;
    }
    case VR_RACING_KINGS: {
      // Rank progress dominates; material is a tie-breaker that buys
      // control of the run.
      Square uk = pos.king_sq(us), tk = pos.king_sq(them);
      int progress = (uk != SQ_NONE ? rank_of(uk) : 0) -
                     (tk != SQ_NONE ? rank_of(tk) : 0);
      score = 120 * progress + (side_score(pos, us) - side_score(pos, them)) / 4;
      break;
    }
    case VR_KING_OF_THE_HILL: {
      score = side_score(pos, us) - side_score(pos, them);
      Square uk = pos.king_sq(us), tk = pos.king_sq(them);
      if (uk != SQ_NONE) score += 25 * (3 - center_distance(uk));
      if (tk != SQ_NONE) score -= 25 * (3 - center_distance(tk));
      break;
    }
    case VR_THREE_CHECK:
      score = side_score(pos, us) - side_score(pos, them);
      // Each delivered check is worth a minor piece; two checks nearly a
      // rook — mirroring how sharply the game tilts.
      score += 250 * (pos.checks_given[us] - pos.checks_given[them]);
      break;
    case VR_CRAZYHOUSE: {
      score = side_score(pos, us) - side_score(pos, them);
      // Pocket pieces are slightly discounted board material (they need
      // a tempo to deploy but strike anywhere).
      for (int pt = PAWN; pt < KING; pt++)
        score += (MATERIAL[pt] * 3 / 4) *
                 (pos.hand[us][pt] - pos.hand[them][pt]);
      break;
    }
    case VR_HORDE: {
      // White's pawns are the army itself: count them at full value via
      // the shared tables; black wants to trade them off. A small bonus
      // for advanced horde pawns (promotion pressure) sharpens play.
      score = side_score(pos, us) - side_score(pos, them);
      Bitboard horde_pawns = pos.pieces(WHITE, PAWN);
      int adv = 0;
      Bitboard p = horde_pawns;
      while (p) adv += rank_of(pop_lsb(p));
      score += (us == WHITE ? adv : -adv);
      break;
    }
    case VR_ATOMIC: {
      score = side_score(pos, us) - side_score(pos, them);
      // King exposure is lethal: penalize enemy pieces adjacent to our
      // king (explosion range) far beyond their attack value.
      Square uk = pos.king_sq(us), tk = pos.king_sq(them);
      if (uk != SQ_NONE)
        score -= 40 * popcount(KING_ATTACKS[uk] & pos.pieces(them));
      if (tk != SQ_NONE)
        score += 40 * popcount(KING_ATTACKS[tk] & pos.pieces(us));
      break;
    }
    default:
      score = side_score(pos, us) - side_score(pos, them);
      break;
  }

  return score + 10;  // tempo
}

}  // namespace fc
