// Core types for the fishnet-tpu native chess engine.
//
// The reference framework (fishnet) delegates all chess rules to external
// C++ engines (Stockfish / Fairy-Stockfish submodules) and to the shakmaty
// Rust library for legality replay (reference: src/queue.rs:524-552).
// This core replaces both: one native rules+search library used for batch
// validation (via ctypes) and for the TPU-batched search engine.
//
// Conventions: square 0 = a1, 7 = h1, 56 = a8, 63 = h8 (little-endian
// rank-file). White moves "up" (+8).

#pragma once

#include <cstdint>
#include <cstdlib>
#include <string>

namespace fc {

using Bitboard = uint64_t;

enum Color : int { WHITE = 0, BLACK = 1, COLOR_NB = 2 };

constexpr Color operator~(Color c) { return Color(c ^ 1); }

enum PieceType : int {
  PAWN = 0,
  KNIGHT = 1,
  BISHOP = 2,
  ROOK = 3,
  QUEEN = 4,
  KING = 5,
  PIECE_TYPE_NB = 6,
  NO_PIECE_TYPE = 7,
};

// Piece = color * 6 + type; 14 = empty.
enum Piece : int { NO_PIECE = 14 };

constexpr int make_piece(Color c, PieceType pt) { return int(c) * 6 + int(pt); }
constexpr Color piece_color(int pc) { return Color(pc / 6); }
constexpr PieceType piece_type(int pc) { return PieceType(pc % 6); }

using Square = int;
constexpr Square SQ_NONE = -1;

constexpr int file_of(Square s) { return s & 7; }
constexpr int rank_of(Square s) { return s >> 3; }
constexpr Square make_square(int file, int rank) { return rank * 8 + file; }

constexpr Bitboard bb(Square s) { return 1ULL << s; }

constexpr Bitboard FILE_A_BB = 0x0101010101010101ULL;
constexpr Bitboard RANK_1_BB = 0xFFULL;
constexpr Bitboard file_bb(int f) { return FILE_A_BB << f; }
constexpr Bitboard rank_bb(int r) { return RANK_1_BB << (8 * r); }

// The four central squares d4/e4/d5/e5 — the king-of-the-hill objective,
// shared by outcome detection, search terminals, and the HCE eval.
constexpr Bitboard CENTER4_BB = bb(make_square(3, 3)) | bb(make_square(4, 3)) |
                                bb(make_square(3, 4)) | bb(make_square(4, 4));

inline int popcount(Bitboard b) { return __builtin_popcountll(b); }
inline Square lsb(Bitboard b) { return __builtin_ctzll(b); }
inline Square msb(Bitboard b) { return 63 - __builtin_clzll(b); }
inline Square pop_lsb(Bitboard& b) {
  Square s = lsb(b);
  b &= b - 1;
  return s;
}

// ---------------------------------------------------------------------------
// Moves. 32-bit encoding: from[0:6] to[6:12] promo[12:15] kind[15:18]
// drop-piece[18:21]. Castling is encoded king-from -> rook-from (works
// uniformly for standard and Chess960, like UCI_Chess960 notation).
// ---------------------------------------------------------------------------

enum MoveKind : int {
  MK_NORMAL = 0,
  MK_CASTLE = 1,
  MK_EN_PASSANT = 2,
  MK_DROP = 3,  // crazyhouse
};

using Move = uint32_t;
constexpr Move MOVE_NONE = 0xFFFFFFFFu;

constexpr Move make_move(Square from, Square to, MoveKind kind = MK_NORMAL,
                         PieceType promo = NO_PIECE_TYPE) {
  return Move(from) | (Move(to) << 6) | (Move(promo) << 12) | (Move(kind) << 15);
}
constexpr Move make_drop(Square to, PieceType pt) {
  return Move(to) << 6 | (Move(NO_PIECE_TYPE) << 12) | (Move(MK_DROP) << 15) |
         (Move(pt) << 18);
}

constexpr Square move_from(Move m) { return Square(m & 0x3F); }
constexpr Square move_to(Move m) { return Square((m >> 6) & 0x3F); }
constexpr PieceType move_promo(Move m) { return PieceType((m >> 12) & 0x7); }
constexpr MoveKind move_kind(Move m) { return MoveKind((m >> 15) & 0x7); }
constexpr PieceType move_drop_piece(Move m) { return PieceType((m >> 18) & 0x7); }

// Variants supported by the rules layer. Mirrors the protocol's variant set
// (reference: src/logger.rs:192-203). STANDARD covers Chess960 via
// rook-square castling rights.
enum VariantRules : int {
  VR_STANDARD = 0,
  VR_ANTICHESS = 1,
  VR_ATOMIC = 2,
  VR_CRAZYHOUSE = 3,
  VR_HORDE = 4,
  VR_KING_OF_THE_HILL = 5,
  VR_RACING_KINGS = 6,
  VR_THREE_CHECK = 7,
};

}  // namespace fc
