// Minimal stackful-coroutine wrapper, used to suspend an alpha-beta
// search at each leaf evaluation so thousands of searches can share one
// TPU eval microbatch.
//
// This replaces the reference's parallelism unit: where fishnet runs one
// blocking single-threaded engine *process* per core (src/main.rs:158-170),
// fishnet-tpu runs thousands of cooperative search fibers per host thread,
// all yielding leaf positions into a shared evaluator batch (SURVEY.md §7
// "the inversion that makes this TPU-shaped").
//
// Two backends behind one interface:
//  * POSIX: ucontext contexts over an mmap'd stack with a PROT_NONE
//    guard page (Linux, macOS);
//  * Windows: the Win32 Fiber API (CreateFiberEx/SwitchToFiber), which
//    is the same shape — the OS manages the stack, reserves the full
//    size, commits pages on touch, and places its own guard page.

#pragma once

#include <cstdint>
#include <cstdlib>
#include <functional>

#ifdef _WIN32

#ifndef WIN32_LEAN_AND_MEAN
#define WIN32_LEAN_AND_MEAN
#endif
#ifndef NOMINMAX
#define NOMINMAX
#endif
#include <windows.h>

namespace fc {

class Fiber {
 public:
  // Reserve the full stack, commit one page up front; the kernel grows
  // it through its guard page exactly like a thread stack, so overflow
  // faults instead of corrupting neighboring slots (the same contract
  // the POSIX backend gets from its explicit PROT_NONE page).
  explicit Fiber(size_t stack_size = 512 * 1024) : stack_size_(stack_size) {
    fiber_ = CreateFiberEx(4096, stack_size_, 0, &Fiber::trampoline, this);
  }

  ~Fiber() {
    if (fiber_) DeleteFiber(fiber_);
  }

  bool valid() const { return fiber_ != nullptr; }

  Fiber(const Fiber&) = delete;
  Fiber& operator=(const Fiber&) = delete;

  // Start running fn on this fiber. fn runs until it yields or returns.
  void start(std::function<void()> fn) {
    fn_ = std::move(fn);
    done_ = false;
    // A finished fiber's entry point has returned control and cannot be
    // re-entered: recreate it so the trampoline starts fresh.
    if (started_) {
      if (fiber_) DeleteFiber(fiber_);
      fiber_ = CreateFiberEx(4096, stack_size_, 0, &Fiber::trampoline, this);
      if (!fiber_) {
        done_ = true;
        return;
      }
    }
    started_ = true;
    resume();
  }

  // Resume the fiber until its next yield() or completion.
  void resume() {
    void*& sched = scheduler_fiber();
    if (!sched) {
      // First resume on this thread: the scheduler must itself be a
      // fiber before SwitchToFiber can leave it.
      sched = IsThreadAFiber() ? GetCurrentFiber()
                               : ConvertThreadToFiber(nullptr);
    }
    caller_ = sched;
    current_ = this;
    SwitchToFiber(fiber_);
    current_ = nullptr;
  }

  // Called from inside the fiber: return control to the scheduler.
  void yield() { SwitchToFiber(caller_); }

  bool done() const { return done_; }

  // The fiber currently executing on this thread (nullptr outside fibers).
  static Fiber* current() { return current_; }

 private:
  static void CALLBACK trampoline(void* p) {
    Fiber* self = static_cast<Fiber*>(p);
    self->fn_();
    self->done_ = true;
    // A fiber procedure must never return (it would exit the thread);
    // hand control back to the scheduler, like uc_link does on POSIX.
    SwitchToFiber(self->caller_);
  }

  static void*& scheduler_fiber() {
    static thread_local void* f = nullptr;
    return f;
  }

  void* fiber_ = nullptr;
  void* caller_ = nullptr;
  size_t stack_size_;
  bool started_ = false;
  std::function<void()> fn_;
  bool done_ = true;
  static thread_local Fiber* current_;
};

inline thread_local Fiber* Fiber::current_ = nullptr;

}  // namespace fc

#else  // POSIX ucontext backend

#include <sys/mman.h>
#include <ucontext.h>
#include <unistd.h>

// macOS has no MAP_STACK (Linux uses it as a hint for stack mappings;
// omitting it is semantically fine everywhere).
#ifndef MAP_STACK
#define MAP_STACK 0
#endif

namespace fc {

class Fiber {
 public:
  // The stack is mmap'd with a PROT_NONE guard page below it, so a search
  // recursion overflowing the stack faults immediately instead of
  // silently corrupting neighboring slots' heap state. Worst case
  // (MAX_PLY alpha-beta frames + qsearch tail, ~2.5 KB/frame) fits in
  // 512 KB with headroom; pages are only committed when touched.
  explicit Fiber(size_t stack_size = 512 * 1024) : stack_size_(stack_size) {
    size_t page = size_t(sysconf(_SC_PAGESIZE));
    map_size_ = stack_size_ + page;
    void* map = mmap(nullptr, map_size_, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
    if (map == MAP_FAILED) {
      map_ = nullptr;
      stack_ = nullptr;
      return;
    }
    map_ = static_cast<char*>(map);
    mprotect(map_, page, PROT_NONE);  // guard page at the low end
    stack_ = map_ + page;
  }

  ~Fiber() {
    if (map_) munmap(map_, map_size_);
  }

  bool valid() const { return stack_ != nullptr; }

  Fiber(const Fiber&) = delete;
  Fiber& operator=(const Fiber&) = delete;

  // Start running fn on this fiber. fn runs until it yields or returns.
  void start(std::function<void()> fn) {
    fn_ = std::move(fn);
    done_ = false;
    getcontext(&ctx_);
    ctx_.uc_stack.ss_sp = stack_;
    ctx_.uc_stack.ss_size = stack_size_;
    ctx_.uc_link = &caller_;
    makecontext(&ctx_, reinterpret_cast<void (*)()>(&Fiber::trampoline), 1, this);
    resume();
  }

  // Resume the fiber until its next yield() or completion.
  void resume() {
    current_ = this;
    swapcontext(&caller_, &ctx_);
    current_ = nullptr;
  }

  // Called from inside the fiber: return control to the scheduler.
  void yield() { swapcontext(&ctx_, &caller_); }

  bool done() const { return done_; }

  // The fiber currently executing on this thread (nullptr outside fibers).
  static Fiber* current() { return current_; }

 private:
  static void trampoline(Fiber* self) {
    self->fn_();
    self->done_ = true;
    // returning switches to uc_link (the caller context)
  }

  ucontext_t ctx_{};
  ucontext_t caller_{};
  char* map_ = nullptr;
  size_t map_size_ = 0;
  char* stack_;
  size_t stack_size_;
  std::function<void()> fn_;
  bool done_ = true;
  static thread_local Fiber* current_;
};

inline thread_local Fiber* Fiber::current_ = nullptr;

}  // namespace fc

#endif  // _WIN32
