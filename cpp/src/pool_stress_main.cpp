// Multithreaded pool stress driver for the sanitizer gates (TSAN/ASAN).
//
// The Python determinism suite (tests/test_multithread.py) can prove
// results don't change across thread counts, but it cannot SEE a data
// race that happens to produce the same move. This driver exercises the
// cross-thread surfaces of the pool — the lockless XOR-validated TT and
// its generation side-array, the shared continuation-history tables,
// the relaxed-atomic counters, and the per-slot stop/abort latches —
// under instrumented builds (`make tsan` / `make asan`), where the
// sanitizer runtime, not luck, decides whether the concurrency is
// sound. Build and gate in CI (.github/workflows/build.yml sanitizers
// job).
//
// Usage: pool-stress [net.nnue] [searches-per-thread] [threads]
//   With a net file, half the traffic is standard-chess scalar-NNUE
//   searches; the rest are variant/HCE searches. Both evaluate on the
//   host and never suspend, so the whole search runs inside
//   fc_pool_step — maximum concurrent TT/history pressure, no device.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "nnue.h"
#include "types.h"

// The pool's C surface (defined in pool.cpp; no public header by design
// — Python binds via ctypes, and this driver links the objects).
extern "C" {
struct SearchPool;
SearchPool* fc_pool_new(int slots, uint64_t tt_bytes, const char* net_path,
                        int n_groups);
void fc_pool_free(SearchPool* pool);
int fc_pool_submit(SearchPool* pool, int group, const char* fen,
                   const char* moves, uint64_t nodes, int depth, int multipv,
                   int skill, int use_scalar, int variant);
void fc_pool_stop_all(SearchPool* pool);
void fc_pool_abort_all(SearchPool* pool);
void fc_pool_set_anchors(SearchPool* pool, int enable);
int fc_pool_provide(SearchPool* pool, int group, const int32_t* values, int n);
int fc_pool_step(SearchPool* pool, int group, uint16_t* packed,
                 int32_t* offsets, int32_t* buckets, int32_t* slots,
                 int32_t* parent, int32_t* material, int capacity, int align,
                 int32_t* rows);
int fc_pool_active(SearchPool* pool, int group);
int fc_pool_next_finished(SearchPool* pool, int group);
int fc_pool_result_summary(SearchPool* pool, int slot, uint64_t* nodes,
                           int32_t* depth, char* best, int best_len,
                           int32_t* n_lines);
void fc_pool_release(SearchPool* pool, int slot);
int fc_pool_counters(SearchPool* pool, uint64_t* out, int n);
}

namespace {

constexpr int CAPACITY = 256;

struct Job {
  const char* fen;
  int variant;  // fc::VariantRules value
  int use_scalar;
};

const char* STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1";
const char* MIDGAME =
    "r2q1rk1/ppp2ppp/2npbn2/2b1p3/4P3/2PP1NN1/PPB2PPP/R1BQ1RK1 w - - 6 9";
const char* ENDGAME = "8/5pk1/6p1/8/3K4/8/5PP1/8 w - - 0 1";
const char* HORDE_START =
    "rnbqkbnr/pppppppp/8/1PP2PP1/PPPPPPPP/PPPPPPPP/PPPPPPPP/PPPPPPPP w kq - 0 1";
const char* RK_START = "8/8/8/8/8/8/krbnNBRK/qrbnNBRQ w - - 0 1";

// Unit phase: the persistent-anchor FULL-PROVIDE contract
// (fc_pool_provide refuses partial provides with anchors enabled, and
// fc_pool_step's stale-batch repair keeps a step-without-provide from
// re-emitting self-referential anchor deltas). Needs a net: standard
// batched searches walk the PSQT table host-side.
int provide_guard_check(const char* net_path) {
  SearchPool* pool = fc_pool_new(/*slots=*/8, /*tt_bytes=*/1 << 20,
                                 net_path, /*n_groups=*/1);
  if (!pool) {
    std::fprintf(stderr, "provide-guard: fc_pool_new failed\n");
    return 1;
  }
  fc_pool_set_anchors(pool, 1);
  for (int i = 0; i < 2; i++) {
    int rc = fc_pool_submit(pool, 0, MIDGAME, "", /*nodes=*/4000,
                            /*depth=*/6, /*multipv=*/1, /*skill=*/20,
                            /*use_scalar=*/0, fc::VR_STANDARD);
    if (rc < 0) {
      std::fprintf(stderr, "provide-guard: submit failed (%d)\n", rc);
      fc_pool_free(pool);
      return 1;
    }
  }
  std::vector<uint16_t> packed((4 * CAPACITY + 4) * 2 * 8);
  std::vector<int32_t> offsets(CAPACITY), buckets(CAPACITY), slots(CAPACITY),
      parent(CAPACITY), material(CAPACITY), values(CAPACITY, 0);
  int32_t rows = 0;
  int failures = 0;
  bool exercised_partial = false, exercised_stale = false;
  for (int iter = 0; iter < 2000 && fc_pool_active(pool, 0) > 0; iter++) {
    int n = fc_pool_step(pool, 0, packed.data(), offsets.data(),
                         buckets.data(), slots.data(), parent.data(),
                         material.data(), CAPACITY, 0, &rows);
    if (n <= 0) continue;
    if (!exercised_partial) {
      // A partial provide must be refused outright and consume nothing.
      if (fc_pool_provide(pool, 0, values.data(), n - 1) != -1) {
        std::fprintf(stderr,
                     "provide-guard: partial provide was not refused\n");
        failures++;
      }
      exercised_partial = true;
      if (fc_pool_provide(pool, 0, values.data(), n) != n) {
        std::fprintf(stderr, "provide-guard: full retry not accepted\n");
        failures++;
      }
      continue;
    }
    if (!exercised_stale) {
      // Step WITHOUT providing: the stale-batch repair must rebuild
      // re-emitted persistent entry-0 deltas as plain fulls (no wire
      // code <= -2 carrying the delta bit may survive the repair).
      int n2 = fc_pool_step(pool, 0, packed.data(), offsets.data(),
                            buckets.data(), slots.data(), parent.data(),
                            material.data(), CAPACITY, 0, &rows);
      for (int i = 0; i < n2; i++) {
        int32_t v = -parent[i] - 2;
        if (parent[i] <= -2 && (v & 2) != 0) {
          std::fprintf(stderr,
                       "provide-guard: persistent delta survived the "
                       "stale-batch repair (entry %d code %d)\n",
                       i, parent[i]);
          failures++;
        }
      }
      exercised_stale = true;
      n = n2;
      if (n <= 0) continue;
    }
    if (fc_pool_provide(pool, 0, values.data(), n) != n) {
      std::fprintf(stderr, "provide-guard: full provide rejected\n");
      failures++;
      break;
    }
  }
  if (!exercised_partial) {
    std::fprintf(stderr, "provide-guard: no eval batch was ever emitted\n");
    failures++;
  }
  int slot;
  while ((slot = fc_pool_next_finished(pool, 0)) >= 0) fc_pool_release(pool, slot);
  fc_pool_abort_all(pool);
  while (fc_pool_active(pool, 0) > 0) {
    int n = fc_pool_step(pool, 0, packed.data(), offsets.data(),
                         buckets.data(), slots.data(), parent.data(),
                         material.data(), CAPACITY, 0, &rows);
    if (n > 0 && fc_pool_provide(pool, 0, values.data(), n) != n) break;
    while ((slot = fc_pool_next_finished(pool, 0)) >= 0)
      fc_pool_release(pool, slot);
  }
  fc_pool_free(pool);
  if (failures == 0)
    std::printf("provide-guard: full-provide contract enforced "
                "(partial refused, stale batch repaired)\n");
  return failures ? 1 : 0;
}

// Unit phase: anchors + PSQT wire cross-check (ABI 9). Drives real
// batched-NNUE search traffic with persistent anchors enabled and, for
// every emitted batch, rebuilds each entry's resolved [2][8] PSQT
// accumulator FROM THE WIRE ALONE — packed feature rows (removal
// encodings decoded via NNUE_DELTA_BASE), parent codes (in-batch refs,
// persistent anchor loads with perspective swap), and a driver-side
// anchor-PSQT table mirroring the device's — then checks the pool's
// host-computed material column against the bucket-selected difference.
// This is the same reconstruction the fused TPU kernel performs, so a
// pass proves host material and device PSQT are interchangeable after
// arbitrary delta chains. Every other step passes out_material=nullptr,
// covering the ABI 9 optional-material wire layout under the
// sanitizers.
int anchors_psqt_check(const char* net_path) {
  using fc::NNUE_DELTA_BASE;
  using fc::NNUE_FEATURES;
  using fc::NNUE_PSQT_BUCKETS;

  fc::NnueNet net;
  std::string err = net.load(net_path);
  if (!err.empty()) {
    std::fprintf(stderr, "anchors-psqt: net load failed: %s\n", err.c_str());
    return 1;
  }
  constexpr int SLOTS = 8;
  SearchPool* pool = fc_pool_new(SLOTS, /*tt_bytes=*/1 << 20, net_path,
                                 /*n_groups=*/1);
  if (!pool) {
    std::fprintf(stderr, "anchors-psqt: fc_pool_new failed\n");
    return 1;
  }
  fc_pool_set_anchors(pool, 1);
  const char* fens[] = {STARTPOS, MIDGAME, ENDGAME};
  for (int i = 0; i < 6; i++) {
    if (fc_pool_submit(pool, 0, fens[i % 3], "", /*nodes=*/8000,
                       /*depth=*/7, /*multipv=*/1, /*skill=*/20,
                       /*use_scalar=*/0, fc::VR_STANDARD) < 0) {
      std::fprintf(stderr, "anchors-psqt: submit failed\n");
      fc_pool_free(pool);
      return 1;
    }
  }

  std::vector<uint16_t> packed((4 * CAPACITY + 4) * 2 * 8);
  std::vector<int32_t> offsets(CAPACITY), buckets(CAPACITY), slots(CAPACITY),
      parent(CAPACITY), material(CAPACITY), values(CAPACITY, 0);
  // Driver-side twin of the device anchor-PSQT table: one [2][8]
  // accumulator per pool slot (n_groups=1, so aid == slot index).
  int64_t table[SLOTS][2][NNUE_PSQT_BUCKETS] = {};
  int64_t resolved[CAPACITY][2][NNUE_PSQT_BUCKETS];
  int32_t rows = 0;
  int failures = 0;
  long verified = 0, persistent_loads = 0;

  auto add_row = [&](int64_t (*acc)[NNUE_PSQT_BUCKETS], int p, uint16_t f) {
    if (f == NNUE_FEATURES || f == NNUE_DELTA_BASE + NNUE_FEATURES) return;
    int sign = 1;
    int fi = int(f);
    if (fi >= NNUE_DELTA_BASE) {
      sign = -1;
      fi -= NNUE_DELTA_BASE;
    }
    const int32_t* prow = &net.ft_psqt[size_t(fi) * NNUE_PSQT_BUCKETS];
    for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) acc[p][b] += sign * prow[b];
  };

  for (int iter = 0; iter < 4000 && fc_pool_active(pool, 0) > 0; iter++) {
    // Every other step ships the ABI 9 wire WITHOUT the material
    // column (out_material=nullptr): the layout the device-PSQT hot
    // path uses; the sanitizers watch the pool skip the column.
    bool with_material = (iter % 2) == 0;
    int n = fc_pool_step(pool, 0, packed.data(), offsets.data(),
                         buckets.data(), slots.data(), parent.data(),
                         with_material ? material.data() : nullptr, CAPACITY,
                         0, &rows);
    for (int idx = 0; idx < n; idx++) {
      int32_t code = parent[idx];
      int32_t v = -code - 2;
      bool is_pers = code <= -2 && (v & 2) != 0;
      bool is_delta = code >= 0 || is_pers;
      int swap = 0;
      int64_t base[2][NNUE_PSQT_BUCKETS] = {};
      if (code >= 0) {
        swap = code & 1;
        std::memcpy(base, resolved[code >> 1], sizeof(base));
      } else if (is_pers) {
        swap = v & 1;
        std::memcpy(base, table[(v >> 2) % SLOTS], sizeof(base));
        persistent_loads++;
      }
      int64_t acc[2][NNUE_PSQT_BUCKETS] = {};
      for (int p = 0; p < 2; p++)
        for (int b = 0; b < NNUE_PSQT_BUCKETS; b++)
          acc[p][b] = is_delta ? base[swap ? 1 - p : p][b] : 0;
      int n_rows = is_delta ? 1 : 4;
      for (int r = 0; r < n_rows; r++)
        for (int p = 0; p < 2; p++)
          for (int k = 0; k < 8; k++)
            add_row(acc, p,
                    packed[((size_t(offsets[idx]) + r) * 2 + p) * 8 + k]);
      std::memcpy(resolved[idx], acc, sizeof(acc));
      if (code <= -2)  // store codes refresh the slot's table row
        std::memcpy(table[(v >> 2) % SLOTS], acc, sizeof(acc));
      int64_t d = acc[0][buckets[idx]] - acc[1][buckets[idx]];
      int32_t expect = int32_t(d / 2);  // C truncation, as fill_full
      if (with_material && material[idx] != expect) {
        if (failures++ < 8)
          std::fprintf(stderr,
                       "anchors-psqt: entry %d (code %d) host material %d "
                       "!= wire reconstruction %d\n",
                       idx, code, material[idx], int(expect));
      }
      verified++;
      values[idx] = expect;  // provide a material-shaped score
    }
    if (n > 0 && fc_pool_provide(pool, 0, values.data(), n) != n) {
      std::fprintf(stderr, "anchors-psqt: full provide rejected\n");
      failures++;
      break;
    }
    int slot;
    while ((slot = fc_pool_next_finished(pool, 0)) >= 0)
      fc_pool_release(pool, slot);
  }
  if (verified == 0) {
    std::fprintf(stderr, "anchors-psqt: no eval entries were emitted\n");
    failures++;
  }
  if (persistent_loads == 0) {
    std::fprintf(stderr,
                 "anchors-psqt: no persistent anchor-load entries seen — "
                 "the phase never exercised the device-table path\n");
    failures++;
  }
  fc_pool_abort_all(pool);
  while (fc_pool_active(pool, 0) > 0) {
    int n = fc_pool_step(pool, 0, packed.data(), offsets.data(),
                         buckets.data(), slots.data(), parent.data(),
                         material.data(), CAPACITY, 0, &rows);
    if (n > 0 && fc_pool_provide(pool, 0, values.data(), n) != n) break;
    int slot;
    while ((slot = fc_pool_next_finished(pool, 0)) >= 0)
      fc_pool_release(pool, slot);
  }
  fc_pool_free(pool);
  if (failures == 0)
    std::printf("anchors-psqt: %ld entries reconstructed from the wire "
                "(%ld persistent loads), host material exact; nullptr "
                "material column exercised\n",
                verified, persistent_loads);
  return failures ? 1 : 0;
}

}  // namespace

int main(int argc, char** argv) {
  const char* net_path = argc > 1 ? argv[1] : "";
  const int per_thread = argc > 2 ? std::atoi(argv[2]) : 48;
  const int n_threads = argc > 3 ? std::atoi(argv[3]) : 4;
  const bool have_net = net_path[0] != '\0';

  // Anchor-contract unit phases first (single-threaded, need the net's
  // PSQT table for batched feature extraction): the full-provide guard,
  // then the ABI 9 anchors+PSQT wire cross-check.
  if (have_net && provide_guard_check(net_path) != 0) return 1;
  if (have_net && anchors_psqt_check(net_path) != 0) return 1;

  // Small TT on purpose: eviction (the racier path — victim ranking,
  // generation reads, XOR re-stores) must fire constantly.
  SearchPool* pool = fc_pool_new(/*slots=*/n_threads * 16,
                                 /*tt_bytes=*/1 << 20, net_path, n_threads);
  if (!pool) {
    std::fprintf(stderr, "pool-stress: fc_pool_new failed\n");
    return 1;
  }

  std::vector<Job> jobs;
  if (have_net) {
    jobs.push_back({STARTPOS, fc::VR_STANDARD, 1});
    jobs.push_back({MIDGAME, fc::VR_STANDARD, 1});
    jobs.push_back({ENDGAME, fc::VR_STANDARD, 1});
  }
  jobs.push_back({STARTPOS, fc::VR_ANTICHESS, 0});
  jobs.push_back({MIDGAME, fc::VR_ATOMIC, 0});
  jobs.push_back({STARTPOS, fc::VR_KING_OF_THE_HILL, 0});
  jobs.push_back({MIDGAME, fc::VR_THREE_CHECK, 0});
  jobs.push_back({HORDE_START, fc::VR_HORDE, 0});
  jobs.push_back({RK_START, fc::VR_RACING_KINGS, 0});

  std::atomic<uint64_t> done{0}, total_nodes{0};
  std::atomic<bool> failed{false}, running{true};

  auto drive = [&](int group) {
    // Per-thread step buffers (owner-thread only, like the service's).
    std::vector<uint16_t> packed((4 * CAPACITY + 4) * 2 * 8);
    std::vector<int32_t> offsets(CAPACITY), buckets(CAPACITY),
        slots(CAPACITY), parent(CAPACITY), material(CAPACITY);
    int submitted = 0, harvested = 0;
    while (harvested < per_thread && !failed.load()) {
      while (submitted < per_thread) {
        const Job& j = jobs[(group * 7 + submitted) % jobs.size()];
        // Low skill on some searches: the weakened multipv pick also
        // runs under the sanitizer.
        int skill = (submitted % 5 == 0) ? -9 : 20;
        int rc = fc_pool_submit(pool, group, j.fen, "", /*nodes=*/20000,
                                /*depth=*/8, /*multipv=*/1, skill,
                                j.use_scalar, j.variant);
        if (rc == -1) break;  // group momentarily full
        if (rc < 0) {
          std::fprintf(stderr, "pool-stress: submit failed (%d)\n", rc);
          failed = true;
          return;
        }
        submitted++;
      }
      int32_t rows = 0;
      int n = fc_pool_step(pool, group, packed.data(), offsets.data(),
                           buckets.data(), slots.data(), parent.data(),
                           material.data(), CAPACITY, 0, &rows);
      if (n != 0) {
        // Scalar/HCE searches never suspend for the device; eval
        // requests here mean a job was misrouted to the batched bridge.
        std::fprintf(stderr, "pool-stress: unexpected eval batch (%d)\n", n);
        failed = true;
        return;
      }
      int slot;
      while ((slot = fc_pool_next_finished(pool, group)) >= 0) {
        uint64_t nodes = 0;
        int32_t depth = 0, n_lines = 0;
        char best[8] = {0};
        fc_pool_result_summary(pool, slot, &nodes, &depth, best,
                               sizeof(best), &n_lines);
        total_nodes.fetch_add(nodes, std::memory_order_relaxed);
        fc_pool_release(pool, slot);
        harvested++;
        done.fetch_add(1, std::memory_order_relaxed);
      }
    }
  };

  // Telemetry thread: hammers the cross-thread read surfaces while the
  // drivers mutate them.
  std::thread telemetry([&] {
    uint64_t counters[16];
    while (running.load(std::memory_order_relaxed)) {
      fc_pool_counters(pool, counters, 16);
      for (int g = 0; g < n_threads; g++) fc_pool_active(pool, g);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });

  // Chaos thread: periodic stop_all exercises the any-thread stop
  // latches against searches mid-node. Searches still return results
  // (first-iteration guarantee), so the harvest loop completes.
  std::thread chaos([&] {
    while (running.load(std::memory_order_relaxed)) {
      std::this_thread::sleep_for(std::chrono::milliseconds(150));
      fc_pool_stop_all(pool);
    }
  });

  std::vector<std::thread> drivers;
  for (int t = 0; t < n_threads; t++) drivers.emplace_back(drive, t);
  for (auto& th : drivers) th.join();
  running = false;
  telemetry.join();
  chaos.join();
  fc_pool_free(pool);

  if (failed.load()) return 1;
  std::printf("pool-stress: %llu searches, %llu nodes, %d threads%s\n",
              (unsigned long long)done.load(),
              (unsigned long long)total_nodes.load(), n_threads,
              have_net ? " (nnue+hce)" : " (hce only)");
  return 0;
}
