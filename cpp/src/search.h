// Alpha-beta search with batched leaf evaluation.
//
// Feature set targets PV/score parity-grade output for the fishnet
// protocol (SURVEY.md §7 step 4): iterative deepening, shared
// transposition table, quiescence search, MultiPV, node budgets, mate
// scores, repetition/50-move draws. The evaluation is *external*: at
// each leaf the search calls EvalBridge::evaluate(), which may suspend
// the calling fiber until a TPU microbatch returns (pool.cpp), or answer
// immediately from the scalar C++ NNUE (CPU fallback / oracle tests).

#pragma once

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "hce.h"
#include "nnue.h"
#include "position.h"

namespace fc {

constexpr int VALUE_MATE = 32000;
constexpr int VALUE_INF = 32500;
constexpr int MAX_PLY = 128;
constexpr int VALUE_MATE_IN_MAX = VALUE_MATE - MAX_PLY;
constexpr int VALUE_DRAW = 0;

// Max positions a search may request in one eval round-trip (the node
// itself plus prefetched siblings/children). Sized to cover a full
// legal-move list (~30-35 typical) so a depth-1 frontier prefetch almost
// never truncates into follow-up single-eval round-trips.
constexpr int EVAL_BLOCK_MAX = 40;

// Centipawn eval provider. Implementations: scalar NNUE (immediate) or
// the fiber pool's batching bridge (suspends).
class EvalBridge {
 public:
  virtual ~EvalBridge() = default;
  // Static eval of pos from the side to move's point of view.
  virtual int evaluate(const Position& pos) = 0;
  // Evaluate n positions in ONE round-trip (the batching bridge splits
  // into suspensions of up to EVAL_BLOCK_MAX). This is the search's
  // lever against device latency; extra speculative evals are nearly
  // free on an otherwise idle accelerator.
  virtual void evaluate_block(const Position* positions, int n, int32_t* out) {
    for (int i = 0; i < n; i++) out[i] = evaluate(positions[i]);
  }
  // True when evaluate_block amortizes round-trip latency (device
  // batching). Speculative prefetches only pay off then; on a scalar
  // CPU eval they are pure waste.
  virtual bool batched() const { return false; }
  // How many SPECULATIVE evals a prefetch block may carry right now.
  // The pool shrinks this under batch-capacity pressure (wasted slots
  // then steal capacity from other fibers) and grows it back when the
  // device batch has room (a missed prefetch costs a whole round-trip).
  virtual int prefetch_budget() const { return EVAL_BLOCK_MAX; }
};

class ScalarEval : public EvalBridge {
 public:
  explicit ScalarEval(const NnueNet* net) : net_(net) {}
  // Incremental path: consecutive evals on one scheduler thread come
  // from one depth-first search (scalar searches run to completion
  // inside a single pool step), so the thread-local cache's previous
  // position is almost always 1-2 moves away — a handful of row
  // updates instead of a ~60-row gather. Bit-identical to the fresh
  // eval; the cache validates against the net's process-unique id.
  int evaluate(const Position& pos) override {
    static thread_local NnueEvalCache cache;
    return nnue_evaluate_cached(*net_, pos, cache);
  }

 private:
  const NnueNet* net_;
};

// Classical eval for variant searches (the reference's MultiVariant/HCE
// flavor, src/assets.rs:384-391). Immediate — never suspends a fiber.
class HceEval : public EvalBridge {
 public:
  int evaluate(const Position& pos) override { return hce_evaluate(pos); }
};

// -- transposition table (shared across all searches AND all scheduler
// threads) ----------------------------------------------------------------
//
// Lockless: each entry is two relaxed-atomic 64-bit words, `data`
// (the packed payload) and `kx` (= key ^ data). A reader validates by
// re-deriving the key; a torn read — data from one store, kx from a
// concurrent other — fails the XOR check and reads as a miss, exactly
// like a key collision. This is the standard multi-threaded engine TT
// (the reference's engines use the same trick for their SMP builds);
// it costs no synchronization on the probe fast path, which multiple
// scheduler threads hit millions of times per second (the reference
// sidesteps the problem with one engine *process* per core,
// /root/reference/src/main.rs:158-170 — a shared table is strictly
// stronger: adjacent plies of one game share work across threads).

enum TTBound : uint8_t { TT_NONE = 0, TT_UPPER = 1, TT_LOWER = 2, TT_EXACT = 3 };

// Sentinel for "no cached static eval" in a TT entry.
constexpr int16_t TT_EVAL_NONE = 32001;

// Decoded (snapshot) view of a TT entry: probe() fills one; callers
// never see table memory directly.
struct TTData {
  Move move = MOVE_NONE;
  int16_t value = 0;
  int16_t eval = TT_EVAL_NONE;
  uint8_t depth = 0;
  TTBound bound = TT_NONE;
  // The cached eval came from a speculative prefetch and has not been
  // consumed yet (cleared via consume_prefetch) — feeds the prefetch
  // hit-rate counter so the block policy can be tuned against
  // measurements.
  bool prefetched = false;
};

class TranspositionTable {
 public:
  // 4-way clusters: a direct-mapped table loses entries to index
  // collisions exactly when it matters (thousands of concurrent
  // searches sharing one table); within a cluster the weakest entry —
  // stale generation first, then shallowest depth — is the victim.
  static constexpr int CLUSTER = 4;

  explicit TranspositionTable(size_t bytes = 256ull << 20);
  // Lockless lookup: true and a decoded snapshot if the table holds a
  // bound or cached eval for this key.
  bool probe(uint64_t key, TTData& out);
  void store(uint64_t key, Move move, int value, int eval, int depth, TTBound bound);
  // Cache a speculative static eval without ever evicting an entry that
  // carries a search bound or eval for a different key — prefetched
  // evals are cheap and must not degrade the shared table's quality;
  // with 4-way clusters there are four chances to find a free slot.
  // `speculative` tags the entry for prefetch hit-rate accounting.
  void store_eval(uint64_t key, int eval, bool speculative = false);
  // Clear the speculative tag on this key's entry (each prefetched eval
  // is counted as a hit at most once).
  void consume_prefetch(uint64_t key);
  void new_generation() { gen_.fetch_add(1, std::memory_order_relaxed); }

 private:
  struct Packed {
    std::atomic<uint64_t> kx{0};    // key ^ data (0,0 = empty: see OCCUPIED)
    std::atomic<uint64_t> data{0};
  };
  // Payload layout (64 bits):
  //   [0,16)  value  (int16 as uint16)
  //   [16,32) eval   (int16 as uint16; TT_EVAL_NONE = none)
  //   [32,53) move   (the 21 used bits of Move; all-ones = MOVE_NONE —
  //                   from==to makes that pattern unreachable by legal
  //                   moves)
  //   [53,60) depth  (0..127; MAX_PLY-1 fits)
  //   [60,62) bound
  //   [62]    prefetched
  //   [63]    OCCUPIED — a zero-initialized entry must not validate for
  //           a position whose hash happens to be 0
  static uint64_t pack(Move move, int16_t value, int16_t eval, uint8_t depth,
                       TTBound bound, bool prefetched) {
    return (uint64_t(uint16_t(value))) | (uint64_t(uint16_t(eval)) << 16) |
           (uint64_t(move & 0x1FFFFF) << 32) | (uint64_t(depth & 0x7F) << 53) |
           (uint64_t(bound) << 60) | (uint64_t(prefetched) << 62) |
           (1ull << 63);
  }
  static TTData unpack(uint64_t d) {
    TTData out;
    out.value = int16_t(uint16_t(d));
    out.eval = int16_t(uint16_t(d >> 16));
    uint32_t m = uint32_t((d >> 32) & 0x1FFFFF);
    out.move = m == 0x1FFFFF ? MOVE_NONE : Move(m);
    out.depth = uint8_t((d >> 53) & 0x7F);
    out.bound = TTBound((d >> 60) & 0x3);
    out.prefetched = (d >> 62) & 1;
    return out;
  }
  Packed* cluster(uint64_t key) { return &entries_[(key & mask_) * CLUSTER]; }
  std::vector<Packed> entries_;
  // Per-entry generation, OUTSIDE the XOR-validated pair: it only feeds
  // replacement ranking, where a racy read merely picks a slightly
  // different victim — not worth a packed bit. Indexed like entries_.
  // 16 bits: new_generation() bumps once per Search::run, and a pool
  // serving hundreds of searches/s would wrap 8 bits in seconds,
  // aliasing ancient entries as fresh in the replacement ranking.
  // atomic<uint16_t> with relaxed ops (same codegen as the plain word on
  // x86/ARM) so the cross-thread accesses are defined behavior instead
  // of a formal data race TSan would flag.
  std::unique_ptr<std::atomic<uint16_t>[]> gens_;
  size_t mask_;  // cluster-index mask
  std::atomic<uint16_t> gen_{0};
};

// -- shared move-ordering history ----------------------------------------
//
// Continuation history, SHARED across all searches of a pool (like the
// TT): per-Search storage would cost ~1.2 MB x thousands of fiber
// slots, and sharing is a feature — fibers analyzing adjacent plies of
// one game teach each other refutation patterns. Indexed by the
// previous move's (piece, to-square) and the candidate's (piece, to-
// square); piece codes include color (make_piece, 0..11). Updates are
// racy across scheduler threads by design: a lost heuristic increment
// merely reorders a move, it cannot corrupt a result (same class of
// benign race every SMP engine accepts for its history tables).
struct ContinuationHistory {
  static constexpr int PIECES = 12;
  // Relaxed atomics, not plain int16_t: scheduler threads race on these
  // by design (a lost heuristic increment merely reorders a move), but
  // the race must still be DEFINED behavior — plain words are formal UB
  // the compiler may miscompile and TSan rightly flags. Relaxed
  // load/store compiles to the identical mov on x86/ARM.
  std::atomic<int16_t> table[PIECES][64][PIECES][64];
  ContinuationHistory() {
    // Runs before any sharing (pool construction), so the byte-wise
    // zero of the trivially-copyable atomics is safe and instant.
    std::memset(static_cast<void*>(table), 0, sizeof(table));
  }
  std::atomic<int16_t>* slot(int prev_pc, Square prev_to, int pc, Square to) {
    return &table[prev_pc][prev_to][pc][to];
  }
  int read(int prev_pc, Square prev_to, int pc, Square to) {
    return table[prev_pc][prev_to][pc][to].load(std::memory_order_relaxed);
  }
  // Standard history gravity: saturates toward +-LIMIT, recent signals
  // outweigh stale ones, no periodic aging pass needed. The
  // read-modify-write is deliberately NOT a CAS loop — losing a racing
  // increment is cheaper than the contention of winning it.
  static void bump(std::atomic<int16_t>* h, int bonus) {
    constexpr int LIMIT = 1 << 14;
    int old = h->load(std::memory_order_relaxed);
    int v = old + bonus - old * std::abs(bonus) / LIMIT;
    h->store(int16_t(v), std::memory_order_relaxed);
  }
};

// The pool's shared ordering state: 1-ply and 2-ply continuation
// history (the two highest-value tables per Stockfish's own ablations).
struct SharedHistory {
  ContinuationHistory cont1;
  ContinuationHistory cont2;
};

// -- search ---------------------------------------------------------------

// Shared eval-traffic accounting. Single writer (the scheduler thread
// that runs all search fibers), but read cross-thread by telemetry
// (fc_pool_counters from the Python event loop), so the fields are
// relaxed atomics: individual values are exact, ratios may lag a step.
//   occupancy    = evals_shipped / (steps * capacity)   [pool side]
//   prefetch ROI = prefetch_hits / prefetch_shipped
//   cache rate   = tt_eval_hits / (tt_eval_hits + demand_evals)
struct SearchCounters {
  std::atomic<uint64_t> demand_evals{0};     // evals needed right now
  std::atomic<uint64_t> prefetch_shipped{0}; // speculative evals shipped
  std::atomic<uint64_t> prefetch_hits{0};    // speculative evals consumed
  std::atomic<uint64_t> tt_eval_hits{0};     // evals answered from the TT
  std::atomic<uint64_t> nodes{0};            // search nodes visited (live)
  void bump(std::atomic<uint64_t>& c, uint64_t n = 1) {
    c.fetch_add(n, std::memory_order_relaxed);
  }
};

struct SearchLimits {
  uint64_t nodes = 0;  // 0 = unlimited
  int depth = 0;       // 0 = unlimited (MAX_PLY)
  int multipv = 1;
  // Engine skill −9..20; below 20 the search plays WEAKENED: candidate
  // root lines are searched MultiPV-style and the reported best_move is
  // sampled among them with a level-scaled value tolerance, so low
  // levels genuinely blunder (the reference forwards the identical
  // range to Stockfish's `Skill Level`, api.rs:222-273 /
  // stockfish.rs:254-261; this is that mechanism, natively).
  int skill = 20;
  // External stop request (e.g. movetime watchdog, service shutdown);
  // polled per node, may be set from any thread. The first depth-1
  // iteration still completes.
  const std::atomic<bool>* stop = nullptr;
  // Hard abort: polled per node WITHOUT the first-iteration guarantee —
  // the search unwinds immediately and may return an empty result.
  // For teardown paths (bench drain, service shutdown) where partial
  // results are worthless but wall-clock is not.
  const std::atomic<bool>* abort_now = nullptr;
};

struct PvLine {
  int multipv = 1;  // 1-based rank
  int depth = 0;
  bool mate = false;
  int value = 0;  // cp, or mate distance in moves (signed) when mate
  std::vector<Move> pv;
};

struct SearchResult {
  std::vector<PvLine> lines;  // one entry per (iteration, multipv rank)
  Move best_move = MOVE_NONE;
  int depth = 0;
  uint64_t nodes = 0;
};

class Search {
 public:
  // ``see_full``: enable the SEE heuristics that assume the eval tracks
  // material (losing-capture demotion in move ordering, qsearch SEE
  // pruning). The pool derives it from nnue_material_correlated() for
  // NNUE-backed searches and hard-codes true for HCE ones; the
  // depth-scaled SEE prune in the main search is active regardless (it
  // was measured to shrink the tree even under a material-blind net).
  // ``shared``: the pool's shared continuation-history tables; nullptr
  // (standalone searches) degrades to plain per-search history.
  Search(TranspositionTable* tt, EvalBridge* eval,
         SearchCounters* counters = nullptr, bool see_full = true,
         SharedHistory* shared = nullptr)
      : tt_(tt), eval_(eval), counters_(counters), see_full_(see_full),
        shared_(shared) {}

  // Run a full iterative-deepening search. game_history: Zobrist hashes
  // of positions before root (for repetition detection), most recent last.
  SearchResult run(const Position& root, const std::vector<uint64_t>& game_history,
                   const SearchLimits& limits);

 private:
  int alpha_beta(const Position& pos, int alpha, int beta, int depth, int ply,
                 bool is_pv);
  int qsearch(const Position& pos, int alpha, int beta, int ply);
  int evaluate(const Position& pos);
  // Evaluate `pos` plus up to `max_children` of the given children in
  // one round-trip, caching every result as a TT static eval. Children
  // that are in check or already TT-cached are skipped. Returns pos's
  // eval. `include_self`=false prefetches children only (returns 0).
  // Pass the children PRE-ORDERED (and pre-filtered to the moves the
  // caller will actually search) and cap `max_children` to the count
  // likely to be visited: measured speculative hit rates collapse past
  // the first few moves (a cut node visits ~1-2), and every unconsumed
  // eval steals batch capacity from another fiber.
  int prefetch_evals(const Position& pos, const MoveList& children,
                     bool include_self, int max_children);
  // Prediction gate for the qsearch stand-pat-miss prefetch: keep only
  // the targets the capture loop is predicted to consume (`pred` = the
  // classical eval standing in for the unknown NNUE stand-pat). Returns
  // the kept count; 0 predicts a stand-pat cutoff (ship self only).
  int filter_qsearch_prefetch(const Position& pos, const MoveList& targets,
                              MoveList& keep, int pred, int alpha,
                              int beta) const;
  bool is_repetition_or_50(const Position& pos, int ply) const;
  void order_moves(const Position& pos, MoveList& moves, Move tt_move, int ply);
  // Score moves into ``scores`` — the single banding source for every
  // ordering consumer. ``eager_see``: demote losing captures now
  // (full-traversal consumers) instead of deferring SEE to pick time.
  void score_moves(const Position& pos, const MoveList& moves, Move tt_move,
                   int ply, int* scores, bool eager_see = false);
  // Quiet-history reads/updates spanning plain history + 1/2-ply
  // continuation history (shared tables when the pool provides them).
  int quiet_history(const Position& pos, Move m, int ply) const;
  void update_quiet_stats(const Position& pos, Move best, int depth, int ply,
                          const Move* tried, int n_tried);

  TranspositionTable* tt_;
  EvalBridge* eval_;
  SearchCounters* counters_ = nullptr;
  bool see_full_ = true;
  SharedHistory* shared_ = nullptr;
  uint64_t nodes_ = 0;
  uint64_t node_limit_ = 0;
  bool stopped_ = false;
  // The first depth-1 iteration always completes so every search yields
  // at least one scored line, whatever the node budget.
  bool allow_stop_ = false;
  const std::atomic<bool>* external_stop_ = nullptr;
  const std::atomic<bool>* abort_now_ = nullptr;
  std::vector<uint64_t> path_;  // hashes from game start through search path
  size_t root_history_len_ = 0;
  Move killers_[MAX_PLY][2];
  int history_[COLOR_NB][64][64];
  // Countermove heuristic: the quiet refutation of the opponent's last
  // move (indexed by its from/to squares). Continuation history lives
  // in the pool's SharedHistory (shared_), not here: ~1.2 MB per table
  // would not fit thousands of per-slot Search objects.
  Move countermove_[64][64];
  // move_stack_[p] / piece_stack_[p] = the move that led to the node at
  // ply p and the (color-coded) piece that made it (MOVE_NONE at the
  // root and after a null move); feeds countermove + continuation
  // history bookkeeping.
  Move move_stack_[MAX_PLY + 1];
  int piece_stack_[MAX_PLY + 1];
  // Per-ply excluded move for singular-extension verification searches
  // (MOVE_NONE when none): the move loop skips it, and neither TT
  // cutoffs nor TT stores apply at a node searched with an exclusion.
  Move excluded_[MAX_PLY + 1];
  // Static (HCE) eval per ply along the current path, for the
  // `improving` signal: a node whose eval rose since two plies ago
  // prunes less and reduces less. Valid only where eval_valid_ (not in
  // check); indices < root ply are never read.
  int eval_stack_[MAX_PLY + 1];
  bool eval_valid_[MAX_PLY + 1];
  Move pv_table_[MAX_PLY][MAX_PLY];
  int pv_len_[MAX_PLY];
  std::vector<Move> excluded_root_moves_;  // for MultiPV iteration
  // Scratch for prefetch_evals (kept off the fiber stack; non-reentrant).
  Position prefetch_block_[EVAL_BLOCK_MAX];
  uint64_t prefetch_keys_[EVAL_BLOCK_MAX];
};

// Convert an internal value to (is_mate, value-for-uci): mate distance in
// moves from the root's side to move, or centipawns.
void value_to_uci(int value, bool& mate, int& out);

// Static exchange evaluation of move m: material outcome (centipawns,
// mover's point of view) of the capture sequence on the target square
// with both sides recapturing by least valuable attacker; sliding
// x-rays are uncovered as the exchange empties squares. Ordering and
// pruning heuristic only — never part of a returned score. Pins are
// ignored (standard engine practice; Stockfish's SEE does the same).
int see(const Position& pos, Move m);

// Whether SEE's standard-capture assumptions hold for a variant: atomic
// explodes the exchange square (a "losing" capture may win outright)
// and antichess both inverts piece worth and removes the right to
// decline a recapture.
inline bool see_applicable(VariantRules v) {
  return v != VR_ATOMIC && v != VR_ANTICHESS;
}

}  // namespace fc
