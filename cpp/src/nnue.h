// Scalar NNUE evaluator: the bit-exact reference implementation of the
// architecture specified in fishnet_tpu/nnue/spec.py. Serves as the
// score-parity oracle for the JAX evaluator and as the CPU fallback eval
// for the search core.

#pragma once

#include <atomic>
#include <cstdint>
#include <string>
#include <vector>

#include "position.h"

namespace fc {

constexpr int NNUE_PLANES = 11;
constexpr int NNUE_KING_BUCKETS = 32;
constexpr int NNUE_FEATURES = NNUE_KING_BUCKETS * NNUE_PLANES * 64;  // 22528
constexpr int NNUE_MAX_ACTIVE = 32;
// Incremental (delta) eval wire constants — MUST match
// fishnet_tpu/nnue/spec.py (DELTA_BASE / DELTA_SLOTS). A removed
// feature is shipped as DELTA_BASE + index (still uint16); the
// evaluators decode by subtraction and SUBTRACT that row — the device
// table stays single-copy. Per perspective of a delta entry: added
// features in slots [0, DELTA_SLOTS), removals in
// [DELTA_SLOTS, 2*DELTA_SLOTS), each region padded with its own
// sentinel (FEATURES, resp. DELTA_BASE + FEATURES).
constexpr int NNUE_DELTA_BASE = NNUE_FEATURES + 1;
constexpr int NNUE_DELTA_SLOTS = 4;
constexpr int NNUE_L1 = 1024;
constexpr int NNUE_L1_HALF = NNUE_L1 / 2;
constexpr int NNUE_PSQT_BUCKETS = 8;
constexpr int NNUE_L2 = 15;
constexpr int NNUE_L3 = 32;

//: Process-unique id per loaded net: NnueEvalCache validates against it
//: instead of the net's address, which a fresh allocation could alias
//: after a pool teardown (stale accumulators for a different net).
inline std::atomic<uint64_t> nnue_net_uid_counter{0};

struct NnueNet {
  const uint64_t uid = ++nnue_net_uid_counter;
  std::vector<int16_t> ft_weight;  // [FEATURES][L1]
  std::vector<int16_t> ft_bias;    // [L1]
  std::vector<int32_t> ft_psqt;    // [FEATURES][PSQT_BUCKETS]
  // Layer stacks, bucket-major.
  std::vector<int8_t> l1_weight;   // [8][L2+1][L1]
  std::vector<int32_t> l1_bias;    // [8][L2+1]
  std::vector<int8_t> l2_weight;   // [8][L3][2*L2]
  std::vector<int32_t> l2_bias;    // [8][L3]
  std::vector<int8_t> out_weight;  // [8][1][L3]
  std::vector<int32_t> out_bias;   // [8][1]

  // Returns empty string on success.
  std::string load(const std::string& path);
};

// HalfKAv2_hm feature index of one piece for one perspective, given that
// perspective's king square. Factored out so incremental (delta) updates
// can index single added/removed pieces.
inline int nnue_feature_index(Square ksq, Color perspective, int piece,
                              Square s) {
  int flip = perspective == BLACK ? 56 : 0;
  int k0 = ksq ^ flip;
  int mirror = file_of(k0) >= 4 ? 7 : 0;
  int okq = k0 ^ mirror;
  int bucket = rank_of(okq) * 4 + file_of(okq);
  PieceType t = piece_type(piece);
  Color c = piece_color(piece);
  int plane = t == KING ? 10 : 2 * int(t) + (c != perspective ? 1 : 0);
  return bucket * (NNUE_PLANES * 64) + plane * 64 + (s ^ flip ^ mirror);
}

// HalfKAv2_hm active features for one perspective. Writes feature indices
// to out (capacity NNUE_MAX_ACTIVE); returns the count. Templated over
// the index type: int32 for the scalar eval, uint16 for the device batch
// (all indices < 22528 fit).
template <typename T>
int nnue_features(const Position& pos, Color perspective, T* out);
extern template int nnue_features<int32_t>(const Position&, Color, int32_t*);
extern template int nnue_features<uint16_t>(const Position&, Color, uint16_t*);

// Layer-stack / PSQT bucket: (piece count - 1) / 4, clamped.
inline int nnue_psqt_bucket(const Position& pos) {
  int bucket = (popcount(pos.occupied()) - 1) / 4;
  return bucket < 0 ? 0
         : bucket >= NNUE_PSQT_BUCKETS ? NNUE_PSQT_BUCKETS - 1
                                       : bucket;
}

// Full evaluation in centipawns from the side-to-move's point of view.
int nnue_evaluate(const NnueNet& net, const Position& pos);

// Incremental-evaluation cache: the previously evaluated position's
// piece placement and COLOR-INDEXED (white=0/black=1, not stm-relative)
// feature-transformer + PSQT accumulators. Consecutive evals in a
// depth-first search are usually one or two moves apart, so the next
// accumulator is the cached one plus a handful of row adds/subtracts —
// the host-side twin of the device batch's delta entries, and exactly
// as bit-exact (integer adds commute). A moved king rebases every
// feature of that color's perspective (HalfKA king buckets/mirroring),
// so such evals rebuild that perspective in full.
struct NnueEvalCache {
  uint64_t net_uid = 0;  // 0 = invalid (uids start at 1)
  int8_t piece_on[64];
  Square ksq[COLOR_NB];
  int32_t acc[COLOR_NB][NNUE_L1];
  int32_t psqt[COLOR_NB][NNUE_PSQT_BUCKETS];
};

// nnue_evaluate through a caller-owned incremental cache. Bit-identical
// to nnue_evaluate for every position (verified by tests over random
// game sequences including castling, promotions, en passant).
int nnue_evaluate_cached(const NnueNet& net, const Position& pos,
                         NnueEvalCache& cache);

// Does this net's eval track material? Probes a handful of fixed
// positions with one side's queen/rook deleted and checks the eval
// moves the way material says it must. Real nets (trained on search
// scores) always pass; random test nets essentially never do. Search
// uses this to decide whether SEE-based capture demotion and qsearch
// SEE pruning are sound for the loaded net — those heuristics assume
// exchanges that lose material lose eval, and enabling them under a
// material-blind net was measured to cost ~35% tree size (the pruned
// captures' subtrees are the cheap ones to search).
bool nnue_material_correlated(const NnueNet& net);

}  // namespace fc
