#include "nnue.h"

#include <cstring>
#include <fstream>

namespace fc {

namespace {

constexpr uint32_t FILE_VERSION = 0x7AF32F20;

int32_t clamp32(int32_t v, int32_t lo, int32_t hi) {
  return v < lo ? lo : (v > hi ? hi : v);
}

}  // namespace

std::string NnueNet::load(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return "cannot open " + path;

  auto read_u32 = [&](uint32_t& out) -> bool {
    return bool(f.read(reinterpret_cast<char*>(&out), 4));
  };
  auto read_vec = [&](auto& vec, size_t count) -> bool {
    vec.resize(count);
    using T = typename std::remove_reference_t<decltype(vec)>::value_type;
    return bool(f.read(reinterpret_cast<char*>(vec.data()), count * sizeof(T)));
  };

  uint32_t version, arch_hash, desc_len;
  if (!read_u32(version) || !read_u32(arch_hash) || !read_u32(desc_len))
    return "truncated header";
  if (version != FILE_VERSION) return "unsupported version";
  if (arch_hash != 0x3E5AA6EEu) return "wrong architecture hash";
  f.seekg(desc_len, std::ios::cur);

  uint32_t section_hash;
  if (!read_u32(section_hash)) return "truncated ft hash";
  if (!read_vec(ft_bias, NNUE_L1)) return "truncated ft bias";
  if (!read_vec(ft_weight, size_t(NNUE_FEATURES) * NNUE_L1)) return "truncated ft weight";
  if (!read_vec(ft_psqt, size_t(NNUE_FEATURES) * NNUE_PSQT_BUCKETS))
    return "truncated ft psqt";

  l1_weight.resize(size_t(NNUE_PSQT_BUCKETS) * (NNUE_L2 + 1) * NNUE_L1);
  l1_bias.resize(size_t(NNUE_PSQT_BUCKETS) * (NNUE_L2 + 1));
  l2_weight.resize(size_t(NNUE_PSQT_BUCKETS) * NNUE_L3 * 2 * NNUE_L2);
  l2_bias.resize(size_t(NNUE_PSQT_BUCKETS) * NNUE_L3);
  out_weight.resize(size_t(NNUE_PSQT_BUCKETS) * NNUE_L3);
  out_bias.resize(NNUE_PSQT_BUCKETS);

  for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) {
    if (!read_u32(section_hash)) return "truncated stack hash";
    if (!f.read(reinterpret_cast<char*>(&l1_bias[b * (NNUE_L2 + 1)]),
                (NNUE_L2 + 1) * 4))
      return "truncated l1 bias";
    if (!f.read(reinterpret_cast<char*>(&l1_weight[size_t(b) * (NNUE_L2 + 1) * NNUE_L1]),
                (NNUE_L2 + 1) * NNUE_L1))
      return "truncated l1 weight";
    if (!f.read(reinterpret_cast<char*>(&l2_bias[b * NNUE_L3]), NNUE_L3 * 4))
      return "truncated l2 bias";
    // l2 rows are serialized over inputs padded to 32 (SF convention);
    // drop the zero pad columns while reading.
    for (int r = 0; r < NNUE_L3; r++) {
      char padded[32];
      static_assert(2 * NNUE_L2 <= 32, "l2 padded width");
      if (!f.read(padded, 32)) return "truncated l2 weight";
      memcpy(&l2_weight[size_t(b) * NNUE_L3 * 2 * NNUE_L2 + size_t(r) * 2 * NNUE_L2],
             padded, 2 * NNUE_L2);
    }
    if (!f.read(reinterpret_cast<char*>(&out_bias[b]), 4)) return "truncated out bias";
    if (!f.read(reinterpret_cast<char*>(&out_weight[b * NNUE_L3]), NNUE_L3))
      return "truncated out weight";
  }
  return "";
}

template <typename T>
int nnue_features(const Position& pos, Color perspective, T* out) {
  Square ksq = pos.king_sq(perspective);
  int n = 0;
  Bitboard occ = pos.occupied();
  while (occ) {
    Square s = pop_lsb(occ);
    out[n++] = T(nnue_feature_index(ksq, perspective, pos.piece_on(s), s));
  }
  return n;
}

template int nnue_features<int32_t>(const Position&, Color, int32_t*);
template int nnue_features<uint16_t>(const Position&, Color, uint16_t*);

namespace {

// Rebuild one color's perspective accumulator + PSQT from scratch.
// The gather is MEMORY-latency bound, not ALU bound (the adds all
// vectorize to AVX-512; ~30 random 2 KB rows of a 46 MB table are
// ~30 cold-miss streams per perspective — the host-side twin of the
// device kernel's DMA-count bound). Prefetch every FOURTH cache
// line of the next row while accumulating the current one: enough
// to prime the hardware stream prefetcher for the lines between,
// without flooding the prefetch queue (measured 17.4 -> 4.3 us/eval;
// a full every-line prefetch measured ~4.8 us — queue pressure).
void rebuild_perspective(const NnueNet& net, const Position& pos, Color c,
                         int32_t* acc, int32_t* psqt) {
  int32_t feats[NNUE_MAX_ACTIVE];
  int n = nnue_features(pos, c, feats);
  for (int i = 0; i < NNUE_L1; i++) acc[i] = net.ft_bias[i];
  for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) psqt[b] = 0;
  for (int j = 0; j < n; j++) {
    if (j + 1 < n) {
      const char* nxt = reinterpret_cast<const char*>(
          &net.ft_weight[size_t(feats[j + 1]) * NNUE_L1]);
      for (int l = 0; l < int(NNUE_L1 * sizeof(int16_t)); l += 256)
        __builtin_prefetch(nxt + l);
      __builtin_prefetch(&net.ft_psqt[size_t(feats[j + 1]) * NNUE_PSQT_BUCKETS]);
    }
    const int16_t* row = &net.ft_weight[size_t(feats[j]) * NNUE_L1];
    for (int i = 0; i < NNUE_L1; i++) acc[i] += row[i];
    const int32_t* prow = &net.ft_psqt[size_t(feats[j]) * NNUE_PSQT_BUCKETS];
    for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) psqt[b] += prow[b];
  }
}

// Apply one feature row to a perspective accumulator, signed.
void apply_row(const NnueNet& net, int idx, int sign, int32_t* acc,
               int32_t* psqt) {
  const int16_t* row = &net.ft_weight[size_t(idx) * NNUE_L1];
  const int32_t* prow = &net.ft_psqt[size_t(idx) * NNUE_PSQT_BUCKETS];
  if (sign > 0) {
    for (int i = 0; i < NNUE_L1; i++) acc[i] += row[i];
    for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) psqt[b] += prow[b];
  } else {
    for (int i = 0; i < NNUE_L1; i++) acc[i] -= row[i];
    for (int b = 0; b < NNUE_PSQT_BUCKETS; b++) psqt[b] -= prow[b];
  }
}

// The dense tail shared by the fresh and cached paths: clipped pairwise
// multiply over the stm-ordered accumulators, then the layer stacks and
// the material term.
int eval_tail(const NnueNet& net, const Position& pos,
              const int32_t* acc_stm, const int32_t* acc_opp,
              const int32_t* psqt_stm, const int32_t* psqt_opp) {
  const int32_t* accs[COLOR_NB] = {acc_stm, acc_opp};
  uint8_t x[NNUE_L1];
  for (int p = 0; p < COLOR_NB; p++) {
    for (int i = 0; i < NNUE_L1_HALF; i++) {
      int32_t a = clamp32(accs[p][i], 0, 127);
      int32_t b = clamp32(accs[p][i + NNUE_L1_HALF], 0, 127);
      x[p * NNUE_L1_HALF + i] = uint8_t((a * b) >> 7);
    }
  }

  int bucket = nnue_psqt_bucket(pos);

  // l1: 1024 -> 16
  int32_t y[NNUE_L2 + 1];
  for (int o = 0; o < NNUE_L2 + 1; o++) {
    const int8_t* row =
        &net.l1_weight[(size_t(bucket) * (NNUE_L2 + 1) + o) * NNUE_L1];
    int32_t sum = net.l1_bias[bucket * (NNUE_L2 + 1) + o];
    for (int i = 0; i < NNUE_L1; i++) sum += int32_t(row[i]) * x[i];
    y[o] = sum;
  }
  int32_t skip = y[NNUE_L2];

  // Activations: squared-clipped then clipped, concatenated (30 values).
  int32_t act[2 * NNUE_L2];
  for (int o = 0; o < NNUE_L2; o++) {
    int64_t sq = (int64_t(y[o]) * y[o]) >> 19;
    act[o] = int32_t(sq > 127 ? 127 : sq);
    act[NNUE_L2 + o] = clamp32(y[o] >> 6, 0, 127);
  }

  // l2: 30 -> 32
  int32_t z[NNUE_L3];
  for (int o = 0; o < NNUE_L3; o++) {
    const int8_t* row =
        &net.l2_weight[(size_t(bucket) * NNUE_L3 + o) * 2 * NNUE_L2];
    int32_t sum = net.l2_bias[bucket * NNUE_L3 + o];
    for (int i = 0; i < 2 * NNUE_L2; i++) sum += int32_t(row[i]) * act[i];
    z[o] = clamp32(sum >> 6, 0, 127);
  }

  // out: 32 -> 1
  const int8_t* orow = &net.out_weight[size_t(bucket) * NNUE_L3];
  int32_t v = net.out_bias[bucket];
  for (int i = 0; i < NNUE_L3; i++) v += int32_t(orow[i]) * z[i];

  int32_t material = (psqt_stm[bucket] - psqt_opp[bucket]) / 2;
  // skip * 9600 / 8128, reduced to stay within int32 (= skip + skip*23/127;
  // exact under C truncation since skip*8128/8128 has no remainder).
  int32_t positional = v + skip + (skip * 23) / 127;
  return (positional + material) / 16;
}

}  // namespace

int nnue_evaluate(const NnueNet& net, const Position& pos) {
  int32_t acc[COLOR_NB][NNUE_L1];
  int32_t psqt[COLOR_NB][NNUE_PSQT_BUCKETS];
  Color stm = pos.stm;
  rebuild_perspective(net, pos, stm, acc[0], psqt[0]);
  rebuild_perspective(net, pos, ~stm, acc[1], psqt[1]);
  return eval_tail(net, pos, acc[0], acc[1], psqt[0], psqt[1]);
}

int nnue_evaluate_cached(const NnueNet& net, const Position& pos,
                         NnueEvalCache& cache) {
  int8_t cur[64];
  for (int s = 0; s < 64; s++) cur[s] = int8_t(pos.piece_on(Square(s)));
  Square ks[COLOR_NB] = {pos.king_sq(WHITE), pos.king_sq(BLACK)};

  if (cache.net_uid == net.uid) {
    // Piece diff vs the cached position. Consecutive evals in a
    // depth-first search are usually 1-2 moves apart: 2-6 touched
    // squares. Beyond MAX_DIFF a rebuild is no slower than the deltas.
    // INVARIANT TWIN: cpp/src/pool.cpp fill_delta encodes the same
    // rules for the DEVICE delta path (64-square before/after diff,
    // remove-then-add via nnue_feature_index, own-king-moved => full
    // rebuild of that perspective, diff-cap => rebuild) — a change to
    // either must be mirrored in the other, and the cached-vs-fresh
    // parity test plus the scalar-vs-jax search parity suites fail if
    // they drift.
    constexpr int MAX_DIFF = 8;
    int dsq[MAX_DIFF];
    int nd = 0;
    bool too_many = false;
    for (int s = 0; s < 64 && !too_many; s++) {
      if (cur[s] == cache.piece_on[s]) continue;
      if (nd >= MAX_DIFF) {
        too_many = true;
        break;
      }
      dsq[nd++] = s;
    }
    for (int c = 0; c < COLOR_NB; c++) {
      if (too_many || ks[c] != cache.ksq[c]) {
        // An own-king move rebases every feature of this perspective
        // (king buckets + mirroring): rebuild. The OPPONENT's king
        // moving is just a piece diff here, handled below.
        rebuild_perspective(net, pos, Color(c), cache.acc[c], cache.psqt[c]);
        continue;
      }
      for (int d = 0; d < nd; d++) {
        Square s = Square(dsq[d]);
        int before = cache.piece_on[s];
        int after = cur[s];
        if (before != NO_PIECE)
          apply_row(net, nnue_feature_index(ks[c], Color(c), before, s), -1,
                    cache.acc[c], cache.psqt[c]);
        if (after != NO_PIECE)
          apply_row(net, nnue_feature_index(ks[c], Color(c), after, s), +1,
                    cache.acc[c], cache.psqt[c]);
      }
    }
  } else {
    rebuild_perspective(net, pos, WHITE, cache.acc[WHITE], cache.psqt[WHITE]);
    rebuild_perspective(net, pos, BLACK, cache.acc[BLACK], cache.psqt[BLACK]);
  }
  memcpy(cache.piece_on, cur, sizeof(cur));
  cache.ksq[WHITE] = ks[WHITE];
  cache.ksq[BLACK] = ks[BLACK];
  cache.net_uid = net.uid;

  Color stm = pos.stm;
  return eval_tail(net, pos, cache.acc[stm], cache.acc[~stm],
                   cache.psqt[stm], cache.psqt[~stm]);
}

bool nnue_material_correlated(const NnueNet& net) {
  // Fixed probe pairs: (base, base with one major piece deleted, sign).
  // sign +1 means the mutated position must evaluate LOWER for white
  // (white lost the piece) by >= margin; -1 means higher (black lost
  // it). All four must hold — a material-blind (random) net passes the
  // joint test with only a few percent probability, while any net
  // trained on search scores clears a queen/rook margin by hundreds of
  // centipawns.
  struct Probe {
    const char* base;
    const char* mutated;
    int sign;
  };
  static const Probe kProbes[] = {
      {"r1bqk2r/ppp2ppp/2np1n2/2b1p3/2B1P3/2PP1N2/PP3PPP/RNBQK2R w KQkq - 0 6",
       "r1bqk2r/ppp2ppp/2np1n2/2b1p3/2B1P3/2PP1N2/PP3PPP/RNB1K2R w KQkq - 0 6",
       +1},
      {"r1bqk2r/ppp2ppp/2np1n2/2b1p3/2B1P3/2PP1N2/PP3PPP/RNBQK2R w KQkq - 0 6",
       "r1b1k2r/ppp2ppp/2np1n2/2b1p3/2B1P3/2PP1N2/PP3PPP/RNBQK2R w KQkq - 0 6",
       -1},
      {"4k3/8/8/8/8/8/4P3/R3K3 w - - 0 1",
       "4k3/8/8/8/8/8/4P3/4K3 w - - 0 1", +1},
      {"3qk3/8/8/8/8/8/8/3QK3 w - - 0 1",
       "3qk3/8/8/8/8/8/8/4K3 w - - 0 1", +1},
  };
  constexpr int kMargin = 150;
  for (const Probe& p : kProbes) {
    Position base, mutated;
    if (!base.set_fen(p.base, VR_STANDARD).empty()) return false;
    if (!mutated.set_fen(p.mutated, VR_STANDARD).empty()) return false;
    // Both probes are white to move; evals are stm (= white) relative.
    int delta = nnue_evaluate(net, base) - nnue_evaluate(net, mutated);
    if (p.sign * delta < kMargin) return false;
  }
  return true;
}

}  // namespace fc
