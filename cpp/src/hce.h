// Hand-crafted (classical) evaluation for chess variants.
//
// The reference routes all variant analysis and every best-move job to
// Fairy-Stockfish, whose evaluation for these variants is classical HCE
// rather than NNUE (reference: src/assets.rs:384-391 maps the
// MultiVariant flavor to EvalFlavor::Hce, src/queue.rs:530-539 routes
// variants there). This is the TPU framework's equivalent: a fast scalar
// centipawn eval with per-variant objective terms, serving the same
// alpha-beta searcher the NNUE path uses. It stays on the host CPU by
// design — at ~100 ns/position a device round-trip could never pay for
// itself, exactly why the reference keeps HCE on CPU too.

#pragma once

#include "position.h"

namespace fc {

// Static evaluation in centipawns from the side to move's perspective.
// Safe on any variant position, including kingless ones (antichess,
// horde, exploded atomic kings).
int hce_evaluate(const Position& pos);

}  // namespace fc
