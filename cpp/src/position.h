// Board state, FEN, move generation, make-move.
//
// Replaces the rules functionality the reference gets from shakmaty
// (legality replay, src/queue.rs:543-552) and from the engines' own
// movegen. Chess960 is handled natively: castling rights are stored as
// rook squares and castling moves are encoded king-from -> rook-from,
// matching UCI_Chess960 notation (the reference always enables
// UCI_Chess960, src/stockfish.rs:212-214).
//
// Search uses copy-make: Position is a flat value type (~200 bytes),
// make() mutates in place, callers copy first.

#pragma once

#include <string>
#include <vector>

#include "bitboard.h"
#include "types.h"

namespace fc {

void init_zobrist();

constexpr int MAX_MOVES = 256;

struct MoveList {
  Move moves[MAX_MOVES];
  int size = 0;
  void push(Move m) {
    if (size < MAX_MOVES) moves[size++] = m;
  }
  const Move* begin() const { return moves; }
  const Move* end() const { return moves + size; }
};

struct Position {
  Bitboard by_color[COLOR_NB] = {0, 0};
  Bitboard by_type[PIECE_TYPE_NB] = {0, 0, 0, 0, 0, 0};
  uint8_t board[64];
  Color stm = WHITE;
  Bitboard castling_rooks = 0;  // rook squares that still have rights
  Square ep_square = SQ_NONE;   // only set when an en-passant capture is legal
  int halfmove = 0;
  int fullmove = 1;
  uint64_t hash = 0;
  VariantRules variant = VR_STANDARD;
  uint8_t checks_given[COLOR_NB] = {0, 0};      // three-check
  uint8_t hand[COLOR_NB][PIECE_TYPE_NB] = {};   // crazyhouse pockets
  Bitboard promoted = 0;                        // crazyhouse: promoted pieces

  // -- accessors --------------------------------------------------------
  Bitboard occupied() const { return by_color[WHITE] | by_color[BLACK]; }
  Bitboard pieces(Color c) const { return by_color[c]; }
  Bitboard pieces(PieceType pt) const { return by_type[pt]; }
  Bitboard pieces(Color c, PieceType pt) const { return by_color[c] & by_type[pt]; }
  int piece_on(Square s) const { return board[s]; }
  bool empty(Square s) const { return board[s] == NO_PIECE; }
  Square king_sq(Color c) const {
    Bitboard k = pieces(c, KING);
    return k ? lsb(k) : SQ_NONE;
  }

  // All attackers (both colors) of square s given occupancy occ.
  Bitboard attackers_to(Square s, Bitboard occ) const;
  bool attacked_by(Square s, Color by, Bitboard occ) const {
    return attackers_to(s, occ) & by_color[by];
  }
  Bitboard checkers() const {
    Square k = king_sq(stm);
    return k == SQ_NONE ? 0 : attackers_to(k, occupied()) & by_color[~stm];
  }
  bool in_check() const { return checkers() != 0; }
  bool kings_adjacent() const {
    Bitboard wk = pieces(WHITE, KING), bk = pieces(BLACK, KING);
    return wk && bk && (KING_ATTACKS[lsb(wk)] & bk);
  }
  // Check for rules purposes. In atomic chess adjacent kings annul check
  // (capturing the king would explode the capturer's own king).
  bool effective_check() const {
    if (variant == VR_ATOMIC && kings_adjacent()) return false;
    return in_check();
  }
  // Variant-terminal test that needs no move generation, usable at every
  // search node. Returns true when the game is over by variant rule;
  // res = +1 win for stm, -1 loss for stm, 0 draw.
  bool variant_terminal(int& res) const;

  // -- setup ------------------------------------------------------------
  // Returns empty string on success, error message otherwise.
  std::string set_fen(const std::string& fen, VariantRules variant);
  std::string fen() const;

  // -- moves ------------------------------------------------------------
  void gen_pseudo(MoveList& out) const;
  bool is_legal(Move m) const;  // pseudo-legal -> fully legal
  void legal_moves(MoveList& out) const;
  void make(Move m);

  // Null move (pass), for null-move pruning in search. Keeps hash/ep
  // bookkeeping consistent; not a legal chess move.
  void make_null();

  std::string uci(Move m) const;
  // Parse a UCI move against this position. Accepts both Chess960
  // (king-takes-rook, e1h1) and standard (e1g1) castling notation, like
  // shakmaty's Uci::to_move does for the reference. MOVE_NONE if illegal.
  Move parse_uci(const std::string& str) const;

  // 0 = ongoing, 1 = checkmate (stm is mated), 2 = stalemate,
  // 3 = variant loss for stm, 4 = variant win for stm, 5 = draw.
  int outcome() const;

  uint64_t compute_hash() const;

 private:
  void put_piece(Square s, int pc);
  void remove_piece(Square s);
  void gen_castling(MoveList& out) const;
  bool castle_path_ok(Square kfrom, Square rfrom) const;
  bool ep_capture_legal() const;  // any fully legal ep capture exists?
};

uint64_t perft(const Position& pos, int depth);

}  // namespace fc
