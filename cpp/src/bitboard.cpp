#include "bitboard.h"

#include <cstring>
#include <vector>

#ifdef __BMI2__
#include <immintrin.h>
#endif

namespace fc {

Bitboard KNIGHT_ATTACKS[64];
Bitboard KING_ATTACKS[64];
Bitboard PAWN_ATTACKS[COLOR_NB][64];
Bitboard BETWEEN[64][64];
Bitboard LINE[64][64];

namespace {

// Step a square by (df, dr); SQ_NONE if off board.
Square step(Square s, int df, int dr) {
  int f = file_of(s) + df, r = rank_of(s) + dr;
  if (f < 0 || f > 7 || r < 0 || r > 7) return SQ_NONE;
  return make_square(f, r);
}

Bitboard ray_attacks(Square s, Bitboard occ, const int dirs[4][2]) {
  Bitboard attacks = 0;
  for (int d = 0; d < 4; d++) {
    Square cur = s;
    while (true) {
      cur = step(cur, dirs[d][0], dirs[d][1]);
      if (cur == SQ_NONE) break;
      attacks |= bb(cur);
      if (occ & bb(cur)) break;
    }
  }
  return attacks;
}

const int ROOK_DIRS[4][2] = {{1, 0}, {-1, 0}, {0, 1}, {0, -1}};
const int BISHOP_DIRS[4][2] = {{1, 1}, {1, -1}, {-1, 1}, {-1, -1}};

Bitboard slow_rook(Square s, Bitboard occ) { return ray_attacks(s, occ, ROOK_DIRS); }
Bitboard slow_bishop(Square s, Bitboard occ) { return ray_attacks(s, occ, BISHOP_DIRS); }

#ifdef __BMI2__
// PEXT tables: per square, relevant-occupancy mask and a dense table indexed
// by _pext_u64(occ, mask).
struct PextEntry {
  Bitboard mask;
  const Bitboard* table;
};

PextEntry ROOK_PEXT[64];
PextEntry BISHOP_PEXT[64];
std::vector<Bitboard> PEXT_STORAGE;

Bitboard relevant_mask(Square s, bool rook) {
  // Attacks on an empty board, minus board-edge squares (a blocker on the
  // edge can't shadow anything further).
  Bitboard edges = ((RANK_1_BB | rank_bb(7)) & ~rank_bb(rank_of(s))) |
                   ((FILE_A_BB | file_bb(7)) & ~file_bb(file_of(s)));
  Bitboard att = rook ? slow_rook(s, 0) : slow_bishop(s, 0);
  return att & ~edges;
}

void init_pext() {
  // Total table size: sum over squares of 2^popcount(mask):
  // rooks 102400 + bishops 5248 entries.
  size_t total = 0;
  for (int rook = 0; rook < 2; rook++)
    for (Square s = 0; s < 64; s++)
      total += 1ULL << popcount(relevant_mask(s, rook));
  PEXT_STORAGE.resize(total);

  size_t offset = 0;
  for (int rook = 0; rook < 2; rook++) {
    for (Square s = 0; s < 64; s++) {
      Bitboard mask = relevant_mask(s, rook);
      PextEntry& e = (rook ? ROOK_PEXT : BISHOP_PEXT)[s];
      e.mask = mask;
      e.table = &PEXT_STORAGE[offset];
      // Enumerate all subsets of mask (Carry-Rippler iteration).
      Bitboard sub = 0;
      do {
        PEXT_STORAGE[offset + _pext_u64(sub, mask)] =
            rook ? slow_rook(s, sub) : slow_bishop(s, sub);
        sub = (sub - mask) & mask;
      } while (sub);
      offset += 1ULL << popcount(mask);
    }
  }
}
#endif  // __BMI2__

}  // namespace

Bitboard rook_attacks(Square s, Bitboard occ) {
#ifdef __BMI2__
  const auto& e = ROOK_PEXT[s];
  return e.table[_pext_u64(occ, e.mask)];
#else
  return slow_rook(s, occ);
#endif
}

Bitboard bishop_attacks(Square s, Bitboard occ) {
#ifdef __BMI2__
  const auto& e = BISHOP_PEXT[s];
  return e.table[_pext_u64(occ, e.mask)];
#else
  return slow_bishop(s, occ);
#endif
}

void init_bitboards() {
  static bool done = false;
  if (done) return;
  done = true;

  const int knight_steps[8][2] = {{1, 2},  {2, 1},  {2, -1}, {1, -2},
                                  {-1, -2}, {-2, -1}, {-2, 1}, {-1, 2}};
  const int king_steps[8][2] = {{1, 0},  {1, 1},  {0, 1},  {-1, 1},
                                {-1, 0}, {-1, -1}, {0, -1}, {1, -1}};

  for (Square s = 0; s < 64; s++) {
    KNIGHT_ATTACKS[s] = 0;
    KING_ATTACKS[s] = 0;
    for (auto& st : knight_steps)
      if (Square t = step(s, st[0], st[1]); t != SQ_NONE) KNIGHT_ATTACKS[s] |= bb(t);
    for (auto& st : king_steps)
      if (Square t = step(s, st[0], st[1]); t != SQ_NONE) KING_ATTACKS[s] |= bb(t);

    PAWN_ATTACKS[WHITE][s] = 0;
    PAWN_ATTACKS[BLACK][s] = 0;
    if (Square t = step(s, 1, 1); t != SQ_NONE) PAWN_ATTACKS[WHITE][s] |= bb(t);
    if (Square t = step(s, -1, 1); t != SQ_NONE) PAWN_ATTACKS[WHITE][s] |= bb(t);
    if (Square t = step(s, 1, -1); t != SQ_NONE) PAWN_ATTACKS[BLACK][s] |= bb(t);
    if (Square t = step(s, -1, -1); t != SQ_NONE) PAWN_ATTACKS[BLACK][s] |= bb(t);
  }

#ifdef __BMI2__
  init_pext();
#endif

  // BETWEEN / LINE tables from slider geometry.
  for (Square a = 0; a < 64; a++) {
    for (Square b = 0; b < 64; b++) {
      BETWEEN[a][b] = 0;
      LINE[a][b] = 0;
      if (a == b) continue;
      if (slow_rook(a, 0) & bb(b)) {
        BETWEEN[a][b] = slow_rook(a, bb(b)) & slow_rook(b, bb(a));
        LINE[a][b] = (slow_rook(a, 0) & slow_rook(b, 0)) | bb(a) | bb(b);
      } else if (slow_bishop(a, 0) & bb(b)) {
        BETWEEN[a][b] = slow_bishop(a, bb(b)) & slow_bishop(b, bb(a));
        LINE[a][b] = (slow_bishop(a, 0) & slow_bishop(b, 0)) | bb(a) | bb(b);
      }
    }
  }
}

}  // namespace fc
