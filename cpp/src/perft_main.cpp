// Standalone perft runner: ./perft [depth] ["fen"] — prints node count,
// or runs the built-in validation suite with no args.

#include <chrono>
#include <cstdio>
#include <cstring>

#include "position.h"

using namespace fc;

struct Case {
  const char* name;
  const char* fen;
  int depth;
  uint64_t nodes;
  VariantRules variant = VR_STANDARD;
};

static VariantRules variant_by_name(const char* name) {
  if (!strcmp(name, "antichess")) return VR_ANTICHESS;
  if (!strcmp(name, "atomic")) return VR_ATOMIC;
  if (!strcmp(name, "crazyhouse")) return VR_CRAZYHOUSE;
  if (!strcmp(name, "horde")) return VR_HORDE;
  if (!strcmp(name, "kingofthehill")) return VR_KING_OF_THE_HILL;
  if (!strcmp(name, "racingkings")) return VR_RACING_KINGS;
  if (!strcmp(name, "3check")) return VR_THREE_CHECK;
  return VR_STANDARD;
}

// Standard perft suite (positions and counts are community-standard test
// vectors, e.g. from the chessprogramming wiki perft results page).
static const Case SUITE[] = {
    {"startpos d5", "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1", 5,
     4865609ULL},
    {"kiwipete d4",
     "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1", 4,
     4085603ULL},
    {"endgame d6", "8/2p5/3p4/KP5r/1R3p1k/8/4P1P1/8 w - - 0 1", 6, 11030083ULL},
    {"promo d5", "r3k2r/Pppp1ppp/1b3nbN/nP6/BBP1P3/q4N2/Pp1P2PP/R2Q1RK1 w kq - 0 1",
     5, 15833292ULL},
    {"pos5 d4", "rnbq1k1r/pp1Pbppp/2p5/8/2B5/8/PPP1NnPP/RNBQK2R w KQ - 1 8", 4,
     2103487ULL},
    {"pos6 d4",
     "r4rk1/1pp1qppp/p1np1n2/2b1p1B1/2B1P1b1/P1NP1N2/1PP1QPPP/R4RK1 w - - 0 10", 4,
     3894594ULL},
    // Variant start positions; expected counts are Fairy-Stockfish's
    // published perft test vectors for the matching lichess rules.
    {"antichess d5", "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w - - 0 1", 5,
     2732672ULL, VR_ANTICHESS},
    {"atomic d5", "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1", 5,
     4864979ULL, VR_ATOMIC},
    {"crazyhouse d5", "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR[] w KQkq - 0 1", 5,
     4888832ULL, VR_CRAZYHOUSE},
    {"horde d6",
     "rnbqkbnr/pppppppp/8/1PP2PP1/PPPPPPPP/PPPPPPPP/PPPPPPPP/PPPPPPPP w kq - 0 1", 6,
     5396554ULL, VR_HORDE},
    {"racingkings d5", "8/8/8/8/8/8/krbnNBRK/qrbnNBRQ w - - 0 1", 5, 9472927ULL,
     VR_RACING_KINGS},
    {"3check d5", "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 3+3 0 1", 5,
     4865609ULL, VR_THREE_CHECK},
    {"koth d5", "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1", 5,
     4865609ULL, VR_KING_OF_THE_HILL},
};

int main(int argc, char** argv) {
  init_bitboards();
  init_zobrist();

  if (argc >= 2) {
    int depth = atoi(argv[1]);
    const char* fen = argc >= 3 ? argv[2]
                                : "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1";
    VariantRules var = argc >= 4 ? variant_by_name(argv[3]) : VR_STANDARD;
    Position pos;
    std::string err = pos.set_fen(fen, var);
    if (!err.empty()) {
      fprintf(stderr, "bad fen: %s\n", err.c_str());
      return 1;
    }
    auto t0 = std::chrono::steady_clock::now();
    uint64_t nodes = perft(pos, depth);
    auto dt = std::chrono::duration<double>(std::chrono::steady_clock::now() - t0).count();
    printf("perft(%d) = %llu  (%.2fs, %.1f Mnps)\n", depth, (unsigned long long)nodes,
           dt, nodes / dt / 1e6);
    return 0;
  }

  int failures = 0;
  for (const Case& c : SUITE) {
    Position pos;
    std::string err = pos.set_fen(c.fen, c.variant);
    if (!err.empty()) {
      printf("FAIL %-12s bad fen: %s\n", c.name, err.c_str());
      failures++;
      continue;
    }
    auto t0 = std::chrono::steady_clock::now();
    uint64_t nodes = perft(pos, c.depth);
    auto dt = std::chrono::duration<double>(std::chrono::steady_clock::now() - t0).count();
    bool ok = nodes == c.nodes;
    printf("%s %-12s got %llu want %llu  (%.2fs, %.1f Mnps)\n", ok ? "ok  " : "FAIL",
           c.name, (unsigned long long)nodes, (unsigned long long)c.nodes, dt,
           nodes / dt / 1e6);
    failures += !ok;
  }
  return failures ? 1 : 0;
}
