"""Config layer tests: scalar parsers, CLI, ini merge precedence, the
interactive dialog, systemd unit generation, and the auto-update hook.

The reference has no tests; behavior is pinned against
src/configure.rs / src/systemd.rs / src/main.rs:440-464 semantics."""

import io
import json
import asyncio

import pytest

from fishnet_tpu import configure as cfg
from fishnet_tpu import systemd as systemd_mod
from fishnet_tpu import update as update_mod


# -- scalar parsers ---------------------------------------------------------


def test_parse_duration():
    assert cfg.parse_duration("90s") == 90.0
    assert cfg.parse_duration("90") == 90.0
    assert cfg.parse_duration("2h") == 7200.0
    assert cfg.parse_duration("1d") == 86400.0
    assert cfg.parse_duration("3m") == 180.0
    assert cfg.parse_duration("500ms") == 0.5
    assert cfg.parse_duration(" 5 s".replace(" s", "s")) == 5.0
    with pytest.raises(cfg.ConfigError):
        cfg.parse_duration("abc")
    with pytest.raises(cfg.ConfigError):
        cfg.parse_duration("1.5h")  # reference parses integers only


def test_parse_backlog():
    assert cfg.parse_backlog("short") == 30.0
    assert cfg.parse_backlog("long") == 3600.0
    assert cfg.parse_backlog("120s") == 120.0
    assert cfg.parse_backlog("0") == 0.0


def test_parse_mesh():
    assert cfg.parse_mesh("auto") == "auto"
    assert cfg.parse_mesh("off") == "off"
    assert cfg.parse_mesh("4x2") == "4x2"
    assert cfg.parse_mesh(" 8X1 ") == "8x1"
    for bad in ("", "4x0", "0x2", "4x2x1", "four", "4*2"):
        with pytest.raises(cfg.ConfigError):
            cfg.parse_mesh(bad)
    opt = cfg.Opt()
    assert opt.resolved_mesh() == "auto"
    opt.mesh = "off"
    assert opt.resolved_mesh() == "off"


def test_parse_key():
    assert cfg.parse_key("abcDEF123") == "abcDEF123"
    with pytest.raises(cfg.ConfigError):
        cfg.parse_key("")
    with pytest.raises(cfg.ConfigError):
        cfg.parse_key("no spaces")
    with pytest.raises(cfg.ConfigError):
        cfg.parse_key("ünïcode")


def test_parse_endpoint():
    assert cfg.parse_endpoint("https://lichess.org/fishnet/") == "https://lichess.org/fishnet"
    assert not cfg.endpoint_is_development("https://lichess.org/fishnet")
    assert cfg.endpoint_is_development("http://localhost:9999/fishnet")
    with pytest.raises(cfg.ConfigError):
        cfg.parse_endpoint("not a url")


def test_cores():
    assert cfg.parse_cores("auto") == "auto"
    assert cfg.parse_cores("max") == "all"
    assert cfg.parse_cores("4") == "4"
    with pytest.raises(cfg.ConfigError):
        cfg.parse_cores("0")
    n = cfg.available_cores()
    assert cfg.resolve_cores("auto") == max(1, n - 1)
    assert cfg.resolve_cores("all") == n
    assert cfg.resolve_cores("3") == 3
    assert cfg.resolve_cores(None) == max(1, n - 1)


def test_parse_toggle():
    assert cfg.parse_toggle("yes") is True
    assert cfg.parse_toggle("NAY") is False
    assert cfg.parse_toggle("") is None
    with pytest.raises(cfg.ConfigError):
        cfg.parse_toggle("maybe")


# -- CLI --------------------------------------------------------------------


def test_cli_basic(tmp_path, monkeypatch):
    monkeypatch.setattr(cfg, "available_cores", lambda: 8)
    opt = cfg.parse_and_configure(
        ["run", "--no-conf", "-k", "k3y", "--cores", "2", "--max-backoff", "10s",
         "--user-backlog", "short", "--engine", "mock", "-vv"],
        output=io.StringIO(),
    )
    assert opt.command == "run"
    assert opt.key == "k3y"
    assert opt.resolved_cores() == 2
    assert opt.resolved_max_backoff() == 10.0
    assert opt.user_backlog == 30.0
    assert opt.resolved_engine() == "mock"
    assert opt.verbose == 2
    assert opt.resolved_endpoint() == cfg.DEFAULT_ENDPOINT


def test_cli_conflicts():
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(["--no-conf", "--key", "a", "--key-file", "x"], output=io.StringIO())
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(["--no-conf", "--stats-file", "a", "--no-stats-file"], output=io.StringIO())


def test_key_file(tmp_path):
    key_file = tmp_path / "key.txt"
    key_file.write_text("  secret123 \n")
    opt = cfg.parse_and_configure(
        ["run", "--no-conf", "--key-file", str(key_file)], output=io.StringIO()
    )
    assert opt.key == "secret123"


def test_core_cap_warning():
    out = io.StringIO()
    opt = cfg.parse_and_configure(
        ["run", "--no-conf", "--cores", str(cfg.available_cores() + 7)], output=out
    )
    assert opt.cores == "all"
    assert "Capped" in out.getvalue()


# -- ini merge --------------------------------------------------------------


def test_ini_merge_cli_wins(tmp_path, monkeypatch):
    monkeypatch.setattr(cfg, "available_cores", lambda: 8)
    conf = tmp_path / "fishnet.ini"
    conf.write_text(
        "[Fishnet]\nKey = inikey\nCores = 3\nEndpoint = http://dev.example/fishnet\n"
        "UserBacklog = long\nEngine = mock\n"
    )
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf), "--key", "clikey"], output=io.StringIO()
    )
    assert opt.key == "clikey"  # CLI wins
    assert opt.cores == "3"  # ini fills the rest
    assert opt.endpoint == "http://dev.example/fishnet"
    assert opt.user_backlog == 3600.0
    assert opt.resolved_engine() == "mock"


def test_ini_invalid_engine(tmp_path):
    conf = tmp_path / "fishnet.ini"
    conf.write_text("[Fishnet]\nEngine = gpu\n")
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(["run", "--conf", str(conf)], output=io.StringIO())


def test_metrics_port(tmp_path, monkeypatch):
    monkeypatch.setattr(cfg, "available_cores", lambda: 8)
    conf = tmp_path / "fishnet.ini"
    conf.write_text("[Fishnet]\nKey = k\nMetricsPort = 9187\n")
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf)], output=io.StringIO()
    )
    assert opt.metrics_port == 9187
    # CLI wins over ini; 0 (= ephemeral) must survive the merge.
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf), "--metrics-port", "0"],
        output=io.StringIO(),
    )
    assert opt.metrics_port == 0
    # Default: telemetry off.
    conf2 = tmp_path / "bare.ini"
    conf2.write_text("[Fishnet]\nKey = k\n")
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf2)], output=io.StringIO()
    )
    assert opt.metrics_port is None


def test_fault_plan_and_batch_deadline(tmp_path, monkeypatch):
    monkeypatch.setattr(cfg, "available_cores", lambda: 8)
    conf = tmp_path / "fishnet.ini"
    conf.write_text(
        "[Fishnet]\nKey = k\n"
        "FaultPlan = seed=1;net.acquire:nth=2:error\n"
        "BatchDeadline = 2m\n"
    )
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf)], output=io.StringIO()
    )
    assert opt.fault_plan == "seed=1;net.acquire:nth=2:error"
    assert opt.resolved_fault_plan() == opt.fault_plan
    assert opt.batch_deadline == 120.0
    # CLI wins over ini.
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf), "--fault-plan",
         "net.submit:p=0.1:latency=0.01", "--batch-deadline", "30s"],
        output=io.StringIO(),
    )
    assert opt.fault_plan == "net.submit:p=0.1:latency=0.01"
    assert opt.batch_deadline == 30.0
    # Defaults: both off; FISHNET_FAULT_PLAN is the env fallback.
    conf2 = tmp_path / "bare.ini"
    conf2.write_text("[Fishnet]\nKey = k\n")
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf2)], output=io.StringIO()
    )
    assert opt.fault_plan is None and opt.batch_deadline is None
    assert opt.resolved_fault_plan() is None
    monkeypatch.setenv("FISHNET_FAULT_PLAN", "queue.schedule:nth=1:error")
    assert opt.resolved_fault_plan() == "queue.schedule:nth=1:error"


def test_fault_plan_invalid(tmp_path):
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(
            ["run", "--no-conf", "--fault-plan", "nosuch.site:nth=1:error"],
            output=io.StringIO(),
        )
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(
            ["run", "--no-conf", "--batch-deadline", "0"],
            output=io.StringIO(),
        )


def test_metrics_port_invalid(tmp_path):
    conf = tmp_path / "fishnet.ini"
    conf.write_text("[Fishnet]\nKey = k\nMetricsPort = 70000\n")
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(["run", "--conf", str(conf)], output=io.StringIO())
    conf.write_text("[Fishnet]\nKey = k\nMetricsPort = web\n")
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(["run", "--conf", str(conf)], output=io.StringIO())


# -- dialog -----------------------------------------------------------------


def test_configure_dialog_writes_ini(tmp_path, monkeypatch):
    monkeypatch.setattr(cfg, "available_cores", lambda: 8)
    conf = tmp_path / "fishnet.ini"
    answers = iter([
        "badkey!!",   # invalid even with ! (second ! is not alnum)
        "mykey99!",   # accepted, force (no network check)
        "2",          # cores
        "yes",        # keep idle -> short/long backlog
    ])
    out = io.StringIO()
    opt = cfg.parse_and_configure(
        ["configure", "--conf", str(conf)],
        input_fn=lambda: next(answers),
        output=out,
        key_check=lambda e, k: "should not be called",
    )
    text = conf.read_text()
    assert "Key = mykey99" in text
    assert "Cores = 2" in text
    assert "UserBacklog = short" in text
    assert "SystemBacklog = long" in text
    # Merged back into the Opt:
    assert opt.key == "mykey99"
    assert opt.cores == "2"


def test_configure_dialog_key_check_rejects(tmp_path):
    conf = tmp_path / "fishnet.ini"
    attempts = []

    def key_check(endpoint, key):
        attempts.append(key)
        return "access denied" if key == "wrong" else None

    answers = iter(["wrong", "right1", "\n", "no"])
    cfg.parse_and_configure(
        ["configure", "--conf", str(conf), "--endpoint", "http://dev.example/f"],
        input_fn=lambda: next(answers),
        output=io.StringIO(),
        key_check=key_check,
    )
    assert attempts == ["wrong", "right1"]
    assert "Key = right1" in conf.read_text()


def test_dialog_dev_endpoint_key_optional(tmp_path):
    conf = tmp_path / "fishnet.ini"
    answers = iter(["\n", "\n", "no"])  # empty key is OK on a dev endpoint
    cfg.parse_and_configure(
        ["configure", "--conf", str(conf), "--endpoint", "http://localhost:1/f"],
        input_fn=lambda: next(answers),
        output=io.StringIO(),
    )
    assert "Key" not in conf.read_text().replace("UserBacklog", "")


def test_bare_invocation_triggers_first_run_dialog(tmp_path, monkeypatch):
    """No subcommand + no ini = first-run dialog (configure.rs:421-423);
    an explicit `run` skips it."""
    monkeypatch.setattr(cfg, "available_cores", lambda: 8)
    conf = tmp_path / "fishnet.ini"
    answers = iter(["devkey1!", "2", "no"])
    opt = cfg.parse_and_configure(
        ["--conf", str(conf)],
        input_fn=lambda: next(answers),
        output=io.StringIO(),
    )
    assert opt.command is None and opt.resolved_command() == "run"
    assert conf.exists() and opt.key == "devkey1"

    # Explicit run with no ini: no dialog, no prompts consumed.
    conf2 = tmp_path / "other.ini"
    opt = cfg.parse_and_configure(
        ["run", "--conf", str(conf2)],
        input_fn=lambda: (_ for _ in ()).throw(AssertionError("dialog ran")),
        output=io.StringIO(),
    )
    assert not conf2.exists()


def test_dialog_eof_raises(tmp_path):
    conf = tmp_path / "fishnet.ini"
    with pytest.raises(cfg.ConfigError):
        cfg.parse_and_configure(
            ["configure", "--conf", str(conf)],
            input_fn=lambda: "",  # closed stdin
            output=io.StringIO(),
        )


# -- systemd ----------------------------------------------------------------


def test_systemd_unit(tmp_path):
    opt = cfg.Opt(command="systemd", key="sekret1", cores="4", user_backlog=30.0,
                  engine="tpu-nnue", auto_update=True, verbose=1, conf=str(tmp_path / "f.ini"))
    (tmp_path / "f.ini").write_text("[Fishnet]\n")
    out = io.StringIO()
    systemd_mod.systemd_system(opt, out)
    unit = out.getvalue()
    assert "[Unit]" in unit and "[Service]" in unit and "[Install]" in unit
    assert "ExecStart=" in unit
    assert "--key sekret1" in unit
    assert "--cores 4" in unit
    assert "--user-backlog 30s" in unit
    assert "--auto-update" in unit
    assert "-v" in unit
    assert unit.rstrip().endswith("WantedBy=multi-user.target")
    # TPU backend keeps device access open:
    assert "PrivateDevices" not in unit
    assert "Restart=on-failure" in unit


def test_systemd_duration_and_extra_flags(tmp_path):
    opt = cfg.Opt(command="systemd", no_conf=True, max_backoff=0.5,
                  microbatch=4096, no_stats_file=True)
    out = io.StringIO()
    systemd_mod.systemd_system(opt, out)
    unit = out.getvalue()
    assert "--max-backoff 500ms" in unit  # 0.5s would fail parse_duration
    assert "--microbatch 4096" in unit
    assert "--no-stats-file" in unit
    # Round-trip: every emitted duration must parse.
    assert cfg.parse_duration("500ms") == 0.5


def test_systemd_user_unit_mock_engine():
    opt = cfg.Opt(command="systemd-user", engine="mock", no_conf=True)
    out = io.StringIO()
    systemd_mod.systemd_user(opt, out)
    unit = out.getvalue()
    assert "WantedBy=default.target" in unit
    assert "DevicePolicy=closed" in unit  # no TPU needed for mock


# -- auto-update ------------------------------------------------------------


def test_parse_version():
    assert update_mod.parse_version("v1.2.3") == (1, 2, 3)
    assert update_mod.parse_version("0.1.0") < update_mod.parse_version("0.2.0")


def test_update_noop_without_source(monkeypatch):
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    status = asyncio.run(update_mod.check_for_update())
    assert not status.checked
    assert not status.update_available


def test_update_against_local_server(tmp_path, monkeypatch):
    from aiohttp import web

    marker = tmp_path / "updated.txt"
    index = {
        "latest": "99.0.0",
        "command": ["touch", str(marker)],
    }

    hits = []

    async def scenario():
        async def handler(request):
            hits.append(1)
            return web.json_response(index)

        app = web.Application()
        app.router.add_get("/index.json", handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        try:
            url = f"http://127.0.0.1:{port}/index.json"
            status = await update_mod.check_for_update(url)
            assert status.checked and status.update_available
            hits.clear()
            status = await update_mod.apply_update(url)
            assert status.updated
            assert len(hits) == 1  # index fetched once, command rode along
            # Same version -> no update.
            index["latest"] = "0.0.1"
            status = await update_mod.check_for_update(url)
            assert not status.update_available
        finally:
            await runner.cleanup()

    asyncio.run(scenario())
    assert marker.exists()


def test_update_drain_then_exec_restart(tmp_path, monkeypatch):
    """The exec-restart path — the part that can brick a deployment
    (main.rs:412-438 analogue): with a newer version on the index,
    auto_update must run the update command, then replace the process
    with THE SAME argv, with the attempt marker set so an update that
    did not actually change the installed version cannot restart-loop."""
    import os
    import sys

    from aiohttp import web

    from fishnet_tpu.utils.logger import Logger

    marker = tmp_path / "updated.txt"
    index = {"latest": "99.0.0", "command": ["touch", str(marker)]}

    # auto_update() is a blocking wrapper (it owns its own asyncio.run),
    # so the mock index server must live on a loop that keeps running
    # meanwhile: a daemon thread.
    import threading

    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def serve_forever():
        asyncio.set_event_loop(loop)

        async def serve():
            async def handler(request):
                return web.json_response(index)

            app = web.Application()
            app.router.add_get("/index.json", handler)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            state["runner"] = runner
            state["port"] = site._server.sockets[0].getsockname()[1]
            started.set()

        loop.run_until_complete(serve())
        loop.run_forever()

    thread = threading.Thread(target=serve_forever, daemon=True)
    thread.start()
    assert started.wait(10)
    port = state["port"]
    try:
        monkeypatch.setenv(
            update_mod.UPDATE_URL_ENV, f"http://127.0.0.1:{port}/index.json"
        )
        monkeypatch.delenv(update_mod._ATTEMPT_ENV, raising=False)
        monkeypatch.setattr(sys, "argv", ["fishnet-tpu", "--cores", "2"])
        execs = []
        monkeypatch.setattr(
            update_mod.os, "execv", lambda exe, argv: execs.append((exe, argv))
        )

        status = update_mod.auto_update(Logger())
        assert status.updated and marker.exists()
        # Re-exec: same interpreter, module entry, original flags.
        assert execs == [
            (sys.executable, [sys.executable, "-m", "fishnet_tpu", "--cores", "2"])
        ]
        # Loop guard armed for the restarted process.
        assert os.environ[update_mod._ATTEMPT_ENV] == "99.0.0"

        # Restarted process, update "succeeded" but version unchanged:
        # must NOT exec again.
        execs.clear()
        status = update_mod.auto_update(Logger())
        assert status.updated and execs == []
    finally:
        asyncio.run_coroutine_threadsafe(
            state["runner"].cleanup(), loop
        ).result(10)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(10)
