"""NNUE tests: weight round-trip, feature extraction invariants, and the
central score-parity oracle — C++ scalar eval == JAX batched eval, bit
for bit, over random positions."""

import random

import numpy as np
import pytest

from fishnet_tpu.chess import Board, STARTPOS_FEN
from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.cpp_oracle import CppNnue
from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit, params_from_weights
from fishnet_tpu.nnue.weights import NnueWeights


@pytest.fixture(scope="module")
def weights():
    return NnueWeights.random(seed=7)


@pytest.fixture(scope="module")
def net_file(weights, tmp_path_factory):
    path = tmp_path_factory.mktemp("nnue") / "test.nnue"
    weights.save(path)
    return path


def random_positions(n, seed=123, max_plies=80):
    random.seed(seed)
    boards = []
    while len(boards) < n:
        b = Board()
        for _ in range(random.randrange(4, max_plies)):
            if b.outcome() != 0:
                break
            b.push_uci(random.choice(b.legal_moves()))
        boards.append(b)
    return boards


def test_weights_roundtrip(weights, net_file):
    loaded = NnueWeights.load(net_file)
    assert np.array_equal(loaded.ft_weight, weights.ft_weight)
    assert np.array_equal(loaded.ft_psqt, weights.ft_psqt)
    assert np.array_equal(loaded.l1_weight, weights.l1_weight)
    assert np.array_equal(loaded.out_bias, weights.out_bias)


def test_feature_extraction_invariants():
    b = Board()
    indices, bucket = b.nnue_features()
    assert indices.shape == (2, 32)
    # Startpos: all 32 pieces active for both perspectives.
    assert (indices < spec.NUM_FEATURES).sum() == 64
    assert bucket == spec.psqt_bucket(32) == 7
    # White and black perspectives of the symmetric startpos coincide.
    assert sorted(indices[0]) == sorted(indices[1])

    # Feature indices in range on random positions; count == piece count.
    for board in random_positions(20, seed=5):
        idx, bkt = board.nnue_features()
        active = idx[idx < spec.NUM_FEATURES]
        assert (active >= 0).all()
        assert 0 <= bkt < spec.NUM_PSQT_BUCKETS
        assert len(active) % 2 == 0  # same piece count from both sides


def test_feature_mirror_symmetry():
    # Mirroring the board horizontally (and rights) must not change the
    # feature multiset (hm = horizontal-mirror invariance).
    b1 = Board("4k3/8/8/3q4/8/8/4P3/4K3 w - - 0 1")
    b2 = Board("3k4/8/8/4q3/8/8/3P4/3K4 w - - 0 1")
    i1, _ = b1.nnue_features()
    i2, _ = b2.nnue_features()
    assert sorted(i1.ravel()) == sorted(i2.ravel())


def test_cpp_jax_score_parity(weights, net_file):
    """The centerpiece: exact agreement between the scalar C++ evaluator
    and the batched JAX evaluator on 200 random positions."""
    oracle = CppNnue(net_file)
    params = params_from_weights(weights)

    boards = random_positions(200, seed=42)
    indices = np.stack([b.nnue_features()[0] for b in boards])
    buckets = np.array([b.nnue_features()[1] for b in boards], dtype=np.int32)

    jax_scores = np.asarray(evaluate_batch_jit(params, indices, buckets))
    cpp_scores = np.array([oracle.evaluate(b) for b in boards], dtype=np.int32)

    mismatches = np.nonzero(jax_scores != cpp_scores)[0]
    assert mismatches.size == 0, (
        f"{mismatches.size} mismatches; first: idx {mismatches[0]} "
        f"fen={boards[mismatches[0]].fen()} "
        f"jax={jax_scores[mismatches[0]]} cpp={cpp_scores[mismatches[0]]}"
    )


def test_eval_changes_with_position(weights, net_file):
    oracle = CppNnue(net_file)
    b = Board()
    v0 = oracle.evaluate(b)
    b.push_uci("e2e4")
    v1 = oracle.evaluate(b)
    assert isinstance(v0, int)
    assert v0 != v1  # random net: overwhelmingly unlikely to coincide


def test_truncated_file_rejected(tmp_path, weights):
    path = tmp_path / "broken.nnue"
    weights.save(path)
    data = path.read_bytes()
    path.write_bytes(data[: len(data) // 2])
    with pytest.raises(Exception):
        NnueWeights.load(path)
    from fishnet_tpu.chess.core import NativeCoreError

    with pytest.raises(NativeCoreError):
        CppNnue(path)
