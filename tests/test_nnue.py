"""NNUE tests: weight round-trip, feature extraction invariants, and the
central score-parity oracle — C++ scalar eval == JAX batched eval, bit
for bit, over random positions."""

import random

import numpy as np
import pytest

from fishnet_tpu.chess import Board, STARTPOS_FEN
from fishnet_tpu.nnue import spec
from fishnet_tpu.nnue.cpp_oracle import CppNnue
from fishnet_tpu.nnue.jax_eval import evaluate_batch_jit, params_from_weights
from fishnet_tpu.nnue.weights import NnueWeights


@pytest.fixture(scope="module")
def weights():
    return NnueWeights.random(seed=7)


@pytest.fixture(scope="module")
def net_file(weights, tmp_path_factory):
    path = tmp_path_factory.mktemp("nnue") / "test.nnue"
    weights.save(path)
    return path


def random_positions(n, seed=123, max_plies=80):
    random.seed(seed)
    boards = []
    while len(boards) < n:
        b = Board()
        for _ in range(random.randrange(4, max_plies)):
            if b.outcome() != 0:
                break
            b.push_uci(random.choice(b.legal_moves()))
        boards.append(b)
    return boards


def test_weights_roundtrip(weights, net_file):
    loaded = NnueWeights.load(net_file)
    assert np.array_equal(loaded.ft_weight, weights.ft_weight)
    assert np.array_equal(loaded.ft_psqt, weights.ft_psqt)
    assert np.array_equal(loaded.l1_weight, weights.l1_weight)
    assert np.array_equal(loaded.out_bias, weights.out_bias)


def test_feature_extraction_invariants():
    b = Board()
    indices, bucket = b.nnue_features()
    assert indices.shape == (2, 32)
    # Startpos: all 32 pieces active for both perspectives.
    assert (indices < spec.NUM_FEATURES).sum() == 64
    assert bucket == spec.psqt_bucket(32) == 7
    # White and black perspectives of the symmetric startpos coincide.
    assert sorted(indices[0]) == sorted(indices[1])

    # Feature indices in range on random positions; count == piece count.
    for board in random_positions(20, seed=5):
        idx, bkt = board.nnue_features()
        active = idx[idx < spec.NUM_FEATURES]
        assert (active >= 0).all()
        assert 0 <= bkt < spec.NUM_PSQT_BUCKETS
        assert len(active) % 2 == 0  # same piece count from both sides


def test_feature_mirror_symmetry():
    # Mirroring the board horizontally (and rights) must not change the
    # feature multiset (hm = horizontal-mirror invariance).
    b1 = Board("4k3/8/8/3q4/8/8/4P3/4K3 w - - 0 1")
    b2 = Board("3k4/8/8/4q3/8/8/3P4/3K4 w - - 0 1")
    i1, _ = b1.nnue_features()
    i2, _ = b2.nnue_features()
    assert sorted(i1.ravel()) == sorted(i2.ravel())


def test_cpp_jax_score_parity(weights, net_file):
    """The centerpiece: exact agreement between the scalar C++ evaluator
    and the batched JAX evaluator on 200 random positions."""
    oracle = CppNnue(net_file)
    params = params_from_weights(weights)

    boards = random_positions(200, seed=42)
    indices = np.stack([b.nnue_features()[0] for b in boards])
    buckets = np.array([b.nnue_features()[1] for b in boards], dtype=np.int32)

    jax_scores = np.asarray(evaluate_batch_jit(params, indices, buckets))
    cpp_scores = np.array([oracle.evaluate(b) for b in boards], dtype=np.int32)

    mismatches = np.nonzero(jax_scores != cpp_scores)[0]
    assert mismatches.size == 0, (
        f"{mismatches.size} mismatches; first: idx {mismatches[0]} "
        f"fen={boards[mismatches[0]].fen()} "
        f"jax={jax_scores[mismatches[0]]} cpp={cpp_scores[mismatches[0]]}"
    )


def test_eval_changes_with_position(weights, net_file):
    oracle = CppNnue(net_file)
    b = Board()
    v0 = oracle.evaluate(b)
    b.push_uci("e2e4")
    v1 = oracle.evaluate(b)
    assert isinstance(v0, int)
    assert v0 != v1  # random net: overwhelmingly unlikely to coincide


def test_truncated_file_rejected(tmp_path, weights):
    path = tmp_path / "broken.nnue"
    weights.save(path)
    data = path.read_bytes()
    path.write_bytes(data[: len(data) // 2])
    with pytest.raises(Exception):
        NnueWeights.load(path)
    from fishnet_tpu.chess.core import NativeCoreError

    with pytest.raises(NativeCoreError):
        CppNnue(path)


def test_delta_reconstruction_parity(weights):
    """Incremental (delta) entries must be bit-identical to full entries:
    an entry encoded as set-differences against a full parent (removals
    via the negated table half at DELTA_BASE) reconstructs exactly the
    same accumulator, including the perspective swap after a move."""
    params = params_from_weights(weights)
    boards = random_positions(40, seed=77)

    full_idx, buckets, parents = [], [], []
    expect_rows = []  # rows of the batch to compare against full eval
    for b in boards:
        moves = b.legal_moves()
        if not moves:
            continue
        child = b.copy()
        child.push_uci(random.choice(moves))
        pf, pb = b.nnue_features()
        cf, cb = child.nnue_features()
        base = len(full_idx)
        full_idx.append(pf)
        buckets.append(pb)
        parents.append(-1)
        # Encode the child as deltas vs the parent, following the wire
        # contract: adds in slots [0, DELTA_SLOTS), removals (encoded
        # DELTA_BASE + f) in [DELTA_SLOTS, 2*DELTA_SLOTS), each region
        # padded with its own sentinel. The move flips the side to move,
        # so child perspective p maps to parent 1-p.
        delta = np.full((2, spec.MAX_ACTIVE_FEATURES), spec.NUM_FEATURES,
                        np.int32)
        ok = True
        for p in (0, 1):
            child_set = set(int(x) for x in cf[p] if x != spec.NUM_FEATURES)
            par_set = set(int(x) for x in pf[1 - p] if x != spec.NUM_FEATURES)
            adds = sorted(child_set - par_set)
            removes = sorted(par_set - child_set)
            if len(adds) > spec.DELTA_SLOTS or len(removes) > spec.DELTA_SLOTS:
                ok = False  # king moved: full rebuild in production too
                break
            delta[p, : len(adds)] = adds
            rem_row = [spec.DELTA_BASE + f for f in removes] + [
                spec.DELTA_BASE + spec.NUM_FEATURES
            ] * (spec.DELTA_SLOTS - len(removes))
            delta[p, spec.DELTA_SLOTS : 2 * spec.DELTA_SLOTS] = rem_row
        if not ok:
            continue
        full_idx.append(delta)
        buckets.append(cb)
        parents.append((base << 1) | 1)  # swap perspectives
        # The same child as a standalone full entry, for comparison.
        full_idx.append(cf)
        buckets.append(cb)
        parents.append(-1)
        expect_rows.append((base + 1, base + 2))

    assert expect_rows, "no delta pairs were generated"
    idx = np.stack(full_idx).astype(np.int32)
    bks = np.asarray(buckets, np.int32)
    par = np.asarray(parents, np.int32)
    scores = np.asarray(evaluate_batch_jit(params, idx, bks, par))
    for delta_row, full_row in expect_rows:
        assert scores[delta_row] == scores[full_row], (
            f"delta row {delta_row} != full row {full_row}: "
            f"{scores[delta_row]} vs {scores[full_row]}"
        )


def test_nnue_golden_byte_layout(tmp_path):
    """Golden-vector serialization check, independent of the writer: a
    .nnue stream is hand-assembled field by field in the documented
    SF/nnue-pytorch order (header, desc, FT bias/weights/psqt, then 8
    bucket stacks with l2 rows padded to 32 inputs) with markers at
    known coordinates. load() must map every marker to the right tensor
    slot, the C++ scalar core must accept the same file, and save() of
    the same values must reproduce the byte stream exactly."""
    import struct

    b = spec.NUM_PSQT_BUCKETS
    ft_bias = (np.arange(spec.L1) % 7 - 3).astype("<i2")
    ft_w = np.zeros((spec.NUM_FEATURES, spec.L1), "<i2")
    ft_w[3, 5] = 11
    ft_w[22527, 1023] = -9
    psqt = np.zeros((spec.NUM_FEATURES, b), "<i4")
    psqt[4, 2] = 1234
    psqt[0, 0] = -777
    l1_b = np.zeros((b, spec.L2 + 1), "<i4")
    l1_b[1, 15] = 4242
    l1_w = np.zeros((b, spec.L2 + 1, spec.L1), "i1")
    l1_w[1, 2, 1000] = 17
    l2_b = np.zeros((b, spec.L3), "<i4")
    l2_b[7, 31] = -31337
    l2_w = np.zeros((b, spec.L3, 2 * spec.L2), "i1")
    l2_w[7, 31, 29] = -5  # LAST real column: catches padded-width bugs
    o_b = np.zeros((b, 1), "<i4")
    o_b[3, 0] = 99
    o_w = np.zeros((b, 1, spec.L3), "i1")
    o_w[3, 0, 31] = 42

    stream = bytearray()
    stream += struct.pack("<II", spec.FILE_VERSION, spec.ARCH_HASH)
    stream += struct.pack("<I", len(spec.ARCH_DESCRIPTION))
    stream += spec.ARCH_DESCRIPTION
    stream += struct.pack("<I", 0x5D69D5B8)  # FT section hash
    stream += ft_bias.tobytes()
    stream += ft_w.tobytes()
    stream += psqt.tobytes()
    for k in range(b):
        stream += struct.pack("<I", 0x63337156)  # stack hash
        stream += l1_b[k].tobytes()
        stream += l1_w[k].tobytes()
        stream += l2_b[k].tobytes()
        padded = np.zeros((spec.L3, spec.L2_PADDED_INPUTS), "i1")
        padded[:, : 2 * spec.L2] = l2_w[k]
        stream += padded.tobytes()
        stream += o_b[k].tobytes()
        stream += o_w[k].tobytes()

    golden = tmp_path / "golden.nnue"
    golden.write_bytes(bytes(stream))

    w = NnueWeights.load(golden)
    assert w.ft_bias[1] == -2 and w.ft_bias[6] == 3
    assert w.ft_weight[3, 5] == 11 and w.ft_weight[22527, 1023] == -9
    assert w.ft_psqt[4, 2] == 1234 and w.ft_psqt[0, 0] == -777
    assert w.l1_bias[1, 15] == 4242
    assert w.l1_weight[1, 2, 1000] == 17
    assert w.l2_bias[7, 31] == -31337
    assert w.l2_weight[7, 31, 29] == -5
    assert w.out_bias[3, 0] == 99 and w.out_weight[3, 0, 31] == 42

    # The writer must reproduce the independent encoding byte for byte.
    roundtrip = tmp_path / "roundtrip.nnue"
    w.save(roundtrip)
    assert roundtrip.read_bytes() == bytes(stream)

    # The native scalar core must accept the same stream and agree with
    # the JAX evaluator on it (the serialization feeding both tiers).
    oracle = CppNnue(golden)
    params = params_from_weights(w)
    board = Board()
    idx, bucket = board.nnue_features()
    jax_score = int(
        np.asarray(
            evaluate_batch_jit(
                params, idx[None].astype(np.int32), np.array([bucket], np.int32)
            )
        )[0]
    )
    assert oracle.evaluate(board) == jax_score


def test_verify_net_subcommand(tmp_path):
    """`fishnet-tpu verify-net --nnue-file X` (fishnet_tpu/verify_net.py)
    is the offline-maximum answer to the real-net gap (the reference
    embeds its net at build time, build.rs:7): every stage — layout,
    scalar load, scalar-vs-JAX bit parity, fixed-depth search parity,
    material probe — must pass against a generated net, and a corrupted
    file must fail the layout stage with the re-export hint."""
    from fishnet_tpu.verify_net import verify_net

    path = tmp_path / "net.nnue"
    NnueWeights.random(seed=13).save(path)
    lines = []
    assert verify_net(str(path), positions=40, depth=2, log=lines.append)
    report = "\n".join(lines)
    assert "layout          PASS" in report
    assert "eval parity     PASS" in report
    assert "search parity   PASS" in report
    assert "material probe" in report

    # Truncation fails stage 1 and mentions the pre-r2 re-export hint.
    data = path.read_bytes()
    bad = tmp_path / "short.nnue"
    bad.write_bytes(data[: len(data) - 512])
    lines = []
    assert not verify_net(str(bad), positions=5, depth=1, log=lines.append)
    assert any("FAIL" in l and "re-export" in l for l in lines)


def test_packed_wire_matches_dense():
    """The compact wire format (packed [R,2,8] rows + offsets; full
    entry = 4 rows, delta entry = 1 row) must evaluate bit-identically
    to the dense [B,2,32] layout it compresses."""
    import numpy as np

    from fishnet_tpu.nnue import spec
    from fishnet_tpu.nnue.jax_eval import (
        evaluate_batch,
        evaluate_packed,
        expand_packed_np,
        params_from_weights,
    )
    from fishnet_tpu.nnue.weights import NnueWeights

    rng = np.random.default_rng(11)
    B = 48
    dense = np.full((B, 2, 32), spec.NUM_FEATURES, np.uint16)
    parent = np.full((B,), -1, np.int32)
    packed_rows = []
    offsets = np.zeros((B,), np.int32)
    last_full = 0
    for b in range(B):
        offsets[b] = len(packed_rows)
        is_delta = b % 4 != 0  # blocks of 1 full + 3 deltas
        if not is_delta:
            k = int(rng.integers(8, 31))
            for p in range(2):
                dense[b, p, :k] = np.sort(
                    rng.choice(spec.NUM_FEATURES, k, replace=False)
                )
            last_full = b
            for r in range(4):
                packed_rows.append(dense[b, :, r * 8 : (r + 1) * 8])
        else:
            for p in range(2):
                dense[b, p, :2] = rng.choice(spec.NUM_FEATURES, 2, replace=False)
                dense[b, p, spec.DELTA_SLOTS : spec.DELTA_SLOTS + 2] = (
                    spec.DELTA_BASE + rng.choice(spec.NUM_FEATURES, 2, replace=False)
                )
                dense[b, p, spec.DELTA_SLOTS + 2 : 2 * spec.DELTA_SLOTS] = (
                    spec.DELTA_BASE + spec.NUM_FEATURES
                )
            parent[b] = (last_full << 1) | int(rng.integers(0, 2))
            packed_rows.append(dense[b, :, :8])
    packed = np.stack(packed_rows).astype(np.uint16)
    buckets = rng.integers(0, 8, B).astype(np.int32)
    material = rng.integers(-2000, 2000, B).astype(np.int32)

    params = params_from_weights(NnueWeights.random(seed=13))
    want = np.asarray(evaluate_batch(params, dense, buckets, parent, material))
    got = np.asarray(
        evaluate_packed(params, packed, offsets, buckets, parent, material)
    )
    assert (want == got).all()
    # The NumPy expansion twin (used for external evaluators) agrees too.
    np.testing.assert_array_equal(
        expand_packed_np(packed, offsets, parent).astype(np.int32),
        dense.astype(np.int32),
    )


def test_cached_eval_matches_fresh_over_game_sequences():
    """nnue_evaluate_cached must be bit-identical to the fresh eval over
    arbitrary eval sequences — including castling (own-king rebuild),
    promotions, en passant, and jumps between unrelated positions."""
    import ctypes
    import random
    import tempfile

    from fishnet_tpu.chess import Board
    from fishnet_tpu.chess.core import load
    from fishnet_tpu.nnue.weights import NnueWeights

    lib = load()
    if not hasattr(lib.fc_nnue_evaluate_cached_test, "_bound"):
        lib.fc_nnue_evaluate_cached_test.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.fc_nnue_evaluate_cached_test.restype = ctypes.c_int
        lib.fc_nnue_cache_new.restype = ctypes.c_void_p
        lib.fc_nnue_cache_free.argtypes = [ctypes.c_void_p]
        lib.fc_nnue_evaluate.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.fc_nnue_evaluate.restype = ctypes.c_int
        lib.fc_nnue_evaluate_cached_test._bound = True

    w = NnueWeights.random(seed=17)
    with tempfile.NamedTemporaryFile(suffix=".nnue") as f:
        w.save(f.name)
        err = ctypes.create_string_buffer(256)
        net = lib.fc_nnue_load(f.name.encode(), err, len(err))
        assert net, err.value
        cache = lib.fc_nnue_cache_new()
        try:
            rng = random.Random(5)
            b = Board()
            checked = 0
            for step in range(400):
                if b.outcome() != 0 or rng.random() < 0.02:
                    # Jump to an unrelated position: large diff / rebuild.
                    b = Board()
                    for _ in range(rng.randrange(0, 30)):
                        if b.outcome() != 0:
                            break
                        b.push_uci(rng.choice(b.legal_moves()))
                else:
                    b.push_uci(rng.choice(b.legal_moves()))
                fresh = lib.fc_nnue_evaluate(net, b._pos)
                cached = lib.fc_nnue_evaluate_cached_test(net, b._pos, cache)
                assert fresh == cached, (step, b.fen())
                checked += 1
            assert checked == 400
        finally:
            lib.fc_nnue_cache_free(cache)
            lib.fc_nnue_free(net)
