"""Production-shaped soak: standard analysis, Chess960, a variant batch,
MultiPV, and best-move play jobs all flowing through one shared batched
engine concurrently — the closest in-repo approximation of the workload
mix a live client serves."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
from fake_server import FakeServer  # noqa: E402
from test_client_e2e import make_client, wait_for  # noqa: E402

from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search.service import SearchService

pytestmark = pytest.mark.anyio

FRC_START = "bqnrkbnr/pppppppp/8/8/8/8/PPPPPPPP/BQNRKBNR w DHdh - 0 1"


async def test_mixed_workload_soak():
    service = SearchService(
        weights=NnueWeights.random(seed=0), pool_slots=64,
        batch_capacity=128, tt_bytes=16 << 20, backend="scalar",
    )
    try:
        async with FakeServer() as server:
            jobs = {
                "standard": server.lichess.add_analysis_job(
                    moves="e2e4 c7c5 g1f3", nodes=2000
                ),
                "frc": server.lichess.add_analysis_job(
                    moves="d2d4", position=FRC_START, variant="chess960",
                    nodes=2000,
                ),
                "multipv": server.lichess.add_analysis_job(
                    moves="e2e4", nodes=2000, multipv=3
                ),
                "atomic": server.lichess.add_analysis_job(
                    moves="e2e4 d7d5", variant="atomic", nodes=2000
                ),
                "crazyhouse": server.lichess.add_analysis_job(
                    moves="e2e4 e7e5", variant="crazyhouse", nodes=2000
                ),
                "play": server.lichess.add_move_job(
                    moves="e2e4 e7e5", level=3
                ),
            }
            client = make_client(
                server.endpoint, cores=4,
                engine_factory=TpuNnueEngineFactory(service),
            )
            await client.start()
            assert await wait_for(
                lambda: all(
                    (j in server.lichess.analyses) or (j in server.lichess.moves)
                    for j in jobs.values()
                ),
                timeout=120,
            ), {
                name: (j in server.lichess.analyses, j in server.lichess.moves)
                for name, j in jobs.items()
            }
            await client.stop()

            assert server.lichess.analyses[jobs["standard"]]["stockfish"]["flavor"] == "nnue"
            assert server.lichess.analyses[jobs["atomic"]]["stockfish"]["flavor"] == "classical"
            # MultiPV analysis: matrix rows for 3 ranks on the final ply.
            parts = server.lichess.analyses[jobs["multipv"]]["analysis"]
            assert any(
                isinstance(p.get("pv"), list) and len(p["pv"]) >= 2
                for p in parts if p and not p.get("skipped")
            )
            # Play job answered with a legal-looking move.
            best = server.lichess.moves[jobs["play"]]["move"]["bestmove"]
            assert best and len(best) >= 4
    finally:
        service.close()
