"""CPU feature detection and native-library tier selection (the
reference's assets.rs tier cascade + AMD slow-PEXT heuristic)."""

import ctypes
import subprocess
from pathlib import Path

import pytest

from fishnet_tpu.chess.cpu import CpuInfo, parse_cpuinfo

CPP_DIR = Path(__file__).resolve().parent.parent / "cpp"

INTEL_V3 = """\
vendor_id\t: GenuineIntel
cpu family\t: 6
flags\t\t: fpu sse4_1 sse4_2 popcnt avx avx2 bmi1 bmi2
"""

AMD_ZEN2 = """\
vendor_id\t: AuthenticAMD
cpu family\t: 23
flags\t\t: fpu sse4_1 sse4_2 popcnt avx avx2 bmi1 bmi2
"""

AMD_ZEN3 = """\
vendor_id\t: AuthenticAMD
cpu family\t: 25
flags\t\t: fpu sse4_1 sse4_2 popcnt avx avx2 bmi1 bmi2
"""

OLD_BOX = """\
vendor_id\t: GenuineIntel
cpu family\t: 6
flags\t\t: fpu sse2 sse4_1 sse4_2 popcnt
"""


def test_intel_gets_v3():
    info = parse_cpuinfo(INTEL_V3)
    assert info.fast_pext
    assert info.best_tier() == "v3"


def test_amd_zen2_pext_demoted_to_v2():
    # BMI2 present but microcoded: the reference demotes exactly this
    # case (assets.rs:94-108).
    info = parse_cpuinfo(AMD_ZEN2)
    assert not info.fast_pext
    assert info.best_tier() == "v2"


def test_amd_zen3_gets_v3():
    info = parse_cpuinfo(AMD_ZEN3)
    assert info.fast_pext
    assert info.best_tier() == "v3"


def test_old_cpu_gets_v2():
    assert parse_cpuinfo(OLD_BOX).best_tier() == "v2"


def test_unknown_cpu_gets_none():
    assert CpuInfo().best_tier() is None


def test_aarch64_gets_arm64_tier():
    # aarch64 /proc/cpuinfo has no x86 flags line; the arch field alone
    # selects the single armv8 tier (reference build.rs:187-276 ships an
    # armv8 engine build the same way).
    info = CpuInfo(arch="aarch64")
    assert info.best_tier() == "arm64"
    assert CpuInfo(arch="x86_64").best_tier() is None


@pytest.mark.slow
def test_tier_builds_load_and_pass_perft():
    import platform

    if platform.machine() not in ("x86_64", "AMD64"):
        pytest.skip("x86-64 tier builds")
    subprocess.run(["make", "-C", str(CPP_DIR), "tiers", "-j2"], check=True,
                   capture_output=True)
    # Only EXECUTE tiers the host can run: dlopen of a higher tier
    # succeeds, but its instructions SIGILL the whole process (e.g. v4
    # on a non-AVX-512 CI runner). best_tier() ranks host capability.
    from fishnet_tpu.chess.cpu import detect

    rank = {"v2": 2, "v3": 3, "v4": 4}
    host = rank.get(detect().best_tier() or "", 0)
    runnable = [t for t in ("v2", "v3", "v4") if rank[t] <= host]
    assert runnable, "host below x86-64-v2; tier artifacts unusable here"
    for tier in runnable:
        lib = ctypes.CDLL(str(CPP_DIR / f"libfishnetcore-{tier}.so"))
        lib.fc_init()
        err = ctypes.create_string_buffer(256)
        lib.fc_pos_new.restype = ctypes.c_void_p
        pos = lib.fc_pos_new(
            b"rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1",
            0, err, 256,
        )
        assert pos
        lib.fc_perft.restype = ctypes.c_uint64
        assert lib.fc_perft(ctypes.c_void_p(pos), 4) == 197281


def test_avx512_gets_v4():
    info = CpuInfo(
        vendor="GenuineIntel", family=6,
        flags=frozenset({
            "sse4_2", "popcnt", "avx2", "bmi2", "avx512f", "avx512bw",
            "avx512cd", "avx512dq", "avx512vl",
        }),
    )
    assert info.best_tier() == "v4"
    # Pre-Zen4-style AMD with microcoded PEXT: demoted past v4 AND v3.
    amd = CpuInfo(vendor="AuthenticAMD", family=0x17, flags=info.flags)
    assert amd.best_tier() == "v2"
