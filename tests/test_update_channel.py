"""Default release-channel auto-update: check -> download -> sha256
verify -> unpack (reference src/main.rs:440-464, the S3 self_update
flow). Served by a local aiohttp app standing in for the S3-compatible
static channel."""

import hashlib
import io
import json
import tarfile

import pytest
from aiohttp import web

from fishnet_tpu import update as update_mod

pytestmark = pytest.mark.anyio


def make_release_tarball() -> bytes:
    """A minimal release artifact in CI's layout (fishnet_tpu/ at the
    top level)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        data = b"__version__ = '99.0.0'\n"
        info = tarfile.TarInfo("fishnet_tpu/_release_marker.py")
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))
    return buf.getvalue()


class FakeChannel:
    """Static-HTTPS release channel fixture: /index.json + the tarball."""

    def __init__(self, latest="99.0.0", sha256=None, tarball=None):
        self.tarball = tarball if tarball is not None else make_release_tarball()
        self.sha256 = sha256 or hashlib.sha256(self.tarball).hexdigest()
        self.latest = latest
        self.index_hits = 0
        self.artifact_hits = 0

    async def __aenter__(self):
        app = web.Application()
        app.router.add_get("/channel/index.json", self._index)
        app.router.add_get(
            "/channel/v99.0.0/fishnet-tpu.tar.gz", self._artifact
        )
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.base = f"http://127.0.0.1:{port}/channel"
        return self

    async def __aexit__(self, *exc):
        await self.runner.cleanup()

    async def _index(self, request):
        self.index_hits += 1
        return web.json_response(
            {
                "latest": self.latest,
                "artifact": "v99.0.0/fishnet-tpu.tar.gz",
                "sha256": self.sha256,
            }
        )

    async def _artifact(self, request):
        self.artifact_hits += 1
        return web.Response(body=self.tarball)


async def test_check_download_verify_install(tmp_path, monkeypatch):
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    async with FakeChannel() as ch:
        status = await update_mod.apply_update(
            url=f"{ch.base}/index.json", install_root=tmp_path
        )
        assert status.checked and status.update_available
        assert status.updated
        assert ch.index_hits == 1 and ch.artifact_hits == 1
        marker = tmp_path / "fishnet_tpu" / "_release_marker.py"
        assert marker.read_bytes() == b"__version__ = '99.0.0'\n"


async def test_hash_mismatch_refuses_install(tmp_path, monkeypatch):
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    async with FakeChannel(sha256="0" * 64) as ch:
        status = await update_mod.apply_update(
            url=f"{ch.base}/index.json", install_root=tmp_path
        )
        assert status.checked and status.update_available
        assert not status.updated  # verification failed -> nothing unpacked
        assert not (tmp_path / "fishnet_tpu").exists()


async def test_default_channel_engages_only_with_auto_update(monkeypatch):
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    async with FakeChannel(latest="0.0.1") as ch:  # older: no install
        monkeypatch.setattr(update_mod, "DEFAULT_CHANNEL", ch.base)
        # Without the opt-in there is no update source at all.
        status = await update_mod.check_for_update()
        assert not status.checked
        # --auto-update (allow_default) reads the default channel.
        status = await update_mod.check_for_update(allow_default=True)
        assert status.checked and status.latest == "0.0.1"
        assert not status.update_available


async def test_env_override_beats_default_channel(monkeypatch):
    async with FakeChannel(latest="0.0.2") as ch:
        monkeypatch.setattr(
            update_mod, "DEFAULT_CHANNEL", "http://127.0.0.1:1/nowhere"
        )
        monkeypatch.setenv(update_mod.UPDATE_URL_ENV, f"{ch.base}/index.json")
        status = await update_mod.check_for_update(allow_default=True)
        assert status.checked and status.latest == "0.0.2"


async def test_traversal_artifact_rejected(tmp_path, monkeypatch):
    """A malicious tarball with a path-escaping member must not write
    outside the install root (tarfile filter='data')."""
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        data = b"evil"
        info = tarfile.TarInfo("../escape.txt")
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))
    evil = buf.getvalue()
    async with FakeChannel(tarball=evil) as ch:
        root = tmp_path / "root"
        root.mkdir()
        status = await update_mod.apply_update(
            url=f"{ch.base}/index.json", install_root=root
        )
        assert not status.updated
        # A successful traversal from the staging dir would land at
        # root/escape.txt; filter='data' must reject the member.
        assert not (root / "escape.txt").exists()
        assert not (tmp_path / "escape.txt").exists()


async def test_defer_promote_stages_without_touching_root(tmp_path, monkeypatch):
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    async with FakeChannel() as ch:
        status = await update_mod.apply_update(
            url=f"{ch.base}/index.json", install_root=tmp_path,
            defer_promote=True,
        )
        assert status.updated and status.deferred
        assert status.staged is not None and status.staged.exists()
        # Nothing promoted yet: the live root has no package files.
        assert not (tmp_path / "fishnet_tpu").exists()
        update_mod.promote_staged(status.staged, tmp_path)
        assert (tmp_path / "fishnet_tpu" / "_release_marker.py").exists()
        assert not status.staged.exists()  # staging consumed


def _sign(data: bytes):
    """Mint a keypair and sign `data`; returns (pubkey_hex, sig_hex).
    Signature round-trip tests need the optional `cryptography` package
    (the client treats its absence like a bad signature, update.py
    verify_signature); skip rather than fail where it is not installed."""
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    key = Ed25519PrivateKey.generate()
    pub = key.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    return pub.hex(), key.sign(data).hex()


class SignedChannel(FakeChannel):
    def __init__(self, signature=None, **kw):
        super().__init__(**kw)
        self.signature = signature

    async def _index(self, request):
        self.index_hits += 1
        body = {
            "latest": self.latest,
            "artifact": "v99.0.0/fishnet-tpu.tar.gz",
            "sha256": self.sha256,
        }
        if self.signature:
            body["signature"] = self.signature
        return web.json_response(body)


async def test_default_channel_requires_signature(tmp_path, monkeypatch):
    """Bucket compromise =/= RCE: an UNSIGNED index from the default
    channel must never be installed, sha256 notwithstanding."""
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    async with SignedChannel() as ch:  # no signature field
        monkeypatch.setattr(update_mod, "DEFAULT_CHANNEL", ch.base)
        status = await update_mod.apply_update(
            allow_default=True, install_root=tmp_path
        )
        assert status.checked and status.update_available
        assert not status.updated
        assert not (tmp_path / "fishnet_tpu").exists()


async def test_default_channel_accepts_pinned_signature(tmp_path, monkeypatch):
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    tarball = make_release_tarball()
    pub, sig = _sign(tarball)
    async with SignedChannel(tarball=tarball, signature=sig) as ch:
        monkeypatch.setattr(update_mod, "DEFAULT_CHANNEL", ch.base)
        monkeypatch.setattr(update_mod, "SIGNING_PUBKEY_HEX", pub)
        status = await update_mod.apply_update(
            allow_default=True, install_root=tmp_path
        )
        assert status.updated
        assert (tmp_path / "fishnet_tpu" / "_release_marker.py").exists()


async def test_default_channel_rejects_wrong_signature(tmp_path, monkeypatch):
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    tarball = make_release_tarball()
    pub, _ = _sign(tarball)
    _, wrong_sig = _sign(b"some other artifact")
    async with SignedChannel(tarball=tarball, signature=wrong_sig) as ch:
        monkeypatch.setattr(update_mod, "DEFAULT_CHANNEL", ch.base)
        monkeypatch.setattr(update_mod, "SIGNING_PUBKEY_HEX", pub)
        status = await update_mod.apply_update(
            allow_default=True, install_root=tmp_path
        )
        assert not status.updated
        assert not (tmp_path / "fishnet_tpu").exists()


async def test_default_channel_never_runs_index_command(tmp_path, monkeypatch):
    """An index `command` from the DEFAULT channel is an RCE attempt,
    not an update mechanism — refuse it outright."""
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    marker = tmp_path / "pwned"

    class CommandChannel(FakeChannel):
        async def _index(self, request):
            return web.json_response(
                {"latest": "99.0.0", "command": ["touch", str(marker)]}
            )

    async with CommandChannel() as ch:
        monkeypatch.setattr(update_mod, "DEFAULT_CHANNEL", ch.base)
        status = await update_mod.apply_update(allow_default=True)
        assert not status.updated
        assert not marker.exists()


async def test_operator_pinned_key_enforced_on_override(tmp_path, monkeypatch):
    """FISHNET_TPU_UPDATE_PUBKEY on a private mirror: omitting the
    signature must FAIL (no silent downgrade), a valid one installs."""
    tarball = make_release_tarball()
    pub, sig = _sign(tarball)
    async with SignedChannel(tarball=tarball) as ch:  # unsigned index
        monkeypatch.setenv(update_mod.UPDATE_URL_ENV, f"{ch.base}/index.json")
        monkeypatch.setenv(update_mod.UPDATE_PUBKEY_ENV, pub)
        status = await update_mod.apply_update(install_root=tmp_path)
        assert not status.updated
    async with SignedChannel(tarball=tarball, signature=sig) as ch:
        monkeypatch.setenv(update_mod.UPDATE_URL_ENV, f"{ch.base}/index.json")
        monkeypatch.setenv(update_mod.UPDATE_PUBKEY_ENV, pub)
        status = await update_mod.apply_update(install_root=tmp_path)
        assert status.updated


def test_validate_member_sanitizes_modes():
    info = tarfile.TarInfo("fishnet_tpu/x.py")
    info.mode = 0o6777  # setuid+setgid+world-writable
    update_mod._validate_member(info)
    assert info.mode == 0o755


async def test_defer_promote_defers_legacy_command(monkeypatch, tmp_path):
    """A command-index update must NOT run the command mid-flight when
    the caller asked for deferral (the live environment would be
    mutated while work drains)."""
    monkeypatch.delenv(update_mod.UPDATE_URL_ENV, raising=False)
    marker = tmp_path / "ran"

    class CommandChannel(FakeChannel):
        async def _index(self, request):
            return web.json_response(
                {"latest": "99.0.0", "command": ["touch", str(marker)]}
            )

    async with CommandChannel() as ch:
        status = await update_mod.apply_update(
            url=f"{ch.base}/index.json", defer_promote=True
        )
        assert status.updated and status.deferred
        assert status.command == ["touch", str(marker)]
        assert not marker.exists()  # not run; caller runs it post-drain
