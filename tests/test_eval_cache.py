"""Position-keyed eval reuse plane (doc/eval-cache.md): EvalCache
units (striping, generation eviction, stats), the hard bit-parity
requirement — cache-on (cold AND warm) analyses identical to
FISHNET_NO_EVAL_CACHE=1 on every psqt_path rung and on the mesh —
cross-service warm reuse (the supervisor-respawn shape), cross-group
position dedup inside fused dispatches, and the exactly-once ledger
under injected device faults with the cache live. ``make cache-smoke``
runs this file."""

import asyncio
import threading

import numpy as np
import pytest

from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.search import eval_cache
from fishnet_tpu.search.eval_cache import EvalCache, MissHistory


# -- units ----------------------------------------------------------------


def test_eval_cache_probe_insert_roundtrip():
    c = EvalCache(capacity=1024, stripes=8)
    assert c.probe(0xDEAD) is None
    c.insert(0xDEAD, -77)
    assert c.probe(0xDEAD) == -77
    assert len(c) == 1
    st = c.stats()
    assert st["hits"] == 1 and st["misses"] == 1 and st["insertions"] == 1
    c.clear()
    assert len(c) == 0 and c.probe(0xDEAD) is None


def test_eval_cache_block_ops_and_mask():
    c = EvalCache(capacity=1024, stripes=8)
    hashes = np.arange(1, 9, dtype=np.uint64)
    c.insert_block(hashes[:4], np.arange(4, dtype=np.int32) * 10)
    vals, mask = c.probe_block(hashes)
    assert mask.tolist() == [True] * 4 + [False] * 4
    assert vals[:4].tolist() == [0, 10, 20, 30]
    # The out= buffer is written in place (the service's scratch path).
    out = np.zeros(8, dtype=np.int32)
    vals2, _ = c.probe_block(hashes, out=out)
    assert vals2 is out and out[:4].tolist() == [0, 10, 20, 30]


def test_eval_cache_generation_eviction_under_tiny_capacity():
    c = EvalCache(capacity=8, stripes=1)
    for h in range(6):
        c.insert(h, h)
    c.advance_generation()
    # Touch ONE old entry: the hit refreshes its generation, so the
    # sweep below must spare it while dropping its untouched peers.
    assert c.probe(3) == 3
    for h in range(100, 104):
        c.insert(h, h)
    assert c.stats()["evictions"] > 0
    assert len(c) <= 8
    assert c.probe(3) == 3, "touched entry evicted before stale peers"
    assert c.probe(0) is None or c.probe(1) is None


def test_eval_cache_thread_safety_smoke():
    c = EvalCache(capacity=4096, stripes=4)
    errs = []

    def writer(base):
        try:
            for i in range(500):
                c.insert(base + i, i)
                c.probe(base + (i * 7) % 500)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [
        threading.Thread(target=writer, args=(b * 10_000,)) for b in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(c) <= 4096


def test_miss_history_window_and_min_sample():
    mh = MissHistory(window=128)
    assert mh.hit_rate(0) is None  # below the minimum sample
    mh.record(0, hits=32, probes=64)
    assert mh.hit_rate(0) == 0.5
    for _ in range(10):  # push past the window: halving-forget engages
        mh.record(0, hits=40, probes=40)
    r = mh.hit_rate(0)
    assert r is not None and r > 0.8  # tracks the current all-hit mix


def test_singleton_escape_hatch_and_capacity_env(monkeypatch):
    monkeypatch.setenv("FISHNET_NO_EVAL_CACHE", "1")
    assert eval_cache.get_cache() is None
    monkeypatch.delenv("FISHNET_NO_EVAL_CACHE")
    monkeypatch.setenv("FISHNET_EVAL_CACHE_CAPACITY", "256")
    eval_cache.reset_cache()
    c = eval_cache.get_cache()
    assert c is not None and c is eval_cache.get_cache()
    assert c._stripe_cap * c._n_stripes >= 256
    eval_cache.reset_cache()


def test_net_fingerprint_matches_weights_fingerprint(tmp_path):
    w = NnueWeights.random(seed=5)
    p = tmp_path / "net.nnue"
    w.save(p)
    assert eval_cache.net_fingerprint(str(p)) == w.fingerprint()
    assert NnueWeights.random(seed=6).fingerprint() != w.fingerprint()


# -- snapshot persistence (warm restart across process death) --------------


def test_eval_cache_snapshot_roundtrip(tmp_path, monkeypatch):
    """Graceful-drain persistence: save the process cache, simulate
    process death (reset), restore — entries and the generation clock
    survive, so the restarted process's first probes hit."""
    snap = tmp_path / "cache.npz"
    monkeypatch.setenv(eval_cache.SNAPSHOT_ENV, str(snap))
    eval_cache.reset_cache()
    c = eval_cache.get_cache()
    for h in range(1, 40):
        c.insert(h * 0x9E3779B9, h)
    c.advance_generation()
    c.insert(0xFEED, 123)
    gen = c.stats()["generation"]

    assert eval_cache.save_snapshot(fingerprint=42) == str(snap)
    assert snap.exists()

    eval_cache.reset_cache()  # the process died
    assert eval_cache.load_snapshot(fingerprint=42) is True
    c2 = eval_cache.get_cache()
    assert c2.probe(0xFEED) == 123
    assert c2.probe(7 * 0x9E3779B9) == 7
    assert c2.stats()["generation"] >= gen
    eval_cache.reset_cache()


def test_eval_cache_snapshot_fingerprint_mismatch_discards(
    tmp_path, monkeypatch
):
    """A snapshot from a DIFFERENT network must be discarded, never
    half-trusted: evals are only meaningful under the net that produced
    them (keys are position-hash x net-fingerprint, but the file-level
    check refuses the whole snapshot up front and deletes it)."""
    snap = tmp_path / "cache.npz"
    monkeypatch.setenv(eval_cache.SNAPSHOT_ENV, str(snap))
    eval_cache.reset_cache()
    eval_cache.get_cache().insert(0xBEEF, 9)
    assert eval_cache.save_snapshot(fingerprint=1) == str(snap)

    eval_cache.reset_cache()
    assert eval_cache.load_snapshot(fingerprint=2) is False
    assert not snap.exists(), "mismatched snapshot must be deleted"
    assert eval_cache.get_cache().probe(0xBEEF) is None
    eval_cache.reset_cache()


def test_eval_cache_snapshot_corrupt_file_discards(tmp_path, monkeypatch):
    snap = tmp_path / "cache.npz"
    monkeypatch.setenv(eval_cache.SNAPSHOT_ENV, str(snap))
    snap.write_bytes(b"not a zip archive at all")
    eval_cache.reset_cache()
    assert eval_cache.load_snapshot(fingerprint=0) is False
    assert not snap.exists(), "corrupt snapshot must be deleted"
    eval_cache.reset_cache()


def test_eval_cache_snapshot_disabled_without_env(monkeypatch):
    monkeypatch.delenv(eval_cache.SNAPSHOT_ENV, raising=False)
    assert eval_cache.snapshot_path() is None
    assert eval_cache.save_snapshot() is None
    assert eval_cache.load_snapshot() is False


# -- service integration ---------------------------------------------------


def _smoke(weights, fens=None, nodes=160, psqt_path=None, mesh_devices=None,
           ledger=None, tag="", before_close=None):
    """One gated deterministic run (test_coalesce's discipline); returns
    (analyses, counters_delta). ``before_close(svc)`` runs after the
    workload while the service (and its telemetry collector) is still
    alive. Workload sized to keep the whole file inside the tier-1
    budget — the parity contract is per-position, not per-node-count."""
    from test_coalesce import _SMOKE_FENS, _GatedService

    fens = _SMOKE_FENS[:4] if fens is None else fens
    svc = _GatedService(
        weights=weights, pool_slots=8, batch_capacity=256,
        tt_bytes=8 << 20, backend="jax", pipeline_depth=4,
        driver_threads=1, psqt_path=psqt_path, mesh_devices=mesh_devices,
    )
    try:
        svc.set_prefetch(0, adaptive=False)
        before = svc.counters()

        async def go():
            async def one(i, fen):
                if ledger is not None:
                    ledger.record_acquired(f"{tag}-{i}")
                r = await svc.search(fen, [], nodes=nodes)
                if ledger is not None:
                    ledger.record_submitted(f"{tag}-{i}")
                return r

            tasks = [
                asyncio.ensure_future(one(i, fen))
                for i, fen in enumerate(fens)
            ]
            await asyncio.sleep(0.3)
            svc.gate.set()
            return await asyncio.gather(*tasks)

        results = asyncio.run(go())
        analyses = [
            (
                r.best_move, r.depth, r.nodes,
                tuple(
                    (l.multipv, l.depth, l.is_mate, l.value, tuple(l.pv))
                    for l in r.lines
                ),
            )
            for r in results
        ]
        after = svc.counters()
        if before_close is not None:
            before_close(svc)
        return analyses, {k: after[k] - before.get(k, 0) for k in after}
    finally:
        svc.gate.set()
        svc.close()


@pytest.mark.parametrize("rung", [None, "xla", "host-material"])
def test_cache_parity_and_warm_reuse(rung, monkeypatch):
    """THE hard requirement, per rung: cache-off, cache-cold and
    cache-warm (fresh service + surviving process cache — the
    supervisor-respawn shape) walk bit-identical search trees; the warm
    run answers its batches pre-wire and skips device dispatches."""
    weights = NnueWeights.random(seed=7)
    monkeypatch.setenv("FISHNET_NO_EVAL_CACHE", "1")
    off, c_off = _smoke(weights, psqt_path=rung)
    monkeypatch.delenv("FISHNET_NO_EVAL_CACHE")

    eval_cache.reset_cache()
    cold, c_cold = _smoke(weights, psqt_path=rung)
    assert cold == off, "cold cache changed analysis output"
    assert c_cold["eval_steps"] == c_off["eval_steps"]

    warm, c_warm = _smoke(weights, psqt_path=rung)
    assert warm == off, "warm cache changed analysis output"
    assert c_warm["cache_prewire_hits"] > 0
    assert c_warm["cache_skipped_dispatches"] > 0
    assert c_warm["dispatches"] < c_cold["dispatches"], (
        c_warm["dispatches"], c_cold["dispatches"],
    )


def test_snapshot_warm_restart_first_batch_resolves_prewire(
    tmp_path, monkeypatch
):
    """The warm-restart contract end to end: run a workload, snapshot
    the cache (the graceful-drain path), kill the process cache, load
    the snapshot (the next start), and the restarted service's FIRST
    warm batch resolves pre-wire — with output bit-identical to the
    cold run."""
    snap = tmp_path / "cache.npz"
    monkeypatch.setenv(eval_cache.SNAPSHOT_ENV, str(snap))
    weights = NnueWeights.random(seed=13)
    fp = weights.fingerprint()

    eval_cache.reset_cache()
    cold, c_cold = _smoke(weights)
    assert eval_cache.save_snapshot(fingerprint=fp) == str(snap)

    eval_cache.reset_cache()  # process death: the in-memory cache is gone
    assert eval_cache.load_snapshot(fingerprint=fp) is True

    warm, c_warm = _smoke(weights)
    assert warm == cold, "snapshot-restored cache changed analysis output"
    assert c_warm["cache_prewire_hits"] > 0
    assert c_warm["dispatches"] < c_cold["dispatches"], (
        c_warm["dispatches"], c_cold["dispatches"],
    )
    eval_cache.reset_cache()


def test_cache_parity_on_mesh_with_ledger():
    """Mesh rung of the parity requirement, audited by the exactly-once
    ledger: cache-off vs cold vs warm on a sharded service."""
    from fishnet_tpu.resilience import accounting

    weights = NnueWeights.random(seed=11)
    ledger = accounting.install()
    try:
        import os

        os.environ["FISHNET_NO_EVAL_CACHE"] = "1"
        try:
            off, _ = _smoke(
                weights, mesh_devices="auto", ledger=ledger, tag="off",
            )
        finally:
            os.environ.pop("FISHNET_NO_EVAL_CACHE", None)
        eval_cache.reset_cache()
        cold, _ = _smoke(
            weights, mesh_devices="auto", ledger=ledger, tag="cold",
        )
        warm, cw = _smoke(
            weights, mesh_devices="auto", ledger=ledger, tag="warm",
        )
        ledger.assert_clean()
        assert cold == off, "mesh cold cache changed analysis output"
        assert warm == off, "mesh warm cache changed analysis output"
        assert cw["cache_prewire_hits"] > 0
    finally:
        accounting.clear()


def test_cross_group_position_dedup_fan_out(monkeypatch):
    """Several tenants analyzing the SAME position land in different
    pipeline groups; their fused dispatch ships each distinct position
    once and fans the value out host-side (position_dedup > 0), with
    results identical across the duplicates and to the dedup-off run."""
    from test_coalesce import _SMOKE_FENS

    weights = NnueWeights.random(seed=7)
    fens = [_SMOKE_FENS[0]] * 8  # one position, every group
    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")

    eval_cache.reset_cache()
    out, c = _smoke(weights, fens=fens, nodes=200)
    assert len(set(out)) == 1, "duplicate searches diverged"
    assert c["position_dedup"] > 0, c
    assert c["fused_dispatches"] >= 1

    monkeypatch.setenv("FISHNET_NO_DEDUP", "1")
    monkeypatch.setenv("FISHNET_NO_EVAL_CACHE", "1")
    eval_cache.reset_cache()
    plain, c2 = _smoke(weights, fens=fens, nodes=200)
    assert plain == out, "position dedup changed analysis output"
    assert c2["position_dedup"] == 0


def test_ledger_clean_under_device_faults_with_cache_live():
    """Injected device_step faults mid-traffic with the cache enabled:
    the mesh's per-shard ladder absorbs them, every search lands
    exactly once, and the cache-on analyses still match cache-off run
    under the same fault schedule (inserts/probes never double-provide
    or drop a batch)."""
    from fishnet_tpu.resilience import accounting, faults
    from test_coalesce import _SMOKE_FENS

    weights = NnueWeights.random(seed=7)
    plan = (
        "service.device_step:nth=2:error;service.device_step:nth=5:error"
    )

    def faulted(tag, ledger):
        faults.install(plan)
        try:
            return _smoke(
                weights, fens=_SMOKE_FENS[:6], nodes=180,
                mesh_devices="auto", ledger=ledger, tag=tag,
            )
        finally:
            faults.clear()

    ledger = accounting.install()
    try:
        import os

        os.environ["FISHNET_NO_EVAL_CACHE"] = "1"
        try:
            off, _ = faulted("f-off", ledger)
        finally:
            os.environ.pop("FISHNET_NO_EVAL_CACHE", None)
        eval_cache.reset_cache()
        on, _ = faulted("f-on", ledger)
        ledger.assert_clean()
        assert on == off, "cache changed output under device faults"
    finally:
        accounting.clear()


def test_cache_skip_counts_and_metrics_exported():
    """A warm same-service workload exports the new telemetry families
    (doc/observability.md): scoped hit counters, entry/eviction gauges
    and the dedup counter all render."""
    from fishnet_tpu import telemetry

    weights = NnueWeights.random(seed=7)
    eval_cache.reset_cache()
    _smoke(weights)
    # Render while the WARM service is alive: the scope-labeled hit and
    # dedup families ride its per-service collector (unregistered at
    # close), while entries/evictions come from the process-wide cache
    # collector and outlive every service.
    rendered = []
    _smoke(  # warm: prewire hits guaranteed
        weights,
        before_close=lambda svc: rendered.append(
            telemetry.REGISTRY.render_prometheus()
        ),
    )
    text = rendered[0]
    assert 'fishnet_eval_cache_hits_total{scope="prewire"}' in text
    assert 'fishnet_eval_cache_hits_total{scope="pool"}' in text
    assert "# TYPE fishnet_position_dedup_total counter" in text
    assert "fishnet_eval_cache_skipped_dispatches_total" in text
    assert "# TYPE fishnet_eval_cache_entries gauge" in text
    assert "# TYPE fishnet_eval_cache_evictions_total counter" in text
    # The cache families survive service teardown (process-wide plane).
    text2 = telemetry.REGISTRY.render_prometheus()
    assert "fishnet_eval_cache_entries" in text2
