"""Continuous profiling plane + per-tenant cost attribution (ISSUE 15):
gate discipline (everything off by default, zero hot-path work), the
sampling profiler's role folding and /profile endpoint contract, the
stage-duration histogram hook, the measured <3% overhead bound, the
profiler-on/off bit-identical parity requirement, and the "attributed
per-tenant device-ms sums to measured total within 2%" acceptance on a
real multi-tenant coalesced workload."""

import asyncio
import threading
import time

import pytest

from fishnet_tpu.nnue.weights import NnueWeights
from fishnet_tpu.telemetry import cost, profiler, spans


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with the plane fully off (the
    process-default state the gate tests pin)."""
    profiler.stop()
    cost.disable()
    cost.reset()
    yield
    profiler.stop()
    cost.disable()
    cost.reset()


# -- gate discipline ----------------------------------------------------------


def test_plane_off_by_default():
    """Off means OFF: no sampler, no span hook, no cost gate — the only
    hot-path residue is one module-attribute read per check."""
    assert not profiler.enabled()
    assert profiler.profiler() is None
    assert spans.STAGE_OBSERVER is None
    assert not cost.enabled()


def test_env_gate(monkeypatch):
    monkeypatch.delenv("FISHNET_PROFILE", raising=False)
    assert profiler.maybe_start_from_env() is None
    monkeypatch.setenv("FISHNET_PROFILE", "0")
    assert profiler.maybe_start_from_env() is None
    monkeypatch.setenv("FISHNET_PROFILE", "1")
    prof = profiler.maybe_start_from_env()
    assert prof is not None and profiler.enabled()


def test_stop_clears_span_hook():
    profiler.start(hz=10)
    assert spans.STAGE_OBSERVER is not None
    profiler.stop()
    assert spans.STAGE_OBSERVER is None
    assert not profiler.enabled()


# -- role folding -------------------------------------------------------------


def test_role_of_contract():
    """The thread-name -> role table from the module docstring (names
    are set at thread creation in service.py / az_engine.py / the net
    tier; this pins both directions of the contract)."""
    assert profiler.role_of("search-driver-0") == "driver"
    assert profiler.role_of("az-mcts-driver") == "driver"
    assert profiler.role_of("dispatch-pack") == "pack"
    assert profiler.role_of("dispatch-decode") == "decode"
    assert profiler.role_of("acquire-stream") == "acquire"
    assert profiler.role_of("api-poll") == "acquire"
    assert profiler.role_of("frontend") == "frontend"
    assert profiler.role_of("tenant-lichess") == "frontend"
    assert profiler.role_of("MainThread") == "main"
    assert profiler.role_of("profile-sampler") == "other"
    assert profiler.role_of("") == "other"


def test_sampler_folds_named_threads():
    """A busy thread named under the pack prefix must show up folded
    under the "pack" role, in top_stacks, and in the collapsed text."""
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(i * i for i in range(200))

    t = threading.Thread(target=spin, name="dispatch-pack-test", daemon=True)
    t.start()
    try:
        prof = profiler.start(hz=200)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            snap = prof.snapshot()
            if snap["samples_by_role"].get("pack", 0) >= 3:
                break
            time.sleep(0.02)
        snap = prof.snapshot()
        assert snap["samples"] > 0
        assert snap["samples_by_role"].get("pack", 0) >= 3
        tops = prof.top_stacks(10)
        assert tops and all(
            set(s) >= {"role", "stack", "count", "share"} for s in tops
        )
        # Other suites may leak idle dispatch-pack threads; OUR spin
        # thread must still fold under the pack role with the test
        # module on its (root-first) stack.
        pack = [s for s in prof.top_stacks(1000) if s["role"] == "pack"]
        assert pack, f"no pack stack in {tops!r}"
        assert any(
            any("test_profiler" in fr for fr in s["stack"]) for s in pack
        ), pack
        collapsed = prof.collapsed()
        assert any(
            line.startswith("pack;") for line in collapsed.splitlines()
        )
        # Every collapsed line ends in its integer sample count.
        for line in collapsed.splitlines():
            assert line.rsplit(" ", 1)[1].isdigit()
    finally:
        stop.set()
        t.join(timeout=2)


# -- stage-duration histograms ------------------------------------------------


def test_stage_observer_feeds_histogram():
    profiler.start(hz=10)
    t0 = time.monotonic() - 0.002
    spans.RECORDER.record("pack", t0)
    spans.RECORDER.record("compute", time.monotonic() - 0.05)
    q = profiler.stage_quantiles()
    assert q["pack"]["count"] >= 1
    assert q["compute"]["count"] >= 1
    assert q["compute"]["p99"] >= q["compute"]["p50"] > 0
    from fishnet_tpu.telemetry import REGISTRY

    text = REGISTRY.render_prometheus()
    assert "# TYPE fishnet_stage_duration_seconds histogram" in text
    assert 'stage="pack"' in text


# -- /profile endpoint --------------------------------------------------------


def test_profile_endpoint_contract():
    import json

    status, ctype, body = profiler.render_endpoint("")
    assert status == 503 and ctype == "application/json"
    assert json.loads(body) == {
        "enabled": False,
        "hint": json.loads(body)["hint"],
    }

    profiler.start(hz=100)
    time.sleep(0.1)
    status, ctype, body = profiler.render_endpoint("")
    assert status == 200 and ctype == "application/json"
    snap = json.loads(body)
    assert snap["enabled"] is True and snap["hz"] == 100.0
    assert "duty_cycle" in snap and "stages" in snap

    status, ctype, body = profiler.render_endpoint("format=collapsed")
    assert status == 200 and ctype.startswith("text/plain")


# -- ledger unit behavior -----------------------------------------------------


def test_ledger_splits_by_row_count():
    """A fused dispatch's wall splits across owners by rows; shortfall
    rows land on the unknown owner; an empty tenant label becomes
    "default"."""
    led = cost.CostLedger()
    led.note_dispatch(
        [(("lichess", "analysis"), 3), (("backfill", "selfplay"), 1)],
        rows=4, wire_bytes=4096, duration_s=0.010,
    )
    snap = led.snapshot()
    assert snap["tenant_device_ms"]["lichess"] == pytest.approx(7.5)
    assert snap["tenant_device_ms"]["backfill"] == pytest.approx(2.5)
    assert snap["family_device_ms"]["selfplay"] == pytest.approx(2.5)
    assert snap["tenant_wire_bytes"]["lichess"] == pytest.approx(3072)
    assert snap["total_device_ms"] == pytest.approx(10.0)

    led.note_dispatch([(("a", "analysis"), 2)], rows=8,
                      wire_bytes=0, duration_s=0.008)
    snap = led.snapshot()
    assert snap["tenant_device_ms"]["unknown"] == pytest.approx(6.0)

    led.note_dispatch([(("", "analysis"), 1)], rows=1,
                      wire_bytes=16, duration_s=0.001)
    assert "default" in led.snapshot()["tenant_device_ms"]

    # Attributed tenant shares always sum to the measured total.
    snap = led.snapshot()
    assert sum(snap["tenant_device_ms"].values()) == pytest.approx(
        snap["total_device_ms"]
    )


def test_ledger_exports_counter_families():
    led = cost.CostLedger()
    led.note_dispatch([(("x", "analysis"), 1)], 1, 64, 0.001)
    led.note_cache_hits([(("x", "analysis"), 5)])
    fams = {f.name: f for f in led.collect()}
    assert set(fams) == {
        "fishnet_tenant_device_ms_total",
        "fishnet_tenant_wire_bytes_total",
        "fishnet_tenant_cache_hits_total",
        "fishnet_workload_device_ms_total",
        "fishnet_cost_device_ms_total",
        "fishnet_cost_dispatches_total",
    }
    hits = fams["fishnet_tenant_cache_hits_total"].samples
    assert hits[0].labels == {"tenant": "x"} and hits[0].value == 5


# -- the acceptance pair: overhead+parity, and the 2% attribution sum ---------


def _run_smoke(monkeypatch):
    from test_coalesce import _smoke_run

    monkeypatch.setenv("FISHNET_COALESCE_WIDTH", "4")
    try:
        return _smoke_run(NnueWeights.random(seed=7))
    finally:
        monkeypatch.delenv("FISHNET_COALESCE_WIDTH")


def test_profiler_overhead_and_parity(monkeypatch):
    """The A/B acceptance: the profiler ON must leave analyses
    bit-identical to OFF (it only ever reads frames), and its measured
    duty cycle — self-accounted sampler walk time over wall — stays
    under the 3% bound on a real coalesced workload."""
    plain, _ = _run_smoke(monkeypatch)

    prof = profiler.start(hz=profiler.DEFAULT_HZ)
    cost.enable()
    profiled, _ = _run_smoke(monkeypatch)
    wall = max(1e-9, time.monotonic() - prof.started_at)
    duty = prof.self_seconds / wall
    profiler.stop()

    assert profiled == plain, "profiling changed analysis output"
    assert prof.samples > 0
    assert duty < 0.03, f"sampler duty cycle {duty:.4f} >= 3%"


def test_cost_attribution_sums_on_multi_tenant_workload():
    """Acceptance: on a real multi-tenant coalesced run the per-tenant
    device-ms shares sum to the measured dispatch wall within 2%, both
    submitted tenants appear, and wire bytes were attributed."""
    from test_coalesce import _SMOKE_FENS, _GatedService

    cost.enable()
    cost.reset()
    svc = _GatedService(
        weights=NnueWeights.random(seed=7), pool_slots=8,
        batch_capacity=256, tt_bytes=8 << 20, backend="jax",
        pipeline_depth=4, driver_threads=1,
    )
    try:
        svc.set_prefetch(0, adaptive=False)

        async def go():
            tenants = ("lichess", "backfill")
            tasks = [
                asyncio.ensure_future(
                    svc.search(fen, [], nodes=280, tenant=tenants[i % 2])
                )
                for i, fen in enumerate(_SMOKE_FENS)
            ]
            await asyncio.sleep(0.3)
            svc.gate.set()
            return await asyncio.gather(*tasks)

        asyncio.run(go())
    finally:
        svc.gate.set()
        svc.close()

    snap = cost.LEDGER.snapshot()
    assert snap["dispatches"] > 0
    assert snap["total_device_ms"] > 0
    attributed = sum(snap["tenant_device_ms"].values())
    assert attributed == pytest.approx(snap["total_device_ms"], rel=0.02), (
        f"attributed {attributed} vs measured {snap['total_device_ms']}"
    )
    for tenant in ("lichess", "backfill"):
        assert snap["tenant_device_ms"].get(tenant, 0) > 0, snap
        assert snap["tenant_wire_bytes"].get(tenant, 0) > 0, snap
    # Throughput-lane searches attribute to the analysis family.
    assert snap["family_device_ms"].get("analysis", 0) > 0
