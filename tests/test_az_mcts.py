"""AlphaZero model family: move/plane encodings, the policy+value net,
batched PUCT MCTS, and the az-mcts engine end-to-end against the fake
lichess server (BASELINE.json config 5)."""

import asyncio

import jax
import numpy as np
import pytest

from fishnet_tpu.chess.board import Board
from fishnet_tpu.models.az import AzConfig, az_forward, init_az_params, value_to_centipawns
from fishnet_tpu.models.az_encoding import (
    INPUT_PLANES,
    POLICY_SIZE,
    board_planes,
    move_to_index,
)
from fishnet_tpu.search.mcts import MctsConfig, MctsPool

STARTPOS = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
TINY = AzConfig(channels=16, blocks=2, value_hidden=16)


# -- move encoding ---------------------------------------------------------


def test_move_index_known_values():
    # e2e4: from-square e2 = 12, north 2 steps = plane 1.
    assert move_to_index("e2e4", True) == 12 * 73 + 1
    # g1f3 (knight, df=-1, dr=+2): plane 56 + index of (-1,2)... computed:
    idx = move_to_index("g1f3", True)
    assert 6 * 73 + 56 <= idx < 6 * 73 + 64
    # Underpromotion capture: a7xb8=n, df=+1 -> plane 64 + 0*3 + 2.
    assert move_to_index("a7b8n", True) == 48 * 73 + 64 + 2


def test_move_index_black_flip():
    # Black's e7e5 must encode like white's e2e4 (perspective flip).
    assert move_to_index("e7e5", False) == move_to_index("e2e4", True)


def test_move_index_unique_over_legal_moves():
    fens = [
        STARTPOS,
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R w KQkq - 0 1",
        "r3k2r/p1ppqpb1/bn2pnp1/3PN3/1p2P3/2N2Q1p/PPPBBPPP/R3K2R b KQkq - 0 1",
        "r3k2r/Pppp1ppp/1b3nbN/nP6/BBP1P3/q4N2/Pp1P2PP/R2Q1RK1 w kq - 0 1",
    ]
    for fen in fens:
        board = Board(fen)
        white = board.turn() == "w"
        indices = [move_to_index(m, white) for m in board.legal_moves()]
        assert len(set(indices)) == len(indices), fen
        assert all(0 <= i < POLICY_SIZE for i in indices)


def test_drop_moves_rejected():
    with pytest.raises(ValueError):
        move_to_index("P@e4", True)


# -- planes ----------------------------------------------------------------


def test_startpos_planes():
    planes = board_planes(STARTPOS)
    assert planes.shape == (8, 8, INPUT_PLANES)
    assert planes[:, :, 0].sum() == 8  # own pawns
    assert planes[:, :, 6].sum() == 8  # opponent pawns
    assert planes[1, :, 0].sum() == 8  # own pawns on rank 2
    assert planes[:, :, 12].all() and planes[:, :, 15].all()  # castling
    assert planes[:, :, 18].all()


def test_black_perspective_flip():
    # After 1.e4, black sees white's e-pawn as an *opponent* pawn on its
    # own 4th rank (from black's perspective).
    after_e4 = "rnbqkbnr/pppppppp/8/8/4P3/8/PPPP1PPP/RNBQKBNR b KQkq - 0 1"
    planes = board_planes(after_e4)
    assert planes[:, :, 0].sum() == 8  # black's pawns, still on "rank 2"
    assert planes[1, :, 0].sum() == 8
    assert planes[4, 4, 6] == 1.0  # white e4 pawn -> opp plane, flipped rank


# -- network ---------------------------------------------------------------


def test_az_forward_shapes_and_finite():
    params = init_az_params(jax.random.PRNGKey(0), TINY)
    planes = np.stack([board_planes(STARTPOS)] * 4)
    logits, values = jax.jit(lambda p, x: az_forward(p, x, TINY))(params, planes)
    assert logits.shape == (4, POLICY_SIZE)
    assert values.shape == (4,)
    assert np.isfinite(np.asarray(logits)).all()
    assert np.all(np.abs(np.asarray(values)) <= 1.0)


def test_value_to_centipawns_monotone():
    vals = [value_to_centipawns(v) for v in (-0.9, -0.5, 0.0, 0.5, 0.9)]
    assert vals == sorted(vals)
    assert value_to_centipawns(0.0) == 0


# -- MCTS ------------------------------------------------------------------


@pytest.fixture(scope="module")
def pool():
    params = init_az_params(jax.random.PRNGKey(1), TINY)
    return MctsPool(params, MctsConfig(batch_capacity=64, az=TINY))


def run_pool(pool, sids):
    for _ in range(10_000):
        pool.step()
        if pool.active() == 0:
            break
    return {sid: pool.harvest(sid) for sid in sids}


def test_mcts_finds_mate_in_one(pool):
    sid = pool.submit("6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [], visits=400)
    result = run_pool(pool, [sid])[sid]
    assert result.best_move == "d1d8"
    assert result.value > 0.8
    assert result.pv[0] == "d1d8"


def test_mcts_terminal_root(pool):
    # Fool's mate: white is already mated.
    sid = pool.submit(
        "rnb1kbnr/pppp1ppp/8/4p3/6Pq/5P2/PPPPP2P/RNBQKBNR w KQkq - 1 3",
        [], visits=64,
    )
    result = run_pool(pool, [sid])[sid]
    assert result.best_move is None
    assert result.value == -1.0


def test_mcts_concurrent_searches(pool):
    sids = [
        pool.submit(STARTPOS, ["e2e4"], visits=48),
        pool.submit(STARTPOS, [], visits=48),
        pool.submit("6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [], visits=200),
    ]
    results = run_pool(pool, sids)
    assert all(r.best_move for r in results.values())
    assert results[sids[2]].best_move == "d1d8"
    assert all(r.visits > 0 for r in results.values())


def test_mcts_forced_move_no_duplicate_expansion(pool):
    # Black has exactly one legal move: every selection walk collides on
    # the same pending edge; the search must still complete the budget
    # without duplicating expansions.
    sid = pool.submit("k7/8/2K5/8/8/8/8/1R6 b - - 0 1", [], visits=24)
    result = run_pool(pool, [sid])[sid]
    assert result.best_move == "a8a7"
    assert result.visits >= 24


def test_mcts_avoids_stalemate_draw(pool):
    # KQ vs K: random net, but terminal draws backpropagate 0 while the
    # mating lines backpropagate +1 — search must not pick the stalemate.
    sid = pool.submit("7k/5Q2/5K2/8/8/8/8/8 w - - 0 1", [], visits=300)
    result = run_pool(pool, [sid])[sid]
    board = Board("7k/5Q2/5K2/8/8/8/8/8 w - - 0 1")
    board.push_uci(result.best_move)
    assert board.outcome() != Board.STALEMATE


# -- async service + engine e2e -------------------------------------------

pytestmark = pytest.mark.anyio


async def test_az_service_search():
    from fishnet_tpu.engine.az_engine import AzMctsService

    params = init_az_params(jax.random.PRNGKey(2), TINY)
    service = AzMctsService(params, MctsConfig(batch_capacity=64, az=TINY))
    try:
        results = await asyncio.gather(
            service.search("6k1/5ppp/8/8/8/8/5PPP/3R2K1 w - - 0 1", [], 300),
            service.search(STARTPOS, [], 48),
        )
        assert results[0].best_move == "d1d8"
        assert results[1].best_move
    finally:
        service.close()


async def test_az_service_multipv_and_cancellation():
    from fishnet_tpu.engine.az_engine import AzMctsService

    params = init_az_params(jax.random.PRNGKey(4), TINY)
    service = AzMctsService(params, MctsConfig(batch_capacity=64, az=TINY))
    try:
        res = await service.search(STARTPOS, [], 64, multipv=3)
        assert [l.multipv for l in res.lines] == [1, 2, 3]
        assert len({l.move for l in res.lines}) == 3
        assert res.lines[0].move == res.best_move

        # Cancellation (worker budget) must stop the underlying search.
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(
                service.search(STARTPOS, [], visits=10_000_000), timeout=0.3
            )
        for _ in range(100):
            if service.pool.active() == 0:
                break
            await asyncio.sleep(0.05)
        assert service.pool.active() == 0, "cancelled search kept running"
    finally:
        service.close()


async def test_az_factory_variant_fallback_routing():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from fake_server import FakeServer
    from test_client_e2e import make_client, wait_for

    from fishnet_tpu.engine.az_engine import AzMctsEngineFactory, AzMctsService
    from fishnet_tpu.engine.tpu_engine import TpuNnueEngineFactory
    from fishnet_tpu.nnue.weights import NnueWeights
    from fishnet_tpu.search.service import SearchService

    params = init_az_params(jax.random.PRNGKey(5), TINY)
    az_service = AzMctsService(params, MctsConfig(batch_capacity=64, az=TINY))
    hce_service = SearchService(
        weights=NnueWeights.random(seed=0), backend="scalar",
        pool_slots=16, batch_capacity=64, tt_bytes=8 << 20,
    )
    try:
        async with FakeServer() as server:
            variant_job = server.lichess.add_analysis_job(
                moves="e2e4", variant="kingofthehill", nodes=3000
            )
            standard_job = server.lichess.add_analysis_job(moves="e2e4", nodes=70_000)
            client = make_client(
                server.endpoint, cores=2,
                engine_factory=AzMctsEngineFactory(
                    az_service, variant_fallback=TpuNnueEngineFactory(hce_service)
                ),
            )
            await client.start()
            assert await wait_for(
                lambda: variant_job in server.lichess.analyses
                and standard_job in server.lichess.analyses,
                timeout=60,
            )
            await client.stop()
            assert (
                server.lichess.analyses[variant_job]["stockfish"]["flavor"]
                == "classical"
            )
    finally:
        az_service.close()
        hce_service.close()


async def test_az_engine_client_e2e():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from fake_server import FakeServer
    from test_client_e2e import make_client, wait_for

    from fishnet_tpu.engine.az_engine import AzMctsEngineFactory, AzMctsService

    params = init_az_params(jax.random.PRNGKey(3), TINY)
    service = AzMctsService(params, MctsConfig(batch_capacity=64, az=TINY))
    try:
        async with FakeServer() as server:
            work_id = server.lichess.add_analysis_job(
                moves="e2e4 e7e5", nodes=70_000  # ~68 visits/position
            )
            client = make_client(
                server.endpoint, cores=1,
                engine_factory=AzMctsEngineFactory(service),
            )
            await client.start()
            assert await wait_for(lambda: work_id in server.lichess.analyses, timeout=60)
            await client.stop()
            parts = server.lichess.analyses[work_id]["analysis"]
            assert len(parts) == 3
            assert all("pv" in p for p in parts)
    finally:
        service.close()


async def test_az_analysis_respects_per_ply_timeout_with_slow_net():
    """VERDICT round 1 weak #5: the protocol's per-ply timeout
    (doc/protocol.md:32) must hold even when the net is slow. The first
    search is bounded by the hard movetime stop (partial result, on
    time); completed searches feed the visits/sec EWMA, which then
    clamps later budgets so searches *plan* to finish inside the
    timeout."""
    import time

    from fishnet_tpu.engine.az_engine import (
        AzMctsEngine,
        AzMctsService,
        NODES_PER_VISIT,
    )
    from fishnet_tpu.ipc import Position
    from fishnet_tpu.protocol.types import (
        EngineFlavor,
        NodeLimit,
        Variant,
        Work,
    )

    params = init_az_params(jax.random.PRNGKey(7), TINY)
    service = AzMctsService(params, MctsConfig(batch_capacity=64, az=TINY))
    # Artificially slow evaluation: every pool step pays a stall, so the
    # un-calibrated budget (1.5M nodes -> ~1465 visits) would blow the
    # timeout by an order of magnitude.
    real_step = service.pool.step

    def slow_step():
        time.sleep(0.05)
        return real_step()

    service.pool.step = slow_step

    timeout_ms = 800
    work = Work(
        kind="analysis", id="azdl1",
        nodes=NodeLimit(classical=4_050_000, sf15=1_500_000),
        timeout_ms=timeout_ms,
    )
    pos = Position(
        work=work, position_id=0, flavor=EngineFlavor.OFFICIAL,
        variant=Variant.STANDARD, root_fen=STARTPOS,
    )
    engine = AzMctsEngine(service, EngineFlavor.OFFICIAL)
    try:
        t0 = time.monotonic()
        resp = await engine.go(pos)
        first = time.monotonic() - t0
        # Hard stop: well under the worker's budget (timeout + slack),
        # never the full visit budget's worth of wall clock.
        assert first < timeout_ms / 1000.0 + 2.0
        assert resp.best_move is not None
        assert resp.nodes <= 1_500_000

        rate = service.visits_per_second()
        assert rate is not None and rate > 0

        # Second search: the EWMA must clamp the PLANNED budget below the
        # uncalibrated 1.5M/1024 = 1464 visits (achieved visits would be
        # bounded by the watchdog either way, so capture what engine.go
        # actually requests).
        planned = {}
        real_search = service.search

        async def capturing_search(fen, mvs, visits, movetime=None, multipv=1):
            planned["visits"] = visits
            planned["movetime"] = movetime
            return await real_search(fen, mvs, visits, movetime,
                                     multipv=multipv)

        service.search = capturing_search
        t0 = time.monotonic()
        resp2 = await engine.go(pos)
        second = time.monotonic() - t0
        assert second < timeout_ms / 1000.0 + 2.0
        assert resp2.best_move is not None
        uncalibrated = 1_500_000 // NODES_PER_VISIT
        assert planned["visits"] < uncalibrated, (
            "EWMA calibration did not clamp the visit budget"
        )
        assert planned["movetime"] == timeout_ms / 1000.0
    finally:
        service.close()
