"""`make soak-smoke`: the tier-1 resilience acceptance gate
(doc/resilience.md). Runs the canned fault plan (acquire flaps, submit
failures, one engine-spawn fault, one device_step crash) through the
full soak harness and asserts the contract: every acquired batch
submitted exactly once (ledger clean, server-side counts all 1), at
least one fused->xla degradation and one pool respawn observed via the
new counters, and /metrics exporting all four resilience families."""

import pytest

from fishnet_tpu.resilience import soak

pytestmark = pytest.mark.anyio


async def test_soak_canned_plan():
    report = await soak.run_soak()
    assert report["ok"], report
    # Exactly-once: nothing lost, nothing duplicated, all submitted.
    assert report["ledger"]["lost"] == []
    assert report["ledger"]["duplicated"] == []
    assert report["ledger"]["submitted"] == report["phase_a"]["jobs"]
    # Whole-run view (phase C's overload traffic included): still clean.
    assert report["ledger_final"]["lost"] == []
    assert report["ledger_final"]["duplicated"] == []
    assert all(
        c == 1
        for c in report["phase_a"]["server_submission_counts"].values()
    )
    # Recovery machinery observed via the new counters.
    assert report["counters"]["requeued"] >= 1
    assert report["counters"]["respawns"] >= 1
    assert report["counters"]["degradations_fused_to_xla"] >= 1
    assert report["phase_b"]["rung"] == "xla"
    # The metric-family contract.
    assert set(report["metric_families"]) == set(soak.REQUIRED_FAMILIES)


def test_soak_cli_rejects_bad_plan(capsys):
    assert soak.main(["--plan", "nosuch.site:nth=1:error"]) == 1
    assert "SOAK FAILED" in capsys.readouterr().err
